// Host event tracer: low-overhead per-thread event recording.
//
// Reference analog: paddle/fluid/platform/profiler/host_event_recorder.h —
// thread-local event buffers appended without locks on the hot path,
// harvested at export time; drives HostTracer in the unified profiler.
// TPU-native role: host-side timeline for the paddle_tpu profiler (the
// device timeline comes from the XLA profiler); RecordEvent scopes call
// begin/end here with ~100ns overhead instead of going through Python.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Event {
  uint32_t name_id;
  uint64_t t_begin_ns;
  uint64_t t_end_ns;
  uint64_t thread_id;
};

struct Recorder {
  std::mutex mu;
  std::vector<std::string> names;
  std::unordered_map<std::string, uint32_t> name_ids;
  std::vector<Event> events;
  std::atomic<bool> enabled{false};
};

Recorder g_recorder;

// Per-thread buffers are registered globally so harvest() (called from the
// profiler's thread) can flush every live thread's events, not just its own.
// The hot path takes the buffer's own (uncontended) mutex only.
struct ThreadBuffer;
struct BufferRegistry {
  std::mutex mu;
  std::vector<ThreadBuffer*> buffers;
};
BufferRegistry g_registry;

struct ThreadBuffer {
  std::mutex mu;
  std::vector<Event> events;
  ThreadBuffer() {
    std::lock_guard<std::mutex> lk(g_registry.mu);
    g_registry.buffers.push_back(this);
  }
  ~ThreadBuffer() {
    {
      // flush remaining events on thread exit
      std::lock_guard<std::mutex> lk1(g_recorder.mu);
      std::lock_guard<std::mutex> lk2(mu);
      g_recorder.events.insert(g_recorder.events.end(), events.begin(),
                               events.end());
    }
    std::lock_guard<std::mutex> lk(g_registry.mu);
    auto& v = g_registry.buffers;
    v.erase(std::remove(v.begin(), v.end(), this), v.end());
  }
};

thread_local ThreadBuffer t_buffer;

// moves every registered thread's events into g_recorder.events.
// caller must hold g_recorder.mu.
void flush_all_buffers_locked() {
  std::lock_guard<std::mutex> lk(g_registry.mu);
  for (ThreadBuffer* b : g_registry.buffers) {
    std::lock_guard<std::mutex> blk(b->mu);
    g_recorder.events.insert(g_recorder.events.end(), b->events.begin(),
                             b->events.end());
    b->events.clear();
  }
}

uint64_t now_ns() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

uint64_t tid() {
  return std::hash<std::thread::id>{}(std::this_thread::get_id());
}

}  // namespace

extern "C" {

uint32_t pd_trace_register_name(const char* name) {
  std::lock_guard<std::mutex> lk(g_recorder.mu);
  auto it = g_recorder.name_ids.find(name);
  if (it != g_recorder.name_ids.end()) return it->second;
  uint32_t id = static_cast<uint32_t>(g_recorder.names.size());
  g_recorder.names.emplace_back(name);
  g_recorder.name_ids.emplace(name, id);
  return id;
}

void pd_trace_enable(int on) { g_recorder.enabled = on != 0; }

int pd_trace_is_enabled() { return g_recorder.enabled ? 1 : 0; }

uint64_t pd_trace_now_ns() { return now_ns(); }

// record a completed [begin, end] span (hot path: thread-local append)
void pd_trace_span(uint32_t name_id, uint64_t t_begin_ns, uint64_t t_end_ns) {
  if (!g_recorder.enabled) return;
  std::lock_guard<std::mutex> lk(t_buffer.mu);
  t_buffer.events.push_back(Event{name_id, t_begin_ns, t_end_ns, tid()});
}

// Harvest: flush calling thread's buffer and copy up to max_events events
// into out (4 x u64 per event: name_id, begin, end, tid). Returns count.
// Clears harvested global events.
uint64_t pd_trace_harvest(uint64_t* out, uint64_t max_events) {
  std::lock_guard<std::mutex> lk(g_recorder.mu);
  flush_all_buffers_locked();
  uint64_t n = g_recorder.events.size();
  if (n > max_events) n = max_events;
  for (uint64_t i = 0; i < n; ++i) {
    const Event& e = g_recorder.events[i];
    out[i * 4 + 0] = e.name_id;
    out[i * 4 + 1] = e.t_begin_ns;
    out[i * 4 + 2] = e.t_end_ns;
    out[i * 4 + 3] = e.thread_id;
  }
  g_recorder.events.erase(g_recorder.events.begin(),
                          g_recorder.events.begin() + n);
  return n;
}

uint64_t pd_trace_pending(void) {
  std::lock_guard<std::mutex> lk(g_recorder.mu);
  flush_all_buffers_locked();
  return g_recorder.events.size();
}

// name lookup: copies name for id into buf (nul-terminated), returns length
// or -1 if unknown
int64_t pd_trace_name(uint32_t id, char* buf, uint64_t buf_len) {
  std::lock_guard<std::mutex> lk(g_recorder.mu);
  if (id >= g_recorder.names.size()) return -1;
  const std::string& s = g_recorder.names[id];
  if (buf != nullptr && buf_len > 0) {
    uint64_t n = s.size() < buf_len - 1 ? s.size() : buf_len - 1;
    std::memcpy(buf, s.data(), n);
    buf[n] = '\0';
  }
  return static_cast<int64_t>(s.size());
}

}  // extern "C"
