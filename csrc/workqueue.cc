// Threadpool + bounded blocking queue + parallel batch collation.
//
// Reference analog: the lock-free WorkQueue under
// paddle/fluid/framework/new_executor/workqueue/ (executor task scheduling)
// and operators/reader/buffered_reader.cc + lod_tensor_blocking_queue.h (the
// bounded producer/consumer pipe feeding the device). TPU-native role: XLA
// owns on-device scheduling, so the native work here is the HOST side of the
// input pipeline — a GIL-free bounded queue for DataLoader prefetch and a
// threadpool that collates sample arrays into batch buffers with parallel
// memcpy (the hot loop of host-side data feeding).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct ThreadPool {
  std::vector<std::thread> workers;
  std::deque<std::function<void()>> tasks;
  std::mutex mu;
  std::condition_variable cv;
  bool stop = false;

  explicit ThreadPool(int n) {
    for (int i = 0; i < n; ++i) {
      workers.emplace_back([this] {
        for (;;) {
          std::function<void()> task;
          {
            std::unique_lock<std::mutex> lk(mu);
            cv.wait(lk, [this] { return stop || !tasks.empty(); });
            if (stop && tasks.empty()) return;
            task = std::move(tasks.front());
            tasks.pop_front();
          }
          task();
        }
      });
    }
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv.notify_all();
    for (auto& w : workers) w.join();
  }

  void submit(std::function<void()> f) {
    {
      std::lock_guard<std::mutex> lk(mu);
      tasks.push_back(std::move(f));
    }
    cv.notify_one();
  }
};

struct BoundedQueue {
  std::deque<uint64_t> items;
  size_t capacity;
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  bool closed = false;

  explicit BoundedQueue(size_t cap) : capacity(cap) {}
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------- pool
void* pd_pool_create(int num_threads) {
  if (num_threads <= 0) num_threads = 1;
  return new ThreadPool(num_threads);
}

void pd_pool_destroy(void* pool) { delete static_cast<ThreadPool*>(pool); }

// Copy n blocks (srcs[i], sizes[i]) -> dsts[i] in parallel; blocks until all
// copies finish. Used for batch collation: dsts point into one contiguous
// batch buffer, srcs are the per-sample arrays.
void pd_pool_parallel_memcpy(void* pool, void** dsts, const void** srcs,
                             const uint64_t* sizes, int n) {
  auto* p = static_cast<ThreadPool*>(pool);
  // completion state on the heap, shared by workers and waiter, so the last
  // worker's notify can never race the waiter's stack unwinding
  struct Done {
    std::mutex mu;
    std::condition_variable cv;
    int remaining;
  };
  auto done = std::make_shared<Done>();
  done->remaining = n;
  for (int i = 0; i < n; ++i) {
    void* dst = dsts[i];
    const void* src = srcs[i];
    uint64_t size = sizes[i];
    p->submit([done, dst, src, size] {
      std::memcpy(dst, src, size);
      std::lock_guard<std::mutex> lk(done->mu);
      if (--done->remaining == 0) done->cv.notify_one();
    });
  }
  std::unique_lock<std::mutex> lk(done->mu);
  done->cv.wait(lk, [&] { return done->remaining == 0; });
}

// ---------------------------------------------------------------- queue
void* pd_queue_create(uint64_t capacity) {
  return new BoundedQueue(capacity ? capacity : 1);
}

void pd_queue_destroy(void* q) { delete static_cast<BoundedQueue*>(q); }

void pd_queue_close(void* qh) {
  auto* q = static_cast<BoundedQueue*>(qh);
  {
    std::lock_guard<std::mutex> lk(q->mu);
    q->closed = true;
  }
  q->not_empty.notify_all();
  q->not_full.notify_all();
}

// 0 = ok, -1 = timeout, -2 = closed
int pd_queue_push(void* qh, uint64_t item, int64_t timeout_ms) {
  auto* q = static_cast<BoundedQueue*>(qh);
  std::unique_lock<std::mutex> lk(q->mu);
  auto pred = [&] { return q->closed || q->items.size() < q->capacity; };
  if (timeout_ms < 0) {
    q->not_full.wait(lk, pred);
  } else if (!q->not_full.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   pred)) {
    return -1;
  }
  if (q->closed) return -2;
  q->items.push_back(item);
  lk.unlock();
  q->not_empty.notify_one();
  return 0;
}

// 0 = ok, -1 = timeout, -2 = closed-and-drained
int pd_queue_pop(void* qh, uint64_t* item, int64_t timeout_ms) {
  auto* q = static_cast<BoundedQueue*>(qh);
  std::unique_lock<std::mutex> lk(q->mu);
  auto pred = [&] { return q->closed || !q->items.empty(); };
  if (timeout_ms < 0) {
    q->not_empty.wait(lk, pred);
  } else if (!q->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                    pred)) {
    return -1;
  }
  if (q->items.empty()) return -2;  // closed and drained
  *item = q->items.front();
  q->items.pop_front();
  lk.unlock();
  q->not_full.notify_one();
  return 0;
}

uint64_t pd_queue_size(void* qh) {
  auto* q = static_cast<BoundedQueue*>(qh);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->items.size();
}

}  // extern "C"
