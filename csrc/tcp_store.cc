// TCPStore: socket key-value rendezvous store.
//
// Reference analog: paddle/fluid/distributed/store/tcp_store.h:117 (TCPStore
// master on rank 0, clients over TCP; set/get/add/wait/barrier) and
// tcp_utils.cc. TPU-native role: bootstrap rendezvous for multi-host jobs
// (the jax coordination-service analog kept native so launch/elastic tooling
// can rendezvous before any JAX runtime exists) and a general KV/barrier
// fabric for the launch CLI and tests.
//
// Protocol (length-prefixed, little-endian):
//   request:  u8 op | u32 key_len | key bytes | u64 arg | u32 val_len | val
//   response: i64 code | u32 val_len | val bytes
// Ops: 0=SET 1=GET 2=ADD 3=WAIT 4=DELETE 5=PING
// GET code: 0 found, -1 missing. WAIT blocks server-side until key exists or
// arg (timeout ms, 0 = forever) elapses; code 0 ok, -2 timeout.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct StoreData {
  std::map<std::string, std::vector<uint8_t>> kv;
  std::mutex mu;
  std::condition_variable cv;
};

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::vector<std::thread> conn_threads;
  std::vector<int> conn_fds;
  std::mutex conn_mu;
  StoreData data;
};

bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_resp(int fd, int64_t code, const uint8_t* val, uint32_t len) {
  std::vector<uint8_t> out(sizeof(int64_t) + sizeof(uint32_t) + len);
  std::memcpy(out.data(), &code, sizeof(code));
  std::memcpy(out.data() + 8, &len, sizeof(len));
  if (len) std::memcpy(out.data() + 12, val, len);
  return write_full(fd, out.data(), out.size());
}

void serve_loop(Server* s, int fd);

// single exit point closes fd exactly once; server_stop only shutdown()s
// tracked fds to wake blocked reads, never closes them. The fd is removed
// from conn_fds under conn_mu BEFORE close so a later connection reusing the
// same fd number can't be shutdown() by server_stop.
void serve_conn(Server* s, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  serve_loop(s, fd);
  {
    std::lock_guard<std::mutex> lk(s->conn_mu);
    for (auto it = s->conn_fds.begin(); it != s->conn_fds.end(); ++it) {
      if (*it == fd) {
        s->conn_fds.erase(it);
        break;
      }
    }
  }
  ::close(fd);
}

void serve_loop(Server* s, int fd) {
  for (;;) {
    uint8_t op;
    uint32_t key_len;
    if (!read_full(fd, &op, 1) || !read_full(fd, &key_len, 4)) break;
    if (key_len > (1u << 20)) break;
    std::string key(key_len, '\0');
    if (key_len && !read_full(fd, key.data(), key_len)) break;
    uint64_t arg;
    uint32_t val_len;
    if (!read_full(fd, &arg, 8) || !read_full(fd, &val_len, 4)) break;
    if (val_len > (1u << 30)) break;
    std::vector<uint8_t> val(val_len);
    if (val_len && !read_full(fd, val.data(), val_len)) break;

    switch (op) {
      case 0: {  // SET
        {
          std::lock_guard<std::mutex> lk(s->data.mu);
          s->data.kv[key] = std::move(val);
        }
        s->data.cv.notify_all();
        if (!send_resp(fd, 0, nullptr, 0)) return;
        break;
      }
      case 1: {  // GET
        std::unique_lock<std::mutex> lk(s->data.mu);
        auto it = s->data.kv.find(key);
        if (it == s->data.kv.end()) {
          lk.unlock();
          if (!send_resp(fd, -1, nullptr, 0)) return;
        } else {
          std::vector<uint8_t> copy = it->second;
          lk.unlock();
          if (!send_resp(fd, 0, copy.data(),
                         static_cast<uint32_t>(copy.size())))
            return;
        }
        break;
      }
      case 2: {  // ADD (value stored as decimal string, like the reference)
        int64_t newv;
        {
          std::lock_guard<std::mutex> lk(s->data.mu);
          int64_t cur = 0;
          auto it = s->data.kv.find(key);
          if (it != s->data.kv.end()) {
            cur = std::strtoll(
                std::string(it->second.begin(), it->second.end()).c_str(),
                nullptr, 10);
          }
          newv = cur + static_cast<int64_t>(arg);
          std::string sv = std::to_string(newv);
          s->data.kv[key] = std::vector<uint8_t>(sv.begin(), sv.end());
        }
        s->data.cv.notify_all();
        if (!send_resp(fd, newv, nullptr, 0)) return;
        break;
      }
      case 3: {  // WAIT
        std::unique_lock<std::mutex> lk(s->data.mu);
        auto pred = [&] { return s->data.kv.count(key) > 0 || s->stop; };
        bool ok;
        if (arg == 0) {
          s->data.cv.wait(lk, pred);
          ok = s->data.kv.count(key) > 0;
        } else {
          ok = s->data.cv.wait_for(lk, std::chrono::milliseconds(arg), pred) &&
               s->data.kv.count(key) > 0;
        }
        lk.unlock();
        if (!send_resp(fd, ok ? 0 : -2, nullptr, 0)) return;
        break;
      }
      case 4: {  // DELETE
        int64_t erased;
        {
          std::lock_guard<std::mutex> lk(s->data.mu);
          erased = static_cast<int64_t>(s->data.kv.erase(key));
        }
        if (!send_resp(fd, erased, nullptr, 0)) return;
        break;
      }
      case 5: {  // PING
        if (!send_resp(fd, 0, nullptr, 0)) return;
        break;
      }
      default:
        send_resp(fd, -3, nullptr, 0);
        return;
    }
  }
}

void accept_loop(Server* s) {
  for (;;) {
    sockaddr_in addr{};
    socklen_t alen = sizeof(addr);
    int fd = ::accept(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    if (fd < 0) {
      if (s->stop) return;
      continue;
    }
    std::lock_guard<std::mutex> lk(s->conn_mu);
    s->conn_fds.push_back(fd);
    s->conn_threads.emplace_back(serve_conn, s, fd);
  }
}

struct Client {
  int fd = -1;
  std::mutex mu;  // one outstanding request per client handle
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------- server
void* pd_store_server_start(int port, int* actual_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 512) < 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  auto* s = new Server();
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  if (actual_port) *actual_port = s->port;
  s->accept_thread = std::thread(accept_loop, s);
  return s;
}

void pd_store_server_stop(void* handle) {
  auto* s = static_cast<Server*>(handle);
  if (!s) return;
  s->stop = true;
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  s->data.cv.notify_all();  // wake WAIT ops (their pred checks s->stop)
  if (s->accept_thread.joinable()) s->accept_thread.join();
  // wake blocked reads, then join every connection thread before freeing
  // the Server they point at (each thread closes its own fd on exit)
  {
    std::lock_guard<std::mutex> lk(s->conn_mu);
    for (int fd : s->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  for (auto& t : s->conn_threads) {
    if (t.joinable()) t.join();
  }
  delete s;
}

// ---------------------------------------------------------------- client
void* pd_store_client_connect(const char* host, int port, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms > 0 ? timeout_ms : 1);
  std::string port_str = std::to_string(port);
  for (;;) {
    // getaddrinfo so cluster hostnames ("worker-0", "localhost") work, not
    // just numeric IPv4 literals; re-resolved per attempt so DNS changes
    // during bring-up are picked up
    addrinfo hints{};
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    int connected_fd = -1;
    if (::getaddrinfo(host, port_str.c_str(), &hints, &res) == 0) {
      for (addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
        int fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
        if (fd < 0) continue;
        if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
          connected_fd = fd;
          break;
        }
        ::close(fd);
      }
      ::freeaddrinfo(res);
    }
    if (connected_fd >= 0) {
      int one = 1;
      ::setsockopt(connected_fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto* c = new Client();
      c->fd = connected_fd;
      return c;
    }
    if (std::chrono::steady_clock::now() >= deadline) return nullptr;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

void pd_store_client_close(void* handle) {
  auto* c = static_cast<Client*>(handle);
  if (!c) return;
  ::close(c->fd);
  delete c;
}

static int64_t request(Client* c, uint8_t op, const char* key, uint64_t arg,
                       const uint8_t* val, uint32_t val_len,
                       std::vector<uint8_t>* out) {
  std::lock_guard<std::mutex> lk(c->mu);
  uint32_t key_len = static_cast<uint32_t>(std::strlen(key));
  std::vector<uint8_t> req(1 + 4 + key_len + 8 + 4 + val_len);
  size_t off = 0;
  req[off++] = op;
  std::memcpy(req.data() + off, &key_len, 4);
  off += 4;
  std::memcpy(req.data() + off, key, key_len);
  off += key_len;
  std::memcpy(req.data() + off, &arg, 8);
  off += 8;
  std::memcpy(req.data() + off, &val_len, 4);
  off += 4;
  if (val_len) std::memcpy(req.data() + off, val, val_len);
  if (!write_full(c->fd, req.data(), req.size())) return -100;
  int64_t code;
  uint32_t rlen;
  if (!read_full(c->fd, &code, 8) || !read_full(c->fd, &rlen, 4)) return -100;
  if (rlen > (1u << 30)) return -100;
  if (out) {
    out->resize(rlen);
    if (rlen && !read_full(c->fd, out->data(), rlen)) return -100;
  } else if (rlen) {
    std::vector<uint8_t> sink(rlen);
    if (!read_full(c->fd, sink.data(), rlen)) return -100;
  }
  return code;
}

int64_t pd_store_set(void* handle, const char* key, const uint8_t* val,
                     uint32_t val_len) {
  return request(static_cast<Client*>(handle), 0, key, 0, val, val_len,
                 nullptr);
}

// returns value length (>=0) and copies min(len, buf_len) bytes into buf;
// -1 if missing, -100 on transport error
int64_t pd_store_get(void* handle, const char* key, uint8_t* buf,
                     uint32_t buf_len) {
  std::vector<uint8_t> out;
  int64_t code =
      request(static_cast<Client*>(handle), 1, key, 0, nullptr, 0, &out);
  if (code < 0) return code;
  uint32_t n = static_cast<uint32_t>(out.size());
  if (buf && buf_len) std::memcpy(buf, out.data(), std::min(n, buf_len));
  return static_cast<int64_t>(n);
}

// new counter value lands in *result; returns 0 ok, -100 transport error.
// (out-param keeps the full int64 range for counter values — no in-band
// sentinel collision)
int64_t pd_store_add(void* handle, const char* key, int64_t delta,
                     int64_t* result) {
  Client* c = static_cast<Client*>(handle);
  std::lock_guard<std::mutex> lk(c->mu);
  uint32_t key_len = static_cast<uint32_t>(std::strlen(key));
  uint64_t arg = static_cast<uint64_t>(delta);
  std::vector<uint8_t> req(1 + 4 + key_len + 8 + 4);
  size_t off = 0;
  req[off++] = 2;  // ADD
  std::memcpy(req.data() + off, &key_len, 4);
  off += 4;
  std::memcpy(req.data() + off, key, key_len);
  off += key_len;
  std::memcpy(req.data() + off, &arg, 8);
  off += 8;
  uint32_t zero = 0;
  std::memcpy(req.data() + off, &zero, 4);
  if (!write_full(c->fd, req.data(), req.size())) return -100;
  int64_t code;
  uint32_t rlen;
  if (!read_full(c->fd, &code, 8) || !read_full(c->fd, &rlen, 4)) return -100;
  if (rlen > (1u << 30)) return -100;
  if (rlen) {
    std::vector<uint8_t> sink(rlen);
    if (!read_full(c->fd, sink.data(), rlen)) return -100;
  }
  if (result) *result = code;
  return 0;
}

int64_t pd_store_wait(void* handle, const char* key, uint64_t timeout_ms) {
  return request(static_cast<Client*>(handle), 3, key, timeout_ms, nullptr, 0,
                 nullptr);
}

int64_t pd_store_delete(void* handle, const char* key) {
  return request(static_cast<Client*>(handle), 4, key, 0, nullptr, 0, nullptr);
}

int64_t pd_store_ping(void* handle) {
  return request(static_cast<Client*>(handle), 5, "", 0, nullptr, 0, nullptr);
}

}  // extern "C"
