// Async checkpoint writer with CRC32 integrity trailer.
//
// Reference analog: the save/load ops + framework serialization
// (paddle/fluid/framework/io/, save_op.cc) and the reference's PS-era
// background uploaders (auto_checkpoint to HDFS) — checkpoint IO happens off
// the training thread. TPU-native role: the training loop hands serialized
// bytes to a native writer thread (no GIL held during fwrite/fsync), so a
// multi-GB state snapshot overlaps the next train steps instead of stalling
// them. Each file gets a 24-byte trailer {magic, payload_len, crc32} the
// loader verifies to catch torn writes from preempted hosts.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <unistd.h>

namespace {

constexpr uint64_t kTrailerMagic = 0x50445450434b5054ULL;  // "PDTPCKPT"

// CRC-32 (IEEE 802.3), small table-driven implementation.
struct Crc32 {
  uint32_t table[256];
  Crc32() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
  }
  uint32_t run(const uint8_t* data, uint64_t n, uint32_t crc = 0) const {
    crc = ~crc;
    for (uint64_t i = 0; i < n; ++i)
      crc = table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
    return ~crc;
  }
};

const Crc32 kCrc;

struct WriteJob {
  std::string path;
  std::string tmp_path;
  uint8_t* data = nullptr;   // owned copy
  uint64_t size = 0;
  std::thread thread;
  std::atomic<int> status{-1};  // -1 running, 0 ok, >0 errno-style failure

  ~WriteJob() { delete[] data; }

  void run() {
    std::FILE* f = std::fopen(tmp_path.c_str(), "wb");
    if (!f) { status.store(1); return; }
    if (std::fwrite(data, 1, size, f) != size) {
      std::fclose(f); std::remove(tmp_path.c_str());
      status.store(2); return;
    }
    uint32_t crc = kCrc.run(data, size);
    uint64_t trailer[3] = {kTrailerMagic, size, crc};
    if (std::fwrite(trailer, 1, sizeof(trailer), f) != sizeof(trailer)) {
      std::fclose(f); std::remove(tmp_path.c_str());
      status.store(3); return;
    }
    std::fflush(f);
    ::fsync(fileno(f));  // survive host preemption: data must hit disk
    std::fclose(f);
    if (std::rename(tmp_path.c_str(), path.c_str()) != 0) {
      std::remove(tmp_path.c_str());
      status.store(4); return;
    }
    // the payload copy is dead weight once written; free it now so
    // poll-only callers don't hold checkpoint-sized memory until wait()
    delete[] data;
    data = nullptr;
    status.store(0);
  }
};

}  // namespace

extern "C" {

// Start an async write of `size` bytes to `path` (atomic via tmp+rename,
// CRC32 trailer appended). Copies the buffer; caller may free immediately.
void* pd_ckpt_async_write(const char* path, const void* data, uint64_t size) {
  static std::atomic<uint64_t> counter{0};
  auto* job = new WriteJob();
  job->path = path;
  // unique tmp per job: concurrent saves to one path must not share it
  job->tmp_path = std::string(path) + ".tmp." +
                  std::to_string(::getpid()) + "." +
                  std::to_string(counter.fetch_add(1));
  job->data = new uint8_t[size];
  job->size = size;
  std::memcpy(job->data, data, size);
  job->thread = std::thread([job] { job->run(); });
  return job;
}

// Non-blocking poll: -1 still running, 0 done ok, >0 failed.
int pd_ckpt_poll(void* handle) {
  return static_cast<WriteJob*>(handle)->status.load();
}

// Join the writer and free the job. Returns final status (0 ok).
int pd_ckpt_wait(void* handle) {
  auto* job = static_cast<WriteJob*>(handle);
  if (job->thread.joinable()) job->thread.join();
  int st = job->status.load();
  delete job;
  return st;
}

// Verify a file's CRC trailer. Returns payload size (>=0) when the trailer
// is present and the CRC matches, -1 when there is no trailer (legacy file),
// -2 on CRC mismatch / torn write, -3 on IO error.
int64_t pd_ckpt_verify(const char* path) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return -3;
  if (std::fseek(f, 0, SEEK_END) != 0) { std::fclose(f); return -3; }
  long end = std::ftell(f);
  if (end < static_cast<long>(24)) { std::fclose(f); return -1; }
  uint64_t trailer[3];
  std::fseek(f, end - 24, SEEK_SET);
  if (std::fread(trailer, 1, 24, f) != 24) { std::fclose(f); return -3; }
  if (trailer[0] != kTrailerMagic ||
      trailer[1] != static_cast<uint64_t>(end - 24)) {
    std::fclose(f);
    return -1;
  }
  uint64_t size = trailer[1];
  // streaming CRC: O(1) memory, single pass
  std::fseek(f, 0, SEEK_SET);
  uint8_t chunk[1 << 16];
  uint64_t left = size;
  uint32_t crc = 0;  // Crc32::run chains: crc_0 = 0 seeds the first chunk
  while (left > 0) {
    uint64_t n = left < sizeof(chunk) ? left : sizeof(chunk);
    if (std::fread(chunk, 1, n, f) != n) { std::fclose(f); return -3; }
    crc = kCrc.run(chunk, n, crc);
    left -= n;
  }
  std::fclose(f);
  return crc == static_cast<uint32_t>(trailer[2])
             ? static_cast<int64_t>(size) : -2;
}

}  // extern "C"
