"""Distributed checkpoint tests: sharded save + reshard-on-load on the
8-device virtual CPU mesh, plus auto_checkpoint epoch resume."""
import os

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.checkpoint import save_state_dict, load_state_dict
from paddle_tpu.incubate.checkpoint import train_epoch_range


def _mesh(shape, names):
    devs = np.array(jax.devices()[:int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


def test_sharded_save_and_reshard_load(tmp_path):
    mesh = _mesh((4, 2), ("data", "model"))
    w = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    sharded = jax.device_put(w, NamedSharding(mesh, P("data", "model")))
    state = {"w": paddle.Tensor(sharded), "step": 7}

    ckpt = str(tmp_path / "ckpt")
    save_state_dict(state, ckpt)
    assert os.path.exists(os.path.join(ckpt, "metadata.json"))

    # load replicated
    loaded = load_state_dict(ckpt)
    np.testing.assert_array_equal(np.asarray(loaded["w"]._value), w)
    assert loaded["step"] == 7

    # reshard onto a DIFFERENT mesh layout (the converter analog)
    mesh2 = _mesh((2, 4), ("data", "model"))
    loaded2 = load_state_dict(ckpt, shardings={"w": P("model", None)},
                              mesh=mesh2)
    arr = loaded2["w"]._value
    np.testing.assert_array_equal(np.asarray(arr), w)
    assert arr.sharding.spec == P("model", None)
    # each model-axis shard holds 16/4 = 4 rows (model axis is 4-way here)
    assert arr.addressable_shards[0].data.shape == (4, 8)


def test_load_numpy_and_partial_spec(tmp_path):
    mesh = _mesh((8,), ("data",))
    a = np.random.randn(8, 4).astype(np.float32)
    b = np.random.randn(3,).astype(np.float32)
    state = {
        "a": paddle.Tensor(jax.device_put(a, NamedSharding(mesh, P("data")))),
        "b": paddle.Tensor(jax.numpy.asarray(b)),
    }
    ckpt = str(tmp_path / "ckpt2")
    save_state_dict(state, ckpt)
    out = load_state_dict(ckpt, return_numpy=True)
    np.testing.assert_allclose(out["a"], a)
    np.testing.assert_allclose(out["b"], b)


def test_model_state_roundtrip_through_dist_ckpt(tmp_path):
    model = paddle.nn.Linear(6, 3)
    ckpt = str(tmp_path / "model_ckpt")
    save_state_dict(model.state_dict(), ckpt)
    loaded = load_state_dict(ckpt)
    model2 = paddle.nn.Linear(6, 3)
    model2.set_state_dict(loaded)
    x = paddle.to_tensor(np.random.randn(2, 6).astype(np.float32))
    np.testing.assert_allclose(model(x).numpy(), model2(x).numpy(),
                               rtol=1e-6)


def test_auto_checkpoint_resume(tmp_path):
    d = str(tmp_path / "auto")
    ran = []
    for epoch in train_epoch_range(5, save_dir=d, run_id="job1"):
        ran.append(epoch)
        if epoch == 2:
            break  # simulate a crash DURING epoch 2 (not marked complete)
    assert ran == [0, 1, 2]

    resumed = list(train_epoch_range(5, save_dir=d, run_id="job1"))
    assert resumed == [2, 3, 4]

    # fresh run id starts over
    fresh = list(train_epoch_range(3, save_dir=d, run_id="job2"))
    assert fresh == [0, 1, 2]
