"""Tests for paddle.fft, paddle.sparse, and paddle.autograd functional APIs."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import fft, sparse, autograd


def _t(a):
    return paddle.to_tensor(np.asarray(a))


# ---------------------------------------------------------------- fft

def test_fft_roundtrip_and_values():
    a = np.random.randn(8).astype(np.float32)
    got = fft.fft(_t(a)).numpy()
    np.testing.assert_allclose(got, np.fft.fft(a), rtol=1e-4, atol=1e-4)
    back = fft.ifft(_t(got)).numpy()
    np.testing.assert_allclose(back.real, a, rtol=1e-4, atol=1e-4)


def test_rfft_hfft_norms():
    a = np.random.randn(16).astype(np.float32)
    np.testing.assert_allclose(fft.rfft(_t(a)).numpy(), np.fft.rfft(a),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        fft.rfft(_t(a), norm="ortho").numpy(),
        np.fft.rfft(a, norm="ortho"), rtol=1e-4, atol=1e-4)
    r = np.fft.rfft(a)
    np.testing.assert_allclose(fft.irfft(_t(r), n=16).numpy(),
                               np.fft.irfft(r, n=16), rtol=1e-4, atol=1e-4)
    c = (np.random.randn(9) + 1j * np.random.randn(9)).astype(np.complex64)
    np.testing.assert_allclose(fft.hfft(_t(c)).numpy(), np.fft.hfft(c),
                               rtol=1e-3, atol=1e-3)


def test_fft2_fftn():
    a = np.random.randn(4, 6).astype(np.float32)
    np.testing.assert_allclose(fft.fft2(_t(a)).numpy(), np.fft.fft2(a),
                               rtol=1e-4, atol=1e-4)
    b = np.random.randn(2, 3, 4).astype(np.float32)
    np.testing.assert_allclose(fft.fftn(_t(b)).numpy(), np.fft.fftn(b),
                               rtol=1e-4, atol=1e-4)


def test_fftfreq_shift():
    np.testing.assert_allclose(fft.fftfreq(8, d=0.5).numpy(),
                               np.fft.fftfreq(8, 0.5), rtol=1e-6)
    a = np.arange(8.0, dtype=np.float32)
    np.testing.assert_allclose(fft.fftshift(_t(a)).numpy(), np.fft.fftshift(a))
    np.testing.assert_allclose(fft.ifftshift(_t(a)).numpy(),
                               np.fft.ifftshift(a))


# ---------------------------------------------------------------- sparse

def test_sparse_coo_roundtrip():
    indices = [[0, 1, 2], [1, 2, 0]]
    values = [1.0, 2.0, 3.0]
    s = sparse.sparse_coo_tensor(_t(np.array(indices, np.int64)),
                                 _t(np.array(values, np.float32)),
                                 shape=[3, 3])
    dense = s.to_dense().numpy()
    expect = np.zeros((3, 3), np.float32)
    expect[0, 1], expect[1, 2], expect[2, 0] = 1, 2, 3
    np.testing.assert_allclose(dense, expect)
    assert s.nnz() == 3


def test_sparse_csr_roundtrip():
    crows = np.array([0, 1, 3], np.int64)
    cols = np.array([1, 0, 2], np.int64)
    vals = np.array([4.0, 5.0, 6.0], np.float32)
    s = sparse.sparse_csr_tensor(_t(crows), _t(cols), _t(vals), [2, 3])
    expect = np.zeros((2, 3), np.float32)
    expect[0, 1], expect[1, 0], expect[1, 2] = 4, 5, 6
    np.testing.assert_allclose(s.to_dense().numpy(), expect)


def test_sparse_ops():
    idx = _t(np.array([[0, 1], [0, 1]], np.int64))
    s = sparse.sparse_coo_tensor(idx, _t(np.array([1.0, -2.0], np.float32)),
                                 shape=[2, 2])
    d = sparse.add(s, s).numpy()
    np.testing.assert_allclose(d, np.diag([2.0, -4.0]).astype(np.float32))
    r = sparse.relu(s)
    np.testing.assert_allclose(r.to_dense().numpy(),
                               np.diag([1.0, 0.0]).astype(np.float32))
    m = sparse.matmul(s, s).numpy()
    np.testing.assert_allclose(m, np.diag([1.0, 4.0]).astype(np.float32))


# ---------------------------------------------------------------- autograd

def test_jacobian():
    x = _t(np.array([1.0, 2.0, 3.0], np.float32))
    jac = autograd.jacobian(lambda v: v * v, x)
    np.testing.assert_allclose(jac.numpy(), np.diag([2.0, 4.0, 6.0]),
                               rtol=1e-5)


def test_hessian():
    x = _t(np.array([1.0, 2.0], np.float32))
    hes = autograd.hessian(lambda v: (v * v * v).sum(), x)
    np.testing.assert_allclose(hes.numpy(), np.diag([6.0, 12.0]), rtol=1e-5)


def test_vjp_jvp():
    x = _t(np.array([1.0, 2.0], np.float32))
    v = _t(np.array([1.0, 1.0], np.float32))
    out, g = autograd.vjp(lambda t: t * t, x, v)
    np.testing.assert_allclose(out.numpy(), [1.0, 4.0], rtol=1e-6)
    np.testing.assert_allclose(g.numpy(), [2.0, 4.0], rtol=1e-6)
    out, tangent = autograd.jvp(lambda t: t * t, x, v)
    np.testing.assert_allclose(tangent.numpy(), [2.0, 4.0], rtol=1e-6)


def test_pylayer():
    class Square(autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, grad):
            (x,) = ctx.saved_tensor
            return grad * 2.0 * x

    x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
    y = Square.apply(x)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0], rtol=1e-6)
