"""paddle.hub protocol (reference analog: python/paddle/hapi/hub.py +
test_hub.py: list/help/load over local and cached remote repos)."""
import os
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import hub


HUBCONF = textwrap.dedent('''
    import paddle_tpu as paddle

    def tiny_mlp(hidden=4):
        """A tiny MLP entrypoint."""
        return paddle.nn.Sequential(paddle.nn.Linear(2, hidden),
                                    paddle.nn.ReLU(),
                                    paddle.nn.Linear(hidden, 1))

    def _private_helper():
        return None
''')


def _make_repo(path):
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "hubconf.py"), "w") as f:
        f.write(HUBCONF)
    return str(path)


def test_local_list_help_load(tmp_path):
    repo = _make_repo(tmp_path / "repo")
    names = hub.list(repo, source="local")
    assert "tiny_mlp" in names and "_private_helper" not in names
    assert "tiny MLP" in hub.help(repo, "tiny_mlp")
    model = hub.load(repo, "tiny_mlp", hidden=8)
    out = model(paddle.to_tensor(np.ones((3, 2), np.float32)))
    assert out.shape == [3, 1]


def test_unknown_entrypoint_raises(tmp_path):
    repo = _make_repo(tmp_path / "repo2")
    with pytest.raises(ValueError, match="tiny_mlp"):
        hub.load(repo, "nope")


def test_remote_cache_hit_skips_download(tmp_path):
    """A pre-populated cache (owner_name_branch dir) serves github loads
    without any network touch (reference: _get_cache_or_reload reusing
    hub_home unless force_reload)."""
    hub.set_hub_home(str(tmp_path / "hubhome"))
    try:
        _make_repo(tmp_path / "hubhome" / "acme_models_main")
        names = hub.list("acme/models", source="github")
        assert "tiny_mlp" in names
        m = hub.load("acme/models:main", "tiny_mlp", source="github")
        assert m is not None
    finally:
        hub.set_hub_home(None)


def test_remote_without_cache_errors_clearly(tmp_path):
    hub.set_hub_home(str(tmp_path / "empty"))
    try:
        with pytest.raises((RuntimeError, Exception)) as ei:
            hub.load("acme/absent", "x", source="github")
        assert "download" in str(ei.value) or "egress" in str(ei.value)
    finally:
        hub.set_hub_home(None)


def test_bad_source_and_repo_format(tmp_path):
    with pytest.raises(ValueError, match="source"):
        hub.list("x", source="bitbucket")
    hub.set_hub_home(str(tmp_path / "h"))
    try:
        with pytest.raises(ValueError, match="owner/name"):
            hub.list("not-a-repo-path", source="github")
    finally:
        hub.set_hub_home(None)
