"""Compiled stochastic sampling suite (paddle_tpu/serving/sampling.py +
the engine's sampler head, logprobs, and pipelined decode).

The contracts pinned here are the PR 18 acceptance criteria:

  * every sampler knob is a per-slot VALUE in the one compiled decode
    step: heterogeneous sampler churn across 64 streams compiles decode
    exactly once;
  * ``temperature=0`` is greedy under the SAME program — token-identical
    to ``model.generate(do_sample=False)`` whatever the other knobs say;
  * a given (seed, prompt, sampler config) reproduces its token stream
    byte-identically across join-order permutations, preemption,
    watchdog rung-2 rebuild, and crash-checkpoint resume (the per-slot
    keys are ``fold_in(PRNGKey(seed), position)``, so a replay is a
    replay, not a re-roll);
  * per-token logprobs and static-K alternative panels ride the same
    executable with zero extra compiles;
  * software-pipelined decode (launch N+1 before committing N) is
    token-identical to the unpipelined engine, and the commit-lag-1
    transaction rolls a launched-but-uncommitted token back instead of
    leaking it into a cancelled stream.
"""
from __future__ import annotations

import os

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.incubate.models import GPTConfig, GPTForCausalLM
from paddle_tpu.ops import guardian
from paddle_tpu.serving import LLMEngine, FINISHED, CANCELLED
from paddle_tpu.serving.sampling import (SAMPLER_VERSION, default_seed,
                                         validate_sampler,
                                         apply_repetition_penalty,
                                         apply_temperature, apply_top_k,
                                         apply_top_p, sample_tokens)

VOCAB = 128


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _prompt(length, seed=0):
    rng = np.random.default_rng(seed * 1000 + length)
    return rng.integers(0, VOCAB, length).tolist()


_REF_CACHE = {}


def _ref(model, prompt, n):
    """Greedy reference through model.generate (memoized per length)."""
    key = (tuple(prompt), n)
    if key not in _REF_CACHE:
        out = model.generate(paddle.Tensor(np.asarray([prompt], np.int64)),
                             max_new_tokens=n, do_sample=False)
        arr = out._value if hasattr(out, "_value") else out
        _REF_CACHE[key] = np.asarray(arr)[0].tolist()
    return _REF_CACHE[key]


# A spread of sampler configs used by the determinism tests: greedy,
# temperature-only, top-k, top-p, and the full stack.
SAMPLERS = (
    dict(),
    dict(temperature=0.7, seed=11),
    dict(temperature=1.0, top_k=12, seed=12),
    dict(temperature=0.9, top_p=0.85, seed=13),
    dict(temperature=1.1, top_k=24, top_p=0.9, repetition_penalty=1.3,
         seed=14),
)


def _run_streams(model, prompts, cfgs, n_new=8, **eng_kw):
    """One engine, one request per (prompt, sampler cfg); returns the
    generated token lists in request order plus the engine."""
    eng = LLMEngine(model, max_batch_size=4, block_size=4, **eng_kw)
    reqs = [eng.add_request(p, max_new_tokens=n_new, **cfg)
            for p, cfg in zip(prompts, cfgs)]
    eng.run()
    return [list(r.generated) for r in reqs], eng


# ---------------------------------------------------------------------------
# pure sampler math (no engine, no model)
# ---------------------------------------------------------------------------

class TestSamplerHelpers:
    def test_validate_sampler_contract(self):
        validate_sampler(0.0, 0, 1.0, 1.0)            # greedy defaults
        validate_sampler(1.5, 40, 0.9, 1.2)           # the full stack
        for bad in (-0.5, float("nan"), float("inf")):
            with pytest.raises(ValueError, match="temperature"):
                validate_sampler(bad, 0, 1.0, 1.0)
        with pytest.raises(ValueError, match="top_k"):
            validate_sampler(1.0, -1, 1.0, 1.0)
        for bad in (0.0, -0.1, 1.5):
            with pytest.raises(ValueError, match="top_p"):
                validate_sampler(1.0, 0, bad, 1.0)
        for bad in (0.0, -1.0, float("inf")):
            with pytest.raises(ValueError, match="repetition_penalty"):
                validate_sampler(1.0, 0, 1.0, bad)

    def test_default_seed_is_stable_and_rid_keyed(self):
        # crc32 of the rid: process-stable (serializes through crash
        # checkpoints), distinct per request id
        assert default_seed("r1") == default_seed("r1")
        assert default_seed("r1") != default_seed("r2")
        s = default_seed("anything")
        assert isinstance(s, int) and 0 <= s < 2**32

    def test_temperature_zero_is_divide_safe(self):
        lg = jnp.asarray(np.random.default_rng(0)
                         .normal(size=(3, 16)).astype(np.float32))
        out = np.asarray(apply_temperature(
            lg, jnp.asarray([0.0, 1.0, 2.0], jnp.float32)))
        assert np.all(np.isfinite(out))
        # T=1 row is untouched, T=2 row is halved
        np.testing.assert_allclose(out[1], np.asarray(lg)[1], rtol=1e-6)
        np.testing.assert_allclose(out[2], np.asarray(lg)[2] / 2,
                                   rtol=1e-6)

    def test_top_k_zero_disables_and_one_is_argmax(self):
        lg = jnp.asarray(np.random.default_rng(1)
                         .normal(size=(2, 32)).astype(np.float32))
        off = np.asarray(apply_top_k(lg, jnp.asarray([0, 0], jnp.int32)))
        np.testing.assert_array_equal(off, np.asarray(lg))
        one = np.asarray(apply_top_k(lg, jnp.asarray([1, 1], jnp.int32)))
        for row, raw in zip(one, np.asarray(lg)):
            kept = np.flatnonzero(row > -1e29)
            assert kept.tolist() == [int(np.argmax(raw))]

    def test_top_p_one_is_exact_noop_and_top1_survives(self):
        lg = jnp.asarray(np.random.default_rng(2)
                         .normal(size=(2, 32)).astype(np.float32))
        noop = np.asarray(apply_top_p(lg, jnp.asarray([1.0, 1.0],
                                                      jnp.float32)))
        np.testing.assert_array_equal(noop, np.asarray(lg))
        # p small enough to keep only the nucleus head: the argmax token
        # must ALWAYS survive (exclusive-mass test)
        tight = np.asarray(apply_top_p(lg, jnp.asarray([1e-6, 1e-6],
                                                       jnp.float32)))
        for row, raw in zip(tight, np.asarray(lg)):
            assert row[int(np.argmax(raw))] > -1e29

    def test_repetition_penalty_noop_and_ctrl_rule(self):
        lg = jnp.asarray([[2.0, -1.0, 0.5, 3.0]], jnp.float32)
        hist = jnp.asarray([[0, 1, 1]], jnp.int32)
        valid = jnp.asarray([[True, True, False]])
        noop = np.asarray(apply_repetition_penalty(
            lg, hist, valid, jnp.asarray([1.0], jnp.float32)))
        np.testing.assert_array_equal(noop, np.asarray(lg))
        out = np.asarray(apply_repetition_penalty(
            lg, hist, valid, jnp.asarray([2.0], jnp.float32)))[0]
        assert out[0] == pytest.approx(1.0)    # seen positive: divided
        assert out[1] == pytest.approx(-2.0)   # seen negative: multiplied
        assert out[2] == pytest.approx(0.5)    # unseen: untouched
        assert out[3] == pytest.approx(3.0)    # invalid history entry

    def test_all_greedy_head_is_raw_argmax_with_logprob_panels(self):
        rng = np.random.default_rng(3)
        lg = jnp.asarray(rng.normal(size=(4, VOCAB)).astype(np.float32))
        zeros = jnp.zeros(4, jnp.float32)
        args = (lg, zeros, jnp.zeros(4, jnp.int32),
                jnp.ones(4, jnp.float32), jnp.ones(4, jnp.float32),
                jnp.zeros(4, jnp.uint32), jnp.zeros(4, jnp.int32),
                jnp.zeros((4, 8), jnp.int32), jnp.zeros((4, 8), bool))
        nxt, chosen, alt_ids, alt_lps = sample_tokens(*args,
                                                      logprobs_topk=3)
        raw = np.asarray(lg)
        np.testing.assert_array_equal(np.asarray(nxt),
                                      np.argmax(raw, axis=-1))
        # chosen logprob comes from the raw log-softmax; the greedy token
        # is also the top-1 panel entry with the identical value
        ref_lp = raw - np.log(np.exp(raw).sum(-1, keepdims=True))
        np.testing.assert_allclose(
            np.asarray(chosen), ref_lp[np.arange(4), np.asarray(nxt)],
            rtol=1e-5)
        assert np.asarray(alt_ids).shape == (4, 3)
        np.testing.assert_array_equal(np.asarray(alt_ids)[:, 0],
                                      np.asarray(nxt))
        np.testing.assert_allclose(np.asarray(alt_lps)[:, 0],
                                   np.asarray(chosen), rtol=1e-5)
        # panels are sorted descending
        lps = np.asarray(alt_lps)
        assert np.all(np.diff(lps, axis=-1) <= 1e-7)

    def test_top_k_one_forces_argmax_even_when_stochastic(self):
        rng = np.random.default_rng(4)
        lg = jnp.asarray(rng.normal(size=(4, VOCAB)).astype(np.float32))
        nxt, _, _, _ = sample_tokens(
            lg, jnp.full(4, 1.0, jnp.float32), jnp.ones(4, jnp.int32),
            jnp.ones(4, jnp.float32), jnp.ones(4, jnp.float32),
            jnp.asarray([5, 6, 7, 8], jnp.uint32),
            jnp.asarray([3, 4, 5, 6], jnp.int32),
            jnp.zeros((4, 8), jnp.int32), jnp.zeros((4, 8), bool))
        np.testing.assert_array_equal(np.asarray(nxt),
                                      np.argmax(np.asarray(lg), -1))

    def test_same_key_same_draw_new_position_new_draw(self):
        rng = np.random.default_rng(5)
        lg = jnp.asarray(rng.normal(size=(8, VOCAB)).astype(np.float32))
        base = (lg, jnp.full(8, 1.2, jnp.float32),
                jnp.zeros(8, jnp.int32), jnp.ones(8, jnp.float32),
                jnp.ones(8, jnp.float32), jnp.arange(8, dtype=jnp.uint32))
        hist = (jnp.zeros((8, 8), jnp.int32), jnp.zeros((8, 8), bool))
        pos = jnp.full(8, 9, jnp.int32)
        a = np.asarray(sample_tokens(*base, pos, *hist)[0])
        b = np.asarray(sample_tokens(*base, pos, *hist)[0])
        np.testing.assert_array_equal(a, b)          # replay == replay
        c = np.asarray(sample_tokens(*base, pos + 1, *hist)[0])
        assert not np.array_equal(a, c)              # stream advanced

    def test_sampler_version_is_pinned(self):
        # bumping the math without bumping the version would let stale
        # AOT exports replay silently — freeze the current value
        assert SAMPLER_VERSION == 2


# ---------------------------------------------------------------------------
# greedy identity: temperature=0 under the sampling program
# ---------------------------------------------------------------------------

class TestGreedyIdentity:
    def test_temperature_zero_matches_generate_in_mixed_batch(self, model):
        """A greedy stream sharing slots with stochastic neighbors stays
        token-identical to model.generate(do_sample=False) — sampling is
        per-slot, never batch-global."""
        prompts = [_prompt(n, seed=50) for n in (9, 7, 11, 6)]
        cfgs = [dict(),
                dict(temperature=1.0, top_k=20, seed=51),
                dict(),
                dict(temperature=0.8, top_p=0.9, seed=52)]
        outs, eng = _run_streams(model, prompts, cfgs)
        assert outs[0] == _ref(model, prompts[0], 8)
        assert outs[2] == _ref(model, prompts[2], 8)
        st = eng.stats()
        assert st["decode_compiles"] == 1
        assert st["sampled_tokens"] == 16            # the two hot streams

    def test_other_knobs_inert_at_temperature_zero(self, model):
        """top_k/top_p/repetition_penalty/seed do nothing at T=0: the
        greedy select reads the RAW logits."""
        p = _prompt(10, seed=53)
        outs, _ = _run_streams(
            model, [p], [dict(temperature=0.0, top_k=3, top_p=0.5,
                              repetition_penalty=1.8, seed=99)])
        assert outs[0] == _ref(model, p, 8)


# ---------------------------------------------------------------------------
# zero-retrace churn
# ---------------------------------------------------------------------------

class TestZeroRetraceSampling:
    def test_heterogeneous_sampler_churn_one_compile(self, model):
        """The acceptance criterion: 32 streams cycling five different
        sampler configs (greedy included) through 4 slots — the decode
        executable compiles exactly once."""
        prompts = [_prompt(3 + (i % 7), seed=54) for i in range(32)]
        cfgs = []
        for i in range(32):
            cfg = dict(SAMPLERS[i % len(SAMPLERS)])
            if "seed" in cfg:
                cfg["seed"] = 1000 + i               # every stream unique
            cfgs.append(cfg)
        outs, eng = _run_streams(model, prompts, cfgs, n_new=5)
        st = eng.stats()
        assert st["decode_compiles"] == 1
        assert st["completed"] == 32
        assert st["sampled_tokens"] > 0
        assert all(len(o) == 5 for o in outs)

    def test_invalid_sampler_refused_not_compiled(self, model):
        eng = LLMEngine(model, max_batch_size=2, block_size=4)
        for bad in (dict(temperature=-1.0), dict(top_k=-2),
                    dict(top_p=0.0), dict(repetition_penalty=0.0)):
            with pytest.raises(ValueError):
                eng.add_request(_prompt(5, seed=55), **bad)
        assert eng.stats()["decode_compiles"] == 0   # nothing traced


# ---------------------------------------------------------------------------
# (seed, prompt, sampler) byte-identical reproduction
# ---------------------------------------------------------------------------

class TestSampledDeterminism:
    def test_streams_invariant_under_join_order(self, model):
        """Each stream's tokens depend only on ITS (seed, prompt,
        sampler) — not on which neighbors shared the batch or the
        admission order."""
        prompts = [_prompt(n, seed=56) for n in (8, 11, 6, 9, 7)]
        cfgs = [dict(SAMPLERS[i % len(SAMPLERS)]) for i in range(5)]
        fwd, e1 = _run_streams(model, prompts, cfgs)
        rev, e2 = _run_streams(model, list(reversed(prompts)),
                               list(reversed(cfgs)))
        assert fwd == list(reversed(rev))
        assert e1.stats()["decode_compiles"] == 1
        assert e2.stats()["decode_compiles"] == 1

    def test_preempt_resume_replays_not_rerolls(self, model):
        """A deliberately tight pool forces eviction of sampled streams;
        the re-prefilled stream continues from restored positions, so the
        draws replay byte-identically vs a roomy never-preempted run."""
        prompts = [_prompt(n, seed=57) for n in (11, 12, 10, 5)]
        cfgs = [dict(temperature=0.9, top_k=16, top_p=0.9,
                     seed=2000 + i) for i in range(4)]
        roomy = LLMEngine(model, max_batch_size=3, block_size=4)
        refs = [roomy.add_request(p, max_new_tokens=10, **c)
                for p, c in zip(prompts, cfgs)]
        roomy.run()
        tight = LLMEngine(model, max_batch_size=3, block_size=4,
                          num_blocks=10, watermark_blocks=1)
        got = [tight.add_request(p, max_new_tokens=10, **c)
               for p, c in zip(prompts, cfgs)]
        tight.run()
        st = tight.stats()
        assert st["evictions"] >= 1                  # the pool actually bit
        assert st["decode_compiles"] == 1
        for r, g in zip(refs, got):
            assert list(g.generated) == list(r.generated)

    def test_rung2_rebuild_replays_sampled_streams(self, model):
        """Two consecutive hangs climb to rung 2: the decode executable
        is REBUILT mid-stream. The rebuilt program derives the same
        fold_in(seed, position) keys, so every sampled stream continues
        byte-identically (the retrace is honest: compiles goes to 2)."""
        prompts = [_prompt(n, seed=58) for n in (9, 6)]
        cfgs = [dict(temperature=0.8, top_k=20, seed=3001),
                dict(temperature=1.0, top_p=0.9, seed=3002)]
        clean, _ = _run_streams(model, prompts, cfgs, n_new=8,
                                max_queue_depth=None)
        set_flags({"FLAGS_serve_step_timeout_ms": 2000})
        eng = LLMEngine(model, max_batch_size=4, block_size=4)
        reqs = [eng.add_request(p, max_new_tokens=8, **c)
                for p, c in zip(prompts, cfgs)]
        for _ in range(3):
            eng.step()
        guardian.inject_fault("hang", op="serve.decode", times=2)
        try:
            eng.run()
        finally:
            guardian.clear_faults()
        st = eng.stats()
        assert st["hangs"] == 2
        assert st["decode_compiles"] == 2            # the rung-2 rebuild
        assert not eng.degraded
        for r, ref in zip(reqs, clean):
            assert r.state == FINISHED and list(r.generated) == ref

    def test_crash_resume_replays_sampled_streams(self, model):
        """state_payload() serializes the sampler identity; a FRESH
        engine restoring mid-flight sampled streams finishes them with
        the same final tokens as the uninterrupted run."""
        prompts = [_prompt(n, seed=59) for n in (11, 6, 9)]
        cfgs = [dict(temperature=0.9, top_k=24, top_p=0.95,
                     repetition_penalty=1.1, seed=4000 + i)
                for i in range(3)]
        clean, _ = _run_streams(model, prompts, cfgs, n_new=10)
        eng = LLMEngine(model, max_batch_size=2, block_size=4)
        for i, (p, c) in enumerate(zip(prompts, cfgs)):
            eng.add_request(p, max_new_tokens=10, request_id=f"s{i}", **c)
        for _ in range(5):
            eng.step()                               # mid-flight
        payload = eng.state_payload()
        assert payload["requests"]
        eng2 = LLMEngine(model, max_batch_size=2, block_size=4)
        restored = eng2.restore_state(payload)
        eng2.run()
        by_rid = {r.rid: r for r in restored}
        for i, ref in enumerate(clean):
            rid = f"s{i}"
            if rid in by_rid:
                assert by_rid[rid].state == FINISHED
                assert list(by_rid[rid].generated) == ref


# ---------------------------------------------------------------------------
# per-token logprobs
# ---------------------------------------------------------------------------

class TestLogprobs:
    def test_logprob_panels_ride_the_one_compile(self, model):
        prompts = [_prompt(n, seed=60) for n in (8, 10)]
        eng = LLMEngine(model, max_batch_size=2, block_size=4,
                        logprobs_topk=2)
        greedy = eng.add_request(prompts[0], max_new_tokens=6)
        hot = eng.add_request(prompts[1], max_new_tokens=6,
                              temperature=0.9, top_k=16, seed=61)
        eng.run()
        assert eng.stats()["decode_compiles"] == 1
        for r in (greedy, hot):
            lp = r.logprobs()
            assert set(lp) == {"token_logprobs", "topk_ids",
                               "topk_logprobs"}
            assert len(lp["token_logprobs"]) == len(r.generated) == 6
            for v in lp["token_logprobs"]:
                assert v is not None and np.isfinite(v) and v <= 1e-6
            for ids, lps in zip(lp["topk_ids"], lp["topk_logprobs"]):
                assert len(ids) == 2 and len(lps) == 2
                assert lps[0] >= lps[1] - 1e-7       # sorted panel
        # the greedy stream's chosen token IS the top-1 alternative, and
        # the two logprob views agree bit-for-bit
        glp = greedy.logprobs()
        for tok, chosen, ids, lps in zip(greedy.generated,
                                         glp["token_logprobs"],
                                         glp["topk_ids"],
                                         glp["topk_logprobs"]):
            assert ids[0] == tok
            assert lps[0] == pytest.approx(chosen, abs=1e-6)

    def test_default_engine_keeps_alt_panels_off(self, model):
        eng = LLMEngine(model, max_batch_size=2, block_size=4)
        req = eng.add_request(_prompt(7, seed=62), max_new_tokens=4,
                              temperature=0.8, seed=63)
        eng.run()
        lp = req.logprobs()
        assert len(lp["token_logprobs"]) == 4
        assert all(a is None for a in lp["topk_ids"])
        assert all(a is None for a in lp["topk_logprobs"])


# ---------------------------------------------------------------------------
# software-pipelined decode
# ---------------------------------------------------------------------------

class TestPipelined:
    def test_pipelined_parity_with_unpipelined(self, model):
        """pipeline_decode=True must change WHEN tokens are committed,
        never WHICH tokens: mixed greedy+sampled streams are bitwise
        identical to the unpipelined engine, one compile each, and the
        clean drain needs zero rollbacks."""
        prompts = [_prompt(n, seed=64) for n in (9, 6, 11, 7, 8)]
        cfgs = [dict(SAMPLERS[i % len(SAMPLERS)]) for i in range(5)]
        plain, e1 = _run_streams(model, prompts, cfgs)
        piped, e2 = _run_streams(model, prompts, cfgs,
                                 pipeline_decode=True)
        assert piped == plain
        assert e1.stats()["decode_compiles"] == 1
        assert e2.stats()["decode_compiles"] == 1
        assert e2.stats()["commit_rollbacks"] == 0

    def test_commit_lag_cancel_rolls_back_not_leaks(self, model):
        """Cancel lands between launch N+1 and its commit: the launched
        token for the cancelled slot is rolled back (never appended),
        the rollback is attributed, and the surviving streams finish
        bitwise-identically to the unpipelined run."""
        prompts = [_prompt(n, seed=65) for n in (10, 8, 9)]
        cfgs = [dict(temperature=0.9, top_k=20, seed=5000 + i)
                for i in range(3)]
        plain, _ = _run_streams(model, prompts, cfgs, n_new=10)
        eng = LLMEngine(model, max_batch_size=4, block_size=4,
                        pipeline_decode=True)
        reqs = [eng.add_request(p, max_new_tokens=10, **c)
                for p, c in zip(prompts, cfgs)]
        for _ in range(4):
            eng.step()                   # an uncommitted launch in flight
        victim = reqs[1]
        n_before = len(victim.generated)
        eng.cancel(victim.rid)
        eng.run()
        st = eng.stats()
        assert victim.state == CANCELLED
        assert len(victim.generated) == n_before     # nothing leaked
        assert st["commit_rollbacks"] >= 1
        assert st["decode_compiles"] == 1
        assert list(reqs[0].generated) == plain[0]
        assert list(reqs[2].generated) == plain[2]
