"""dy2static: AST conversion of data-dependent control flow to
lax.cond/while_loop (reference analog: dygraph_to_static
ifelse_transformer.py / loop_transformer.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.dy2static import (ast_transform, convert_ifelse,
                                      convert_while, convert_range_for,
                                      convert_iter_for, Dy2StaticError)


def test_tensor_if_compiles_both_branches():
    @paddle.jit.to_static
    def f(x):
        if x.sum() > 0:
            y = x * 2
        else:
            y = x - 1
        return y

    pos = paddle.to_tensor(np.ones(3, np.float32))
    neg = paddle.to_tensor(-np.ones(3, np.float32))
    np.testing.assert_allclose(np.asarray(f(pos)._value), 2.0)
    np.testing.assert_allclose(np.asarray(f(neg)._value), -2.0)
    # one cache entry serves both predicate values (it's a lax.cond, not a
    # retrace per branch)
    assert len(f._jitted) == 1


def test_tensor_while_compiles():
    @paddle.jit.to_static
    def g(x):
        s = x
        while s.sum() < 100.0:
            s = s * 2
        return s

    out = np.asarray(g(paddle.to_tensor(np.ones(3, np.float32)))._value)
    assert out.sum() >= 100 and out.sum() / 2 < 100


def test_python_condition_untouched():
    @paddle.jit.to_static
    def h(x, flag=True):
        if flag:
            return x + 1
        return x - 1

    x = paddle.to_tensor(np.ones(2, np.float32))
    np.testing.assert_allclose(np.asarray(h(x)._value), 2.0)


def test_if_updating_multiple_locals():
    @paddle.jit.to_static
    def f(x):
        a = x
        b = x * 0
        if x.mean() > 0:
            a = a + 10
            b = b + 1
        else:
            a = a - 10
            b = b - 1
        return a + b

    pos = paddle.to_tensor(np.ones(2, np.float32))
    neg = paddle.to_tensor(-np.ones(2, np.float32))
    np.testing.assert_allclose(np.asarray(f(pos)._value), 12.0)
    np.testing.assert_allclose(np.asarray(f(neg)._value), -12.0)


def test_loop_accumulator_with_counter():
    @paddle.jit.to_static
    def f(x, n):
        i = paddle.to_tensor(np.int32(0))
        acc = x * 0
        while i < n:
            acc = acc + x
            i = i + 1
        return acc

    x = paddle.to_tensor(np.full(3, 2.0, np.float32))
    n = paddle.to_tensor(np.int32(5))
    np.testing.assert_allclose(np.asarray(f(x, n)._value), 10.0)


def test_grad_through_transformed_if():
    @paddle.jit.to_static
    def f(x):
        if x.sum() > 0:
            y = x * x
        else:
            y = x * 3
        return y.sum()

    x = paddle.to_tensor(np.array([2.0, 1.0], np.float32),
                         stop_gradient=False)
    f(x).backward()
    np.testing.assert_allclose(np.asarray(x.grad._value), [4.0, 2.0])


def test_ast_transform_returns_none_for_closures():
    z = 3

    def f(x):
        return x + z        # closure over z

    assert ast_transform(f) is None


def test_convert_helpers_concrete_fallback():
    out = convert_ifelse(True, lambda a: (a + 1,), lambda a: (a - 1,), (5,))
    assert out == (6,)
    out = convert_while(lambda i: i < 3, lambda i: (i + 1,), (0,))
    assert out == (3,)


def test_tensor_bounded_for_compiles():
    # `range(n)` with a traced bound: one lax.while_loop, not a retrace
    # per n (reference analog: loop_transformer.py for_loop conversion)
    @paddle.jit.to_static
    def f(x, n):
        s = x * 0
        for i in range(n):
            s = s + x
        return s

    x = paddle.to_tensor(np.full(3, 2.0, np.float32))
    for n in (3, 5):
        out = f(x, paddle.to_tensor(np.int32(n)))
        np.testing.assert_allclose(np.asarray(out._value), 2.0 * n)
    assert len(f._jitted) == 1


def test_for_loop_carried_state_parity():
    # transformed function == eager python semantics, incl. start/step
    def f(x):
        s = x * 0
        for i in range(1, 8, 2):
            s = s + x * i
        return s, i

    g = ast_transform(f)
    assert g is not None
    x = paddle.to_tensor(np.ones(2, np.float32))
    s1, i1 = f(x)
    s2, i2 = g(x)
    np.testing.assert_allclose(np.asarray(s1._value), np.asarray(s2._value))
    assert int(i1) == 7 and int(i2) == 7


def test_while_with_break():
    @paddle.jit.to_static
    def f(x, limit):
        s = x
        while s.sum() < 1000.0:
            s = s * 2
            if s.sum() > limit:
                break
        return s

    x = paddle.to_tensor(np.ones(2, np.float32))
    out = np.asarray(f(x, paddle.to_tensor(np.float32(10.0)))._value)
    assert out.sum() > 10.0 and out.sum() / 2 <= 10.0


def test_while_concrete_cond_traced_break():
    # concrete loop condition, but the lowered break flag becomes traced
    # mid-loop: convert_while must restart as a lax.while_loop
    @paddle.jit.to_static
    def f(x, limit):
        i = 0
        s = x * 0
        while i < 5:
            s = s + x
            if s.sum() > limit:
                break
            i = i + 1
        return s

    x = paddle.to_tensor(np.ones(2, np.float32))
    out = np.asarray(f(x, paddle.to_tensor(np.float32(4.5)))._value)
    np.testing.assert_allclose(out, 3.0)     # breaks once sum() = 6 > 4.5


def test_for_with_continue():
    @paddle.jit.to_static
    def f(x, n):
        s = x * 0
        for i in range(n):
            if i % 2 == 1:
                continue
            s = s + x
        return s

    x = paddle.to_tensor(np.full(2, 3.0, np.float32))
    out = np.asarray(f(x, paddle.to_tensor(np.int32(6)))._value)
    np.testing.assert_allclose(out, 9.0)   # i = 0, 2, 4


def test_for_over_tensor_with_break():
    @paddle.jit.to_static
    def f(xs, limit):
        s = xs[0] * 0
        for v in xs:
            if v.sum() > limit:
                break
            s = s + v
        return s

    xs = paddle.to_tensor(np.arange(6, dtype=np.float32))
    out = np.asarray(f(xs, paddle.to_tensor(np.float32(3.5)))._value)
    np.testing.assert_allclose(out, 0.0 + 1 + 2 + 3)


def test_convert_for_helpers_concrete():
    out = convert_range_for((3,), lambda v, s: (s + v,), (0,))
    assert out == (3,)     # 0 + 1 + 2
    out = convert_range_for((1, 8, 2), lambda v, s: (s + v,), (0,))
    assert out == (16,)
    out = convert_iter_for([4, 5], lambda v, s: (s + v,), (1,))
    assert out == (10,)
    # break flag honored in the python path (flag at index 1)
    out = convert_range_for(
        (10,), lambda v, s, brk: (s + v, v >= 2), (0, False),
        item_idx=None, brk_idx=1)
    assert out[0] == 0 + 1 + 2


def test_mismatched_branches_raise():
    import jax
    import jax.numpy as jnp

    def run(xv):
        t = paddle.Tensor(xv, stop_gradient=True)
        out = convert_ifelse(
            (t.sum() > 0),
            lambda a: (a * 2,),            # tensor
            lambda a: ("static-string",),  # static
            (t,))
        return out[0]._value

    with pytest.raises(Dy2StaticError):
        jax.jit(run)(jnp.ones(2))
