"""Live HTTP observability plane (PR 13): telemetry server, fleet merge,
and per-step goodput attribution.

Contracts pinned here:

  * every endpoint (/metrics, /metrics.json, /goodput, /doctor, /events,
    /healthz, /readyz) answers with its documented shape, and the server
    is fully inert when off (heartbeats are a no-op, FLAGS_telemetry_port
    defaults to 0);
  * /healthz is a real liveness probe: the train heartbeat goes stale
    past its window on an open accounting window (and not on a finalized
    one), and an injected wall-clock stall (guardian.inject_fault
    "stall") flips a busy engine unhealthy within one watchdog window —
    recovering after the first clean step;
  * /readyz mirrors the engine degraded latch + decode-compiled state;
  * a scraper hammering /metrics + /doctor at ~100 Hz while 64 mixed
    streams churn leaves `decode_compiles == 1` and every response
    parseable; kill-9 mid-scrape leaves no stuck socket — the port
    rebinds immediately;
  * the goodput accountant attributes WHICH steps landed in each
    non-productive bucket (bounded rings), visible in /goodput, the
    doctor report, and the goodput_step_index exposition gauge;
  * tools/fleet_metrics.py merges >=2 process sinks/endpoints into one
    fleet view whose goodput equals the hand-merged accountant
    snapshots (±1e-9), with per-host labels and a drift section;
  * `fusion_doctor --url` renders a live process's /doctor report with
    the same schema as --json.
"""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops import guardian
from paddle_tpu.ops.dispatch import clear_dispatch_cache
from paddle_tpu.profiler import goodput as pg
from paddle_tpu.profiler import metrics as pm
from paddle_tpu.profiler import telemetry_server as ts
from paddle_tpu.profiler.events import clear_fusion_events

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_DEFAULT_FLAGS = {
    "FLAGS_metrics": False,
    "FLAGS_check_numerics": False,
    "FLAGS_check_numerics_level": 0,
    "FLAGS_profiler_events": False,
    "FLAGS_serve_step_timeout_ms": 0,
    "FLAGS_telemetry_port": 0,
    "FLAGS_telemetry_stale_s": 120.0,
    "FLAGS_eager_op_cache": True,
    "FLAGS_eager_chain_fusion": True,
    "FLAGS_eager_chain_fusion_min_count": 3,
    "FLAGS_eager_step_fusion": True,
    "FLAGS_eager_step_fusion_min_count": 4,
}


@pytest.fixture(autouse=True)
def _fresh():
    set_flags(dict(_DEFAULT_FLAGS))
    ts.stop()
    ts._ENGINES.clear()
    pm.reset_metrics()
    clear_fusion_events()
    guardian.clear_faults()
    guardian.reset_thread_state()
    yield
    ts.stop()
    ts._ENGINES.clear()
    set_flags(dict(_DEFAULT_FLAGS))
    pm.reset_metrics()
    clear_fusion_events()
    guardian.clear_faults()
    guardian.reset_thread_state()


def _get(url, timeout=15):
    """(status, parsed body) via the shared client helper — 4xx/5xx
    return their JSON body too, /metrics comes back as text."""
    return ts.probe_endpoint(url, timeout=timeout)


VOCAB = 128


@pytest.fixture(scope="module")
def smodel():
    from paddle_tpu.incubate.models import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _prompts(n, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, int(k)).tolist()
            for k in rng.integers(3, 16, n)]


def _train_loop(steps, d=32):
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((16, d)).astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((d, d)).astype(np.float32),
                         stop_gradient=False)
    b = paddle.to_tensor(rng.standard_normal(d).astype(np.float32),
                         stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=1e-3, parameters=[w, b])
    for _ in range(steps):
        y = F.gelu(paddle.add(paddle.matmul(x, w), b))
        loss = y.sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    w._value.block_until_ready()


# ---------------------------------------------------------------------------
# off-state + unit pieces
# ---------------------------------------------------------------------------

class TestOffState:
    def test_default_flag_is_off_and_beat_is_inert(self):
        assert ts.maybe_start_from_flags() is None
        assert ts.server() is None and ts.server_port() is None
        ts.beat("train", step=7)
        assert ts._HEART == {}          # module-bool gate: nothing stored

    def test_format_step_ranges(self):
        fmt = pg.format_step_ranges
        assert fmt([]) == ""
        assert fmt([5]) == "5"
        assert fmt([1032, 2048, 4096, 4097, 4098, 4099]) \
            == "1032, 2048, 4096-4099"
        assert fmt([3, 1, 2, 9]) == "1-3, 9"
        assert fmt([4, 4, 5]) == "4-5"  # dedup


# ---------------------------------------------------------------------------
# endpoints
# ---------------------------------------------------------------------------

class TestEndpoints:
    def test_every_endpoint_answers(self):
        set_flags({"FLAGS_metrics": True, "FLAGS_profiler_events": True})
        srv = ts.start(port=0)
        assert ts.server_port() == srv.port
        _train_loop(5)
        st, idx = _get(srv.url + "/")
        assert st == 200 and "/metrics" in idx["endpoints"]
        # /metrics: valid Prometheus text matching the registry contract
        st, text = _get(srv.url + "/metrics")
        assert st == 200
        lines = text.splitlines()
        assert any(l.startswith("# TYPE paddle_tpu_train_step_seconds "
                                "histogram") for l in lines)
        for l in lines:
            if l.startswith("#") or not l:
                continue
            name, _, val = l.rpartition(" ")
            float(val)
            assert name.startswith("paddle_tpu_")
        # /metrics.json: the registry snapshot — every contract name
        # present (other suites may register extra families in the
        # shared process registry; registrations survive reset)
        st, snap = _get(srv.url + "/metrics.json")
        assert st == 200 and set(pm.METRIC_NAMES) <= set(snap)
        # /goodput: the accountant snapshot with the attribution rings
        st, good = _get(srv.url + "/goodput")
        assert st == 200 and good["steps"] == 5
        assert "step_indices" in good and "step_indices_pretty" in good
        # /doctor: explain() schema + metrics/goodput sections (armed)
        st, doc = _get(srv.url + "/doctor")
        assert st == 200
        for k in ("verdict", "headline", "findings", "step", "dispatch"):
            assert k in doc
        assert set(pm.METRIC_NAMES) <= set(doc["metrics"])
        assert doc["goodput"]["steps"] == 5
        # /events: bounded tail, newest last
        st, ev = _get(srv.url + "/events?n=5")
        assert st == 200 and ev["returned"] == 5 and len(ev["events"]) == 5
        assert ev["events"][-1]["seq"] > ev["events"][0]["seq"]
        st, ev = _get(srv.url + "/events?n=999999")
        assert st == 200 and ev["returned"] <= 4096
        # liveness/readiness + 404
        st, h = _get(srv.url + "/healthz")
        assert st == 200 and h["healthy"]
        assert h["sources"]["train"]["step"] == 5
        assert h["last_heartbeat_age_s"] is not None
        st, r = _get(srv.url + "/readyz")
        assert st == 200 and r["ready"]
        st, _ = _get(srv.url + "/nope")
        assert st == 404

    def test_metrics_endpoint_matches_registry_snapshot(self):
        """Acceptance: /metrics is the SAME exposition the in-process
        registry renders — one computation, scraped."""
        set_flags({"FLAGS_metrics": True})
        pm.SERVE.tokens.inc(13)
        pm.SERVE.refusals.labels(reason="queue_full").inc(2)
        srv = ts.start(port=0)
        st, text = _get(srv.url + "/metrics")
        assert st == 200
        assert "paddle_tpu_serve_tokens_total 13" in text.splitlines()
        assert ('paddle_tpu_serve_refusals_total{reason="queue_full"} 2'
                in text.splitlines())

    def test_busy_port_warns_instead_of_crashing(self):
        """A bind failure on the implicit flag path (restart racing the
        old socket, a DataLoader worker inheriting the env flag) must
        degrade to no-server with a warning — the diagnostics plane
        never kills the process it monitors."""
        holder = socket.socket()
        holder.bind(("127.0.0.1", 0))
        holder.listen(1)
        port = holder.getsockname()[1]
        try:
            set_flags({"FLAGS_telemetry_port": port})
            with pytest.warns(UserWarning, match="could not bind"):
                assert ts.maybe_start_from_flags() is None
            assert ts.server() is None
            # the explicit API still raises (a deliberate start must
            # not silently do nothing)
            with pytest.raises(OSError):
                ts.start(port=port)
        finally:
            holder.close()

    def test_start_is_idempotent_and_stop_rebinds(self):
        srv = ts.start(port=0)
        assert ts.start(port=0) is srv
        port = srv.port
        ts.stop()
        srv2 = ts.start(port=port)       # same port, fresh server
        st, _ = _get(srv2.url + "/healthz")
        assert st == 200


# ---------------------------------------------------------------------------
# liveness / readiness
# ---------------------------------------------------------------------------

class TestHealth:
    def test_train_heartbeat_staleness_and_finalize(self):
        set_flags({"FLAGS_metrics": True,
                   "FLAGS_telemetry_stale_s": 0.15})
        srv = ts.start(port=0)
        _train_loop(3)
        st, h = _get(srv.url + "/healthz")
        assert st == 200 and not h["sources"]["train"]["stale"]
        time.sleep(0.3)                  # open window + stale heartbeat
        st, h = _get(srv.url + "/healthz")
        assert st == 503 and h["sources"]["train"]["stale"]
        pg.ACCOUNTANT.finalize()         # closed window: idle, not dead
        st, h = _get(srv.url + "/healthz")
        assert st == 200 and h["sources"]["train"]["finalized"]

    def test_stale_s_zero_disables_heartbeat_staleness(self):
        """FLAGS_telemetry_stale_s=0 is the opt-out for scripts with
        legitimate long non-stepping phases (eval/checkpoint): ages stay
        reported, nothing drives /healthz to 503."""
        set_flags({"FLAGS_telemetry_stale_s": 0.0})
        srv = ts.start(port=0)
        _train_loop(2)
        time.sleep(0.2)                  # any window >0 would be stale
        st, h = _get(srv.url + "/healthz")
        assert st == 200 and not h["sources"]["train"]["stale"]
        assert h["sources"]["train"]["age_s"] > 0

    def test_readyz_mirrors_degraded_latch(self, smodel):
        from paddle_tpu.serving import LLMEngine
        srv = ts.start(port=0)
        engine = LLMEngine(smodel, max_batch_size=2, block_size=4)
        # fresh engine: ready (first request pays compile by design)
        st, r = _get(srv.url + "/readyz")
        assert st == 200 and r["ready"]
        assert r["engines"][0]["decode_compiled"] is False
        engine.generate(_prompts(2, seed=1), max_new_tokens=3)
        st, r = _get(srv.url + "/readyz")
        assert st == 200 and r["engines"][0]["decode_compiled"] is True
        assert "aot" in r and "enabled" in r["aot"]
        engine.degraded = True           # the watchdog/fault latch
        st, r = _get(srv.url + "/readyz")
        assert st == 503 and not r["ready"]
        assert r["engines"][0]["degraded"]
        # first clean decode step clears the latch organically
        engine.generate(_prompts(1, seed=2), max_new_tokens=2)
        assert engine.degraded is False
        st, r = _get(srv.url + "/readyz")
        assert st == 200 and r["ready"]

    def test_healthz_flips_within_watchdog_window_of_a_stall(self,
                                                            smodel):
        """Acceptance: an injected wall-clock hang
        (guardian.inject_fault "stall") on a busy engine flips /healthz
        to 503 within one watchdog window, and the endpoint recovers
        after the first clean step. /readyz reads 503 while the
        degraded latch holds."""
        from paddle_tpu.serving import LLMEngine
        budget_ms = 150
        set_flags({"FLAGS_metrics": True,
                   "FLAGS_serve_step_timeout_ms": budget_ms})
        srv = ts.start(port=0)
        engine = LLMEngine(smodel, max_batch_size=2, block_size=4)
        reqs = [engine.add_request(p, max_new_tokens=8)
                for p in _prompts(3, seed=3)]
        for _ in range(3):
            engine.step()                # warm, heartbeat fresh
        st, _ = _get(srv.url + "/healthz")
        assert st == 200
        samples = []
        stop = threading.Event()

        def scraper():
            while not stop.is_set():
                for ep in ("/healthz", "/readyz"):
                    try:
                        samples.append(
                            (time.perf_counter(), ep,
                             _get(srv.url + ep, timeout=5)[0]))
                    except Exception:
                        pass
                time.sleep(0.01)

        thr = threading.Thread(target=scraper, daemon=True)
        thr.start()
        t_hang = time.perf_counter()
        guardian.inject_fault("stall", op="serve.decode", times=2)
        try:
            engine.run()                 # wedges ~2 budgets, recovers
        finally:
            guardian.clear_faults()
        stop.set()
        thr.join(timeout=10)
        unhealthy = [t for t, ep, st in samples
                     if ep == "/healthz" and st == 503]
        assert unhealthy, "healthz never flipped during the stall"
        # flip bound: one watchdog window per wedged attempt + scrape
        # cadence slack
        assert min(unhealthy) - t_hang <= 2 * budget_ms / 1e3 + 0.25
        assert any(ep == "/readyz" and st == 503
                   for _, ep, st in samples), \
            "readyz never reported the degraded latch"
        # recovered: healthy, ready, and the streams all finished
        st, h = _get(srv.url + "/healthz")
        assert st == 200, h
        st, _ = _get(srv.url + "/readyz")
        assert st == 200
        assert all(r.finished for r in reqs)
        assert engine.stats()["hangs"] == 2
        # per-step attribution: the stalled decode steps are named
        st, good = _get(srv.url + "/goodput")
        assert good["step_indices"].get("stalled"), good["step_indices"]

    def test_idle_busy_engine_goes_stale_without_steps(self, smodel):
        """The blind-tunnel shape: requests pending but the driver never
        steps (wedged outside the engine entirely) — /healthz flips once
        the heartbeat passes the window; an IDLE engine never does."""
        from paddle_tpu.serving import LLMEngine
        set_flags({"FLAGS_telemetry_stale_s": 0.1})
        srv = ts.start(port=0)
        engine = LLMEngine(smodel, max_batch_size=2, block_size=4)
        engine.generate(_prompts(1, seed=4), max_new_tokens=2)  # warm
        time.sleep(0.25)
        st, h = _get(srv.url + "/healthz")
        assert st == 200, h              # idle: never dead
        engine.add_request(_prompts(1, seed=5)[0], max_new_tokens=4)
        time.sleep(0.25)                 # busy + no step() = wedged
        st, h = _get(srv.url + "/healthz")
        assert st == 503
        eng = h["engines"][0]
        assert eng["busy"] and eng["stale"]
        engine.run()                     # drains; healthy again
        st, _ = _get(srv.url + "/healthz")
        assert st == 200


# ---------------------------------------------------------------------------
# scrape under churn + kill-9 port reuse (satellite)
# ---------------------------------------------------------------------------

class TestScrapeChurn:
    @pytest.mark.perf_smoke
    def test_100hz_scrape_under_64_stream_churn(self, smodel):
        """Satellite: a scraper hammering /metrics + /doctor at ~100 Hz
        while 64 mixed streams churn must leave decode_compiles == 1 and
        produce parseable output on EVERY response."""
        from paddle_tpu.serving import LLMEngine
        set_flags({"FLAGS_metrics": True, "FLAGS_profiler_events": True})
        srv = ts.start(port=0)
        engine = LLMEngine(smodel, max_batch_size=4, block_size=4)
        results = []
        errors = []
        stop = threading.Event()

        def scraper():
            while not stop.is_set():
                try:
                    with urllib.request.urlopen(srv.url + "/metrics",
                                                timeout=10) as r:
                        text = r.read().decode()
                    for l in text.splitlines():
                        if l.startswith("#") or not l:
                            continue
                        float(l.rpartition(" ")[2])   # parseable or die
                    with urllib.request.urlopen(srv.url + "/doctor",
                                                timeout=10) as r:
                        json.loads(r.read().decode())
                    results.append(1)
                except Exception as e:     # noqa: BLE001 — recorded
                    errors.append(repr(e)[:200])
                time.sleep(0.005)          # ~100+ Hz across endpoints

        thr = threading.Thread(target=scraper, daemon=True)
        thr.start()
        try:
            engine.generate(_prompts(64, seed=9), max_new_tokens=5)
        finally:
            stop.set()
            thr.join(timeout=15)
        assert not errors, errors[:3]
        assert len(results) >= 10, "scraper barely ran — guard is moot"
        s = engine.stats()
        assert s["decode_compiles"] == 1, \
            "scraping retraced the decode program"
        assert s["completed"] == 64

    def test_kill9_mid_scrape_leaves_no_stuck_socket(self):
        """Satellite: SIGKILL a serving process mid-scrape; the
        replacement binds the SAME port immediately (allow_reuse_address
        — accepted sockets in TIME_WAIT must not wedge the restart)."""
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        child = _CHILD_SERVER.format(root=_ROOT, port=port)
        proc = subprocess.Popen([sys.executable, "-c", child],
                                stdout=subprocess.PIPE, text=True,
                                env={**os.environ,
                                     "JAX_PLATFORMS": "cpu"})
        try:
            assert proc.stdout.readline().strip() == f"PORT {port}"
            url = f"http://127.0.0.1:{port}"
            stop = threading.Event()

            def hammer():
                while not stop.is_set():
                    try:
                        urllib.request.urlopen(url + "/metrics",
                                               timeout=2).read()
                    except Exception:
                        pass

            thr = threading.Thread(target=hammer, daemon=True)
            thr.start()
            time.sleep(0.2)              # scrapes in flight
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
            stop.set()
            thr.join(timeout=5)
        finally:
            if proc.poll() is None:
                proc.kill()
        # restart on the SAME port must succeed immediately
        srv = ts.TelemetryServer(port=port).start()
        try:
            st, h = _get(f"http://127.0.0.1:{port}/healthz")
            assert st in (200, 503) and "healthy" in h
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# per-step goodput attribution
# ---------------------------------------------------------------------------

class TestStepAttribution:
    @pytest.mark.filterwarnings(
        "ignore:Operator .* produced a non-finite output")
    def test_guardian_skip_steps_are_named(self):
        """Tentpole: the accountant records WHICH steps the guardian
        skipped — in the snapshot rings, the /goodput endpoint, and the
        goodput_step_index exposition gauge."""
        clear_dispatch_cache()
        set_flags({"FLAGS_metrics": True, "FLAGS_check_numerics": True,
                   "FLAGS_check_numerics_level": 1,
                   "FLAGS_eager_chain_fusion": False,
                   "FLAGS_eager_step_fusion": False})
        srv = ts.start(port=0)
        pg.ACCOUNTANT.reset(warm=True)
        guardian.inject_fault("nan_output", op="matmul", after=3, times=1)
        try:
            _train_loop(10)
            guardian.flush()
            pg.ACCOUNTANT.step_boundary()
        finally:
            guardian.clear_faults()
        snap = pg.ACCOUNTANT.snapshot()
        skipped = snap["step_indices"].get("skipped")
        assert skipped, snap["step_indices"]
        assert all(1 <= i <= 11 for i in skipped)
        assert snap["step_indices_pretty"]["skipped"] \
            == pg.format_step_ranges(skipped)
        # the endpoint reports the same rings
        st, good = _get(srv.url + "/goodput")
        assert good["step_indices"]["skipped"] == skipped
        # the exposition carries the last-index watermark gauge
        st, text = _get(srv.url + "/metrics")
        assert (f'paddle_tpu_goodput_step_index{{bucket="skipped"}} '
                f"{skipped[-1]}" in text.splitlines())

    def test_attribution_rings_are_bounded(self):
        set_flags({"FLAGS_metrics": True})
        acct = pg.GoodputAccountant()
        for i in range(500):
            acct._attribute_step("skipped", i)
        ring = acct.step_indices["skipped"]
        assert len(ring) == pg._ATTR_RING
        assert list(ring)[-1] == 499      # newest win, oldest dropped

    def test_doctor_cli_prints_step_indices(self, capsys):
        """`fusion_doctor --demo metrics` names the skipped steps in its
        goodput line (the per-step attribution reaching the human)."""
        sys.path.insert(0, os.path.join(_ROOT, "tools"))
        import fusion_doctor
        rc = fusion_doctor.main(["--demo", "metrics", "--steps", "16"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "goodput :" in out
        assert "skipped at step(s)" in out


# ---------------------------------------------------------------------------
# fleet merge (tools/fleet_metrics.py)
# ---------------------------------------------------------------------------

_CHILD_SINK = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {root!r})
sys.path.insert(0, os.path.join({root!r}, "tools"))
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.profiler import metrics as pm
from paddle_tpu.profiler import goodput as pg
import metrics_export
set_flags({{"FLAGS_metrics": True}})
pm.SERVE.tokens.inc({tokens})
pm.SERVE.occupancy.set({occ})
acct = pg.ACCOUNTANT
acct.steps = {steps}
acct.buckets["productive"] = {prod}
acct.buckets["skipped"] = {skipped}
acct._attribute_step("skipped", {skip_at})
sink = metrics_export.MetricsSink(path={path!r})
sink.write()
print("WROTE")
"""

_CHILD_SERVER = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {root!r})
from paddle_tpu.profiler import telemetry_server as ts
srv = ts.start(port={port})
print("PORT", srv.port, flush=True)
time.sleep(300)
"""


class TestFleetMerge:
    def _write_sinks(self, tmp_path):
        specs = [dict(tokens=11, occ=0.9, steps=10, prod=8.0,
                      skipped=2.0, skip_at=7),
                 dict(tokens=31, occ=0.7, steps=20, prod=12.0,
                      skipped=3.0, skip_at=14)]
        paths = []
        for i, spec in enumerate(specs):
            p = str(tmp_path / f"host{i}.jsonl")
            r = subprocess.run(
                [sys.executable, "-c",
                 _CHILD_SINK.format(root=_ROOT, path=p, **spec)],
                capture_output=True, text=True, timeout=180,
                env={**os.environ, "JAX_PLATFORMS": "cpu"})
            assert r.returncode == 0, r.stderr[-800:]
            paths.append(p)
        return paths, specs

    def test_sink_merge_fleet_goodput_exact(self, tmp_path):
        """Acceptance: fleet_metrics merging >=2 process sinks reports
        fleet goodput equal (±1e-9) to hand-merging the snapshots, with
        per-step skip indices visible per host."""
        sys.path.insert(0, os.path.join(_ROOT, "tools"))
        import fleet_metrics
        paths, specs = self._write_sinks(tmp_path)
        hosts = fleet_metrics.sink_hosts(paths)
        assert len(hosts) == 2
        view = fleet_metrics.fleet_view(hosts)
        # hand merge: sum productive / sum total over the raw snapshots
        prod = sum(s["prod"] for s in specs)
        total = sum(s["prod"] + s["skipped"] for s in specs)
        assert abs(view["fleet_goodput"]["goodput"] - prod / total) \
            <= 1e-9
        assert view["fleet_goodput"]["steps"] == 30
        # policy merge: occupancy ADDS fleet-wide, tokens add
        merged = view["merged"]
        assert merged["serve_occupancy"]["series"][0]["value"] \
            == pytest.approx(1.6)
        assert merged["serve_tokens_total"]["series"][0]["value"] == 42
        # per-host skip indices survive with their host prefix
        idx = view["fleet_goodput"]["step_indices"]["skipped"]
        assert sorted(v[0] for v in idx.values()) == [7, 14]
        # drift: per-host goodput present for both hosts
        per_host = view["drift"]["per_host"]
        assert len(per_host) == 2
        assert all(v["goodput"] is not None for v in per_host.values())
        # the summary renders without error and names the skip steps
        text = fleet_metrics.format_fleet_summary(view)
        assert "goodput" in text and "skipped steps" in text

    def test_host_labeled_exposition(self, tmp_path):
        sys.path.insert(0, os.path.join(_ROOT, "tools"))
        import fleet_metrics
        paths, _ = self._write_sinks(tmp_path)
        hosts = fleet_metrics.sink_hosts(paths)
        view = fleet_metrics.fleet_view(hosts)
        text = pm.exposition(view["labeled"])
        host_lines = [l for l in text.splitlines()
                      if l.startswith("paddle_tpu_serve_tokens_total")]
        # one labeled series per host, values NOT collapsed
        assert len(host_lines) == 2
        assert all('host="' in l for l in host_lines)
        assert {l.rpartition(" ")[2] for l in host_lines} == {"11", "31"}

    def test_cli_merges_sinks(self, tmp_path, capsys):
        sys.path.insert(0, os.path.join(_ROOT, "tools"))
        import fleet_metrics
        paths, _ = self._write_sinks(tmp_path)
        rc = fleet_metrics.main(["--sink", str(tmp_path / "*.jsonl"),
                                 "--merged-prom"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "paddle_tpu_serve_tokens_total 42" in out.splitlines()

    def test_live_url_scrape_two_processes(self, smodel):
        """Fleet merge over LIVE endpoints: this process's server plus a
        subprocess server — two hosts, one drift view."""
        sys.path.insert(0, os.path.join(_ROOT, "tools"))
        import fleet_metrics
        set_flags({"FLAGS_metrics": True})
        pm.SERVE.tokens.inc(5)
        _train_loop(3)
        srv = ts.start(port=0)
        proc = subprocess.Popen(
            [sys.executable, "-c",
             _CHILD_SERVER.format(root=_ROOT, port=0)],
            stdout=subprocess.PIPE, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        try:
            line = proc.stdout.readline().strip()
            child_port = int(line.split()[1])
            hosts = {}
            for label, port in (("self", srv.port),
                                ("child", child_port)):
                hosts[label] = fleet_metrics.fetch_host(
                    f"http://127.0.0.1:{port}")
            view = fleet_metrics.fleet_view(hosts)
            assert view["hosts"] == ["child", "self"]
            merged = view["merged"]
            assert merged["serve_tokens_total"]["series"][0]["value"] \
                == 5                      # child contributed zeros
            assert view["fleet_goodput"]["steps"] == 3
        finally:
            proc.kill()
            proc.wait(timeout=30)


class TestFleetGenerations:
    """stale_member classification (elastic fabric, PR 20): a host whose
    `/fleet` generation trails the fleet's — or that the coordinator
    lists in stale_hosts — is named stale_member, excluded from the
    drift ratio, and skipped by the straggler classifier."""

    @staticmethod
    def _goodput(p50):
        return {"steps": 6, "goodput": 0.9, "mfu": 0.1,
                "tokens_per_sec": 0.0, "step_ms_p50": p50,
                "step_ms_p99": p50, "buckets_s": {"productive": 1.0}}

    def _hosts(self):
        return {"h0": ({}, self._goodput(10.0)),
                "h1": ({}, self._goodput(11.0)),
                "h2": ({}, self._goodput(500.0))}

    def test_trailing_generation_is_stale_member(self):
        sys.path.insert(0, os.path.join(_ROOT, "tools"))
        import fleet_metrics
        fleet = {
            "h0": {"armed": True, "generation": 2,
                   "member": {"host": "w0", "generation": 2},
                   "coordinator": {"generation": 2,
                                   "stale_hosts": ["w2"]}},
            "h1": {"armed": True, "generation": 2,
                   "member": {"host": "w1", "generation": 2}},
            "h2": {"armed": True, "generation": 1,
                   "member": {"host": "w2", "generation": 1}},
        }
        view = fleet_metrics.fleet_view(self._hosts(), fleet=fleet)
        drift = view["drift"]
        assert drift["fleet_generation"] == 2
        assert drift["generations"] == {"h0": 2, "h1": 2, "h2": 1}
        # both stale signals (trailing generation, coordinator
        # stale_hosts with host_id->label mapping) agree on h2
        assert drift["stale_members"] == ["h2"]
        per = drift["per_host"]
        assert per["h2"]["status"] == "stale_member"
        assert per["h2"]["generation"] == 1
        assert per["h0"]["status"] == per["h1"]["status"] == "ok"
        # the 50x-slower h2 is STALE, not the straggler: the ratio must
        # come from the two live hosts only
        assert drift["slowest_host"] == "h1"
        assert drift["step_time_ratio"] == pytest.approx(1.1)
        text = fleet_metrics.format_fleet_summary(view)
        assert "stale_member" in text and "generation 2" in text

    def test_coordinator_stale_hosts_without_generations(self):
        """A member crash leaves no `/fleet` scrape for it — only the
        coordinator's stale_hosts names it (by fabric host_id, reported
        as-is when no scraped label matches)."""
        sys.path.insert(0, os.path.join(_ROOT, "tools"))
        import fleet_metrics
        fleet = {"h0": {"armed": True, "generation": 3,
                        "member": {"host": "w0", "generation": 3},
                        "coordinator": {"generation": 3,
                                        "stale_hosts": ["w9"]}}}
        view = fleet_metrics.fleet_view(self._hosts(), fleet=fleet)
        assert view["drift"]["stale_members"] == ["w9"]
        assert view["drift"]["per_host"]["h0"]["status"] == "ok"

    def test_no_fleet_scrape_degrades_to_metrics_view(self):
        sys.path.insert(0, os.path.join(_ROOT, "tools"))
        import fleet_metrics
        for fleet in (None, {}, {"h0": None, "h1": None, "h2": None}):
            view = fleet_metrics.fleet_view(self._hosts(), fleet=fleet)
            drift = view["drift"]
            assert "stale_members" not in drift
            assert "fleet_generation" not in drift
            assert all(v["status"] == "ok"
                       for v in drift["per_host"].values())


# ---------------------------------------------------------------------------
# fusion_doctor --url + bench autopsy probe
# ---------------------------------------------------------------------------

class TestRemoteDoctor:
    def test_doctor_url_same_schema_as_json(self, capsys):
        set_flags({"FLAGS_metrics": True, "FLAGS_profiler_events": True})
        srv = ts.start(port=0)
        _train_loop(8)
        sys.path.insert(0, os.path.join(_ROOT, "tools"))
        import fusion_doctor
        rc = fusion_doctor.main(["--url", srv.url, "--json"])
        out = capsys.readouterr().out
        assert rc == 0
        remote = json.loads(out)
        local = ts.doctor_report()
        assert set(remote) == set(local)   # same schema, same sections
        for k in ("verdict", "headline", "metrics", "goodput"):
            assert k in remote
        # text mode renders the live report + metrics + goodput line
        rc = fusion_doctor.main(["--url", srv.url])
        out = capsys.readouterr().out
        assert rc == 0
        assert "fusion doctor" in out and "goodput :" in out

    def test_doctor_url_unreachable_fails_cleanly(self, capsys):
        sys.path.insert(0, os.path.join(_ROOT, "tools"))
        import fusion_doctor
        rc = fusion_doctor.main(["--url", "http://127.0.0.1:9",
                                 "--json"])
        assert rc == 1
        assert "could not reach" in capsys.readouterr().err

    def test_bench_autopsy_probe_reads_live_child(self):
        """Satellite: the bench harness's timeout autopsy helper reads
        last_heartbeat_age_s + the live goodput snapshot off a child's
        telemetry server (what rounds 3-4 were missing)."""
        set_flags({"FLAGS_metrics": True})
        srv = ts.start(port=0)
        _train_loop(3)
        sys.path.insert(0, _ROOT)
        import bench
        autopsy = bench._probe_child_health(srv.port)
        assert autopsy["healthz"]["last_heartbeat_age_s"] is not None
        assert autopsy["goodput"]["steps"] == 3
        # an unreachable child degrades to a note, never a raise
        dead = bench._probe_child_health(bench._alloc_port())
        assert "unreachable" in dead["healthz"]
