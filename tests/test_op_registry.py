"""Op schema registry: schema rows, infer_meta via abstract eval, and
custom-kernel overrides consulted by dispatch.

Reference analog: phi/api/yaml/ops.yaml schema rows, phi/core/
kernel_factory.h KernelFactory, phi/core/custom_kernel.cc plug-in kernels.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops import (all_ops, get_op, describe, infer_meta,
                            override_kernel, use_kernel)


class TestSchema:
    def test_corpus_has_schema_rows(self):
        ops = all_ops()
        assert len(ops) > 150
        with_args = [od for od in ops.values() if od.args]
        # the signature capture fills the yaml `args:` column
        assert len(with_args) > 100

    def test_describe(self):
        row = describe("matmul")
        assert row["args"][:2] == ["x", "y"]
        assert row["kernel"] == "jax/XLA"
        assert row["backward"] == "matmul_grad (vjp)"

    def test_infer_meta_matmul(self):
        out = infer_meta("matmul",
                         jax.ShapeDtypeStruct((3, 4), jnp.float32),
                         jax.ShapeDtypeStruct((4, 5), jnp.float32))
        assert out.shape == (3, 5) and out.dtype == jnp.float32

    def test_infer_meta_runs_no_compute(self):
        """eval_shape only: works for shapes far too big to materialize."""
        out = infer_meta("exp", jax.ShapeDtypeStruct((1 << 20, 1 << 16),
                                                     jnp.float32))
        assert out.shape == (1 << 20, 1 << 16)


class TestKernelOverride:
    def teardown_method(self, _m):
        od = get_op("tanh")
        od.active = None
        od.overrides.clear()

    def test_override_routes_dispatch(self):
        """An installed+activated override actually serves the op."""
        calls = []

        def fake_tanh(v):
            calls.append(v.shape)
            return jnp.tanh(v) * 2.0          # visibly different result

        override_kernel("tanh", "custom", fake_tanh, activate=True)
        x = paddle.to_tensor(np.array([0.5], np.float32))
        y = paddle.tanh(x)
        assert calls, "override was not consulted"
        np.testing.assert_allclose(np.asarray(y._value),
                                   2 * np.tanh(0.5), rtol=1e-6)

    def test_use_kernel_scopes_activation(self):
        override_kernel("tanh", "doubled", lambda v: jnp.tanh(v) * 2.0)
        x = paddle.to_tensor(np.array([0.5], np.float32))
        base = float(paddle.tanh(x))
        with use_kernel("tanh", "doubled"):
            doubled = float(paddle.tanh(x))
        after = float(paddle.tanh(x))
        np.testing.assert_allclose(doubled, 2 * base, rtol=1e-6)
        np.testing.assert_allclose(after, base, rtol=1e-6)

    def test_override_is_differentiable(self):
        """Dispatch captures the override's VJP like any kernel."""
        override_kernel("tanh", "scaled", lambda v: jnp.tanh(v) * 3.0,
                        activate=True)
        x = paddle.to_tensor(np.array([0.3], np.float32),
                             stop_gradient=False)
        y = paddle.tanh(x).sum()
        y.backward()
        np.testing.assert_allclose(np.asarray(x.grad._value),
                                   3 * (1 - np.tanh(0.3) ** 2), rtol=1e-5)

    def test_unknown_override_raises(self):
        with pytest.raises(KeyError):
            use_kernel("tanh", "nope")
