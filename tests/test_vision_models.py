"""Forward-shape smoke tests for the vision model zoo.

Mirrors the reference's model tests (python/paddle/tests/test_vision_models.py):
build each architecture at reduced input size, check logits shape, and verify
the graph is trainable (one backward on a small model).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models


def _run(model, size=64, num_classes=10):
    x = paddle.to_tensor(
        np.random.randn(1, 3, size, size).astype(np.float32))
    model.eval()
    return model(x)


@pytest.mark.parametrize("ctor", [
    models.resnet18, models.resnext50_32x4d, models.wide_resnet50_2])
def test_resnet_family(ctor):
    out = _run(ctor(num_classes=10))
    assert list(out.shape) == [1, 10]


def test_densenet():
    out = _run(models.densenet121(num_classes=10))
    assert list(out.shape) == [1, 10]


def test_googlenet():
    # aux heads need the 14x14 grid of a 224 input
    out, aux1, aux2 = _run(models.googlenet(num_classes=10), size=224)
    assert list(out.shape) == [1, 10]
    assert list(aux1.shape) == [1, 10]
    assert list(aux2.shape) == [1, 10]


def test_inception_v3():
    out = _run(models.inception_v3(num_classes=10), size=299)
    assert list(out.shape) == [1, 10]


def test_mobilenets():
    for ctor in (models.mobilenet_v1, models.mobilenet_v2,
                 models.mobilenet_v3_small):
        out = _run(ctor(num_classes=10))
        assert list(out.shape) == [1, 10]


def test_shufflenet_squeezenet():
    out = _run(models.shufflenet_v2_x0_25(num_classes=10))
    assert list(out.shape) == [1, 10]
    out = _run(models.squeezenet1_1(num_classes=10))
    assert list(out.shape) == [1, 10]


def test_small_model_trains():
    model = models.squeezenet1_1(num_classes=4)
    model.train()
    x = paddle.to_tensor(np.random.randn(2, 3, 64, 64).astype(np.float32))
    y = paddle.to_tensor(np.array([0, 1], np.int64))
    import paddle_tpu.nn.functional as F
    loss = F.cross_entropy(model(x), y)
    loss.backward()
    grads = [p.grad for p in model.parameters() if not p.stop_gradient]
    assert any(g is not None and float(np.abs(g.numpy()).sum()) > 0
               for g in grads)


# ---- ViT (BASELINE config 5) ------------------------------------------------

class TestVisionTransformer:
    def _tiny(self, fused):
        import paddle_tpu as paddle
        paddle.seed(0)
        from paddle_tpu.vision.models import VisionTransformer
        return VisionTransformer(img_size=16, patch_size=8, embed_dim=32,
                                 depth=2, num_heads=4, num_classes=5,
                                 dropout=0.0, attention_dropout=0.0,
                                 use_fused_attn=fused)

    def test_fused_matches_unfused_with_mapped_weights(self):
        """The fused encoder computes the same function as the plain one
        when weights are mapped (qkv stacking per fused_attention_op.cu
        layout)."""
        import numpy as np
        import jax.numpy as jnp
        import paddle_tpu as paddle
        fused = self._tiny(True)
        plain = self._tiny(False)
        # shared trunk params
        for dst, src in [(fused.patch_embed.proj, plain.patch_embed.proj),
                         (fused.norm, plain.norm), (fused.head, plain.head)]:
            dst.weight._value = src.weight._value
            dst.bias._value = src.bias._value
        fused.cls_token._value = plain.cls_token._value
        fused.pos_embed._value = plain.pos_embed._value
        H, D = 4, 8
        for fb, pb in zip(fused.blocks, plain.blocks):
            at, ff = fb.fused_attn, fb.ffn
            sa = pb.self_attn
            qkv = np.stack([
                np.asarray(l.weight._value).T.reshape(H, D, 32)
                for l in (sa.q_proj, sa.k_proj, sa.v_proj)])
            at.qkv_weight._value = jnp.asarray(qkv)
            at.qkv_bias._value = jnp.asarray(np.stack(
                [np.asarray(l.bias._value).reshape(H, D)
                 for l in (sa.q_proj, sa.k_proj, sa.v_proj)]))
            at.linear_weight._value = sa.out_proj.weight._value
            at.linear_bias._value = sa.out_proj.bias._value
            at.pre_ln_scale._value = pb.norm1.weight._value
            at.pre_ln_bias._value = pb.norm1.bias._value
            ff.ln1_scale._value = pb.norm2.weight._value
            ff.ln1_bias._value = pb.norm2.bias._value
            ff.linear1_weight._value = pb.linear1.weight._value
            ff.linear1_bias._value = pb.linear1.bias._value
            ff.linear2_weight._value = pb.linear2.weight._value
            ff.linear2_bias._value = pb.linear2.bias._value
        fused.eval(); plain.eval()
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(2, 3, 16, 16))
            .astype(np.float32))
        np.testing.assert_allclose(np.asarray(fused(x)._value),
                                   np.asarray(plain(x)._value),
                                   rtol=2e-4, atol=2e-4)

    def test_vit_trains(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        from paddle_tpu.jit import TrainStep
        model = self._tiny(True)
        model.train()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        step = TrainStep(model, lambda o, y: F.cross_entropy(o, y), opt)
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(4, 3, 16, 16))
                             .astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 5, 4).astype(np.int64))
        losses = [float(step(x, y)) for _ in range(10)]
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()

    def test_constructors(self):
        from paddle_tpu.vision.models import vit_b_16, vit_l_16, vit_l_32
        m = vit_b_16(num_classes=10, img_size=32)
        assert len(m.blocks) == 12 and m.embed_dim == 768
        m = vit_l_32(num_classes=0, img_size=64)
        assert len(m.blocks) == 24 and m.head is None
