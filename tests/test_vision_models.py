"""Forward-shape smoke tests for the vision model zoo.

Mirrors the reference's model tests (python/paddle/tests/test_vision_models.py):
build each architecture at reduced input size, check logits shape, and verify
the graph is trainable (one backward on a small model).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models


def _run(model, size=64, num_classes=10):
    x = paddle.to_tensor(
        np.random.randn(1, 3, size, size).astype(np.float32))
    model.eval()
    return model(x)


@pytest.mark.parametrize("ctor", [
    models.resnet18, models.resnext50_32x4d, models.wide_resnet50_2])
def test_resnet_family(ctor):
    out = _run(ctor(num_classes=10))
    assert list(out.shape) == [1, 10]


def test_densenet():
    out = _run(models.densenet121(num_classes=10))
    assert list(out.shape) == [1, 10]


def test_googlenet():
    # aux heads need the 14x14 grid of a 224 input
    out, aux1, aux2 = _run(models.googlenet(num_classes=10), size=224)
    assert list(out.shape) == [1, 10]
    assert list(aux1.shape) == [1, 10]
    assert list(aux2.shape) == [1, 10]


def test_inception_v3():
    out = _run(models.inception_v3(num_classes=10), size=299)
    assert list(out.shape) == [1, 10]


def test_mobilenets():
    for ctor in (models.mobilenet_v1, models.mobilenet_v2,
                 models.mobilenet_v3_small):
        out = _run(ctor(num_classes=10))
        assert list(out.shape) == [1, 10]


def test_shufflenet_squeezenet():
    out = _run(models.shufflenet_v2_x0_25(num_classes=10))
    assert list(out.shape) == [1, 10]
    out = _run(models.squeezenet1_1(num_classes=10))
    assert list(out.shape) == [1, 10]


def test_small_model_trains():
    model = models.squeezenet1_1(num_classes=4)
    model.train()
    x = paddle.to_tensor(np.random.randn(2, 3, 64, 64).astype(np.float32))
    y = paddle.to_tensor(np.array([0, 1], np.int64))
    import paddle_tpu.nn.functional as F
    loss = F.cross_entropy(model(x), y)
    loss.backward()
    grads = [p.grad for p in model.parameters() if not p.stop_gradient]
    assert any(g is not None and float(np.abs(g.numpy()).sum()) > 0
               for g in grads)
