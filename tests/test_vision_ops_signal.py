"""Tests for vision.ops (detection) and paddle.signal (stft/istft)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.vision import ops as vops
from paddle_tpu import signal


def _t(a):
    return paddle.to_tensor(np.asarray(a, np.float32))


# ------------------------------------------------------------ vision.ops

def test_box_iou():
    a = _t([[0, 0, 2, 2]])
    b = _t([[1, 1, 3, 3], [0, 0, 2, 2], [4, 4, 5, 5]])
    iou = vops.box_iou(a, b).numpy()[0]
    np.testing.assert_allclose(iou, [1 / 7, 1.0, 0.0], rtol=1e-5)


def test_nms_basic_and_categories():
    boxes = _t([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]])
    scores = _t([0.9, 0.8, 0.7])
    keep = vops.nms(boxes, 0.5, scores).numpy()
    np.testing.assert_array_equal(keep, [0, 2])
    cats = paddle.to_tensor(np.array([0, 1, 0], np.int64))
    keep = vops.nms(boxes, 0.5, scores, category_idxs=cats).numpy()
    # different categories -> overlapping boxes both kept
    assert set(keep.tolist()) == {0, 1, 2}


def test_roi_align_uniform_feature():
    # constant feature map -> every aligned value equals the constant
    feat = np.full((1, 3, 16, 16), 5.0, np.float32)
    boxes = _t([[2.0, 2.0, 10.0, 10.0]])
    out = vops.roi_align(_t(feat), boxes, paddle.to_tensor(
        np.array([1], np.int32)), output_size=4)
    assert list(out.shape) == [1, 3, 4, 4]
    np.testing.assert_allclose(out.numpy(), 5.0, rtol=1e-5)


def test_roi_align_gradient_flows():
    feat = paddle.to_tensor(
        np.random.randn(1, 2, 8, 8).astype(np.float32), stop_gradient=False)
    boxes = _t([[1.0, 1.0, 6.0, 6.0]])
    out = vops.roi_align(feat, boxes, paddle.to_tensor(
        np.array([1], np.int32)), output_size=2)
    out.sum().backward()
    g = feat.grad.numpy()
    assert np.abs(g).sum() > 0


def test_roi_pool_exact_bins():
    feat = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    boxes = _t([[0.0, 0.0, 3.0, 3.0]])
    out = vops.roi_pool(_t(feat), boxes, paddle.to_tensor(
        np.array([1], np.int32)), output_size=2)
    # max over quadrants of the full 4x4 map
    np.testing.assert_allclose(out.numpy()[0, 0], [[5, 7], [13, 15]])


def test_box_coder_roundtrip():
    priors = _t([[0, 0, 10, 10], [5, 5, 20, 25]])
    targets = _t([[1, 1, 9, 11], [4, 6, 22, 24]])
    enc = vops.box_coder(priors, [1.0, 1.0, 1.0, 1.0], targets,
                         code_type="encode_center_size")
    dec = vops.box_coder(priors, [1.0, 1.0, 1.0, 1.0], enc,
                         code_type="decode_center_size")
    np.testing.assert_allclose(dec.numpy(), targets.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_yolo_box_shapes():
    n, na, c, h, w = 1, 3, 4, 5, 5
    x = _t(np.random.randn(n, na * (5 + c), h, w))
    img = paddle.to_tensor(np.array([[320, 320]], np.int32))
    boxes, scores = vops.yolo_box(x, img, anchors=[10, 13, 16, 30, 33, 23],
                                  class_num=c, downsample_ratio=32)
    assert list(boxes.shape) == [1, na * h * w, 4]
    assert list(scores.shape) == [1, na * h * w, c]
    assert np.isfinite(boxes.numpy()).all()


def test_prior_box():
    feat = _t(np.zeros((1, 8, 4, 4)))
    img = _t(np.zeros((1, 3, 32, 32)))
    boxes, variances = vops.prior_box(feat, img, min_sizes=[8.0],
                                      aspect_ratios=[2.0], flip=True,
                                      clip=True)
    # 1 min-size square + 2 flipped ratios = 3 priors per cell
    assert list(boxes.shape) == [4, 4, 3, 4]
    assert boxes.numpy().min() >= 0 and boxes.numpy().max() <= 1
    assert list(variances.shape) == [4, 4, 3, 4]


def test_distribute_fpn_proposals():
    rois = _t([[0, 0, 10, 10], [0, 0, 120, 120], [0, 0, 500, 500]])
    multi, restore = vops.distribute_fpn_proposals(rois, 2, 5, 4, 224)
    total = sum(r.shape[0] for r in multi)
    assert total == 3
    # restore index maps concatenated-by-level order back to input order
    cat = np.concatenate([r.numpy() for r in multi if r.shape[0]])
    np.testing.assert_allclose(cat[restore.numpy()[:, 0]], rois.numpy())


# ---------------------------------------------------------------- signal

def test_stft_matches_manual():
    x = np.random.randn(2, 512).astype(np.float32)
    spec = signal.stft(_t(x), n_fft=128, hop_length=64,
                       window="hann").numpy()
    assert spec.shape == (2, 65, 9)
    # frame 0 vs manual
    xp = np.pad(x[0], (64, 64), mode="reflect")
    w = np.hanning(129)[:-1]
    ref = np.fft.rfft(xp[:128] * w)
    np.testing.assert_allclose(spec[0, :, 0], ref, rtol=1e-3, atol=1e-3)


def test_stft_istft_roundtrip():
    x = np.random.randn(1, 1024).astype(np.float32)
    spec = signal.stft(_t(x), n_fft=256, hop_length=64, window="hann")
    rec = signal.istft(spec, n_fft=256, hop_length=64, window="hann",
                       length=1024).numpy()
    np.testing.assert_allclose(rec, x, rtol=1e-3, atol=1e-4)


def test_frame_overlap_add_inverse():
    x = np.arange(32, dtype=np.float32)
    f = signal.frame(_t(x), frame_length=8, hop_length=8)
    assert list(f.shape) == [8, 4]
    back = signal.overlap_add(f, hop_length=8).numpy()
    np.testing.assert_allclose(back, x)
