"""Round-3 vision surface: transform functionals/classes + detection ops.

Reference analogs: python/paddle/vision/transforms/functional_cv2.py,
python/paddle/vision/ops.py (deform_conv2d, matrix_nms,
generate_proposals, yolo_loss, decode_jpeg).
"""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.vision.ops as V
import paddle_tpu.vision.transforms as T
import paddle_tpu.vision.transforms.functional as TF


class TestTransformFunctionals:
    img = (np.random.RandomState(0).rand(8, 10, 3) * 255).astype("uint8")

    def test_rotate_90_square(self):
        sq = (np.random.RandomState(1).rand(9, 9, 3) * 255).astype("uint8")
        np.testing.assert_array_equal(
            TF.rotate(sq, 90, interpolation="nearest"), np.rot90(sq, 1))

    def test_rotate_identity(self):
        np.testing.assert_array_equal(TF.rotate(self.img, 0), self.img)

    def test_affine_translate(self):
        t = TF.affine(self.img, 0, (2, 1), 1.0, (0, 0))
        np.testing.assert_array_equal(t[1:, 2:], self.img[:-1, :-2])

    def test_perspective_identity_and_translate(self):
        pts = [(0, 0), (9, 0), (9, 7), (0, 7)]
        np.testing.assert_array_equal(
            TF.perspective(self.img, pts, pts), self.img)
        dst = [(1, 0), (10, 0), (10, 7), (1, 7)]
        pt = TF.perspective(self.img, pts, dst)
        np.testing.assert_array_equal(pt[:, 1:], self.img[:, :-1])

    def test_color_ops(self):
        b = TF.adjust_brightness(self.img, 2.0)
        assert b.dtype == np.uint8
        assert TF.to_grayscale(self.img).shape == (8, 10, 1)
        assert TF.to_grayscale(self.img, 3).shape == (8, 10, 3)
        assert TF.adjust_contrast(self.img, 0.5).shape == self.img.shape
        h0 = TF.adjust_hue(self.img, 0.0)
        assert np.abs(h0.astype(int) - self.img.astype(int)).max() <= 1
        # full hue cycle returns the original colors
        h1 = TF.adjust_hue(TF.adjust_hue(self.img, 0.5), 0.5)
        assert np.abs(h1.astype(int) - self.img.astype(int)).max() <= 2
        with pytest.raises(ValueError):
            TF.adjust_hue(self.img, 0.7)

    def test_crop_pad_erase(self):
        assert TF.crop(self.img, 1, 2, 3, 4).shape == (3, 4, 3)
        assert TF.center_crop(self.img, 4).shape == (4, 4, 3)
        assert TF.pad(self.img, 2).shape == (12, 14, 3)
        er = TF.erase(self.img, 1, 1, 2, 2, 0)
        assert (er[1:3, 1:3] == 0).all()

    def test_transform_classes_run(self):
        for cls in [T.ColorJitter(0.2, 0.2, 0.2, 0.2), T.Grayscale(),
                    T.RandomRotation(30),
                    T.RandomAffine(15, translate=(0.1, 0.1),
                                   scale=(0.8, 1.2), shear=10),
                    T.RandomPerspective(prob=1.0),
                    T.RandomErasing(prob=1.0),
                    T.ContrastTransform(0.3), T.SaturationTransform(0.3),
                    T.HueTransform(0.3)]:
            out = cls(self.img)
            assert out is not None

    def test_random_erasing_random_fill_uint8(self):
        # value="random" on a uint8 image must fill with non-zero noise,
        # not uniform [0,1) values that truncate to all-zeros
        np.random.seed(3)
        img = np.full((32, 32, 3), 128, np.uint8)
        out = T.RandomErasing(prob=1.0, value="random")(img)
        changed = out != img
        assert changed.any()
        assert out[changed].std() > 1.0  # actual noise, not a constant

    def test_grayscale_matches_rec601(self):
        g = TF.to_grayscale(self.img)[..., 0]
        ref = (self.img[..., 0] * 0.299 + self.img[..., 1] * 0.587
               + self.img[..., 2] * 0.114)
        np.testing.assert_allclose(g.astype(np.float32), ref, atol=1.0)


class TestDeformConv:
    def setup_method(self, _):
        rng = np.random.RandomState(0)
        self.x = rng.randn(2, 4, 8, 8).astype("float32")
        self.w = rng.randn(6, 4, 3, 3).astype("float32")
        self.b = rng.randn(6).astype("float32")
        self.off = np.zeros((2, 18, 6, 6), "float32")

    def test_zero_offset_equals_conv(self):
        got = V.deform_conv2d(paddle.to_tensor(self.x),
                              paddle.to_tensor(self.off),
                              paddle.to_tensor(self.w),
                              paddle.to_tensor(self.b)).numpy()
        ref = torch.nn.functional.conv2d(
            torch.tensor(self.x), torch.tensor(self.w),
            torch.tensor(self.b)).numpy()
        np.testing.assert_allclose(got, ref, atol=1e-4)

    def test_mask_modulation(self):
        m = np.full((2, 9, 6, 6), 0.5, "float32")
        got = V.deform_conv2d(paddle.to_tensor(self.x),
                              paddle.to_tensor(self.off),
                              paddle.to_tensor(self.w), None,
                              mask=paddle.to_tensor(m)).numpy()
        ref = 0.5 * torch.nn.functional.conv2d(
            torch.tensor(self.x), torch.tensor(self.w)).numpy()
        np.testing.assert_allclose(got, ref, atol=1e-4)

    def test_integer_offset_is_shift(self):
        off = self.off.copy()
        off[:, 0::2] = 1.0  # dy=+1 on every tap
        got = V.deform_conv2d(paddle.to_tensor(self.x),
                              paddle.to_tensor(off),
                              paddle.to_tensor(self.w)).numpy()
        xs = np.zeros_like(self.x)
        xs[:, :, :-1] = self.x[:, :, 1:]
        ref = torch.nn.functional.conv2d(torch.tensor(xs),
                                         torch.tensor(self.w)).numpy()
        np.testing.assert_allclose(got[:, :, :-1], ref[:, :, :-1], atol=1e-3)

    def test_layer_and_grad(self):
        layer = V.DeformConv2D(4, 6, 3)
        x = paddle.to_tensor(self.x)
        x.stop_gradient = False
        out = layer(x, paddle.to_tensor(self.off))
        assert out.shape == [2, 6, 6, 6]
        paddle.sum(out).backward()
        assert x.grad is not None and layer.weight.grad is not None


class TestDetectionOps:
    def test_matrix_nms_decays_overlaps(self):
        bboxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11],
                            [50, 50, 60, 60]]], "float32")
        scores = np.concatenate(
            [np.zeros((1, 1, 3), "float32"),
             np.array([[[0.9, 0.8, 0.7]]], "float32")], axis=1)
        out, nums = V.matrix_nms(paddle.to_tensor(bboxes),
                                 paddle.to_tensor(scores), 0.1, 0.0,
                                 keep_top_k=10)
        o = out.numpy()
        assert int(nums.numpy()[0]) == 3
        assert abs(o[:, 1].max() - 0.9) < 1e-6       # top box untouched
        assert o[o[:, 2] == 1][0, 1] < 0.8           # overlapped decayed
        assert abs(o[o[:, 2] == 50][0, 1] - 0.7) < 1e-3  # isolated kept

    def test_matrix_nms_gaussian_decay(self):
        # reference decay_score<T, true>: exp((max_iou^2 - iou^2) * sigma)
        bboxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11],
                            [50, 50, 60, 60]]], "float32")
        scores = np.concatenate(
            [np.zeros((1, 1, 3), "float32"),
             np.array([[[0.9, 0.8, 0.7]]], "float32")], axis=1)
        sigma = 2.0
        out, nums = V.matrix_nms(paddle.to_tensor(bboxes),
                                 paddle.to_tensor(scores), 0.1, 0.0,
                                 keep_top_k=10, use_gaussian=True,
                                 gaussian_sigma=sigma)
        o = out.numpy()
        inter = 9.0 * 9.0
        iou01 = inter / (100.0 + 100.0 - inter)
        expect = 0.8 * np.exp((0.0 - iou01 ** 2) * sigma)
        got = o[o[:, 2] == 1][0, 1]
        np.testing.assert_allclose(got, expect, rtol=1e-5)

    def test_generate_proposals(self):
        rng = np.random.RandomState(0)
        N, A, H, W = 1, 3, 4, 4
        sc = rng.rand(N, A, H, W).astype("float32")
        bd = (rng.randn(N, 4 * A, H, W) * 0.1).astype("float32")
        anchors = np.tile(
            np.array([[0, 0, 15, 15], [0, 0, 31, 31], [0, 0, 7, 7]],
                     "float32"), (H * W, 1)).reshape(H, W, A, 4)
        rois, rn = V.generate_proposals(
            paddle.to_tensor(sc), paddle.to_tensor(bd),
            paddle.to_tensor(np.array([[64, 64]], "float32")),
            paddle.to_tensor(anchors), paddle.to_tensor(np.ones_like(anchors)),
            pre_nms_top_n=20, post_nms_top_n=5, min_size=1.0)
        r = rois.numpy()
        assert r.shape[1] == 4 and 0 < int(rn.numpy()[0]) <= 5
        assert (r >= 0).all() and (r <= 64).all()  # clipped to image

    def test_yolo_loss_trains(self):
        rng = np.random.RandomState(1)
        x = paddle.to_tensor(
            rng.randn(2, 3 * 9, 5, 5).astype("float32") * 0.1)
        x.stop_gradient = False
        gtb = np.zeros((2, 3, 4), "float32")
        gtb[0, 0] = [40, 40, 30, 30]
        gtb[1, 0] = [20, 60, 25, 18]
        gtl = np.zeros((2, 3), "int64")
        anchors = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119,
                   116, 90, 156, 198, 373, 326]
        loss = V.yolo_loss(x, paddle.to_tensor(gtb), paddle.to_tensor(gtl),
                           anchors, [3, 4, 5], 4, 0.7, 16)
        assert loss.shape == [2] and np.isfinite(loss.numpy()).all()
        paddle.sum(loss).backward()
        assert x.grad is not None and np.isfinite(x.grad.numpy()).all()

    def test_jpeg_roundtrip(self, tmp_path):
        from PIL import Image
        # smooth gradient (noise doesn't survive lossy JPEG)
        yy, xx = np.mgrid[0:16, 0:20]
        arr = np.stack([yy * 8, xx * 6, (yy + xx) * 4], -1).astype("uint8")
        fp = str(tmp_path / "t.jpg")
        Image.fromarray(arr).save(fp, quality=95)
        dec = V.decode_jpeg(V.read_file(fp))
        assert dec.shape == [3, 16, 20]
        got = dec.numpy().transpose(1, 2, 0).astype(int)
        assert np.abs(got - arr.astype(int)).mean() < 8


class TestFlowersVOC:
    def test_flowers_dataset(self):
        from paddle_tpu.vision.datasets import Flowers
        ds = Flowers(mode="train")
        img, lab = ds[0]
        assert img.shape == (3, 64, 64) and 0 <= int(lab) < 102
        assert len(Flowers(mode="test")) > 0

    def test_voc2012_segmentation_pairs(self):
        from paddle_tpu.vision.datasets import VOC2012
        ds = VOC2012(mode="train")
        img, mask = ds[0]
        assert img.shape == (3, 64, 64) and mask.shape == (64, 64)
        assert 0 <= mask.max() < 21
        # loader-compatible
        import paddle_tpu as paddle
        batch = next(iter(paddle.io.DataLoader(ds, batch_size=4)))
        assert batch[0].shape[0] == 4 and batch[1].shape == [4, 64, 64]

    def test_profiler_enums_and_protobuf_export(self, tmp_path):
        import pickle
        import paddle_tpu.profiler as profiler
        p = profiler.Profiler(
            on_trace_ready=profiler.export_protobuf(str(tmp_path)))
        with p:
            with profiler.RecordEvent("work"):
                sum(range(1000))
        files = list(tmp_path.glob("*.pb"))
        assert files
        events = pickle.loads(files[0].read_bytes())
        assert any(e["name"] == "work" for e in events)
        p.summary(sorted_by=profiler.SortedKeys.CPUAvg)

    def test_require_version(self):
        import paddle_tpu as paddle
        paddle.utils.require_version("0.0.1", "99.0")
        import pytest as _pytest
        with _pytest.raises(Exception):
            paddle.utils.require_version("99.0")
        with _pytest.raises(TypeError):
            paddle.utils.require_version(1)
