"""Surface completion extras (round 4): incubate graph/segment/fused ops,
LookAhead/ModelAverage, saved_tensors_hooks, worker info, jit
ProgramTranslator switch, vision image backend, device probes.

Reference analogs: python/paddle/incubate/__init__.py __all__,
autograd/saved_tensors_hooks, fluid/dataloader/worker.py,
dygraph_to_static/program_translator.py.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate as incubate
import paddle_tpu.autograd as autograd


class TestIncubateGraphOps:
    def test_graph_send_recv_aliases_send_u_recv(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(3, 2))
        src = paddle.to_tensor(np.array([0, 1, 2], np.int64))
        dst = paddle.to_tensor(np.array([1, 1, 0], np.int64))
        out = incubate.graph_send_recv(x, src, dst, pool_type="sum")
        want = np.zeros((3, 2), np.float32)
        want[1] = x.numpy()[0] + x.numpy()[1]
        want[0] = x.numpy()[2]
        np.testing.assert_allclose(out.numpy(), want)

    def test_segment_reexports(self):
        data = paddle.to_tensor(np.array([1.0, 2.0, 3.0, 4.0], np.float32))
        ids = paddle.to_tensor(np.array([0, 0, 1, 1], np.int64))
        np.testing.assert_allclose(
            incubate.segment_sum(data, ids).numpy(), [3.0, 7.0])
        np.testing.assert_allclose(
            incubate.segment_mean(data, ids).numpy(), [1.5, 3.5])

    def test_graph_khop_sampler(self):
        # CSC chain graph: 0<-1<-2<-3 (colptr over 4 nodes)
        row = paddle.to_tensor(np.array([1, 2, 3], np.int64))
        colptr = paddle.to_tensor(np.array([0, 1, 2, 3, 3], np.int64))
        nodes = paddle.to_tensor(np.array([0], np.int64))
        src, dst, sample_index, reindex_nodes = incubate.graph_khop_sampler(
            row, colptr, nodes, sample_sizes=[1, 1])
        # sample_index: ORIGINAL ids aligned with local ids, inputs first
        assert sample_index.numpy()[0] == 0
        assert set(sample_index.numpy().tolist()) == {0, 1, 2}
        # reindex_nodes: local ids of the input nodes
        np.testing.assert_array_equal(reindex_nodes.numpy(), [0])
        assert len(src.numpy()) == len(dst.numpy()) == 2
        # edges reference valid local ids
        n_local = len(sample_index.numpy())
        assert (src.numpy() < n_local).all() and \
            (dst.numpy() < n_local).all()

    def test_softmax_mask_fuse(self):
        x = paddle.to_tensor(np.zeros((1, 1, 2, 4), np.float32))
        mask = paddle.to_tensor(
            np.array([0, 0, -1e9, -1e9], np.float32).reshape(1, 1, 1, 4))
        out = incubate.softmax_mask_fuse(x, mask).numpy()
        np.testing.assert_allclose(out[0, 0, 0], [0.5, 0.5, 0, 0],
                                   atol=1e-6)

    def test_softmax_mask_fuse_upper_triangle_is_causal(self):
        x = paddle.to_tensor(np.zeros((1, 1, 3, 3), np.float32))
        out = incubate.softmax_mask_fuse_upper_triangle(x).numpy()[0, 0]
        np.testing.assert_allclose(out[0], [1, 0, 0], atol=1e-6)
        np.testing.assert_allclose(out[2], [1 / 3] * 3, atol=1e-6)

    def test_identity_loss_reductions(self):
        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        assert float(incubate.identity_loss(x, 0).numpy()) == 6.0
        assert float(incubate.identity_loss(x, "mean").numpy()) == 2.0
        np.testing.assert_allclose(
            incubate.identity_loss(x, "none").numpy(), [1, 2, 3])
        with pytest.raises(ValueError):
            incubate.identity_loss(x, "bogus")


class TestIncubateOptimizers:
    def test_lookahead_syncs_slow_weights(self):
        paddle.seed(0)
        w = paddle.to_tensor(np.ones(4, np.float32), stop_gradient=False)
        inner = paddle.optimizer.SGD(0.5, parameters=[w])
        la = incubate.LookAhead(inner, alpha=0.5, k=2)
        # two steps of d(loss)/dw = 1 -> fast goes 1.0 -> 0.0; slow syncs
        # to 1.0 + 0.5*(0.0 - 1.0) = 0.5 at step k
        for _ in range(2):
            loss = w.sum()
            loss.backward()
            la.step()
            la.clear_grad()
        np.testing.assert_allclose(np.asarray(w._value), 0.5, atol=1e-6)

    def test_lookahead_validates(self):
        inner = paddle.optimizer.SGD(
            0.1, parameters=[paddle.to_tensor(np.ones(1, np.float32),
                                              stop_gradient=False)])
        with pytest.raises(ValueError):
            incubate.LookAhead(inner, alpha=2.0)
        with pytest.raises(ValueError):
            incubate.LookAhead(inner, k=0)

    def test_model_average_apply_restore(self):
        w = paddle.to_tensor(np.zeros(3, np.float32), stop_gradient=False)
        ma = incubate.ModelAverage(1.0, parameters=[w],
                                   min_average_window=100)
        for v in (1.0, 2.0, 3.0):
            w._value = np.full(3, v, np.float32) + 0 * w._value
            ma.step()
        with ma.apply():
            np.testing.assert_allclose(np.asarray(w._value), 2.0)
        np.testing.assert_allclose(np.asarray(w._value), 3.0)  # restored


class TestSavedTensorsHooks:
    def test_pack_unpack_roundtrip_through_double_grad(self):
        # a saved CONSTANT operand (stop_gradient) must round-trip through
        # pack at record time and unpack at double-grad replay;
        # differentiable operands replay through their producer edges
        x = paddle.to_tensor(np.array([2.0, 3.0], np.float32),
                             stop_gradient=False)
        c = paddle.to_tensor(np.array([5.0, 7.0], np.float32))
        events = []
        with autograd.saved_tensors_hooks(
                lambda t: (events.append("pack"),
                           np.asarray(t._value))[1],
                lambda p: (events.append("unpack"),
                           paddle.to_tensor(p))[1]):
            y = (x * x * c).sum()
        g = paddle.grad(y, x, create_graph=True)[0]   # 2xc
        g2 = paddle.grad(g.sum(), x)[0]               # 2c, via replay
        np.testing.assert_allclose(np.asarray(g2._value),
                                   2 * np.array([5.0, 7.0]), rtol=1e-5)
        assert "pack" in events and "unpack" in events

    def test_hooks_scope_exits(self):
        from paddle_tpu.framework.autograd import _saved_tensor_hooks
        with autograd.saved_tensors_hooks(lambda t: t, lambda p: p):
            assert len(_saved_tensor_hooks) == 1
        assert len(_saved_tensor_hooks) == 0


class TestWorkerInfo:
    def test_main_process_returns_none(self):
        assert paddle.io.get_worker_info() is None


class TestProgramTranslatorSwitch:
    def test_enable_false_runs_dygraph(self):
        from paddle_tpu.jit import ProgramTranslator
        calls = []

        @paddle.jit.to_static
        def f(x):
            calls.append(1)
            return x * 2

        x = paddle.to_tensor(np.ones(2, np.float32))
        try:
            ProgramTranslator().enable(False)
            out = f(x)
            np.testing.assert_allclose(np.asarray(out._value), 2.0)
            assert len(f._jitted) == 0       # nothing was traced/compiled
        finally:
            ProgramTranslator().enable(True)
        f(x)
        assert len(f._jitted) == 1           # jit path restored


class TestVisionImageBackend:
    def test_backend_roundtrip_and_load(self, tmp_path):
        import paddle_tpu.vision as vision
        assert vision.get_image_backend() == "pil"
        with pytest.raises(ValueError):
            vision.set_image_backend("turbo")
        from PIL import Image
        p = str(tmp_path / "img.png")
        Image.fromarray(np.full((4, 4, 3), 128, np.uint8)).save(p)
        img = vision.image_load(p)
        assert img.size == (4, 4)


class TestDeviceProbes:
    def test_probes(self):
        import paddle_tpu.device as device
        assert device.get_cudnn_version() is None
        assert device.is_compiled_with_ipu() is False
        assert device.is_compiled_with_cinn() is False
        assert device.is_compiled_with_mlu() is False
        assert isinstance(device.get_available_custom_device(), list)
