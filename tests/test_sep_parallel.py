"""Context/sequence parallelism: ring attention + Ulysses vs dense reference.

Runs on the 8-device virtual CPU mesh (conftest). The reference snapshot has
no sequence parallelism (SURVEY.md §5) — correctness is checked against the
framework's own dense attention.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from paddle_tpu.distributed.fleet.meta_parallel.sep_parallel import (
    ring_attention, ulysses_attention)
from paddle_tpu.nn.functional.attention import _plain_attention

B, N, H, D = 2, 32, 4, 16
SEP = 4


def _mesh():
    return Mesh(np.array(jax.devices()[:SEP]), ("sep",))


def _qkv(seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, N, H, D)), jnp.float32)
    return mk(), mk(), mk()


def _ref(q, k, v, causal):
    return _plain_attention(q, k, v, None, causal, 1.0 / (D ** 0.5))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    q, k, v = _qkv()
    mesh = _mesh()
    fn = shard_map(
        lambda a, b, c: ring_attention(a, b, c, "sep", causal=causal),
        mesh=mesh, in_specs=P(None, "sep"), out_specs=P(None, "sep"))
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v,
                                                                causal)),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(causal):
    q, k, v = _qkv(1)
    mesh = _mesh()
    fn = shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, "sep", causal=causal),
        mesh=mesh, in_specs=P(None, "sep"), out_specs=P(None, "sep"))
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v,
                                                                causal)),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_gradients_match_dense():
    q, k, v = _qkv(2)
    mesh = _mesh()

    def ring_loss(a, b, c):
        fn = shard_map(
            lambda x, y, z: ring_attention(x, y, z, "sep", causal=True),
            mesh=mesh, in_specs=P(None, "sep"), out_specs=P(None, "sep"))
        return jnp.sum(fn(a, b, c) ** 2)

    def dense_loss(a, b, c):
        return jnp.sum(_ref(a, b, c, True) ** 2)

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=5e-5, atol=5e-5)


def test_ring_attention_eight_way():
    """Full 8-way split, one query position per shard pair."""
    q, k, v = _qkv(3)
    mesh = Mesh(np.array(jax.devices()), ("sep",))
    fn = shard_map(
        lambda a, b, c: ring_attention(a, b, c, "sep", causal=True),
        mesh=mesh, in_specs=P(None, "sep"), out_specs=P(None, "sep"))
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_ref(q, k, v, True)),
                               rtol=2e-5, atol=2e-5)


def test_sdpa_dispatches_to_ring_under_sep_axis():
    """nn.functional.scaled_dot_product_attention auto-routes to ring
    attention when traced inside a shard_map binding the sep axis."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.core import Tensor

    q, k, v = _qkv(4)
    mesh = _mesh()

    def local(a, b, c):
        return F.scaled_dot_product_attention(
            Tensor(a, stop_gradient=True), Tensor(b, stop_gradient=True),
            Tensor(c, stop_gradient=True), is_causal=True)._value

    fn = shard_map(local, mesh=mesh, in_specs=P(None, "sep"),
                   out_specs=P(None, "sep"))
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_ref(q, k, v, True)),
                               rtol=2e-5, atol=2e-5)
