"""Multi-process collective harness: REAL processes, not a virtual mesh.

Reference analog: unittests/test_dist_base.py:901 (TestDistBase Popens
trainer subprocesses at :1150 with env-crafted endpoints) and the
per-primitive scripts under unittests/collective/. Here the ranks are
tests/multiproc_runner.py processes: native-TCPStore rendezvous →
jax.distributed.initialize → every eager collective asserted cross-process.
"""
import os
import socket
import subprocess
import sys

import pytest

_RUNNER = os.path.join(os.path.dirname(__file__), "multiproc_runner.py")


def _cpu_multiproc_collectives_supported():
    """Capability probe: can the CPU backend run CROSS-PROCESS collectives?

    jax 0.4.x's CPU client has no cross-process collective implementation
    (the Gloo-backed CPU collectives landed in the 0.5 line), so the ranks
    rendezvous fine and then hang/fail inside the first psum. Probe the
    version instead of burning the 240 s harness timeout per test.
    """
    import jax
    try:
        major, minor = (int(v) for v in jax.__version__.split(".")[:2])
    except ValueError:
        return True          # unparseable future scheme: assume capable
    return (major, minor) >= (0, 5)


pytestmark = pytest.mark.skipif(
    not _cpu_multiproc_collectives_supported(),
    reason="jax CPU backend lacks multiprocess collectives before 0.5.x")


def _free_port():
    """A port P with P and P+1 both currently bindable (the coordinator
    deterministically uses store port + 1)."""
    for _ in range(32):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
        try:
            with socket.socket() as s2:
                s2.bind(("127.0.0.1", p + 1))
            return p
        except OSError:
            continue
    raise RuntimeError("no consecutive free port pair found")


def _launch(world_size, timeout=240):
    port = _free_port()
    procs = []
    for rank in range(world_size):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world_size),
            "PADDLE_MASTER": f"127.0.0.1:{port}",
            # one CPU device per rank — the children force the cpu platform
            # in-process (sitecustomize preselects TPU otherwise)
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        })
        repo_root = os.path.dirname(os.path.dirname(_RUNNER))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        procs.append(subprocess.Popen(
            [sys.executable, _RUNNER], env=env, cwd=repo_root,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def test_two_rank_collectives():
    procs, outs = _launch(2)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"RANK {rank} OK" in out, f"rank {rank} output:\n{out}"


def test_four_rank_collectives():
    procs, outs = _launch(4)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"RANK {rank} OK" in out, f"rank {rank} output:\n{out}"
