"""Distributed step fusion (ops/spmd_fusion.py): collective-aware
promotion of sharded training cycles into ONE shard_map executable per
mesh, on the 8 emulated CPU devices tests/conftest.py forces.

Covers: dp=8 fused-vs-unfused parity (loss/param trajectories, allclose
per the single-program layout caveat) with exactly one promotion and zero
post-promotion retraces; dp×sharding (ZeRO stage-1 `shard_optimizer_states`)
parity with the optimizer slots STAYING sharded through fused fires; the
guardian+GradScaler backoff where only ONE shard sees a non-finite grad
(globally-consistent skip + identical scale trajectories); probation
demotion on a sum-reduced loss (`spmd_divergence` — plain jit still
fires); mesh relayout mid-run (`mesh_mismatch` split + re-promotion on
the new mesh); collective keying in the dispatch funnel (mesh-keyed
groups key, pg-less groups poison as `collective_unkeyed` and the doctor
names it); the AOT env fingerprint's mesh-topology token; and the
jax_compat shard_map shim regressions the promoter leans on (psum over
donated buffers, the partial-auto `axis_names` emulation, axis_size /
pcast) on jax 0.4.x.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.distributed as dist
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.framework.jax_compat import axis_size, pcast, shard_map
from paddle_tpu.distributed.mesh import (build_mesh, mesh_key,
                                         set_global_mesh, topology_token,
                                         value_mesh_and_spec)
from paddle_tpu.distributed.fleet.sharding_opt import shard_optimizer_states
from paddle_tpu.ops.dispatch import clear_dispatch_cache, mark_collective
from paddle_tpu.ops.step_fusion import STEP, step_cache_info
from paddle_tpu.profiler import (reset_step_fusion_stats,
                                 step_fusion_stats)
from paddle_tpu.profiler.events import clear_fusion_events, fusion_events

_DEFAULT_FLAGS = {
    "FLAGS_eager_op_cache": True,
    "FLAGS_eager_op_cache_size": 512,
    "FLAGS_eager_chain_fusion": True,
    "FLAGS_eager_chain_fusion_min_count": 3,
    "FLAGS_eager_step_fusion": True,
    "FLAGS_eager_step_fusion_min_count": 3,
    "FLAGS_eager_step_fusion_cache_size": 8,
    "FLAGS_eager_step_fusion_spmd": True,
    "FLAGS_profiler_events": True,
    "FLAGS_check_numerics": False,
}

N_DEV = jax.device_count()
needs_mesh = pytest.mark.skipif(
    N_DEV < 8, reason="needs the 8 emulated devices (conftest XLA_FLAGS)")


@pytest.fixture(autouse=True)
def _fresh():
    prev_events = bool(
        paddle.framework.flags._FLAGS.get("FLAGS_profiler_events"))
    set_flags(dict(_DEFAULT_FLAGS))
    clear_dispatch_cache()
    reset_step_fusion_stats()
    clear_fusion_events()
    yield
    set_flags(dict(_DEFAULT_FLAGS,
                   FLAGS_profiler_events=prev_events,
                   FLAGS_check_numerics=False))
    clear_dispatch_cache()
    reset_step_fusion_stats()
    set_global_mesh(None)


def _batches(steps, b=16, din=32, dout=8, seed=0):
    rng = np.random.default_rng(seed)
    return ([rng.standard_normal((b, din)).astype(np.float32)
             for _ in range(steps)],
            [rng.standard_normal((b, dout)).astype(np.float32)
             for _ in range(steps)])


def _mlp_params(seed=1, din=32, dh=16, dout=8):
    ri = np.random.default_rng(seed)
    w1 = paddle.to_tensor((ri.standard_normal((din, dh)) * 0.1)
                          .astype(np.float32), stop_gradient=False)
    b1 = paddle.to_tensor(np.zeros(dh, np.float32), stop_gradient=False)
    w2 = paddle.to_tensor((ri.standard_normal((dh, dout)) * 0.1)
                          .astype(np.float32), stop_gradient=False)
    return [w1, b1, w2]


def _run_loop(xs, ys, fused, sharding=None, opt_fn=None, loss_kind="mean",
              scaler_args=None, shard_states=False):
    """One fresh training run; returns (losses, params, opt, scaler)."""
    set_flags({"FLAGS_eager_step_fusion": fused})
    clear_dispatch_cache()
    STEP.clear()
    paddle.seed(0)
    params = _mlp_params()
    w1, b1, w2 = params
    opt = (opt_fn or (lambda ps: paddle.optimizer.Momentum(
        learning_rate=0.05, momentum=0.9, parameters=ps)))(params)
    if shard_states:
        opt._create_accumulators(params)
        shard_optimizer_states(opt)
    scaler = paddle.amp.GradScaler(**scaler_args) if scaler_args else None
    losses, scales = [], []
    for xv, yv in zip(xs, ys):
        if sharding is not None:
            xv = jax.device_put(xv, sharding)
            yv = jax.device_put(yv, sharding)
        x = paddle.Tensor(xv, stop_gradient=True)
        y = paddle.Tensor(yv, stop_gradient=True)
        h = F.relu(paddle.add(paddle.matmul(x, w1), b1))
        out = paddle.matmul(h, w2)
        diff = paddle.subtract(out, y)
        sq = paddle.multiply(diff, diff)
        loss = paddle.sum(sq) if loss_kind == "sum" else paddle.mean(sq)
        if scaler is None:
            loss.backward()
            opt.step()
        else:
            scaler.scale(loss).backward()
            scaler.step(opt)
            scaler.update()
            scales.append(float(np.asarray(scaler._state_arrays()[0])))
        opt.clear_grad()
        losses.append(float(loss))
    return losses, [np.asarray(p._value) for p in params], opt, scales


def _dp_mesh(dp=None, sharding=1):
    dp = dp if dp is not None else N_DEV // sharding
    mesh = build_mesh(dp=dp, pp=1, sharding=sharding, sep=1, mp=1)
    set_global_mesh(mesh)
    axes = ("data",) if sharding == 1 else ("data", "sharding")
    return mesh, NamedSharding(mesh, P(axes if len(axes) > 1 else "data"))


def _events(cat=None, reason=None):
    return [e for e in fusion_events()
            if (cat is None or e["cat"] == cat)
            and (reason is None or e.get("reason") == reason)]


# ---------------------------------------------------------------------------
# dp=8: ONE shard_map executable, parity, zero retraces
# ---------------------------------------------------------------------------

@needs_mesh
class TestDataParallelPromotion:
    def test_dp8_parity_and_one_executable(self):
        xs, ys = _batches(20)
        base_l, base_p, _, _ = _run_loop(xs, ys, fused=False)
        _, sharding = _dp_mesh()
        clear_fusion_events()
        fused_l, fused_p, _, _ = _run_loop(xs, ys, fused=True,
                                           sharding=sharding)
        info = step_cache_info()
        assert len(info["programs"]) == 1
        assert info["programs"][0]["spmd"] == "data8"
        promotes = _events("step.promote")
        assert len(promotes) == 1
        assert promotes[0]["detail"]["spmd"] is True
        assert promotes[0]["detail"]["mesh"] == "data8"
        # probation validated on the first fire attempt (eager committed)
        probes = [e for e in _events("step.record")
                  if (e.get("detail") or {}).get("kind") == "spmd_probation"]
        assert len(probes) == 1 and probes[0]["detail"]["ok"] is True
        # min_count=3 → the steady signature (cycle 1 lacks the leading
        # clear_grad) promotes at boundary 4, probation commits eager at
        # step 5, the remaining steps ALL fire the one fused executable
        assert len(_events("step.fire")) == len(xs) - 5
        assert not _events("step.split")
        # trajectories agree within the single-program layout caveat
        assert np.allclose(base_l, fused_l, rtol=2e-5, atol=1e-6)
        for a, b in zip(base_p, fused_p):
            assert np.allclose(a, b, rtol=2e-5, atol=1e-6)

    def test_dp8_zero_retraces_after_promotion(self):
        xs, ys = _batches(24)
        _, sharding = _dp_mesh()
        set_flags({"FLAGS_eager_step_fusion": True})
        clear_dispatch_cache()
        STEP.clear()
        paddle.seed(0)
        params = _mlp_params()
        w1, b1, w2 = params
        opt = paddle.optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                                        parameters=params)
        retraces_at = []
        for xv, yv in zip(xs, ys):
            x = paddle.Tensor(jax.device_put(xv, sharding),
                              stop_gradient=True)
            y = paddle.Tensor(jax.device_put(yv, sharding),
                              stop_gradient=True)
            h = F.relu(paddle.add(paddle.matmul(x, w1), b1))
            diff = paddle.subtract(paddle.matmul(h, w2), y)
            loss = paddle.mean(paddle.multiply(diff, diff))
            loss.backward()
            opt.step()
            opt.clear_grad()
            retraces_at.append(step_fusion_stats()["retraces"])
        # one compile (the probation fire), then a flat line: the
        # shard_map executable never re-traces on the stable sharded cycle
        assert retraces_at[-1] == retraces_at[7], retraces_at
        assert retraces_at[-1] >= 1

    def test_conv_flatten_model_lowers_spmd(self):
        """Conv nets used to demote (`flatten`/`reshape` baked the GLOBAL
        batch into their closures → shard_map trace_fail): the ops now
        emit leading-dim-polymorphic targets, so a LeNet-shaped cycle
        lowers through the mesh and its loss still falls."""
        _, sharding = _dp_mesh()
        paddle.seed(0)
        rng = np.random.default_rng(0)
        conv = paddle.nn.Conv2D(1, 2, 3)
        fc = paddle.nn.Linear(2 * 6 * 6, 4)
        params = [p for p in list(conv.parameters()) + list(fc.parameters())
                  if not p.stop_gradient]
        opt = paddle.optimizer.Adam(3e-3, parameters=params)
        x = paddle.Tensor(jax.device_put(
            rng.standard_normal((16, 1, 8, 8)).astype(np.float32),
            sharding), stop_gradient=True)
        y = paddle.Tensor(jax.device_put(
            rng.integers(0, 4, (16, 1)).astype(np.int64), sharding),
            stop_gradient=True)
        losses = []
        for _ in range(12):
            h = paddle.flatten(F.relu(conv(x)), 1)
            loss = F.cross_entropy(fc(h), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        info = step_cache_info()
        assert info["programs"] and info["programs"][0]["spmd"] == "data8"
        assert _events("step.fire") and not _events("step.split")
        assert not _events(reason="spmd_divergence")
        assert losses[-1] < losses[0]

    def test_grads_land_full_and_replicated(self):
        """p.grad from a fused fire is the POST-psum global gradient —
        what the eager path leaves after its (GSPMD) backward."""
        xs, ys = _batches(8)
        _, sharding = _dp_mesh()
        set_flags({"FLAGS_eager_step_fusion": True})
        clear_dispatch_cache()
        STEP.clear()
        paddle.seed(0)
        params = _mlp_params()
        w1, b1, w2 = params
        opt = paddle.optimizer.SGD(learning_rate=0.0, parameters=params)
        grads = []
        for _ in xs:
            # SAME batch every step (lr=0 keeps params frozen), so the
            # eager grads (head steps) and fused grads (tail steps) are
            # directly comparable
            x = paddle.Tensor(jax.device_put(xs[0], sharding),
                              stop_gradient=True)
            y = paddle.Tensor(jax.device_put(ys[0], sharding),
                              stop_gradient=True)
            h = F.relu(paddle.add(paddle.matmul(x, w1), b1))
            diff = paddle.subtract(paddle.matmul(h, w2), y)
            loss = paddle.mean(paddle.multiply(diff, diff))
            loss.backward()
            grads.append(np.asarray(w1.grad._value))
            opt.step()
            opt.clear_grad()
        # lr=0: every step sees the identical batch-grad; the fused steps
        # (tail) must agree with the eager ones (head)
        assert np.allclose(grads[0], grads[-1], rtol=2e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# dp×sharding: ZeRO stage-1 slots stay sharded through fused fires
# ---------------------------------------------------------------------------

@needs_mesh
class TestGroupShardedPromotion:
    def test_dp2_sharding4_parity_slots_stay_sharded(self):
        xs, ys = _batches(16)
        opt_fn = lambda ps: paddle.optimizer.Adam(learning_rate=0.01,
                                                  parameters=ps)
        base_l, base_p, _, _ = _run_loop(xs, ys, fused=False,
                                         opt_fn=opt_fn)
        mesh, sharding = _dp_mesh(dp=2, sharding=4)
        fused_l, fused_p, fopt, _ = _run_loop(
            xs, ys, fused=True, sharding=sharding, opt_fn=opt_fn,
            shard_states=True)
        info = step_cache_info()
        assert info["programs"][0]["spmd"] == "data2×sharding4"
        assert _events("step.fire")
        assert np.allclose(base_l, fused_l, rtol=5e-5, atol=1e-6)
        for a, b in zip(base_p, fused_p):
            assert np.allclose(a, b, rtol=5e-5, atol=1e-6)
        # the ZeRO placement survived every fused fire: each moment slot
        # is still sharded over "sharding" and device 0 holds ~1/4
        for name in ("moment1", "moment2"):
            for pname, v in fopt._accumulators[name].items():
                m, norm = value_mesh_and_spec(v)
                assert m is not None and any(
                    axes == ("sharding",) for axes in norm), (name, pname)
                frac = v.addressable_shards[0].data.nbytes / v.nbytes
                assert frac <= 0.25 + 1e-6


# ---------------------------------------------------------------------------
# guardian + GradScaler: one poisoned shard, globally-consistent skip
# ---------------------------------------------------------------------------

@needs_mesh
class TestGlobalGuardian:
    def test_scaler_backoff_single_bad_shard(self):
        set_flags({"FLAGS_check_numerics": True})
        xs, ys = _batches(18)
        bad = 12
        xs[bad] = xs[bad].copy()
        xs[bad][4:6, :] = np.inf     # rows 4–5 → ONE shard of 8
        scaler_args = dict(init_loss_scaling=1024.0,
                           incr_every_n_steps=1000,
                           decr_every_n_nan_or_inf=1)
        _, sharding = _dp_mesh()
        b_l, b_p, _, b_s = _run_loop(xs, ys, fused=False,
                                     sharding=sharding,
                                     scaler_args=scaler_args)
        f_l, f_p, _, f_s = _run_loop(xs, ys, fused=True,
                                     sharding=sharding,
                                     scaler_args=scaler_args)
        info = step_cache_info()
        assert info["programs"][0]["spmd"] == "data8"
        assert "GradScaler" in info["programs"][0]["label"]
        # the skip + backoff decision is identical on every shard and
        # between fused and eager: one bad shard halves the scale once
        assert f_s == b_s
        assert f_s[bad] == f_s[bad - 1] / 2
        for a, b in zip(b_p, f_p):
            assert np.allclose(a, b, rtol=2e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# probation: the pmean contract is verified before fused results commit
# ---------------------------------------------------------------------------

@needs_mesh
class TestProbation:
    def test_sum_loss_demotes_to_plain_jit(self):
        xs, ys = _batches(14)
        # a sum loss is 128x the mean: a tiny LR keeps the trajectory
        # numerically comparable instead of chaotic
        opt_fn = lambda ps: paddle.optimizer.SGD(learning_rate=1e-4,
                                                 parameters=ps)
        base_l, base_p, _, _ = _run_loop(xs, ys, fused=False,
                                         loss_kind="sum", opt_fn=opt_fn)
        _, sharding = _dp_mesh()
        clear_fusion_events()
        fused_l, fused_p, _, _ = _run_loop(xs, ys, fused=True,
                                           sharding=sharding,
                                           loss_kind="sum", opt_fn=opt_fn)
        divs = _events(reason="spmd_divergence")
        assert len(divs) == 1
        assert divs[0]["detail"]["why"] == "numeric_divergence"
        # demoted, not dead: the plain jit lowering fires for the rest
        assert _events("step.fire")
        assert step_cache_info()["programs"][0]["spmd"] is None
        # and numerics still match the unfused path
        assert np.allclose(base_l, fused_l, rtol=5e-5, atol=1e-6)
        for a, b in zip(base_p, fused_p):
            assert np.allclose(a, b, rtol=5e-5, atol=1e-6)

    def test_probation_step_commits_eager_bitwise(self):
        """The probation step itself must be the EAGER result: run two
        fused loops where one disables spmd — their probation-step params
        must agree bitwise (both committed by the eager optimizer)."""
        xs, ys = _batches(4)
        _, sharding = _dp_mesh()
        set_flags({"FLAGS_eager_step_fusion_spmd": False})
        plain_l, plain_p, _, _ = _run_loop(xs, ys, fused=False,
                                           sharding=sharding)
        set_flags({"FLAGS_eager_step_fusion_spmd": True})
        spmd_l, spmd_p, _, _ = _run_loop(xs, ys, fused=True,
                                         sharding=sharding)
        # 4 steps with min_count=3: promote at 3, probation at 4 — NO
        # fused fire ever committed, so the whole run is bitwise eager
        assert plain_l == spmd_l
        for a, b in zip(plain_p, spmd_p):
            assert np.array_equal(a, b)


# ---------------------------------------------------------------------------
# mesh lifecycle
# ---------------------------------------------------------------------------

@needs_mesh
class TestMeshLifecycle:
    def test_relayout_splits_mesh_mismatch_and_repromotes(self):
        xs, ys = _batches(8)
        mesh8, shard8 = _dp_mesh()
        mesh2 = build_mesh(dp=4, pp=1, sharding=2, sep=1, mp=1)
        shard2 = NamedSharding(mesh2, P(("data", "sharding")))
        set_flags({"FLAGS_eager_step_fusion": True})
        clear_dispatch_cache()
        STEP.clear()
        paddle.seed(0)
        params = _mlp_params()
        w1, b1, w2 = params
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=params)
        for i in range(14):
            use = shard8 if i < 8 else shard2
            x = paddle.Tensor(jax.device_put(xs[i % 8], use),
                              stop_gradient=True)
            y = paddle.Tensor(jax.device_put(ys[i % 8], use),
                              stop_gradient=True)
            h = F.relu(paddle.add(paddle.matmul(x, w1), b1))
            diff = paddle.subtract(paddle.matmul(h, w2), y)
            loss = paddle.mean(paddle.multiply(diff, diff))
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert _events(reason="mesh_mismatch")
        promotes = _events("step.promote")
        assert len(promotes) == 2
        assert promotes[0]["detail"]["mesh"] == "data8"
        assert promotes[1]["detail"]["mesh"] == "data4×sharding2"

    def test_mesh_key_and_topology_token(self):
        m8 = build_mesh(dp=8, pp=1, sharding=1, sep=1, mp=1)
        m8b = build_mesh(dp=8, pp=1, sharding=1, sep=1, mp=1)
        m24 = build_mesh(dp=2, pp=1, sharding=4, sep=1, mp=1)
        assert mesh_key(m8) == mesh_key(m8b)
        assert mesh_key(m8) != mesh_key(m24)
        set_global_mesh(m8)
        t8 = topology_token()
        set_global_mesh(m24)
        t24 = topology_token()
        set_global_mesh(None)
        tnone = topology_token()
        assert t8 != t24 != tnone
        assert t8[0] == N_DEV and ("data", 8) in t8[1]

    def test_aot_fingerprint_carries_mesh_topology(self):
        from paddle_tpu.ops import aot_cache
        set_global_mesh(None)
        fp0 = dict(aot_cache.env_fingerprint())
        d0 = aot_cache.fingerprint_digest()
        set_global_mesh(build_mesh(dp=8, pp=1, sharding=1, sep=1, mp=1))
        fp8 = dict(aot_cache.env_fingerprint())
        d8 = aot_cache.fingerprint_digest()
        set_global_mesh(build_mesh(dp=2, pp=1, sharding=4, sep=1, mp=1))
        d24 = aot_cache.fingerprint_digest()
        # a single-chip artifact can never deserialize into a sharded
        # process — nor a dp=8 artifact into a dp=2×sharding=4 one
        assert fp0["mesh"] != fp8["mesh"]
        assert len({d0, d8, d24}) == 3


# ---------------------------------------------------------------------------
# collective keying in the dispatch funnel
# ---------------------------------------------------------------------------

class TestCollectiveKeying:
    def test_mesh_backed_collective_keys(self):
        from paddle_tpu.ops import dispatch as dmod
        mesh = build_mesh(dp=N_DEV, pp=1, sharding=1, sep=1, mp=1)
        fn = mark_collective(lambda v: v,
                             ("all_reduce", "sum", mesh_key(mesh)))
        t = paddle.to_tensor(np.ones(4, np.float32))
        key = dmod._make_key("dist.all_reduce", fn, [t], None, (0, 0))
        assert key is not None
        assert key[1][0] == "collective"
        # same kind+op+mesh keys equal across distinct fn objects
        fn2 = mark_collective(lambda v: v,
                              ("all_reduce", "sum", mesh_key(mesh)))
        key2 = dmod._make_key("dist.all_reduce", fn2, [t], None, (0, 0))
        assert key == key2

    def test_pg_less_group_is_collective_unkeyed(self):
        from paddle_tpu.ops import dispatch as dmod
        fn = mark_collective(lambda v: v, None)
        t = paddle.to_tensor(np.ones(4, np.float32))
        key = dmod._make_key("dist.all_reduce", fn, [t], None, (0, 0))
        assert key is None
        assert dmod._classify_bypass("dist.all_reduce") \
            == "collective_unkeyed"

    @needs_mesh
    def test_unkeyed_grad_collective_poisons_cycle(self):
        xs, ys = _batches(8)
        _, sharding = _dp_mesh()
        group = dist.collective.Group(0, N_DEV, id=91,
                                      ranks=list(range(N_DEV)))
        set_flags({"FLAGS_eager_step_fusion": True})
        clear_dispatch_cache()
        STEP.clear()
        paddle.seed(0)
        params = _mlp_params()
        w1, b1, w2 = params
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=params)
        for xv, yv in zip(xs, ys):
            x = paddle.Tensor(jax.device_put(xv, sharding),
                              stop_gradient=True)
            y = paddle.Tensor(jax.device_put(yv, sharding),
                              stop_gradient=True)
            h = F.relu(paddle.add(paddle.matmul(x, w1), b1))
            diff = paddle.subtract(paddle.matmul(h, w2), y)
            loss = paddle.mean(paddle.multiply(diff, diff))
            loss.backward()
            dist.all_reduce(w1.grad, group=group)
            opt.step()
            opt.clear_grad()
        assert _events(reason="collective_unkeyed")
        assert not _events("step.promote")
        from paddle_tpu.profiler.explain import explain
        rep = explain()
        assert rep["verdict"] == "never_promoted"
        assert "collective_unkeyed" in rep["headline"]

    def test_keyed_collective_via_default_group_stays_clean(self):
        """The single-controller identity path of a mesh-backed group
        must not disturb promotion (no dispatch, no poison)."""
        xs, ys = _batches(8)
        set_flags({"FLAGS_eager_step_fusion": True})
        clear_dispatch_cache()
        STEP.clear()
        paddle.seed(0)
        params = _mlp_params()
        w1, b1, w2 = params
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=params)
        for xv, yv in zip(xs, ys):
            x = paddle.Tensor(xv, stop_gradient=True)
            y = paddle.Tensor(yv, stop_gradient=True)
            h = F.relu(paddle.add(paddle.matmul(x, w1), b1))
            diff = paddle.subtract(paddle.matmul(h, w2), y)
            loss = paddle.mean(paddle.multiply(diff, diff))
            loss.backward()
            dist.all_reduce(loss)      # default group: identity, no-op
            opt.step()
            opt.clear_grad()
        assert _events("step.promote")
        assert not _events(reason="collective_unkeyed")


# ---------------------------------------------------------------------------
# jax_compat shard_map shim regressions (the promoter leans on these)
# ---------------------------------------------------------------------------

@needs_mesh
class TestJaxCompatShims:
    def _mesh(self):
        return build_mesh(dp=4, pp=1, sharding=2, sep=1, mp=1)

    def test_psum_over_donated_buffers(self):
        """The fused SPMD step donates its optimizer-slot buffers into a
        jit(shard_map(psum ...)) program — the exact shape the promoter
        compiles. Donation must not perturb the collective's result on
        jax 0.4.x (check_rep=False path)."""
        mesh = self._mesh()

        def body(x, acc):
            s = jax.lax.pmean(x, ("data", "sharding"))
            return s, acc + s

        fn = jax.jit(shard_map(body, mesh=mesh,
                               in_specs=(P(("data", "sharding")), P()),
                               out_specs=(P(), P())),
                     donate_argnums=(1,))
        xs = np.arange(16, dtype=np.float32).reshape(16, 1)
        x = jax.device_put(xs, NamedSharding(mesh, P(("data", "sharding"))))
        acc = jnp.zeros((2, 1), jnp.float32)
        expected = xs.reshape(8, 2, 1).mean(axis=0)
        for i in range(3):
            out, acc = fn(x, acc)
            np.testing.assert_allclose(np.asarray(out), expected,
                                       rtol=1e-6)
        np.testing.assert_allclose(np.asarray(acc), 3 * expected,
                                   rtol=1e-6)

    def test_partial_auto_axis_names_emulation(self):
        """axis_names={"data"} (partial-manual) on 0.4.x maps every axis
        manually with replication over the unnamed ones — numerically
        identical to real partial-auto for specs that never mention
        them."""
        mesh = self._mesh()

        def body(x):
            return jax.lax.psum(x, "data")

        fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P("data"),
                               out_specs=P("data"),
                               axis_names={"data"}))
        x = np.arange(8, dtype=np.float32).reshape(4, 2)
        out = np.asarray(fn(x))
        expected = np.tile(x.sum(axis=0, keepdims=True), (4, 1))
        np.testing.assert_allclose(out, expected, rtol=1e-6)

    def test_axis_names_validated_against_mesh(self):
        mesh = self._mesh()
        with pytest.raises(ValueError, match="not in mesh axes"):
            shard_map(lambda x: x, mesh=mesh, in_specs=P(),
                      out_specs=P(), axis_names={"bogus"})

    def test_axis_size_and_pcast_inside_manual_region(self):
        mesh = self._mesh()

        def body(x):
            n = axis_size("data")
            return pcast(x * n, "data", to="varying")

        fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P("data"),
                               out_specs=P("data")))
        x = np.ones((4, 2), np.float32)
        np.testing.assert_allclose(np.asarray(fn(x)), 4 * x, rtol=1e-6)


# ---------------------------------------------------------------------------
# perf guard + doctor fixture
# ---------------------------------------------------------------------------

@needs_mesh
class TestPerfGuards:
    @pytest.mark.perf_smoke
    def test_promoted_dp_step_beats_eager_collectives(self):
        """The perf_smoke leg (i) as a pytest: zero retraces after
        promotion and ≥1.3x over the unfused eager-collective loop."""
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        os.pardir, "tools"))
        import perf_smoke

        def timed(step):
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(perf_smoke.MEASURE):
                    step()
                step.sync()
                best = min(best,
                           (time.perf_counter() - t0) / perf_smoke.MEASURE)
            return best

        step = perf_smoke._dp_loop(step_fused=False)
        for _ in range(perf_smoke.WARMUP):
            step()
        step.sync()
        t_eager = timed(step)
        step = perf_smoke._dp_loop(step_fused=True)
        for _ in range(perf_smoke.WARMUP):
            step()
        step.sync()
        s0 = step_fusion_stats()
        t_fused = timed(step)
        s1 = step_fusion_stats()
        assert s1["retraces"] == s0["retraces"], "post-promotion retrace"
        assert s1["fused_steps"] > s0["fused_steps"]
        assert next((p["spmd"] for p in step_cache_info()["programs"]
                     if p["spmd"]), None) == f"data{N_DEV}"
        speedup = t_eager / t_fused
        assert speedup >= perf_smoke.DP_SPEEDUP_GUARD, (
            f"promoted DP step speedup {speedup:.2f}x below "
            f"{perf_smoke.DP_SPEEDUP_GUARD}x (eager {t_eager*1e6:.0f}us "
            f"vs fused {t_fused*1e6:.0f}us)")

    @pytest.mark.perf_smoke
    def test_doctor_demo_dp_names_collective_unkeyed(self):
        out = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                          "fusion_doctor.py"),
             "--demo", "dp", "--steps", "10", "--json"],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr
        rep = json.loads(out.stdout)
        assert rep["verdict"] == "never_promoted"
        assert "collective_unkeyed" in rep["headline"]
        assert "dist.all_reduce" in rep["headline"]


class TestSuperCycleSPMD:
    """Universal promotion: a sharded k-micro-batch accumulation loop
    promotes under the SPMD path — the sub-executable accumulates LOCAL
    gradient sums with NO per-micro-batch collective, and the update
    executable fires ONE fused pmean over the accumulated sums (k× less
    gradient traffic), probation-validated against the bitwise eager
    replay."""

    def test_dp8_accum_promotes_with_parity(self):
        xs, _ = _batches(60)
        it = iter(xs)

        def run(fused, shard):
            set_flags({"FLAGS_eager_step_fusion": fused})
            clear_dispatch_cache()
            STEP.clear()
            paddle.seed(0)
            params = _mlp_params()
            w1, b1, w2 = params
            opt = paddle.optimizer.Momentum(
                learning_rate=0.05, momentum=0.9, parameters=params)
            losses = []
            src = iter(xs)
            for _ in range(14):
                for _m in range(3):
                    xv = next(src)
                    x = paddle.Tensor(
                        jax.device_put(xv, shard) if shard is not None
                        else jnp.asarray(xv), stop_gradient=True)
                    h = F.relu(paddle.add(paddle.matmul(x, w1), b1))
                    loss = paddle.mean(
                        paddle.multiply(paddle.matmul(h, w2),
                                        paddle.matmul(h, w2)))
                    loss.backward()
                opt.step()
                opt.clear_grad()
                # post-step read: served from the sub-executable output
                losses.append(float(loss.numpy()))
            return np.asarray(losses), w1.numpy().copy()

        base_l, base_w = run(False, None)
        _, sharding = _dp_mesh()
        clear_fusion_events()
        fused_l, fused_w = run(True, sharding)
        s = step_fusion_stats()
        assert s["steps_promoted"] == 1
        assert s["fused_steps"] >= 8, s
        assert s["fallback_splits"] == 0, s
        promo = [e for e in fusion_events("step.promote")]
        assert promo and promo[-1]["detail"]["spmd"] \
            and promo[-1]["detail"]["super"], promo
        prob = [e for e in fusion_events("step.record")
                if e.get("detail", {}).get("kind") == "spmd_probation"]
        assert prob and prob[-1]["detail"]["ok"], prob
        np.testing.assert_allclose(fused_l, base_l, rtol=2e-3, atol=1e-5)
        np.testing.assert_allclose(fused_w, base_w, rtol=2e-3, atol=1e-4)

    def test_dp8_accum_zero_retraces_any_k(self):
        """After the probation fire, k changes replay on the SAME two
        shard_map executables — zero fresh retraces."""
        _, sharding = _dp_mesh()
        set_flags({"FLAGS_eager_step_fusion": True})
        clear_dispatch_cache()
        STEP.clear()
        reset_step_fusion_stats()
        paddle.seed(0)
        params = _mlp_params()
        w1, b1, w2 = params
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=params)
        rng = np.random.default_rng(0)

        def cycle(k):
            for _ in range(k):
                xv = rng.standard_normal((16, 32)).astype(np.float32)
                x = paddle.Tensor(jax.device_put(xv, sharding),
                                  stop_gradient=True)
                h = F.relu(paddle.add(paddle.matmul(x, w1), b1))
                loss = paddle.mean(paddle.matmul(h, w2))
                loss.backward()
            opt.step()
            opt.clear_grad()

        for _ in range(8):
            cycle(2)
        s0 = step_fusion_stats()
        assert s0["steps_promoted"] == 1
        assert s0["fused_steps"] >= 2, s0
        for k in (4, 3, 6):
            cycle(k)
        s1 = step_fusion_stats()
        assert s1["retraces"] == s0["retraces"], (s0["retraces"],
                                                 s1["retraces"])
        assert s1["fallback_splits"] == 0
