"""Promotion-safety static analyzer (paddle_tpu/analysis) — the fusion
linter.

Covers the PR 15 contract end to end:

  * one golden known-bad fixture per rule (tests/fixtures/lint/),
    asserting the EXACT {rule, reason_code, line} findings — the rules
    must keep firing on the seeded violations;
  * the clean-tree gate: `tools/fusion_lint.py --baseline` exits 0 on
    the repo (this IS the tier-1 CI wiring) and finishes inside the
    10 s budget;
  * per-fixture CLI runs exit non-zero (all six rules demonstrated);
  * baseline add/expire round-trip + stale-suppression reporting;
  * the --json schema (version/findings/summary keys, every finding
    carrying a valid REASON_CODES entry that has a REASON_HINTS hint);
  * the R5 contract freeze on the LIVE tree (extends
    tests/test_fusion_events.py's REASON_CODES/HINTS freeze to the
    whole observability surface);
  * `fusion_doctor --demo ... --lint` smoke (the lint section rides the
    doctor report).
"""
import json
import os
import subprocess
import sys
import time

import pytest

from paddle_tpu.analysis import (Baseline, analyze, findings_to_dicts,
                                 validate_findings)
from paddle_tpu.analysis.baseline import DEFAULT_BASELINE
from paddle_tpu.profiler.events import REASON_CODES
from paddle_tpu.profiler.explain import REASON_HINTS

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "lint")

_FIXTURE_PATHS = {
    "R1": ["r1_unkeyable.py"],
    "R2": ["r2_stateful_rng.py"],
    "R3": ["r3_host_sync.py"],
    "R4": ["distributed/r4_unkeyed.py",
           "incubate/distributed/r4_lax_unkeyed.py"],
    "R5": ["r5_project"],
    "R6": ["serving/r6_locks.py", "serving/r6_tenancy.py",
           "distributed/fabric.py"],
    "R7": ["r7_perf_contract.py"],
}


def _fixture_findings(rule):
    return analyze(root=FIXTURES, paths=_FIXTURE_PATHS[rule])


def _triples(findings):
    return sorted((f.rule, f.reason_code, f.line) for f in findings)


class TestRuleFixtures:
    """Exact {rule, reason_code, line} findings per golden fixture. A
    changed line number here means the fixture drifted — keep them in
    sync deliberately."""

    def test_r1_unkeyable_closure(self):
        fs = _fixture_findings("R1")
        assert _triples(fs) == [
            ("R1", "unkeyable_closure", 19),   # captured array idx
            ("R1", "unkeyable_closure", 28),   # captured Tensor m
            ("R1", "unkeyable_closure", 36),   # mutable module global
        ]
        # the fixed form (index threaded as input) stays clean
        assert not any(f.symbol == "good_threaded" for f in fs)

    def test_r2_stateful_rng(self):
        fs = _fixture_findings("R2")
        assert _triples(fs) == [
            ("R2", "rng_rekey", 14),           # get_rng_key()
            ("R2", "rng_rekey", 19),           # split_key()
            ("R2", "rng_rekey", 25),           # default_generator.next_key()
        ]
        assert not any(f.symbol == "good_hoisted" for f in fs)

    def test_r3_host_sync(self):
        fs = _fixture_findings("R3")
        assert _triples(fs) == [
            ("R3", "mid_step_peek", 11),       # .numpy()
            ("R3", "mid_step_peek", 12),       # float()
            ("R3", "mid_step_peek", 24),       # .item()
        ]
        assert not any(f.symbol == "good_aval_op" for f in fs)

    def test_r4_unkeyed_collective(self):
        fs = _fixture_findings("R4")
        assert _triples(fs) == [
            ("R4", "collective_unkeyed", 8),   # pg call outside the funnel
            ("R4", "collective_unkeyed", 13),  # unstamped lax.ppermute
            ("R4", "collective_unkeyed", 14),  # funnel without the stamp
            ("R4", "collective_unkeyed", 20),  # unstamped lax.all_to_all
        ]
        assert not any(f.symbol == "good_marked_collective" for f in fs)
        # the stamped and shard_map-only lax forms stay clean
        assert not any(f.symbol.startswith("good_") for f in fs)

    def test_r5_contract_coverage(self):
        fs = _fixture_findings("R5")
        got = {(f.rule, f.reason_code, f.file, f.line) for f in fs}
        assert got == {
            ("R5", "contract_drift", "r5_project/events.py", 7),
            ("R5", "contract_drift", "r5_project/events.py", 24),
            ("R5", "contract_drift", "r5_project/events.py", 25),
            ("R5", "contract_drift", "r5_project/explain.py", 3),
            ("R5", "contract_drift", "r5_project/metrics.py", 4),
            ("R5", "contract_drift", "r5_project/metrics.py", 21),
            ("R5", "contract_drift", "r5_project/consumer.py", 8),
        }

    def test_r6_lock_discipline(self):
        fs = _fixture_findings("R6")
        got = {(f.rule, f.reason_code, f.file, f.line) for f in fs}
        assert got == {
            # serving/r6_locks.py + r6_tenancy.py
            ("R6", "lock_discipline", "serving/r6_locks.py", 16),
            ("R6", "lock_discipline", "serving/r6_tenancy.py", 18),
            ("R6", "lock_discipline", "serving/r6_locks.py", 22),
            ("R6", "lock_discipline", "serving/r6_locks.py", 23),
            ("R6", "lock_discipline", "serving/r6_tenancy.py", 24),
            ("R6", "lock_discipline", "serving/r6_tenancy.py", 25),
            ("R6", "lock_discipline", "serving/r6_locks.py", 35),
            ("R6", "lock_discipline", "serving/r6_tenancy.py", 38),
            # distributed/fabric.py (the elastic-fabric control plane)
            ("R6", "lock_discipline", "distributed/fabric.py", 18),
            ("R6", "lock_discipline", "distributed/fabric.py", 24),
            ("R6", "lock_discipline", "distributed/fabric.py", 25),
            ("R6", "lock_discipline", "distributed/fabric.py", 34),
        }
        # the snapshot-then-invoke pattern stays clean
        assert not any(f.symbol.startswith("GoodRegistry") for f in fs)
        # ...and the tenancy-flavored fixed form (the discipline
        # serving/tenancy.py actually ships) stays clean too
        assert not any(f.symbol.startswith("GoodPrefixIndex") for f in fs)
        # ...and the fabric-flavored collect-then-emit form
        assert not any(f.symbol.startswith("GoodCoordinator") for f in fs)

    def test_r7_perf_contract(self):
        fs = _fixture_findings("R7")
        assert _triples(fs) == [
            ("R7", "perf_contract", 34),       # heavy op, uncoverable name
            ("R7", "perf_contract", 57),       # flag off the fingerprint
        ]
        # matmul-family dispatch name and declared estimator stay clean
        assert not any(f.symbol.startswith("good_") for f in fs)
        # neutral + fingerprinted flag reads stay clean (the `routed`
        # finding is the undeclared flag only)
        assert all("FLAGS_undeclared_routing" in f.message
                   for f in fs if f.symbol == "routed")

    def test_every_finding_on_the_reason_contract(self):
        """Static findings and runtime attributions are ONE taxonomy:
        every fixture finding carries a REASON_CODES entry with a
        REASON_HINTS hint."""
        for rule in _FIXTURE_PATHS:
            fs = _fixture_findings(rule)
            assert fs, f"{rule} fixture produced no findings"
            assert validate_findings(fs) == []
            for d in findings_to_dicts(fs):
                assert d["reason_code"] in REASON_CODES
                assert d["reason_code"] in REASON_HINTS
                assert d["hint"]


class TestCleanTree:
    """The repo itself holds the invariants the linter proves."""

    def test_repo_findings_all_baselined(self):
        findings = analyze(root=REPO)
        bl = Baseline.load(DEFAULT_BASELINE)
        live, muted = bl.split(findings)
        assert live == [], (
            "unsuppressed fusion_lint findings on the tree:\n"
            + "\n".join(f"{f.file}:{f.line} {f.rule} {f.message}"
                        for f in live))
        assert bl.stale(findings) == [], "stale baseline suppressions"

    def test_r5_contract_freeze_on_live_tree(self):
        """The R5 audit runs CLEAN on the real contracts — frozen as a
        tier-1 test so a reason code without a hint, a metric without a
        merge policy, an off-contract category, or an unregistered
        FLAGS read can never land again."""
        assert analyze(root=REPO, rules=["R5"]) == []

    def test_r6_lock_discipline_clean_on_live_tree(self):
        assert analyze(root=REPO, rules=["R6"]) == []

    def test_r7_perf_contract_on_live_tree(self):
        """Every heavy op is coverable (family name or declare_op_flops)
        and every ops/nn flag is classified — except einsum, whose
        equation-dependent cost is a deliberate, noted baseline entry."""
        fs = analyze(root=REPO, rules=["R7"])
        assert [(f.file, f.symbol) for f in fs] == \
            [("paddle_tpu/ops/einsum_op.py", "einsum")]
        bl = Baseline.load(DEFAULT_BASELINE)
        assert bl.split(fs)[0] == []

    def test_cli_gate_exits_zero_within_budget(self):
        """The tier-1 CI wiring: `python tools/fusion_lint.py
        --baseline` exits 0 on the tree, inside the 10 s budget."""
        t0 = time.monotonic()
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "fusion_lint.py"),
             "--baseline"],
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        dt = time.monotonic() - t0
        assert out.returncode == 0, out.stdout + out.stderr
        assert "0 unsuppressed finding(s)" in out.stdout
        assert dt < 10.0, f"fusion_lint took {dt:.1f}s (budget 10s)"


class TestCLI:
    def test_each_fixture_fails_the_gate(self):
        """Acceptance: non-zero exit on each seeded violation — all six
        rules demonstrated through the real CLI."""
        for rule, paths in sorted(_FIXTURE_PATHS.items()):
            out = subprocess.run(
                [sys.executable,
                 os.path.join(REPO, "tools", "fusion_lint.py"),
                 "--root", FIXTURES] + paths,
                capture_output=True, text=True, timeout=120,
                env={**os.environ, "JAX_PLATFORMS": "cpu"})
            assert out.returncode == 1, \
                f"{rule}: expected exit 1, got {out.returncode}\n" \
                + out.stdout + out.stderr
            assert rule in out.stdout

    def test_json_schema(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "fusion_lint.py"),
             "--root", FIXTURES, "--json"] + _FIXTURE_PATHS["R1"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 1
        doc = json.loads(out.stdout)
        assert doc["version"] == 1
        assert set(doc) == {"version", "findings", "suppressed",
                            "stale_suppressions", "rules", "summary"}
        assert doc["summary"]["findings"] == len(doc["findings"]) > 0
        assert set(doc["summary"]["by_rule"]) == {"R1"}
        for f in doc["findings"]:
            assert set(f) == {"rule", "file", "line", "symbol",
                              "reason_code", "message", "hint"}
            assert f["reason_code"] in REASON_CODES
            assert f["reason_code"] in REASON_HINTS
            assert f["hint"]
        # the rule table rides along for consumers
        assert set(doc["rules"]) == {"R1", "R2", "R3", "R4", "R5", "R6",
                                     "R7"}

    def test_fix_hints_render(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "fusion_lint.py"),
             "--root", FIXTURES, "--fix-hints"] + _FIXTURE_PATHS["R2"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 1
        assert "fix: " in out.stdout
        assert "rng_key_input" in out.stdout


class TestBaseline:
    def test_add_match_expire_roundtrip(self, tmp_path):
        findings = _fixture_findings("R1")
        assert findings
        path = str(tmp_path / "baseline.json")
        bl = Baseline()
        for f in findings:
            bl.add(f, note="fixture acknowledgment")
        bl.save(path)

        bl2 = Baseline.load(path)
        live, muted = bl2.split(findings)
        assert live == [] and len(muted) == len(findings)
        assert bl2.stale(findings) == []

        # the violations get fixed -> every entry expires
        dead = bl2.stale([])
        assert len(dead) == len(bl2.entries)
        removed = bl2.expire([])
        assert removed == dead and bl2.entries == []

    def test_partial_expiry_keeps_live_entries(self, tmp_path):
        r1 = _fixture_findings("R1")
        r2 = _fixture_findings("R2")
        bl = Baseline()
        for f in r1 + r2:
            bl.add(f, note="n")
        # R2's violations get fixed; R1's remain
        removed = bl.expire(r1)
        assert all(e["rule"] == "R2" for e in removed)
        assert all(e["rule"] == "R1" for e in bl.entries)
        live, muted = bl.split(r1)
        assert live == []

    def test_add_is_idempotent(self):
        f = _fixture_findings("R1")[0]
        bl = Baseline()
        e1 = bl.add(f, note="x")
        e2 = bl.add(f, note="y")
        assert e1 is e2 and len(bl.entries) == 1

    def test_checked_in_baseline_entries_all_noted(self):
        """Every shipped suppression carries a human justification."""
        bl = Baseline.load(DEFAULT_BASELINE)
        assert bl.entries, "the checked-in baseline exists"
        for e in bl.entries:
            assert e.get("note") and "fill me in" not in e["note"], e


class TestDoctorLint:
    @pytest.mark.perf_smoke
    def test_doctor_demo_with_lint_section(self):
        """`fusion_doctor --demo masked --lint --json`: the lint block
        rides the doctor report, clean on the shipped tree."""
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "fusion_doctor.py"),
             "--demo", "masked", "--steps", "8", "--lint", "--json"],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr
        rep = json.loads(out.stdout)
        lint = rep["lint"]
        assert lint["findings"] == []
        assert lint["suppressed"] > 0
        assert lint["stale_suppressions"] == 0
        assert lint["predicted"] == []     # clean promotion: nothing to
        #                                    cross-reference


class TestGateCannotSilentlyPass:
    """The three silent-pass holes a lint gate must not have: a typo'd
    scan path, an unknown rule id, and an unparsable file must each
    FAIL loudly instead of scanning nothing and reporting clean."""

    def test_missing_explicit_path_is_an_error(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "fusion_lint.py"),
             "paddle_tpu/no_such_dir", "--baseline"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 2
        assert "does not exist" in out.stderr

    def test_unknown_rule_id_is_an_error(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "fusion_lint.py"),
             "--rules", "R99"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 2
        assert "unknown rule" in out.stderr

    def test_unparsable_file_is_an_error(self, tmp_path):
        bad = tmp_path / "broken.py"
        bad.write_text("def broken(:\n    <<<<<<< merge marker\n")
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "fusion_lint.py"),
             "--root", str(tmp_path), "broken.py"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 2
        assert "cannot parse" in out.stderr
