"""Elastic fleet fabric membership edge cases (PR 20,
distributed/fabric.py).

The chaos scenarios (`tools/chaos.py --scenario fleet_kill /
fleet_flap`) prove the END-TO-END contract — SIGKILL mid-super-cycle,
checkpoint restore, AOT warm rejoin. This file pins the membership
PROTOCOL itself with sub-second leases and no jax training:

  * initial rendezvous is a barrier: the spec publishes once, at
    generation 1, with distinct compact ranks;
  * a full-lease silence is a loss: ONE generation bump, survivor ranks
    compact, `fleet.leave` (host_lost) + `fleet.rebuild` (mesh_rebuild)
    attributed;
  * two hosts lost in one reap window cost ONE bump (one rebuild), not
    two;
  * slow-but-alive inside the lease flaps NOTHING;
  * a rejoin lands at the CURRENT generation (fleet.rejoin), never a
    fresh count;
  * a replacement coordinator that recovers a consistent incumbent
    fleet republishes at the SAME generation with zero rebuilds;
  * members refuse a lower generation (stale/rogue coordinator) and
    fast-forward it instead — fleet generations are monotonic even
    across coordinator kill-9.
"""
from __future__ import annotations

import threading
import time

import pytest

from paddle_tpu.distributed import fabric
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.profiler.events import clear_fusion_events, fusion_events


@pytest.fixture(autouse=True)
def _fresh():
    set_flags({"FLAGS_profiler_events": True})
    clear_fusion_events()
    yield
    clear_fusion_events()
    set_flags({"FLAGS_profiler_events": False})


def _events(cat):
    return [e for e in fusion_events() if e["cat"] == cat]


def _join_all(coord, hosts, **kw):
    """Concurrent rendezvous (join blocks until the barrier opens)."""
    members = {h: fabric.Member((coord.host, coord.port), h, **kw)
               for h in hosts}
    results = {}

    def run(h):
        results[h] = members[h].join(timeout=30.0)

    threads = [threading.Thread(target=run, args=(h,)) for h in hosts]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert len(results) == len(hosts)
    return members, results


def _wait(pred, timeout=10.0, tick=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(tick)
    return False


def _sync_leases(members):
    """Line up every member's coordinator-side lease clock so a
    subsequent batch of silences lands in ONE reap window."""
    for m in members:
        m.heartbeat_once()


class TestRendezvous:
    def test_barrier_publishes_once_at_generation_one(self):
        coord = fabric.Coordinator(lease_s=5.0, expected=3)
        try:
            members, results = _join_all(coord, ("a", "b", "c"))
            ranks = sorted(r for r, _ in results.values())
            assert ranks == [0, 1, 2]
            specs = [s for _, s in results.values()]
            assert all(s["generation"] == 1 for s in specs)
            assert all(s["world"] == 3 for s in specs)
            assert coord.generation == 1
            assert coord.report()["rebuilds"] == 1   # the forming publish
            assert len(_events("fleet.join")) == 3
            # nobody has anything to adopt: the forming spec was returned
            # by join itself
            assert all(m.poll() is None for m in members.values())
        finally:
            for m in members.values():
                m.close()
            coord.close()

    def test_forming_fleet_reaps_nothing(self):
        coord = fabric.Coordinator(lease_s=0.2, expected=2)
        try:
            m = fabric.Member((coord.host, coord.port), "only")
            with pytest.raises(TimeoutError):
                m.join(timeout=0.8)     # barrier never opens
            # well past the lease: a FORMING fleet must not reap members
            assert coord.report()["world"] == 1
            assert coord.generation == 0
        finally:
            m.close()
            coord.close()


class TestLeaseMembership:
    def test_host_lost_one_bump_ranks_compact(self):
        coord = fabric.Coordinator(lease_s=0.4, expected=3)
        members, _ = _join_all(coord, ("a", "b", "c"))
        try:
            victim = next(h for h, m in members.items() if m.rank == 1)
            members[victim].close()              # crash-shaped: no leave
            assert _wait(lambda: coord.generation == 2, timeout=5.0)
            rep = coord.report()
            assert rep["world"] == 2
            assert rep["lost"] == [{"host": victim, "generation": 2}]
            leaves = _events("fleet.leave")
            assert [e["reason"] for e in leaves] == ["host_lost"]
            assert leaves[0]["op"] == victim
            # survivors adopt exactly one rebuild with compacted ranks
            survivors = [m for h, m in members.items() if h != victim]
            for m in survivors:
                assert _wait(lambda: m.poll() is not None, timeout=5.0)
            assert sorted(m.rank for m in survivors) == [0, 1]
            assert all(m.generation == 2 for m in survivors)
        finally:
            for m in members.values():
                m.close()
            coord.close()

    def test_two_losses_in_one_window_cost_one_bump(self):
        coord = fabric.Coordinator(lease_s=0.4, expected=3)
        members, _ = _join_all(coord, ("a", "b", "c"))
        try:
            doomed = [members["a"], members["b"]]
            # one reap window: align the lease clocks, then silence both
            _sync_leases(doomed)
            for m in doomed:
                m.close()
            assert _wait(lambda: coord.report()["world"] == 1,
                         timeout=5.0)
            rep = coord.report()
            assert coord.generation == 2         # ONE bump for the batch
            assert rep["rebuilds"] == 2          # forming + this batch
            assert {r["generation"] for r in rep["lost"]} == {2}
            assert len(_events("fleet.leave")) == 2
            assert _wait(lambda: members["c"].poll() is not None,
                         timeout=5.0)
            assert members["c"].rank == 0
            assert members["c"].generation == 2
        finally:
            for m in members.values():
                m.close()
            coord.close()

    def test_slow_but_alive_inside_lease_never_flaps(self):
        coord = fabric.Coordinator(lease_s=0.6, expected=2)
        members, _ = _join_all(coord, ("a", "b"))
        try:
            members["a"].pause_heartbeats(0.3)   # half the lease
            time.sleep(0.9)                      # several reap ticks
            assert coord.generation == 1
            assert coord.report()["world"] == 2
            assert coord.report()["rebuilds"] == 1
            assert all(m.poll() is None for m in members.values())
            assert not _events("fleet.leave")
        finally:
            for m in members.values():
                m.close()
            coord.close()


class TestRejoin:
    def test_rejoin_lands_at_current_generation(self):
        coord = fabric.Coordinator(lease_s=0.4, expected=2)
        members, _ = _join_all(coord, ("a", "b"))
        try:
            members["b"].close()
            assert _wait(lambda: coord.generation == 2, timeout=5.0)
            assert _wait(lambda: members["a"].poll() is not None,
                         timeout=5.0)
            # the restarted host carries its last adopted generation
            again = fabric.Member((coord.host, coord.port), "b",
                                  gen_seen=1)
            rank, spec = again.join(timeout=10.0)
            assert spec["generation"] == 3       # rejoin bumps once
            assert spec["world"] == 2
            assert again.generation == 3
            rejoins = _events("fleet.rejoin")
            assert rejoins and rejoins[-1]["op"] == "b"
            # the incumbent keeps rank 0; the rejoiner appends
            assert _wait(lambda: members["a"].poll() is not None,
                         timeout=5.0)
            assert members["a"].rank == 0 and rank == 1
            again.close()
        finally:
            for m in members.values():
                m.close()
            coord.close()


class TestCoordinatorRestart:
    def test_replacement_recovers_consistent_fleet_without_rebuild(self):
        coord = fabric.Coordinator(lease_s=0.6, expected=2)
        port = coord.port
        members, _ = _join_all(coord, ("a", "b"))
        try:
            coord.close()                        # kill-9 the control plane
            time.sleep(0.2)
            # members keep training at their generation (split-brain rule)
            assert all(m.generation == 1 for m in members.values())
            repl = fabric.Coordinator(port=port, lease_s=0.6,
                                      recovering=True, recovery_s=0.6)
            try:
                # unknown-host heartbeats re-register both members inside
                # the window; the recovered fleet is consistent, so the
                # spec republishes at the SAME generation, silently
                assert _wait(lambda: repl.report()["state"] == "live",
                             timeout=5.0)
                assert _wait(lambda: repl.report()["world"] == 2,
                             timeout=5.0)
                assert repl.generation == 1
                assert repl.report()["rebuilds"] == 0
                assert all(m.poll() is None for m in members.values())
                assert {r["rank"] for r in repl.report()["hosts"]} \
                    == {0, 1}
            finally:
                repl.close()
        finally:
            for m in members.values():
                m.close()
            coord.close()

    def test_member_refuses_lower_generation_and_fast_forwards(self):
        coord = fabric.Coordinator(lease_s=5.0, expected=1)
        m = fabric.Member((coord.host, coord.port), "a")
        try:
            m.join(timeout=10.0)
            assert coord.generation == 1
            rebuilds_before = coord.report()["rebuilds"]
            # the member lived through generations this coordinator never
            # saw (it was restarted from scratch): refuse + fast-forward
            with m._lock:
                m._generation = 5
            m.heartbeat_once()
            refusals = [e for e in _events("fleet.rejoin")
                        if e.get("reason") == "stale_member"]
            assert refusals
            assert refusals[-1]["detail"]["refused_generation"] == 1
            assert refusals[-1]["detail"]["generation"] == 5
            # the coordinator adopted the higher generation in place:
            # same membership, no rebuild
            assert _wait(lambda: coord.generation == 5, timeout=5.0)
            assert coord.report()["rebuilds"] == rebuilds_before
            assert m.heartbeat_once()["generation"] == 5
            # and the member never adopted anything lower
            assert m.generation == 5 and m.poll() is None
        finally:
            m.close()
            coord.close()


class TestHelpers:
    def test_mesh_for_spec_rejects_oversized_world(self):
        import jax
        spec = {"generation": 1, "world": len(jax.devices()) + 1,
                "hosts": []}
        with pytest.raises(ValueError, match="local"):
            fabric.mesh_for_spec(spec)

    def test_prefetch_artifacts_empty_store(self, tmp_path):
        out = fabric.prefetch_artifacts(str(tmp_path))
        assert out == {"artifacts": 0, "bytes": 0, "corrupt": 0,
                       "other_fingerprint": 0}

    def test_fleet_report_armed_states(self):
        assert fabric.fleet_report() == {"armed": False}
        coord = fabric.Coordinator(lease_s=5.0, expected=1)
        m = fabric.Member((coord.host, coord.port), "solo")
        try:
            m.join(timeout=10.0)
            # the join recorded the PRE-join generation (0); the first
            # heartbeat reports the adopted one and clears the stale flag
            m.heartbeat_once()
            rep = fabric.fleet_report()
            assert rep["armed"] and rep["generation"] == 1
            assert rep["member"]["host"] == "solo"
            assert rep["coordinator"]["world"] == 1
            assert rep["coordinator"]["stale_hosts"] == []
        finally:
            m.close()
            coord.close()
        assert fabric.fleet_report() == {"armed": False}
