"""Tests for paddle.geometric, paddle.text, paddle.audio."""
import itertools

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import geometric, text, audio


def _t(a):
    return paddle.to_tensor(np.asarray(a))


# ------------------------------------------------------------- geometric

def test_segment_ops():
    data = np.array([[1., 2.], [3., 4.], [5., 6.], [7., 8.]], np.float32)
    ids = np.array([0, 0, 1, 2], np.int64)
    np.testing.assert_allclose(
        geometric.segment_sum(_t(data), _t(ids)).numpy(),
        [[4., 6.], [5., 6.], [7., 8.]])
    np.testing.assert_allclose(
        geometric.segment_mean(_t(data), _t(ids)).numpy(),
        [[2., 3.], [5., 6.], [7., 8.]])
    np.testing.assert_allclose(
        geometric.segment_max(_t(data), _t(ids)).numpy(),
        [[3., 4.], [5., 6.], [7., 8.]])
    np.testing.assert_allclose(
        geometric.segment_min(_t(data), _t(ids)).numpy(),
        [[1., 2.], [5., 6.], [7., 8.]])


def test_send_u_recv():
    x = np.array([[1.], [2.], [4.]], np.float32)
    src = np.array([0, 1, 2, 0], np.int64)
    dst = np.array([1, 2, 1, 0], np.int64)
    out = geometric.send_u_recv(_t(x), _t(src), _t(dst),
                                reduce_op="sum").numpy()
    np.testing.assert_allclose(out, [[1.], [5.], [2.]])
    out = geometric.send_u_recv(_t(x), _t(src), _t(dst),
                                reduce_op="max").numpy()
    np.testing.assert_allclose(out, [[1.], [4.], [2.]])


def test_send_ue_recv_send_uv():
    x = np.array([[1.], [2.]], np.float32)
    e = np.array([[10.], [20.]], np.float32)
    src = np.array([0, 1], np.int64)
    dst = np.array([1, 0], np.int64)
    out = geometric.send_ue_recv(_t(x), _t(e), _t(src), _t(dst),
                                 message_op="add", reduce_op="sum").numpy()
    np.testing.assert_allclose(out, [[22.], [11.]])
    out = geometric.send_uv(_t(x), _t(x), _t(src), _t(dst),
                            message_op="mul").numpy()
    np.testing.assert_allclose(out, [[2.], [2.]])


def test_segment_grad():
    x = paddle.to_tensor(np.array([[1.], [2.], [3.]], np.float32),
                         stop_gradient=False)
    out = geometric.segment_sum(x, _t(np.array([0, 0, 1], np.int64)))
    out.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1.], [1.], [1.]])


def test_sample_neighbors_reindex():
    # CSC graph: node0 <- {1,2}, node1 <- {2}, node2 <- {}
    row = _t(np.array([1, 2, 2], np.int64))
    colptr = _t(np.array([0, 2, 3, 3], np.int64))
    nodes = _t(np.array([0, 1], np.int64))
    nbrs, cnt = geometric.sample_neighbors(row, colptr, nodes)
    np.testing.assert_array_equal(cnt.numpy(), [2, 1])
    np.testing.assert_array_equal(np.sort(nbrs.numpy()[:2]), [1, 2])
    re_nbr, dst, out_nodes = geometric.reindex_graph(nodes, nbrs, cnt)
    assert out_nodes.numpy()[0] == 0 and out_nodes.numpy()[1] == 1
    assert re_nbr.shape[0] == 3


# ------------------------------------------------------------------ text

def _brute_viterbi(pot, trans, include_bos_eos):
    t, n = pot.shape
    best, path = -np.inf, None
    # reference convention: last tag is BOS/start, second-to-last is EOS/stop
    bos, eos = n - 1, n - 2
    for tags in itertools.product(range(n), repeat=t):
        s = pot[0, tags[0]] + (trans[bos, tags[0]] if include_bos_eos else 0)
        for i in range(1, t):
            s += trans[tags[i - 1], tags[i]] + pot[i, tags[i]]
        if include_bos_eos:
            s += trans[tags[-1], eos]
        if s > best:
            best, path = s, tags
    return best, np.array(path)


def test_viterbi_decode_matches_bruteforce():
    rng = np.random.default_rng(0)
    t, n = 4, 4
    pot = rng.random((2, t, n)).astype(np.float32)
    trans = rng.random((n, n)).astype(np.float32)
    lens = np.array([t, t], np.int64)
    scores, paths = text.viterbi_decode(_t(pot), _t(trans), _t(lens),
                                        include_bos_eos_tag=True)
    for b in range(2):
        bs, bp = _brute_viterbi(pot[b], trans, True)
        np.testing.assert_allclose(scores.numpy()[b], bs, rtol=1e-5)
        np.testing.assert_array_equal(paths.numpy()[b], bp)


def test_viterbi_decoder_layer_and_no_bos():
    rng = np.random.default_rng(1)
    pot = rng.random((1, 3, 3)).astype(np.float32)
    trans = rng.random((3, 3)).astype(np.float32)
    dec = text.ViterbiDecoder(_t(trans), include_bos_eos_tag=False)
    scores, paths = dec(_t(pot), _t(np.array([3], np.int64)))
    bs, bp = _brute_viterbi(pot[0], trans, False)
    np.testing.assert_allclose(scores.numpy()[0], bs, rtol=1e-5)
    np.testing.assert_array_equal(paths.numpy()[0], bp)


def test_text_datasets():
    for cls in (text.Imdb, text.Imikolov, text.Movielens, text.UCIHousing,
                text.WMT14, text.WMT16, text.Conll05st):
        ds = cls(mode="train")
        assert len(ds) > 0
        item = ds[0]
        assert isinstance(item, tuple)
    feats, price = text.UCIHousing(mode="test")[0]
    assert feats.shape == (13,) and price.shape == (1,)


# ----------------------------------------------------------------- audio

def test_mel_conversions():
    assert abs(audio.functional.hz_to_mel(1000.0, htk=True) - 999.99) < 0.1
    m = audio.functional.hz_to_mel(440.0)
    back = audio.functional.mel_to_hz(m)
    assert abs(back - 440.0) < 1e-3


def test_fbank_matrix():
    fb = audio.functional.compute_fbank_matrix(16000, 512, n_mels=40).numpy()
    assert fb.shape == (40, 257)
    assert (fb >= 0).all()
    assert fb.sum() > 0


def test_spectrogram_parseval():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, 2048)).astype(np.float32)
    spec = audio.Spectrogram(n_fft=256, hop_length=128, power=2.0)(_t(x))
    assert spec.numpy().shape[1] == 129  # 1 + n_fft//2
    assert np.isfinite(spec.numpy()).all() and spec.numpy().max() > 0
    # compare one frame against a straight numpy stft (center pad reflect)
    xp = np.pad(x[0], (128, 128), mode="reflect")
    frame0 = xp[:256] * np.hanning(257)[:-1]
    ref = np.abs(np.fft.rfft(frame0)) ** 2
    np.testing.assert_allclose(spec.numpy()[0, :, 0], ref, rtol=1e-3,
                               atol=1e-3)


def test_mel_and_mfcc_shapes():
    rng = np.random.default_rng(0)
    x = _t(rng.standard_normal((2, 4000)).astype(np.float32))
    mel = audio.MelSpectrogram(sr=16000, n_fft=512, n_mels=64)(x)
    assert mel.numpy().shape[:2] == (2, 64)
    logmel = audio.LogMelSpectrogram(sr=16000, n_fft=512, n_mels=64)(x)
    assert np.isfinite(logmel.numpy()).all()
    mfcc = audio.MFCC(sr=16000, n_mfcc=20, n_fft=512)(x)
    assert mfcc.numpy().shape[:2] == (2, 20)
