"""Tests for paddle.geometric, paddle.text, paddle.audio."""
import itertools

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import geometric, text, audio


def _t(a):
    return paddle.to_tensor(np.asarray(a))


# ------------------------------------------------------------- geometric

def test_segment_ops():
    data = np.array([[1., 2.], [3., 4.], [5., 6.], [7., 8.]], np.float32)
    ids = np.array([0, 0, 1, 2], np.int64)
    np.testing.assert_allclose(
        geometric.segment_sum(_t(data), _t(ids)).numpy(),
        [[4., 6.], [5., 6.], [7., 8.]])
    np.testing.assert_allclose(
        geometric.segment_mean(_t(data), _t(ids)).numpy(),
        [[2., 3.], [5., 6.], [7., 8.]])
    np.testing.assert_allclose(
        geometric.segment_max(_t(data), _t(ids)).numpy(),
        [[3., 4.], [5., 6.], [7., 8.]])
    np.testing.assert_allclose(
        geometric.segment_min(_t(data), _t(ids)).numpy(),
        [[1., 2.], [5., 6.], [7., 8.]])


def test_send_u_recv():
    x = np.array([[1.], [2.], [4.]], np.float32)
    src = np.array([0, 1, 2, 0], np.int64)
    dst = np.array([1, 2, 1, 0], np.int64)
    out = geometric.send_u_recv(_t(x), _t(src), _t(dst),
                                reduce_op="sum").numpy()
    np.testing.assert_allclose(out, [[1.], [5.], [2.]])
    out = geometric.send_u_recv(_t(x), _t(src), _t(dst),
                                reduce_op="max").numpy()
    np.testing.assert_allclose(out, [[1.], [4.], [2.]])


def test_send_ue_recv_send_uv():
    x = np.array([[1.], [2.]], np.float32)
    e = np.array([[10.], [20.]], np.float32)
    src = np.array([0, 1], np.int64)
    dst = np.array([1, 0], np.int64)
    out = geometric.send_ue_recv(_t(x), _t(e), _t(src), _t(dst),
                                 message_op="add", reduce_op="sum").numpy()
    np.testing.assert_allclose(out, [[22.], [11.]])
    out = geometric.send_uv(_t(x), _t(x), _t(src), _t(dst),
                            message_op="mul").numpy()
    np.testing.assert_allclose(out, [[2.], [2.]])


def test_segment_grad():
    x = paddle.to_tensor(np.array([[1.], [2.], [3.]], np.float32),
                         stop_gradient=False)
    out = geometric.segment_sum(x, _t(np.array([0, 0, 1], np.int64)))
    out.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1.], [1.], [1.]])


def test_sample_neighbors_reindex():
    # CSC graph: node0 <- {1,2}, node1 <- {2}, node2 <- {}
    row = _t(np.array([1, 2, 2], np.int64))
    colptr = _t(np.array([0, 2, 3, 3], np.int64))
    nodes = _t(np.array([0, 1], np.int64))
    nbrs, cnt = geometric.sample_neighbors(row, colptr, nodes)
    np.testing.assert_array_equal(cnt.numpy(), [2, 1])
    np.testing.assert_array_equal(np.sort(nbrs.numpy()[:2]), [1, 2])
    re_nbr, dst, out_nodes = geometric.reindex_graph(nodes, nbrs, cnt)
    assert out_nodes.numpy()[0] == 0 and out_nodes.numpy()[1] == 1
    assert re_nbr.shape[0] == 3


# ------------------------------------------------------------------ text

def _brute_viterbi(pot, trans, include_bos_eos):
    t, n = pot.shape
    best, path = -np.inf, None
    # reference convention: last tag is BOS/start, second-to-last is EOS/stop
    bos, eos = n - 1, n - 2
    for tags in itertools.product(range(n), repeat=t):
        s = pot[0, tags[0]] + (trans[bos, tags[0]] if include_bos_eos else 0)
        for i in range(1, t):
            s += trans[tags[i - 1], tags[i]] + pot[i, tags[i]]
        if include_bos_eos:
            s += trans[tags[-1], eos]
        if s > best:
            best, path = s, tags
    return best, np.array(path)


def test_viterbi_decode_matches_bruteforce():
    rng = np.random.default_rng(0)
    t, n = 4, 4
    pot = rng.random((2, t, n)).astype(np.float32)
    trans = rng.random((n, n)).astype(np.float32)
    lens = np.array([t, t], np.int64)
    scores, paths = text.viterbi_decode(_t(pot), _t(trans), _t(lens),
                                        include_bos_eos_tag=True)
    for b in range(2):
        bs, bp = _brute_viterbi(pot[b], trans, True)
        np.testing.assert_allclose(scores.numpy()[b], bs, rtol=1e-5)
        np.testing.assert_array_equal(paths.numpy()[b], bp)


def test_viterbi_decoder_layer_and_no_bos():
    rng = np.random.default_rng(1)
    pot = rng.random((1, 3, 3)).astype(np.float32)
    trans = rng.random((3, 3)).astype(np.float32)
    dec = text.ViterbiDecoder(_t(trans), include_bos_eos_tag=False)
    scores, paths = dec(_t(pot), _t(np.array([3], np.int64)))
    bs, bp = _brute_viterbi(pot[0], trans, False)
    np.testing.assert_allclose(scores.numpy()[0], bs, rtol=1e-5)
    np.testing.assert_array_equal(paths.numpy()[0], bp)


def test_text_datasets():
    for cls in (text.Imdb, text.Imikolov, text.Movielens, text.UCIHousing,
                text.WMT14, text.WMT16, text.Conll05st):
        ds = cls(mode="train")
        assert len(ds) > 0
        item = ds[0]
        assert isinstance(item, tuple)
    feats, price = text.UCIHousing(mode="test")[0]
    assert feats.shape == (13,) and price.shape == (1,)


# ----------------------------------------------------------------- audio

def test_mel_conversions():
    assert abs(audio.functional.hz_to_mel(1000.0, htk=True) - 999.99) < 0.1
    m = audio.functional.hz_to_mel(440.0)
    back = audio.functional.mel_to_hz(m)
    assert abs(back - 440.0) < 1e-3


def test_fbank_matrix():
    fb = audio.functional.compute_fbank_matrix(16000, 512, n_mels=40).numpy()
    assert fb.shape == (40, 257)
    assert (fb >= 0).all()
    assert fb.sum() > 0


def test_spectrogram_parseval():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, 2048)).astype(np.float32)
    spec = audio.Spectrogram(n_fft=256, hop_length=128, power=2.0)(_t(x))
    assert spec.numpy().shape[1] == 129  # 1 + n_fft//2
    assert np.isfinite(spec.numpy()).all() and spec.numpy().max() > 0
    # compare one frame against a straight numpy stft (center pad reflect)
    xp = np.pad(x[0], (128, 128), mode="reflect")
    frame0 = xp[:256] * np.hanning(257)[:-1]
    ref = np.abs(np.fft.rfft(frame0)) ** 2
    np.testing.assert_allclose(spec.numpy()[0, :, 0], ref, rtol=1e-3,
                               atol=1e-3)


def test_mel_and_mfcc_shapes():
    rng = np.random.default_rng(0)
    x = _t(rng.standard_normal((2, 4000)).astype(np.float32))
    mel = audio.MelSpectrogram(sr=16000, n_fft=512, n_mels=64)(x)
    assert mel.numpy().shape[:2] == (2, 64)
    logmel = audio.LogMelSpectrogram(sr=16000, n_fft=512, n_mels=64)(x)
    assert np.isfinite(logmel.numpy()).all()
    mfcc = audio.MFCC(sr=16000, n_mfcc=20, n_fft=512)(x)
    assert mfcc.numpy().shape[:2] == (2, 20)


class TestAudioBackendsDatasets:
    def test_wav_save_load_info_roundtrip(self, tmp_path):
        import paddle_tpu as paddle
        path = str(tmp_path / "t.wav")
        t = np.linspace(0, 1, 1600, dtype=np.float32)
        wav = paddle.to_tensor(np.stack([np.sin(2 * np.pi * 440 * t)]))
        paddle.audio.save(path, wav, 16000)
        meta = paddle.audio.info(path)
        assert meta.sample_rate == 16000 and meta.num_channels == 1
        assert meta.num_samples == 1600 and meta.bits_per_sample == 16
        back, sr = paddle.audio.load(path)
        assert sr == 16000 and back.shape == [1, 1600]
        np.testing.assert_allclose(np.asarray(back._value),
                                   np.asarray(wav._value), atol=1e-3)

    def test_load_offset_and_channels_last(self, tmp_path):
        import paddle_tpu as paddle
        path = str(tmp_path / "st.wav")
        wav = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(2, 800))
            .astype(np.float32) * 0.1)
        paddle.audio.save(path, wav, 8000)
        seg, sr = paddle.audio.load(path, frame_offset=100, num_frames=200,
                                    channels_first=False)
        assert seg.shape == [200, 2]

    def test_tess_esc50(self):
        import paddle_tpu as paddle
        ds = paddle.audio.datasets.TESS(mode="train")
        wav, lab = ds[0]
        assert wav.ndim == 1 and 0 <= int(lab) < 7
        ds2 = paddle.audio.datasets.ESC50(mode="test",
                                          feat_type="melspectrogram",
                                          n_fft=256)
        feat, lab2 = ds2[0]
        assert feat.ndim == 2 and 0 <= int(lab2) < 50

    def test_backend_registry(self):
        import paddle_tpu as paddle
        assert paddle.audio.backends.list_available_backends() == \
            ["wave_backend"]
        import pytest as _pytest
        with _pytest.raises(NotImplementedError):
            paddle.audio.backends.set_backend("soundfile")


class TestIncubateAutogradMatrix:
    def test_jacobian_matrix_view(self):
        import paddle_tpu as paddle
        from paddle_tpu.incubate.autograd import Jacobian
        x = paddle.to_tensor(np.asarray([1.0, 2.0, 3.0], np.float32))
        x.stop_gradient = False

        def f(v):
            return (v * v)

        J = Jacobian(f, x)
        assert J.shape == [3, 3]
        np.testing.assert_allclose(np.asarray(J[:, :]._value),
                                   np.diag([2.0, 4.0, 6.0]), rtol=1e-6)

    def test_hessian_matrix_view(self):
        import paddle_tpu as paddle
        from paddle_tpu.incubate.autograd import Hessian
        x = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
        x.stop_gradient = False

        def f(v):
            return (v * v).sum()

        H = Hessian(f, x)
        assert H.shape == [2, 2]
        np.testing.assert_allclose(np.asarray(H[:, :]._value),
                                   2 * np.eye(2), rtol=1e-6)

    def test_prim_toggles(self):
        from paddle_tpu.incubate import autograd as ia
        ia.enable_prim()
        assert ia.prim_enabled()
        ia.disable_prim()
        assert not ia.prim_enabled()


class TestFusedLinear:
    def test_matches_linear(self):
        import paddle_tpu as paddle
        from paddle_tpu.incubate.nn import FusedLinear
        paddle.seed(0)
        fl = FusedLinear(8, 4)
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(3, 8)).astype(np.float32))
        out = fl(x)
        ref = np.asarray(x._value) @ np.asarray(fl.weight._value) + \
            np.asarray(fl.bias._value)
        np.testing.assert_allclose(np.asarray(out._value), ref, rtol=1e-5)


class TestAutogradMatrixRegressions:
    def test_jacobian_multi_input_hstacks(self):
        import paddle_tpu as paddle
        from paddle_tpu.incubate.autograd import Jacobian
        x1 = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
        x2 = paddle.to_tensor(np.asarray([3.0], np.float32))
        x1.stop_gradient = False
        x2.stop_gradient = False

        def f(a, b):
            return a * a + b.sum()

        J = Jacobian(f, [x1, x2])
        assert J.shape == [2, 3]
        np.testing.assert_allclose(np.asarray(J[:, :]._value),
                                   [[2, 0, 1], [0, 4, 1]], rtol=1e-6)

    def test_hessian_multi_input_blocks(self):
        import paddle_tpu as paddle
        from paddle_tpu.incubate.autograd import Hessian
        x1 = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
        x2 = paddle.to_tensor(np.asarray([3.0], np.float32))
        x1.stop_gradient = False
        x2.stop_gradient = False

        def f(a, b):
            return (a * a).sum() + 3.0 * (b * b).sum()

        H = Hessian(f, [x1, x2])
        assert H.shape == [3, 3]
        np.testing.assert_allclose(np.asarray(H[:, :]._value),
                                   np.diag([2.0, 2.0, 6.0]), rtol=1e-6)

    def test_hessian_batched(self):
        import paddle_tpu as paddle
        from paddle_tpu.incubate.autograd import Hessian
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32))
        x.stop_gradient = False

        def f(v):
            return (v * v).sum()

        H = Hessian(f, x, is_batched=True)
        assert H.shape == [4, 3, 3]
        for b in range(4):
            np.testing.assert_allclose(np.asarray(H[b]._value),
                                       2 * np.eye(3), rtol=1e-6)

    def test_fused_linear_transpose_weight(self):
        import paddle_tpu as paddle
        from paddle_tpu.incubate.nn import FusedLinear
        paddle.seed(0)
        fl = FusedLinear(8, 4, transpose_weight=True)
        assert list(fl.weight.shape) == [4, 8]   # stored transposed
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(3, 8)).astype(np.float32))
        out = fl(x)
        ref = np.asarray(x._value) @ np.asarray(fl.weight._value).T + \
            np.asarray(fl.bias._value)
        np.testing.assert_allclose(np.asarray(out._value), ref, rtol=1e-5)


def test_multi_box_head_priors_align_with_heads():
    """locs/confs per-image count must equal the generated prior count,
    including ar=1.0 entries (review regression)."""
    import paddle_tpu as paddle
    import paddle_tpu.static.nn as snn
    paddle.seed(0)
    feats = [paddle.to_tensor(
        np.random.default_rng(0).normal(size=(1, 8, 4, 4))
        .astype(np.float32))]
    img = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
    locs, confs, boxes, vars_ = snn.multi_box_head(
        feats, img, base_size=64, num_classes=3,
        aspect_ratios=[[1.0, 2.0]], name="mbox_align")
    assert locs.shape[1] == boxes.shape[0]
    assert confs.shape[1] == boxes.shape[0]
