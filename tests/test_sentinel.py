"""Performance regression sentinel (PR 19, ROADMAP 7(b)).

Contracts pinned here:

  * `classify(record, bands)` names every violated band with a
    REASON_CODES verdict — goodput/throughput floors -> perf_drift,
    p50/p99 bands -> latency_drift, reason-histogram escapes and
    hang/skip storms -> split_regression, retrace/rebuild allowances ->
    compile_storm — sorted worst-first, and stays silent on partial or
    idle records (a band with no observation is not a violation);
  * `bands_from_record` derives the tolerance windows: goodput floor is
    half the observed fraction, latency/throughput scale with `slack`,
    the reason histogram is closed, decode/prefill rebuilds get NO
    headroom;
  * `PerfBaseline` keeps tools/perf_baselines.json honest: add requires
    a note, save/load round-trips, split() three-ways records into
    violations/passed/unbaselined, stale/expire retire dead legs, and
    the checked-in file actually covers the bench + perf_smoke legs;
  * `tools/perf_baseline.py --check` exits 0 on records inside their
    bands, 1 on a violating or unbaselined record (naming the finding),
    and --write-baseline seeds a loadable file;
  * the live watcher self-calibrates on its first active window, flags
    an injected stall storm as split_regression and a fresh engine's
    decode rebuild as compile_storm, recovers on the next clean window,
    and its disarmed tick is a no-op that never opens windows;
  * /sentinel serves the snapshot schema and /readyz folds the degraded
    latch in: 503 with the machine-readable finding attached while the
    latch is set, 200 again after recovery.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops import guardian
from paddle_tpu.profiler import metrics as pm
from paddle_tpu.profiler import sentinel as snt
from paddle_tpu.profiler import telemetry_server as ts
from paddle_tpu.profiler.events import clear_fusion_events
from paddle_tpu.profiler.sentinel import (PerfBaseline, bands_from_record,
                                          capture_record, classify)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CLI = os.path.join(_ROOT, "tools", "perf_baseline.py")

_DEFAULT_FLAGS = {
    "FLAGS_metrics": False,
    "FLAGS_check_numerics": False,
    "FLAGS_profiler_events": False,
    "FLAGS_serve_step_timeout_ms": 0,
    "FLAGS_telemetry_port": 0,
    "FLAGS_sentinel": False,
    "FLAGS_sentinel_leg": "",
    "FLAGS_sentinel_baseline": "",
    "FLAGS_sentinel_window_s": 10.0,
    "FLAGS_eager_op_cache": True,
    "FLAGS_eager_chain_fusion": True,
    "FLAGS_eager_chain_fusion_min_count": 3,
    "FLAGS_eager_step_fusion": True,
    "FLAGS_eager_step_fusion_min_count": 4,
}


@pytest.fixture(autouse=True)
def _fresh():
    snt.disarm()
    snt.SENTINEL.reset()
    set_flags(dict(_DEFAULT_FLAGS))
    ts.stop()
    ts._ENGINES.clear()
    ts._HEART.clear()
    pm.reset_metrics()
    clear_fusion_events()
    guardian.clear_faults()
    guardian.reset_thread_state()
    yield
    snt.disarm()
    snt.SENTINEL.reset()
    ts.stop()
    ts._ENGINES.clear()
    ts._HEART.clear()
    set_flags(dict(_DEFAULT_FLAGS))
    pm.reset_metrics()
    clear_fusion_events()
    guardian.clear_faults()
    guardian.reset_thread_state()


def _clean_record(**over):
    """A healthy fused-train leg record; tests perturb one axis each."""
    rec = {
        "version": 1, "leg": "unit", "kind": "train",
        "window_s": 2.0, "steps": 40, "serve_steps": 0,
        "goodput": 0.9,
        "buckets_s": {"productive": 1.8, "stalled": 0.2},
        "step_ms_p50": 5.0, "step_ms_p99": 9.0,
        "serve_ms_p50": 0.0, "serve_ms_p99": 0.0,
        "tokens_per_sec": 1000.0,
        "reasons": {"chain.split": {}, },
        "compiles": {"dispatch": 2, "chain": 1, "step": 1,
                     "decode": 0, "prefill": 0},
        "hangs": 0, "skips": 0,
    }
    rec["reasons"] = {"chain.split:shape_change": 3}
    rec.update(over)
    return rec


VERDICTS = ("perf_drift", "split_regression", "compile_storm",
            "latency_drift")


# ---------------------------------------------------------------------------
# classify: one verdict per band family
# ---------------------------------------------------------------------------

class TestClassify:
    def test_clean_record_has_no_findings(self):
        rec = _clean_record()
        assert classify(rec, bands_from_record(rec)) == []

    def test_goodput_drop_is_perf_drift(self):
        bands = bands_from_record(_clean_record())
        fs = classify(_clean_record(goodput=0.2), bands)
        assert fs and fs[0]["reason"] == "perf_drift"
        assert fs[0]["metric"] == "goodput"
        assert fs[0]["observed"] == 0.2
        assert fs[0]["bound"] == pytest.approx(0.45)

    def test_throughput_floor_is_perf_drift(self):
        bands = bands_from_record(_clean_record(), slack=2.0)
        fs = classify(_clean_record(tokens_per_sec=100.0), bands)
        assert [f["reason"] for f in fs] == ["perf_drift"]
        assert fs[0]["metric"] == "tokens_per_sec"

    def test_latency_band_is_latency_drift(self):
        bands = bands_from_record(_clean_record(), slack=2.0)
        fs = classify(_clean_record(step_ms_p99=50.0), bands)
        assert [f["reason"] for f in fs] == ["latency_drift"]
        assert fs[0]["metric"] == "step_ms_p99"

    def test_novel_reason_is_split_regression(self):
        bands = bands_from_record(_clean_record())
        bad = _clean_record(reasons={"chain.split:shape_change": 3,
                                     "step.deactivate:retrace_storm": 1})
        fs = classify(bad, bands)
        assert [f["reason"] for f in fs] == ["split_regression"]
        assert "outside the baseline histogram" in fs[0]["message"]

    def test_reason_storm_over_cap_is_split_regression(self):
        bands = bands_from_record(_clean_record())
        # cap is max(4n, 8) = 12 for the 3x baseline reason
        ok = classify(_clean_record(
            reasons={"chain.split:shape_change": 12}), bands)
        assert ok == []
        fs = classify(_clean_record(
            reasons={"chain.split:shape_change": 13}), bands)
        assert [f["reason"] for f in fs] == ["split_regression"]

    def test_hang_and_skip_storms_are_split_regression(self):
        bands = bands_from_record(_clean_record(hangs=1, skips=1))
        assert classify(_clean_record(hangs=2, skips=2), bands) == []
        fs = classify(_clean_record(hangs=3, skips=3), bands)
        assert {f["metric"] for f in fs} == {"hangs", "skips"}
        assert {f["reason"] for f in fs} == {"split_regression"}

    def test_decode_rebuild_is_compile_storm_with_no_headroom(self):
        bands = bands_from_record(_clean_record())
        bad = _clean_record(compiles={"dispatch": 2, "chain": 1,
                                      "step": 1, "decode": 1,
                                      "prefill": 0})
        fs = classify(bad, bands)
        assert [f["reason"] for f in fs] == ["compile_storm"]
        assert fs[0]["metric"] == "compiles.decode"
        assert fs[0]["bound"] == 0

    def test_severity_order_worst_first(self):
        bands = bands_from_record(_clean_record(), slack=2.0)
        bad = _clean_record(
            goodput=0.1, step_ms_p50=99.0,
            reasons={"serve.hang:watchdog": 5},
            compiles={"dispatch": 99, "chain": 1, "step": 1,
                      "decode": 3, "prefill": 0})
        order = [f["reason"] for f in classify(bad, bands)]
        assert order == sorted(
            order, key=("compile_storm", "split_regression",
                        "perf_drift", "latency_drift").index)
        assert order[0] == "compile_storm"

    def test_idle_record_never_drifts(self):
        bands = bands_from_record(_clean_record())
        idle = _clean_record(steps=0, serve_steps=0, goodput=0.0,
                             tokens_per_sec=0.0, buckets_s={},
                             reasons={}, compiles={})
        assert classify(idle, bands) == []

    def test_partial_record_is_band_neutral(self):
        bands = bands_from_record(_clean_record())
        assert classify({"leg": "unit", "steps": 1}, bands) == []

    def test_every_finding_reason_is_on_the_contract(self):
        from paddle_tpu.profiler.events import REASON_CODES
        assert set(VERDICTS) <= set(REASON_CODES)


class TestBands:
    def test_slack_scales_latency_and_throughput_only(self):
        rec = _clean_record()
        tight = bands_from_record(rec, slack=2.0)
        wide = bands_from_record(rec, slack=20.0)
        assert wide["step_ms_p99_max"] == 10 * tight["step_ms_p99_max"]
        assert wide["tokens_per_sec_min"] == pytest.approx(
            tight["tokens_per_sec_min"] / 10)
        # structural bands are slack-independent
        assert wide["goodput_min"] == tight["goodput_min"] == 0.45
        assert wide["max_compiles"] == tight["max_compiles"]
        assert wide["allowed_reasons"] == tight["allowed_reasons"]

    def test_decode_prefill_get_no_headroom(self):
        mc = bands_from_record(_clean_record())["max_compiles"]
        assert mc["decode"] == 0 and mc["prefill"] == 0
        assert mc["dispatch"] == 4      # 2 + max(2, 2)

    def test_zero_latency_axes_are_unbanded(self):
        bands = bands_from_record(_clean_record())
        assert "serve_ms_p50_max" not in bands
        assert "serve_ms_p99_max" not in bands


# ---------------------------------------------------------------------------
# the checked-in baseline
# ---------------------------------------------------------------------------

class TestPerfBaseline:
    def test_add_save_load_match_round_trip(self, tmp_path):
        path = str(tmp_path / "pb.json")
        bl = PerfBaseline(policy="unit policy")
        entry = bl.add(_clean_record(), note="unit seed", slack=5.0)
        bl.save(path)
        re = PerfBaseline.load(path)
        assert re.policy == "unit policy"
        assert re.match("unit") == entry
        assert re.match("unit")["note"] == "unit seed"
        assert re.match("missing") is None

    def test_add_requires_a_note(self):
        with pytest.raises(ValueError, match="needs a note"):
            PerfBaseline().add(_clean_record(), note="")

    def test_readd_keeps_old_note_when_blank(self):
        bl = PerfBaseline()
        bl.add(_clean_record(), note="first")
        bl.add(_clean_record(step_ms_p50=4.0), note=None)
        assert bl.match("unit")["note"] == "first"
        assert bl.match("unit")["captured"]["step_ms_p50"] == 4.0

    def test_split_three_ways(self):
        bl = PerfBaseline()
        bl.add(_clean_record(), note="n")
        good = _clean_record()
        bad = _clean_record(goodput=0.1)
        unk = _clean_record(leg="other")
        viol, passed, unb = bl.split([good, bad, unk])
        assert passed == [good] and unb == [unk]
        assert len(viol) == 1 and viol[0][0] is bad
        assert viol[0][1][0]["reason"] == "perf_drift"

    def test_stale_and_expire(self):
        bl = PerfBaseline()
        bl.add(_clean_record(), note="n")
        bl.add(_clean_record(leg="dead"), note="n")
        assert bl.stale([_clean_record()]) == ["dead"]
        assert bl.expire([_clean_record()]) == ["dead"]
        assert sorted(bl.legs) == ["unit"]

    def test_version_skew_is_an_error(self, tmp_path):
        path = tmp_path / "pb.json"
        path.write_text('{"version": 99, "legs": {}}')
        with pytest.raises(ValueError, match="version"):
            PerfBaseline.load(str(path))

    def test_checked_in_baseline_covers_the_legs(self):
        bl = PerfBaseline.load()
        assert bl.policy, "checked-in baseline needs a policy line"
        need = {"perf_smoke", "gpt2_train", "accum4", "dp8", "pp2",
                "moe8", "serve_1", "serve_8", "serve_64",
                "serve_8_prefix", "serve_8_sampled"}
        missing = need - set(bl.legs)
        assert not missing, f"unbaselined legs: {sorted(missing)}"
        for leg, entry in bl.legs.items():
            assert entry["note"], f"{leg} entry has no note"
            assert "bands" in entry and "captured" in entry


# ---------------------------------------------------------------------------
# the CLI gate
# ---------------------------------------------------------------------------

def _cli(args, **kw):
    return subprocess.run(
        [sys.executable, _CLI] + args, capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), **kw)


class TestPerfBaselineCLI:
    def _seed(self, tmp_path):
        recfile = tmp_path / "rec.json"
        recfile.write_text(json.dumps(
            {"extra": {"sentinel_record": _clean_record()}}))
        blfile = tmp_path / "pb.json"
        w = _cli(["--write-baseline", str(recfile), "--baseline",
                  str(blfile), "--note", "unit seed", "--slack", "5"])
        assert w.returncode == 0, w.stderr + w.stdout
        return recfile, blfile

    def test_write_then_check_passes(self, tmp_path):
        recfile, blfile = self._seed(tmp_path)
        assert os.path.exists(blfile)
        r = _cli(["--check", str(recfile), "--baseline", str(blfile)])
        assert r.returncode == 0, r.stdout + r.stderr
        assert "0 violating" in r.stdout and "1 clean" in r.stdout

    def test_violating_record_exits_1_and_names_the_finding(
            self, tmp_path):
        _, blfile = self._seed(tmp_path)
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(_clean_record(goodput=0.1)))
        r = _cli(["--check", str(bad), "--baseline", str(blfile)])
        assert r.returncode == 1
        assert "perf_drift" in r.stdout
        assert "goodput" in r.stdout

    def test_unbaselined_record_exits_1(self, tmp_path):
        _, blfile = self._seed(tmp_path)
        unk = tmp_path / "unk.json"
        unk.write_text(json.dumps(_clean_record(leg="mystery")))
        r = _cli(["--check", str(unk), "--baseline", str(blfile)])
        assert r.returncode == 1
        assert "mystery" in r.stdout

    def test_garbage_input_exits_2(self, tmp_path):
        bad = tmp_path / "garbage.json"
        bad.write_text("not json at all {")
        r = _cli(["--check", str(bad)])
        assert r.returncode == 2

    def test_checked_in_tree_is_clean_against_itself(self, tmp_path):
        """The acceptance gate: a record rebuilt from every checked-in
        entry's captured shape must pass --check against the file."""
        bl = PerfBaseline.load()
        recs = []
        for leg, entry in bl.legs.items():
            rec = dict(entry["captured"])
            rec.update(leg=leg, kind=entry.get("kind") or "train",
                       version=1)
            rec.setdefault("buckets_s",
                           {"productive": rec.get("window_s") or 1.0})
            recs.append(rec)
        f = tmp_path / "tree.json"
        f.write_text(json.dumps(recs))
        r = _cli(["--check", str(f)])
        assert r.returncode == 0, r.stdout + r.stderr


# ---------------------------------------------------------------------------
# the live watcher
# ---------------------------------------------------------------------------

VOCAB = 128


@pytest.fixture(scope="module")
def smodel():
    from paddle_tpu.incubate.models import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32,
                    num_hidden_layers=2, num_attention_heads=4,
                    intermediate_size=64, max_position_embeddings=64,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _prompts(n, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, int(k)).tolist()
            for k in rng.integers(3, 16, n)]


def _train_steps(steps, d=32):
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((16, d)).astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((d, d)).astype(np.float32),
                         stop_gradient=False)
    b = paddle.to_tensor(rng.standard_normal(d).astype(np.float32),
                         stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=1e-3, parameters=[w, b])
    for _ in range(steps):
        y = F.gelu(paddle.add(paddle.matmul(x, w), b))
        loss = y.sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    w._value.block_until_ready()


def _serve_round(engine, n=4, tokens=4):
    """Fixed prompt lengths -> fixed padded prefill shapes: after one
    warm round every compile is paid, so armed windows are compile-free
    unless a test deliberately breaks that."""
    rng = np.random.default_rng(7)
    for k in (4, 7, 10, 14)[:n]:
        engine.add_request(rng.integers(0, VOCAB, k).tolist(),
                           max_new_tokens=tokens)
    engine.run()


def _run_windows(drive, want, timeout=30.0):
    """Drive workload until the sentinel has evaluated >= want
    windows."""
    t0 = time.monotonic()
    while snt.SENTINEL.windows < want:
        drive()
        if time.monotonic() - t0 > timeout:
            raise AssertionError(
                f"sentinel stuck at {snt.SENTINEL.windows} windows "
                f"(wanted {want}): {snt.SENTINEL.snapshot()['checks']}")


def _drive_until_clean(drive, timeout=30.0):
    """Drive clean workload until the watcher has calibrated AND judged
    at least one window clean with the latch down. A single jittery CI
    window can genuinely read latency_drift on the tight 4x
    self-calibration bands — the contract under test is that clean
    traffic always RETURNS to clean, not that noise never fires."""
    t0 = time.monotonic()
    while True:
        s = snt.SENTINEL
        if s.windows >= 2 and not s.degraded \
                and s.checks.get("clean", 0) >= 1:
            return
        drive()
        if time.monotonic() - t0 > timeout:
            raise AssertionError(
                f"no clean settled window in {timeout}s: "
                f"{snt.SENTINEL.snapshot()['checks']}")


class TestLiveWatcher:
    def test_disarmed_tick_is_inert(self):
        for _ in range(1000):
            snt.tick()
        s = snt.SENTINEL.snapshot()
        assert s["windows"] == 0 and not s["armed"]

    def test_self_calibration_then_clean(self):
        # pay compiles + the whole-step promotion retrace BEFORE arming:
        # a trace spike inside an armed window is a REAL latency_drift
        _train_steps(8)
        snt.arm(window_s=0.15)
        try:
            _drive_until_clean(lambda: _train_steps(3))
            s = snt.SENTINEL.snapshot()
            assert s["band_source"] == "self"
            assert s["checks"].get("calibrate") == 1
            assert s["checks"].get("clean", 0) >= 1
            assert not s["degraded"]
            assert s["last_record"]["kind"] == "train"
        finally:
            snt.disarm()

    def test_arm_restores_borrowed_flags_on_disarm(self):
        from paddle_tpu.framework.flags import _FLAGS
        assert not _FLAGS.get("FLAGS_metrics")
        snt.arm(window_s=5.0)
        assert _FLAGS.get("FLAGS_metrics")
        assert _FLAGS.get("FLAGS_profiler_events")
        snt.disarm()
        assert not _FLAGS.get("FLAGS_metrics")
        assert not _FLAGS.get("FLAGS_profiler_events")

    def test_arm_with_unknown_leg_refuses(self):
        with pytest.raises(ValueError, match="no baseline entry"):
            snt.arm(leg="never_a_leg")

    def test_stall_storm_flips_split_regression_then_recovers(
            self, smodel):
        from paddle_tpu.serving import LLMEngine
        engine = LLMEngine(smodel, max_batch_size=4, block_size=4)
        _serve_round(engine)        # decode compiled before calibration
        snt.arm(window_s=0.15)
        try:
            _drive_until_clean(lambda: _serve_round(engine))
            assert snt.SENTINEL.band_source == "self"
            assert not snt.SENTINEL.degraded
            # arm the watchdog only for the storm: on a loaded CPU a
            # GENUINE >budget step during calibration would seed
            # serve.hang into the allowed histogram
            set_flags({"FLAGS_serve_step_timeout_ms": 60})
            # one stall per round: each watchdog firing emits a
            # serve.hang reason without the two-consecutive-hang decode
            # rebuild (that escalation is the compile_storm test)
            deadline = time.monotonic() + 30
            while not snt.SENTINEL.degraded:
                guardian.inject_fault("stall", op="serve.decode",
                                      times=1)
                _serve_round(engine)
                assert time.monotonic() < deadline, \
                    "stall storm never tripped the sentinel"
            f = snt.SENTINEL.finding
            assert f["reason"] == "split_regression"
            # the storm attributes through a hang-family signal: the
            # serve.hang/serve.degrade reason histogram or the raw
            # hang counter, whichever band trips first
            assert ("hang" in f["metric"]
                    or f["metric"].startswith("serve.")), f
            assert {"observed", "bound", "window", "leg"} <= set(f)
            # recovery: clean windows clear the latch (watchdog off
            # again so jitter hangs can't re-trip it)
            guardian.clear_faults()
            set_flags({"FLAGS_serve_step_timeout_ms": 0})
            deadline = time.monotonic() + 30
            while snt.SENTINEL.degraded:
                _serve_round(engine)
                assert time.monotonic() < deadline, \
                    "sentinel never recovered after the fault cleared"
            assert snt.SENTINEL.finding is not None   # postmortem stays
            assert snt.SENTINEL.snapshot()["finding"] is None
        finally:
            guardian.clear_faults()
            snt.disarm()

    def test_decode_rebuild_flips_compile_storm(self, smodel):
        from paddle_tpu.serving import LLMEngine
        engine = LLMEngine(smodel, max_batch_size=4, block_size=4)
        _serve_round(engine)
        snt.arm(window_s=0.15)
        try:
            _drive_until_clean(lambda: _serve_round(engine))
            assert not snt.SENTINEL.degraded
            # a brand-new engine re-traces decode: zero-headroom band
            engine2 = LLMEngine(smodel, max_batch_size=2, block_size=4)
            deadline = time.monotonic() + 30
            while not snt.SENTINEL.degraded:
                _serve_round(engine2, n=2)
                assert time.monotonic() < deadline, \
                    "decode rebuild never tripped the sentinel"
            f = snt.SENTINEL.finding
            assert f["reason"] == "compile_storm"
            assert f["metric"].startswith("compiles.")
        finally:
            snt.disarm()

    def test_capture_record_shape(self):
        set_flags({"FLAGS_metrics": True, "FLAGS_profiler_events": True})
        _train_steps(5)
        rec = capture_record("unit_leg")
        assert rec["leg"] == "unit_leg" and rec["kind"] == "train"
        assert rec["steps"] >= 5 and rec["version"] == 1
        assert set(rec) >= {"goodput", "buckets_s", "reasons",
                            "compiles", "hangs", "skips",
                            "step_ms_p50", "step_ms_p99",
                            "tokens_per_sec", "window_s"}
        assert json.loads(json.dumps(rec)) == rec    # JSON-able


# ---------------------------------------------------------------------------
# the HTTP surface: /sentinel + the /readyz fold
# ---------------------------------------------------------------------------

class TestHTTPSurface:
    def test_sentinel_endpoint_schema(self):
        srv = ts.start(port=0)
        _train_steps(8)             # promotion retrace paid pre-arm
        snt.arm(window_s=0.15)
        try:
            _drive_until_clean(lambda: _train_steps(3))
            st, body = ts.probe_endpoint(f"{srv.url}/sentinel")
            assert st == 200
            assert set(body) == {
                "armed", "leg", "band_source", "window_s", "windows",
                "checks", "degraded", "finding", "findings",
                "last_record", "bands", "history"}
            assert body["armed"] is True
            assert body["band_source"] == "self"
            assert body["windows"] >= 2
            assert body["degraded"] is False and body["finding"] is None
            assert body["last_record"]["leg"] == "live"
            assert isinstance(body["history"], list)
        finally:
            snt.disarm()

    def test_endpoint_index_lists_sentinel(self):
        srv = ts.start(port=0)
        st, body = ts.probe_endpoint(f"{srv.url}/")
        assert st == 200 and "/sentinel" in body["endpoints"]

    def test_readyz_folds_the_degraded_latch(self, smodel):
        from paddle_tpu.serving import LLMEngine
        srv = ts.start(port=0)
        engine = LLMEngine(smodel, max_batch_size=4, block_size=4)
        _serve_round(engine)
        snt.arm(window_s=0.15)
        try:
            _drive_until_clean(lambda: _serve_round(engine))
            st, body = ts.probe_endpoint(f"{srv.url}/readyz")
            assert st == 200
            assert body["sentinel"]["armed"] is True
            assert body["sentinel"]["degraded"] is False
            set_flags({"FLAGS_serve_step_timeout_ms": 60})
            guardian.inject_fault("stall", op="serve.decode", times=2)
            deadline = time.monotonic() + 30
            while not snt.SENTINEL.degraded:
                _serve_round(engine)
                assert time.monotonic() < deadline
            st, body = ts.probe_endpoint(f"{srv.url}/readyz")
            assert st == 503
            f = body["sentinel"]["finding"]
            assert f and f["reason"] in VERDICTS
            assert {"metric", "observed", "bound"} <= set(f)
            # recovery: fault cleared -> clean window -> 200 again
            guardian.clear_faults()
            set_flags({"FLAGS_serve_step_timeout_ms": 0})
            deadline = time.monotonic() + 30
            ready = False
            while time.monotonic() < deadline and not ready:
                _serve_round(engine)
                st, body = ts.probe_endpoint(f"{srv.url}/readyz")
                ready = (st == 200
                         and body["sentinel"]["degraded"] is False)
            assert ready, "readyz never recovered after the fault"
        finally:
            guardian.clear_faults()
            snt.disarm()

    def test_disarmed_sentinel_never_degrades_readyz(self):
        srv = ts.start(port=0)
        st, body = ts.probe_endpoint(f"{srv.url}/readyz")
        assert st == 200
        assert body["sentinel"] == {"armed": False, "degraded": False,
                                    "finding": None}

    def test_sentinel_metrics_in_exposition(self):
        srv = ts.start(port=0)
        _train_steps(8)             # promotion retrace paid pre-arm
        snt.arm(window_s=0.15)
        try:
            _drive_until_clean(lambda: _train_steps(3))
            assert not snt.SENTINEL.degraded
            st, text = ts.probe_endpoint(f"{srv.url}/metrics")
            assert st == 200
            assert "sentinel_checks_total" in text
            assert 'verdict="calibrate"' in text
            assert "sentinel_degraded 0" in text
        finally:
            snt.disarm()


# ---------------------------------------------------------------------------
# flag arming
# ---------------------------------------------------------------------------

class TestFlagArming:
    def test_maybe_arm_from_flags(self):
        assert snt.maybe_arm_from_flags() is False
        set_flags({"FLAGS_sentinel": True,
                   "FLAGS_sentinel_window_s": 0.5})
        try:
            assert snt.maybe_arm_from_flags() is True
            assert snt.SENTINEL.armed
            assert snt.SENTINEL.window_s == 0.5
            # idempotent: a second call does not re-arm/reset
            snt.SENTINEL.windows = 7
            assert snt.maybe_arm_from_flags() is True
            assert snt.SENTINEL.windows == 7
        finally:
            snt.disarm()
