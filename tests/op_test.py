"""OpTest harness: forward vs NumPy reference + numeric gradient checks.

Methodology port (not code port) of the reference's OpTest base class
(python/paddle/fluid/tests/unittests/op_test.py:333): declare inputs and a
NumPy reference, check forward outputs, and check analytic gradients against
central finite differences.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.framework.core import Tensor


def check_forward(op_fn, np_fn, inputs, atol=1e-5, rtol=1e-5, **op_kwargs):
    """inputs: list of np arrays. Compares op_fn(*tensors) to np_fn(*arrays)."""
    tensors = [paddle.to_tensor(a) for a in inputs]
    out = op_fn(*tensors, **op_kwargs)
    expected = np_fn(*inputs)
    if isinstance(out, (list, tuple)):
        for o, e in zip(out, expected):
            np.testing.assert_allclose(o.numpy(), e, atol=atol, rtol=rtol)
    else:
        np.testing.assert_allclose(np.asarray(out.numpy(), np.float64)
                                   if np.asarray(expected).dtype == np.float64
                                   else out.numpy(),
                                   expected, atol=atol, rtol=rtol)
    return out


def numeric_grad(op_fn, inputs, wrt_index, delta=1e-3, **op_kwargs):
    """Central finite difference of sum(op_fn(inputs)) w.r.t. inputs[wrt]."""
    base = [np.array(a, np.float64) for a in inputs]

    def eval_sum(arrs):
        ts = [paddle.to_tensor(a.astype(np.float32)) for a in arrs]
        out = op_fn(*ts, **op_kwargs)
        if isinstance(out, (list, tuple)):
            return sum(float(np.sum(o.numpy(), dtype=np.float64)) for o in out)
        return float(np.sum(out.numpy(), dtype=np.float64))

    x = base[wrt_index]
    grad = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        orig = x[idx]
        x[idx] = orig + delta
        plus = eval_sum(base)
        x[idx] = orig - delta
        minus = eval_sum(base)
        x[idx] = orig
        grad[idx] = (plus - minus) / (2 * delta)
        it.iternext()
    return grad


def check_grad(op_fn, inputs, wrt=None, atol=5e-3, rtol=5e-3, delta=1e-3,
               **op_kwargs):
    """Compare tape gradients against finite differences (sum-of-outputs loss)."""
    wrt = wrt if wrt is not None else list(range(len(inputs)))
    tensors = [paddle.to_tensor(np.asarray(a, np.float32),
                                stop_gradient=False) for a in inputs]
    out = op_fn(*tensors, **op_kwargs)
    if isinstance(out, (list, tuple)):
        loss = out[0].sum()
        for o in out[1:]:
            loss = loss + o.sum()
    else:
        loss = out.sum()
    loss.backward()
    for i in wrt:
        assert tensors[i].grad is not None, f"no grad for input {i}"
        ng = numeric_grad(op_fn, [np.asarray(a, np.float64) for a in inputs],
                          i, delta=delta, **op_kwargs)
        np.testing.assert_allclose(tensors[i].grad.numpy(), ng,
                                   atol=atol, rtol=rtol,
                                   err_msg=f"analytic vs numeric grad "
                                           f"mismatch for input {i}")
