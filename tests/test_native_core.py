"""Native runtime core: TCPStore, BoundedQueue, ThreadPool, host tracer.

Reference analogs: store/tcp_store.h TCPStore tests, workqueue tests
(new_executor/workqueue/workqueue_test.cc), host_event_recorder. Multi-process
store rendezvous follows the TestDistBase pattern (test_dist_base.py:901):
subprocess ranks on localhost.
"""
import queue
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.core import (TCPStore, ThreadPool, BoundedQueue,
                             native_available, host_tracer, parallel_collate)

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native core not built (no g++)")


def test_store_set_get_add():
    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=1)
    client = TCPStore("127.0.0.1", master.port, is_master=False, world_size=1)
    master.set("alpha", b"value-1")
    assert client.get("alpha") == b"value-1"
    assert client.add("counter", 3) == 3
    assert master.add("counter", 4) == 7
    with pytest.raises(KeyError):
        client.get("missing", wait=False)
    assert client.delete_key("alpha")


def test_store_wait_blocks_until_set():
    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=1)
    client = TCPStore("127.0.0.1", master.port, is_master=False, world_size=1)

    def later():
        time.sleep(0.15)
        master.set("slow", b"done")
    t = threading.Thread(target=later)
    t.start()
    t0 = time.monotonic()
    client.wait(["slow"])
    assert time.monotonic() - t0 >= 0.1
    t.join()
    with pytest.raises(TimeoutError):
        client.wait(["never"], timeout=0.1)


def test_store_barrier_two_parties():
    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=2)
    client = TCPStore("127.0.0.1", master.port, is_master=False, world_size=2)
    order = []

    def party(store, name):
        store.barrier("sync")
        order.append(name)

    t = threading.Thread(target=party, args=(client, "client"))
    t.start()
    time.sleep(0.05)
    assert not order          # client must be blocked until master arrives
    party(master, "master")
    t.join()
    assert sorted(order) == ["client", "master"]


_WORKER = r"""
import importlib.util
import os
import sys

# load paddle_tpu.core standalone (skip the full framework import: jax
# bring-up per subprocess would dominate the test)
core_dir = os.path.join(sys.argv[4], "paddle_tpu", "core")
spec = importlib.util.spec_from_file_location(
    "ptcore", os.path.join(core_dir, "__init__.py"),
    submodule_search_locations=[core_dir])
ptcore = importlib.util.module_from_spec(spec)
sys.modules["ptcore"] = ptcore
spec.loader.exec_module(ptcore)
TCPStore = ptcore.TCPStore
rank, world, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
store = TCPStore("127.0.0.1", port, is_master=False, world_size=world)
store.set(f"rank/{rank}", str(rank).encode())
store.wait([f"rank/{r}" for r in range(world)])
vals = sorted(int(store.get(f"rank/{r}")) for r in range(world))
assert vals == list(range(world)), vals
store.barrier("exit")
print("RANK_OK", rank)
"""


def test_store_multiprocess_rendezvous(tmp_path):
    """Three subprocess ranks rendezvous through one master store."""
    world = 3
    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=world + 1)
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    import os
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), str(world), str(master.port),
         repo_root],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(world)]
    master.barrier("exit")
    for r, p in enumerate(procs):
        out, _ = p.communicate(timeout=60)
        assert p.returncode == 0, out
        assert f"RANK_OK {r}" in out


def test_bounded_queue_blocking_and_close():
    q = BoundedQueue(2)
    assert q.is_native
    q.push("a")
    q.push("b")
    with pytest.raises(queue.Full):
        q.push("c", timeout=0.05)
    assert q.pop() == "a"
    assert q.pop() == "b"
    with pytest.raises(queue.Empty):
        q.pop(timeout=0.05)
    q.push("tail")
    q.close()
    assert q.pop() == "tail"       # close drains remaining items first
    with pytest.raises(StopIteration):
        q.pop()


def test_bounded_queue_producer_consumer():
    q = BoundedQueue(4)
    n = 200
    got = []

    def producer():
        for i in range(n):
            q.push(i)
        q.close()

    t = threading.Thread(target=producer)
    t.start()
    while True:
        try:
            got.append(q.pop())
        except StopIteration:
            break
    t.join()
    assert got == list(range(n))


def test_parallel_collate_matches_stack():
    arrays = [np.random.default_rng(i).standard_normal(
        (128, 512)).astype("float32") for i in range(16)]
    np.testing.assert_array_equal(parallel_collate(arrays), np.stack(arrays))
    small = [np.arange(4, dtype=np.int32) + i for i in range(3)]
    np.testing.assert_array_equal(parallel_collate(small), np.stack(small))


def test_host_tracer_spans_roundtrip():
    host_tracer.enable(True)
    try:
        t0 = host_tracer.now_ns()
        t1 = host_tracer.now_ns()
        host_tracer.span("unit_event", t0, t1)
        events = host_tracer.harvest()
    finally:
        host_tracer.enable(False)
    names = [e[0] for e in events]
    assert "unit_event" in names
    ev = events[names.index("unit_event")]
    assert ev[2] >= ev[1]


def test_profiler_uses_native_tracer():
    import paddle_tpu.profiler as profiler
    prof = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU])
    prof.start()
    with profiler.RecordEvent("traced_region"):
        time.sleep(0.01)
    prof.stop()
    names = [e["name"] for e in prof._events]
    assert "traced_region" in names


def test_host_tracer_worker_thread_events_visible():
    """Events recorded on other live threads must appear in harvest
    (reference: host_event_recorder harvests all thread buffers)."""
    host_tracer.enable(True)
    try:
        def worker():
            t0 = host_tracer.now_ns()
            host_tracer.span("worker_span", t0, host_tracer.now_ns())
        t = threading.Thread(target=worker)
        t.start()
        t.join()
        # also record from a thread that stays alive during harvest
        alive_done = threading.Event()
        release = threading.Event()

        def long_lived():
            t0 = host_tracer.now_ns()
            host_tracer.span("live_span", t0, host_tracer.now_ns())
            alive_done.set()
            release.wait(5)
        t2 = threading.Thread(target=long_lived)
        t2.start()
        alive_done.wait(5)
        names = [e[0] for e in host_tracer.harvest()]
        release.set()
        t2.join()
    finally:
        host_tracer.enable(False)
    assert "worker_span" in names
    assert "live_span" in names


def test_dataloader_early_abandon_no_crash():
    """Breaking out of a DataLoader loop with a full prefetch queue must not
    crash when the iterator is dropped (producer joined before queue free)."""
    import gc
    from paddle_tpu.io import DataLoader, Dataset

    class Big(Dataset):
        def __len__(self):
            return 64

        def __getitem__(self, i):
            return np.zeros((64, 64), np.float32)

    for _ in range(5):
        it = iter(DataLoader(Big(), batch_size=4))
        next(it)
        del it          # abandon with producer likely blocked on full queue
        gc.collect()
