"""PS-lite tests: one server + one trainer process over rpc; the trainer
learns a sparse embedding + dense weight living on the server (the reference's
TestDistBase PS pattern, SURVEY.md §4)."""
import os
import socket
import subprocess
import sys

_WORKER = r"""
import sys
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
from paddle_tpu.distributed import rpc
from paddle_tpu.distributed.ps import PSClient, PSServer

rank = int(sys.argv[1]); port = sys.argv[2]
name = "ps0" if rank == 0 else f"trainer{rank}"
rpc.init_rpc(name, rank=rank, world_size=2,
             master_endpoint=f"127.0.0.1:{port}")

if rank == 0:
    PSServer()           # tables live here; handlers run in rpc threads
else:
    client = PSClient("ps0")
    client.create_sparse_table("emb", dim=4, initializer="zeros")
    client.create_dense_table("w", shape=[4], initializer="zeros")

    # dense push/pull arithmetic: w = 0 - 0.1 * (-1) = 0.1 per dim
    client.push_dense("w", -np.ones(4, np.float32), lr=0.1)
    w = client.pull_dense("w").numpy()
    assert np.allclose(w, 0.1), w

    # learn emb rows (fixed w): linear regression, converges geometrically
    ids = np.array([3, 7, 3], np.int64)          # duplicate id: grads sum
    emb = client.pull_sparse("emb", ids).numpy()
    assert emb.shape == (3, 4) and (emb == 0).all()
    label = np.array([1.0, -1.0, 1.0], np.float32)
    for step in range(80):
        e = client.pull_sparse("emb", ids).numpy()    # [3, 4]
        err = e @ w - label
        ge = np.outer(err, w)
        client.push_sparse("emb", ids, ge, lr=5.0)

    e = client.pull_sparse("emb", ids).numpy()
    loss = ((e @ w - label) ** 2).mean()
    assert loss < 1e-3, loss
    assert client.table_size("emb") == 2   # only ids 3 and 7 materialized

    # CTR accessor over rpc: stats accumulate server-side, shrink evicts
    # by decayed score (reference: ps/table/ctr_accessor.cc)
    client.create_ctr_table("ctr", dim=2, show_decay_rate=0.98)
    client.pull_ctr("ctr", np.array([1, 2], np.int64),
                    shows=[5.0, 5.0], clicks=[5.0, 0.0])
    ev = client.shrink("ctr", threshold=0.5)
    assert ev == 1, ev                     # the click-less row goes
    print("PS_OK", loss)

rpc.shutdown()
"""


def test_ps_server_trainer(tmp_path):
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    script = tmp_path / "ps_worker.py"
    script.write_text(_WORKER)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo_root)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for r in range(2)]
    outs = [p.communicate(timeout=180)[0].decode() for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    assert "PS_OK" in outs[1]


def test_ctr_table_shrink():
    """CTR accessor semantics: show/click scoring + score-based eviction
    (reference: ps/table/ctr_accessor.cc shrink)."""
    import numpy as np
    from paddle_tpu.distributed.ps import CTRSparseTable
    t = CTRSparseTable("ctr", dim=4, show_decay_rate=0.5)
    # id 1: shown and clicked (high score); id 2: shown never clicked (low)
    t.pull(np.array([1, 2]), shows=[10.0, 10.0], clicks=[5.0, 0.0])
    assert t.score(1) > t.score(2) > 0
    # threshold between the two scores evicts only the click-less row
    evicted = t.shrink(threshold=(t.score(1) + t.score(2)) / 4)
    assert evicted == 1 and 1 in t.rows and 2 not in t.rows
    # repeated shrink decays the survivor's stats until it too goes
    for _ in range(20):
        t.shrink(threshold=0.5)
    assert len(t.rows) == 0


def test_async_communicator_merges_and_sends():
    """AsyncCommunicator queues pushes, merges per table, sends in the
    background (reference: communicator.h AsyncCommunicator)."""
    import numpy as np
    import paddle_tpu.distributed.ps as ps

    ps._TABLES.clear()
    client = ps.LocalPSClient()
    client.create_dense_table("w", shape=[4], initializer="zeros")
    client.create_sparse_table("emb", dim=2, initializer="zeros")
    comm = ps.AsyncCommunicator(client, send_interval=0.01,
                                batches_per_send=100).start()
    # 3 dense pushes of -1 each merge into one push of -3: w = 0.1 * 3
    for _ in range(3):
        comm.push_dense_async("w", -np.ones(4, np.float32), lr=0.1)
    # sparse: id 5 pushed twice accumulates, id 9 once
    comm.push_sparse_async("emb", np.array([5], np.int64),
                           -np.ones((1, 2), np.float32), lr=1.0)
    comm.push_sparse_async("emb", np.array([5, 9], np.int64),
                           -np.ones((2, 2), np.float32), lr=1.0)
    comm.flush()
    np.testing.assert_allclose(client.pull_dense("w").numpy(), 0.3,
                               rtol=1e-6)
    rows = client.pull_sparse("emb", np.array([5, 9], np.int64)).numpy()
    np.testing.assert_allclose(rows[0], 2.0)   # two accumulated grads
    np.testing.assert_allclose(rows[1], 1.0)
    comm.stop()


def test_async_communicator_background_thread_drains():
    import time
    import numpy as np
    import paddle_tpu.distributed.ps as ps

    ps._TABLES.clear()
    client = ps.LocalPSClient()
    client.create_dense_table("bg", shape=[2], initializer="zeros")
    comm = ps.AsyncCommunicator(client, send_interval=0.01,
                                batches_per_send=2).start()
    comm.push_dense_async("bg", np.ones(2, np.float32), lr=1.0)
    comm.push_dense_async("bg", np.ones(2, np.float32), lr=1.0)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if np.allclose(client.pull_dense("bg").numpy(), -2.0):
            break
        time.sleep(0.01)
    np.testing.assert_allclose(client.pull_dense("bg").numpy(), -2.0)
    comm.stop()
