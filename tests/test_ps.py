"""PS-lite tests: one server + one trainer process over rpc; the trainer
learns a sparse embedding + dense weight living on the server (the reference's
TestDistBase PS pattern, SURVEY.md §4)."""
import os
import socket
import subprocess
import sys

_WORKER = r"""
import sys
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
from paddle_tpu.distributed import rpc
from paddle_tpu.distributed.ps import PSClient, PSServer

rank = int(sys.argv[1]); port = sys.argv[2]
name = "ps0" if rank == 0 else f"trainer{rank}"
rpc.init_rpc(name, rank=rank, world_size=2,
             master_endpoint=f"127.0.0.1:{port}")

if rank == 0:
    PSServer()           # tables live here; handlers run in rpc threads
else:
    client = PSClient("ps0")
    client.create_sparse_table("emb", dim=4, initializer="zeros")
    client.create_dense_table("w", shape=[4], initializer="zeros")

    # dense push/pull arithmetic: w = 0 - 0.1 * (-1) = 0.1 per dim
    client.push_dense("w", -np.ones(4, np.float32), lr=0.1)
    w = client.pull_dense("w").numpy()
    assert np.allclose(w, 0.1), w

    # learn emb rows (fixed w): linear regression, converges geometrically
    ids = np.array([3, 7, 3], np.int64)          # duplicate id: grads sum
    emb = client.pull_sparse("emb", ids).numpy()
    assert emb.shape == (3, 4) and (emb == 0).all()
    label = np.array([1.0, -1.0, 1.0], np.float32)
    for step in range(80):
        e = client.pull_sparse("emb", ids).numpy()    # [3, 4]
        err = e @ w - label
        ge = np.outer(err, w)
        client.push_sparse("emb", ids, ge, lr=5.0)

    e = client.pull_sparse("emb", ids).numpy()
    loss = ((e @ w - label) ** 2).mean()
    assert loss < 1e-3, loss
    assert client.table_size("emb") == 2   # only ids 3 and 7 materialized
    print("PS_OK", loss)

rpc.shutdown()
"""


def test_ps_server_trainer(tmp_path):
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    script = tmp_path / "ps_worker.py"
    script.write_text(_WORKER)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo_root)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for r in range(2)]
    outs = [p.communicate(timeout=180)[0].decode() for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    assert "PS_OK" in outs[1]
