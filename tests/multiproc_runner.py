"""One rank of the multi-process collective harness.

Launched by tests/test_multiproc_collective.py via subprocess.Popen with
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER set (reference
analog: the trainer scripts TestDistBase forks,
unittests/test_dist_base.py:1150 + collective/collective_sendrecv_api.py).

Each rank: TCPStore rendezvous -> jax.distributed.initialize -> runs every
eager collective across REAL processes and asserts the cross-process result.
"""
import os
import sys


def main():
    # the axon sitecustomize preselects a TPU platform; the harness must be
    # CPU and must be forced in-process (env vars are too late)
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    rank = dist.get_rank()
    world = dist.get_world_size()
    assert world == int(os.environ["PADDLE_TRAINERS_NUM"]), \
        (world, os.environ["PADDLE_TRAINERS_NUM"])
    assert jax.process_count() == world

    def t(arr):
        return paddle.to_tensor(np.asarray(arr, np.float32))

    # --- all_reduce: sum of (rank+1) over ranks -----------------------------
    x = t([float(rank + 1)] * 4)
    dist.all_reduce(x)
    expect = sum(r + 1 for r in range(world))
    np.testing.assert_allclose(np.asarray(x._value), expect)

    # --- broadcast from rank 0 ---------------------------------------------
    b = t([rank * 10.0, rank * 10.0])
    dist.broadcast(b, src=0)
    np.testing.assert_allclose(np.asarray(b._value), 0.0)

    # --- all_gather ---------------------------------------------------------
    gathered = []
    dist.all_gather(gathered, t([float(rank)] * 3))
    assert len(gathered) == world
    for r in range(world):
        np.testing.assert_allclose(np.asarray(gathered[r]._value), float(r))

    # --- send/recv: ring r -> (r+1) % world ---------------------------------
    # EVERY rank sends first — the host-mediated p2p must not deadlock on
    # crossing sends (an SPMD-collective p2p would)
    payload = t([float(100 + rank)] * 2)
    inbox = t([0.0, 0.0])
    src = (rank - 1) % world
    dst = (rank + 1) % world
    dist.send(payload, dst=dst)
    dist.recv(inbox, src=src)
    np.testing.assert_allclose(np.asarray(inbox._value), float(100 + src))
    # second round (reversed ring) proves sequence keys don't collide
    dist.send(payload, dst=src)
    dist.recv(inbox, src=dst)
    np.testing.assert_allclose(np.asarray(inbox._value), float(100 + dst))

    # --- partial_send/partial_recv: exchange one half-slice ------------------
    big = t([float(rank)] * 8)
    slot = t([0.0] * 8)
    dist.partial_send(big, dst=dst, nranks=2, rank_id=1)
    dist.partial_recv(slot, src=src, nranks=2, rank_id=1)
    got = np.asarray(slot._value)
    np.testing.assert_allclose(got[:4], 0.0)       # untouched half
    np.testing.assert_allclose(got[4:], float(src))

    # --- batch_isend_irecv ---------------------------------------------------
    # every rank lists irecv FIRST (the canonical ring-exchange order):
    # the batch must hoist the sends, or both ends would deadlock
    outbox = t([float(rank * 2)] * 2)
    inbox2 = t([0.0, 0.0])
    tasks = dist.batch_isend_irecv([
        dist.P2POp(dist.irecv, inbox2, src),
        dist.P2POp(dist.isend, outbox, dst)])
    for tk in tasks:
        tk.wait()
    np.testing.assert_allclose(np.asarray(inbox2._value), float(src * 2))

    # --- reduce_scatter -----------------------------------------------------
    parts = [t([float(rank + 1)] * 2) for _ in range(world)]
    out = t([0.0, 0.0])
    dist.reduce_scatter(out, parts)
    np.testing.assert_allclose(np.asarray(out._value), expect)

    # --- alltoall -----------------------------------------------------------
    ins = [t([float(rank * world + j)] * 2) for j in range(world)]
    outs = []
    dist.alltoall(ins, outs)
    for i in range(world):
        np.testing.assert_allclose(np.asarray(outs[i]._value),
                                   float(i * world + rank))

    # --- alltoall_single ----------------------------------------------------
    flat = t([float(rank * world + j) for j in range(world)])
    single_out = t([0.0] * world)
    dist.alltoall_single(flat, single_out)
    np.testing.assert_allclose(
        np.asarray(single_out._value),
        [float(i * world + rank) for i in range(world)])

    # --- scatter from rank 0 ------------------------------------------------
    chunk = t([0.0, 0.0])
    if rank == 0:
        dist.scatter(chunk, [t([float(7 + r)] * 2) for r in range(world)],
                     src=0)
    else:
        dist.scatter(chunk, src=0)
    np.testing.assert_allclose(np.asarray(chunk._value), float(7 + rank))

    # --- all_gather_object (pickled, ragged) --------------------------------
    objs = []
    dist.all_gather_object(objs, {"rank": rank, "tag": "x" * (rank + 1)})
    assert [o["rank"] for o in objs] == list(range(world))
    assert all(objs[r]["tag"] == "x" * (r + 1) for r in range(world))

    # --- LocalSGD over a REAL dp axis ----------------------------------------
    # each rank trains on DIFFERENT data for k_steps, then the averaging
    # step must leave every rank with IDENTICAL parameters (reference
    # localsgd_optimizer.py semantics)
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        LocalSGDOptimizer, DGCMomentum)
    paddle.seed(0)                       # same init on every rank
    m = nn.Linear(4, 2)
    opt = LocalSGDOptimizer(
        paddle.optimizer.SGD(learning_rate=1e-2,
                             parameters=m.parameters()), k_steps=3)
    rng = np.random.default_rng(100 + rank)     # different data per rank
    for i in range(3):                   # step 3 triggers the averaging
        x_ = paddle.to_tensor(rng.normal(size=(8, 4)).astype(np.float32))
        y_ = paddle.to_tensor(rng.normal(size=(8, 2)).astype(np.float32))
        loss = ((m(x_) - y_) * (m(x_) - y_)).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    mine = np.asarray(m.weight._value)
    gathered = []
    dist.all_gather_object(gathered, mine)
    for other in gathered:
        np.testing.assert_allclose(other, mine, rtol=1e-6, atol=1e-7)

    # --- DGC over a REAL dp axis ---------------------------------------------
    # identical data + identical init => the compressed all-reduced grads
    # are identical, so params must track exactly across ranks
    paddle.seed(1)
    m2 = nn.Linear(4, 2)
    opt2 = DGCMomentum(
        paddle.optimizer.Momentum(learning_rate=1e-2, momentum=0.9,
                                  parameters=m2.parameters()),
        sparsity=(0.5,))
    rng2 = np.random.default_rng(7)      # SAME data on every rank
    for i in range(3):
        x_ = paddle.to_tensor(rng2.normal(size=(8, 4)).astype(np.float32))
        y_ = paddle.to_tensor(rng2.normal(size=(8, 2)).astype(np.float32))
        loss = ((m2(x_) - y_) * (m2(x_) - y_)).mean()
        loss.backward()
        opt2.step()
        opt2.clear_grad()
    mine2 = np.asarray(m2.weight._value)
    gathered2 = []
    dist.all_gather_object(gathered2, mine2)
    for other in gathered2:
        np.testing.assert_allclose(other, mine2, rtol=1e-6, atol=1e-7)

    # --- global_scatter / global_gather (MoE token exchange) -----------------
    # 1 expert per card: rank r sends `r+1` tokens to every card; the
    # gather must return exactly the original tokens
    if world <= 4:
        import warnings as _w
        from paddle_tpu.distributed.utils import (global_scatter,
                                                  global_gather)
        n_send = world * (rank + 1)
        x_moe = t(np.arange(n_send * 2, dtype=np.float32)
                  .reshape(n_send, 2) + 100 * rank)
        local_count = t(np.asarray([rank + 1] * world, np.int64))
        # rank r receives (c+1) tokens from each card c
        global_count = t(np.asarray([c + 1 for c in range(world)],
                                    np.int64))
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            recv = global_scatter(x_moe, local_count, global_count)
            assert recv._value.shape[0] == sum(
                c + 1 for c in range(world)), recv._value.shape
            back = global_gather(recv, local_count, global_count)
        np.testing.assert_allclose(np.asarray(back._value),
                                   np.asarray(x_moe._value))

    # --- barrier + store round-trip -----------------------------------------
    dist.barrier()
    store = dist.env.get_store()
    assert store is not None
    store.set(f"mark/{rank}", str(rank))
    store.barrier("marks")
    for r in range(world):
        assert store.get(f"mark/{r}").decode() == str(r)

    print(f"RANK {rank} OK", flush=True)


if __name__ == "__main__":
    main()
