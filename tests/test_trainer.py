"""Trainer/DeviceWorker stack (reference analog: trainer_factory.py,
device_worker.py Hogwild/DownpourSGD, multi_trainer.cc)."""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.trainer import (TrainerDesc, Hogwild,
                                            DownpourSGD, MultiTrainer,
                                            DistMultiTrainer,
                                            TrainerFactory)
from paddle_tpu.distributed.ps import LocalPSClient


def _batches(n, batch=16, seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(4, 1)).astype(np.float32)
    for _ in range(n):
        x = rng.normal(size=(batch, 4)).astype(np.float32)
        y = x @ w_true
        yield (paddle.to_tensor(x), paddle.to_tensor(y))


def test_hogwild_multitrainer_learns():
    paddle.seed(0)
    model = nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
    desc = TrainerDesc()
    desc._set_thread(2)
    trainer = MultiTrainer(desc, lambda tid: Hogwild(
        model, lambda o, y: F.mse_loss(o, y), opt))
    losses = trainer.run(_batches(60))
    assert len(losses) == 60
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) / 5


def test_downpour_ps_worker_learns():
    """DownpourSGD against the (local) parameter server: dense weight and
    sparse embedding rows both live on the PS and both get trained."""
    client = LocalPSClient()
    client.create_dense_table("w", shape=[4], initializer="zeros")
    client.create_sparse_table("emb", dim=4, initializer="zeros")

    rng = np.random.default_rng(0)
    target = {3: 1.0, 7: -1.0, 11: 0.5}

    def loss_of(w, rows, labels):
        pred = rows @ w
        return ((pred - labels) ** 2).mean() + 1e-4 * (w ** 2).sum()

    worker = DownpourSGD(client, "w", "emb", loss_of, lr=0.5)
    # seed w away from zero so emb rows receive gradient
    client.push_dense("w", -np.ones(4, np.float32), lr=0.25)

    losses = []
    for step in range(150):
        ids = np.array(list(target), np.int64)
        labels = jnp.asarray([target[i] for i in ids], jnp.float32)
        losses.append(worker.train_one_batch((ids, labels)))
    assert losses[-1] < 1e-2, losses[-1]
    assert client.table_size("emb") == 3


def test_dist_multitrainer_with_ps():
    client = LocalPSClient()
    client.create_dense_table("w2", shape=[4], initializer="zeros")
    client.create_sparse_table("emb2", dim=4, initializer="zeros")
    client.push_dense("w2", -np.ones(4, np.float32), lr=0.25)

    def loss_of(w, rows, labels):
        return (((rows @ w) - labels) ** 2).mean()

    desc = TrainerDesc()
    desc._set_thread(2)
    desc._set_device_worker("DownpourSGD")
    trainer = TrainerFactory().create_trainer(
        "DistMultiTrainer", desc,
        lambda tid: DownpourSGD(client, "w2", "emb2", loss_of, lr=0.3))

    rng = np.random.default_rng(1)

    def batches():
        for _ in range(80):
            ids = rng.choice([1, 2, 5, 9], size=3, replace=False) \
                .astype(np.int64)
            labels = jnp.asarray((ids % 3 - 1).astype(np.float32))
            yield ids, labels

    losses = trainer.run(batches())
    assert np.mean(losses[-10:]) < np.mean(losses[:10])


def test_trainer_factory_unknown_raises():
    with pytest.raises(ValueError):
        TrainerFactory().create_trainer("Nope", TrainerDesc(), lambda t: None)


def test_worker_error_propagates():
    desc = TrainerDesc()
    desc._set_thread(2)

    class Bad(Hogwild):
        def train_one_batch(self, batch):
            raise RuntimeError("worker exploded")

    trainer = MultiTrainer(desc, lambda tid: Bad(None, None, None))
    with pytest.raises(RuntimeError, match="worker exploded"):
        trainer.run(_batches(4))
