"""Test env: force an 8-device virtual CPU platform BEFORE jax import.

Mirrors the reference's fake-backend fixture strategy
(python/paddle/fluid/tests/custom_runtime/ CustomCPU plugin): tests run
against a pluggable non-accelerator backend so CI needs no TPU; the driver
separately dry-runs the multi-chip path.
"""
import os

# force CPU even when the session env preselects a TPU platform. jax may
# already be imported (sitecustomize), so set both the env var and the config.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", "tests must run on CPU"
assert jax.device_count() == 8, "tests expect an 8-device virtual CPU mesh"

# Persistent XLA compilation cache: the distributed suites (pipeline /
# hybrid / auto-parallel over the 8-device mesh) are dominated by large
# SPMD compiles that are identical run-to-run. Caching them keeps tier-1
# wall time inside its budget on re-runs (850s cold -> 714s warm); only
# compiles ≥0.1 s are written so trivial eager micro-test compiles don't
# churn the cache. PADDLE_TPU_CACHE_DIR overrides the root; the AOT
# executable store (ops/aot_cache.py) defaults to <root>/aot, so one env
# var relocates both caches together (the historical path stays the
# default so existing CI images keep their warm entries).
_cache_root = os.environ.setdefault("PADDLE_TPU_CACHE_DIR",
                                    "/tmp/paddle_tpu_jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_root)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed_everything():
    np.random.seed(0)
    import paddle_tpu as paddle
    paddle.seed(0)
    yield
