"""Test env: force an 8-device virtual CPU platform BEFORE jax import.

Mirrors the reference's fake-backend fixture strategy
(python/paddle/fluid/tests/custom_runtime/ CustomCPU plugin): tests run
against a pluggable non-accelerator backend so CI needs no TPU; the driver
separately dry-runs the multi-chip path.
"""
import os

# force CPU even when the session env preselects a TPU platform. jax may
# already be imported (sitecustomize), so set both the env var and the config.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", "tests must run on CPU"
assert jax.device_count() == 8, "tests expect an 8-device virtual CPU mesh"

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed_everything():
    np.random.seed(0)
    import paddle_tpu as paddle
    paddle.seed(0)
    yield
