"""paddle.linalg numeric tests against NumPy references.

Mirrors the reference's OpTest methodology (unittests/op_test.py:333): values
checked against numpy.linalg, one gradient spot-checked analytically.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import linalg


def _t(a):
    return paddle.to_tensor(np.asarray(a, np.float32))


def test_norm_variants():
    a = np.random.randn(3, 4).astype(np.float32)
    x = _t(a)
    np.testing.assert_allclose(float(linalg.norm(x)), np.linalg.norm(a),
                               rtol=1e-5)
    np.testing.assert_allclose(
        linalg.norm(x, p=1, axis=1).numpy(),
        np.linalg.norm(a, ord=1, axis=1), rtol=1e-5)
    np.testing.assert_allclose(
        linalg.norm(x, p=np.inf, axis=0).numpy(),
        np.abs(a).max(axis=0), rtol=1e-5)


def test_det_slogdet_inv():
    a = np.random.randn(4, 4).astype(np.float32) + 4 * np.eye(4, dtype=np.float32)
    x = _t(a)
    np.testing.assert_allclose(float(linalg.det(x)), np.linalg.det(a),
                               rtol=1e-4)
    s = linalg.slogdet(x).numpy()
    sign, logdet = np.linalg.slogdet(a)
    np.testing.assert_allclose(s, [sign, logdet], rtol=1e-4)
    np.testing.assert_allclose(linalg.inv(x).numpy(), np.linalg.inv(a),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(linalg.pinv(x).numpy(), np.linalg.pinv(a),
                               rtol=1e-3, atol=1e-4)


def test_svd_qr_reconstruct():
    a = np.random.randn(5, 3).astype(np.float32)
    u, s, v = linalg.svd(_t(a))
    rec = u.numpy() @ np.diag(s.numpy()) @ v.numpy().T
    np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-5)
    q, r = linalg.qr(_t(a))
    np.testing.assert_allclose(q.numpy() @ r.numpy(), a, rtol=1e-4, atol=1e-5)


def test_eigh_eigvalsh():
    a = np.random.randn(4, 4).astype(np.float32)
    a = (a + a.T) / 2
    w, v = linalg.eigh(_t(a))
    wr = np.linalg.eigvalsh(a)
    np.testing.assert_allclose(np.sort(w.numpy()), np.sort(wr), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.sort(linalg.eigvalsh(_t(a)).numpy()),
                               np.sort(wr), rtol=1e-4, atol=1e-5)
    # eigvectors: A v = w v
    av = a @ v.numpy()
    wv = v.numpy() * w.numpy()[None, :]
    np.testing.assert_allclose(av, wv, rtol=1e-3, atol=1e-4)


def test_solve_family():
    a = np.random.randn(4, 4).astype(np.float32) + 4 * np.eye(4, dtype=np.float32)
    b = np.random.randn(4, 2).astype(np.float32)
    np.testing.assert_allclose(linalg.solve(_t(a), _t(b)).numpy(),
                               np.linalg.solve(a, b), rtol=1e-3, atol=1e-4)
    spd = a @ a.T + np.eye(4, dtype=np.float32)
    chol = np.linalg.cholesky(spd).astype(np.float32)
    got = linalg.cholesky_solve(_t(b), _t(chol)).numpy()
    np.testing.assert_allclose(got, np.linalg.solve(spd, b), rtol=1e-3,
                               atol=1e-3)
    tri = np.triu(a)
    got = linalg.triangular_solve(_t(tri), _t(b), upper=True).numpy()
    np.testing.assert_allclose(tri @ got, b, rtol=1e-3, atol=1e-3)


def test_cholesky():
    a = np.random.randn(4, 4).astype(np.float32)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    l = linalg.cholesky(_t(spd)).numpy()
    np.testing.assert_allclose(l @ l.T, spd, rtol=1e-4, atol=1e-4)
    u = linalg.cholesky(_t(spd), upper=True).numpy()
    np.testing.assert_allclose(u.T @ u, spd, rtol=1e-4, atol=1e-4)


def test_lstsq():
    a = np.random.randn(6, 3).astype(np.float32)
    b = np.random.randn(6, 2).astype(np.float32)
    sol, _, rank, _ = linalg.lstsq(_t(a), _t(b))
    ref = np.linalg.lstsq(a, b, rcond=None)[0]
    np.testing.assert_allclose(sol.numpy(), ref, rtol=1e-3, atol=1e-3)
    assert int(rank.numpy()) == 3


def test_lu_and_unpack_reconstruct():
    # small diagonal entries force partial pivoting to produce a nontrivial
    # permutation, exercising the sequential pivot-composition loop
    a = (np.random.randn(5, 5) + 5 * np.eye(5)[::-1]).astype(np.float32)
    lu_mat, piv = linalg.lu(_t(a))
    p, l, u = linalg.lu_unpack(lu_mat, piv)
    assert not np.allclose(p.numpy(), np.eye(5)), "want a nontrivial P"
    rec = p.numpy() @ l.numpy() @ u.numpy()
    np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-4)
    p2, l2, u2 = linalg.lu_unpack(lu_mat, piv, unpack_ludata=False)
    assert l2 is None and u2 is None and p2 is not None


def test_matrix_power_rank_multidot():
    a = np.random.randn(3, 3).astype(np.float32)
    np.testing.assert_allclose(linalg.matrix_power(_t(a), 3).numpy(),
                               np.linalg.matrix_power(a, 3), rtol=1e-3,
                               atol=1e-3)
    assert int(linalg.matrix_rank(_t(np.eye(4))).numpy()) == 4
    b = np.random.randn(3, 5).astype(np.float32)
    c = np.random.randn(5, 2).astype(np.float32)
    np.testing.assert_allclose(
        linalg.multi_dot([_t(a), _t(b), _t(c)]).numpy(),
        a @ b @ c, rtol=1e-4, atol=1e-4)


def test_cov_corrcoef_cross():
    a = np.random.randn(3, 10).astype(np.float32)
    np.testing.assert_allclose(linalg.cov(_t(a)).numpy(), np.cov(a),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(linalg.corrcoef(_t(a)).numpy(),
                               np.corrcoef(a), rtol=1e-4, atol=1e-5)
    x = np.random.randn(4, 3).astype(np.float32)
    y = np.random.randn(4, 3).astype(np.float32)
    np.testing.assert_allclose(linalg.cross(_t(x), _t(y)).numpy(),
                               np.cross(x, y), rtol=1e-5, atol=1e-6)


def test_det_gradient():
    # d det(A) / dA = det(A) * A^-T
    a = np.random.randn(3, 3).astype(np.float32) + 3 * np.eye(3, dtype=np.float32)
    x = paddle.to_tensor(a, stop_gradient=False)
    d = linalg.det(x)
    d.backward()
    expect = np.linalg.det(a) * np.linalg.inv(a).T
    np.testing.assert_allclose(x.grad.numpy(), expect, rtol=1e-3, atol=1e-4)


def test_histogram_bincount_vander():
    x = np.array([0, 1, 1, 3, 2, 1], np.int64)
    np.testing.assert_array_equal(
        linalg.bincount(paddle.to_tensor(x)).numpy(), np.bincount(x))
    h = linalg.histogram(_t([1.0, 2.0, 1.0]), bins=4, min=0, max=3)
    np.testing.assert_array_equal(h.numpy(), [0, 2, 1, 0])
    v = np.array([1.0, 2.0, 3.0], np.float32)
    np.testing.assert_allclose(linalg.vander(_t(v), n=3).numpy(),
                               np.vander(v, 3), rtol=1e-6)
