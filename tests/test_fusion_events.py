"""Fusion flight recorder (profiler/events.py) + doctor + trace lanes.

Covers the PR 4 observability contract end to end:
  * the event-category and reason-code sets are PUBLIC contracts — the
    fusion doctor, the perf-smoke "no unexplained splits" guard, and
    downstream trace tooling key on the exact strings;
  * the ring buffer stays bounded under sustained emission, separates
    emitting threads, and records NOTHING (not one event) when
    FLAGS_profiler_events is off;
  * the three fusion tiers emit their lifecycle (dispatch hit/miss/bypass,
    chain detect/fire/split, step promote/fire/split/record) with reason
    attribution — dropout now PROMOTES (hoisted stream keys; only a
    stateful key baked into a closure still blames `rng_rekey`), masked
    attention and nll_loss no longer bypass at all (PR 4 satellite);
  * profiler/explain.py turns the timeline into the right verdicts;
  * Profiler windows auto-arm the recorder, export chrome traces with
    fusion lanes, and `load_profiler_result` round-trips them losslessly.
"""
from __future__ import annotations

import json
import os
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops.dispatch import clear_dispatch_cache
from paddle_tpu.ops import manipulation as manip
from paddle_tpu.profiler import (Profiler, SummaryView, dispatch_cache_stats,
                                 load_profiler_result,
                                 reset_chain_fusion_stats,
                                 reset_dispatch_cache_stats,
                                 reset_step_fusion_stats)
from paddle_tpu.profiler.events import (CATEGORIES, EVENTS, REASON_CODES,
                                        clear_fusion_events, events_summary,
                                        fusion_events)
from paddle_tpu.profiler.explain import explain, format_report

_DEFAULT_FLAGS = {
    "FLAGS_eager_op_cache": True,
    "FLAGS_eager_op_cache_size": 512,
    "FLAGS_eager_chain_fusion": True,
    "FLAGS_eager_chain_fusion_min_count": 3,
    "FLAGS_eager_chain_cache_size": 128,
    "FLAGS_eager_chain_stitching": True,
    "FLAGS_eager_step_fusion": True,
    "FLAGS_eager_step_fusion_min_count": 4,
    "FLAGS_eager_step_fusion_cache_size": 8,
    "FLAGS_profiler_events": False,
    "FLAGS_profiler_events_capacity": 65536,
}


@pytest.fixture(autouse=True)
def _fresh():
    set_flags(dict(_DEFAULT_FLAGS))
    clear_dispatch_cache()
    clear_fusion_events()
    reset_dispatch_cache_stats()
    reset_chain_fusion_stats()
    reset_step_fusion_stats()
    yield
    set_flags(dict(_DEFAULT_FLAGS))
    clear_dispatch_cache()
    clear_fusion_events()
    reset_dispatch_cache_stats()
    reset_chain_fusion_stats()
    reset_step_fusion_stats()


def _train_loop(steps, dropout_p=0.0, with_mask=False, b=4, d=16,
                legacy_rng=False):
    """Tiny fwd+bwd+SGD loop; optional dropout / masked attention /
    a deliberately STATEFUL-RNG op (a fresh key baked into its closure
    every call — the shape the hoisted-key path retired, kept here as the
    rng_rekey attribution fixture)."""
    import jax
    from paddle_tpu.framework.random import get_rng_key
    from paddle_tpu.ops._helpers import unary

    rng = np.random.default_rng(3)
    x = paddle.to_tensor(rng.standard_normal((b, d)).astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((d, d)).astype(np.float32),
                         stop_gradient=False)
    bias = paddle.to_tensor(rng.standard_normal(d).astype(np.float32),
                            stop_gradient=False)
    mask = None
    if with_mask:
        mask = paddle.to_tensor(np.tril(np.ones((b, b), bool))[None, None])
    opt = paddle.optimizer.SGD(learning_rate=1e-3, parameters=[w, bias])
    for _ in range(steps):
        h = F.gelu(paddle.add(paddle.matmul(x, w), bias))
        if dropout_p:
            h = F.dropout(h, dropout_p)
        if legacy_rng:
            noise = jax.random.normal(get_rng_key(), (b, d)) * 0.01
            h = unary("legacy_noise", lambda v: v + noise.astype(v.dtype),
                      h)
        if with_mask:
            q = manip.reshape(h, [1, b, 1, d])
            h = manip.reshape(
                F.scaled_dot_product_attention(q, q, q, attn_mask=mask),
                [b, d])
        h.sum().backward()
        opt.step()
        opt.clear_grad()
    return w, bias


class TestPublicContract:
    """The category and reason-code sets are frozen API: changing them
    breaks the doctor, the smoke guard, and saved traces. Additions are
    deliberate (update this test); renames/removals are regressions."""

    def test_categories_exact(self):
        assert CATEGORIES == frozenset({
            "dispatch.hit", "dispatch.miss", "dispatch.bypass",
            "dispatch.retrace",
            "chain.detect", "chain.compile", "chain.fire", "chain.split",
            "chain.stitch",
            "step.record", "step.promote", "step.fire", "step.split",
            "step.deactivate",
            # serving-engine request lifecycle (PR 6, paddle_tpu/serving)
            "serve.enqueue", "serve.admit", "serve.step", "serve.evict",
            "serve.complete",
            # serving resilience (PR 7, serving/resilience.py)
            "serve.cancel", "serve.expire", "serve.refuse", "serve.hang",
            "serve.degrade", "serve.resume",
            # multi-tenant serving (PR 17, serving/tenancy.py)
            "serve.prefix_hit", "serve.prefix_miss", "serve.prefix_evict",
            "serve.swap",
            # compiled stochastic sampling + pipelined decode (PR 18)
            "serve.sample",
            # persistent AOT executable cache (PR 9, ops/aot_cache.py)
            "aot.hit", "aot.miss", "aot.store", "aot.corrupt",
            "aot.version_skew", "aot.evict",
            # kernel tier (PR 11, kernels/pallas/ + int8 KV cache)
            "kernel.fallback", "kernel.quantized",
            # regression sentinel (PR 19, profiler/sentinel.py)
            "sentinel.arm", "sentinel.check", "sentinel.drift",
            "sentinel.recover",
            # elastic fleet fabric (PR 20, distributed/fabric.py)
            "fleet.join", "fleet.leave", "fleet.rebuild", "fleet.rejoin",
        })

    def test_reason_codes_exact(self):
        assert REASON_CODES == frozenset({
            "unkeyable_closure", "rng_rekey", "tracer_input",
            "cache_disabled", "unjittable",
            "key_mismatch", "shape_mismatch", "wiring_mismatch",
            "registry_bump", "mid_chain_escape", "mid_step_peek",
            "event_mismatch", "param_mismatch", "optimizer_state_change",
            "hook_present", "exec_fault", "trace_fail", "debug_interrupt",
            "flag_off",
            "uncached_dispatch", "multi_backward", "cycle_too_long",
            "unpromotable_cycle", "fail_streak",
            # step-guardian decisions (PR 5, FLAGS_check_numerics)
            "nonfinite_output", "nonfinite_skip", "scaler_backoff",
            "injected_fault",
            # serving-engine outcomes (PR 6, paddle_tpu/serving)
            "kv_exhausted", "bucket_retrace",
            # serving resilience decisions (PR 7, serving/resilience.py)
            "client_cancel", "deadline_expired", "queue_full",
            "deadline_infeasible", "step_hang", "decode_fault",
            "crash_resume",
            # multi-tenant serving (PR 17, serving/tenancy.py)
            "prefix_hit", "adapter_mismatch", "torn_swap",
            # compiled sampling + pipelined decode (PR 18,
            # serving/sampling.py)
            "sampler_mismatch", "commit_lag_rollback",
            # distributed step fusion (PR 10, ops/spmd_fusion.py);
            # pipeline promotion registry (PR 16) adds schedule churn
            "collective_unkeyed", "mesh_mismatch", "spmd_divergence",
            "pipe_schedule_mismatch",
            # AOT executable-store decisions (PR 9, ops/aot_cache.py)
            "artifact_corrupt", "version_skew",
            # kernel tier (PR 11, FLAGS_serve_attention_kernel + int8 KV)
            "kernel_fallback", "kv_quantized",
            # promotion-safety static analyzer (PR 15,
            # paddle_tpu/analysis/): static-only finding classes — the
            # R1-R4 rules reuse the runtime codes above
            "contract_drift", "lock_discipline",
            # regression sentinel verdicts (PR 19, profiler/sentinel.py)
            # + the R7 static perf-contract finding class
            "perf_drift", "split_regression", "compile_storm",
            "latency_drift", "perf_contract",
            # elastic fleet fabric (PR 20, distributed/fabric.py)
            "host_lost", "mesh_rebuild", "stale_member",
        })

    def test_every_reason_has_a_doctor_hint(self):
        from paddle_tpu.profiler.explain import REASON_HINTS
        assert set(REASON_HINTS) == REASON_CODES


class TestRingBuffer:
    def test_bounded_under_sustained_emission(self):
        set_flags({"FLAGS_profiler_events": True,
                   "FLAGS_profiler_events_capacity": 64})
        clear_fusion_events()      # re-applies the capacity flag
        for i in range(1000):
            EVENTS.emit("dispatch.hit", f"op{i}")
        assert len(EVENTS) == 64
        snap = fusion_events()
        assert len(snap) == 64
        # oldest dropped, newest kept, seq strictly increasing
        assert snap[-1]["op"] == "op999"
        seqs = [e["seq"] for e in snap]
        assert seqs == sorted(seqs)

    def test_zero_events_when_off(self):
        assert not EVENTS.enabled
        _train_loop(4)
        EVENTS.emit("dispatch.hit", "manual")
        assert len(EVENTS) == 0
        assert fusion_events() == []

    def test_thread_id_separation(self):
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        tids = []

        def worker():
            tids.append(threading.get_ident())
            rng = np.random.default_rng(0)
            a = paddle.to_tensor(
                rng.standard_normal((4, 4)).astype(np.float32))
            for _ in range(6):
                paddle.matmul(a, a)

        threads = [threading.Thread(target=worker) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ev_tids = {e["tid"] for e in fusion_events("dispatch")}
        assert set(tids) <= ev_tids
        by_thread = {t: [e for e in fusion_events("dispatch")
                         if e["tid"] == t] for t in tids}
        for t in tids:
            assert by_thread[t], f"thread {t} emitted no dispatch events"

    def test_snapshot_filters(self):
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        EVENTS.emit("dispatch.hit", "a")
        EVENTS.emit("chain.fire", "b")
        mark = EVENTS.total
        EVENTS.emit("step.fire", "c")
        assert [e["cat"] for e in fusion_events("chain")] == ["chain.fire"]
        assert [e["op"] for e in fusion_events(since_seq=mark)] == ["c"]

    def test_key_digest_never_leaks_raw_keys(self):
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        EVENTS.emit("dispatch.hit", "op", key=("matmul", 1, (2, 3)))
        EVENTS.emit("dispatch.bypass", "op", key=None, reason="rng_rekey")
        a, b = fusion_events()
        assert isinstance(a["key"], str) and len(a["key"]) == 12
        assert b["key"] is None


class TestLifecycleEvents:
    def test_fused_loop_emits_all_tiers(self):
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        _train_loop(12)
        cats = events_summary()["by_category"]
        for expected in ("dispatch.miss", "dispatch.hit", "chain.detect",
                         "step.promote", "step.fire", "step.record"):
            assert cats.get(expected, 0) > 0, (expected, cats)

    def test_dropout_promotes_with_hoisted_keys(self):
        """Universal promotion: dropout keys on a hoisted stream
        position now — zero rng_rekey poisons, zero dispatch bypasses,
        and the cycle PROMOTES (the exact loop that used to be the
        never-promotes fixture)."""
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        _train_loop(10, dropout_p=0.2)
        poisons = [e for e in fusion_events("step.record")
                   if e["reason"] == "rng_rekey"]
        assert poisons == []
        bypass_ops = [e["op"] for e in fusion_events("dispatch.bypass")]
        assert "dropout" not in bypass_ops
        cats = events_summary()["by_category"]
        assert cats.get("step.promote", 0) >= 1
        assert cats.get("step.fire", 0) >= 1

    def test_stateful_rng_closure_blames_rng_rekey(self):
        """The rng_rekey attribution survives for ops that still bake a
        STATEFUL fresh key into their closure (the legacy shape)."""
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        _train_loop(10, legacy_rng=True)
        poisons = [e for e in fusion_events("step.record")
                   if e["reason"] == "rng_rekey"]
        assert len(poisons) >= 8
        assert {e["op"] for e in poisons} == {"legacy_noise"}
        assert events_summary()["by_category"].get("step.promote", 0) == 0

    def test_masked_attention_and_nll_do_not_bypass(self):
        """PR 4 satellite: mask/label are dispatch inputs now — the
        unkeyable_closure count for these ops must be zero."""
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        _train_loop(6, with_mask=True)
        rng = np.random.default_rng(0)
        logp = paddle.to_tensor(
            np.log(rng.dirichlet(np.ones(5), 8)).astype(np.float32))
        lab = paddle.to_tensor(rng.integers(0, 5, 8))
        F.nll_loss(logp, lab)
        bypass_ops = [e["op"] for e in fusion_events("dispatch.bypass")]
        assert "scaled_dot_product_attention" not in bypass_ops
        assert "nll_loss" not in bypass_ops
        ops = dispatch_cache_stats(per_op=True)["ops"]
        assert ops["scaled_dot_product_attention"]["bypasses"] == 0
        assert ops["nll_loss"]["bypasses"] == 0

    def test_masked_attention_promotes_cleanly(self):
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        _train_loop(12, with_mask=True)
        rep = explain()
        assert rep["verdict"] == "clean_promotion", rep["headline"]
        assert rep["step"]["fired"] > 0

    def test_mid_step_peek_split_reason(self):
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        rng = np.random.default_rng(3)
        x = paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
        w = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32),
                             stop_gradient=False)
        opt = paddle.optimizer.SGD(learning_rate=1e-3, parameters=[w])
        for i in range(10):
            loss = F.gelu(paddle.matmul(x, w)).sum()
            loss.backward()
            if i == 8:
                float(loss)     # peek mid-replay: must split, attributed
            opt.step()
            opt.clear_grad()
        splits = fusion_events("step.split")
        assert splits and splits[0]["reason"] == "mid_step_peek"

    def test_all_emitted_reasons_are_known(self):
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        _train_loop(10, dropout_p=0.2)
        _train_loop(10, with_mask=True)
        bad = [e for e in fusion_events()
               if e["reason"] is not None and e["reason"] not in REASON_CODES]
        assert bad == []


class TestExplain:
    def test_no_data_verdict(self):
        rep = explain([])
        assert rep["verdict"] == "no_data"

    def test_never_promoted_names_the_op(self):
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        _train_loop(10, legacy_rng=True)
        rep = explain()
        assert rep["verdict"] == "never_promoted"
        assert "rng_rekey" in rep["headline"]
        assert "legacy_noise" in rep["headline"]
        text = format_report(rep)
        assert "never_promoted" in text and "rng_rekey" in text

    def test_report_is_json_ready(self):
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        _train_loop(6)
        json.dumps(explain())


class TestProfilerIntegration:
    def test_window_arms_and_restores_flag(self):
        assert not EVENTS.enabled
        prof = Profiler()
        prof.start()
        assert EVENTS.enabled
        _train_loop(3)
        prof.stop()
        assert not EVENTS.enabled
        assert prof._fusion_events

    def test_summary_has_fusion_view(self, capsys):
        prof = Profiler()
        prof.start()
        _train_loop(8)
        prof.stop()
        table = prof.summary()
        capsys.readouterr()
        assert "Fusion View" in table
        assert "step_fusion" in table
        assert "step.fire" in table
        # the pre-existing counter structs are folded in (PR 4 satellite)
        assert "hit_rate" in table and "fused_steps" in table
        # view filtering still honors non-fusion selections
        host_only = prof.summary(views=[SummaryView.OperatorView])
        capsys.readouterr()
        assert "Fusion View" not in host_only

    def test_chrome_trace_lanes_and_roundtrip(self, tmp_path):
        prof = Profiler()
        prof.start()
        _train_loop(10)
        prof.stop()
        path = os.path.join(tmp_path, "trace.json")
        prof.export(path)
        res = load_profiler_result(path)
        lanes = {e.get("cat") for e in res.trace_events
                 if str(e.get("cat", "")).startswith("fusion.")}
        assert lanes == {"fusion.dispatch", "fusion.chain", "fusion.step"}
        names = {e["args"]["name"] for e in res.trace_events
                 if e.get("ph") == "M" and e.get("name") == "thread_name"}
        assert {"fusion:dispatch", "fusion:chain",
                "fusion:step"} <= names
        # lossless round-trip: the raw events survive re-load and
        # re-summarize identically (satellite: load_profiler_result)
        assert len(res.fusion_events) == len(prof._fusion_events)
        assert res.events_summary() == events_summary(prof._fusion_events)
        assert [e["seq"] for e in res.fusion_events] \
            == [e["seq"] for e in prof._fusion_events]
        assert "step.fire" in res.summary()
        # instant events sit on the synthetic lanes with μs timestamps
        inst = [e for e in res.trace_events
                if str(e.get("cat", "")).startswith("fusion.")
                and e.get("ph") == "i"]
        assert inst and all(e["ts"] > 0 for e in inst)


class TestDoctorCLI:
    @pytest.mark.perf_smoke
    def test_demo_dropout_promotes_cleanly(self):
        """Universal promotion acceptance: the dropout GPT demo — the
        historical rng_rekey fixture — now reports clean_promotion."""
        import subprocess
        import sys
        out = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                          "fusion_doctor.py"),
             "--demo", "dropout", "--steps", "12", "--json"],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr
        rep = json.loads(out.stdout)
        assert rep["verdict"] == "clean_promotion", rep["headline"]

    @pytest.mark.perf_smoke
    def test_demo_accum_promotes_cleanly(self):
        """Universal promotion acceptance: the k=4 grad-accumulation GPT
        demo promotes as a super-cycle with no rng_rekey /
        unpromotable_cycle findings."""
        import subprocess
        import sys
        out = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                          "fusion_doctor.py"),
             "--demo", "accum", "--steps", "12", "--json"],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr
        rep = json.loads(out.stdout)
        assert rep["verdict"] == "clean_promotion", rep["headline"]
        text = json.dumps(rep)
        assert "rng_rekey" not in text
        assert "unpromotable_cycle" not in text

    @pytest.mark.perf_smoke
    def test_demo_masked_promotes_cleanly(self):
        import subprocess
        import sys
        out = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                          "fusion_doctor.py"),
             "--demo", "masked", "--steps", "12", "--json"],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr
        rep = json.loads(out.stdout)
        assert rep["verdict"] == "clean_promotion"
        assert rep["step"]["fired"] > 0
