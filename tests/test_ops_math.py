"""Math-op forward/grad checks (OpTest methodology)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_forward, check_grad


RNG = np.random.default_rng(0)


def randf(*shape):
    return RNG.standard_normal(shape).astype(np.float32)


def randpos(*shape):
    return (RNG.random(shape).astype(np.float32) + 0.5)


class TestBinaryOps:
    @pytest.mark.parametrize("op,npop", [
        (paddle.add, np.add), (paddle.subtract, np.subtract),
        (paddle.multiply, np.multiply), (paddle.divide, np.divide),
        (paddle.maximum, np.maximum), (paddle.minimum, np.minimum),
    ])
    def test_forward(self, op, npop):
        check_forward(op, npop, [randf(3, 4), randpos(3, 4)])

    def test_broadcast(self):
        check_forward(paddle.add, np.add, [randf(3, 4), randf(4)])
        check_forward(paddle.multiply, np.multiply, [randf(2, 1, 4),
                                                     randf(3, 1)])

    def test_grad_add_mul(self):
        check_grad(paddle.add, [randf(3, 4), randf(3, 4)])
        check_grad(paddle.multiply, [randf(3, 4), randf(3, 4)])
        check_grad(paddle.divide, [randf(3, 4), randpos(3, 4)])

    def test_scalar_operand(self):
        x = paddle.to_tensor(randf(3, 4))
        np.testing.assert_allclose((x + 2.0).numpy(), x.numpy() + 2.0,
                                   rtol=1e-6)
        np.testing.assert_allclose((2.0 * x).numpy(), 2.0 * x.numpy(),
                                   rtol=1e-6)
        np.testing.assert_allclose((1.0 / (x + 10)).numpy(),
                                   1.0 / (x.numpy() + 10), rtol=1e-5)

    def test_pow_mod(self):
        check_forward(paddle.pow, np.power, [randpos(3, 3), randf(3, 3)],
                      atol=1e-4, rtol=1e-4)
        check_forward(paddle.mod, np.mod, [randpos(4), randpos(4)])


class TestUnaryOps:
    @pytest.mark.parametrize("op,npop", [
        (paddle.exp, np.exp), (paddle.tanh, np.tanh), (paddle.sin, np.sin),
        (paddle.cos, np.cos), (paddle.abs, np.abs), (paddle.floor, np.floor),
        (paddle.ceil, np.ceil), (paddle.square, np.square),
    ])
    def test_forward(self, op, npop):
        check_forward(op, npop, [randf(3, 4)], atol=1e-5)

    def test_log_sqrt(self):
        check_forward(paddle.log, np.log, [randpos(3, 4)], atol=1e-5)
        check_forward(paddle.sqrt, np.sqrt, [randpos(3, 4)], atol=1e-5)
        check_grad(paddle.log, [randpos(3, 3)])
        check_grad(paddle.sqrt, [randpos(3, 3)])

    def test_grad_elementwise(self):
        check_grad(paddle.tanh, [randf(3, 3)])
        check_grad(paddle.exp, [randf(3, 3) * 0.5])
        check_grad(paddle.square, [randf(3, 3)])

    def test_clip(self):
        check_forward(paddle.clip, lambda a: np.clip(a, -0.5, 0.5),
                      [randf(4, 4)], min=-0.5, max=0.5)


class TestMatmul:
    def test_forward(self):
        check_forward(paddle.matmul, np.matmul, [randf(3, 4), randf(4, 5)],
                      atol=1e-4)
        check_forward(paddle.matmul, lambda a, b: np.matmul(a, b),
                      [randf(2, 3, 4), randf(2, 4, 5)], atol=1e-4)

    def test_transpose_flags(self):
        a, b = randf(4, 3), randf(4, 5)
        out = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b),
                            transpose_x=True)
        np.testing.assert_allclose(out.numpy(), a.T @ b, atol=1e-4)

    def test_grad(self):
        check_grad(paddle.matmul, [randf(3, 4), randf(4, 2)], atol=1e-2,
                   rtol=1e-2)

    def test_dot_outer(self):
        check_forward(paddle.dot, lambda a, b: np.sum(a * b, -1),
                      [randf(5), randf(5)], atol=1e-5)
        check_forward(paddle.outer, np.outer, [randf(3), randf(4)], atol=1e-5)


class TestReductions:
    @pytest.mark.parametrize("op,npop", [
        (paddle.sum, np.sum), (paddle.mean, np.mean), (paddle.max, np.max),
        (paddle.min, np.min), (paddle.prod, np.prod),
    ])
    def test_full(self, op, npop):
        check_forward(op, npop, [randf(3, 4)], atol=1e-5)

    def test_axis_keepdim(self):
        x = randf(3, 4, 5)
        check_forward(paddle.sum, lambda a: np.sum(a, axis=1), [x],
                      atol=1e-5, axis=1)
        check_forward(paddle.mean, lambda a: np.mean(a, axis=(0, 2),
                                                     keepdims=True),
                      [x], atol=1e-5, axis=[0, 2], keepdim=True)

    def test_grad(self):
        check_grad(paddle.sum, [randf(3, 4)])
        check_grad(paddle.mean, [randf(3, 4)], axis=1)
        check_grad(lambda x: paddle.logsumexp(x, axis=-1), [randf(3, 4)])

    def test_std_var(self):
        x = randf(5, 6)
        check_forward(paddle.std, lambda a: np.std(a, ddof=1), [x], atol=1e-5)
        check_forward(paddle.var, lambda a: np.var(a, ddof=1), [x], atol=1e-5)

    def test_cumsum(self):
        x = randf(3, 4)
        check_forward(paddle.cumsum, lambda a: np.cumsum(a, axis=1), [x],
                      atol=1e-5, axis=1)
        check_forward(paddle.cumsum, lambda a: np.cumsum(a.reshape(-1)), [x],
                      atol=1e-5)


class TestLogic:
    def test_compare(self):
        a, b = randf(3, 4), randf(3, 4)
        ta, tb = paddle.to_tensor(a), paddle.to_tensor(b)
        np.testing.assert_array_equal((ta > tb).numpy(), a > b)
        np.testing.assert_array_equal((ta == ta).numpy(), a == a)
        np.testing.assert_array_equal(
            paddle.logical_and(ta > 0, tb > 0).numpy(), (a > 0) & (b > 0))

    def test_allclose_equal_all(self):
        a = randf(3)
        assert bool(paddle.allclose(paddle.to_tensor(a), paddle.to_tensor(a)))
        assert bool(paddle.equal_all(paddle.to_tensor(a), paddle.to_tensor(a)))
        assert not bool(paddle.equal_all(paddle.to_tensor(a),
                                         paddle.to_tensor(a + 1)))


class TestLinalg:
    def test_inv_det(self):
        x = randf(4, 4) + 4 * np.eye(4, dtype=np.float32)
        check_forward(paddle.linalg.inv, np.linalg.inv, [x], atol=1e-4)
        check_forward(paddle.linalg.det, np.linalg.det, [x], atol=1e-3,
                      rtol=1e-3)

    def test_svd_qr_cholesky(self):
        x = randf(5, 3)
        u, s, v = paddle.linalg.svd(paddle.to_tensor(x))
        recon = u.numpy() @ np.diag(s.numpy()) @ v.numpy().T
        np.testing.assert_allclose(recon, x, atol=1e-4)
        q, r = paddle.linalg.qr(paddle.to_tensor(x))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), x, atol=1e-4)
        spd = x.T @ x + 3 * np.eye(3, dtype=np.float32)
        l = paddle.linalg.cholesky(paddle.to_tensor(spd))
        np.testing.assert_allclose(l.numpy() @ l.numpy().T, spd, atol=1e-4)

    def test_norm_solve(self):
        x = randf(3, 3) + 3 * np.eye(3, dtype=np.float32)
        b = randf(3, 2)
        sol = paddle.linalg.solve(paddle.to_tensor(x), paddle.to_tensor(b))
        np.testing.assert_allclose(x @ sol.numpy(), b, atol=1e-4)
        check_forward(paddle.linalg.norm, np.linalg.norm, [randf(4, 5)],
                      atol=1e-5)


class TestSearchSort:
    def test_argmax_topk(self):
        x = randf(4, 6)
        t = paddle.to_tensor(x)
        np.testing.assert_array_equal(paddle.argmax(t, axis=1).numpy(),
                                      np.argmax(x, axis=1))
        vals, idx = paddle.topk(t, 3, axis=1)
        expected = np.sort(x, axis=1)[:, ::-1][:, :3]
        np.testing.assert_allclose(vals.numpy(), expected, atol=1e-6)

    def test_sort_unique(self):
        x = np.array([3, 1, 2, 1, 3], np.float32)
        np.testing.assert_allclose(paddle.sort(paddle.to_tensor(x)).numpy(),
                                   np.sort(x))
        u = paddle.unique(paddle.to_tensor(x))
        np.testing.assert_allclose(u.numpy(), [1, 2, 3])

    def test_nonzero_where(self):
        x = np.array([[1, 0], [0, 2]], np.float32)
        nz = paddle.nonzero(paddle.to_tensor(x))
        np.testing.assert_array_equal(nz.numpy(), [[0, 0], [1, 1]])
        out = paddle.where(paddle.to_tensor(x) > 0, paddle.to_tensor(x),
                           paddle.zeros([2, 2]))
        np.testing.assert_allclose(out.numpy(), np.where(x > 0, x, 0))
