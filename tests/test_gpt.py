"""GPT model family + driver entry points (tiny configs on the CPU mesh)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.models import (GPTConfig, GPTForCausalLM,
                                        GPTPretrainingCriterion, gpt2_124m,
                                        shard_gpt)


def tiny_cfg(**kw):
    base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                num_attention_heads=4, intermediate_size=64,
                max_position_embeddings=32, hidden_dropout_prob=0.0,
                attention_probs_dropout_prob=0.0)
    base.update(kw)
    return GPTConfig(**base)


def test_forward_shape_and_tied_head():
    model = GPTForCausalLM(tiny_cfg())
    ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)))
    logits = model(ids)
    assert logits.shape == [2, 16, 128]
    # tied embeddings: no separate lm_head parameter
    names = [n for n, _ in model.named_parameters()]
    assert not any("lm_head" in n for n in names)


def test_config_presets():
    cfg = gpt2_124m()
    model = GPTForCausalLM(cfg)
    n = model.num_params()
    assert 120e6 < n < 130e6, f"GPT-2 124M param count off: {n}"


def test_training_reduces_loss():
    paddle.seed(0)
    model = GPTForCausalLM(tiny_cfg())
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    crit = GPTPretrainingCriterion()
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, 128, (4, 16)).astype(np.int64))
    labels = paddle.to_tensor(rng.integers(0, 128, (4, 16)).astype(np.int64))
    losses = []
    for _ in range(10):
        loss = crit(model(ids), labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_train_step_fused():
    paddle.seed(0)
    model = GPTForCausalLM(tiny_cfg())
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    crit = GPTPretrainingCriterion()
    from paddle_tpu.jit import TrainStep
    step = TrainStep(model, lambda l, y: crit(l, y), opt)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.integers(0, 128, (4, 16)).astype(np.int64))
    y = paddle.to_tensor(rng.integers(0, 128, (4, 16)).astype(np.int64))
    l0 = float(step(x, y))
    for _ in range(10):
        last = float(step(x, y))
    assert last < l0


def test_sharded_training_on_mesh():
    """tp+dp+sharding over the 8-device CPU mesh (the dryrun path)."""
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g
    g.dryrun_multichip(8)


def test_entry_compiles():
    import sys
    import jax
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (1, 128, 50304)


def test_kv_cache_decode_matches_full_forward():
    paddle.seed(0)
    model = GPTForCausalLM(tiny_cfg())
    model.eval()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (2, 8)).astype(np.int64)
    full = model(paddle.to_tensor(ids)).numpy()

    caches = model.gen_caches(batch_size=2)
    outs = []
    for t in range(8):
        step_ids = paddle.to_tensor(ids[:, t:t + 1])
        logits, caches = model(step_ids, caches=caches)
        outs.append(logits.numpy())
    decoded = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(decoded, full, atol=2e-4, rtol=2e-3)


# ---- serving decode (VERDICT r2 item 10) ------------------------------------

def test_generate_matches_eager_greedy_loop():
    """model.generate (one compiled program: prefill + lax.scan over static
    KV buffers) produces the same tokens as the eager dynamic-cache loop."""
    paddle.seed(0)
    model = GPTForCausalLM(tiny_cfg(use_flash_attention=False))
    model.eval()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (2, 5)).astype(np.int64)
    out = np.asarray(model.generate(paddle.to_tensor(ids),
                                    max_new_tokens=6)._value)
    caches = model.gen_caches(batch_size=2)
    logits, caches = model(paddle.to_tensor(ids), caches=caches)
    tok = np.argmax(np.asarray(logits._value)[:, -1, :], -1)
    ref = [tok]
    for _ in range(5):
        lg, caches = model(paddle.to_tensor(tok[:, None].astype(np.int64)),
                           caches=caches)
        tok = np.argmax(np.asarray(lg._value)[:, -1, :], -1)
        ref.append(tok)
    np.testing.assert_array_equal(out, np.stack(ref, 1))


def test_generate_sampling_reproducible():
    paddle.seed(0)
    model = GPTForCausalLM(tiny_cfg(use_flash_attention=False))
    model.eval()
    ids = paddle.to_tensor(np.random.randint(0, 128, (2, 4)))
    a = np.asarray(model.generate(ids, max_new_tokens=8, do_sample=True,
                                  top_k=5, temperature=0.8, seed=7)._value)
    b = np.asarray(model.generate(ids, max_new_tokens=8, do_sample=True,
                                  top_k=5, temperature=0.8, seed=7)._value)
    c = np.asarray(model.generate(ids, max_new_tokens=8, do_sample=True,
                                  top_k=5, temperature=0.8, seed=8)._value)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (2, 8)
    assert not np.array_equal(a, c)      # different seed, different draw
    assert (a >= 0).all() and (a < 128).all()


def test_decode_step_predictor_roundtrip(tmp_path):
    """Save the GPTDecodeStep artifact, reload through the inference
    Predictor, and drive batched decode — tokens must match generate()."""
    import jax.numpy as jnp
    from paddle_tpu.incubate.models import GPTDecodeStep
    from paddle_tpu.jit import save as jit_save, InputSpec
    from paddle_tpu.inference import Config, create_predictor

    paddle.seed(0)
    cfg = tiny_cfg(use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    B, P, N = 2, 4, 5
    T = P + N
    L, H = cfg.num_hidden_layers, cfg.num_attention_heads
    D = cfg.hidden_size // H
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (B, P)).astype(np.int64)
    want = np.asarray(model.generate(paddle.to_tensor(ids),
                                     max_new_tokens=N)._value)

    step = GPTDecodeStep(model)
    path = str(tmp_path / "gpt_decode")
    jit_save(step, path, input_spec=[
        InputSpec([B, 1], "int64"), InputSpec([L, B, T, H, D], "float32"),
        InputSpec([L, B, T, H, D], "float32"), InputSpec([], "int32")])

    config = Config(path)
    predictor = create_predictor(config)

    # prefill eagerly (dynamic cache), pack buffers
    caches = model.gen_caches(batch_size=B)
    logits, caches = model(paddle.to_tensor(ids), caches=caches)
    kb = np.zeros((L, B, T, H, D), np.float32)
    vb = np.zeros((L, B, T, H, D), np.float32)
    for l, (ck, cv) in enumerate(caches):
        kb[l, :, :P] = np.asarray(ck._value)
        vb[l, :, :P] = np.asarray(cv._value)
    tok = np.argmax(np.asarray(logits._value)[:, -1, :], -1)
    got = [tok]
    for i in range(N - 1):
        outs = predictor.run([tok[:, None].astype(np.int64), kb, vb,
                              np.asarray(P + i, np.int32)])
        lg, kb, vb = outs[0], outs[1], outs[2]
        tok = np.argmax(lg[:, -1, :], -1)
        got.append(tok)
    np.testing.assert_array_equal(np.stack(got, 1), want)


def test_static_cache_multi_token_prefill_matches_full_forward():
    """Feeding the whole prompt through the static cache (multi-token
    chunk) must equal the plain forward — the chunk mask is causal within
    the chunk (regression: rows after the first could not see themselves)."""
    import jax.numpy as jnp
    paddle.seed(0)
    model = GPTForCausalLM(tiny_cfg(use_flash_attention=False))
    model.eval()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (2, 6)).astype(np.int64)
    full = model(paddle.to_tensor(ids)).numpy()
    caches = [(k, v, paddle.Tensor(jnp.asarray(0, jnp.int32)))
              for k, v in model.gen_static_caches(batch_size=2, max_len=8)]
    logits, _ = model(paddle.to_tensor(ids), caches=caches)
    np.testing.assert_allclose(logits.numpy(), full, atol=2e-4, rtol=2e-3)
