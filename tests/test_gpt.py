"""GPT model family + driver entry points (tiny configs on the CPU mesh)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.models import (GPTConfig, GPTForCausalLM,
                                        GPTPretrainingCriterion, gpt2_124m,
                                        shard_gpt)


def tiny_cfg(**kw):
    base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                num_attention_heads=4, intermediate_size=64,
                max_position_embeddings=32, hidden_dropout_prob=0.0,
                attention_probs_dropout_prob=0.0)
    base.update(kw)
    return GPTConfig(**base)


def test_forward_shape_and_tied_head():
    model = GPTForCausalLM(tiny_cfg())
    ids = paddle.to_tensor(np.random.randint(0, 128, (2, 16)))
    logits = model(ids)
    assert logits.shape == [2, 16, 128]
    # tied embeddings: no separate lm_head parameter
    names = [n for n, _ in model.named_parameters()]
    assert not any("lm_head" in n for n in names)


def test_config_presets():
    cfg = gpt2_124m()
    model = GPTForCausalLM(cfg)
    n = model.num_params()
    assert 120e6 < n < 130e6, f"GPT-2 124M param count off: {n}"


def test_training_reduces_loss():
    paddle.seed(0)
    model = GPTForCausalLM(tiny_cfg())
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    crit = GPTPretrainingCriterion()
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, 128, (4, 16)).astype(np.int64))
    labels = paddle.to_tensor(rng.integers(0, 128, (4, 16)).astype(np.int64))
    losses = []
    for _ in range(10):
        loss = crit(model(ids), labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_train_step_fused():
    paddle.seed(0)
    model = GPTForCausalLM(tiny_cfg())
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    crit = GPTPretrainingCriterion()
    from paddle_tpu.jit import TrainStep
    step = TrainStep(model, lambda l, y: crit(l, y), opt)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.integers(0, 128, (4, 16)).astype(np.int64))
    y = paddle.to_tensor(rng.integers(0, 128, (4, 16)).astype(np.int64))
    l0 = float(step(x, y))
    for _ in range(10):
        last = float(step(x, y))
    assert last < l0


def test_sharded_training_on_mesh():
    """tp+dp+sharding over the 8-device CPU mesh (the dryrun path)."""
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g
    g.dryrun_multichip(8)


def test_entry_compiles():
    import sys
    import jax
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (1, 128, 50304)


def test_kv_cache_decode_matches_full_forward():
    paddle.seed(0)
    model = GPTForCausalLM(tiny_cfg())
    model.eval()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (2, 8)).astype(np.int64)
    full = model(paddle.to_tensor(ids)).numpy()

    caches = model.gen_caches(batch_size=2)
    outs = []
    for t in range(8):
        step_ids = paddle.to_tensor(ids[:, t:t + 1])
        logits, caches = model(step_ids, caches=caches)
        outs.append(logits.numpy())
    decoded = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(decoded, full, atol=2e-4, rtol=2e-3)
