"""auto_parallel: ProcessMesh, shard_tensor, Engine (reference analog:
python/paddle/fluid/tests/unittests/auto_parallel/). Runs on the 8-device
CPU mesh from conftest."""
import numpy as np
import pytest
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import (ProcessMesh, shard_tensor, reshard,
                                    unshard_dtensor, get_dist_attr)
from paddle_tpu.distributed.auto_parallel import Engine, Strategy


def make_mesh():
    n = len(jax.devices())
    return ProcessMesh(np.arange(n).reshape(2, n // 2),
                       dim_names=["x", "y"])


def test_process_mesh_basics():
    pm = make_mesh()
    assert pm.ndim == 2
    assert pm.dim_names == ["x", "y"]
    assert pm.get_dim_size("x") == 2
    jm = pm.jax_mesh()
    assert jm.axis_names == ("x", "y")
    assert pm == make_mesh()
    assert len({pm, make_mesh()}) == 1


def test_shard_tensor_places_data():
    pm = make_mesh()
    x = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(8, 4))
    sx = shard_tensor(x, pm, ["x", None])
    spec = sx._value.sharding.spec
    assert tuple(spec)[0] == "x"
    attr = get_dist_attr(sx)
    assert attr[0] == pm and attr[1] == ["x", None]
    # values unchanged
    np.testing.assert_allclose(np.asarray(sx._value), np.arange(32).reshape(8, 4))


def test_shard_tensor_context_mesh_and_reshard():
    pm = make_mesh()
    with pm:
        x = shard_tensor(paddle.ones([8, 8]), shard_spec=["x", "y"])
    assert get_dist_attr(x)[1] == ["x", "y"]
    y = reshard(x, pm, ["y", None])
    assert tuple(y._value.sharding.spec)[0] == "y"
    z = unshard_dtensor(y)
    assert z._value.sharding.is_fully_replicated
    np.testing.assert_allclose(z.numpy(), np.ones((8, 8)))


def test_shard_tensor_bad_axis():
    pm = make_mesh()
    with pytest.raises(ValueError):
        shard_tensor(paddle.ones([4]), pm, ["nope"])


def test_shard_tensor_under_jit_constraint():
    pm = make_mesh()

    def f(v):
        t = paddle.Tensor(v, stop_gradient=True)
        s = shard_tensor(t, pm, ["x", None])
        return (s * 2)._value

    out = jax.jit(f)(np.ones((8, 4), np.float32))
    np.testing.assert_allclose(np.asarray(out), 2 * np.ones((8, 4)))


def test_engine_fit_and_evaluate():
    paddle.seed(0)
    n = len(jax.devices())
    pm = ProcessMesh(np.arange(n), dim_names=["data"])

    class DS(paddle.io.Dataset):
        def __init__(self):
            rng = np.random.default_rng(0)
            self.x = rng.standard_normal((64, 8)).astype(np.float32)
            w = rng.standard_normal((8, 1)).astype(np.float32)
            self.y = self.x @ w

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return 64

    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    engine = Engine(model, loss=nn.MSELoss(), optimizer=opt,
                    strategy=Strategy(), process_mesh=pm)
    hist = engine.fit(DS(), epochs=3, batch_size=16, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    res = engine.evaluate(DS(), batch_size=16)
    assert res["loss"] is not None and np.isfinite(res["loss"])


def test_engine_tp_annotation():
    """Megatron-style col/row sharding annotated via shard_tensor; GSPMD
    completes the rest (reference: dist_matmul rules)."""
    paddle.seed(0)
    n = len(jax.devices())
    pm = ProcessMesh(np.arange(n).reshape(1, n), dim_names=["data", "model"])

    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 1))
    # column-parallel first weight, row-parallel second
    shard_tensor(model[0].weight, pm, [None, "model"])
    shard_tensor(model[2].weight, pm, ["model", None])
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())

    class DS(paddle.io.Dataset):
        def __init__(self):
            rng = np.random.default_rng(1)
            self.x = rng.standard_normal((32, 8)).astype(np.float32)
            self.y = self.x.sum(-1, keepdims=True).astype(np.float32)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return 32

    st = Strategy({"dataset": {"batch_dim": "data"}})
    engine = Engine(model, loss=nn.MSELoss(), optimizer=opt, strategy=st,
                    process_mesh=pm)
    hist = engine.fit(DS(), epochs=4, batch_size=32, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    # the parameter kept its annotation through training
    assert tuple(model[0].weight._value.sharding.spec)[-1] == "model"


class TestMeshPlanner:
    """Cost-model mesh planner (reference analog: auto_parallel
    planner_v2.py + cost_model.py)."""

    def _stats(self):
        from paddle_tpu.distributed.auto_parallel import gpt_stats
        from paddle_tpu.incubate.models import gpt3_6p7b
        return gpt_stats(gpt3_6p7b())

    def test_small_model_prefers_pure_dp(self):
        from paddle_tpu.distributed.auto_parallel import (plan_mesh,
                                                          ModelStats)
        st = ModelStats(n_params=10_000_000, n_layers=12, hidden=768,
                        seq_len=512)
        best = plan_mesh(st, n_devices=8, batch=64, hbm_bytes=16e9)[0]
        assert best.feasible
        assert best.mp == 1 and best.pp == 1   # no model parallel needed

    def test_big_model_needs_model_parallelism(self):
        from paddle_tpu.distributed.auto_parallel import plan_mesh
        ranked = plan_mesh(self._stats(), n_devices=64, batch=64,
                           hbm_bytes=16e9)
        best = ranked[0]
        assert best.feasible, best.rationale
        # 6.7B bf16 + f32 AdamW state cannot fit replicated in 16 GB
        assert best.mp * best.pp * best.sharding > 1
        assert best.dp * best.mp * best.pp * best.sharding == 64

    def test_memory_infeasible_plans_ranked_out(self):
        from paddle_tpu.distributed.auto_parallel import plan_mesh
        ranked = plan_mesh(self._stats(), n_devices=8, batch=8,
                           hbm_bytes=16e9)
        for c in ranked:
            if c.feasible:
                # every feasible plan really fits
                assert c.mem_bytes <= 16e9
        # the fully replicated layout must be infeasible for 6.7B
        rep = [c for c in plan_mesh(self._stats(), 8, 8, hbm_bytes=16e9)
               if c.mp == c.pp == c.sharding == 1]
        assert not rep or not rep[0].feasible

    def test_pp_requires_divisible_layers(self):
        from paddle_tpu.distributed.auto_parallel import (plan_mesh,
                                                          ModelStats)
        st = ModelStats(n_params=1_000_000, n_layers=7, hidden=64)
        for c in plan_mesh(st, n_devices=8, batch=8):
            assert c.pp == 1 or 7 % c.pp == 0
