"""auto_parallel: ProcessMesh, shard_tensor, Engine (reference analog:
python/paddle/fluid/tests/unittests/auto_parallel/). Runs on the 8-device
CPU mesh from conftest."""
import numpy as np
import pytest
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import (ProcessMesh, shard_tensor, reshard,
                                    unshard_dtensor, get_dist_attr)
from paddle_tpu.distributed.auto_parallel import Engine, Strategy


def make_mesh():
    n = len(jax.devices())
    return ProcessMesh(np.arange(n).reshape(2, n // 2),
                       dim_names=["x", "y"])


def test_process_mesh_basics():
    pm = make_mesh()
    assert pm.ndim == 2
    assert pm.dim_names == ["x", "y"]
    assert pm.get_dim_size("x") == 2
    jm = pm.jax_mesh()
    assert jm.axis_names == ("x", "y")
    assert pm == make_mesh()
    assert len({pm, make_mesh()}) == 1


def test_shard_tensor_places_data():
    pm = make_mesh()
    x = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(8, 4))
    sx = shard_tensor(x, pm, ["x", None])
    spec = sx._value.sharding.spec
    assert tuple(spec)[0] == "x"
    attr = get_dist_attr(sx)
    assert attr[0] == pm and attr[1] == ["x", None]
    # values unchanged
    np.testing.assert_allclose(np.asarray(sx._value), np.arange(32).reshape(8, 4))


def test_shard_tensor_context_mesh_and_reshard():
    pm = make_mesh()
    with pm:
        x = shard_tensor(paddle.ones([8, 8]), shard_spec=["x", "y"])
    assert get_dist_attr(x)[1] == ["x", "y"]
    y = reshard(x, pm, ["y", None])
    assert tuple(y._value.sharding.spec)[0] == "y"
    z = unshard_dtensor(y)
    assert z._value.sharding.is_fully_replicated
    np.testing.assert_allclose(z.numpy(), np.ones((8, 8)))


def test_shard_tensor_bad_axis():
    pm = make_mesh()
    with pytest.raises(ValueError):
        shard_tensor(paddle.ones([4]), pm, ["nope"])


def test_shard_tensor_under_jit_constraint():
    pm = make_mesh()

    def f(v):
        t = paddle.Tensor(v, stop_gradient=True)
        s = shard_tensor(t, pm, ["x", None])
        return (s * 2)._value

    out = jax.jit(f)(np.ones((8, 4), np.float32))
    np.testing.assert_allclose(np.asarray(out), 2 * np.ones((8, 4)))


def test_engine_fit_and_evaluate():
    paddle.seed(0)
    n = len(jax.devices())
    pm = ProcessMesh(np.arange(n), dim_names=["data"])

    class DS(paddle.io.Dataset):
        def __init__(self):
            rng = np.random.default_rng(0)
            self.x = rng.standard_normal((64, 8)).astype(np.float32)
            w = rng.standard_normal((8, 1)).astype(np.float32)
            self.y = self.x @ w

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return 64

    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    engine = Engine(model, loss=nn.MSELoss(), optimizer=opt,
                    strategy=Strategy(), process_mesh=pm)
    hist = engine.fit(DS(), epochs=3, batch_size=16, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    res = engine.evaluate(DS(), batch_size=16)
    assert res["loss"] is not None and np.isfinite(res["loss"])


def test_engine_tp_annotation():
    """Megatron-style col/row sharding annotated via shard_tensor; GSPMD
    completes the rest (reference: dist_matmul rules)."""
    paddle.seed(0)
    n = len(jax.devices())
    pm = ProcessMesh(np.arange(n).reshape(1, n), dim_names=["data", "model"])

    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 1))
    # column-parallel first weight, row-parallel second
    shard_tensor(model[0].weight, pm, [None, "model"])
    shard_tensor(model[2].weight, pm, ["model", None])
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())

    class DS(paddle.io.Dataset):
        def __init__(self):
            rng = np.random.default_rng(1)
            self.x = rng.standard_normal((32, 8)).astype(np.float32)
            self.y = self.x.sum(-1, keepdims=True).astype(np.float32)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return 32

    st = Strategy({"dataset": {"batch_dim": "data"}})
    engine = Engine(model, loss=nn.MSELoss(), optimizer=opt, strategy=st,
                    process_mesh=pm)
    hist = engine.fit(DS(), epochs=4, batch_size=32, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    # the parameter kept its annotation through training
    assert tuple(model[0].weight._value.sharding.spec)[-1] == "model"


class TestMeshPlanner:
    """Cost-model mesh planner (reference analog: auto_parallel
    planner_v2.py + cost_model.py)."""

    def _stats(self):
        from paddle_tpu.distributed.auto_parallel import gpt_stats
        from paddle_tpu.incubate.models import gpt3_6p7b
        return gpt_stats(gpt3_6p7b())

    def test_small_model_prefers_pure_dp(self):
        from paddle_tpu.distributed.auto_parallel import (plan_mesh,
                                                          ModelStats)
        st = ModelStats(n_params=10_000_000, n_layers=12, hidden=768,
                        seq_len=512)
        best = plan_mesh(st, n_devices=8, batch=64, hbm_bytes=16e9)[0]
        assert best.feasible
        assert best.mp == 1 and best.pp == 1   # no model parallel needed

    def test_big_model_needs_model_parallelism(self):
        from paddle_tpu.distributed.auto_parallel import plan_mesh
        ranked = plan_mesh(self._stats(), n_devices=64, batch=64,
                           hbm_bytes=16e9)
        best = ranked[0]
        assert best.feasible, best.rationale
        # 6.7B bf16 + f32 AdamW state cannot fit replicated in 16 GB
        assert best.mp * best.pp * best.sharding > 1
        assert best.dp * best.mp * best.pp * best.sharding == 64

    def test_memory_infeasible_plans_ranked_out(self):
        from paddle_tpu.distributed.auto_parallel import plan_mesh
        ranked = plan_mesh(self._stats(), n_devices=8, batch=8,
                           hbm_bytes=16e9)
        for c in ranked:
            if c.feasible:
                # every feasible plan really fits
                assert c.mem_bytes <= 16e9
        # the fully replicated layout must be infeasible for 6.7B
        rep = [c for c in plan_mesh(self._stats(), 8, 8, hbm_bytes=16e9)
               if c.mp == c.pp == c.sharding == 1]
        assert not rep or not rep[0].feasible

    def test_pp_requires_divisible_layers(self):
        from paddle_tpu.distributed.auto_parallel import (plan_mesh,
                                                          ModelStats)
        st = ModelStats(n_params=1_000_000, n_layers=7, hidden=64)
        for c in plan_mesh(st, n_devices=8, batch=8):
            assert c.pp == 1 or 7 % c.pp == 0


class TestCompletion:
    """Parameter-graph sharding completion from PARTIAL annotations
    (reference analog: auto_parallel/completion.py propagating DistAttrs;
    here Megatron pairing over the parameter graph, GSPMD finishing the
    intermediates)."""

    def _mesh(self):
        n = len(jax.devices())
        return ProcessMesh(np.arange(n).reshape(1, n),
                           dim_names=["data", "model"])

    def test_column_mark_completes_row_partner(self):
        from paddle_tpu.distributed.auto_parallel import \
            complete_model_sharding
        paddle.seed(0)
        pm = self._mesh()
        model = nn.Sequential(nn.Linear(8, 32), nn.GELU(),
                              nn.Linear(32, 8), nn.LayerNorm(8))
        # the ONLY user annotation: column-parallel first weight
        shard_tensor(model[0].weight, pm, [None, "model"])
        decisions = complete_model_sharding(model, pm)
        # bias of the column linear follows the axis
        assert tuple(model[0].bias._value.sharding.spec) == ("model",)
        # the next linear completes ROW-parallel
        assert tuple(model[2].weight._value.sharding.spec)[0] == "model"
        # its bias and the LayerNorm complete replicated
        for p in [model[2].bias, model[3].weight, model[3].bias]:
            spec = p._value.sharding.spec
            assert all(s is None for s in spec), spec
        assert len(decisions) == len(list(model.parameters()))

    def test_completion_idempotent_on_annotated(self):
        from paddle_tpu.distributed.auto_parallel import \
            complete_model_sharding
        paddle.seed(0)
        pm = self._mesh()
        model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 8))
        shard_tensor(model[0].weight, pm, [None, "model"])
        shard_tensor(model[2].weight, pm, ["model", None])
        complete_model_sharding(model, pm)
        assert tuple(model[0].weight._value.sharding.spec)[-1] == "model"
        assert tuple(model[2].weight._value.sharding.spec)[0] == "model"

    def test_engine_fit_with_partial_annotation_matches_full(self):
        """Engine.fit on a NON-GPT model where only the first weight is
        annotated: completion must produce the same training trajectory as
        the fully-annotated Megatron layout."""
        def run(annotate_all):
            paddle.seed(0)
            n = len(jax.devices())
            pm = ProcessMesh(np.arange(n).reshape(1, n),
                             dim_names=["data", "model"])
            model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(),
                                  nn.Linear(32, 1))
            shard_tensor(model[0].weight, pm, [None, "model"])
            if annotate_all:
                shard_tensor(model[0].bias, pm, ["model"])
                shard_tensor(model[2].weight, pm, ["model", None])

            class DS(paddle.io.Dataset):
                def __init__(self):
                    rng = np.random.default_rng(1)
                    self.x = rng.standard_normal((32, 8)).astype(np.float32)
                    self.y = self.x.sum(-1, keepdims=True).astype(np.float32)

                def __getitem__(self, i):
                    return self.x[i], self.y[i]

                def __len__(self):
                    return 32

            opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                        parameters=model.parameters())
            st = Strategy({"dataset": {"batch_dim": "data"}})
            engine = Engine(model, loss=nn.MSELoss(), optimizer=opt,
                            strategy=st, process_mesh=pm)
            hist = engine.fit(DS(), epochs=3, batch_size=32, verbose=0)
            return hist["loss"], model

        partial_losses, pmodel = run(annotate_all=False)
        full_losses, _ = run(annotate_all=True)
        np.testing.assert_allclose(partial_losses, full_losses,
                                   rtol=1e-5, atol=1e-6)
        assert partial_losses[-1] < partial_losses[0]
        # completion actually placed the row partner
        assert tuple(pmodel[2].weight._value.sharding.spec)[0] == "model"


class TestPlannerValidation:
    """The planner's analytic ordering vs MEASURED step times on the
    virtual mesh (VERDICT round-3 item 4: relative ordering, not absolute;
    the virtual CPU mesh timeshares cores, so only well-separated pairs are
    asserted)."""

    @pytest.mark.slow
    def test_planner_ordering_matches_measured(self):
        """2 configs x 1 round (~1-2 min on a loaded box): a wall-time
        measurement, so it lives in the opt-in slow tier — with the
        formerly shard_map-blocked SPMD suites now running, tier-1 has no
        budget for in-suite benchmarking. The full validation (3 configs x
        2 interleaved rounds, ~10 min) is the variant below."""
        self._planner_ordering(full=False)

    @pytest.mark.slow
    def test_planner_ordering_matches_measured_full(self):
        """Opt-in: `pytest -m slow` (deselected by default via addopts)."""
        self._planner_ordering(full=True)

    def _planner_ordering(self, full):
        import time
        import jax.numpy as jnp
        from paddle_tpu.distributed.auto_parallel import (plan_mesh,
                                                          gpt_stats)
        from paddle_tpu.distributed.mesh import build_mesh, set_global_mesh
        from paddle_tpu.distributed.fleet.meta_parallel import \
            PipelineTrainStep
        from paddle_tpu.incubate.models import (
            GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
            gpt_pipeline_layers, shard_gpt)
        from paddle_tpu.jit import TrainStep

        # compute-dominant workload: the virtual mesh cannot price real ICI
        # traffic, so the validation regime is one where both the analytic
        # model and the measurement agree compute/overheads dominate
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_hidden_layers=4,
                        num_attention_heads=4, intermediate_size=128,
                        max_position_embeddings=512, hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0,
                        use_flash_attention=False)
        batch, seq, steps = 32, 512, 2
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 256, (batch, seq)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 256, (batch, seq)), jnp.int32)

        def measure(dp, mp, pp):
            mesh = build_mesh(dp=dp, pp=pp, sharding=1, sep=1, mp=mp,
                              devices=jax.devices()[:8])
            set_global_mesh(mesh)
            paddle.seed(0)
            model = GPTForCausalLM(cfg)
            if mp > 1:
                shard_gpt(model, mesh)
            opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                         parameters=model.parameters())
            crit = GPTPretrainingCriterion()
            if pp > 1:
                step = PipelineTrainStep(gpt_pipeline_layers(model), crit,
                                         opt, mesh=mesh, num_microbatches=pp)
            else:
                step = TrainStep(model, lambda o, y: crit(o, y), opt)
            x = paddle.Tensor(ids, stop_gradient=True)
            y = paddle.Tensor(labels, stop_gradient=True)
            float(step(x, y))                 # compile
            t0 = time.perf_counter()
            for _ in range(steps):
                l = step(x, y)
            float(l)
            return (time.perf_counter() - t0) / steps

        configs = [(8, 1, 1), (2, 4, 1), (4, 1, 2)] if full else \
            [(8, 1, 1), (2, 4, 1)]
        # min over interleaved rounds: a CPU burst during one config's
        # window (CI contention) must not poison its estimate
        measured = {c: measure(*c) for c in configs}
        if full:
            for c in configs:
                measured[c] = min(measured[c], measure(*c))

        stats = gpt_stats(cfg, seq_len=seq)
        ranked = plan_mesh(stats, n_devices=8, batch=batch,
                           micro_batches=2)
        cost = {(c.dp, c.mp, c.pp): c.cost for c in ranked}
        planned = {c: cost[c] for c in configs}

        # argmin agreement: the planner picks the config that actually
        # measures fastest — asserted only when the measurement is
        # decisive (>1.3x over the runner-up) so scheduler noise on the
        # timeshared CPU mesh can't flip the test
        best_measured = min(measured, key=measured.get)
        best_planned = min(planned, key=planned.get)
        runner_up = sorted(measured.values())[1]
        if runner_up > 2.0 * measured[best_measured]:
            assert best_planned == best_measured, (measured, planned)
        # pairwise agreement wherever the measured separation is decisive
        # (2x: anything tighter is scheduler noise on a timeshared mesh)
        for a in configs:
            for b in configs:
                if measured[a] > 2.0 * measured[b]:
                    assert planned[a] > planned[b], \
                        (a, b, measured, planned)


class TestCompletionEdgeCases:
    """Regressions from review: short shard_specs, user-pinned replication
    closing the Megatron pair, and annotation-mesh preference."""

    def test_short_spec_annotation_pads(self):
        from paddle_tpu.distributed.auto_parallel import \
            complete_model_sharding
        paddle.seed(0)
        n = len(jax.devices())
        pm = ProcessMesh(np.arange(n).reshape(1, n),
                         dim_names=["data", "model"])
        model = nn.Sequential(nn.Linear(8, 16), nn.Linear(16, 8))
        # spec shorter than ndim — shard_tensor accepts it; completion
        # must pad, not crash
        shard_tensor(model[0].weight, pm, ["model"])
        complete_model_sharding(model, pm)
        assert tuple(model[0].weight._value.sharding.spec)[0] == "model"

    def test_pinned_replication_closes_pair(self):
        from paddle_tpu.distributed.auto_parallel import \
            complete_model_sharding
        paddle.seed(0)
        n = len(jax.devices())
        pm = ProcessMesh(np.arange(n).reshape(1, n),
                         dim_names=["data", "model"])
        model = nn.Sequential(nn.Linear(8, 16), nn.Linear(16, 16),
                              nn.Linear(16, 8))
        shard_tensor(model[0].weight, pm, [None, "model"])   # column mark
        shard_tensor(model[1].weight, pm, [None, None])      # user pin
        complete_model_sharding(model, pm)
        # the pinned layer closed the pair: layer 2 completes REPLICATED,
        # the carried axis must not leak onto it
        spec = tuple(model[2].weight._value.sharding.spec)
        assert all(s is None for s in spec), spec

    def test_engine_uses_annotation_mesh(self):
        """Engine built WITHOUT process_mesh while the marks reference a
        2-D mesh: completion must run on the annotations' mesh, not the
        Engine's 1-D fallback."""
        paddle.seed(0)
        n = len(jax.devices())
        pm = ProcessMesh(np.arange(n).reshape(1, n),
                         dim_names=["data", "model"])
        model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(),
                              nn.Linear(32, 1))
        shard_tensor(model[0].weight, pm, [None, "model"])

        class DS(paddle.io.Dataset):
            def __init__(self):
                rng = np.random.default_rng(1)
                self.x = rng.standard_normal((16, 8)).astype(np.float32)
                self.y = self.x.sum(-1, keepdims=True).astype(np.float32)

            def __getitem__(self, i):
                return self.x[i], self.y[i]

            def __len__(self):
                return 16

        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=model.parameters())
        engine = Engine(model, loss=nn.MSELoss(), optimizer=opt)
        hist = engine.fit(DS(), epochs=2, batch_size=16, verbose=0)
        assert np.isfinite(hist["loss"][-1])
        assert tuple(model[2].weight._value.sharding.spec)[0] == "model"


class TestCompletionPatterns:
    """Completion beyond Linear/Embedding pairs: fused-qkv attention,
    conv channel pairing, MoE expert banks (round-4 verdict item 5)."""

    def _mesh(self):
        n = len(jax.devices())
        return ProcessMesh(np.arange(n).reshape(1, n),
                           dim_names=["data", "model"])

    def test_fused_qkv_attention_completes_head_parallel(self):
        from paddle_tpu.distributed.auto_parallel import \
            complete_model_sharding
        from paddle_tpu.incubate.nn import FusedMultiHeadAttention
        paddle.seed(0)
        pm = self._mesh()
        attn = FusedMultiHeadAttention(embed_dim=64, num_heads=8)
        # the ONLY mark: qkv_weight [3, H, D, h] on the heads dim
        shard_tensor(attn.qkv_weight, pm, [None, "model", None, None])
        complete_model_sharding(attn, pm)
        assert tuple(attn.qkv_bias._value.sharding.spec)[1] == "model"
        # out projection completes ROW-parallel
        assert tuple(attn.linear_weight._value.sharding.spec)[0] == "model"
        for p in [attn.linear_bias, attn.ln_scale, attn.ln_bias]:
            assert all(s is None for s in p._value.sharding.spec)

    def test_fused_ffn_completes_row_partner(self):
        from paddle_tpu.distributed.auto_parallel import \
            complete_model_sharding
        from paddle_tpu.incubate.nn import FusedFeedForward
        paddle.seed(0)
        pm = self._mesh()
        ffn = FusedFeedForward(d_model=16, dim_feedforward=64)
        shard_tensor(ffn.linear1_weight, pm, [None, "model"])
        complete_model_sharding(ffn, pm)
        assert tuple(ffn.linear1_bias._value.sharding.spec) == ("model",)
        assert tuple(ffn.linear2_weight._value.sharding.spec)[0] == "model"
        assert all(s is None
                   for s in ffn.linear2_bias._value.sharding.spec)

    def test_conv_tower_channel_pairing(self):
        from paddle_tpu.distributed.auto_parallel import \
            complete_model_sharding
        paddle.seed(0)
        pm = self._mesh()
        model = nn.Sequential(nn.Conv2D(3, 16, 3), nn.ReLU(),
                              nn.Conv2D(16, 8, 3))
        # mark the FIRST conv out-channel-parallel
        shard_tensor(model[0].weight, pm, ["model", None, None, None])
        complete_model_sharding(model, pm)
        assert tuple(model[0].bias._value.sharding.spec) == ("model",)
        # next conv completes IN-channel-sharded (dim 1), closing the pair
        spec2 = tuple(model[2].weight._value.sharding.spec)
        assert spec2[1] == "model" and spec2[0] is None
        assert all(s is None for s in model[2].bias._value.sharding.spec)

    def test_conv_tower_forward_matches_replicated(self):
        """The completed channel-pair placement must be numerically
        invisible: GSPMD inserts the psum."""
        from paddle_tpu.distributed.auto_parallel import \
            complete_model_sharding
        paddle.seed(0)
        model = nn.Sequential(nn.Conv2D(3, 16, 3), nn.ReLU(),
                              nn.Conv2D(16, 8, 3))
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(2, 3, 12, 12))
            .astype(np.float32))
        ref = model(x).numpy()
        pm = self._mesh()
        shard_tensor(model[0].weight, pm, ["model", None, None, None])
        complete_model_sharding(model, pm)
        np.testing.assert_allclose(model(x).numpy(), ref,
                                   rtol=2e-5, atol=2e-5)

    def test_moe_expert_bank_completes_on_expert_axis(self):
        from paddle_tpu.distributed.auto_parallel import \
            complete_model_sharding
        from paddle_tpu.incubate.distributed.models.moe import MoELayer
        paddle.seed(0)
        pm = self._mesh()
        moe = MoELayer(d_model=16, d_hidden=32, num_experts=8,
                       moe_axis="model")
        # one mark: w1 [E, d, ff] on the expert dim
        shard_tensor(moe.w1, pm, ["model", None, None])
        complete_model_sharding(moe, pm)
        for p in [moe.b1, moe.w2, moe.b2]:
            assert tuple(p._value.sharding.spec)[0] == "model", p.name
        # the gate stays replicated
        assert all(s is None
                   for s in moe.gate_weight._value.sharding.spec)


class TestMeasuringTuner:
    """Reference analog: auto_parallel/tuner/parallel_tuner.py — the tuner
    must pick the MEASURED best, not the analytic best. (Also hosts three
    completion-regression tests appended from review findings.)"""

    def _mesh(self):
        n = len(jax.devices())
        return ProcessMesh(np.arange(n).reshape(1, n),
                           dim_names=["data", "model"])

    def test_measurement_overrides_analytic_rank(self):
        """When the injected measurements say analytic rank-2 is faster,
        the tuner chooses it."""
        from paddle_tpu.distributed.auto_parallel import (gpt_stats,
                                                          tune_mesh)
        from paddle_tpu.incubate.models import GPTConfig
        cfg = GPTConfig(vocab_size=256, hidden_size=64,
                        num_hidden_layers=4, num_attention_heads=4,
                        intermediate_size=128, max_position_embeddings=128)
        stats = gpt_stats(cfg, seq_len=128)
        ranked_order = []

        def fake_measure(choice):
            ranked_order.append(choice)
            # rank-2 (the second candidate trialed) measures fastest
            return 0.5 if len(ranked_order) == 2 else 1.0

        report = tune_mesh(stats, n_devices=8, batch=32,
                           measure_fn=fake_measure, top_k=3)
        assert len(report.candidates) == 3
        second = report.candidates[1]
        assert (report.best.dp, report.best.mp, report.best.pp,
                report.best.sharding) == (second.dp, second.mp,
                                          second.pp, second.sharding)
        assert report.measurement_changed_plan

    def test_agreement_keeps_analytic_best(self):
        from paddle_tpu.distributed.auto_parallel import (gpt_stats,
                                                          tune_mesh)
        from paddle_tpu.incubate.models import GPTConfig
        cfg = GPTConfig(vocab_size=256, hidden_size=64,
                        num_hidden_layers=4, num_attention_heads=4,
                        intermediate_size=128, max_position_embeddings=128)
        stats = gpt_stats(cfg, seq_len=128)
        costs = iter([0.1, 0.5, 0.9])

        def fake_measure(choice):
            return next(costs)

        report = tune_mesh(stats, n_devices=8, batch=32,
                           measure_fn=fake_measure, top_k=3)
        assert not report.measurement_changed_plan

    def test_rounds_take_min(self):
        from paddle_tpu.distributed.auto_parallel import (gpt_stats,
                                                          tune_mesh)
        from paddle_tpu.incubate.models import GPTConfig
        cfg = GPTConfig(vocab_size=256, hidden_size=64,
                        num_hidden_layers=4, num_attention_heads=4,
                        intermediate_size=128, max_position_embeddings=128)
        stats = gpt_stats(cfg, seq_len=128)
        calls = {}

        def fake_measure(choice):
            k = (choice.dp, choice.mp, choice.pp, choice.sharding)
            calls[k] = calls.get(k, 0) + 1
            return 1.0 / calls[k]        # later rounds measure faster

        report = tune_mesh(stats, n_devices=8, batch=32,
                           measure_fn=fake_measure, top_k=2, rounds=2)
        assert all(v == 2 for v in calls.values())
        assert all(t == 0.5 for t in report.measured_s.values())

    def test_real_compile_and_time_top2(self):
        """End-to-end: the tuner compiles and times the top-2 plans of a
        tiny GPT on the live virtual mesh and returns a measured winner."""
        from paddle_tpu.distributed.auto_parallel import (gpt_stats,
                                                          tune_mesh,
                                                          gpt_measure_fn)
        from paddle_tpu.incubate.models import GPTConfig
        cfg = GPTConfig(vocab_size=128, hidden_size=32,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=64, max_position_embeddings=64,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0,
                        use_flash_attention=False)
        stats = gpt_stats(cfg, seq_len=64)
        report = tune_mesh(stats, n_devices=8, batch=16,
                           measure_fn=gpt_measure_fn(cfg, batch=16, seq=64,
                                                     steps=1),
                           top_k=2)
        assert len(report.measured_s) == 2
        assert all(t > 0 for t in report.measured_s.values())
        key = (report.best.dp, report.best.mp, report.best.pp,
               report.best.sharding)
        assert report.measured_s[key] == min(report.measured_s.values())

    def test_fused_ffn_square_dims_keep_norms_replicated(self):
        """d_model == dim_feedforward: ln params share linear1_bias's shape
        but must stay replicated (review regression)."""
        from paddle_tpu.distributed.auto_parallel import \
            complete_model_sharding
        from paddle_tpu.incubate.nn import FusedFeedForward
        paddle.seed(0)
        pm = self._mesh()
        ffn = FusedFeedForward(d_model=64, dim_feedforward=64)
        shard_tensor(ffn.linear1_weight, pm, [None, "model"])
        complete_model_sharding(ffn, pm)
        assert tuple(ffn.linear1_bias._value.sharding.spec) == ("model",)
        assert tuple(ffn.linear2_weight._value.sharding.spec)[0] == "model"
        for n, p in ffn.named_parameters():
            if "ln" in n or n.endswith("linear2_bias"):
                assert all(s is None for s in p._value.sharding.spec), n

    def test_moe_gate_replicated_when_dmodel_equals_experts(self):
        """d_model == num_experts: the gate's leading dim collides with E
        but must stay replicated (review regression)."""
        from paddle_tpu.distributed.auto_parallel import \
            complete_model_sharding
        from paddle_tpu.incubate.distributed.models.moe import MoELayer
        paddle.seed(0)
        pm = self._mesh()
        moe = MoELayer(d_model=8, d_hidden=32, num_experts=8,
                       moe_axis="model")
        shard_tensor(moe.w1, pm, ["model", None, None])
        complete_model_sharding(moe, pm)
        assert all(s is None
                   for s in moe.gate_weight._value.sharding.spec)
        assert tuple(moe.w2._value.sharding.spec)[0] == "model"

    def test_conv_transpose_channel_dims_swapped(self):
        """Conv2DTranspose stores [in_c, out_c, kh, kw]: an out-channel
        mark is dim 1 and the pairing must respect it."""
        from paddle_tpu.distributed.auto_parallel import \
            complete_model_sharding
        paddle.seed(0)
        pm = self._mesh()
        model = nn.Sequential(nn.Conv2DTranspose(3, 16, 3), nn.ReLU(),
                              nn.Conv2D(16, 8, 3))
        shard_tensor(model[0].weight, pm, [None, "model", None, None])
        complete_model_sharding(model, pm)
        assert tuple(model[0].bias._value.sharding.spec) == ("model",)
        spec2 = tuple(model[2].weight._value.sharding.spec)
        assert spec2[1] == "model" and spec2[0] is None

    def test_engine_tune_installs_winning_mesh(self):
        """Engine.tune trials plans and installs the measured winner's
        mesh for the next fit (reference Engine._tune analog)."""
        from paddle_tpu.distributed.auto_parallel import (Engine, Strategy,
                                                          gpt_stats)
        from paddle_tpu.incubate.models import GPTConfig
        cfg = GPTConfig(vocab_size=256, hidden_size=64,
                        num_hidden_layers=4, num_attention_heads=4,
                        intermediate_size=128, max_position_embeddings=128)
        stats = gpt_stats(cfg, seq_len=128)
        st = Strategy()
        st.tuning.enable = True
        engine = Engine(model=nn.Linear(4, 4), loss=nn.MSELoss(),
                        strategy=st)
        calls = []

        def fake_measure(choice):
            calls.append(choice)
            return 0.1 if len(calls) == 3 else 1.0   # 3rd candidate wins

        report = engine.tune(stats, batch=32, measure_fn=fake_measure,
                             n_devices=8)
        assert len(calls) == 3
        b = report.best
        third = report.candidates[2]
        assert (b.dp, b.mp, b.pp, b.sharding) == \
            (third.dp, third.mp, third.pp, third.sharding)
        pm = engine._process_mesh
        assert pm is not None
        assert int(np.prod(pm.shape)) == 8
        assert "model" in pm.dim_names
