"""MoE layer: gates, capacity semantics, expert-parallel all-to-all path.

Reference analog: the reference's MoE tests exercise MoELayer with
gshard/switch gates over global_scatter/global_gather
(incubate/distributed/models/moe/); here the expert exchange is
jax.lax.all_to_all over a mesh axis, validated against the dense local path.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

import paddle_tpu as paddle
from paddle_tpu.framework.core import Tensor
from paddle_tpu.incubate.distributed.models.moe import (
    MoELayer, top1_dispatch, top2_dispatch)

M, H, E = 16, 32, 8


def test_top1_dispatch_shapes_and_mass():
    gates = jax.nn.softmax(
        jnp.asarray(np.random.default_rng(0).standard_normal((24, E)),
                    jnp.float32))
    disp, comb, aux = top1_dispatch(gates, capacity=8)
    assert disp.shape == (24, E, 8) and comb.shape == (24, E, 8)
    # capacity 8*E >= 24 tokens: nothing dropped, every token dispatched once
    np.testing.assert_allclose(np.asarray(jnp.sum(disp)), 24.0, rtol=1e-6)
    assert float(aux) > 0


def test_top2_dispatch_two_slots_normalized():
    gates = jax.nn.softmax(
        jnp.asarray(np.random.default_rng(1).standard_normal((16, E)),
                    jnp.float32))
    disp, comb, aux = top2_dispatch(gates, capacity=16)
    # every token lands in exactly two expert slots, combine sums to 1
    np.testing.assert_allclose(np.asarray(jnp.sum(disp, axis=(1, 2))), 2.0,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(jnp.sum(comb, axis=(1, 2))), 1.0,
                               rtol=1e-5)


def test_capacity_overflow_drops_tokens():
    # all tokens prefer expert 0; capacity 2 keeps only the first two
    gates = jnp.tile(jnp.asarray([[0.9] + [0.1 / (E - 1)] * (E - 1)],
                                 jnp.float32), (10, 1))
    disp, comb, _ = top1_dispatch(gates, capacity=2)
    assert float(jnp.sum(disp)) == 2.0


@pytest.mark.parametrize("gate", ["gshard", "switch", "naive"])
def test_moe_forward_backward_local(gate):
    m = MoELayer(M, H, E, gate=gate)
    x = paddle.Tensor(np.random.default_rng(2).standard_normal(
        (2, 12, M)).astype("float32"), stop_gradient=False)
    y = m(x)
    assert y.shape == [2, 12, M]
    loss = (y ** 2).sum() + m.l_aux
    loss.backward()
    assert m.w1.grad is not None
    assert float((m.gate_weight.grad ** 2).sum().numpy()) > 0


def test_moe_expert_parallel_matches_dense():
    """4-way expert parallelism over the 'data' axis == dense computation
    when capacity is generous (no token drops)."""
    ep = 4
    m = MoELayer(M, H, E, gate="gshard", capacity_factor=8.0, eval_capacity_factor=8.0, moe_axis="data")
    m.eval()
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((ep * 2, 6, M)), jnp.float32)

    dense = m(paddle.Tensor(x, stop_gradient=True))._value

    wg = m.gate_weight._value
    w1, b1 = m.w1._value, m.b1._value
    w2, b2 = m.w2._value, m.b2._value
    mesh = Mesh(np.array(jax.devices()[:ep]), ("data",))

    def local(xs, wgs, w1s, b1s, w2s, b2s):
        mm = MoELayer(M, H, E, gate="gshard", capacity_factor=8.0,
                      eval_capacity_factor=8.0, moe_axis="data")
        mm.eval()
        for p, v in zip((mm.gate_weight, mm.w1, mm.b1, mm.w2, mm.b2),
                        (wgs, w1s, b1s, w2s, b2s)):
            p._value = v
        return mm(Tensor(xs, stop_gradient=True))._value

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P("data"), P(None, None), P("data"), P("data"),
                             P("data"), P("data")),
                   out_specs=P("data"))
    sharded = jax.jit(fn)(x, wg, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(dense),
                               rtol=1e-4, atol=1e-4)
