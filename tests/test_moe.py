"""MoE layer: gates, capacity semantics, expert-parallel all-to-all path.

Reference analog: the reference's MoE tests exercise MoELayer with
gshard/switch gates over global_scatter/global_gather
(incubate/distributed/models/moe/); here the expert exchange is
jax.lax.all_to_all over a mesh axis, validated against the dense local path.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

import paddle_tpu as paddle
from paddle_tpu.framework.core import Tensor
from paddle_tpu.incubate.distributed.models.moe import (
    MoELayer, top1_dispatch, top2_dispatch)

M, H, E = 16, 32, 8


def test_top1_dispatch_shapes_and_mass():
    gates = jax.nn.softmax(
        jnp.asarray(np.random.default_rng(0).standard_normal((24, E)),
                    jnp.float32))
    disp, comb, aux = top1_dispatch(gates, capacity=8)
    assert disp.shape == (24, E, 8) and comb.shape == (24, E, 8)
    # capacity 8*E >= 24 tokens: nothing dropped, every token dispatched once
    np.testing.assert_allclose(np.asarray(jnp.sum(disp)), 24.0, rtol=1e-6)
    assert float(aux) > 0


def test_top2_dispatch_two_slots_normalized():
    gates = jax.nn.softmax(
        jnp.asarray(np.random.default_rng(1).standard_normal((16, E)),
                    jnp.float32))
    disp, comb, aux = top2_dispatch(gates, capacity=16)
    # every token lands in exactly two expert slots, combine sums to 1
    np.testing.assert_allclose(np.asarray(jnp.sum(disp, axis=(1, 2))), 2.0,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(jnp.sum(comb, axis=(1, 2))), 1.0,
                               rtol=1e-5)


def test_capacity_overflow_drops_tokens():
    # all tokens prefer expert 0; capacity 2 keeps only the first two
    gates = jnp.tile(jnp.asarray([[0.9] + [0.1 / (E - 1)] * (E - 1)],
                                 jnp.float32), (10, 1))
    disp, comb, _ = top1_dispatch(gates, capacity=2)
    assert float(jnp.sum(disp)) == 2.0


@pytest.mark.parametrize("gate", ["gshard", "switch", "naive"])
def test_moe_forward_backward_local(gate):
    m = MoELayer(M, H, E, gate=gate)
    x = paddle.Tensor(np.random.default_rng(2).standard_normal(
        (2, 12, M)).astype("float32"), stop_gradient=False)
    y = m(x)
    assert y.shape == [2, 12, M]
    loss = (y ** 2).sum() + m.l_aux
    loss.backward()
    assert m.w1.grad is not None
    assert float((m.gate_weight.grad ** 2).sum().numpy()) > 0


def test_moe_expert_parallel_matches_dense():
    """4-way expert parallelism over the 'data' axis == dense computation
    when capacity is generous (no token drops)."""
    ep = 4
    m = MoELayer(M, H, E, gate="gshard", capacity_factor=8.0, eval_capacity_factor=8.0, moe_axis="data")
    m.eval()
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((ep * 2, 6, M)), jnp.float32)

    dense = m(paddle.Tensor(x, stop_gradient=True))._value

    wg = m.gate_weight._value
    w1, b1 = m.w1._value, m.b1._value
    w2, b2 = m.w2._value, m.b2._value
    mesh = Mesh(np.array(jax.devices()[:ep]), ("data",))

    def local(xs, wgs, w1s, b1s, w2s, b2s):
        mm = MoELayer(M, H, E, gate="gshard", capacity_factor=8.0,
                      eval_capacity_factor=8.0, moe_axis="data")
        mm.eval()
        for p, v in zip((mm.gate_weight, mm.w1, mm.b1, mm.w2, mm.b2),
                        (wgs, w1s, b1s, w2s, b2s)):
            p._value = v
        return mm(Tensor(xs, stop_gradient=True))._value

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P("data"), P(None, None), P("data"), P("data"),
                             P("data"), P("data")),
                   out_specs=P("data"))
    sharded = jax.jit(fn)(x, wg, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(dense),
                               rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# PR 16 tentpole (b): the MoE dispatch rides the promotion funnel — the
# gate fn is stamped via dispatch.mark_collective, so gshard/switch MoE
# keys by (gate, d_model, axis, capacity, mesh) instead of poisoning
# every cycle as collective_unkeyed.
# ---------------------------------------------------------------------------
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops.dispatch import clear_dispatch_cache
from paddle_tpu.profiler import (reset_step_fusion_stats,
                                 step_fusion_stats)

_FUNNEL_FLAGS = {
    "FLAGS_eager_op_cache": True,
    "FLAGS_eager_op_cache_size": 512,
    "FLAGS_eager_chain_fusion": True,
    "FLAGS_eager_chain_fusion_min_count": 3,
    "FLAGS_eager_chain_cache_size": 128,
    "FLAGS_eager_step_fusion": True,
    "FLAGS_eager_step_fusion_min_count": 4,
    "FLAGS_eager_step_fusion_cache_size": 8,
}


@pytest.fixture
def funnel():
    set_flags(dict(_FUNNEL_FLAGS))
    clear_dispatch_cache()
    reset_step_fusion_stats()
    yield
    set_flags(dict(_FUNNEL_FLAGS))
    clear_dispatch_cache()
    reset_step_fusion_stats()


def _moe_train(fused, gate, n=12, cf=4.0, seed=5):
    set_flags({"FLAGS_eager_step_fusion": fused,
               "FLAGS_eager_chain_fusion": fused,
               "FLAGS_eager_op_cache": fused})
    clear_dispatch_cache()
    paddle.seed(seed)
    rng = np.random.default_rng(seed)
    x = paddle.to_tensor(
        rng.standard_normal((4, 12, M)).astype(np.float32))
    m = MoELayer(M, H, E, gate=gate, capacity_factor=cf,
                 eval_capacity_factor=cf)
    m.train()
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=m.parameters())
    losses = []
    for _ in range(n):
        y = m(x)
        loss = paddle.mean(paddle.multiply(y, y)) + 0.01 * m.l_aux
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    # snapshot BEFORE the trailing eval forward: that read escapes the
    # then-pending cycle by design and would count one fallback split
    stats = dict(step_fusion_stats())
    return (np.asarray(losses), np.asarray(m(x)._value),
            np.asarray(m.gate_weight._value), stats)


@pytest.mark.parametrize("gate", ["gshard", "switch"])
def test_moe_funnel_parity(funnel, gate):
    """Fused-vs-eager training trajectories match at 8 experts; the
    gate promotes (steps_promoted ≥ 1) instead of poisoning as
    collective_unkeyed, and replays with zero fresh retraces."""
    eager_l, eager_y, eager_wg, _ = _moe_train(False, gate)
    fused_l, fused_y, fused_wg, s = _moe_train(True, gate)
    assert s["steps_promoted"] >= 1, s
    assert s["fused_steps"] >= 4, s
    assert s["fallback_splits"] == 0, s
    np.testing.assert_allclose(fused_l, eager_l, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(fused_y, eager_y, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(fused_wg, eager_wg, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("gate", ["gshard", "switch"])
def test_moe_capacity_overflow_drops_identical(funnel, gate):
    """Under a tight capacity factor the gate drops tokens; fused and
    eager agree on WHICH tokens drop (trajectory parity), and the
    drops are real (a generous-capacity run diverges)."""
    eager_l, eager_y, _, _ = _moe_train(False, gate, cf=0.5, seed=9)
    fused_l, fused_y, _, s = _moe_train(True, gate, cf=0.5, seed=9)
    assert s["steps_promoted"] >= 1, s
    np.testing.assert_allclose(fused_l, eager_l, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(fused_y, eager_y, rtol=1e-4, atol=1e-5)
    roomy_l, _, _, _ = _moe_train(False, gate, cf=8.0, seed=9)
    assert not np.allclose(roomy_l, eager_l, rtol=1e-5, atol=1e-7), \
        "capacity 0.5 dropped nothing — the overflow case is untested"


def test_moe_zero_steady_retraces(funnel):
    """After promotion at 8 experts, further steps replay the promoted
    cycle with ZERO fresh retraces — shapes and the stamped key are
    stable."""
    paddle.seed(5)
    rng = np.random.default_rng(5)
    x = paddle.to_tensor(
        rng.standard_normal((4, 12, M)).astype(np.float32))
    m = MoELayer(M, H, E, gate="gshard", capacity_factor=4.0,
                 eval_capacity_factor=4.0)
    m.train()
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=m.parameters())

    def step():
        y = m(x)
        loss = paddle.mean(paddle.multiply(y, y)) + 0.01 * m.l_aux
        loss.backward()
        opt.step()
        opt.clear_grad()

    for _ in range(10):
        step()
    s0 = dict(step_fusion_stats())
    assert s0["steps_promoted"] >= 1, s0
    assert s0["fallback_splits"] == 0, s0
    for _ in range(8):
        step()
    s1 = step_fusion_stats()
    assert s1["retraces"] == s0["retraces"], (s0, s1)
    assert s1["fallback_splits"] == 0, s1
    assert s1["fused_steps"] - s0["fused_steps"] == 8, (s0, s1)
