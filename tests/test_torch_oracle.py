"""Cross-framework numeric oracle: paddle_tpu ops vs torch CPU.

Reference analog: the OpTest methodology (unittests/op_test.py:333) checks
ops against NumPy references; for ops whose semantics are easy to get
subtly wrong (conv transpose padding, norm statistics, loss reductions,
attention masking), an independent full-framework oracle is stronger than
a hand-written NumPy model. torch (CPU) ships in the image and its op
semantics match the reference's (both follow the same conventions).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

torch = pytest.importorskip("torch")


def _np(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


def _close(a, b, rtol=2e-4, atol=2e-5):
    np.testing.assert_allclose(_np(a), b.detach().numpy(), rtol=rtol,
                               atol=atol)


RNG = np.random.default_rng(0)


def _pair(shape):
    x = RNG.normal(size=shape).astype(np.float32)
    return paddle.to_tensor(x), torch.tensor(x)


class TestConvOracle:
    def test_conv2d_strided_padded(self):
        x, tx = _pair((2, 3, 11, 11))
        w, tw = _pair((5, 3, 3, 3))
        b, tb = _pair((5,))
        got = F.conv2d(x, w, b, stride=2, padding=1)
        ref = torch.nn.functional.conv2d(tx, tw, tb, stride=2, padding=1)
        _close(got, ref)

    def test_conv2d_dilated_grouped(self):
        x, tx = _pair((1, 4, 13, 13))
        w, tw = _pair((8, 2, 3, 3))
        got = F.conv2d(x, w, stride=1, padding=2, dilation=2, groups=2)
        ref = torch.nn.functional.conv2d(tx, tw, padding=2, dilation=2,
                                         groups=2)
        _close(got, ref)

    def test_conv2d_transpose(self):
        x, tx = _pair((2, 4, 7, 7))
        w, tw = _pair((4, 6, 3, 3))
        got = F.conv2d_transpose(x, w, stride=2, padding=1,
                                 output_padding=1)
        ref = torch.nn.functional.conv_transpose2d(tx, tw, stride=2,
                                                   padding=1,
                                                   output_padding=1)
        _close(got, ref)

    def test_conv3d(self):
        x, tx = _pair((1, 2, 5, 6, 7))
        w, tw = _pair((4, 2, 3, 3, 3))
        got = F.conv3d(x, w, padding=1)
        ref = torch.nn.functional.conv3d(tx, tw, padding=1)
        _close(got, ref)

    def test_avg_and_max_pool2d(self):
        x, tx = _pair((2, 3, 10, 10))
        _close(F.max_pool2d(x, 3, stride=2, padding=1),
               torch.nn.functional.max_pool2d(tx, 3, stride=2, padding=1))
        _close(F.avg_pool2d(x, 2, stride=2),
               torch.nn.functional.avg_pool2d(tx, 2, stride=2))


class TestNormOracle:
    def test_layer_norm(self):
        x, tx = _pair((4, 6, 8))
        w, tw = _pair((8,))
        b, tb = _pair((8,))
        got = F.layer_norm(x, [8], weight=w, bias=b, epsilon=1e-5)
        ref = torch.nn.functional.layer_norm(tx, [8], tw, tb, eps=1e-5)
        _close(got, ref)

    def test_group_norm(self):
        x, tx = _pair((2, 8, 5, 5))
        w, tw = _pair((8,))
        b, tb = _pair((8,))
        got = F.group_norm(x, 4, weight=w, bias=b, epsilon=1e-5)
        ref = torch.nn.functional.group_norm(tx, 4, tw, tb, eps=1e-5)
        _close(got, ref)

    def test_instance_norm(self):
        x, tx = _pair((2, 3, 6, 6))
        got = F.instance_norm(x, eps=1e-5)
        ref = torch.nn.functional.instance_norm(tx, eps=1e-5)
        _close(got, ref)

    def test_batch_norm_eval(self):
        x, tx = _pair((4, 5, 3, 3))
        rm, trm = _pair((5,))
        rv = np.abs(RNG.normal(size=5)).astype(np.float32) + 0.5
        w, tw = _pair((5,))
        b, tb = _pair((5,))
        got = F.batch_norm(x, paddle.to_tensor(rm._value),
                           paddle.to_tensor(rv), weight=w, bias=b,
                           training=False, epsilon=1e-5)
        ref = torch.nn.functional.batch_norm(
            tx, trm, torch.tensor(rv), tw, tb, training=False, eps=1e-5)
        _close(got, ref)


class TestActivationLossOracle:
    def test_activations(self):
        x, tx = _pair((3, 17))
        _close(F.gelu(x), torch.nn.functional.gelu(tx), rtol=1e-3)
        _close(F.silu(x), torch.nn.functional.silu(tx))
        _close(F.elu(x, 0.7), torch.nn.functional.elu(tx, 0.7))
        _close(F.hardswish(x), torch.nn.functional.hardswish(tx))
        _close(F.log_softmax(x, axis=-1),
               torch.nn.functional.log_softmax(tx, dim=-1))

    def test_cross_entropy_variants(self):
        logits, tlogits = _pair((6, 10))
        labels = RNG.integers(0, 10, 6)
        got = F.cross_entropy(logits, paddle.to_tensor(labels))
        ref = torch.nn.functional.cross_entropy(tlogits,
                                                torch.tensor(labels))
        _close(got, ref)
        # soft labels
        soft = np.abs(RNG.normal(size=(6, 10))).astype(np.float32)
        soft /= soft.sum(-1, keepdims=True)
        got2 = F.cross_entropy(logits, paddle.to_tensor(soft),
                               soft_label=True)
        ref2 = torch.nn.functional.cross_entropy(tlogits,
                                                 torch.tensor(soft))
        _close(got2, ref2)

    def test_nll_kl_smoothl1(self):
        x, tx = _pair((5, 7))
        y, ty = _pair((5, 7))
        _close(F.smooth_l1_loss(x, y),
               torch.nn.functional.smooth_l1_loss(tx, ty))
        logp = F.log_softmax(x, axis=-1)
        tlogp = torch.nn.functional.log_softmax(tx, dim=-1)
        tgt = np.abs(RNG.normal(size=(5, 7))).astype(np.float32)
        tgt /= tgt.sum(-1, keepdims=True)
        got = F.kl_div(logp, paddle.to_tensor(tgt), reduction="batchmean")
        ref = torch.nn.functional.kl_div(tlogp, torch.tensor(tgt),
                                         reduction="batchmean")
        _close(got, ref)

    def test_embedding_padding_idx(self):
        w, tw = _pair((20, 6))
        ids = RNG.integers(0, 20, (3, 4))
        got = F.embedding(paddle.to_tensor(ids), w, padding_idx=2)
        ref = torch.nn.functional.embedding(torch.tensor(ids), tw,
                                            padding_idx=2)
        _close(got, ref)


class TestAttentionOracle:
    def test_sdpa_causal(self):
        q, tq = _pair((2, 8, 4, 16))     # paddle layout [B, N, H, D]
        k, tk = _pair((2, 8, 4, 16))
        v, tv = _pair((2, 8, 4, 16))
        got = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                             dropout_p=0.0)
        ref = torch.nn.functional.scaled_dot_product_attention(
            tq.permute(0, 2, 1, 3), tk.permute(0, 2, 1, 3),
            tv.permute(0, 2, 1, 3), is_causal=True).permute(0, 2, 1, 3)
        _close(got, ref, rtol=1e-3, atol=1e-4)

    def test_sdpa_boolean_mask(self):
        q, tq = _pair((1, 5, 2, 8))
        k, tk = _pair((1, 5, 2, 8))
        v, tv = _pair((1, 5, 2, 8))
        mask = RNG.random((1, 2, 5, 5)) > 0.3
        mask[..., 0] = True              # keep rows attendable
        got = F.scaled_dot_product_attention(
            q, k, v, attn_mask=paddle.to_tensor(mask), dropout_p=0.0)
        ref = torch.nn.functional.scaled_dot_product_attention(
            tq.permute(0, 2, 1, 3), tk.permute(0, 2, 1, 3),
            tv.permute(0, 2, 1, 3),
            attn_mask=torch.tensor(mask)).permute(0, 2, 1, 3)
        _close(got, ref, rtol=1e-3, atol=1e-4)


class TestGradOracle:
    def test_conv_backward_matches(self):
        xv = RNG.normal(size=(2, 3, 8, 8)).astype(np.float32)
        wv = RNG.normal(size=(4, 3, 3, 3)).astype(np.float32)
        x = paddle.to_tensor(xv)
        x.stop_gradient = False
        w = paddle.to_tensor(wv)
        w.stop_gradient = False
        loss = (F.conv2d(x, w, padding=1) ** 2).mean()
        loss.backward()
        tx = torch.tensor(xv, requires_grad=True)
        tw = torch.tensor(wv, requires_grad=True)
        tloss = (torch.nn.functional.conv2d(tx, tw, padding=1) ** 2).mean()
        tloss.backward()
        _close(x.grad, tx.grad, rtol=1e-3, atol=1e-5)
        _close(w.grad, tw.grad, rtol=1e-3, atol=1e-5)

    def test_layer_norm_backward_matches(self):
        xv = RNG.normal(size=(4, 10)).astype(np.float32)
        x = paddle.to_tensor(xv)
        x.stop_gradient = False
        loss = (F.layer_norm(x, [10]) ** 3).mean()
        loss.backward()
        tx = torch.tensor(xv, requires_grad=True)
        tloss = (torch.nn.functional.layer_norm(tx, [10]) ** 3).mean()
        tloss.backward()
        _close(x.grad, tx.grad, rtol=1e-3, atol=1e-5)
