"""RNG stream durability for universal promotion (framework/random.py).

The global generator is a fold_in STREAM over a fixed base key: position i
yields `fold_in(base, i)`, whether the key is drawn eagerly, materialized
lazily by a transactional split, or derived IN-GRAPH by a promoted step's
hoisted (base data, position) scalars. These tests pin the contract:

  * derivation equivalence — `derive_key_data(base_data, i)` is bit-equal
    to the eager draw at position i (the fused/eager parity bedrock);
  * checkpoint exactness — `rng_checkpoint_state` round-trips (base,
    position) so a restored run continues the interrupted stream
    bit-for-bit;
  * kill-9 durability (the PR 5 chaos pattern extended to hoisted keys):
    a StepCheckpointer-ticked, PROMOTED dropout loop killed mid-run and
    restored reproduces the uninterrupted run's loss trajectory — the
    dropout masks after restore are the ones the unkilled run drew.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile

import numpy as np
import jax
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework import random as frandom
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops.dispatch import clear_dispatch_cache


@pytest.fixture(autouse=True)
def _fresh():
    clear_dispatch_cache()
    yield
    clear_dispatch_cache()


class TestStreamContract:
    def test_hoisted_derivation_matches_eager_draw(self):
        """rng_key_input() reserves position i; in-graph derivation from
        (base data, i) yields the SAME key data bit-for-bit."""
        paddle.seed(123)
        base_data = frandom.stream_base_data()
        pos0 = frandom.default_generator.epoch
        kd_tensor = frandom.rng_key_input()
        assert kd_tensor._rng_epoch == pos0
        derived = frandom.derive_key_data(base_data, pos0)
        np.testing.assert_array_equal(np.asarray(kd_tensor._value),
                                      np.asarray(derived))
        # the traced form (an int32 scalar position) derives identically
        traced = jax.jit(frandom.derive_key_data)(
            base_data, np.int32(pos0))
        np.testing.assert_array_equal(np.asarray(traced),
                                      np.asarray(derived))

    def test_lazy_key_answers_aval_without_deriving(self):
        paddle.seed(0)
        t = frandom.rng_key_input()
        assert t._fusion_aval is not None      # keyable pre-derivation
        shape, dtype, _ = t._fusion_aval
        v = t._value                           # forces
        assert tuple(v.shape) == tuple(shape) and v.dtype == dtype
        assert t._fusion_aval is None          # materialized now

    def test_checkpoint_roundtrip_resumes_stream(self):
        paddle.seed(7)
        _ = [frandom.get_rng_key() for _ in range(5)]
        snap = frandom.rng_checkpoint_state()
        a = [np.asarray(jax.random.key_data(frandom.get_rng_key()))
             for _ in range(4)]
        frandom.set_rng_checkpoint_state(snap)
        b = [np.asarray(jax.random.key_data(frandom.get_rng_key()))
             for _ in range(4)]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def test_hoisted_consumption_does_not_bump_legacy_epoch(self):
        """Only STATEFUL draws feed the rng_rekey bypass heuristic;
        hoisted consumption advances the stream, not the legacy count."""
        paddle.seed(0)
        leg0 = frandom.rng_epoch()
        pos0 = frandom.default_generator.epoch
        frandom.rng_key_input()
        assert frandom.default_generator.epoch == pos0 + 1
        assert frandom.rng_epoch() == leg0
        frandom.get_rng_key()
        assert frandom.rng_epoch() == leg0 + 1


_CHILD = r"""
import json, os, signal, sys
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.incubate.checkpoint import StepCheckpointer

ck_dir, log_path, n_steps, kill_at = (
    sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]))
set_flags({"FLAGS_eager_op_cache": True,
           "FLAGS_eager_chain_fusion": True,
           "FLAGS_eager_chain_fusion_min_count": 3,
           "FLAGS_eager_step_fusion": True,
           "FLAGS_eager_step_fusion_min_count": 3})
paddle.seed(42)
model = paddle.nn.Linear(16, 16)
rng = np.random.default_rng(5)
x = paddle.to_tensor(rng.standard_normal((8, 16)).astype(np.float32))
opt = paddle.optimizer.SGD(learning_rate=0.05,
                           parameters=model.parameters())
ck = StepCheckpointer(ck_dir, save_every_n_steps=1, run_id="rngchaos")
start = ck.restore(model=model, optimizer=opt)
for step in range(start + 1, n_steps):
    y = F.dropout(F.gelu(model(x)), 0.3)
    loss = y.sum()
    loss.backward()
    opt.step()
    opt.clear_grad()
    with open(log_path, "a") as f:
        f.write(json.dumps({"step": step,
                            "loss": float(loss.numpy())}) + "\n")
    ck.tick(step, model=model, optimizer=opt)
    if kill_at >= 0 and step == kill_at:
        os.kill(os.getpid(), signal.SIGKILL)
"""


_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_child(ck_dir, log_path, n_steps, kill_at):
    return subprocess.run(
        [sys.executable, "-c", _CHILD, ck_dir, log_path, str(n_steps),
         str(kill_at)],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": _REPO_ROOT + os.pathsep
             + os.environ.get("PYTHONPATH", "")})


def _read_log(path):
    out = {}
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            out[rec["step"]] = rec["loss"]
    return out


class TestKill9Durability:
    def test_kill9_restore_reproduces_dropout_trajectory(self):
        """SIGKILL mid-promoted-dropout-loop; the restored process
        continues the SAME hoisted key stream: the union of pre-kill and
        resumed losses matches the uninterrupted run step for step."""
        n = 18
        with tempfile.TemporaryDirectory() as tmp:
            ref_log = os.path.join(tmp, "ref.jsonl")
            r = _run_child(os.path.join(tmp, "ck_ref"), ref_log, n, -1)
            assert r.returncode == 0, r.stderr[-800:]
            ref = _read_log(ref_log)

            ck = os.path.join(tmp, "ck_kill")
            kill_log = os.path.join(tmp, "kill.jsonl")
            r1 = _run_child(ck, kill_log, n, 11)
            assert r1.returncode == -signal.SIGKILL, r1.stderr[-500:]
            r2 = _run_child(ck, kill_log, n, -1)
            assert r2.returncode == 0, r2.stderr[-800:]
            got = _read_log(kill_log)
        assert set(got) == set(ref)
        for step in sorted(ref):
            assert abs(got[step] - ref[step]) <= 1e-4 * abs(ref[step]) \
                + 1e-6, (step, got[step], ref[step])


class TestLegacyStateShapes:
    def test_set_rng_state_accepts_every_historical_shape(self):
        """Pre-stream get_rng_state() returned a bare [key]; every shape
        — [(key, pos)], (key, pos), [key], bare key, [] — must restore
        without crashing, and a bare key restarts its stream."""
        paddle.seed(3)
        st_new = frandom.get_rng_state()       # [(key, pos)]
        a = paddle.rand([2]).numpy()
        frandom.set_rng_state(st_new)
        np.testing.assert_allclose(a, paddle.rand([2]).numpy())
        frandom.set_rng_state(st_new[0])       # bare (key, pos) pair
        np.testing.assert_allclose(a, paddle.rand([2]).numpy())
        k = jax.random.key(3)
        frandom.set_rng_state([k])             # legacy list-of-keys
        b = paddle.rand([2]).numpy()
        frandom.set_rng_state(k)               # bare key
        np.testing.assert_allclose(b, paddle.rand([2]).numpy())
        frandom.set_rng_state([])              # empty list: no crash
        paddle.seed(3)


class TestHoistedGeneratorOps:
    """ROADMAP 1(c) closed (PR 15): EVERY registered sampler — including
    the former stateful stragglers randint/multinomial/randperm — draws
    through a hoisted stream position (rng_key_input) as a dispatch
    input. Pins: (a) bit-parity with the fold_in(base, position) oracle
    (the stateful path drew exactly these bits, so seeded runs are
    unchanged across the migration); (b) stream/legacy-epoch accounting
    (hoisted draws advance the stream, never the rng_rekey heuristic);
    (c) funnel entry — a second structurally-identical call HITS the
    per-op executable cache instead of bypassing (zero R2 baseline
    suppressions is the linter-side acceptance)."""

    def _oracle_key(self, seed, pos=0):
        return jax.random.fold_in(jax.random.key(seed), pos)

    def test_randint_parity_with_stream_oracle(self):
        paddle.seed(101)
        got = paddle.randint(0, 1000, (16,))
        exp = jax.random.randint(self._oracle_key(101), (16,), 0, 1000,
                                 np.asarray(got.numpy()).dtype)
        np.testing.assert_array_equal(np.asarray(got.numpy()),
                                      np.asarray(exp))

    def test_randperm_parity_with_stream_oracle(self):
        paddle.seed(33)
        got = paddle.randperm(17)
        exp = jax.random.permutation(self._oracle_key(33), 17)
        np.testing.assert_array_equal(np.asarray(got.numpy()),
                                      np.asarray(exp).astype(np.int64))

    def test_multinomial_parity_with_stream_oracle(self):
        probs = np.array([[0.1, 0.2, 0.3, 0.4]], np.float32)
        paddle.seed(7)
        got = paddle.multinomial(paddle.to_tensor(probs), 2)
        key = self._oracle_key(7)
        logits = np.log(np.clip(probs / probs.sum(-1, keepdims=True),
                                1e-30, None))
        g = np.asarray(jax.random.gumbel(key, probs.shape))
        exp = np.argsort(-(logits + g), axis=-1)[:, :2]
        np.testing.assert_array_equal(np.asarray(got.numpy()), exp)

    def test_rand_randn_normal_uniform_consume_one_position_each(self):
        paddle.seed(0)
        g = frandom.default_generator
        leg0 = frandom.rng_epoch()
        for i, draw in enumerate((
                lambda: paddle.rand([3]),
                lambda: paddle.randn([3]),
                lambda: paddle.normal(0.0, 1.0, [3]),
                lambda: paddle.uniform([3]),
                lambda: paddle.randint(0, 9, (3,)),
                lambda: paddle.randperm(5),
                lambda: paddle.poisson(paddle.to_tensor(
                    np.ones((3,), np.float32))),
                lambda: paddle.multinomial(paddle.to_tensor(
                    np.ones((1, 4), np.float32)), 1))):
            before = g.epoch
            draw()
            assert g.epoch == before + 1, f"draw {i} consumed != 1"
        # none of them bumped the STATEFUL (rng_rekey) epoch
        assert frandom.rng_epoch() == leg0

    def test_hoisted_ops_hit_the_dispatch_cache(self):
        """Funnel entry: the second structurally-identical draw is a
        dispatch HIT (keyed on the stable key-data aval), not a bypass —
        the promotion-poisoning class the R2 lint rule guards."""
        from paddle_tpu.profiler.events import EVENTS, clear_fusion_events
        paddle.seed(1)
        set_flags({"FLAGS_profiler_events": True})
        try:
            for draw in (lambda: paddle.randint(0, 9, (4,)),
                         lambda: paddle.randperm(6),
                         lambda: paddle.multinomial(paddle.to_tensor(
                             np.ones((1, 4), np.float32)), 1)):
                draw()                      # warm (miss -> compile)
                clear_fusion_events()
                draw()
                ev = EVENTS.snapshot()
                hits = [e for e in ev if e["cat"] == "dispatch.hit"]
                bypasses = [e for e in ev if e["cat"] == "dispatch.bypass"]
                assert hits and not bypasses, (draw, ev)
        finally:
            set_flags({"FLAGS_profiler_events": False})
            clear_fusion_events()

    def test_gumbel_softmax_and_rrelu_hoisted(self):
        """The activation-family stragglers ride the same stream: one
        position per call, same bits as the old stateful draw."""
        import paddle_tpu.nn.functional as F
        x = paddle.to_tensor(
            np.random.default_rng(3).standard_normal((2, 6))
            .astype(np.float32))
        paddle.seed(19)
        g = frandom.default_generator
        y1 = F.gumbel_softmax(x)
        assert g.epoch == 1
        r1 = F.rrelu(x, training=True)
        assert g.epoch == 2
        paddle.seed(19)
        y2 = F.gumbel_softmax(x)
        r2 = F.rrelu(x, training=True)
        np.testing.assert_array_equal(np.asarray(y1.numpy()),
                                      np.asarray(y2.numpy()))
        np.testing.assert_array_equal(np.asarray(r1.numpy()),
                                      np.asarray(r2.numpy()))
        # eval-mode rrelu is deterministic and consumes NO position
        before = g.epoch
        F.rrelu(x, training=False)
        assert g.epoch == before
