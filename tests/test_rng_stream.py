"""RNG stream durability for universal promotion (framework/random.py).

The global generator is a fold_in STREAM over a fixed base key: position i
yields `fold_in(base, i)`, whether the key is drawn eagerly, materialized
lazily by a transactional split, or derived IN-GRAPH by a promoted step's
hoisted (base data, position) scalars. These tests pin the contract:

  * derivation equivalence — `derive_key_data(base_data, i)` is bit-equal
    to the eager draw at position i (the fused/eager parity bedrock);
  * checkpoint exactness — `rng_checkpoint_state` round-trips (base,
    position) so a restored run continues the interrupted stream
    bit-for-bit;
  * kill-9 durability (the PR 5 chaos pattern extended to hoisted keys):
    a StepCheckpointer-ticked, PROMOTED dropout loop killed mid-run and
    restored reproduces the uninterrupted run's loss trajectory — the
    dropout masks after restore are the ones the unkilled run drew.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile

import numpy as np
import jax
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework import random as frandom
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops.dispatch import clear_dispatch_cache


@pytest.fixture(autouse=True)
def _fresh():
    clear_dispatch_cache()
    yield
    clear_dispatch_cache()


class TestStreamContract:
    def test_hoisted_derivation_matches_eager_draw(self):
        """rng_key_input() reserves position i; in-graph derivation from
        (base data, i) yields the SAME key data bit-for-bit."""
        paddle.seed(123)
        base_data = frandom.stream_base_data()
        pos0 = frandom.default_generator.epoch
        kd_tensor = frandom.rng_key_input()
        assert kd_tensor._rng_epoch == pos0
        derived = frandom.derive_key_data(base_data, pos0)
        np.testing.assert_array_equal(np.asarray(kd_tensor._value),
                                      np.asarray(derived))
        # the traced form (an int32 scalar position) derives identically
        traced = jax.jit(frandom.derive_key_data)(
            base_data, np.int32(pos0))
        np.testing.assert_array_equal(np.asarray(traced),
                                      np.asarray(derived))

    def test_lazy_key_answers_aval_without_deriving(self):
        paddle.seed(0)
        t = frandom.rng_key_input()
        assert t._fusion_aval is not None      # keyable pre-derivation
        shape, dtype, _ = t._fusion_aval
        v = t._value                           # forces
        assert tuple(v.shape) == tuple(shape) and v.dtype == dtype
        assert t._fusion_aval is None          # materialized now

    def test_checkpoint_roundtrip_resumes_stream(self):
        paddle.seed(7)
        _ = [frandom.get_rng_key() for _ in range(5)]
        snap = frandom.rng_checkpoint_state()
        a = [np.asarray(jax.random.key_data(frandom.get_rng_key()))
             for _ in range(4)]
        frandom.set_rng_checkpoint_state(snap)
        b = [np.asarray(jax.random.key_data(frandom.get_rng_key()))
             for _ in range(4)]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def test_hoisted_consumption_does_not_bump_legacy_epoch(self):
        """Only STATEFUL draws feed the rng_rekey bypass heuristic;
        hoisted consumption advances the stream, not the legacy count."""
        paddle.seed(0)
        leg0 = frandom.rng_epoch()
        pos0 = frandom.default_generator.epoch
        frandom.rng_key_input()
        assert frandom.default_generator.epoch == pos0 + 1
        assert frandom.rng_epoch() == leg0
        frandom.get_rng_key()
        assert frandom.rng_epoch() == leg0 + 1


_CHILD = r"""
import json, os, signal, sys
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.incubate.checkpoint import StepCheckpointer

ck_dir, log_path, n_steps, kill_at = (
    sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]))
set_flags({"FLAGS_eager_op_cache": True,
           "FLAGS_eager_chain_fusion": True,
           "FLAGS_eager_chain_fusion_min_count": 3,
           "FLAGS_eager_step_fusion": True,
           "FLAGS_eager_step_fusion_min_count": 3})
paddle.seed(42)
model = paddle.nn.Linear(16, 16)
rng = np.random.default_rng(5)
x = paddle.to_tensor(rng.standard_normal((8, 16)).astype(np.float32))
opt = paddle.optimizer.SGD(learning_rate=0.05,
                           parameters=model.parameters())
ck = StepCheckpointer(ck_dir, save_every_n_steps=1, run_id="rngchaos")
start = ck.restore(model=model, optimizer=opt)
for step in range(start + 1, n_steps):
    y = F.dropout(F.gelu(model(x)), 0.3)
    loss = y.sum()
    loss.backward()
    opt.step()
    opt.clear_grad()
    with open(log_path, "a") as f:
        f.write(json.dumps({"step": step,
                            "loss": float(loss.numpy())}) + "\n")
    ck.tick(step, model=model, optimizer=opt)
    if kill_at >= 0 and step == kill_at:
        os.kill(os.getpid(), signal.SIGKILL)
"""


_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_child(ck_dir, log_path, n_steps, kill_at):
    return subprocess.run(
        [sys.executable, "-c", _CHILD, ck_dir, log_path, str(n_steps),
         str(kill_at)],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": _REPO_ROOT + os.pathsep
             + os.environ.get("PYTHONPATH", "")})


def _read_log(path):
    out = {}
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            out[rec["step"]] = rec["loss"]
    return out


class TestKill9Durability:
    def test_kill9_restore_reproduces_dropout_trajectory(self):
        """SIGKILL mid-promoted-dropout-loop; the restored process
        continues the SAME hoisted key stream: the union of pre-kill and
        resumed losses matches the uninterrupted run step for step."""
        n = 18
        with tempfile.TemporaryDirectory() as tmp:
            ref_log = os.path.join(tmp, "ref.jsonl")
            r = _run_child(os.path.join(tmp, "ck_ref"), ref_log, n, -1)
            assert r.returncode == 0, r.stderr[-800:]
            ref = _read_log(ref_log)

            ck = os.path.join(tmp, "ck_kill")
            kill_log = os.path.join(tmp, "kill.jsonl")
            r1 = _run_child(ck, kill_log, n, 11)
            assert r1.returncode == -signal.SIGKILL, r1.stderr[-500:]
            r2 = _run_child(ck, kill_log, n, -1)
            assert r2.returncode == 0, r2.stderr[-800:]
            got = _read_log(kill_log)
        assert set(got) == set(ref)
        for step in sorted(ref):
            assert abs(got[step] - ref[step]) <= 1e-4 * abs(ref[step]) \
                + 1e-6, (step, got[step], ref[step])


class TestLegacyStateShapes:
    def test_set_rng_state_accepts_every_historical_shape(self):
        """Pre-stream get_rng_state() returned a bare [key]; every shape
        — [(key, pos)], (key, pos), [key], bare key, [] — must restore
        without crashing, and a bare key restarts its stream."""
        paddle.seed(3)
        st_new = frandom.get_rng_state()       # [(key, pos)]
        a = paddle.rand([2]).numpy()
        frandom.set_rng_state(st_new)
        np.testing.assert_allclose(a, paddle.rand([2]).numpy())
        frandom.set_rng_state(st_new[0])       # bare (key, pos) pair
        np.testing.assert_allclose(a, paddle.rand([2]).numpy())
        k = jax.random.key(3)
        frandom.set_rng_state([k])             # legacy list-of-keys
        b = paddle.rand([2]).numpy()
        frandom.set_rng_state(k)               # bare key
        np.testing.assert_allclose(b, paddle.rand([2]).numpy())
        frandom.set_rng_state([])              # empty list: no crash
        paddle.seed(3)
