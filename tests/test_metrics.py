"""Production telemetry plane (PR 12): metrics registry, goodput
accountant, serving latency story, and export plumbing.

Contracts pinned here:

  * ``METRIC_NAMES`` / ``GOODPUT_BUCKETS`` are stable public APIs like
    ``REASON_CODES`` — dashboards and the fusion doctor key on the exact
    strings, and the default registry pre-installs exactly that set;
  * the bounded log-bucket histogram tracks numpy percentiles on known
    distributions, stays fresh past its window (the ServeStats
    100k-freeze fix), merges across snapshots, and never grows its
    bucket storage;
  * with ``FLAGS_metrics`` off, nothing is recorded — not one sample;
  * the JSONL sink round-trips through the Prometheus/merge tooling,
    merges across two subprocess registries, and survives kill -9
    without a torn file;
  * serving requests report TTFT / inter-token / queue-wait percentiles
    per engine AND per completed handle, emit per-request chrome-trace
    spans, and the doctor's serving verdict cites live latency;
  * the goodput accountant reports live MFU within 2% of bench.py's
    offline computation and attributes injected guardian skips and
    watchdog stalls to the right wall-time buckets.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops import guardian
from paddle_tpu.ops.dispatch import clear_dispatch_cache
from paddle_tpu.profiler import metrics as pm
from paddle_tpu.profiler import goodput as pg
from paddle_tpu.profiler.events import clear_fusion_events, fusion_events
from paddle_tpu.profiler.explain import explain
from paddle_tpu.profiler import _fusion_trace_events

_DEFAULT_FLAGS = {
    "FLAGS_metrics": False,
    "FLAGS_metrics_window": 100_000,
    "FLAGS_check_numerics": False,
    "FLAGS_check_numerics_level": 0,
    "FLAGS_profiler_events": False,
    "FLAGS_serve_step_timeout_ms": 0,
    "FLAGS_eager_op_cache": True,
    "FLAGS_eager_chain_fusion": True,
    "FLAGS_eager_chain_fusion_min_count": 3,
    "FLAGS_eager_step_fusion": True,
    "FLAGS_eager_step_fusion_min_count": 4,
}


@pytest.fixture(autouse=True)
def _fresh():
    set_flags(dict(_DEFAULT_FLAGS))
    pm.reset_metrics()
    clear_fusion_events()
    guardian.clear_faults()
    guardian.reset_thread_state()
    yield
    set_flags(dict(_DEFAULT_FLAGS))
    pm.reset_metrics()
    clear_fusion_events()
    guardian.clear_faults()
    guardian.reset_thread_state()


# ---------------------------------------------------------------------------
# contract freeze
# ---------------------------------------------------------------------------

class TestContract:
    def test_metric_names_frozen(self):
        """The metric-name set is a PUBLIC contract: additions are
        deliberate API changes (update this test AND the README table),
        removals/renames break downstream dashboards."""
        assert pm.METRIC_NAMES == frozenset({
            "dispatch_events_total", "chain_events_total",
            "step_fusion_events_total", "aot_events_total",
            "guardian_events_total", "collectives_total",
            "train_step_seconds", "spmd_step_seconds",
            "train_tokens_total", "train_flops_per_step", "train_mfu",
            "train_tokens_per_second", "train_goodput",
            "goodput_seconds_total", "goodput_step_index",
            "serve_step_seconds", "serve_ttft_seconds",
            "serve_inter_token_seconds", "serve_queue_wait_seconds",
            "serve_tokens_total", "serve_occupancy",
            "serve_requests_total", "serve_refusals_total",
            "serve_hangs_total", "serve_preemptions_total",
            "serve_prefix_hit_tokens_total", "serve_prefix_hit_rate",
            "serve_adapter_switches_total", "serve_weight_swaps_total",
            "serve_sampled_tokens_total", "serve_commit_rollbacks_total",
            "sentinel_checks_total", "sentinel_degraded",
        })

    def test_goodput_buckets_frozen(self):
        assert pm.GOODPUT_BUCKETS == ("productive", "compile", "skipped",
                                      "stalled", "warmup", "probation",
                                      "other")

    def test_merge_policy_map_frozen(self):
        """PR 13 satellite: the per-metric fleet-merge policy is a
        public contract like the names — a policy change silently
        re-means every fleet dashboard. Every METRIC_NAMES entry has an
        explicit policy; occurrence mass (counters/histograms) always
        sums; the gauges that were wrong under the old blanket max
        (occupancy, tokens/s) are explicitly additive; watermarks stay
        max."""
        assert set(pm.METRIC_MERGE) == set(pm.METRIC_NAMES)
        assert set(pm.METRIC_MERGE.values()) <= {"sum", "max", "last"}
        # occurrence mass: every counter/histogram family sums
        snap = pm.metrics_snapshot()
        for name, fam in snap.items():
            if fam["type"] in ("counter", "histogram"):
                assert pm.METRIC_MERGE[name] == "sum", name
        # the gauge semantics the satellite fixes / preserves
        assert pm.METRIC_MERGE["serve_occupancy"] == "sum"
        assert pm.METRIC_MERGE["train_tokens_per_second"] == "sum"
        assert pm.METRIC_MERGE["train_mfu"] == "max"
        assert pm.METRIC_MERGE["train_flops_per_step"] == "max"
        assert pm.METRIC_MERGE["goodput_step_index"] == "max"
        # any degraded host degrades the fleet: the sentinel latch maxes
        assert pm.METRIC_MERGE["sentinel_degraded"] == "max"
        # unknown names keep the kind defaults
        assert pm.merge_policy("_not_a_metric", "counter") == "sum"
        assert pm.merge_policy("_not_a_metric", "gauge") == "max"

    def test_registry_preinstalls_exactly_the_contract(self):
        snap = pm.metrics_snapshot()
        assert set(snap) == pm.METRIC_NAMES
        for name, fam in snap.items():
            assert fam["type"] in ("counter", "gauge", "histogram"), name

    def test_conflicting_reregistration_rejected(self):
        with pytest.raises(ValueError):
            pm.REGISTRY.gauge("serve_tokens_total")
        with pytest.raises(ValueError):
            pm.REGISTRY.counter("serve_refusals_total")   # labels differ


# ---------------------------------------------------------------------------
# histogram
# ---------------------------------------------------------------------------

class TestHistogram:
    @pytest.mark.parametrize("dist", ["uniform", "lognormal"])
    def test_quantile_accuracy_vs_numpy(self, dist):
        rng = np.random.default_rng(7)
        if dist == "uniform":
            vals = rng.uniform(1e-4, 1e-1, 20_000)
        else:
            vals = rng.lognormal(-6.0, 1.2, 20_000)
        h = pm.LogHistogram(window=0)
        for v in vals:
            h.observe(float(v))
        for p in (50, 90, 99):
            ref = float(np.percentile(vals, p))
            est = h.percentile(p)
            # log buckets at 20/decade: one-bucket resolution is ~12%
            assert abs(est - ref) / ref < 0.15, (p, est, ref)
        assert h.count == len(vals)
        assert abs(h.sum - vals.sum()) / vals.sum() < 1e-6

    def test_constant_stream_lands_in_one_bucket(self):
        h = pm.LogHistogram(window=0)
        for _ in range(1000):
            h.observe(0.004)
        assert abs(h.percentile(50) - 0.004) / 0.004 < 0.12
        assert abs(h.percentile(99) - 0.004) / 0.004 < 0.12

    def test_window_keeps_percentiles_fresh(self):
        """The ServeStats fix: after far more samples than the window,
        NEW samples still move the percentiles — the old raw list froze
        at its 100k cap and reported stale p50/p99 forever."""
        h = pm.LogHistogram(window=500)
        for _ in range(2000):
            h.observe(0.001)           # old regime: 1 ms
        for _ in range(1100):          # > 2 windows of the new regime
            h.observe(0.1)             # new regime: 100 ms
        p50 = h.percentile(50)
        assert abs(p50 - 0.1) / 0.1 < 0.15, \
            f"p50 {p50} still reflects the pre-window regime"

    def test_bounded_memory_under_sustained_observation(self):
        h = pm.LogHistogram(window=1000)
        h.observe(1e-4)
        n0 = len(h._cur)
        size0 = sys.getsizeof(h._cur)
        for i in range(25_000):
            h.observe(1e-5 * (1 + i % 321))
        assert len(h._cur) == n0
        assert sys.getsizeof(h._cur) == size0
        assert h._prev is None or len(h._prev) == n0

    def test_exposition_stays_cumulative_past_the_window(self):
        """Prometheus invariant: bucket counters are monotonic and the
        +Inf bucket equals _count even after the freshness window has
        rotated old samples out — rate()/histogram_quantile() must never
        see a band rotation as a counter reset."""
        set_flags({"FLAGS_metrics": True})
        h = pm.REGISTRY.histogram("_t_rot_seconds", "t", window=200)
        for _ in range(750):                  # several rotations
            h.observe(0.003)
        snap = h._default.snapshot()
        assert sum(snap["buckets"].values()) == 750
        assert snap["count"] == 750
        assert sum(snap["window_buckets"].values()) < 750
        text = pm.exposition({"_t_rot_seconds": {
            "type": "histogram", "help": "", "labelnames": [],
            "series": [dict(snap, labels={})]}})
        lines = text.splitlines()
        assert 'paddle_tpu__t_rot_seconds_bucket{le="+Inf"} 750' in lines
        assert "paddle_tpu__t_rot_seconds_count 750" in lines

    def test_merge_snapshots_adds_counts(self):
        a, b = pm.LogHistogram(window=0), pm.LogHistogram(window=0)
        for _ in range(100):
            a.observe(0.001)
        for _ in range(300):
            b.observe(0.1)
        m = pm.LogHistogram.merge_snapshot(a.snapshot(), b.snapshot())
        assert m["count"] == 400
        # 75% of merged mass at 100ms -> p50 sits in the 100ms bucket
        p50 = pm.LogHistogram.snapshot_quantile(m, 0.5)
        assert abs(p50 - 0.1) / 0.1 < 0.15
        assert m["min"] == a.min and m["max"] == b.max


# ---------------------------------------------------------------------------
# gating
# ---------------------------------------------------------------------------

class TestGate:
    def test_off_gate_records_nothing(self):
        assert not pm.enabled()
        pm.TRAIN.step_s.observe(0.01)
        pm.SERVE.tokens.inc(5)
        pm.SERVE.refusals.labels(reason="queue_full").inc()
        pm.TRAIN.mfu.set(0.5)
        assert pm.TRAIN.step_s.count == 0
        assert pm.SERVE.tokens.value == 0
        assert pm.SERVE.refusals.labels(reason="queue_full").value == 0
        assert pm.TRAIN.mfu.value == 0.0

    def test_on_gate_records(self):
        set_flags({"FLAGS_metrics": True})
        pm.TRAIN.step_s.observe(0.01)
        pm.SERVE.tokens.inc(5)
        pm.TRAIN.mfu.set(0.5)
        assert pm.TRAIN.step_s.count == 1
        assert pm.SERVE.tokens.value == 5
        assert pm.TRAIN.mfu.value == 0.5


# ---------------------------------------------------------------------------
# exposition + merge
# ---------------------------------------------------------------------------

class TestExposition:
    def test_prometheus_text_parses(self):
        set_flags({"FLAGS_metrics": True})
        pm.TRAIN.step_s.observe(0.02)
        pm.SERVE.refusals.labels(reason="queue_full").inc(3)
        text = pm.REGISTRY.exposition()
        lines = text.splitlines()
        assert any(l.startswith("# TYPE paddle_tpu_train_step_seconds "
                                "histogram") for l in lines)
        assert 'paddle_tpu_serve_refusals_total{reason="queue_full"} 3' \
            in lines
        # histogram: cumulative buckets, +Inf terminal, sum/count
        bk = [l for l in lines
              if l.startswith("paddle_tpu_train_step_seconds_bucket")]
        assert bk and bk[-1].startswith(
            'paddle_tpu_train_step_seconds_bucket{le="+Inf"} 1')
        assert "paddle_tpu_train_step_seconds_count 1" in lines
        # every sample line is NAME{labels} VALUE — parseable
        for l in lines:
            if l.startswith("#") or not l:
                continue
            name, _, val = l.rpartition(" ")
            float(val)
            assert name

    def test_merge_counters_add_gauges_max(self):
        set_flags({"FLAGS_metrics": True})
        pm.SERVE.tokens.inc(7)
        pm.TRAIN.mfu.set(0.3)
        pm.TRAIN.step_s.observe(0.01)
        snap = pm.metrics_snapshot()
        other = json.loads(json.dumps(snap))   # simulate a second process
        other["train_mfu"]["series"][0]["value"] = 0.5
        merged = pm.merge_snapshots([snap, other])
        assert merged["serve_tokens_total"]["series"][0]["value"] == 14
        assert merged["train_mfu"]["series"][0]["value"] == 0.5
        assert merged["train_step_seconds"]["series"][0]["count"] == 2
        # merged snapshots render through the same exposition path
        assert "paddle_tpu_serve_tokens_total 14" \
            in pm.exposition(merged).splitlines()

    def test_merge_honors_per_metric_policy(self):
        """PR 13 satellite: merge_snapshots follows METRIC_MERGE — a
        fleet of engines at 0.9 occupancy reports summed occupied
        capacity (1.8 across two hosts), NOT the old blanket max (0.9);
        fleet tokens/s adds; the step-index watermark maxes. Both
        metrics_export --merge and fleet_metrics flow through this one
        implementation."""
        set_flags({"FLAGS_metrics": True})
        pm.SERVE.occupancy.set(0.9)
        pm.TRAIN.tokens_per_s._default.set_raw(100.0)
        pm.TRAIN.step_index.labels(bucket="skipped").set_raw(40)
        snap = pm.metrics_snapshot()
        other = json.loads(json.dumps(snap))
        other["serve_occupancy"]["series"][0]["value"] = 0.7
        other["train_tokens_per_second"]["series"][0]["value"] = 50.0
        other["goodput_step_index"]["series"][0]["value"] = 90
        merged = pm.merge_snapshots([snap, other])
        assert merged["serve_occupancy"]["series"][0]["value"] \
            == pytest.approx(1.6)
        assert merged["train_tokens_per_second"]["series"][0]["value"] \
            == pytest.approx(150.0)
        assert merged["goodput_step_index"]["series"][0]["value"] == 90


# ---------------------------------------------------------------------------
# JSONL sink: cross-process merge + kill-9 safety
# ---------------------------------------------------------------------------

_CHILD_WRITE = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {root!r})
sys.path.insert(0, os.path.join({root!r}, "tools"))
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.profiler import metrics as pm
import metrics_export
set_flags({{"FLAGS_metrics": True}})
pm.SERVE.tokens.inc({tokens})
pm.SERVE.refusals.labels(reason="queue_full").inc({refused})
for _ in range({obs}):
    pm.TRAIN.step_s.observe(0.002)
sink = metrics_export.MetricsSink(path={path!r})
sink.write()
print("WROTE")
"""

_CHILD_SPIN = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {root!r})
sys.path.insert(0, os.path.join({root!r}, "tools"))
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.profiler import metrics as pm
import metrics_export
set_flags({{"FLAGS_metrics": True}})
sink = metrics_export.MetricsSink(path={path!r})
print("READY", flush=True)
i = 0
while True:
    pm.SERVE.tokens.inc()
    i += 1
    sink.write()
"""

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_child(code, timeout=120):
    return subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=timeout,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"})


class TestSinkCrossProcess:
    def test_two_process_merge_roundtrip(self, tmp_path):
        sys.path.insert(0, os.path.join(_ROOT, "tools"))
        import metrics_export
        paths = []
        for i, (tok, ref) in enumerate(((11, 2), (31, 5))):
            p = str(tmp_path / f"m{i}.jsonl")
            r = _run_child(_CHILD_WRITE.format(root=_ROOT, tokens=tok,
                                               refused=ref, obs=50,
                                               path=p))
            assert r.returncode == 0, r.stderr[-800:]
            paths.append(p)
        merged = metrics_export.merge_files(paths)
        assert merged["serve_tokens_total"]["series"][0]["value"] == 42
        ref_series = merged["serve_refusals_total"]["series"]
        assert {tuple(r["labels"].items()): r["value"]
                for r in ref_series} == {(("reason", "queue_full"),): 7}
        assert merged["train_step_seconds"]["series"][0]["count"] == 100
        # renders as prometheus text without error
        text = pm.exposition(merged)
        assert "paddle_tpu_serve_tokens_total 42" in text

    def test_kill9_never_leaves_a_torn_sink(self, tmp_path):
        sys.path.insert(0, os.path.join(_ROOT, "tools"))
        import metrics_export
        p = str(tmp_path / "spin.jsonl")
        proc = subprocess.Popen(
            [sys.executable, "-c", _CHILD_SPIN.format(root=_ROOT, path=p)],
            stdout=subprocess.PIPE, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        try:
            assert proc.stdout.readline().strip() == "READY"
            deadline = time.time() + 60
            while not os.path.exists(p) and time.time() < deadline:
                time.sleep(0.01)
            time.sleep(0.2)            # let a few rewrite cycles race
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert os.path.exists(p)
        rows = metrics_export.read_sink(p)   # CRC-verified, never torn
        assert rows, "sink unreadable after kill -9"
        last = rows[-1]["metrics"]
        assert last["serve_tokens_total"]["series"][0]["value"] >= 1


# ---------------------------------------------------------------------------
# serving: TTFT / inter-token / queue-wait + spans + doctor live view
# ---------------------------------------------------------------------------

VOCAB = 128


@pytest.fixture(scope="module")
def smodel():
    from paddle_tpu.incubate.models import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _prompts(n, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, VOCAB, int(k)).tolist()
            for k in rng.integers(3, 16, n)]


class TestServingLatency:
    def test_engine_reports_ttft_and_inter_token(self, smodel):
        from paddle_tpu.serving import LLMEngine
        set_flags({"FLAGS_metrics": True})
        engine = LLMEngine(smodel, max_batch_size=4, block_size=4)
        engine.generate(_prompts(6, seed=1), max_new_tokens=5)
        s = engine.stats()
        # per engine: the satellite contract — first_token_ns finally
        # reaches stats(), plus the inter-token and queue-wait story
        for k in ("ttft_p50_ms", "ttft_p99_ms", "inter_token_p50_ms",
                  "inter_token_p99_ms", "queue_wait_p50_ms",
                  "queue_wait_p99_ms"):
            assert k in s
        assert s["ttft_p50_ms"] > 0
        assert s["inter_token_p50_ms"] > 0
        assert s["ttft_p99_ms"] >= s["ttft_p50_ms"]
        # registry sees the same stream
        assert pm.SERVE.ttft_s.count >= 6
        assert pm.SERVE.inter_token_s.count > 0
        assert pm.SERVE.tokens.value > 0

    def test_snapshot_keys_backward_compatible(self, smodel):
        """PR 6/7 consumers of ServeStats.snapshot() keep every key they
        had before the histogram replacement."""
        from paddle_tpu.serving import LLMEngine
        engine = LLMEngine(smodel, max_batch_size=2, block_size=4)
        engine.generate(_prompts(2, seed=2), max_new_tokens=3)
        s = engine.stats()
        for k in ("steps", "tokens_generated", "prefills",
                  "decode_compiles", "prefill_compiles", "admitted",
                  "evictions", "completed", "failed", "refused",
                  "refused_queue_full", "refused_deadline", "cancelled",
                  "expired", "hangs", "eager_fallbacks", "resumed",
                  "occupancy_mean", "occupancy_saturated", "p50_step_ms",
                  "p99_step_ms", "elapsed_s", "tokens_per_sec"):
            assert k in s, f"snapshot lost key {k}"
        assert s["p50_step_ms"] > 0
        # the admission wait estimate still has its recent raw samples
        assert engine._stats.step_times_s

    def test_no_percentile_freeze_on_long_engines(self):
        """The satellite itself: percentiles keep moving long past what
        the old 100k-list cap would have frozen."""
        from paddle_tpu.serving.engine import ServeStats
        st = ServeStats()
        st.step_hist = pm.LogHistogram(window=300)
        for _ in range(1000):
            st.step_hist.observe(0.001)
        frozen = st.snapshot()["p50_step_ms"]
        for _ in range(700):
            st.step_hist.observe(0.05)
        fresh = st.snapshot()["p50_step_ms"]
        assert abs(frozen - 1.0) < 0.2
        assert abs(fresh - 50.0) / 50.0 < 0.2

    def test_per_request_latency_handle(self, smodel):
        from paddle_tpu.serving import LLMEngine
        engine = LLMEngine(smodel, max_batch_size=2, block_size=4)
        req = engine.add_request(_prompts(1, seed=3)[0], max_new_tokens=6)
        engine.run()
        lat = req.latency()
        assert lat["tokens"] == 6
        assert lat["ttft_ms"] > 0
        assert lat["queue_wait_ms"] is not None \
            and lat["queue_wait_ms"] <= lat["ttft_ms"]
        assert lat["inter_token_p50_ms"] > 0
        assert lat["inter_token_p99_ms"] >= lat["inter_token_p50_ms"]

    @pytest.mark.perf_smoke
    def test_64_stream_churn_metrics_on_decode_compiles_once(self,
                                                            smodel):
        """Acceptance: under 64-stream churn with the telemetry plane
        ARMED, the engine reports TTFT/inter-token/queue-wait
        percentiles from the bounded histograms and the decode
        executable still compiles exactly once — instrumentation is
        host-side observation, never a traced shape."""
        from paddle_tpu.serving import LLMEngine
        set_flags({"FLAGS_metrics": True})
        engine = LLMEngine(smodel, max_batch_size=4, block_size=4)
        engine.generate(_prompts(64, seed=9), max_new_tokens=5)
        s = engine.stats()
        assert s["decode_compiles"] == 1
        assert s["completed"] == 64
        assert s["ttft_p99_ms"] > 0 and s["inter_token_p99_ms"] > 0
        assert s["queue_wait_p99_ms"] >= 0
        # bounded memory: the histograms never grew past their bands
        for h in (engine._stats.step_hist, engine._stats.ttft_hist,
                  engine._stats.inter_token_hist):
            assert len(h._cur) == len(pm.LogHistogram()._cur)
        assert pm.SERVE.requests.labels(outcome="completed").value == 64

    def test_refusal_and_outcome_counters(self, smodel):
        from paddle_tpu.serving import LLMEngine, ServeRefusal
        set_flags({"FLAGS_metrics": True})
        engine = LLMEngine(smodel, max_batch_size=1, block_size=4,
                           max_queue_depth=2)
        p = _prompts(1, seed=4)[0]
        engine.add_request(p, max_new_tokens=3)
        engine.add_request(p, max_new_tokens=3)     # fills the queue
        with pytest.raises(ServeRefusal):
            engine.add_request(p, max_new_tokens=3)
        engine.run()
        assert pm.SERVE.refusals.labels(reason="queue_full").value == 1
        assert pm.SERVE.requests.labels(outcome="completed").value == 2


class TestServeSpans:
    def test_request_span_lifecycle_in_chrome_trace(self, smodel):
        """Per-request trace spans (the tentpole's third surface): each
        request projects an async begin at enqueue, an admit instant,
        and an end at completion — ordered — beside the fusion lanes."""
        from paddle_tpu.serving import LLMEngine
        clear_fusion_events()
        set_flags({"FLAGS_profiler_events": True})
        try:
            engine = LLMEngine(smodel, max_batch_size=2, block_size=4)
            reqs = [engine.add_request(p, max_new_tokens=3)
                    for p in _prompts(2, seed=5)]
            engine.run()
            ev = fusion_events()
        finally:
            set_flags({"FLAGS_profiler_events": False})
        trace = _fusion_trace_events(ev)
        lanes = [t["args"]["name"] for t in trace if t.get("ph") == "M"]
        assert "fusion:serve" in lanes
        for r in reqs:
            spans = [t for t in trace if t.get("cat") == "serve.request"
                     and t.get("id") == r.rid]
            phases = [t["ph"] for t in spans]
            assert phases[0] == "b" and phases[-1] == "e", (r.rid, phases)
            assert "n" in phases                       # admit instant
            ts = [t["ts"] for t in spans]
            assert ts == sorted(ts)
            ends = [t for t in spans if t["ph"] == "e"]
            assert ends[0]["args"]["outcome"] == "complete"
        # engine-wide decode ticks ride the serve lane as instants
        serve_tid = 0x7F5E0004
        assert any(t.get("tid") == serve_tid and t.get("ph") == "i"
                   and "serve.step" in t["name"] for t in trace)

    def test_cancelled_request_span_closes(self, smodel):
        from paddle_tpu.serving import LLMEngine
        clear_fusion_events()
        set_flags({"FLAGS_profiler_events": True})
        try:
            engine = LLMEngine(smodel, max_batch_size=2, block_size=4)
            req = engine.add_request(_prompts(1, seed=6)[0],
                                     max_new_tokens=8)
            engine.step()
            engine.cancel(req.rid)
            ev = fusion_events()
        finally:
            set_flags({"FLAGS_profiler_events": False})
        spans = [t for t in _fusion_trace_events(ev)
                 if t.get("cat") == "serve.request"
                 and t.get("id") == req.rid]
        assert spans[-1]["ph"] == "e"
        assert spans[-1]["args"]["outcome"] == "cancel"


# ---------------------------------------------------------------------------
# goodput accounting
# ---------------------------------------------------------------------------

def _train_loop(steps, d=32):
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((16, d)).astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((d, d)).astype(np.float32),
                         stop_gradient=False)
    b = paddle.to_tensor(rng.standard_normal(d).astype(np.float32),
                         stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=1e-3, parameters=[w, b])
    for _ in range(steps):
        y = F.gelu(paddle.add(paddle.matmul(x, w), b))
        loss = y.sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    w._value.block_until_ready()


class TestGoodput:
    def test_live_mfu_within_2pct_of_offline(self):
        """Acceptance: the registry-read MFU/tokens-per-second must match
        the pre-PR 12 offline computation (tokens x flops / elapsed /
        peak) on the same run — the exact TrainStep shape bench.py
        measures."""
        import jax.numpy as jnp
        from paddle_tpu.incubate.models import (GPTConfig, GPTForCausalLM,
                                                GPTPretrainingCriterion)
        from paddle_tpu.jit import TrainStep
        paddle.seed(0)
        seq, batch, steps = 64, 2, 12
        cfg = GPTConfig(vocab_size=256, hidden_size=64,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=128,
                        max_position_embeddings=seq,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        crit = GPTPretrainingCriterion()
        step = TrainStep(model, lambda lg, y: crit(lg, y), opt)
        rng = np.random.default_rng(0)
        x = paddle.Tensor(jnp.asarray(
            rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32),
            stop_gradient=True)
        y = paddle.Tensor(jnp.asarray(
            rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32),
            stop_gradient=True)
        float(step(x, y))                          # compile
        set_flags({"FLAGS_metrics": True})
        fpt = model.flops_per_token(seq, training=True)
        peak = pg.peak_flops_per_chip()
        pg.ACCOUNTANT.reset(warm=True)
        pg.ACCOUNTANT.set_flops_per_step(fpt * batch * seq,
                                         tokens=batch * seq, peak=peak)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(x, y)
        float(loss)
        pg.ACCOUNTANT.finalize()
        elapsed = time.perf_counter() - t0
        snap = pg.ACCOUNTANT.snapshot()
        offline_tps = batch * seq * steps / elapsed
        offline_mfu = offline_tps * fpt / peak
        assert snap["steps"] == steps
        assert abs(snap["tokens_per_sec"] - offline_tps) / offline_tps \
            < 0.02, (snap["tokens_per_sec"], offline_tps)
        assert abs(snap["mfu"] - offline_mfu) / offline_mfu < 0.02
        assert snap["goodput"] == 1.0              # clean steady window
        # the registry gauges carry the same numbers
        reg = pm.metrics_snapshot()
        assert reg["train_mfu"]["series"][0]["value"] \
            == pytest.approx(snap["mfu"], rel=1e-2, abs=1e-6)

    @pytest.mark.filterwarnings(
        "ignore:Operator .* produced a non-finite output")
    def test_guardian_skip_attributed(self):
        """Acceptance: goodput correctly attributes injected
        guardian-skip time (guardian.inject_fault reuse)."""
        clear_dispatch_cache()
        # per-op dispatch only: the dispatch-level fault hook is not
        # consulted for ops replayed inside fused chains/steps
        set_flags({"FLAGS_metrics": True, "FLAGS_check_numerics": True,
                   "FLAGS_check_numerics_level": 1,
                   "FLAGS_eager_chain_fusion": False,
                   "FLAGS_eager_step_fusion": False})
        pg.ACCOUNTANT.reset(warm=True)
        guardian.inject_fault("nan_output", op="matmul", after=3, times=1)
        try:
            _train_loop(10)
            guardian.flush()
            pg.ACCOUNTANT.step_boundary()   # boundary after the flush
        finally:
            guardian.clear_faults()
        snap = pg.ACCOUNTANT.snapshot()
        assert guardian.guardian_stats()["steps_skipped"] >= 1
        assert snap["buckets_s"]["skipped"] > 0, snap["buckets_s"]
        assert snap["goodput"] < 1.0

    def test_watchdog_stall_attributed(self, smodel):
        """Acceptance: an injected decode hang lands its watchdog budget
        in the stalled bucket and bumps serve_hangs_total."""
        from paddle_tpu.serving import LLMEngine
        set_flags({"FLAGS_metrics": True,
                   "FLAGS_serve_step_timeout_ms": 2000})
        try:
            engine = LLMEngine(smodel, max_batch_size=2, block_size=4)
            reqs = [engine.add_request(p, max_new_tokens=6)
                    for p in _prompts(2, seed=7)]
            engine.step()
            pg.ACCOUNTANT.reset(warm=True)
            guardian.inject_fault("hang", op="serve.decode", times=1)
            engine.run()
        finally:
            guardian.clear_faults()
            set_flags({"FLAGS_serve_step_timeout_ms": 0})
        snap = pg.ACCOUNTANT.snapshot()
        assert pm.SERVE.hangs.value == 1
        assert snap["buckets_s"]["stalled"] >= 2.0   # the 2s budget
        # no double count: the stalled seconds must NOT also appear in
        # productive (the recovered decode step's dt spans the hang)
        assert snap["buckets_s"]["productive"] < 1.0, snap["buckets_s"]
        assert snap["goodput"] < 0.5
        assert all(r.finished for r in reqs)

    def test_cycle_derived_flops(self):
        """With nothing pinned, the accountant derives analytic
        FLOPs/step from the promoted cycle's recorded op keys (matmul
        dominates: 3 x 2mnk for fwd+bwd)."""
        clear_dispatch_cache()
        set_flags({"FLAGS_metrics": True,
                   "FLAGS_eager_step_fusion_min_count": 4})
        pg.ACCOUNTANT.reset(warm=True)
        _train_loop(12)
        snap = pg.ACCOUNTANT.snapshot()
        assert snap["flops_source"] == "cycle"
        expect = 3 * 2 * 16 * 32 * 32              # the matmul term
        assert expect <= snap["flops_per_step"] <= expect * 1.25
        assert snap["mfu"] > 0

    def test_explain_serving_cites_live_metrics(self, smodel):
        """Satellite: a degraded engine's doctor report carries the live
        p99/refusal view, not just event counts."""
        from paddle_tpu.serving import LLMEngine
        clear_fusion_events()
        set_flags({"FLAGS_metrics": True, "FLAGS_profiler_events": True,
                   "FLAGS_serve_step_timeout_ms": 2000})
        try:
            engine = LLMEngine(smodel, max_batch_size=2, block_size=4)
            for p in _prompts(2, seed=8):
                engine.add_request(p, max_new_tokens=5)
            engine.step()
            guardian.inject_fault("hang", op="serve.decode", times=1)
            engine.run()
            rep = explain(fusion_events())
        finally:
            guardian.clear_faults()
            set_flags({"FLAGS_profiler_events": False,
                       "FLAGS_serve_step_timeout_ms": 0})
        assert rep["verdict"] == "serving_degraded"
        live = rep["serving"]["live"]
        assert live["p99_step_ms"] > 0
        assert live["hangs"] == 1
        assert "[live:" in rep["headline"]


# ---------------------------------------------------------------------------
# perf_smoke-marked mirrors of CLI leg (k)'s non-timing guards
# ---------------------------------------------------------------------------

@pytest.mark.perf_smoke
class TestPerfGuards:
    def test_off_gate_is_silent_and_histogram_bounded(self):
        assert not pm.enabled()
        h = pm.TRAIN.step_s._default
        for _ in range(10_000):
            pm.TRAIN.step_s.observe(0.001)
        assert h.count == 0
        set_flags({"FLAGS_metrics": True})
        g = pm.LogHistogram(window=2_000)
        g.observe(0.001)
        n0, s0 = len(g._cur), sys.getsizeof(g._cur)
        for i in range(20_000):
            g.observe(0.0001 * (1 + i % 57))
        assert (len(g._cur), sys.getsizeof(g._cur)) == (n0, s0)

    def test_metrics_demo_fixture(self):
        """`fusion_doctor --demo metrics` stays a working acceptance
        fixture: live registry + goodput below 1.0 with the injected
        guardian skip attributed."""
        r = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "tools",
                                          "fusion_doctor.py"),
             "--demo", "metrics", "--steps", "12", "--json"],
            capture_output=True, text=True, timeout=420,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stderr[-800:]
        rep = json.loads(r.stdout)
        assert rep["goodput"]["goodput"] < 1.0
        assert rep["goodput"]["buckets_s"]["skipped"] > 0
        assert set(rep["metrics"]) == set(pm.METRIC_NAMES)
        g = rep["metrics"]["guardian_events_total"]["series"]
        skipped = [s for s in g
                   if s["labels"].get("event") == "steps_skipped"]
        assert skipped and skipped[0]["value"] >= 1
