"""Serving engine suite (paddle_tpu/serving): continuous batching, paged
KV cache, ONE compiled decode step.

The contracts pinned here are the ISSUE 6 acceptance criteria:

  * decode output is token-identical to `model.generate(do_sample=False)`
    for every stream, whatever the batch composition;
  * a stream already running keeps producing ITS tokens bit-for-bit when
    other requests join or leave mid-flight (iteration-level batching
    must not perturb neighbors);
  * preemption (KV pool dry -> evict -> re-prefill -> resume) is
    token-equivalent to never having been preempted;
  * a request whose peak KV footprint can never fit is refused at
    admission (attributed `kv_exhausted`), not deadlocked;
  * the decode executable compiles exactly ONCE while 64 mixed-length
    streams churn through the slots (zero retraces).

The scheduler tests are pure host-side policy checks (no jax work).
"""
from __future__ import annotations

import os

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import paddle_tpu as paddle
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.incubate.models import GPTConfig, GPTForCausalLM
from paddle_tpu.profiler.events import clear_fusion_events, fusion_events
from paddle_tpu.profiler.explain import explain
from paddle_tpu.serving import (BlockAllocator, LLMEngine, Request,
                                Scheduler, NULL_BLOCK, QUEUED, RUNNING,
                                FINISHED)

VOCAB = 128


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _prompt(length, seed=0):
    rng = np.random.default_rng(seed * 1000 + length)
    return rng.integers(0, VOCAB, length).tolist()


_REF_CACHE = {}


def _ref(model, prompt, n):
    """Greedy reference through model.generate (ONE XLA scan program per
    prompt length — memoized so the module compiles each length once)."""
    key = (tuple(prompt), n)
    if key not in _REF_CACHE:
        out = model.generate(paddle.Tensor(np.asarray([prompt], np.int64)),
                             max_new_tokens=n, do_sample=False)
        arr = out._value if hasattr(out, "_value") else out
        _REF_CACHE[key] = np.asarray(arr)[0].tolist()
    return _REF_CACHE[key]


# ---------------------------------------------------------------------------
# scheduler policy (pure host-side, no jax)
# ---------------------------------------------------------------------------

class TestSchedulerPolicy:
    def _sched(self, num_slots=2, num_blocks=9, block_size=4,
               watermark=1):
        alloc = BlockAllocator(num_blocks)
        return Scheduler(num_slots, alloc, block_size,
                         watermark_blocks=watermark), alloc

    def test_allocator_all_or_nothing_and_null_guard(self):
        alloc = BlockAllocator(4)
        assert alloc.capacity == 3
        assert alloc.allocate(4) is None          # more than free: nothing
        got = alloc.allocate(3)
        assert len(got) == 3 and NULL_BLOCK not in got
        with pytest.raises(ValueError):
            alloc.free([NULL_BLOCK])
        alloc.free(got)
        assert alloc.num_free == 3

    def test_fcfs_head_only_no_skipping(self):
        sched, _ = self._sched(num_slots=2, num_blocks=9, watermark=0)
        big = Request("big", list(range(20)), 4)     # needs 6 blocks
        small = Request("small", [1], 2)             # needs 1 block
        sched.enqueue(big)
        sched.enqueue(small)
        # head needs 6 of 8 free; admit it, then the pool can't take the
        # NEXT head... admit everything that fits in arrival order only
        first = sched.try_admit()
        assert first is big                           # strict FCFS
        second = sched.try_admit()
        assert second is small

    def test_watermark_blocks_admission(self):
        sched, alloc = self._sched(num_slots=2, num_blocks=9, watermark=2)
        # 8 allocatable; a 20-token context needs 6 blocks -> 2 left ==
        # watermark: OK. A second 4-token request (2 blocks) would leave
        # 0 < watermark: refused for now (stays QUEUED, not failed)
        a = Request("a", list(range(20)), 2)
        b = Request("b", [1, 2, 3, 4], 2)
        sched.enqueue(a)
        sched.enqueue(b)
        assert sched.try_admit() is a
        assert sched.try_admit() is None
        assert b.state == QUEUED
        assert sched.waiting == [b]

    def test_growth_dips_into_watermark(self):
        sched, alloc = self._sched(num_slots=1, num_blocks=4, watermark=2)
        r = Request("r", [1, 2, 3], 8)
        sched.enqueue(r)
        assert sched.try_admit() is r                 # 1 block, 2 free left
        assert sched.grow(r) and sched.grow(r)        # growth ignores mark
        assert alloc.num_free == 0

    def test_preempt_victim_is_lifo_and_requeue_keeps_arrival_order(self):
        sched, _ = self._sched(num_slots=3, num_blocks=20, watermark=0)
        reqs = [Request(f"r{i}", [1, 2], 4) for i in range(3)]
        for r in reqs:
            sched.enqueue(r)
        for _ in range(3):
            assert sched.try_admit() is not None
        victim = sched.preempt_victim()
        assert victim is reqs[2]                      # newest admission
        sched.preempt(victim)
        assert victim.state == QUEUED and victim.blocks == []
        assert victim.preemptions == 1
        late = Request("late", [1], 2)
        sched.enqueue(late)
        # the preempted request resumes BEFORE later arrivals
        assert sched.waiting.index(victim) < sched.waiting.index(late)

    def test_release_returns_blocks_and_slot(self):
        sched, alloc = self._sched(num_slots=1, num_blocks=9, watermark=0)
        r = Request("r", list(range(6)), 2)
        sched.enqueue(r)
        sched.try_admit()
        held = list(r.blocks)
        assert held
        sched.release(r)
        assert alloc.num_free == 8 and sched.slots == [None]

    def test_can_ever_fit_respects_watermark(self):
        sched, _ = self._sched(num_slots=1, num_blocks=4, block_size=4,
                               watermark=0)
        assert sched.block_budget() == 3
        assert sched.can_ever_fit(Request("ok", [1] * 8, 4))      # 3 blocks
        assert not sched.can_ever_fit(Request("big", [1] * 8, 20))
        # the watermark reserve is never granted: a request needing the
        # WHOLE pool can never be admitted once a reserve exists
        sched2, _ = self._sched(num_slots=1, num_blocks=4, block_size=4,
                                watermark=1)
        assert not sched2.can_ever_fit(Request("ok", [1] * 8, 4))


# ---------------------------------------------------------------------------
# engine: parity / continuity / preemption / refusal / zero-retrace
# ---------------------------------------------------------------------------

class TestDecodeParity:
    def test_mixed_length_batch_matches_generate(self, model):
        prompts = [_prompt(n) for n in (11, 5, 17, 3)]
        refs = [_ref(model, p, 10) for p in prompts]
        engine = LLMEngine(model, max_batch_size=4, block_size=4)
        outs = engine.generate(prompts, max_new_tokens=10)
        assert outs == refs
        st = engine.stats()
        assert st["decode_compiles"] == 1
        assert st["completed"] == 4

    def test_eos_stops_a_stream_early(self, model):
        p = _prompt(7)
        ref = _ref(model, p, 12)
        eos = ref[4]                       # force a stop mid-stream
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        req = engine.add_request(p, max_new_tokens=12, eos_token_id=eos)
        engine.run()
        assert req.state == FINISHED
        # stop at the FIRST occurrence (a tiny model may repeat tokens)
        stop = ref.index(eos)
        assert req.generated == ref[:stop + 1]
        assert len(req.generated) < 12

    def test_streaming_callbacks_fire_per_token(self, model):
        p = _prompt(9)
        ref = _ref(model, p, 8)
        seen = []
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        engine.add_request(p, max_new_tokens=8,
                           on_token=lambda r, tok, text: seen.append(tok))
        engine.run()
        assert seen == ref                 # streamed in generation order


class TestContinuousBatching:
    def test_join_mid_flight_keeps_running_stream_bitwise(self, model):
        """A request joining the batch must not perturb a stream that is
        already decoding: same tokens as a solo run, bit for bit."""
        pa, pb = _prompt(13, seed=1), _prompt(6, seed=2)
        ref_a = _ref(model, pa, 12)
        ref_b = _ref(model, pb, 8)
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        ra = engine.add_request(pa, max_new_tokens=12)
        for _ in range(5):                 # a is mid-flight...
            engine.step()
        tokens_before = list(ra.generated)
        assert tokens_before == ref_a[:len(tokens_before)]
        rb = engine.add_request(pb, max_new_tokens=8)   # ...b joins
        engine.run()
        assert ra.generated == ref_a       # a never noticed
        assert rb.generated == ref_b
        assert engine.stats()["decode_compiles"] == 1

    def test_departure_mid_flight_keeps_survivors_bitwise(self, model):
        """Short streams finishing and leaving slots must not perturb the
        longer streams still running."""
        long_p, short_p = _prompt(10, seed=3), _prompt(4, seed=4)
        ref_long = _ref(model, long_p, 14)
        engine = LLMEngine(model, max_batch_size=3, block_size=4)
        rl = engine.add_request(long_p, max_new_tokens=14)
        rs = engine.add_request(short_p, max_new_tokens=2)
        engine.run()
        assert rs.state == FINISHED and len(rs.generated) == 2
        assert rl.generated == ref_long

    def test_preempt_resume_token_equivalence(self, model):
        """A deliberately tight pool forces eviction; the evicted stream
        re-prefills from its block-table-less state and must still match
        the never-preempted reference."""
        prompts = [_prompt(n, seed=5) for n in (11, 12, 10, 5)]
        refs = [_ref(model, p, 10) for p in prompts]
        engine = LLMEngine(model, max_batch_size=3, block_size=4,
                           num_blocks=10, watermark_blocks=1)
        outs = engine.generate(prompts, max_new_tokens=10)
        st = engine.stats()
        assert st["evictions"] >= 1        # the tight pool actually bit
        assert outs == refs
        assert st["decode_compiles"] == 1  # eviction is a table edit
        assert any(r.preemptions for r in engine.requests.values())

    def test_kv_exhaustion_admission_refusal(self, model):
        """A request whose PEAK footprint exceeds the pool budget can
        never be served: refuse at admission instead of deadlocking the
        queue."""
        engine = LLMEngine(model, max_batch_size=2, block_size=4,
                           num_blocks=6, watermark_blocks=1)
        with pytest.raises(ValueError, match="KV blocks at peak"):
            engine.add_request(_prompt(20), max_new_tokens=20)
        assert engine.stats()["refused"] == 1
        # a request that merely can't fit RIGHT NOW queues instead
        ok = engine.add_request(_prompt(4), max_new_tokens=4)
        assert ok.state == QUEUED

    def test_context_overflow_refused(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        with pytest.raises(ValueError, match="exceeds max_context"):
            engine.add_request(_prompt(40), max_new_tokens=60)
        with pytest.raises(ValueError, match="empty prompt"):
            engine.add_request([], max_new_tokens=4)

    def test_duplicate_active_request_id_refused(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        engine.add_request(_prompt(5), max_new_tokens=4, request_id="x")
        with pytest.raises(ValueError, match="already queued/running"):
            engine.add_request(_prompt(6), max_new_tokens=4,
                               request_id="x")
        engine.run()
        # finished ids may be reused (the old handle is replaced)
        again = engine.add_request(_prompt(6), max_new_tokens=4,
                                   request_id="x")
        engine.run()
        assert again.state == FINISHED


class TestZeroRetrace:
    def test_64_mixed_streams_one_decode_compile(self, model):
        """The acceptance criterion: 64 concurrent mixed-length requests
        churning through 8 slots, ONE decode trace, every stream
        token-identical to generate()."""
        lengths = (3, 5, 8, 11, 16, 21)
        uniques = {n: _prompt(n, seed=7) for n in lengths}
        refs = {n: _ref(model, p, 6) for n, p in uniques.items()}
        prompts = [uniques[lengths[i % len(lengths)]] for i in range(64)]
        engine = LLMEngine(model, max_batch_size=8, block_size=4)
        outs = engine.generate(prompts, max_new_tokens=6)
        st = engine.stats()
        assert st["decode_compiles"] == 1
        assert st["completed"] == 64
        # prefill buckets are the pow-2 cover of the lengths, compiled
        # once each — admission never touches the decode program
        assert st["prefill_compiles"] <= 5
        for i, out in enumerate(outs):
            assert out == refs[lengths[i % len(lengths)]], f"stream {i}"

    @pytest.mark.perf_smoke
    def test_churn_occupancy_saturated(self, model):
        """perf_smoke guard (mirrors tools/perf_smoke.py leg e): under
        saturation (demand >= slots) continuous batching must keep the
        slots >= 75% full, and the decode program must not retrace."""
        prompts = [_prompt(3 + (i % 9), seed=8) for i in range(24)]
        engine = LLMEngine(model, max_batch_size=4, block_size=4)
        engine.generate(prompts, max_new_tokens=5)
        st = engine.stats()
        assert st["decode_compiles"] == 1
        assert st["occupancy_saturated"] >= 0.75

    def test_reset_stats_opens_a_clean_window(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        engine.generate([_prompt(5)], max_new_tokens=3)   # warmup
        engine.reset_stats()
        engine.generate([_prompt(5)], max_new_tokens=3)
        st = engine.stats()
        assert st["decode_compiles"] == 0       # no retrace in the window
        assert st["completed"] == 1


# ---------------------------------------------------------------------------
# telemetry: serve.* events through the flight recorder + doctor
# ---------------------------------------------------------------------------

class TestServeTelemetry:
    def test_lifecycle_events_and_doctor_verdict(self, model):
        clear_fusion_events()
        set_flags({"FLAGS_profiler_events": True})
        try:
            prompts = [_prompt(n, seed=9) for n in (11, 12, 10, 5, 7)]
            engine = LLMEngine(model, max_batch_size=3, block_size=4,
                               num_blocks=10, watermark_blocks=1)
            engine.generate(prompts, max_new_tokens=6)
            ev = fusion_events()
        finally:
            set_flags({"FLAGS_profiler_events": False})
            clear_fusion_events()
        cats = {e["cat"] for e in ev}
        assert {"serve.enqueue", "serve.admit", "serve.step",
                "serve.complete"} <= cats
        evicts = [e for e in ev if e["cat"] == "serve.evict"]
        assert evicts and all(e["reason"] == "kv_exhausted" for e in evicts)
        resumed = [e for e in ev if e["cat"] == "serve.admit"
                   and (e.get("detail") or {}).get("resumed")]
        assert resumed                       # the evicted stream came back
        rep = explain(ev)
        assert rep["verdict"] == "serving"
        sv = rep["serving"]
        assert sv["completed"] == len(prompts)
        assert sv["evictions"] == len(evicts)
        assert sv["occupancy_mean"] is not None
        assert "kv_exhausted" in sv["reasons"]
        assert any("kv_exhausted" in f for f in rep["findings"])

    def test_refusal_attributed_kv_exhausted(self, model):
        clear_fusion_events()
        set_flags({"FLAGS_profiler_events": True})
        try:
            engine = LLMEngine(model, max_batch_size=2, block_size=4,
                               num_blocks=6, watermark_blocks=1)
            with pytest.raises(ValueError):
                engine.add_request(_prompt(20), max_new_tokens=20)
            ev = fusion_events()
        finally:
            set_flags({"FLAGS_profiler_events": False})
            clear_fusion_events()
        refusals = [e for e in ev if e["cat"] == "serve.enqueue"
                    and e["reason"] == "kv_exhausted"]
        assert len(refusals) == 1
        d = refusals[0]["detail"]
        assert d["blocks_needed"] > d["blocks_budget"]
