"""Serving engine suite (paddle_tpu/serving): continuous batching, paged
KV cache, ONE compiled decode step.

The contracts pinned here are the ISSUE 6 acceptance criteria:

  * decode output is token-identical to `model.generate(do_sample=False)`
    for every stream, whatever the batch composition;
  * a stream already running keeps producing ITS tokens bit-for-bit when
    other requests join or leave mid-flight (iteration-level batching
    must not perturb neighbors);
  * preemption (KV pool dry -> evict -> re-prefill -> resume) is
    token-equivalent to never having been preempted;
  * a request whose peak KV footprint can never fit is refused at
    admission (attributed `kv_exhausted`), not deadlocked;
  * the decode executable compiles exactly ONCE while 64 mixed-length
    streams churn through the slots (zero retraces).

The scheduler tests are pure host-side policy checks (no jax work).
"""
from __future__ import annotations

import os

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import time

import paddle_tpu as paddle
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.incubate.models import GPTConfig, GPTForCausalLM
from paddle_tpu.ops import guardian
from paddle_tpu.profiler.events import clear_fusion_events, fusion_events
from paddle_tpu.profiler.explain import explain
from paddle_tpu.serving import (BlockAllocator, LLMEngine, Request,
                                Scheduler, ServeRefusal, NULL_BLOCK,
                                QUEUED, RUNNING, FINISHED, FAILED,
                                CANCELLED, EXPIRED)

VOCAB = 128


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _prompt(length, seed=0):
    rng = np.random.default_rng(seed * 1000 + length)
    return rng.integers(0, VOCAB, length).tolist()


_REF_CACHE = {}


def _ref(model, prompt, n):
    """Greedy reference through model.generate (ONE XLA scan program per
    prompt length — memoized so the module compiles each length once)."""
    key = (tuple(prompt), n)
    if key not in _REF_CACHE:
        out = model.generate(paddle.Tensor(np.asarray([prompt], np.int64)),
                             max_new_tokens=n, do_sample=False)
        arr = out._value if hasattr(out, "_value") else out
        _REF_CACHE[key] = np.asarray(arr)[0].tolist()
    return _REF_CACHE[key]


# ---------------------------------------------------------------------------
# scheduler policy (pure host-side, no jax)
# ---------------------------------------------------------------------------

class TestSchedulerPolicy:
    def _sched(self, num_slots=2, num_blocks=9, block_size=4,
               watermark=1):
        alloc = BlockAllocator(num_blocks)
        return Scheduler(num_slots, alloc, block_size,
                         watermark_blocks=watermark), alloc

    def test_allocator_all_or_nothing_and_null_guard(self):
        alloc = BlockAllocator(4)
        assert alloc.capacity == 3
        assert alloc.allocate(4) is None          # more than free: nothing
        got = alloc.allocate(3)
        assert len(got) == 3 and NULL_BLOCK not in got
        with pytest.raises(ValueError):
            alloc.free([NULL_BLOCK])
        alloc.free(got)
        assert alloc.num_free == 3

    def test_fcfs_head_only_no_skipping(self):
        sched, _ = self._sched(num_slots=2, num_blocks=9, watermark=0)
        big = Request("big", list(range(20)), 4)     # needs 6 blocks
        small = Request("small", [1], 2)             # needs 1 block
        sched.enqueue(big)
        sched.enqueue(small)
        # head needs 6 of 8 free; admit it, then the pool can't take the
        # NEXT head... admit everything that fits in arrival order only
        first = sched.try_admit()
        assert first is big                           # strict FCFS
        second = sched.try_admit()
        assert second is small

    def test_watermark_blocks_admission(self):
        sched, alloc = self._sched(num_slots=2, num_blocks=9, watermark=2)
        # 8 allocatable; a 20-token context needs 6 blocks -> 2 left ==
        # watermark: OK. A second 4-token request (2 blocks) would leave
        # 0 < watermark: refused for now (stays QUEUED, not failed)
        a = Request("a", list(range(20)), 2)
        b = Request("b", [1, 2, 3, 4], 2)
        sched.enqueue(a)
        sched.enqueue(b)
        assert sched.try_admit() is a
        assert sched.try_admit() is None
        assert b.state == QUEUED
        assert sched.waiting == [b]

    def test_growth_dips_into_watermark(self):
        sched, alloc = self._sched(num_slots=1, num_blocks=4, watermark=2)
        r = Request("r", [1, 2, 3], 8)
        sched.enqueue(r)
        assert sched.try_admit() is r                 # 1 block, 2 free left
        assert sched.grow(r) and sched.grow(r)        # growth ignores mark
        assert alloc.num_free == 0

    def test_preempt_victim_is_lifo_and_requeue_keeps_arrival_order(self):
        sched, _ = self._sched(num_slots=3, num_blocks=20, watermark=0)
        reqs = [Request(f"r{i}", [1, 2], 4) for i in range(3)]
        for r in reqs:
            sched.enqueue(r)
        for _ in range(3):
            assert sched.try_admit() is not None
        victim = sched.preempt_victim()
        assert victim is reqs[2]                      # newest admission
        sched.preempt(victim)
        assert victim.state == QUEUED and victim.blocks == []
        assert victim.preemptions == 1
        late = Request("late", [1], 2)
        sched.enqueue(late)
        # the preempted request resumes BEFORE later arrivals
        assert sched.waiting.index(victim) < sched.waiting.index(late)

    def test_release_returns_blocks_and_slot(self):
        sched, alloc = self._sched(num_slots=1, num_blocks=9, watermark=0)
        r = Request("r", list(range(6)), 2)
        sched.enqueue(r)
        sched.try_admit()
        held = list(r.blocks)
        assert held
        sched.release(r)
        assert alloc.num_free == 8 and sched.slots == [None]

    def test_can_ever_fit_respects_watermark(self):
        sched, _ = self._sched(num_slots=1, num_blocks=4, block_size=4,
                               watermark=0)
        assert sched.block_budget() == 3
        assert sched.can_ever_fit(Request("ok", [1] * 8, 4))      # 3 blocks
        assert not sched.can_ever_fit(Request("big", [1] * 8, 20))
        # the watermark reserve is never granted: a request needing the
        # WHOLE pool can never be admitted once a reserve exists
        sched2, _ = self._sched(num_slots=1, num_blocks=4, block_size=4,
                                watermark=1)
        assert not sched2.can_ever_fit(Request("ok", [1] * 8, 4))


# ---------------------------------------------------------------------------
# engine: parity / continuity / preemption / refusal / zero-retrace
# ---------------------------------------------------------------------------

class TestDecodeParity:
    def test_mixed_length_batch_matches_generate(self, model):
        prompts = [_prompt(n) for n in (11, 5, 17, 3)]
        refs = [_ref(model, p, 10) for p in prompts]
        engine = LLMEngine(model, max_batch_size=4, block_size=4)
        outs = engine.generate(prompts, max_new_tokens=10)
        assert outs == refs
        st = engine.stats()
        assert st["decode_compiles"] == 1
        assert st["completed"] == 4

    def test_eos_stops_a_stream_early(self, model):
        p = _prompt(7)
        ref = _ref(model, p, 12)
        eos = ref[4]                       # force a stop mid-stream
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        req = engine.add_request(p, max_new_tokens=12, eos_token_id=eos)
        engine.run()
        assert req.state == FINISHED
        # stop at the FIRST occurrence (a tiny model may repeat tokens)
        stop = ref.index(eos)
        assert req.generated == ref[:stop + 1]
        assert len(req.generated) < 12

    def test_streaming_callbacks_fire_per_token(self, model):
        p = _prompt(9)
        ref = _ref(model, p, 8)
        seen = []
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        engine.add_request(p, max_new_tokens=8,
                           on_token=lambda r, tok, text: seen.append(tok))
        engine.run()
        assert seen == ref                 # streamed in generation order


class TestContinuousBatching:
    def test_join_mid_flight_keeps_running_stream_bitwise(self, model):
        """A request joining the batch must not perturb a stream that is
        already decoding: same tokens as a solo run, bit for bit."""
        pa, pb = _prompt(13, seed=1), _prompt(6, seed=2)
        ref_a = _ref(model, pa, 12)
        ref_b = _ref(model, pb, 8)
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        ra = engine.add_request(pa, max_new_tokens=12)
        for _ in range(5):                 # a is mid-flight...
            engine.step()
        tokens_before = list(ra.generated)
        assert tokens_before == ref_a[:len(tokens_before)]
        rb = engine.add_request(pb, max_new_tokens=8)   # ...b joins
        engine.run()
        assert ra.generated == ref_a       # a never noticed
        assert rb.generated == ref_b
        assert engine.stats()["decode_compiles"] == 1

    def test_departure_mid_flight_keeps_survivors_bitwise(self, model):
        """Short streams finishing and leaving slots must not perturb the
        longer streams still running."""
        long_p, short_p = _prompt(10, seed=3), _prompt(4, seed=4)
        ref_long = _ref(model, long_p, 14)
        engine = LLMEngine(model, max_batch_size=3, block_size=4)
        rl = engine.add_request(long_p, max_new_tokens=14)
        rs = engine.add_request(short_p, max_new_tokens=2)
        engine.run()
        assert rs.state == FINISHED and len(rs.generated) == 2
        assert rl.generated == ref_long

    def test_preempt_resume_token_equivalence(self, model):
        """A deliberately tight pool forces eviction; the evicted stream
        re-prefills from its block-table-less state and must still match
        the never-preempted reference."""
        prompts = [_prompt(n, seed=5) for n in (11, 12, 10, 5)]
        refs = [_ref(model, p, 10) for p in prompts]
        engine = LLMEngine(model, max_batch_size=3, block_size=4,
                           num_blocks=10, watermark_blocks=1)
        outs = engine.generate(prompts, max_new_tokens=10)
        st = engine.stats()
        assert st["evictions"] >= 1        # the tight pool actually bit
        assert outs == refs
        assert st["decode_compiles"] == 1  # eviction is a table edit
        assert any(r.preemptions for r in engine.requests.values())

    def test_kv_exhaustion_admission_refusal(self, model):
        """A request whose PEAK footprint exceeds the pool budget can
        never be served: refuse at admission instead of deadlocking the
        queue."""
        engine = LLMEngine(model, max_batch_size=2, block_size=4,
                           num_blocks=6, watermark_blocks=1)
        with pytest.raises(ValueError, match="KV blocks at peak"):
            engine.add_request(_prompt(20), max_new_tokens=20)
        assert engine.stats()["refused"] == 1
        # a request that merely can't fit RIGHT NOW queues instead
        ok = engine.add_request(_prompt(4), max_new_tokens=4)
        assert ok.state == QUEUED

    def test_context_overflow_refused(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        with pytest.raises(ValueError, match="exceeds max_context"):
            engine.add_request(_prompt(40), max_new_tokens=60)
        with pytest.raises(ValueError, match="empty prompt"):
            engine.add_request([], max_new_tokens=4)

    def test_duplicate_active_request_id_refused(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        engine.add_request(_prompt(5), max_new_tokens=4, request_id="x")
        with pytest.raises(ValueError, match="already queued/running"):
            engine.add_request(_prompt(6), max_new_tokens=4,
                               request_id="x")
        engine.run()
        # finished ids may be reused (the old handle is replaced)
        again = engine.add_request(_prompt(6), max_new_tokens=4,
                                   request_id="x")
        engine.run()
        assert again.state == FINISHED


class TestZeroRetrace:
    def test_64_mixed_streams_one_decode_compile(self, model):
        """The acceptance criterion: 64 concurrent mixed-length requests
        churning through 8 slots, ONE decode trace, every stream
        token-identical to generate()."""
        lengths = (3, 5, 8, 11, 16, 21)
        uniques = {n: _prompt(n, seed=7) for n in lengths}
        refs = {n: _ref(model, p, 6) for n, p in uniques.items()}
        prompts = [uniques[lengths[i % len(lengths)]] for i in range(64)]
        engine = LLMEngine(model, max_batch_size=8, block_size=4)
        outs = engine.generate(prompts, max_new_tokens=6)
        st = engine.stats()
        assert st["decode_compiles"] == 1
        assert st["completed"] == 64
        # prefill buckets are the pow-2 cover of the lengths, compiled
        # once each — admission never touches the decode program
        assert st["prefill_compiles"] <= 5
        for i, out in enumerate(outs):
            assert out == refs[lengths[i % len(lengths)]], f"stream {i}"

    @pytest.mark.perf_smoke
    def test_churn_occupancy_saturated(self, model):
        """perf_smoke guard (mirrors tools/perf_smoke.py leg e): under
        saturation (demand >= slots) continuous batching must keep the
        slots >= 75% full, and the decode program must not retrace."""
        prompts = [_prompt(3 + (i % 9), seed=8) for i in range(24)]
        engine = LLMEngine(model, max_batch_size=4, block_size=4)
        engine.generate(prompts, max_new_tokens=5)
        st = engine.stats()
        assert st["decode_compiles"] == 1
        assert st["occupancy_saturated"] >= 0.75

    def test_reset_stats_opens_a_clean_window(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        engine.generate([_prompt(5)], max_new_tokens=3)   # warmup
        engine.reset_stats()
        engine.generate([_prompt(5)], max_new_tokens=3)
        st = engine.stats()
        assert st["decode_compiles"] == 0       # no retrace in the window
        assert st["completed"] == 1


# ---------------------------------------------------------------------------
# telemetry: serve.* events through the flight recorder + doctor
# ---------------------------------------------------------------------------

class TestServeTelemetry:
    def test_lifecycle_events_and_doctor_verdict(self, model):
        clear_fusion_events()
        set_flags({"FLAGS_profiler_events": True})
        try:
            prompts = [_prompt(n, seed=9) for n in (11, 12, 10, 5, 7)]
            engine = LLMEngine(model, max_batch_size=3, block_size=4,
                               num_blocks=10, watermark_blocks=1)
            engine.generate(prompts, max_new_tokens=6)
            ev = fusion_events()
        finally:
            set_flags({"FLAGS_profiler_events": False})
            clear_fusion_events()
        cats = {e["cat"] for e in ev}
        assert {"serve.enqueue", "serve.admit", "serve.step",
                "serve.complete"} <= cats
        evicts = [e for e in ev if e["cat"] == "serve.evict"]
        assert evicts and all(e["reason"] == "kv_exhausted" for e in evicts)
        resumed = [e for e in ev if e["cat"] == "serve.admit"
                   and (e.get("detail") or {}).get("resumed")]
        assert resumed                       # the evicted stream came back
        rep = explain(ev)
        assert rep["verdict"] == "serving"
        sv = rep["serving"]
        assert sv["completed"] == len(prompts)
        assert sv["evictions"] == len(evicts)
        assert sv["occupancy_mean"] is not None
        assert "kv_exhausted" in sv["reasons"]
        assert any("kv_exhausted" in f for f in rep["findings"])

    def test_refusal_attributed_kv_exhausted(self, model):
        clear_fusion_events()
        set_flags({"FLAGS_profiler_events": True})
        try:
            engine = LLMEngine(model, max_batch_size=2, block_size=4,
                               num_blocks=6, watermark_blocks=1)
            with pytest.raises(ValueError):
                engine.add_request(_prompt(20), max_new_tokens=20)
            ev = fusion_events()
        finally:
            set_flags({"FLAGS_profiler_events": False})
            clear_fusion_events()
        # refusals emit serve.refuse (PR 7): one category for every
        # admission bounce, whatever the reason code
        refusals = [e for e in ev if e["cat"] == "serve.refuse"
                    and e["reason"] == "kv_exhausted"]
        assert len(refusals) == 1
        d = refusals[0]["detail"]
        assert d["blocks_needed"] > d["blocks_budget"]


# ---------------------------------------------------------------------------
# resilience: deadlines, cancellation, backpressure, watchdog, fallback,
# crash-resume (PR 7, serving/resilience.py)
# ---------------------------------------------------------------------------

@pytest.fixture(autouse=True)
def _no_stale_resilience_state():
    guardian.clear_faults()
    set_flags({"FLAGS_serve_step_timeout_ms": 0})
    yield
    guardian.clear_faults()
    set_flags({"FLAGS_serve_step_timeout_ms": 0})


class TestBackpressure:
    def test_queue_full_refusal_is_structured_and_ordered(self, model):
        """The bounded queue refuses the overflow request with a
        structured ServeRefusal (a ValueError, reason `queue_full`),
        WITHOUT perturbing the queued work — the survivors are then
        served strictly FCFS."""
        engine = LLMEngine(model, max_batch_size=1, block_size=4,
                           max_queue_depth=2)
        first = engine.add_request(_prompt(5, seed=11), max_new_tokens=3,
                                   request_id="a")
        engine.step()                                 # "a" is running
        engine.add_request(_prompt(6, seed=12), max_new_tokens=3,
                           request_id="b")
        engine.add_request(_prompt(7, seed=13), max_new_tokens=3,
                           request_id="c")
        with pytest.raises(ServeRefusal) as ei:
            engine.add_request(_prompt(8, seed=14), max_new_tokens=3,
                               request_id="d")
        assert ei.value.reason == "queue_full"
        assert isinstance(ei.value, ValueError)       # PR 6 compat
        assert ei.value.detail["max_queue_depth"] == 2
        assert engine.stats()["refused_queue_full"] == 1
        # queue untouched by the refusal, still FCFS behind the head
        assert [r.rid for r in engine.scheduler.waiting] == ["b", "c"]
        engine.run()
        done = [engine.requests[rid] for rid in ("a", "b", "c")]
        assert all(r.state == FINISHED for r in done)
        assert (done[0].finish_ns < done[1].finish_ns
                < done[2].finish_ns)                  # strict FCFS
        assert first.state == FINISHED

    def test_deadline_infeasible_refused_at_enqueue(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        # TTL already spent at enqueue
        with pytest.raises(ServeRefusal) as ei:
            engine.add_request(_prompt(5, seed=15), max_new_tokens=4,
                               ttl_s=0.0)
        assert ei.value.reason == "deadline_infeasible"
        # with latency samples, an impossible wait+service estimate is
        # refused even though the TTL has not yet expired
        engine.generate([_prompt(5, seed=16)], max_new_tokens=4)
        assert engine._stats.step_times_s
        with pytest.raises(ServeRefusal) as ei:
            engine.add_request(_prompt(4, seed=17), max_new_tokens=40,
                               ttl_s=1e-5)
        assert ei.value.reason == "deadline_infeasible"
        assert engine.stats()["refused_deadline"] == 2


class TestDeadlines:
    def test_expiry_while_queued_does_not_block_admission(self, model):
        """An expired QUEUED request is cleared at the boundary before
        FCFS admission looks at the head — it must never shadow live
        work behind it, and the running stream never notices."""
        ref = _ref(model, _prompt(10, seed=18), 10)
        engine = LLMEngine(model, max_batch_size=1, block_size=4)
        live = engine.add_request(_prompt(10, seed=18), max_new_tokens=10)
        engine.step()                                   # live is running
        # a generous TTL passes admission; the deterministic seam pulls
        # the deadline into the past once it is safely queued (wall-clock
        # racing against CPU step times would flake)
        doomed = engine.add_request(_prompt(5, seed=19),
                                    max_new_tokens=4, ttl_s=60.0)
        behind = engine.add_request(_prompt(6, seed=20), max_new_tokens=2)
        doomed.deadline_ns = time.perf_counter_ns() - 1
        clear_fusion_events()
        set_flags({"FLAGS_profiler_events": True})
        try:
            engine.run()
            ev = fusion_events()
        finally:
            set_flags({"FLAGS_profiler_events": False})
        assert doomed.state == EXPIRED
        assert doomed.error == "deadline_expired"
        assert behind.state == FINISHED                 # not shadowed
        assert live.generated == ref                    # undisturbed
        exp = [e for e in ev if e["cat"] == "serve.expire"]
        assert len(exp) == 1
        assert exp[0]["reason"] == "deadline_expired"
        assert exp[0]["detail"]["where"] == "queued"
        assert engine.stats()["decode_compiles"] == 1

    def test_expiry_while_running_frees_the_slot(self, model):
        """A RUNNING stream whose deadline passes is cleared at the next
        iteration boundary (a value-only slot edit): the slot is reused,
        the survivor stream is bitwise-unaffected, and the decode
        program never retraces."""
        ref_b = _ref(model, _prompt(7, seed=21), 12)
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        doomed = engine.add_request(_prompt(9, seed=22),
                                    max_new_tokens=12, ttl_s=60.0)
        keeper = engine.add_request(_prompt(7, seed=21), max_new_tokens=12)
        for _ in range(4):
            engine.step()
        assert doomed.state == RUNNING
        # deterministic expiry: pull the deadline into the past instead
        # of racing wall-clock against CPU step times
        doomed.deadline_ns = time.perf_counter_ns() - 1
        clear_fusion_events()
        set_flags({"FLAGS_profiler_events": True})
        try:
            engine.step()
            ev = fusion_events()
        finally:
            set_flags({"FLAGS_profiler_events": False})
        assert doomed.state == EXPIRED
        exp = [e for e in ev if e["cat"] == "serve.expire"]
        assert exp and exp[0]["detail"]["where"] == "running"
        waiter = engine.add_request(_prompt(5, seed=23), max_new_tokens=3)
        engine.run()
        assert keeper.generated == ref_b                # bitwise
        assert waiter.state == FINISHED                 # slot was reused
        assert engine.stats()["decode_compiles"] == 1


class TestCancellation:
    def test_cancel_queued_and_running(self, model):
        ref = _ref(model, _prompt(8, seed=24), 10)
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        keeper = engine.add_request(_prompt(8, seed=24), max_new_tokens=10)
        victim = engine.add_request(_prompt(6, seed=25), max_new_tokens=10)
        queued = engine.add_request(_prompt(5, seed=26), max_new_tokens=4)
        for _ in range(3):
            engine.step()
        assert victim.state == RUNNING and queued.state == QUEUED
        clear_fusion_events()
        set_flags({"FLAGS_profiler_events": True})
        try:
            assert engine.cancel(victim.rid) is True
            assert engine.cancel(queued.rid) is True
            ev = fusion_events()
        finally:
            set_flags({"FLAGS_profiler_events": False})
        assert victim.state == CANCELLED
        assert queued.state == CANCELLED
        cancels = [e for e in ev if e["cat"] == "serve.cancel"]
        assert {e["reason"] for e in cancels} == {"client_cancel"}
        assert {e["detail"]["was_running"] for e in cancels} == \
            {True, False}
        engine.run()
        assert keeper.generated == ref                  # bitwise
        assert engine.stats()["decode_compiles"] == 1
        assert engine.stats()["cancelled"] == 2

    def test_cancel_from_streaming_callback_defers_to_boundary(
            self, model):
        """A cancel issued from inside an on_token callback — the
        natural place to notice a client disconnect — must not edit the
        slot arrays under step()'s feet: it defers to the next boundary
        sweep, the neighbor stream stays bitwise, and the decode program
        never retraces."""
        ref = _ref(model, _prompt(8, seed=42), 10)
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        victim = engine.add_request(_prompt(6, seed=43), max_new_tokens=10)

        def on_tok(req, tok, text):
            if len(req.generated) == 3:
                # cross-request cancel from a live stream's callback
                assert engine.cancel(victim.rid) is True
        keeper = engine.add_request(_prompt(8, seed=42), max_new_tokens=10,
                                    on_token=on_tok)
        engine.run()
        assert victim.state == CANCELLED
        assert len(victim.generated) <= 4      # stopped at the boundary
        assert keeper.generated == ref         # bitwise undisturbed
        assert engine.stats()["decode_compiles"] == 1
        # self-cancel from the victim's own callback is equally safe
        engine2 = LLMEngine(model, max_batch_size=2, block_size=4)
        selfc = engine2.add_request(
            _prompt(7, seed=44), max_new_tokens=10,
            on_token=lambda r, t, txt: (len(r.generated) == 2
                                        and engine2.cancel(r.rid)))
        other = engine2.add_request(_prompt(8, seed=42), max_new_tokens=10)
        engine2.run()
        assert selfc.state == CANCELLED
        assert other.generated == ref

    def test_pop_finished_drains_terminal_handles(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        done = engine.add_request(_prompt(5, seed=45), max_new_tokens=3,
                                  request_id="d")
        live = engine.add_request(_prompt(6, seed=46), max_new_tokens=40,
                                  request_id="l")
        while done.state != FINISHED:
            engine.step()
        drained = engine.pop_finished()
        assert set(drained) == {"d"} and drained["d"] is done
        assert set(engine.requests) == {"l"}   # live handles stay
        engine.cancel(live.rid)

    def test_cancel_racing_completion_is_noop(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        req = engine.add_request(_prompt(5, seed=27), max_new_tokens=3)
        engine.run()
        assert req.state == FINISHED
        assert engine.cancel(req.rid) is False          # too late: no-op
        assert req.state == FINISHED
        assert engine.cancel("never-existed") is False
        assert engine.stats()["cancelled"] == 0


class TestAgingGuard:
    def _sched(self, **kw):
        alloc = BlockAllocator(20)
        return Scheduler(3, alloc, 4, watermark_blocks=0, **kw)

    def test_protected_request_never_chosen_as_victim(self):
        sched = self._sched(aging_max_preemptions=2)
        reqs = [Request(f"r{i}", [1, 2], 4) for i in range(3)]
        for r in reqs:
            sched.enqueue(r)
            sched.try_admit()
        reqs[2].preemptions = 2                    # paid its dues
        assert sched.protected(reqs[2])
        # LIFO would pick r2 (newest); the guard redirects to r1
        assert sched.preempt_victim() is reqs[1]
        reqs[1].preemptions = 2
        reqs[0].preemptions = 2
        assert sched.preempt_victim() is None      # everyone protected

    def test_sustained_preemption_cannot_starve(self, model):
        """A request bounced by LIFO preemption becomes protected after
        aging_max_preemptions evictions: under a sustained stream of
        competing work over a deliberately tight pool, every stream
        still completes, nobody's preemption count passes the cap + 1,
        and the outputs stay token-identical."""
        prompts = [_prompt(n, seed=28) for n in (11, 12, 10, 5, 9, 7)]
        refs = [_ref(model, p, 10) for p in prompts]
        engine = LLMEngine(model, max_batch_size=3, block_size=4,
                           num_blocks=10, watermark_blocks=1,
                           aging_max_preemptions=2)
        outs = engine.generate(prompts, max_new_tokens=10)
        assert outs == refs
        assert engine.stats()["evictions"] >= 1    # churn actually bit
        cap = engine.scheduler.aging_max_preemptions
        assert all(r.preemptions <= cap + 1
                   for r in engine.requests.values())

    def test_grower_steps_aside_when_victims_protected(self, model):
        """When every other tenant is protected, the grower self-preempts
        (requeued at its arrival slot) instead of being terminally
        failed — bounded fairness, not collateral damage."""
        engine = LLMEngine(model, max_batch_size=2, block_size=4,
                           num_blocks=8, watermark_blocks=1,
                           aging_max_preemptions=3)
        a = engine.add_request(_prompt(10, seed=29), max_new_tokens=10)
        b = engine.add_request(_prompt(9, seed=30), max_new_tokens=10)
        for _ in range(2):
            engine.step()
        assert a.state == RUNNING and b.state == RUNNING
        a.preemptions = 3                          # a is protected
        engine.run()
        assert a.state == FINISHED and b.state == FINISHED
        assert b.preemptions >= 1                  # b stepped aside
        assert a.generated == _ref(model, _prompt(10, seed=29), 10)
        assert b.generated == _ref(model, _prompt(9, seed=30), 10)


class TestWatchdog:
    def test_injected_hang_recovers_within_budget(self, model):
        """Rung 1: one hung decode step is detected by the watchdog and
        retried — every stream finishes token-identically, the decode
        program does NOT retrace, and the hang is attributed."""
        prompts = [_prompt(n, seed=31) for n in (9, 6)]
        refs = [_ref(model, p, 8) for p in prompts]
        set_flags({"FLAGS_serve_step_timeout_ms": 2000})
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        reqs = [engine.add_request(p, max_new_tokens=8) for p in prompts]
        for _ in range(3):
            engine.step()
        clear_fusion_events()
        set_flags({"FLAGS_profiler_events": True})
        guardian.inject_fault("hang", op="serve.decode", times=1)
        try:
            engine.run()
            ev = fusion_events()
        finally:
            guardian.clear_faults()
            set_flags({"FLAGS_profiler_events": False})
        st = engine.stats()
        assert st["hangs"] == 1
        assert st["decode_compiles"] == 1
        assert not engine.degraded                  # recovered
        for r, ref in zip(reqs, refs):
            assert r.state == FINISHED and r.generated == ref
        hangs = [e for e in ev if e["cat"] == "serve.hang"]
        assert hangs and hangs[0]["reason"] == "step_hang"
        # the degraded window is visible: entry + recovery transitions
        degr = [e for e in ev if e["cat"] == "serve.degrade"]
        assert any((e.get("detail") or {}).get("rung") == "retry"
                   for e in degr)
        assert any((e.get("detail") or {}).get("recovered")
                   for e in degr)
        rep = explain(ev)
        assert rep["verdict"] == "serving_degraded"

    def test_three_hangs_fail_active_without_wedging(self, model):
        """Rung 3: a step that will not come back fails the ACTIVE
        requests with an attributed reason; queued and new requests are
        then served normally — the process never wedges."""
        set_flags({"FLAGS_serve_step_timeout_ms": 2000})
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        doomed = engine.add_request(_prompt(6, seed=32), max_new_tokens=8)
        engine.step()
        guardian.inject_fault("hang", op="serve.decode", times=3)
        try:
            engine.run()
        finally:
            guardian.clear_faults()
        assert doomed.state == FAILED
        assert doomed.error == "step_hang"
        assert engine.stats()["hangs"] == 3
        fresh = engine.add_request(_prompt(5, seed=33), max_new_tokens=4)
        engine.run()
        assert fresh.state == FINISHED


class TestDegradedFallback:
    def test_poisoned_decode_falls_back_eager_token_identically(
            self, model):
        """A poisoned compiled-decode launch is discarded; every
        in-flight stream finishes through the model's own generate()
        path with IDENTICAL tokens, streaming callbacks included, and
        the engine keeps serving new work on the (unrebuilt) compiled
        program."""
        prompts = [_prompt(n, seed=34) for n in (10, 7)]
        refs = [_ref(model, p, 9) for p in prompts]
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        streamed = {p[0]: [] for p in ("a", "b")}
        reqs = [engine.add_request(
                    p, max_new_tokens=9, request_id=rid,
                    on_token=lambda r, tok, text: streamed[r.rid]
                    .append(tok))
                for rid, p in zip(("a", "b"), prompts)]
        for _ in range(4):
            engine.step()
        clear_fusion_events()
        set_flags({"FLAGS_profiler_events": True})
        guardian.inject_fault("nan_output", op="serve.decode", times=1)
        try:
            engine.run()
            ev = fusion_events()
        finally:
            guardian.clear_faults()
            set_flags({"FLAGS_profiler_events": False})
        st = engine.stats()
        assert st["eager_fallbacks"] == 2
        assert st["decode_compiles"] == 1           # no rebuild
        for r, ref in zip(reqs, refs):
            assert r.state == FINISHED and r.generated == ref
            assert streamed[r.rid] == ref           # stream continuity
        degr = [e for e in ev if e["cat"] == "serve.degrade"
                and e["reason"] == "decode_fault"]
        assert degr
        # and the compiled path still serves new requests, zero retrace
        again = engine.add_request(prompts[0], max_new_tokens=9)
        engine.run()
        assert again.generated == refs[0]
        assert engine.stats()["decode_compiles"] == 1


class TestCrashResume:
    def test_state_payload_restores_byte_identically(self, model):
        """A mid-flight snapshot restored into a FRESH engine finishes
        every stream with the same final tokens as the uninterrupted
        run (re-prefill of prompt + emitted tokens is the PR 6
        token-identical resume path)."""
        prompts = [_prompt(n, seed=35) for n in (11, 6, 9)]
        refs = [_ref(model, p, 10) for p in prompts]
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        for i, p in enumerate(prompts):
            engine.add_request(p, max_new_tokens=10, request_id=f"s{i}")
        for _ in range(5):
            engine.step()                           # mid-flight
        payload = engine.state_payload()
        assert payload["requests"]                  # live streams inside
        engine2 = LLMEngine(model, max_batch_size=2, block_size=4)
        clear_fusion_events()
        set_flags({"FLAGS_profiler_events": True})
        try:
            restored = engine2.restore_state(payload)
            ev = fusion_events()
        finally:
            set_flags({"FLAGS_profiler_events": False})
        assert [e["reason"] for e in ev
                if e["cat"] == "serve.resume"] \
            == ["crash_resume"] * len(restored)
        engine2.run()
        by_rid = {r.rid: r for r in restored}
        for i, ref in enumerate(refs):
            rid = f"s{i}"
            if rid in by_rid:                       # was still in flight
                assert by_rid[rid].generated == ref
                assert by_rid[rid].state == FINISHED
        assert engine2.stats()["resumed"] == len(restored)

    def test_restore_rejects_live_duplicate(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        engine.add_request(_prompt(5, seed=36), max_new_tokens=6,
                           request_id="dup")
        payload = engine.state_payload()
        with pytest.raises(ValueError, match="already live"):
            engine.restore_state(payload)

    def test_serve_checkpointer_roundtrip_and_corruption_refusal(
            self, model, tmp_path):
        from paddle_tpu.framework.io import CheckpointCorruptError
        from paddle_tpu.incubate.checkpoint import ServeCheckpointer
        ref = _ref(model, _prompt(8, seed=37), 8)
        ck = ServeCheckpointer(str(tmp_path), save_every_n_steps=1,
                               max_checkpoints=2)
        engine = LLMEngine(model, max_batch_size=2, block_size=4)
        engine.add_request(_prompt(8, seed=37), max_new_tokens=8,
                           request_id="k")
        for n in range(1, 4):
            engine.step()
            ck.tick(n, engine.state_payload())
        assert len(ck._retained()) == 2             # rolling retention
        engine2 = LLMEngine(model, max_batch_size=2, block_size=4)
        [req] = engine2.restore_state(ck.restore())
        engine2.run()
        assert req.generated == ref                 # byte-identical
        # torn writes on every retained snapshot -> REFUSE, never start
        # empty while silently dropping in-flight user streams
        for s in ck._retained():
            p = os.path.join(ck.checkpoint_path(s), ck.CKPT_FILE)
            with open(p, "r+b") as fh:
                fh.seek(8)
                fh.write(b"XXXX")
        with pytest.raises(CheckpointCorruptError, match="refusing"):
            ck.restore()

    @pytest.mark.perf_smoke
    def test_decode_compiles_once_under_lifecycle_churn(self, model):
        """The acceptance criterion: cancel/expire/refuse/resume are
        VALUE edits to the fixed slot layout — the decode executable
        compiles exactly once through all of it (mirrors
        tools/perf_smoke.py leg g)."""
        set_flags({"FLAGS_serve_step_timeout_ms": 2000})
        engine = LLMEngine(model, max_batch_size=4, block_size=4,
                           max_queue_depth=6)
        engine.generate([_prompt(5, seed=38)], max_new_tokens=3)  # warm
        engine.reset_stats()
        live = [engine.add_request(_prompt(4 + i, seed=39),
                                   max_new_tokens=6) for i in range(4)]
        doomed = engine.add_request(_prompt(5, seed=40), max_new_tokens=6,
                                    ttl_s=60.0)
        doomed.deadline_ns = 0        # deterministic queued expiry
        with pytest.raises(ServeRefusal):
            for _ in range(16):
                engine.add_request(_prompt(6, seed=41), max_new_tokens=6)
        for _ in range(2):
            engine.step()
        engine.cancel(live[0].rid)
        mid = engine.state_payload()
        engine.run()
        resumed = engine.restore_state(mid)
        engine.run()
        st = engine.stats()
        assert st["decode_compiles"] == 0           # post-warmup window
        assert st["cancelled"] >= 1 and st["expired"] >= 1
        assert st["refused_queue_full"] >= 1 and len(resumed) >= 1
