"""Kernel tier suite (ISSUE 11): blockwise/Pallas paged decode attention
+ the int8 KV cache.

Contracts pinned here:

  * fused-vs-reference parity — the blockwise (lax.scan online-softmax)
    and Pallas (interpret=True on CPU) variants match the dense
    gather-by-block-table oracle to fp32 tolerance, share its exact
    write path bitwise, and agree on every edge shape: seq_len at an
    exact block boundary, a slot right after prefill (zero generated
    tokens), and an inactive slot whose table still points at null
    block 0;
  * fp32 softmax numerics — bf16 serving computes scores/softmax/PV in
    fp32 (the satellite fix), so the bf16 paged path tracks an all-fp32
    computation to input-rounding error, not accumulation error;
  * int8 KV — quantize->dequantize error is bounded by half a quant step
    per element (per-block-per-head scales), greedy decode through the
    int8 pool is token-identical to fp32 KV on the tiny-GPT fixture
    (incl. under preemption churn), and the same byte budget admits
    >= 1.8x the concurrent streams before the pool runs dry;
  * keying — FLAGS_serve_attention_kernel is keyed into the per-op
    dispatch cache (each variant is a distinct executable) and the AOT
    env fingerprint / decode digest (kernel flips never deserialize a
    stale artifact); kernel fallbacks are attributed `kernel.fallback`
    events, never silent;
  * perf floors (perf_smoke) — blockwise beats the dense gather at
    seq >= 1k on CPU, and an int8 engine compiles decode exactly once
    under churn.
"""
from __future__ import annotations

import os

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.framework.flags import get_flags, set_flags
from paddle_tpu.incubate.models import GPTConfig, GPTForCausalLM
from paddle_tpu.nn.functional.attention import (paged_decode_attention,
                                                resolve_paged_kernel,
                                                PAGED_KERNELS)
from paddle_tpu.quantization.kv_cache import (QMAX, quantize_scatter,
                                              quantize_block_write,
                                              dequantize)
from paddle_tpu.serving import LLMEngine, num_blocks_for_bytes
from paddle_tpu.profiler.events import (clear_fusion_events, fusion_events,
                                        EVENTS)

VOCAB = 128

VARIANTS = ("reference", "blockwise", "pallas")


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _prompt(length, seed=0):
    rng = np.random.default_rng(seed * 1000 + length)
    return rng.integers(0, VOCAB, length).tolist()


_REF_CACHE = {}


def _ref(model, prompt, n):
    key = (tuple(prompt), n)
    if key not in _REF_CACHE:
        out = model.generate(paddle.Tensor(np.asarray([prompt], np.int64)),
                             max_new_tokens=n, do_sample=False)
        _REF_CACHE[key] = np.asarray(out._value)[0].tolist()
    return _REF_CACHE[key]


def _paged_state(S=4, H=3, D=16, bs=4, M=6, lens=(0, 4, 8, 23),
                 active=(True, True, True, True), seed=0,
                 dtype=jnp.float32):
    """A filled paged-cache state: per-slot dense-prefix block tables over
    disjoint pool blocks, pools populated with random history."""
    rng = np.random.default_rng(seed)
    nb = S * M + 1
    mk = lambda sh: jnp.asarray(
        rng.standard_normal(sh).astype(np.float32)).astype(dtype)
    q, kn, vn = mk((S, 1, H, D)), mk((S, 1, H, D)), mk((S, 1, H, D))
    kp, vp = mk((nb, bs, H, D)), mk((nb, bs, H, D))
    tables = jnp.asarray(np.stack(
        [1 + s * M + np.arange(M) for s in range(S)]).astype(np.int32))
    return (q, kn, vn, kp, vp, tables,
            jnp.asarray(np.asarray(lens, np.int32)),
            jnp.asarray(np.asarray(active, bool)))


def _run(variant, state, bs, **kw):
    q, kn, vn, kp, vp, tables, lens, active = state
    interpret = variant == "pallas"
    return paged_decode_attention(q, kn, vn, kp, vp, tables, lens, active,
                                  bs, kernel=variant, interpret=interpret,
                                  **kw)


# ---------------------------------------------------------------------------
# fused-vs-reference parity + edge cases
# ---------------------------------------------------------------------------

class TestVariantParity:
    def test_blockwise_and_pallas_match_dense_oracle(self):
        """Core parity: identical semantics across the three variants to
        fp32 tolerance (the Pallas kernel runs interpret=True on CPU),
        and a BITWISE-identical pool write path."""
        bs = 4
        state = _paged_state(bs=bs)
        o_ref, k_ref, v_ref = _run("reference", state, bs)
        o_bw, k_bw, v_bw = _run("blockwise", state, bs)
        o_pl, k_pl, v_pl = _run("pallas", state, bs)
        act = np.asarray(state[-1])
        for name, o in (("blockwise", o_bw), ("pallas", o_pl)):
            np.testing.assert_allclose(
                np.asarray(o)[act], np.asarray(o_ref)[act],
                rtol=1e-5, atol=1e-5, err_msg=name)
        for k in (k_bw, k_pl):
            assert np.array_equal(np.asarray(k), np.asarray(k_ref))
        for v in (v_bw, v_pl):
            assert np.array_equal(np.asarray(v), np.asarray(v_ref))

    @pytest.mark.parametrize("variant", VARIANTS)
    def test_seq_len_at_exact_block_boundary(self, variant):
        """len == k*block_size: the new token opens a FRESH block (write
        at offset 0 of table entry k) and attention spans the boundary."""
        bs = 4
        for length in (bs, 2 * bs, 5 * bs):
            state = _paged_state(S=2, M=6, bs=bs,
                                 lens=(length, length - 1),
                                 active=(True, True), seed=length)
            o_ref, k_ref, _ = _run("reference", state, bs)
            if variant == "reference":
                # the boundary write must land at (table[len//bs], 0)
                tables = np.asarray(state[5])
                blk = tables[0, length // bs]
                written = np.asarray(k_ref)[blk, 0]
                expect = np.asarray(state[1])[0, 0]
                np.testing.assert_allclose(written, expect, rtol=1e-6)
                continue
            out, k_pool, _ = _run(variant, state, bs)
            np.testing.assert_allclose(np.asarray(out), np.asarray(o_ref),
                                       rtol=1e-5, atol=1e-5)
            assert np.array_equal(np.asarray(k_pool), np.asarray(k_ref))

    @pytest.mark.parametrize("variant", VARIANTS)
    def test_inactive_slot_null_table_does_not_perturb_neighbors(
            self, variant):
        """An inactive slot whose table still points at null block 0:
        its garbage stays in block 0, active slots' outputs equal the
        solo computation, and no NaN leaks anywhere."""
        bs = 4
        state = _paged_state(S=3, M=4, bs=bs, lens=(7, 0, 5),
                             active=(True, False, True))
        q, kn, vn, kp, vp, tables, lens, active = state
        # zero the inactive slot's table entirely (the engine's cleared
        # slot shape)
        tables = tables.at[1].set(0)
        out, new_k, new_v = paged_decode_attention(
            q, kn, vn, kp, vp, tables, lens, active, bs, kernel=variant,
            interpret=(variant == "pallas"))
        solo = paged_decode_attention(
            q, kn, vn, kp, vp, tables,
            lens, jnp.asarray([True, True, True]), bs, kernel="reference")
        # active rows agree with a run where slot 1's table is unchanged
        np.testing.assert_allclose(np.asarray(out)[[0, 2]],
                                   np.asarray(solo[0])[[0, 2]],
                                   rtol=1e-5, atol=1e-5)
        assert np.isfinite(np.asarray(out)[[0, 2]]).all()
        # only the null block and the two active write targets changed
        diff = np.where(np.any(np.asarray(new_k) != np.asarray(kp),
                               axis=(1, 2, 3)))[0]
        tables_np = np.asarray(tables)
        allowed = {0, int(tables_np[0, 7 // bs]), int(tables_np[2, 5 // bs])}
        assert set(diff.tolist()) <= allowed

    def test_zero_generated_tokens_right_after_prefill(self, model):
        """The first decode step after admission (cached_len == prompt
        len, nothing generated yet) produces exactly the reference's
        first token — for every kernel variant and the int8 pool."""
        p = _prompt(9, seed=11)
        first = _ref(model, p, 1)[0]
        for kw in ({"attention_kernel": "reference"},
                   {"attention_kernel": "blockwise"},
                   {"kv_dtype": "int8"}):
            engine = LLMEngine(model, max_batch_size=2, block_size=4, **kw)
            req = engine.add_request(p, max_new_tokens=3)
            engine.step()
            assert req.generated[:1] == [first], kw


# ---------------------------------------------------------------------------
# fp32 softmax numerics (bf16 serving keeps its tail tokens)
# ---------------------------------------------------------------------------

class TestBf16Numerics:
    @pytest.mark.parametrize("variant", ("reference", "blockwise"))
    def test_bf16_paged_attention_tracks_fp32(self, variant):
        """Scores + softmax + PV accumulate in fp32 even for bf16
        inputs: the bf16 path must track the all-fp32 computation to
        INPUT-rounding error (~1e-2 for bf16), with a long history whose
        tail would vanish under bf16 accumulation."""
        bs = 4
        st16 = _paged_state(S=2, H=2, D=8, M=16, bs=bs, lens=(60, 31),
                            active=(True, True), dtype=jnp.bfloat16)
        st32 = tuple(x.astype(jnp.float32)
                     if x.dtype == jnp.bfloat16 else x for x in st16)
        out16 = _run(variant, st16, bs)[0]
        out32 = _run("reference", st32, bs)[0]
        assert out16.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out16, np.float32), np.asarray(out32),
            rtol=0.0, atol=2e-2)


# ---------------------------------------------------------------------------
# int8 KV cache
# ---------------------------------------------------------------------------

class TestInt8KV:
    def test_quantize_roundtrip_error_bound_per_block(self):
        """quantize->dequantize error <= half a quant step per element,
        where the step is that block's per-head scale / 127."""
        rng = np.random.default_rng(3)
        bs, H, D, nb = 4, 3, 8, 9
        T = 24
        vals = jnp.asarray(rng.standard_normal((T, H, D)).astype(np.float32)
                           * rng.uniform(0.1, 10.0, (T, 1, 1)))
        pool = jnp.zeros((nb, bs, H, D), jnp.int8)
        scales = jnp.full((nb, H), 7.7, jnp.float32)  # stale tenant scale
        block_row = jnp.asarray([1, 2, 3, 4, 5, 6, 0, 0], jnp.int32)
        pidx = np.arange(T)
        blocks = jnp.asarray(np.where(pidx < 22, block_row[pidx // bs], 0)
                             .astype(np.int32))
        offs = jnp.asarray((pidx % bs).astype(np.int32))
        pool, scales = quantize_scatter(pool, scales, vals, blocks, offs,
                                        block_row, jnp.int32(22))
        deq = np.asarray(dequantize(pool, scales))
        sc = np.asarray(scales)
        for t in range(22):
            b, o = int(blocks[t]), int(offs[t])
            err = np.abs(deq[b, o] - np.asarray(vals)[t])
            bound = sc[b][:, None] / QMAX * 0.5 + 1e-6
            assert (err <= bound).all(), f"token {t}"

    def test_block_write_requant_is_stable_and_bounded(self):
        """Appending tokens one by one into a block: stored values stay
        within half a quant step of the LAST-written fp values (requant
        is exact while the scale does not grow), and the scale is the
        running per-head amax."""
        rng = np.random.default_rng(4)
        bs, H, D = 8, 2, 4
        pool = jnp.zeros((3, bs, H, D), jnp.int8)
        scales = jnp.zeros((3, H), jnp.float32)
        written = []
        for i in range(bs):
            vec = jnp.asarray(
                rng.standard_normal((1, H, D)).astype(np.float32) * (i + 1))
            written.append(np.asarray(vec)[0])
            pool, scales = quantize_block_write(
                pool, scales, vec, jnp.asarray([1], jnp.int32),
                jnp.asarray([i], jnp.int32))
        deq = np.asarray(dequantize(pool, scales))[1]       # [bs, H, D]
        sc = np.asarray(scales)[1]                          # [H]
        amax = np.abs(np.stack(written)).max(axis=(0, 2))
        np.testing.assert_allclose(sc, amax, rtol=1e-5)
        for i, vec in enumerate(written):
            # requant error accrues only on scale-raising writes: each of
            # the <= bs regrids adds at most half a (then-current <=
            # final) quant step — this schedule raises the scale on EVERY
            # write, the worst case
            bound = sc[:, None] / QMAX * (0.5 * bs)
            assert (np.abs(deq[i] - vec) <= bound + 1e-6).all(), i

    def test_int8_greedy_decode_token_identical_to_fp32(self, model):
        """End-to-end: the int8-KV engine reproduces the fp32 reference
        stream token for token on the tiny-GPT fixture — including under
        preemption churn (evict -> requeue -> re-prefill requantizes)."""
        prompts = [_prompt(n, seed=21) for n in (11, 5, 17, 3)]
        refs = [_ref(model, p, 10) for p in prompts]
        engine = LLMEngine(model, max_batch_size=4, block_size=4,
                           kv_dtype="int8")
        outs = engine.generate(prompts, max_new_tokens=10)
        assert outs == refs
        st = engine.stats()
        assert st["kv_dtype"] == "int8"
        assert st["decode_compiles"] == 1
        # tight pool: eviction + resume stays token-identical on int8
        prompts2 = [_prompt(n, seed=22) for n in (11, 12, 10, 5)]
        refs2 = [_ref(model, p, 10) for p in prompts2]
        churn = LLMEngine(model, max_batch_size=3, block_size=4,
                          num_blocks=10, watermark_blocks=1,
                          kv_dtype="int8")
        outs2 = churn.generate(prompts2, max_new_tokens=10)
        st2 = churn.stats()
        assert st2["evictions"] >= 1
        assert outs2 == refs2
        assert st2["decode_compiles"] == 1

    def test_int8_admits_1p8x_streams_at_same_pool_bytes(self, model):
        """The capacity win: with the SAME byte budget, the int8 pool
        admits >= 1.8x the concurrent streams before it runs dry
        (admission here is pure host-side block accounting — no
        compiles)."""
        cfg = model.config
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        budget = 96 * 1024

        def admitted(kv_dtype, dt):
            nb = num_blocks_for_bytes(budget, cfg.num_hidden_layers,
                                      cfg.num_attention_heads, head_dim,
                                      4, dt)
            eng = LLMEngine(model, max_batch_size=96, block_size=4,
                            num_blocks=nb, watermark_blocks=1,
                            kv_dtype=kv_dtype)
            for i in range(96):
                eng.add_request(_prompt(8, seed=30 + i), max_new_tokens=8)
            n = 0
            while eng.scheduler.try_admit() is not None:
                n += 1
            return n

        n_fp32 = admitted(None, jnp.float32)
        n_int8 = admitted("int8", jnp.int8)
        assert n_int8 >= 1.8 * n_fp32, (n_int8, n_fp32)


# ---------------------------------------------------------------------------
# keying: dispatch cache, AOT fingerprint, fallback attribution
# ---------------------------------------------------------------------------

class TestKernelKeying:
    def test_variant_is_keyed_into_dispatch_cache(self, model):
        """Flipping the kernel variant re-keys the paged attention op in
        the per-op executable cache: each variant is a distinct MISS,
        repeats are HITS — never a stale replay of the other variant."""
        from paddle_tpu.framework.core import Tensor
        from paddle_tpu.serving.cache import PagedCacheView

        cfg = model.config
        attn = model.gpt.h[0].attn
        S, bs, M = 2, 4, 4
        nb = S * M + 1
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        rng = np.random.default_rng(5)
        x = Tensor(jnp.asarray(rng.standard_normal(
            (S, 1, cfg.hidden_size)).astype(np.float32)),
            stop_gradient=True)
        pools = jnp.asarray(rng.standard_normal(
            (nb, bs, cfg.num_attention_heads, head_dim)).astype(np.float32))
        tables = jnp.asarray(np.stack(
            [1 + s * M + np.arange(M) for s in range(S)]).astype(np.int32))
        lens = jnp.asarray([3, 5], jnp.int32)
        active = jnp.ones((S,), bool)

        prev = get_flags(["FLAGS_profiler_events"])
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        try:
            for variant in ("reference", "blockwise",
                            "reference", "blockwise"):
                view = PagedCacheView(pools, pools, tables, lens, active,
                                      bs, kernel=variant)
                attn(x, cache=view)
        finally:
            set_flags(prev)
        ev = [e for e in fusion_events("dispatch")
              if e["op"] == "gpt_paged_decode_attention"]
        misses = [e for e in ev if e["cat"] == "dispatch.miss"]
        hits = [e for e in ev if e["cat"] == "dispatch.hit"]
        assert len(misses) == 2, [e["cat"] for e in ev]
        assert len(hits) == 2, [e["cat"] for e in ev]

    def test_flag_keyed_into_aot_env_fingerprint(self):
        """A kernel flip re-fingerprints the AOT store so a stale
        artifact misses by construction."""
        from paddle_tpu.ops import aot_cache
        prev = get_flags(["FLAGS_serve_attention_kernel"])
        try:
            set_flags({"FLAGS_serve_attention_kernel": "blockwise"})
            d_block = aot_cache.fingerprint_digest()
            fp = aot_cache.env_fingerprint()
            assert ("FLAGS_serve_attention_kernel", "blockwise") \
                in fp["flags"]
            set_flags({"FLAGS_serve_attention_kernel": "reference"})
            d_ref = aot_cache.fingerprint_digest()
            assert d_block != d_ref
            set_flags({"FLAGS_serve_attention_kernel": "blockwise"})
            assert aot_cache.fingerprint_digest() == d_block
        finally:
            set_flags(prev)

    def test_decode_digest_rekeys_on_kernel_and_kv_dtype(self, model):
        """The engine's AOT decode digest separates kernel variants and
        KV dtypes — a blockwise/int8 artifact never replays elsewhere."""
        digs = set()
        for kw in ({"attention_kernel": "reference"},
                   {"attention_kernel": "blockwise"},
                   {"kv_dtype": "int8"}):
            eng = LLMEngine(model, max_batch_size=2, block_size=4, **kw)
            d = eng._aot_decode_digest()
            assert d is not None
            digs.add(d)
        assert len(digs) == 3

    def test_pallas_fallback_is_attributed_not_silent(self):
        """Requesting the Pallas kernel off-TPU demotes to blockwise AND
        emits a kernel.fallback event with the why."""
        prev = get_flags(["FLAGS_profiler_events"])
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        try:
            got = resolve_paged_kernel("pallas", head_dim=64, block_size=16)
        finally:
            set_flags(prev)
        assert got == "blockwise"
        ev = [e for e in fusion_events("kernel.fallback")]
        assert len(ev) == 1
        assert ev[0]["reason"] == "kernel_fallback"
        assert ev[0]["detail"]["requested"] == "pallas"
        assert ev[0]["detail"]["actual"] == "blockwise"
        assert ev[0]["detail"]["why"] == "not_on_tpu"

    def test_kv_quantized_engine_is_attributed(self, model):
        """Building an int8-KV engine leaves a kv_quantized marker in
        the flight recorder and the doctor's kernel section/hints."""
        from paddle_tpu.profiler.explain import explain, REASON_HINTS
        prev = get_flags(["FLAGS_profiler_events"])
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        try:
            LLMEngine(model, max_batch_size=2, block_size=4,
                      kv_dtype="int8")
        finally:
            set_flags(prev)
        ev = fusion_events("kernel.quantized")
        assert any(e["reason"] == "kv_quantized" for e in ev)
        # the marker is informational: it must NOT pollute the fallback
        # (demotion) stream
        assert fusion_events("kernel.fallback") == []
        report = explain(fusion_events())
        assert "kernel" in report
        assert "kv_quantized" in report["kernel"]["reasons"]
        assert any("kv_quantized" in f for f in report["findings"])
        assert "kv_quantized" in REASON_HINTS
        assert "kernel_fallback" in REASON_HINTS

    def test_unknown_variant_rejected(self):
        with pytest.raises(ValueError, match="unknown paged attention"):
            resolve_paged_kernel("warp")
        assert set(PAGED_KERNELS) == {"pallas", "blockwise", "reference"}


# ---------------------------------------------------------------------------
# perf floors (mirrored in tools/perf_smoke.py leg j)
# ---------------------------------------------------------------------------

class TestPerfFloors:
    @pytest.mark.perf_smoke
    def test_blockwise_beats_dense_gather_at_seq_1k(self):
        """The kernel tier's reason to exist on CPU: at seq >= 1k the
        streaming path must beat materializing the [S, T, H, D] context
        (best-of-windows against CI noise)."""
        import time
        S, H, D, bs, M = 8, 4, 32, 16, 64          # seq = 1024
        nb = S * M + 1
        state = _paged_state(S=S, H=H, D=D, bs=bs, M=M,
                             lens=(1000,) * S, active=(True,) * S)
        q, kn, vn, kp, vp, tables, lens, active = state
        assert kp.shape[0] == nb

        def jit_of(kernel):
            @jax.jit
            def f(q, kn, vn, kp, vp):
                return paged_decode_attention(
                    q, kn, vn, kp, vp, tables, lens, active, bs,
                    kernel=kernel)[0]
            f(q, kn, vn, kp, vp).block_until_ready()
            return f

        def window(f, iters=10):
            t0 = time.perf_counter()
            for _ in range(iters):
                f(q, kn, vn, kp, vp).block_until_ready()
            return (time.perf_counter() - t0) / iters

        f_dense, f_block = jit_of("reference"), jit_of("blockwise")
        # interleaved paired windows, guard the MAX ratio: a real
        # regression deflates every pair, a load spike only some
        ratios = []
        for _ in range(6):
            ratios.append(window(f_dense) / window(f_block))
        assert max(ratios) > 1.0, (
            f"blockwise never beat the dense gather at seq 1k: "
            f"paired ratios {[round(r, 2) for r in ratios]}")

    @pytest.mark.perf_smoke
    def test_int8_decode_compiles_once_under_churn(self, model):
        """int8 KV is value edits + two extra donated side-tables —
        never a shape change: 24 churning streams, ONE decode trace."""
        prompts = [_prompt(3 + (i % 9), seed=40) for i in range(24)]
        engine = LLMEngine(model, max_batch_size=4, block_size=4,
                           kv_dtype="int8")
        engine.generate(prompts, max_new_tokens=5)
        st = engine.stats()
        assert st["decode_compiles"] == 1
        assert st["completed"] == 24
