"""Per-primitive collective matrix on the 8-device mesh.

Reference analog: the unittests/collective/ per-op scripts
(collective_allreduce_api.py, collective_allgather_api.py,
collective_reduce_scatter_api.py, collective_alltoall_api.py,
collective_sendrecv_api.py ...) — one focused correctness check per
communication primitive, here against the XLA collectives that implement
them on the ICI mesh (SURVEY §2.5 "c_* ops ≙ lax collectives").
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist

N = 8


def _mesh():
    return Mesh(np.array(jax.devices()[:N]), ("g",))


def _vals():
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.normal(size=(N, 4)), jnp.float32)


def _run(body, x, out_specs=P("g")):
    return jax.shard_map(body, mesh=_mesh(), in_specs=P("g"),
                         out_specs=out_specs)(x)


class TestSPMDPrimitives:
    def test_all_reduce_sum(self):
        x = _vals()
        out = _run(lambda v: lax.psum(v, "g"), x)
        np.testing.assert_allclose(np.asarray(out),
                                   np.tile(x.sum(0), (N, 1)), rtol=1e-6)

    def test_all_reduce_max(self):
        x = _vals()
        out = _run(lambda v: lax.pmax(v, "g"), x)
        np.testing.assert_allclose(np.asarray(out),
                                   np.tile(x.max(0), (N, 1)), rtol=1e-6)

    def test_all_reduce_mean(self):
        x = _vals()
        out = _run(lambda v: lax.pmean(v, "g"), x)
        np.testing.assert_allclose(np.asarray(out),
                                   np.tile(x.mean(0), (N, 1)), rtol=1e-6)

    def test_all_gather(self):
        x = _vals()
        out = _run(lambda v: lax.all_gather(v, "g", tiled=True)[None], x,
                   out_specs=P("g"))
        # every rank sees the full concatenation
        for r in range(N):
            np.testing.assert_allclose(np.asarray(out[r]), np.asarray(x),
                                       rtol=1e-6)

    def test_reduce_scatter(self):
        """psum_scatter: rank i owns the i-th chunk of the sum."""
        x = jnp.asarray(np.random.default_rng(1).normal(size=(N, N)),
                        jnp.float32)
        out = _run(lambda v: lax.psum_scatter(v, "g", scatter_dimension=1,
                                              tiled=True), x)
        ref = x.sum(0)  # [N]; rank i gets element i
        np.testing.assert_allclose(np.asarray(out).reshape(-1),
                                   np.asarray(ref), rtol=1e-5)

    def test_alltoall(self):
        """all_to_all transposes the (rank, chunk) layout."""
        x = jnp.arange(N * N, dtype=jnp.float32).reshape(N, N)
        out = _run(lambda v: lax.all_to_all(v, "g", split_axis=1,
                                            concat_axis=1, tiled=True), x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x).T)

    def test_ppermute_ring(self):
        """ppermute one hop around the ring — the pipeline handoff / p2p
        send-recv primitive."""
        x = _vals()
        perm = [(i, (i + 1) % N) for i in range(N)]
        out = _run(lambda v: lax.ppermute(v, "g", perm), x)
        np.testing.assert_allclose(np.asarray(out),
                                   np.roll(np.asarray(x), 1, axis=0),
                                   rtol=1e-6)

    def test_ppermute_send_recv_pair(self):
        """A single (src->dst) edge: dst receives src's value, everyone else
        receives zeros — point-to-point send/recv semantics."""
        x = _vals()
        out = _run(lambda v: lax.ppermute(v, "g", [(2, 5)]), x)
        got = np.asarray(out)
        np.testing.assert_allclose(got[5], np.asarray(x)[2], rtol=1e-6)
        for r in range(N):
            if r != 5:
                np.testing.assert_allclose(got[r], 0.0)

    def test_broadcast_from_src(self):
        x = _vals()
        out = _run(lambda v: lax.all_gather(v, "g")[3], x)
        for r in range(N):
            np.testing.assert_allclose(np.asarray(out[r]),
                                       np.asarray(x)[3], rtol=1e-6)

    def test_axis_index(self):
        out = _run(lambda v: v * 0 + lax.axis_index("g"), _vals())
        for r in range(N):
            assert np.all(np.asarray(out[r]) == r)


class TestEagerCollectiveAPI:
    """paddle.distributed.* eager entry points (single-controller mode)."""

    def test_all_reduce(self):
        t = paddle.Tensor(jnp.ones((4,), jnp.float32))
        dist.all_reduce(t)
        np.testing.assert_allclose(np.asarray(t._value), 1.0)

    def test_all_gather(self):
        out = []
        t = paddle.Tensor(jnp.arange(4, dtype=jnp.float32))
        dist.all_gather(out, t)
        assert len(out) == 1
        np.testing.assert_allclose(np.asarray(out[0]._value),
                                   np.arange(4, dtype=np.float32))

    def test_reduce_scatter(self):
        dst = paddle.Tensor(jnp.zeros((4,), jnp.float32))
        parts = [paddle.Tensor(jnp.full((4,), float(i)))
                 for i in range(2)]
        dist.reduce_scatter(dst, parts)
        np.testing.assert_allclose(np.asarray(dst._value), 1.0)

    def test_broadcast(self):
        t = paddle.Tensor(jnp.full((3,), 7.0))
        dist.broadcast(t, src=0)
        np.testing.assert_allclose(np.asarray(t._value), 7.0)

    def test_send_recv_roundtrip(self):
        src = paddle.Tensor(jnp.asarray([1.0, 2.0, 3.0]))
        dst = paddle.Tensor(jnp.zeros((3,)))
        dist.send(src, dst=0)
        dist.recv(dst, src=0)
        np.testing.assert_allclose(np.asarray(dst._value),
                                   np.asarray(src._value))

    def test_barrier_and_group(self):
        dist.barrier()
        g = dist.get_group(0)
        assert g is not None and g.nranks >= 1
