"""ONNX export, nan/inf sanitizer flags, and static-shell behaviors.

Reference analogs: python/paddle/onnx/export.py + paddle2onnx,
fluid/framework/details/nan_inf_utils.h (FLAGS_check_nan_inf),
fluid/layers/py_func_op (py_func), fluid/executor.py.
"""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit import InputSpec


# ---- minimal protobuf wire-format reader (validation only) -----------------

def _read_varint(buf, i):
    shift, val = 0, 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def _fields(buf):
    """Yield (field_number, wire_type, value) for one message level."""
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, i = _read_varint(buf, i)
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            val = buf[i:i + ln]
            i += ln
        elif wire == 5:
            val = buf[i:i + 4]
            i += 4
        elif wire == 1:
            val = buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"wire type {wire}")
        yield field, wire, val


def _parse_model(path):
    buf = open(path, "rb").read()
    model = {"opset": None, "producer": None, "graph": None}
    for f, w, v in _fields(buf):
        if f == 2:
            model["producer"] = v.decode()
        elif f == 7:
            model["graph"] = v
        elif f == 8:
            for f2, _, v2 in _fields(v):
                if f2 == 2:
                    model["opset"] = v2
    nodes, inits, g_in, g_out = [], [], [], []
    for f, w, v in _fields(model["graph"]):
        if f == 1:
            op_type, ins, outs = None, [], []
            for f2, _, v2 in _fields(v):
                if f2 == 4:
                    op_type = v2.decode()
                elif f2 == 1:
                    ins.append(v2.decode())
                elif f2 == 2:
                    outs.append(v2.decode())
            nodes.append((op_type, ins, outs))
        elif f == 5:
            name, dims, raw, dt = None, [], None, None
            for f2, _, v2 in _fields(v):
                if f2 == 8:
                    name = v2.decode()
                elif f2 == 1:
                    dims.append(v2)
                elif f2 == 9:
                    raw = v2
                elif f2 == 2:
                    dt = v2
            inits.append((name, tuple(dims), raw, dt))
        elif f == 11:
            g_in.append(v)
        elif f == 12:
            g_out.append(v)
    return model, nodes, inits, g_in, g_out


class TestOnnxExport:
    def test_mlp_structure_roundtrip(self, tmp_path):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                              nn.Linear(16, 4), nn.Softmax())
        p = paddle.onnx.export(model, str(tmp_path / "mlp"),
                               input_spec=[InputSpec([2, 8])])
        meta, nodes, inits, g_in, g_out = _parse_model(p)
        assert meta["producer"] == "paddle-tpu"
        assert meta["opset"] == 17
        ops = [op for op, _, _ in nodes]
        assert "MatMul" in ops and "Tanh" in ops
        assert len(g_in) == 1 and len(g_out) == 1
        # the weight initializers carry the exact parameter bytes
        w0 = np.asarray(model[0].weight._value)
        raws = [raw for _, dims, raw, _ in inits
                if dims == (8, 16) and raw is not None]
        assert any(np.frombuffer(r, np.float32).reshape(8, 16)
                   .tobytes() == w0.astype(np.float32).tobytes()
                   for r in raws)

    def test_every_node_input_is_defined(self, tmp_path):
        """Graph is topologically valid: every node input is an initializer,
        a graph input, or a prior node output."""
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 8), nn.Sigmoid(),
                              nn.Linear(8, 2))
        p = paddle.onnx.export(model, str(tmp_path / "m"),
                               input_spec=[InputSpec([1, 8])])
        _, nodes, inits, g_in, _ = _parse_model(p)
        known = {name for name, *_ in inits} | {"input_0"}
        for op, ins, outs in nodes:
            for i in ins:
                assert i in known, (op, i)
            known.update(outs)

    def test_unsupported_model_raises_with_alternative(self, tmp_path):
        paddle.seed(0)
        conv = nn.Conv2D(3, 4, 3)
        with pytest.raises(ValueError, match="StableHLO"):
            paddle.onnx.export(conv, str(tmp_path / "c"),
                               input_spec=[InputSpec([1, 3, 8, 8])])


class TestNanInfSanitizer:
    def teardown_method(self, _m):
        paddle.set_flags({"FLAGS_check_nan_inf": False,
                          "FLAGS_check_nan_inf_level": 0,
                          "FLAGS_benchmark": False})

    def test_eager_op_raises_with_op_name(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError, match="divide"):
            x / 0.0

    def test_level_1_warns_instead(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True,
                          "FLAGS_check_nan_inf_level": 1})
        x = paddle.to_tensor(np.array([1.0], np.float32))
        with pytest.warns(UserWarning, match="divide"):
            x / 0.0

    def test_grad_path_checked_too(self):
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        x = paddle.to_tensor(np.array([-1.0], np.float32),
                             stop_gradient=False)
        with pytest.raises(FloatingPointError):
            paddle.sqrt(x)          # nan, on the differentiable path

    def test_train_step_loss_checked(self):
        from paddle_tpu.jit import TrainStep
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        paddle.seed(0)
        model = nn.Linear(4, 2)
        # lr large enough to blow up in a couple of steps with x*1e20
        opt = paddle.optimizer.SGD(1e30, parameters=model.parameters())
        step = TrainStep(model, lambda o, y: (o * 1e30).mean(), opt)
        x = paddle.to_tensor(np.ones((2, 4), np.float32) * 1e30)
        with pytest.raises(FloatingPointError):
            for _ in range(5):
                step(x, x)

    def test_benchmark_flag_syncs(self):
        paddle.set_flags({"FLAGS_benchmark": True})
        x = paddle.to_tensor(np.ones(4, np.float32))
        y = x + 1                      # must not raise; result ready
        np.testing.assert_allclose(np.asarray(y._value), 2.0)

    def test_flags_have_readers(self):
        """Every defined FLAGS_* is consumed somewhere in the package (no
        dead flags — round-2 verdict item 7)."""
        import subprocess, pathlib
        from paddle_tpu.framework.flags import _DEFS
        root = pathlib.Path(paddle.__file__).parent
        text = "".join(p.read_text() for p in root.rglob("*.py"))
        for name in _DEFS:
            bare = name[len("FLAGS_"):]
            assert name in text.replace("define_flag", "") or \
                f'"{bare}"' in text or f"'{bare}'" in text or \
                f".{bare}" in text, f"flag {name} has no reader"


class TestStaticShell:
    def test_py_func(self):
        from paddle_tpu.static import py_func
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        out = paddle.to_tensor(np.zeros(2, np.float32))
        py_func(lambda t: t * 3, x, out)
        np.testing.assert_allclose(np.asarray(out._value), [3.0, 6.0])

    def test_executor_run_fetches(self):
        from paddle_tpu.static import Executor
        exe = Executor()
        t = paddle.to_tensor(np.array([5.0], np.float32))
        res = exe.run(fetch_list=[t])
        np.testing.assert_allclose(res[0], [5.0])


class TestInferenceModelRoundTrip:
    """save_inference_model -> load_inference_model -> Executor.run with
    feed/fetch rewiring, parity with the live model (reference:
    python/paddle/static/io.py + fluid/io.py load_inference_model
    returning [program, feed_target_names, fetch_targets])."""

    def _model(self):
        import paddle_tpu.nn as nn
        paddle.seed(3)
        return nn.Sequential(nn.Linear(6, 16), nn.Tanh(), nn.Linear(16, 4))

    def test_roundtrip_parity(self, tmp_path):
        from paddle_tpu.static import (Executor, InputSpec,
                                       save_inference_model,
                                       load_inference_model)
        model = self._model()
        prefix = str(tmp_path / "infer")
        save_inference_model(
            prefix, [InputSpec([2, 6], "float32", name="x")], model)
        program, feed_names, fetch_targets = load_inference_model(prefix)
        assert feed_names == ["x"]
        rng = np.random.RandomState(0)
        x = rng.randn(2, 6).astype("float32")
        exe = Executor()
        got = exe.run(program, feed={"x": x}, fetch_list=fetch_targets)
        want = model(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got[0], want, rtol=1e-5, atol=1e-6)

    def test_feed_by_name_order_independent(self, tmp_path):
        """Feed dict order must not matter — rewiring is by NAME."""
        import paddle_tpu.nn as nn
        from paddle_tpu.static import (Executor, InputSpec,
                                       save_inference_model,
                                       load_inference_model)

        class TwoIn(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4)

            def forward(self, a, b):
                return self.lin(a) + 2.0 * b

        paddle.seed(4)
        model = TwoIn()
        prefix = str(tmp_path / "two")
        save_inference_model(
            prefix, [InputSpec([3, 4], "float32", name="a"),
                     InputSpec([3, 4], "float32", name="b")], model)
        program, feed_names, fetches = load_inference_model(prefix)
        assert feed_names == ["a", "b"]
        rng = np.random.RandomState(1)
        a = rng.randn(3, 4).astype("float32")
        b = rng.randn(3, 4).astype("float32")
        exe = Executor()
        # dict literal in the "wrong" order — names drive the wiring
        got = exe.run(program, feed={"b": b, "a": a}, fetch_list=fetches)
        want = model(paddle.to_tensor(a), paddle.to_tensor(b)).numpy()
        np.testing.assert_allclose(got[0], want, rtol=1e-5, atol=1e-6)

    def test_missing_feed_raises(self, tmp_path):
        from paddle_tpu.static import (Executor, InputSpec,
                                       save_inference_model,
                                       load_inference_model)
        model = self._model()
        prefix = str(tmp_path / "miss")
        save_inference_model(
            prefix, [InputSpec([2, 6], "float32", name="x")], model)
        program, _, fetches = load_inference_model(prefix)
        with pytest.raises(KeyError, match="x"):
            Executor().run(program, feed={}, fetch_list=fetches)


class TestStaticGraphSurface:
    """The static-graph API tier added for reference parity
    (python/paddle/static/__init__.py __all__, 50/50 present):
    functional entries execute eagerly, legacy executor machinery is an
    accepted-knob shell."""

    def test_data_feeds_save_inference_model(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu import static
        paddle.seed(0)
        model = nn.Linear(4, 2)
        spec = static.data("inp", [2, 4], "float32")
        prefix = str(tmp_path / "m")
        static.save_inference_model(prefix, [spec], model)
        program, feeds, fetches = static.load_inference_model(prefix)
        assert feeds == ["inp"]
        x = np.ones((2, 4), np.float32)
        out = static.Executor().run(program, feed={"inp": x},
                                    fetch_list=fetches)
        np.testing.assert_allclose(out[0], model(paddle.to_tensor(x)).numpy(),
                                   rtol=1e-5)

    def test_gradients_and_append_backward(self):
        from paddle_tpu import static
        x = paddle.to_tensor(np.array([2.0, 3.0], np.float32),
                             stop_gradient=False)
        y = (x * x).sum()
        (g,) = static.gradients(y, x)
        np.testing.assert_allclose(np.asarray(g._value), [4.0, 6.0])

    def test_scope_guard(self):
        from paddle_tpu import static
        s = static.Scope()
        with static.scope_guard(s):
            v = static.create_global_var([2], 7.0, "float32", name="gv")
            assert static.global_scope().find_var("gv") is v
        assert static.global_scope().find_var("gv") is None

    def test_accuracy_and_auc(self):
        from paddle_tpu import static
        logits = paddle.to_tensor(np.array([[0.1, 0.9], [0.8, 0.2],
                                            [0.3, 0.7]], np.float32))
        labels = paddle.to_tensor(np.array([[1], [0], [0]], np.int64))
        acc = static.accuracy(logits, labels, k=1)
        np.testing.assert_allclose(float(acc.numpy()), 2.0 / 3.0, rtol=1e-6)
        # perfectly separable scores -> AUC 1.0
        probs = paddle.to_tensor(np.array([[0.1, 0.9], [0.9, 0.1],
                                           [0.2, 0.8]], np.float32))
        lab = paddle.to_tensor(np.array([1, 0, 1], np.int64))
        np.testing.assert_allclose(float(static.auc(probs, lab).numpy()),
                                   1.0, rtol=1e-6)

    def test_exponential_moving_average(self):
        from paddle_tpu import static
        import paddle_tpu.nn as nn
        paddle.seed(0)
        model = nn.Linear(3, 3)
        ema = static.ExponentialMovingAverage(decay=0.5)
        ema.register(model.parameters())
        before = np.asarray(model.weight._value).copy()
        model.weight._value = model.weight._value + 10.0
        ema.update()
        with ema.apply():
            inside = np.asarray(model.weight._value)
        after = np.asarray(model.weight._value)
        # inside apply(): shadow (between old and new); outside: restored
        assert inside.mean() < after.mean()
        np.testing.assert_allclose(after, before + 10.0)

    def test_program_serialize_roundtrip(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu import static
        paddle.seed(1)
        model = nn.Linear(4, 2)
        spec = [static.InputSpec([2, 4], "float32", name="x")]
        prog_bytes = static.serialize_program(spec, model)
        w_bytes = static.serialize_persistables(spec, model)
        p = str(tmp_path / "prog.bin")
        static.save_to_file(p, prog_bytes)
        translated = static.deserialize_program(static.load_from_file(p))
        state = static.deserialize_persistables(None, w_bytes)
        assert translated.has_forward and "weight" in " ".join(state)
        # program-only artifact: arm it with the persistables, then run
        translated.set_state(state)
        x = np.ones((2, 4), np.float32)
        np.testing.assert_allclose(
            np.asarray(translated(x)._value),
            model(paddle.to_tensor(x)).numpy(), rtol=1e-5)

    def test_legacy_executor_shells(self):
        from paddle_tpu import static
        bs = static.BuildStrategy()
        bs.fuse_bn_act_ops = True          # arbitrary knobs accepted
        cp = static.CompiledProgram(lambda: 41).with_data_parallel(
            build_strategy=bs)
        assert cp() == 41
        with static.device_guard("cpu"):
            pass
        assert static.cuda_places() == []
        assert len(static.cpu_places()) >= 1

    def test_ipu_guarded(self):
        from paddle_tpu import static
        with pytest.raises(NotImplementedError):
            static.ipu_shard_guard()

    def test_exponential_decay_schedule(self):
        from paddle_tpu import static
        lr = static.exponential_decay(0.1, decay_steps=10, decay_rate=0.5)
        assert abs(lr() - 0.1) < 1e-9
        for _ in range(10):
            lr.step()
        assert abs(lr() - 0.05) < 1e-9      # one full decay interval

    def test_exponential_decay_staircase_plateaus(self):
        from paddle_tpu import static
        lr = static.exponential_decay(0.1, decay_steps=10, decay_rate=0.5,
                                      staircase=True)
        for _ in range(9):
            lr.step()
        assert abs(lr() - 0.1) < 1e-9       # still on the first plateau
        lr.step()
        assert abs(lr() - 0.05) < 1e-9      # dropped exactly at step 10

    def test_serialize_program_with_nonpersistable_buffer(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu import static

        class WithBuf(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4)
                self.register_buffer(
                    "scale_buf", paddle.to_tensor(np.float32(2.0)),
                    persistable=False)

            def forward(self, x):
                return self.lin(x) * self.scale_buf

        paddle.seed(2)
        model = WithBuf()
        spec = [static.InputSpec([2, 4], "float32", name="x")]
        prog = static.deserialize_program(
            static.serialize_program(spec, model))
        prog.set_state(static.deserialize_persistables(
            None, static.serialize_persistables(spec, model)))
        x = np.ones((2, 4), np.float32)
        np.testing.assert_allclose(np.asarray(prog(x)._value),
                                   model(paddle.to_tensor(x)).numpy(),
                                   rtol=1e-5)

    def test_create_parameter_seeded_and_distinct(self):
        from paddle_tpu import static
        paddle.seed(5)
        a = static.create_parameter([4, 4], "float32")
        b = static.create_parameter([4, 4], "float32")
        assert not np.allclose(np.asarray(a._value), np.asarray(b._value))
        paddle.seed(5)
        c = static.create_parameter([4, 4], "float32")
        np.testing.assert_allclose(np.asarray(a._value),
                                   np.asarray(c._value))
