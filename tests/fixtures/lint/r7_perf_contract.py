"""R7 golden fixture: perf-contract drift.

A heavy-contraction op dispatching under a name the goodput estimator
cannot cover (and with no declare_op_flops declaration), plus a
compiled-path flag that is neither in the env fingerprint nor declared
fusion-neutral. The good forms (matmul-family dispatch name, declared
estimator, fingerprinted/neutral flags) stay clean.
"""
import jnp

# contract surfaces (mini mirrors of ops/aot_cache.py)
FUSION_NEUTRAL_FLAGS = frozenset({"FLAGS_neutral_cache_size"})


def env_fingerprint():
    return {"flags": [("FLAGS_routes_kernel", True)]}


def register_op(name, kind, ref=None):
    def deco(fn):
        return fn
    return deco


def binary(name, fn, a, b):
    return fn(a, b)


def declare_op_flops(name, fn):
    return fn


@register_op("bad_contract", "math")
def bad_contract(x, y):
    # heavy einsum under an uncoverable dispatch name -> finding
    return binary("bad_contract",
                  lambda a, b: jnp.einsum("ij,jk->ik", a, b), x, y)


@register_op("good_family_name", "math")
def good_family_name(x, y):
    # dispatches under "matmul": the estimator's family heuristic covers it
    return binary("matmul", jnp.matmul, x, y)


@register_op("good_declared", "math")
def good_declared(x, y):
    # heavy tensordot, but its dispatch name carries a declaration below
    return binary("declared_contraction", jnp.tensordot, x, y)


declare_op_flops("declared_contraction", lambda shapes: 1)


@register_op("routed", "math")
def routed(x):
    if read_flag("FLAGS_undeclared_routing"):   # off-contract -> finding
        return x
    if read_flag("FLAGS_neutral_cache_size"):   # declared neutral: clean
        return x
    if read_flag("FLAGS_routes_kernel"):        # fingerprinted: clean
        return x
    return x


def read_flag(name):
    return False
