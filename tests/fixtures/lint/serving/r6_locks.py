"""R6 golden known-bad: blocking work / callback invocation under a
registry lock, plus a lock-order inversion."""
import threading
import time


class BadRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._callbacks = []
        self._rows = {}

    def slow_write(self, row):
        with self._lock:
            time.sleep(0.01)                    # line 16: blocking
            self._rows[row] = 1

    def notify(self, payload):
        with self._lock:
            for cb in self._callbacks:
                cb(payload)                     # line 22: callback held
            self.on_change(payload)             # line 23: callback held

    def on_change(self, payload):
        pass

    def forward_order(self):
        with self._lock:
            with self._state_lock:              # _lock -> _state_lock
                return dict(self._rows)

    def reverse_order(self):
        with self._state_lock:
            with self._lock:                    # inversion -> finding
                return len(self._rows)


class GoodRegistry:
    """The fixed form: snapshot under the lock, act after release."""

    def __init__(self):
        self._lock = threading.Lock()
        self._callbacks = []
        self._rows = {}

    def notify(self, payload):
        with self._lock:
            callbacks = list(self._callbacks)
        for cb in callbacks:
            cb(payload)
