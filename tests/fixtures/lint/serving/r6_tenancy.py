"""R6 golden known-bad, tenancy flavor (PR 17): device sync and event
side effects while holding the prefix-index lock, plus an inversion
against the allocator lock — the race classes serving/tenancy.py's
snapshot-then-act discipline exists to rule out."""
import threading


class BadPrefixIndex:
    def __init__(self):
        self._lock = threading.Lock()
        self._alloc_lock = threading.Lock()
        self._entries = {}
        self._evict_hooks = []

    def publish(self, key, block, pool):
        with self._lock:
            self._entries[key] = block
            pool.block_until_ready()            # line 18: device sync held

    def reclaim(self, key):
        with self._lock:
            block = self._entries.pop(key)
            for hook in self._evict_hooks:
                hook(key, block)                # line 24: observer held
            self.on_evict(key)                  # line 25: event emit held
        return block

    def on_evict(self, key):
        pass

    def acquire(self, key):
        with self._lock:
            with self._alloc_lock:              # _lock -> _alloc_lock
                return self._entries.get(key)

    def refcount_fast(self, key):
        with self._alloc_lock:
            with self._lock:                    # line 38: inversion
                return key in self._entries


class GoodPrefixIndex:
    """The fixed form tenancy.py ships: mutate the index/refcounts under
    the lock, emit events and touch the device after release."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}
        self._evict_hooks = []

    def reclaim(self, key):
        with self._lock:
            block = self._entries.pop(key)
            hooks = list(self._evict_hooks)
        for hook in hooks:
            hook(key, block)
        return block
