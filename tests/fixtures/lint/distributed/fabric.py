"""R6 golden known-bad, fabric-flavored: blocking work / rebuild
listener invocation under the membership lock, plus a membership/state
lock inversion — the races distributed/fabric.py's snapshot-then-emit
discipline (collect events under the lock, emit after release) avoids."""
import threading
import time


class BadCoordinator:
    def __init__(self):
        self._lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._members = {}
        self._listeners = []

    def reap(self):
        with self._lock:
            time.sleep(0.05)                    # line 18: lease wait held
            self._members.clear()

    def publish(self, spec):
        with self._lock:
            for listener in self._listeners:
                listener(spec)                  # line 24: listener held
            print("fleet.rebuild", spec)        # line 25: blocking held

    def forward(self):
        with self._lock:
            with self._state_lock:              # _lock -> _state_lock
                return dict(self._members)

    def inverted(self):
        with self._state_lock:
            with self._lock:                    # line 34: inversion
                return len(self._members)


class GoodCoordinator:
    """The shipped discipline (fabric._publish_locked + _emit): mutate
    and collect under the lock, notify after release."""

    def __init__(self):
        self._lock = threading.Lock()
        self._listeners = []
        self._spec = None

    def publish(self, spec):
        with self._lock:
            self._spec = spec
            listeners = list(self._listeners)
        for listener in listeners:
            listener(spec)
