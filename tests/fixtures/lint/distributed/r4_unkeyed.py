"""R4 golden known-bad: process-group collectives entering (or
bypassing) the funnel without a dispatch.mark_collective stamp."""
from paddle_tpu.ops.dispatch import call_op, mark_collective


def bad_direct_collective(tensor, group):
    pg = group.pg
    return pg.all_reduce(tensor._value, "sum")        # line 8: no funnel


def bad_unmarked_funnel(tensor, group):
    pg = group.pg
    return call_op("dist.all_reduce",
                   lambda v: pg.all_reduce(v, "sum"),  # line 14: unmarked
                   [tensor])


def _dispatch_marked(name, fn, tensor, key):
    """The marking funnel (the _dispatch_collective pattern)."""
    mark_collective(fn, key)
    return call_op(name, fn, [tensor])


def good_marked_collective(tensor, group, key):
    """The fixed form: the fn flows through the marking funnel."""
    pg = group.pg
    return _dispatch_marked("dist.all_reduce",
                            lambda v: pg.all_reduce(v, "sum"), tensor, key)
