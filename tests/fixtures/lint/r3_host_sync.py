"""R3 golden known-bad: host-forcing reads (.numpy()/.item()/float())
of Tensors inside a dispatch-funnel wrapper — each one splits any
pending fused chain/step at runtime."""
import jax.numpy as jnp

from paddle_tpu.ops._helpers import ensure_tensor, call_op


def bad_peeking_op(x, name=None):
    x = ensure_tensor(x)
    host_copy = x.numpy()                     # line 11: forces the value
    peak = float(x)                           # line 12: forces again
    if host_copy.ndim > 0 and peak >= 0.0:
        pass

    def fn(v):
        return jnp.tanh(v)
    return call_op("bad_peek", fn, (x,))


def bad_item_op(x, threshold, name=None):
    x = ensure_tensor(x)
    t = ensure_tensor(threshold)
    limit = t.item()                          # line 23: forces the value
    return call_op("bad_item", lambda v: jnp.clip(v, -limit, limit), (x,))


def good_aval_op(x, name=None):
    """The fixed form: aval-safe shape peek — no finding."""
    x = ensure_tensor(x)
    n = x.shape[0]
    return call_op("good_aval", lambda v: v.reshape(n, -1), (x,))
