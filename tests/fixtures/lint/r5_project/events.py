"""R5 fixture: miniature contract surfaces seeded with drift."""

CATEGORIES = frozenset({
    "step.fire", "step.split",
})

REASON_CODES = frozenset({
    "rng_rekey",
    "shape_mismatch",
    "orphan_code",          # line 10: no REASON_HINTS entry -> finding
})


class _Ring:
    def emit(self, cat, op="", key=None, reason=None, detail=None):
        pass


EVENTS = _Ring()


def fire(key):
    EVENTS.emit("step.fire", "op", key)
    EVENTS.emit("step.ghost", "op", key)                  # line 23: bad cat
    EVENTS.emit("step.split", "op", key, "made_up_code")  # line 24: bad code
