"""R5 fixture: the flag registry."""

_FLAGS = {}


def define_flag(name, default, help_str=""):
    _FLAGS[name] = default
    return default


define_flag("FLAGS_fixture_known", True, "a registered flag")
