"""R5 fixture: hints map missing one code and carrying one stale key."""

REASON_HINTS = {
    "rng_rekey": "hoist the key",
    "shape_mismatch": "pad/bucket shapes",
    "ancient_code": "this code no longer exists",   # stale -> finding
}
