"""R5 fixture: flag reads — one registered, one typo'd (never
registered, silently reads None forever at runtime)."""
from .flags import _FLAGS


def configured():
    ok = _FLAGS.get("FLAGS_fixture_known")
    bad = _FLAGS.get("FLAGS_fixture_typod")     # line 8: unregistered
    return ok, bad
