"""R5 fixture: metric contract with a policy-less name and an
off-contract registration."""

METRIC_NAMES = frozenset({
    "train_step_seconds",
    "orphan_metric",            # no METRIC_MERGE policy -> finding
})

METRIC_MERGE = {
    "train_step_seconds": "sum",
}


class _Reg:
    def counter(self, name, help=""):
        return name


def install(reg):
    reg.counter("train_step_seconds")
    reg.counter("rogue_total")      # off the METRIC_NAMES contract
