"""R1 golden known-bad: op fns capturing Tensor/array/mutable-global
state that never enters the dispatch-input list (the PR 3/4 bug class).
Line numbers are asserted exactly by tests/test_fusion_lint.py — edit
with care."""
import jax.numpy as jnp

from paddle_tpu.ops._helpers import ensure_tensor, call_op, unary
from paddle_tpu.ops.registry import register_op

_LOOKUP_STATE = {"scale": 2.0}            # mutable module global


@register_op("bad_gather", "fixture")
def bad_gather(x, index, name=None):
    x = ensure_tensor(x)
    idx = ensure_tensor(index)._value     # raw array...

    def fn(v):
        return jnp.take(v, idx, axis=0)   # line 19: captured, not an input
    return call_op("bad_gather", fn, (x,))


@register_op("bad_mask", "fixture")
def bad_mask(x, mask, name=None):
    x = ensure_tensor(x)
    m = ensure_tensor(mask)               # a Tensor...
    return unary("bad_mask",
                 lambda v: jnp.where(m._value, v, 0.0), x)   # line 28


@register_op("bad_global", "fixture")
def bad_global(x, name=None):
    x = ensure_tensor(x)

    def fn(v):
        return v * _LOOKUP_STATE["scale"]   # line 36: mutable global read
    return call_op("bad_global", fn, (x,))


@register_op("good_threaded", "fixture")
def good_threaded(x, index, name=None):
    """The fixed form: the index rides as a dispatch input — no finding."""
    x = ensure_tensor(x)
    idx = ensure_tensor(index)

    def fn(v, iv):
        return jnp.take(v, iv, axis=0)
    return call_op("good_threaded", fn, (x, idx))
