"""R2 golden known-bad: a registered op body drawing stateful global
randomness instead of hoisting a stream position via rng_key_input()."""
import jax

from paddle_tpu.framework.core import Tensor
from paddle_tpu.framework.random import get_rng_key, split_key, \
    default_generator, rng_key_input
from paddle_tpu.ops._helpers import ensure_tensor, call_op
from paddle_tpu.ops.registry import register_op


@register_op("bad_noise", "fixture")
def bad_noise(shape, name=None):
    return Tensor(jax.random.normal(get_rng_key(), tuple(shape)))  # line 14


@register_op("bad_split", "fixture")
def bad_split(shape, name=None):
    keys = split_key(2)                                            # line 19
    return Tensor(jax.random.normal(keys[0], tuple(shape)))


@register_op("bad_direct", "fixture")
def bad_direct(shape, name=None):
    key = default_generator.next_key()                             # line 25
    return Tensor(jax.random.normal(key, tuple(shape)))


@register_op("good_hoisted", "fixture")
def good_hoisted(x, name=None):
    """The fixed form: a hoisted stream position — no finding."""
    x = ensure_tensor(x)
    kd = rng_key_input()

    def fn(v, key_data):
        return jax.random.bernoulli(
            jax.random.wrap_key_data(key_data), v).astype(v.dtype)
    return call_op("good_hoisted", fn, (x, kd))
