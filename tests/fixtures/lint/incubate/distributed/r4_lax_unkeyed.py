"""R4 golden known-bad (lax surface): named-axis collectives inside an
eagerly dispatched fn without the dispatch.mark_collective stamp — the
closure scan cannot key the axis binding, so every cycle containing the
op poisons. shard_map-only bodies never reach the funnel and are clean."""
import jax

from paddle_tpu.framework.jax_compat import shard_map
from paddle_tpu.ops.dispatch import call_op, mark_collective


def bad_unstamped_ppermute(tensor, perm):
    def fn(v):
        return jax.lax.ppermute(v, "pipe", perm)       # line 13: unstamped
    return call_op("p2p.ppermute", fn, (tensor,))


def bad_unstamped_alltoall(tensor):
    return call_op(
        "moe.dispatch",
        lambda v: jax.lax.all_to_all(v, "expert",      # line 20: unstamped
                                     split_axis=0, concat_axis=0),
        (tensor,))


def good_stamped_ppermute(tensor, perm, key):
    """The fixed form: the stamp keys the fn before any closure walk."""
    def fn(v):
        return jax.lax.ppermute(v, "pipe", perm)
    mark_collective(fn, key)
    return call_op("p2p.ppermute", fn, (tensor,))


def good_shard_map_body(tensor, mesh, specs):
    """A compiled SPMD program: the collective is the intended lowering
    and never touches the dispatch cache."""
    def body(v):
        return jax.lax.ppermute(v, "pipe", [(0, 1)])
    return shard_map(body, mesh=mesh, in_specs=specs,
                     out_specs=specs)(tensor)
