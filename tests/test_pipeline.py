"""Pipeline parallelism over the mesh "pipe" axis.

Reference analog: hybrid_parallel_pp_* suites
(unittests/collective/fleet/hybrid_parallel_pp_layer.py etc.) — pipelined
training must match single-device training; the schedule must actually
overlap micro-batches across stages.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.mesh import build_mesh, set_global_mesh
from paddle_tpu.distributed.fleet.meta_parallel import (
    spmd_pipeline, pipeline_schedule, PipelineTrainStep, find_block_run)
from paddle_tpu.incubate.models import (
    GPTConfig, GPTForCausalLM, GPTPretrainingCriterion, gpt_pipeline_layers,
    shard_gpt)
from paddle_tpu.jit import TrainStep


def tiny_cfg(**kw):
    base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=4,
                num_attention_heads=4, intermediate_size=64,
                max_position_embeddings=32, hidden_dropout_prob=0.0,
                attention_probs_dropout_prob=0.0, use_flash_attention=False)
    base.update(kw)
    return GPTConfig(**base)


class TestSchedule:
    def test_steady_state_overlap(self):
        """Micro-batch overlap: in steady state every stage is busy on a
        DIFFERENT micro-batch at the same timestep."""
        M, S = 8, 4
        sched = pipeline_schedule(M, S)
        assert len(sched) == M + S - 1
        steady = sched[S - 1:M]
        for active in steady:
            assert len(active) == S                       # all stages busy
            stages = {s for s, _ in active}
            micros = {m for _, m in active}
            assert len(stages) == S and len(micros) == S  # all distinct
        # every (stage, micro) pair appears exactly once overall
        all_pairs = [p for step in sched for p in step]
        assert len(all_pairs) == M * S
        assert len(set(all_pairs)) == M * S

    def test_fill_and_drain(self):
        sched = pipeline_schedule(4, 4)
        assert sched[0] == {(0, 0)}
        assert sched[-1] == {(3, 3)}


class TestSpmdPipeline:
    def test_forward_matches_sequential(self):
        """spmd_pipeline over pp=4 == applying the 4 stages in sequence."""
        mesh = build_mesh(dp=1, pp=4, sharding=1, sep=1, mp=1,
                          devices=jax.devices()[:4])
        S, M, mb, d = 4, 8, 2, 16
        rng = np.random.default_rng(0)
        ws = jnp.asarray(rng.normal(size=(S, d, d)) * 0.1, jnp.float32)
        x = jnp.asarray(rng.normal(size=(M, mb, d)), jnp.float32)

        def stage_fn(params, h):
            return jnp.tanh(h @ params[0])

        y = spmd_pipeline(stage_fn, [ws], x, mesh=mesh)
        ref = x
        for s in range(S):
            ref = jnp.tanh(ref @ ws[s])
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_grad_flows_through_pipeline(self):
        """jax.grad through the ppermute ring gives the same gradients as
        the sequential composition (the reverse pipeline is implicit)."""
        mesh = build_mesh(dp=1, pp=2, sharding=1, sep=1, mp=1,
                          devices=jax.devices()[:2])
        S, M, mb, d = 2, 4, 2, 8
        rng = np.random.default_rng(1)
        ws = jnp.asarray(rng.normal(size=(S, d, d)) * 0.1, jnp.float32)
        x = jnp.asarray(rng.normal(size=(M, mb, d)), jnp.float32)

        def stage_fn(params, h):
            return jnp.tanh(h @ params[0])

        def loss_pipe(w):
            return jnp.sum(spmd_pipeline(stage_fn, [w], x, mesh=mesh) ** 2)

        def loss_seq(w):
            h = x
            for s in range(S):
                h = jnp.tanh(h @ w[s])
            return jnp.sum(h ** 2)

        g_pipe = jax.grad(loss_pipe)(ws)
        g_seq = jax.grad(loss_seq)(ws)
        np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq),
                                   rtol=2e-5, atol=2e-5)


class TestFindBlockRun:
    def test_gpt_run(self):
        model = GPTForCausalLM(tiny_cfg())
        layers = gpt_pipeline_layers(model)
        start, count = find_block_run(layers, 2)
        assert start == 1 and count == 4

    def test_no_run_raises(self):
        model = GPTForCausalLM(tiny_cfg(num_hidden_layers=1))
        layers = gpt_pipeline_layers(model)
        with pytest.raises(ValueError):
            find_block_run(layers, 2)

    def test_trims_to_multiple(self):
        model = GPTForCausalLM(tiny_cfg(num_hidden_layers=5))
        layers = gpt_pipeline_layers(model)
        start, count = find_block_run(layers, 2)
        assert count == 4


def _train_losses_pipeline(pp, mp, steps=5, num_micro=4, lr=1e-2,
                           stage_sizes=None, layers=4):
    n_dev = 8
    dp = n_dev // (pp * mp)
    mesh = build_mesh(dp=dp, pp=pp, sharding=1, sep=1, mp=mp,
                      devices=jax.devices()[:n_dev])
    set_global_mesh(mesh)
    paddle.seed(0)
    model = GPTForCausalLM(tiny_cfg(num_hidden_layers=layers))
    if mp > 1:
        shard_gpt(model, mesh)
    step = PipelineTrainStep(
        gpt_pipeline_layers(model), GPTPretrainingCriterion(),
        paddle.optimizer.AdamW(learning_rate=lr,
                               parameters=model.parameters()),
        mesh=mesh, num_microbatches=num_micro, stage_sizes=stage_sizes)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (8, 16))
    labels = rng.integers(0, 128, (8, 16))
    losses = []
    for _ in range(steps):
        losses.append(float(step(jnp.asarray(ids, jnp.int32),
                                 jnp.asarray(labels, jnp.int32))))
    return losses, step, model


def _train_losses_single(steps=5, lr=1e-2, layers=4):
    set_global_mesh(build_mesh(dp=1, pp=1, sharding=1, sep=1, mp=1,
                               devices=jax.devices()[:1]))
    paddle.seed(0)
    model = GPTForCausalLM(tiny_cfg(num_hidden_layers=layers))
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=model.parameters())
    crit = GPTPretrainingCriterion()
    step = TrainStep(model, lambda o, y: crit(o, y), opt)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, (8, 16))
    labels = rng.integers(0, 128, (8, 16))
    losses = []
    for _ in range(steps):
        losses.append(float(step(jnp.asarray(ids, jnp.int32),
                                 jnp.asarray(labels, jnp.int32))))
    return losses


class TestPipelineTraining:
    def test_pp2_matches_single_device(self):
        ref = _train_losses_single()
        got, _, _ = _train_losses_pipeline(pp=2, mp=1)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
        assert got[-1] < got[0]          # actually learning

    def test_pp4_matches_single_device(self):
        ref = _train_losses_single()
        got, _, _ = _train_losses_pipeline(pp=4, mp=1, num_micro=4)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_pp2_mp2_matches_single_device(self):
        """Hybrid pp=2 x mp=2 (x dp=2): Megatron shardings on the stacked
        stage params compose with the pipe-axis pipeline."""
        ref = _train_losses_single()
        got, _, _ = _train_losses_pipeline(pp=2, mp=2)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_stage_params_sharded_over_pipe(self):
        """Each stacked block-param leaf is actually placed over the pipe
        axis (dim 0) — stages live on different devices."""
        _, step, _ = _train_losses_pipeline(pp=2, mp=1, steps=1)
        from jax.sharding import NamedSharding
        for leaf in step._stacked:
            shd = leaf.sharding
            assert isinstance(shd, NamedSharding)
            assert shd.spec[0] == "pipe"
            # shards on distinct pipe coordinates hold disjoint stage slices
            assert leaf.shape[0] == 2

    def test_sync_to_model_roundtrip(self):
        _, step, model = _train_losses_pipeline(pp=2, mp=1, steps=2)
        step.sync_to_model()
        for p in model.parameters():
            assert np.all(np.isfinite(np.asarray(p._value)))

    def test_tied_embedding_gets_trained(self):
        """The tied wte weight (used by both prologue and epilogue) must
        receive gradient updates."""
        set_global_mesh(build_mesh(dp=4, pp=2, sharding=1, sep=1, mp=1,
                                   devices=jax.devices()[:8]))
        paddle.seed(0)
        model = GPTForCausalLM(tiny_cfg())
        wte_before = np.asarray(model.gpt.wte.weight._value).copy()
        step = PipelineTrainStep(
            gpt_pipeline_layers(model), GPTPretrainingCriterion(),
            paddle.optimizer.AdamW(learning_rate=1e-2,
                                   parameters=model.parameters()),
            num_microbatches=2)
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 128, (4, 16)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 128, (4, 16)), jnp.int32)
        step(ids, labels)
        step.sync_to_model()
        wte_after = np.asarray(model.gpt.wte.weight._value)
        assert not np.allclose(wte_before, wte_after)

    def test_edge_params_sharded_over_pipe_not_replicated(self):
        """Heterogeneous edges: the embedding/head (prologue/epilogue)
        parameters must be SHARDED over the pipe axis, not replicated on
        every stage group (reference analog: LayerDesc places them on edge
        stages, pp_layers.py:208; here they distribute across all pipe
        groups)."""
        _, step, model = _train_losses_pipeline(pp=2, mp=1, steps=1)
        from jax.sharding import NamedSharding
        wte = model.gpt.wte.weight._value
        shd = wte.sharding
        assert isinstance(shd, NamedSharding)
        flat_axes = set()
        for d in shd.spec:
            flat_axes.update(d if isinstance(d, tuple) else (d,))
        assert "pipe" in flat_axes, shd.spec
        # each pipe group holds half the table, not a full copy
        assert wte.addressable_shards[0].data.size <= wte.size // 2

    def test_ragged_stage_sizes_match_single_device(self):
        """Heterogeneous partition: stage 0 gets 1 block, stage 1 gets 3
        (reference analog: SegmentLayers non-uniform segmentation). The
        masked schedule must reproduce single-device training exactly."""
        ref = _train_losses_single()
        got, step, _ = _train_losses_pipeline(pp=2, mp=1,
                                              stage_sizes=[1, 3])
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
        assert step._stage_sizes_eff == [1, 3]
        assert step._per_stage == 3          # padded to the widest stage

    def test_ragged_sync_to_model_skips_padding(self):
        _, step, model = _train_losses_pipeline(pp=2, mp=1, steps=2,
                                                stage_sizes=[3, 1])
        step.sync_to_model()
        for p in model.parameters():
            assert np.all(np.isfinite(np.asarray(p._value)))

    def test_pipeline_layer_segments_drive_ragged_partition(self):
        """A PipelineLayer whose SegmentLayers split is non-uniform flows
        its per-stage block counts into the masked pipeline."""
        from paddle_tpu.distributed.fleet.meta_parallel.pp_layers import \
            PipelineLayer
        n_dev = 8
        mesh = build_mesh(dp=4, pp=2, sharding=1, sep=1, mp=1,
                          devices=jax.devices()[:n_dev])
        set_global_mesh(mesh)
        paddle.seed(0)
        model = GPTForCausalLM(tiny_cfg(num_hidden_layers=5))
        pl = PipelineLayer(gpt_pipeline_layers(model), num_stages=2)
        # 7 layers -> uniform segmentation [0,4,7]: stage0 = emb + 3 blocks,
        # stage1 = 2 blocks + head -> ragged block split [3, 2]
        step = PipelineTrainStep(
            pl, GPTPretrainingCriterion(),
            paddle.optimizer.AdamW(learning_rate=1e-2,
                                   parameters=model.parameters()),
            mesh=mesh, num_microbatches=4)
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 128, (8, 16)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 128, (8, 16)), jnp.int32)
        l0 = float(step(ids, labels))
        l1 = float(step(ids, labels))
        assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0
        assert step._stage_sizes_eff == [3, 2]
        # parity with single-device on the same 5-layer model
        ref = _train_losses_single(steps=2, layers=5)
        np.testing.assert_allclose([l0, l1], ref, rtol=1e-5, atol=1e-5)

    def test_batch_not_divisible_raises(self):
        set_global_mesh(build_mesh(dp=1, pp=2, sharding=1, sep=1, mp=1,
                                   devices=jax.devices()[:2]))
        paddle.seed(0)
        model = GPTForCausalLM(tiny_cfg())
        step = PipelineTrainStep(
            gpt_pipeline_layers(model), GPTPretrainingCriterion(),
            paddle.optimizer.AdamW(learning_rate=1e-2,
                                   parameters=model.parameters()),
            num_microbatches=3)
        ids = jnp.zeros((4, 16), jnp.int32)
        with pytest.raises(ValueError):
            step(ids, ids)

    def test_too_few_microbatches_raises(self):
        set_global_mesh(build_mesh(dp=1, pp=4, sharding=1, sep=1, mp=1,
                                   devices=jax.devices()[:4]))
        model = GPTForCausalLM(tiny_cfg())
        with pytest.raises(ValueError):
            PipelineTrainStep(
                gpt_pipeline_layers(model), GPTPretrainingCriterion(),
                paddle.optimizer.AdamW(learning_rate=1e-2,
                                       parameters=model.parameters()),
                num_microbatches=2)


def _train_losses_bf16_mp(pp, steps=5, num_micro=4, lr=1e-2):
    """bf16 weights + f32 master AdamW (multi_precision) — the BASELINE
    config-4 recipe — either single-device (pp=1) or pipelined."""
    if pp == 1:
        set_global_mesh(build_mesh(dp=1, pp=1, sharding=1, sep=1, mp=1,
                                   devices=jax.devices()[:1]))
    else:
        set_global_mesh(build_mesh(dp=8 // pp, pp=pp, sharding=1, sep=1,
                                   mp=1, devices=jax.devices()[:8]))
    paddle.seed(0)
    model = GPTForCausalLM(tiny_cfg())
    model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=model.parameters(),
                                 multi_precision=True)
    crit = GPTPretrainingCriterion()
    if pp == 1:
        step = TrainStep(model, lambda o, y: crit(o, y), opt)
    else:
        step = PipelineTrainStep(gpt_pipeline_layers(model), crit, opt,
                                 num_microbatches=num_micro)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 128, (8, 16)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, 128, (8, 16)), jnp.int32)
    losses = [float(step(ids, labels)) for _ in range(steps)]
    return losses, step, model


class TestPipelineMultiPrecision:
    def test_pp2_bf16_master_matches_single_device(self):
        """multi_precision (bf16 weights + f32 master) through the pipeline
        matches single-device multi_precision training. Reference analog:
        hybrid_parallel_optimizer.py:186 master-weight path."""
        ref, _, _ = _train_losses_bf16_mp(pp=1)
        got, _, _ = _train_losses_bf16_mp(pp=2)
        np.testing.assert_allclose(got, ref, rtol=3e-2, atol=3e-2)
        assert got[-1] < got[0]

    def test_master_weights_stay_f32_params_stay_bf16(self):
        _, step, model = _train_losses_bf16_mp(pp=2, steps=2)
        assert "master_weight" in step._acc_names
        mw_ix = step._acc_names.index("master_weight")
        n_master = 0
        for accs in step._stacked_accs:
            a = accs[mw_ix]
            if a is not None:
                assert a.dtype == jnp.float32
                n_master += 1
        assert n_master > 0
        step.sync_to_model()
        for p in model.parameters():
            assert p._value.dtype == jnp.bfloat16

    def test_master_weight_drives_update_precision(self):
        """With lr small enough that bf16 rounding would swallow updates,
        the f32 master still accumulates them (the whole point of
        multi_precision)."""
        losses, step, _ = _train_losses_bf16_mp(pp=2, steps=8, lr=2e-3)
        assert losses[-1] < losses[0]


class TestPipelineParallelAPI:
    def test_train_batch_uses_spmd_pipeline(self):
        """The reference-parity PipelineParallel.train_batch rides the SPMD
        pipeline when the global mesh has pipe > 1."""
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineParallel, PipelineLayer, LayerDesc)
        from paddle_tpu.distributed.fleet.base.topology import (
            CommunicateTopology, HybridCommunicateGroup)
        import paddle_tpu.nn as nn

        mesh = build_mesh(dp=1, pp=2, sharding=1, sep=1, mp=1,
                          devices=jax.devices()[:2])
        set_global_mesh(mesh)
        paddle.seed(0)
        model = GPTForCausalLM(tiny_cfg())
        crit = GPTPretrainingCriterion()
        pipe_model = PipelineLayer(gpt_pipeline_layers(model), num_stages=2,
                                   loss_fn=crit)
        pp_runner = PipelineParallel(pipe_model, hcg=None)
        pp_runner.accumulate_steps = 2
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 128, (4, 16)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 128, (4, 16)), jnp.int32)
        l1 = float(pp_runner.train_batch((ids, labels), opt))
        l2 = float(pp_runner.train_batch((ids, labels), opt))
        assert pp_runner._spmd_step is not None   # took the SPMD path
        assert np.isfinite(l1) and np.isfinite(l2) and l2 < l1


class TestPipelineRNGAndState:
    def test_distinct_dropout_keys_per_microbatch_and_stage(self):
        """With a key, stage_fn sees a key folded over (timestep, stage):
        noise injected per micro-batch must differ."""
        mesh = build_mesh(dp=1, pp=2, sharding=1, sep=1, mp=1,
                          devices=jax.devices()[:2])
        S, M, mb, d = 2, 4, 2, 8
        ws = jnp.zeros((S, 1))

        def stage_fn(params, h, k):
            return h + jax.random.normal(k, h.shape, h.dtype)

        x = jnp.zeros((M, mb, d), jnp.float32)
        y = spmd_pipeline(stage_fn, [ws], x, mesh=mesh,
                          key=jax.random.PRNGKey(0))
        ymb = np.asarray(y)
        # each micro-batch accumulated noise from a different key chain
        for i in range(M):
            for j in range(i + 1, M):
                assert not np.allclose(ymb[i], ymb[j])

    def test_training_with_dropout_learns(self):
        mesh = build_mesh(dp=1, pp=2, sharding=1, sep=1, mp=1,
                          devices=jax.devices()[:2])
        set_global_mesh(mesh)
        paddle.seed(0)
        model = GPTForCausalLM(tiny_cfg(hidden_dropout_prob=0.1,
                                        attention_probs_dropout_prob=0.1))
        model.train()
        step = PipelineTrainStep(
            gpt_pipeline_layers(model), GPTPretrainingCriterion(),
            paddle.optimizer.AdamW(learning_rate=5e-3,
                                   parameters=model.parameters()),
            mesh=mesh, num_microbatches=2)
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 128, (4, 16)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 128, (4, 16)), jnp.int32)
        losses = [float(step(ids, labels)) for _ in range(10)]
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]

    def test_sync_writes_optimizer_state(self):
        """After sync_to_model, optimizer.state_dict-visible accumulators
        hold the live moments (non-zero after training)."""
        _, step, model = _train_losses_pipeline(pp=2, mp=1, steps=2)
        step.sync_to_model()
        opt = step.optimizer
        nonzero = 0
        for n in step._acc_names:
            for pname, val in opt._accumulators[n].items():
                if np.any(np.asarray(val) != 0):
                    nonzero += 1
        assert nonzero > 0

    def test_train_batch_syncs_model(self):
        """PipelineParallel.train_batch keeps the eager model in sync: eval
        after training sees the trained weights."""
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineParallel, PipelineLayer)
        mesh = build_mesh(dp=1, pp=2, sharding=1, sep=1, mp=1,
                          devices=jax.devices()[:2])
        set_global_mesh(mesh)
        paddle.seed(0)
        model = GPTForCausalLM(tiny_cfg())
        crit = GPTPretrainingCriterion()
        pl = PipelineLayer(gpt_pipeline_layers(model), num_stages=2,
                           loss_fn=crit)
        runner = PipelineParallel(pl, hcg=None)
        runner.accumulate_steps = 2
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 128, (4, 16)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 128, (4, 16)), jnp.int32)
        eval0 = float(runner.eval_batch((ids, labels)))
        for _ in range(10):
            runner.train_batch((ids, labels), opt)
        eval1 = float(runner.eval_batch((ids, labels)))
        assert eval1 < eval0  # eager model actually advanced


class TestInterleavedPipeline:
    """Virtual pipeline stages (reference analog:
    PipelineParallelWithInterleave, pipeline_parallel.py:461)."""

    def test_schedule_properties(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            interleaved_schedule)
        M, S, V = 8, 4, 2
        sched, total, bubble = interleaved_schedule(M, S, V)
        assert total == V * M + S - 1
        # V-fold bubble reduction vs GPipe fill/drain
        gpipe_bubble = (S - 1) / (M + S - 1)
        assert bubble < gpipe_bubble
        # every (stage, lap, micro) work item appears exactly once
        items = [it for step in sched for it in step]
        assert len(items) == S * V * M
        assert len(set(items)) == S * V * M
        # steady state keeps all stages busy
        for step in sched[S - 1:V * M]:
            assert len(step) == S

    def test_forward_matches_sequential(self):
        """V=2 x S=2 interleaved == applying the 4 chunks in order."""
        from paddle_tpu.distributed.fleet.meta_parallel import spmd_pipeline
        mesh = build_mesh(dp=1, pp=2, sharding=1, sep=1, mp=1,
                          devices=jax.devices()[:2])
        S, V, M, mb, d = 2, 2, 4, 2, 8
        rng = np.random.default_rng(0)
        # chunk (l, s) applies ws[l, s]; execution order = l*S + s
        ws = jnp.asarray(rng.normal(size=(V, S, d, d)) * 0.1, jnp.float32)
        x = jnp.asarray(rng.normal(size=(M, mb, d)), jnp.float32)

        def stage_fn(params, h):
            return jnp.tanh(h @ params[0])

        y = spmd_pipeline(stage_fn, [ws], x, mesh=mesh, num_virtual=V)
        ref = x
        for c in range(V * S):
            ref = jnp.tanh(ref @ ws[c // S, c % S])
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_grad_matches_sequential(self):
        from paddle_tpu.distributed.fleet.meta_parallel import spmd_pipeline
        mesh = build_mesh(dp=1, pp=2, sharding=1, sep=1, mp=1,
                          devices=jax.devices()[:2])
        S, V, M, mb, d = 2, 2, 4, 2, 8
        rng = np.random.default_rng(1)
        ws = jnp.asarray(rng.normal(size=(V, S, d, d)) * 0.1, jnp.float32)
        x = jnp.asarray(rng.normal(size=(M, mb, d)), jnp.float32)

        def stage_fn(params, h):
            return jnp.tanh(h @ params[0])

        def loss_pipe(w):
            return jnp.sum(spmd_pipeline(stage_fn, [w], x, mesh=mesh,
                                         num_virtual=V) ** 2)

        def loss_seq(w):
            h = x
            for c in range(V * S):
                h = jnp.tanh(h @ w[c // S, c % S])
            return jnp.sum(h ** 2)

        np.testing.assert_allclose(np.asarray(jax.grad(loss_pipe)(ws)),
                                   np.asarray(jax.grad(loss_seq)(ws)),
                                   rtol=2e-5, atol=2e-5)

    def test_training_matches_single_device(self):
        """pp=2 x V=2 over an 8-layer GPT matches single-device training."""
        ref = _train_losses_single(steps=5, lr=1e-2, layers=8)
        set_global_mesh(build_mesh(dp=4, pp=2, sharding=1, sep=1, mp=1,
                                   devices=jax.devices()[:8]))
        paddle.seed(0)
        model = GPTForCausalLM(tiny_cfg(num_hidden_layers=8))
        step = PipelineTrainStep(
            gpt_pipeline_layers(model), GPTPretrainingCriterion(),
            paddle.optimizer.AdamW(learning_rate=1e-2,
                                   parameters=model.parameters()),
            num_microbatches=4, num_virtual=2)
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 128, (8, 16)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 128, (8, 16)), jnp.int32)
        got = [float(step(ids, labels)) for _ in range(5)]
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
        assert got[-1] < got[0]
        step.sync_to_model()
        for p in model.parameters():
            assert np.all(np.isfinite(np.asarray(p._value)))

    def test_indivisible_microbatches_raises(self):
        set_global_mesh(build_mesh(dp=1, pp=2, sharding=1, sep=1, mp=1,
                                   devices=jax.devices()[:2]))
        model = GPTForCausalLM(tiny_cfg(num_hidden_layers=8))
        with pytest.raises(ValueError):
            PipelineTrainStep(
                gpt_pipeline_layers(model), GPTPretrainingCriterion(),
                paddle.optimizer.AdamW(learning_rate=1e-2,
                                       parameters=model.parameters()),
                num_microbatches=3, num_virtual=2)


class TestRaggedInterleaved:
    """Ragged chunk sizes COMPOSED with interleaved virtual stages —
    reference composes SegmentLayers uneven partitions (pp_layers.py:92)
    with PipelineParallelWithInterleave (pipeline_parallel.py:461)."""

    def test_ragged_v2_matches_single_device(self):
        """pp=2 x V=2 with chunk sizes [1,2,2,1] over a 6-layer GPT matches
        single-device training step for step."""
        ref = _train_losses_single(steps=4, lr=1e-2, layers=6)
        set_global_mesh(build_mesh(dp=4, pp=2, sharding=1, sep=1, mp=1,
                                   devices=jax.devices()[:8]))
        paddle.seed(0)
        model = GPTForCausalLM(tiny_cfg(num_hidden_layers=6))
        step = PipelineTrainStep(
            gpt_pipeline_layers(model), GPTPretrainingCriterion(),
            paddle.optimizer.AdamW(learning_rate=1e-2,
                                   parameters=model.parameters()),
            num_microbatches=4, num_virtual=2, stage_sizes=[1, 2, 2, 1])
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 128, (8, 16)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 128, (8, 16)), jnp.int32)
        got = [float(step(ids, labels)) for _ in range(4)]
        assert step._stage_sizes_eff == [1, 2, 2, 1]
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
        assert got[-1] < got[0]

    def test_ragged_v2_sync_to_model_skips_padding(self):
        set_global_mesh(build_mesh(dp=4, pp=2, sharding=1, sep=1, mp=1,
                                   devices=jax.devices()[:8]))
        paddle.seed(0)
        model = GPTForCausalLM(tiny_cfg(num_hidden_layers=6))
        step = PipelineTrainStep(
            gpt_pipeline_layers(model), GPTPretrainingCriterion(),
            paddle.optimizer.AdamW(learning_rate=1e-2,
                                   parameters=model.parameters()),
            num_microbatches=4, num_virtual=2, stage_sizes=[2, 1, 1, 2])
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 128, (8, 16)), jnp.int32)
        float(step(ids, ids))
        step.sync_to_model()
        for p in model.parameters():
            assert np.all(np.isfinite(np.asarray(p._value)))

    def test_ragged_v2_wrong_chunk_count_raises(self):
        set_global_mesh(build_mesh(dp=4, pp=2, sharding=1, sep=1, mp=1,
                                   devices=jax.devices()[:8]))
        model = GPTForCausalLM(tiny_cfg(num_hidden_layers=6))
        step = PipelineTrainStep(
            gpt_pipeline_layers(model), GPTPretrainingCriterion(),
            paddle.optimizer.AdamW(learning_rate=1e-2,
                                   parameters=model.parameters()),
            num_microbatches=4, num_virtual=2, stage_sizes=[3, 3])
        ids = jnp.zeros((8, 16), jnp.int32)
        with pytest.raises(ValueError, match="chunks"):
            float(step(ids, ids))

    def test_pipeline_layer_segments_drive_ragged_interleave(self):
        """A PipelineLayer with num_virtual_pipeline_stages=2 segments into
        S*V chunks; an uneven split flows into the masked interleaved
        pipeline (reference SegmentLayers + interleave composition)."""
        from paddle_tpu.distributed.fleet.meta_parallel.pp_layers import \
            PipelineLayer
        mesh = build_mesh(dp=4, pp=2, sharding=1, sep=1, mp=1,
                          devices=jax.devices()[:8])
        set_global_mesh(mesh)
        paddle.seed(0)
        # 9 pipeline items (emb + 7 blocks + head) over 4 chunks ->
        # uniform segmentation [0,3,5,7,9]: the 7-block run splits ragged
        # [2,2,2,1] across the S*V interleave chunks
        model = GPTForCausalLM(tiny_cfg(num_hidden_layers=7))
        pl = PipelineLayer(gpt_pipeline_layers(model), num_stages=2,
                           num_virtual_pipeline_stages=2)
        assert len(pl.segment_parts) == 5          # S*V + 1 chunks
        step = PipelineTrainStep(
            pl, GPTPretrainingCriterion(),
            paddle.optimizer.AdamW(learning_rate=1e-2,
                                   parameters=model.parameters()),
            mesh=mesh, num_microbatches=4, num_virtual=2)
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 128, (8, 16)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 128, (8, 16)), jnp.int32)
        l0 = float(step(ids, labels))
        l1 = float(step(ids, labels))
        assert step._stage_sizes_eff == [2, 2, 2, 1]
        ref = _train_losses_single(steps=2, layers=7)
        np.testing.assert_allclose([l0, l1], ref, rtol=1e-5, atol=1e-5)

    def test_train_batch_forwards_interleave(self):
        """An interleave-configured PipelineLayer flows its num_virtual into
        the SPMD step, and the single-controller fallback runs ALL S*V
        chunks (head included)."""
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineParallel, PipelineLayer)

        mesh = build_mesh(dp=1, pp=2, sharding=1, sep=1, mp=1,
                          devices=jax.devices()[:2])
        set_global_mesh(mesh)
        paddle.seed(0)
        model = GPTForCausalLM(tiny_cfg(num_hidden_layers=8))
        crit = GPTPretrainingCriterion()
        pl = PipelineLayer(gpt_pipeline_layers(model), num_stages=2,
                           loss_fn=crit, num_virtual_pipeline_stages=2)
        runner = PipelineParallel(pl, hcg=None)
        runner.accumulate_steps = 2      # rounded up to a multiple of S
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 128, (4, 16)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 128, (4, 16)), jnp.int32)
        l1 = float(runner.train_batch((ids, labels), opt))
        assert runner._spmd_step is not None
        assert runner._spmd_step.num_virtual == 2
        # eval_batch exercises the fallback chunk walk: must produce a LOSS
        # (i.e. the head chunk ran), not hidden states
        ev = runner.eval_batch((ids, labels))
        assert np.isfinite(float(ev))
        assert np.isfinite(l1)


class TestPipelinePromotion:
    """PR 16 tentpole (a): train_batch over a pipe>1 mesh routes through
    the ops/spmd_fusion pipeline registry — ONE promoted
    ppermute-handoff program per (mesh, schedule, stage structure,
    optimizer), fired with launch accounting and zero steady-state
    retraces. Interleaved (virtual>1) schedules key into the same
    signature."""

    @pytest.fixture(autouse=True)
    def _events_on(self):
        from paddle_tpu.framework.flags import set_flags, _FLAGS
        from paddle_tpu.profiler.events import clear_fusion_events
        from paddle_tpu.profiler import reset_step_fusion_stats
        from paddle_tpu.ops.spmd_fusion import clear_pipeline_programs
        prev = bool(_FLAGS.get("FLAGS_profiler_events"))
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        clear_pipeline_programs()
        reset_step_fusion_stats()
        yield
        set_flags({"FLAGS_profiler_events": prev})
        clear_pipeline_programs()
        set_global_mesh(None)

    def _runner(self, virtual=2, accum=4, layers=8, seed=0):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            PipelineParallel, PipelineLayer)
        mesh = build_mesh(dp=1, pp=2, sharding=1, sep=1, mp=1,
                          devices=jax.devices()[:2])
        set_global_mesh(mesh)
        paddle.seed(seed)
        model = GPTForCausalLM(tiny_cfg(num_hidden_layers=layers))
        crit = GPTPretrainingCriterion()
        pl = PipelineLayer(gpt_pipeline_layers(model), num_stages=2,
                           loss_fn=crit,
                           num_virtual_pipeline_stages=virtual)
        runner = PipelineParallel(pl, hcg=None)
        runner.accumulate_steps = accum
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        rng = np.random.default_rng(seed)
        ids = jnp.asarray(rng.integers(0, 128, (4, 16)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 128, (4, 16)), jnp.int32)
        return runner, opt, ids, labels

    def _events(self, cat, reason=None):
        from paddle_tpu.profiler.events import fusion_events
        return [e for e in fusion_events()
                if e["cat"] == cat
                and (reason is None or e.get("reason") == reason)]

    def test_pp_interleaved_promotes_fires_zero_steady_retraces(self):
        from paddle_tpu.profiler import step_fusion_stats
        runner, opt, ids, labels = self._runner(virtual=2)
        losses = [float(runner.train_batch((ids, labels), opt))
                  for _ in range(3)]
        s0 = dict(step_fusion_stats())
        promotes = self._events("step.promote")
        assert len(promotes) == 1, promotes
        d = promotes[0]["detail"]
        assert d["pipe"] is True
        # interleaved schedule keys into the signature: (S, V, M)
        assert tuple(d["schedule"]) == (2, 2, 4), d
        assert d["launches_estimate"] > 1
        # every train_batch fired the ONE promoted program
        assert len(self._events("step.fire")) == 3
        assert not self._events("step.split")
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]
        # steady state: more batches, zero fresh retraces
        for _ in range(4):
            runner.train_batch((ids, labels), opt)
        s1 = step_fusion_stats()
        assert s1["retraces"] == s0["retraces"], (s0, s1)
        assert len(self._events("step.fire")) == 7

    def test_schedule_change_is_attributed(self):
        """Rebinding the SAME model+mesh+optimizer to a different
        micro-batch count re-promotes and emits the
        pipe_schedule_mismatch attribution (the REASON_CODES entry the
        doctor hints on)."""
        runner, opt, ids, labels = self._runner(virtual=2)
        runner.train_batch((ids, labels), opt)
        assert len(self._events("step.promote")) == 1
        runner.accumulate_steps = 2          # new M over the same base
        runner.train_batch((ids, labels), opt)
        assert len(self._events("step.promote")) == 2
        mismatches = self._events("step.record", "pipe_schedule_mismatch")
        assert len(mismatches) == 1, mismatches
        det = mismatches[0]["detail"]
        assert tuple(det["prev_schedule"]) == (2, 2, 4)
        assert tuple(det["schedule"]) == (2, 2, 2)

    def test_distinct_models_do_not_alias(self):
        """Two models with identical architecture promote two programs
        (the per-model token in the stage structure): no cross-model
        executable aliasing."""
        r1, o1, ids, labels = self._runner(virtual=2, seed=0)
        r1.train_batch((ids, labels), o1)
        r2, o2, _, _ = self._runner(virtual=2, seed=1)
        r2.train_batch((ids, labels), o2)
        assert len(self._events("step.promote")) == 2
        # same-shape schedules on DIFFERENT models are not a mismatch
        assert not self._events("step.record", "pipe_schedule_mismatch")
