"""Numeric tests for the Pallas fused kernels (interpreter mode on CPU).

The same kernel code runs compiled on TPU; the driver's bench exercises that
path. Here the Pallas interpreter validates block/padding logic and VJPs.
"""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.kernels.cross_entropy import fused_softmax_cross_entropy
from paddle_tpu.kernels.fused_ln import (fused_bias_residual_layer_norm,
                                         _reference)


def test_fused_ce_forward_and_grad():
    rng = np.random.default_rng(0)
    R, V = 70, 3000  # non-multiples: exercises row + vocab padding
    logits = jnp.asarray(rng.standard_normal((R, V)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, R), jnp.int32)

    loss = fused_softmax_cross_entropy(logits, labels, True)
    ref = -jax.nn.log_softmax(logits, -1)[jnp.arange(R), labels]
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    g = jax.grad(lambda lg: fused_softmax_cross_entropy(lg, labels,
                                                        True).sum())(logits)
    gref = jax.grad(lambda lg: (-jax.nn.log_softmax(lg, -1)
                                [jnp.arange(R), labels]).sum())(logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref),
                               rtol=1e-4, atol=1e-5)


def test_fused_ln_forward_and_grad():
    rng = np.random.default_rng(0)
    R, D = 200, 256
    x = jnp.asarray(rng.standard_normal((R, D)), jnp.float32)
    res = jnp.asarray(rng.standard_normal((R, D)), jnp.float32)
    bias = jnp.asarray(rng.standard_normal(D), jnp.float32)
    scale = jnp.asarray(rng.standard_normal(D), jnp.float32)
    shift = jnp.asarray(rng.standard_normal(D), jnp.float32)

    out = fused_bias_residual_layer_norm(x, res, bias, scale, shift, 1e-5,
                                         True)
    ref = _reference(x, res, bias, scale, shift, 1e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    gs = jax.grad(lambda *a: fused_bias_residual_layer_norm(
        *a, 1e-5, True).sum(), argnums=(0, 1, 2, 3, 4))(
        x, res, bias, scale, shift)
    grefs = jax.grad(lambda *a: _reference(*a, 1e-5).sum(),
                     argnums=(0, 1, 2, 3, 4))(x, res, bias, scale, shift)
    for a, b in zip(gs, grefs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_fused_bias_dropout_residual_ln_layer():
    from paddle_tpu.incubate.nn import FusedBiasDropoutResidualLayerNorm
    layer = FusedBiasDropoutResidualLayerNorm(64, dropout_rate=0.0)
    layer.eval()
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((2, 8, 64)).astype(np.float32))
    res = paddle.to_tensor(rng.standard_normal((2, 8, 64)).astype(np.float32))
    out = layer(x, res)
    assert list(out.shape) == [2, 8, 64]
    # dropout_rate=0, bias=0, scale=1, shift=0 -> plain LN of x+res
    import paddle_tpu.nn.functional as F
    ref = F.layer_norm(x + res, [64])
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5, atol=1e-5)


def test_functional_entry_trains():
    from paddle_tpu.incubate.nn import functional as FF
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((4, 32)).astype(np.float32),
                         stop_gradient=False)
    res = paddle.to_tensor(rng.standard_normal((4, 32)).astype(np.float32))
    out = FF.fused_bias_dropout_residual_layer_norm(
        x, res, dropout_rate=0.0, training=False)
    out.sum().backward()
    assert x.grad is not None
    assert np.isfinite(x.grad.numpy()).all()


def _dense_attention_ref(q, k, v, causal, scale):
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s = jnp.einsum("bhnd,bhmd->bhnm", qt, kt) * scale
    if causal:
        n, m = s.shape[-2], s.shape[-1]
        mask = (jnp.arange(n)[:, None] + (m - n)) >= jnp.arange(m)[None, :]
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, -1)
    out = jnp.einsum("bhnm,bhmd->bhnd", p, vt)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def test_flash_attention_forward_interpret():
    """Pallas flash forward (interpreter) matches dense attention."""
    import math
    from paddle_tpu.kernels import flash_attention as fa
    rng = np.random.default_rng(0)
    b, n, h, d = 2, 256, 2, 64
    scale = 1.0 / math.sqrt(d)
    q = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)
    for causal in (True, False):
        out, lse = fa._flash_fwd(q, k, v, causal, scale, block_q=128,
                                 block_k=128, interpret=True)
        ref = _dense_attention_ref(q, k, v, causal, scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_flash_attention_backward_interpret():
    """Pallas flash backward (dQ + dK/dV kernels, interpreter) matches the
    gradients of dense attention."""
    import math
    from paddle_tpu.kernels import flash_attention as fa
    rng = np.random.default_rng(1)
    b, n, h, d = 1, 256, 2, 64
    scale = 1.0 / math.sqrt(d)
    q = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)
    for causal in (True, False):
        out, lse = fa._flash_fwd(q, k, v, causal, scale, block_q=128,
                                 block_k=128, interpret=True)
        dq, dk, dv = fa._flash_bwd(q, k, v, out, lse, g, causal, scale,
                                   block_q=128, block_k=128, interpret=True)
        rq, rk, rv = jax.grad(
            lambda qq, kk, vv: jnp.sum(
                _dense_attention_ref(qq, kk, vv, causal, scale) * g),
            argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(dq), np.asarray(rq),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(dk), np.asarray(rk),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(dv), np.asarray(rv),
                                   rtol=1e-4, atol=1e-4)


def test_flash_attention_mixed_block_sizes_interpret():
    import math
    from paddle_tpu.kernels import flash_attention as fa
    rng = np.random.default_rng(2)
    b, n, h, d = 1, 512, 1, 64
    scale = 1.0 / math.sqrt(d)
    q = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((b, n, h, d)), jnp.float32)
    ref = _dense_attention_ref(q, k, v, True, scale)
    out, lse = fa._flash_fwd(q, k, v, True, scale, block_q=256, block_k=128,
                             interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    dq, dk, dv = fa._flash_bwd(q, k, v, out, lse, g, True, scale,
                               block_q=256, block_k=128, interpret=True)
    rq = jax.grad(lambda qq: jnp.sum(
        _dense_attention_ref(qq, k, v, True, scale) * g))(q)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(rq),
                               rtol=1e-4, atol=1e-4)


def test_flash_auto_blocks_divide_sequence():
    """Auto-picked blocks must divide the sequence (non-dividing blocks would
    silently drop tail rows — regression for seq 1152)."""
    import math
    from paddle_tpu.kernels import flash_attention as fa
    for n in (128, 256, 384, 512, 1024, 1152, 1280, 2048, 4096):
        bq, bk = fa._auto_blocks(n, n)
        assert n % bq == 0 and n % bk == 0 and bq % bk == 0, (n, bq, bk)
    rng = np.random.default_rng(3)
    n, d = 384, 64
    scale = 1.0 / math.sqrt(d)
    q = jnp.asarray(rng.standard_normal((1, n, 1, d)), jnp.float32)
    out, _ = fa._flash_fwd(q, q, q, True, scale, interpret=True)
    ref = _dense_attention_ref(q, q, q, True, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
