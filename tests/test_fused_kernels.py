"""Numeric tests for the Pallas fused kernels (interpreter mode on CPU).

The same kernel code runs compiled on TPU; the driver's bench exercises that
path. Here the Pallas interpreter validates block/padding logic and VJPs.
"""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.kernels.cross_entropy import fused_softmax_cross_entropy
from paddle_tpu.kernels.fused_ln import (fused_bias_residual_layer_norm,
                                         _reference)


def test_fused_ce_forward_and_grad():
    rng = np.random.default_rng(0)
    R, V = 70, 3000  # non-multiples: exercises row + vocab padding
    logits = jnp.asarray(rng.standard_normal((R, V)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, R), jnp.int32)

    loss = fused_softmax_cross_entropy(logits, labels, True)
    ref = -jax.nn.log_softmax(logits, -1)[jnp.arange(R), labels]
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    g = jax.grad(lambda lg: fused_softmax_cross_entropy(lg, labels,
                                                        True).sum())(logits)
    gref = jax.grad(lambda lg: (-jax.nn.log_softmax(lg, -1)
                                [jnp.arange(R), labels]).sum())(logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref),
                               rtol=1e-4, atol=1e-5)


def test_fused_ln_forward_and_grad():
    rng = np.random.default_rng(0)
    R, D = 200, 256
    x = jnp.asarray(rng.standard_normal((R, D)), jnp.float32)
    res = jnp.asarray(rng.standard_normal((R, D)), jnp.float32)
    bias = jnp.asarray(rng.standard_normal(D), jnp.float32)
    scale = jnp.asarray(rng.standard_normal(D), jnp.float32)
    shift = jnp.asarray(rng.standard_normal(D), jnp.float32)

    out = fused_bias_residual_layer_norm(x, res, bias, scale, shift, 1e-5,
                                         True)
    ref = _reference(x, res, bias, scale, shift, 1e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    gs = jax.grad(lambda *a: fused_bias_residual_layer_norm(
        *a, 1e-5, True).sum(), argnums=(0, 1, 2, 3, 4))(
        x, res, bias, scale, shift)
    grefs = jax.grad(lambda *a: _reference(*a, 1e-5).sum(),
                     argnums=(0, 1, 2, 3, 4))(x, res, bias, scale, shift)
    for a, b in zip(gs, grefs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_fused_bias_dropout_residual_ln_layer():
    from paddle_tpu.incubate.nn import FusedBiasDropoutResidualLayerNorm
    layer = FusedBiasDropoutResidualLayerNorm(64, dropout_rate=0.0)
    layer.eval()
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((2, 8, 64)).astype(np.float32))
    res = paddle.to_tensor(rng.standard_normal((2, 8, 64)).astype(np.float32))
    out = layer(x, res)
    assert list(out.shape) == [2, 8, 64]
    # dropout_rate=0, bias=0, scale=1, shift=0 -> plain LN of x+res
    import paddle_tpu.nn.functional as F
    ref = F.layer_norm(x + res, [64])
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5, atol=1e-5)


def test_functional_entry_trains():
    from paddle_tpu.incubate.nn import functional as FF
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((4, 32)).astype(np.float32),
                         stop_gradient=False)
    res = paddle.to_tensor(rng.standard_normal((4, 32)).astype(np.float32))
    out = FF.fused_bias_dropout_residual_layer_norm(
        x, res, dropout_rate=0.0, training=False)
    out.sum().backward()
    assert x.grad is not None
    assert np.isfinite(x.grad.numpy()).all()
