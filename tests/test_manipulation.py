"""Manipulation-op tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_forward, check_grad

RNG = np.random.default_rng(1)


def randf(*shape):
    return RNG.standard_normal(shape).astype(np.float32)


def test_reshape_transpose():
    x = randf(2, 3, 4)
    check_forward(paddle.reshape, lambda a: a.reshape(4, 6), [x],
                  shape=[4, 6])
    check_forward(paddle.transpose, lambda a: a.transpose(2, 0, 1), [x],
                  perm=[2, 0, 1])
    check_grad(paddle.reshape, [randf(2, 6)], shape=[3, 4])


def test_concat_stack_split():
    a, b = randf(2, 3), randf(2, 3)
    out = paddle.concat([paddle.to_tensor(a), paddle.to_tensor(b)], axis=0)
    np.testing.assert_allclose(out.numpy(), np.concatenate([a, b], 0))
    out = paddle.stack([paddle.to_tensor(a), paddle.to_tensor(b)], axis=1)
    np.testing.assert_allclose(out.numpy(), np.stack([a, b], 1))
    parts = paddle.split(paddle.to_tensor(a), [1, 2], axis=1)
    np.testing.assert_allclose(parts[0].numpy(), a[:, :1])
    np.testing.assert_allclose(parts[1].numpy(), a[:, 1:])


def test_concat_grad():
    a, b = randf(2, 2), randf(2, 2)
    ta = paddle.to_tensor(a, stop_gradient=False)
    tb = paddle.to_tensor(b, stop_gradient=False)
    out = paddle.concat([ta, tb], axis=0)
    (out * 2).sum().backward()
    np.testing.assert_allclose(ta.grad.numpy(), np.full((2, 2), 2.0))
    np.testing.assert_allclose(tb.grad.numpy(), np.full((2, 2), 2.0))


def test_squeeze_unsqueeze_flatten():
    x = randf(2, 1, 3)
    assert paddle.squeeze(paddle.to_tensor(x), 1).shape == [2, 3]
    assert paddle.unsqueeze(paddle.to_tensor(x), 0).shape == [1, 2, 1, 3]
    assert paddle.flatten(paddle.to_tensor(randf(2, 3, 4)), 1).shape == [2, 12]


def test_expand_tile():
    x = randf(1, 3)
    assert paddle.expand(paddle.to_tensor(x), [4, 3]).shape == [4, 3]
    np.testing.assert_allclose(
        paddle.tile(paddle.to_tensor(x), [2, 2]).numpy(), np.tile(x, (2, 2)))


def test_gather_scatter():
    x = randf(5, 3)
    idx = np.array([0, 2, 4])
    out = paddle.gather(paddle.to_tensor(x), paddle.to_tensor(idx))
    np.testing.assert_allclose(out.numpy(), x[idx])

    base = np.zeros((5, 3), np.float32)
    upd = randf(2, 3)
    out = paddle.scatter(paddle.to_tensor(base),
                         paddle.to_tensor(np.array([1, 3])),
                         paddle.to_tensor(upd))
    np.testing.assert_allclose(out.numpy()[[1, 3]], upd)


def test_gather_nd_scatter_nd():
    x = randf(3, 4)
    idx = np.array([[0, 1], [2, 3]])
    out = paddle.gather_nd(paddle.to_tensor(x), paddle.to_tensor(idx))
    np.testing.assert_allclose(out.numpy(), x[[0, 2], [1, 3]])
    upd = np.array([5.0, 6.0], np.float32)
    out = paddle.scatter_nd(paddle.to_tensor(idx), paddle.to_tensor(upd),
                            [3, 4])
    assert float(out.numpy()[0, 1]) == 5.0


def test_getitem_setitem():
    x = randf(4, 5)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(t[1].numpy(), x[1])
    np.testing.assert_allclose(t[1:3, 2:].numpy(), x[1:3, 2:])
    np.testing.assert_allclose(t[:, -1].numpy(), x[:, -1])
    t[0] = 0.0
    assert np.all(t.numpy()[0] == 0)
    # boolean mask read
    mask = x > 0
    np.testing.assert_allclose(
        paddle.masked_select(paddle.to_tensor(x),
                             paddle.to_tensor(mask)).numpy(), x[mask])


def test_getitem_grad():
    x = randf(4, 4)
    t = paddle.to_tensor(x, stop_gradient=False)
    t[1:3].sum().backward()
    expected = np.zeros((4, 4), np.float32)
    expected[1:3] = 1
    np.testing.assert_allclose(t.grad.numpy(), expected)


def test_pad():
    x = randf(2, 3)
    out = paddle.to_tensor(x)
    padded = paddle.ops.manipulation.pad(out, [1, 1, 2, 2])
    assert padded.shape == [4, 7]
    x4 = randf(1, 2, 3, 3)
    padded = paddle.ops.manipulation.pad(paddle.to_tensor(x4), [1, 1, 1, 1])
    assert padded.shape == [1, 2, 5, 5]


def test_where_flip_roll():
    x = randf(3, 3)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(paddle.flip(t, [0]).numpy(), x[::-1])
    np.testing.assert_allclose(paddle.roll(t, 1, axis=0).numpy(),
                               np.roll(x, 1, 0))


def test_take_along_put_along():
    x = randf(3, 4)
    idx = np.argsort(x, axis=1)
    out = paddle.take_along_axis(paddle.to_tensor(x),
                                 paddle.to_tensor(idx), 1)
    np.testing.assert_allclose(out.numpy(), np.take_along_axis(x, idx, 1))
    put = paddle.put_along_axis(paddle.to_tensor(np.zeros((2, 2), np.float32)),
                                paddle.to_tensor(np.array([[0], [1]])),
                                paddle.to_tensor(np.array([[5.0], [6.0]],
                                                          np.float32)), 1)
    np.testing.assert_allclose(put.numpy(), [[5, 0], [0, 6]])


def test_cast_astype():
    x = paddle.to_tensor(np.array([1.7, 2.3], np.float32))
    assert x.astype("int32").numpy().dtype == np.int32
    assert paddle.cast(x, "float64").numpy().dtype == np.float64
    assert x.astype(paddle.bfloat16).dtype.name == "bfloat16"


def test_unbind_chunk():
    x = randf(3, 4)
    parts = paddle.unbind(paddle.to_tensor(x), 0)
    assert len(parts) == 3
    np.testing.assert_allclose(parts[1].numpy(), x[1])
    chunks = paddle.chunk(paddle.to_tensor(x), 2, axis=1)
    np.testing.assert_allclose(chunks[0].numpy(), x[:, :2])


def test_repeat_interleave_einsum():
    x = randf(2, 3)
    np.testing.assert_allclose(
        paddle.repeat_interleave(paddle.to_tensor(x), 2, axis=0).numpy(),
        np.repeat(x, 2, 0))
    a, b = randf(3, 4), randf(4, 5)
    np.testing.assert_allclose(
        paddle.einsum("ij,jk->ik", paddle.to_tensor(a),
                      paddle.to_tensor(b)).numpy(), a @ b, atol=1e-4)
