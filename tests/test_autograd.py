"""Autograd engine tests (reference analog: eager backward tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32),
                         stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0, 6.0])


def test_grad_accumulation_two_paths():
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    y = x * 3 + x * x  # dy/dx = 3 + 2x = 7
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [7.0])


def test_repeated_backward_accumulates():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0, 5.0])


def test_stop_gradient():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=True)
    ((x * y).sum()).backward()
    assert x.grad is not None
    assert y.grad is None


def test_detach_cuts_graph():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = (x * 2).detach()
    assert y.stop_gradient
    z = y * 3
    assert z.stop_gradient


def test_no_grad_context():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._grad_node is None


def test_no_grad_decorator():
    @paddle.no_grad()
    def f(t):
        return t * 2
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    assert f(x).stop_gradient


def test_paddle_grad_api():
    x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
    y = x * x
    (g,) = paddle.grad(y, x)
    np.testing.assert_allclose(g.numpy(), [6.0])
    assert x.grad is None  # paddle.grad must not write .grad


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                         stop_gradient=False)
    a, b = paddle.split(x, 2, axis=0)
    loss = (a * 2).sum() + (b * 3).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               [[2, 2, 2], [3, 3, 3]])


def test_backward_with_grad_tensor():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = x * 2
    y.backward(paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32)))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0, 6.0])


def test_register_hook_on_leaf():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    seen = {}

    def hook(g):
        seen["g"] = g.numpy().copy()
        return g * 10

    x.register_hook(hook)
    (x * 2).sum().backward()
    np.testing.assert_allclose(seen["g"], [2.0, 2.0])
    np.testing.assert_allclose(x.grad.numpy(), [20.0, 20.0])


def test_hook_remove():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    h = x.register_hook(lambda g: g * 100)
    h.remove()
    (x * 2).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_clear_grad():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    (x * 2).sum().backward()
    x.clear_grad()
    assert x.grad is None


def test_diamond_dependency():
    x = paddle.to_tensor(np.array([1.0], np.float32), stop_gradient=False)
    a = x * 2
    b = a * 3
    c = a * 4
    (b + c).backward()  # d/dx = 2*3 + 2*4 = 14
    np.testing.assert_allclose(x.grad.numpy(), [14.0])


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            return grad * 2

    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0, 2.0])


def test_jacobian_vjp_jvp():
    from paddle_tpu.autograd import jacobian, vjp, jvp
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    jac = jacobian(lambda t: t * t, x)
    np.testing.assert_allclose(jac.numpy(), np.diag([2.0, 4.0]), atol=1e-5)
    out, g = vjp(lambda t: (t * t).sum(), x)
    np.testing.assert_allclose(g.numpy(), [2.0, 4.0], atol=1e-5)
    out, tangent = jvp(lambda t: (t * t).sum(), x)
    np.testing.assert_allclose(float(tangent), 6.0, atol=1e-5)


# ---- double grad (create_graph=True) ---------------------------------------
# Reference analog: eager/general_grad.h + test_imperative_double_grad.py;
# implementation here is functional replay (framework/autograd.py replay_pure).

def test_double_grad_tanh():
    """d2/dx2 tanh(x).sum() == -2 tanh(x) (1 - tanh(x)^2)."""
    xv = np.array([0.3, -0.7, 1.2], np.float32)
    x = paddle.to_tensor(xv, stop_gradient=False)
    y = paddle.tanh(x).sum()
    (g1,) = paddle.grad(y, x, create_graph=True)
    assert not g1.stop_gradient
    (g2,) = paddle.grad(g1.sum(), x)
    t = np.tanh(xv)
    np.testing.assert_allclose(g2.numpy(), -2 * t * (1 - t * t),
                               rtol=1e-5, atol=1e-6)


def test_double_grad_matmul_matches_finite_diff():
    """d2/dW2 of sum((x@W)^3) via grad-of-grad vs central finite differences."""
    rng = np.random.default_rng(0)
    xv = rng.normal(size=(3, 4)).astype(np.float32)
    wv = rng.normal(size=(4, 2)).astype(np.float32)
    x = paddle.to_tensor(xv)
    w = paddle.to_tensor(wv, stop_gradient=False)
    y = (x.matmul(w) ** 3).sum()
    (g1,) = paddle.grad(y, w, create_graph=True)
    (g2,) = paddle.grad(g1.sum(), w)

    def first_grad(wnp):
        import jax.numpy as jnp
        import jax
        return np.asarray(jax.grad(
            lambda W: jnp.sum((xv @ W) ** 3))(jnp.asarray(wnp)))

    eps = 1e-3
    fd = np.zeros_like(wv)
    for i in range(wv.shape[0]):
        for j in range(wv.shape[1]):
            dp = wv.copy(); dp[i, j] += eps
            dm = wv.copy(); dm[i, j] -= eps
            fd[i, j] = (first_grad(dp).sum() - first_grad(dm).sum()) \
                / (2 * eps)
    np.testing.assert_allclose(g2.numpy(), fd, rtol=2e-2, atol=2e-2)


def test_double_grad_softmax():
    import jax
    import jax.numpy as jnp
    rng = np.random.default_rng(1)
    xv = rng.normal(size=(5,)).astype(np.float32)
    x = paddle.to_tensor(xv, stop_gradient=False)
    y = (paddle.nn.functional.softmax(x) ** 2).sum()
    (g1,) = paddle.grad(y, x, create_graph=True)
    (g2,) = paddle.grad((g1 ** 2).sum(), x)

    def f(v):
        return jnp.sum(jax.nn.softmax(v) ** 2)

    ref = jax.grad(lambda v: jnp.sum(jax.grad(f)(v) ** 2))(jnp.asarray(xv))
    np.testing.assert_allclose(g2.numpy(), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_gradient_penalty_pattern():
    """WGAN-GP style: ||d out/d x||^2 as a loss term, backward to params."""
    paddle.seed(0)
    lin = paddle.nn.Linear(4, 1)
    x = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32),
                         stop_gradient=False)
    out = lin(x).sum()
    (gx,) = paddle.grad(out, x, create_graph=True)
    gp = (gx ** 2).sum()
    gp.backward()
    wgrad = lin.weight.grad
    assert wgrad is not None
    # d gp / d W = 2 * N * W (gx = W broadcast over batch of 8 rows)
    np.testing.assert_allclose(wgrad.numpy(),
                               16 * lin.weight.numpy(), rtol=1e-4)


def test_triple_grad():
    """Third order: d3/dx3 of x^4 = 24 x."""
    x = paddle.to_tensor(np.array([1.5], np.float32), stop_gradient=False)
    y = (x ** 4).sum()
    (g1,) = paddle.grad(y, x, create_graph=True)
    (g2,) = paddle.grad(g1.sum(), x, create_graph=True)
    (g3,) = paddle.grad(g2.sum(), x)
    np.testing.assert_allclose(g3.numpy(), [24 * 1.5], rtol=1e-5)


def test_double_grad_unused_input():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    z = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = (x * x).sum()
    with pytest.raises(RuntimeError):
        paddle.grad(y, [z], create_graph=True)
    g = paddle.grad(y, [x, z], create_graph=True, allow_unused=True)
    assert g[1] is None
    np.testing.assert_allclose(g[0].numpy(), 2 * np.ones(3), rtol=1e-6)


def test_forward_grad_incubate():
    """incubate.autograd.forward_grad: JVP over the recorded graph."""
    from paddle_tpu.incubate.autograd import forward_grad
    xv = np.array([0.5, 1.0], np.float32)
    x = paddle.to_tensor(xv, stop_gradient=False)
    y = (x * x).sum()
    t = forward_grad(y, x)
    np.testing.assert_allclose(float(t), float((2 * xv).sum()), rtol=1e-6)
    # and the tangent is differentiable further
    (g,) = paddle.grad(t, x)
    np.testing.assert_allclose(g.numpy(), [2.0, 2.0], rtol=1e-6)
