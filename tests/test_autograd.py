"""Autograd engine tests (reference analog: eager backward tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32),
                         stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0, 6.0])


def test_grad_accumulation_two_paths():
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    y = x * 3 + x * x  # dy/dx = 3 + 2x = 7
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [7.0])


def test_repeated_backward_accumulates():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0, 5.0])


def test_stop_gradient():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=True)
    ((x * y).sum()).backward()
    assert x.grad is not None
    assert y.grad is None


def test_detach_cuts_graph():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = (x * 2).detach()
    assert y.stop_gradient
    z = y * 3
    assert z.stop_gradient


def test_no_grad_context():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._grad_node is None


def test_no_grad_decorator():
    @paddle.no_grad()
    def f(t):
        return t * 2
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    assert f(x).stop_gradient


def test_paddle_grad_api():
    x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
    y = x * x
    (g,) = paddle.grad(y, x)
    np.testing.assert_allclose(g.numpy(), [6.0])
    assert x.grad is None  # paddle.grad must not write .grad


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                         stop_gradient=False)
    a, b = paddle.split(x, 2, axis=0)
    loss = (a * 2).sum() + (b * 3).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               [[2, 2, 2], [3, 3, 3]])


def test_backward_with_grad_tensor():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = x * 2
    y.backward(paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32)))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0, 6.0])


def test_register_hook_on_leaf():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    seen = {}

    def hook(g):
        seen["g"] = g.numpy().copy()
        return g * 10

    x.register_hook(hook)
    (x * 2).sum().backward()
    np.testing.assert_allclose(seen["g"], [2.0, 2.0])
    np.testing.assert_allclose(x.grad.numpy(), [20.0, 20.0])


def test_hook_remove():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    h = x.register_hook(lambda g: g * 100)
    h.remove()
    (x * 2).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_clear_grad():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    (x * 2).sum().backward()
    x.clear_grad()
    assert x.grad is None


def test_diamond_dependency():
    x = paddle.to_tensor(np.array([1.0], np.float32), stop_gradient=False)
    a = x * 2
    b = a * 3
    c = a * 4
    (b + c).backward()  # d/dx = 2*3 + 2*4 = 14
    np.testing.assert_allclose(x.grad.numpy(), [14.0])


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            return grad * 2

    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0, 2.0])


def test_jacobian_vjp_jvp():
    from paddle_tpu.autograd import jacobian, vjp, jvp
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    jac = jacobian(lambda t: t * t, x)
    np.testing.assert_allclose(jac.numpy(), np.diag([2.0, 4.0]), atol=1e-5)
    out, g = vjp(lambda t: (t * t).sum(), x)
    np.testing.assert_allclose(g.numpy(), [2.0, 4.0], atol=1e-5)
    out, tangent = jvp(lambda t: (t * t).sum(), x)
    np.testing.assert_allclose(float(tangent), 6.0, atol=1e-5)
