"""Round-3 nn surface completion: unpool, grid ops, new losses, beam
search decode, sparse ops.

Reference analogs: python/paddle/nn/functional/{vision,loss,extension}.py,
python/paddle/fluid/layers/rnn.py, python/paddle/sparse/.
"""
import itertools

import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.sparse as sparse


class TestUnpool:
    def test_max_unpool2d_roundtrip_matches_torch(self):
        x = np.random.RandomState(0).randn(2, 3, 8, 8).astype("float32")
        out, mask = F.max_pool2d(paddle.to_tensor(x), 2, 2, return_mask=True)
        up = F.max_unpool2d(out, mask, 2, 2).numpy()
        tout, tmask = torch.nn.functional.max_pool2d(
            torch.tensor(x), 2, 2, return_indices=True)
        tup = torch.nn.functional.max_unpool2d(tout, tmask, 2, 2).numpy()
        np.testing.assert_allclose(up, tup)

    @pytest.mark.parametrize("nd", [1, 3])
    def test_max_unpool_1d_3d(self, nd):
        rng = np.random.RandomState(1)
        if nd == 1:
            x = rng.randn(2, 3, 10).astype("float32")
            o, m = F.max_pool1d(paddle.to_tensor(x), 2, 2, return_mask=True)
            up = F.max_unpool1d(o, m, 2, 2).numpy()
            to, tm = torch.nn.functional.max_pool1d(
                torch.tensor(x), 2, 2, return_indices=True)
            ref = torch.nn.functional.max_unpool1d(to, tm, 2, 2).numpy()
        else:
            x = rng.randn(2, 2, 4, 4, 4).astype("float32")
            o, m = F.max_pool3d(paddle.to_tensor(x), 2, 2, return_mask=True)
            up = F.max_unpool3d(o, m, 2, 2).numpy()
            to, tm = torch.nn.functional.max_pool3d(
                torch.tensor(x), 2, 2, return_indices=True)
            ref = torch.nn.functional.max_unpool3d(to, tm, 2, 2).numpy()
        np.testing.assert_allclose(up, ref)

    def test_unpool_layers(self):
        x = np.random.RandomState(2).randn(1, 2, 6).astype("float32")
        o, m = F.max_pool1d(paddle.to_tensor(x), 2, 2, return_mask=True)
        up = nn.MaxUnPool1D(2, 2)(o, m)
        assert up.shape == [1, 2, 6]
        x3 = np.random.RandomState(3).randn(1, 2, 4, 4, 4).astype("float32")
        o3, m3 = F.max_pool3d(paddle.to_tensor(x3), 2, 2, return_mask=True)
        assert nn.MaxUnPool3D(2, 2)(o3, m3).shape == [1, 2, 4, 4, 4]


class TestGridOps:
    @pytest.mark.parametrize("align", [True, False])
    def test_affine_grid(self, align):
        th = np.random.RandomState(0).randn(2, 2, 3).astype("float32")
        got = F.affine_grid(paddle.to_tensor(th), [2, 3, 5, 7],
                            align_corners=align).numpy()
        ref = torch.nn.functional.affine_grid(
            torch.tensor(th), [2, 3, 5, 7], align_corners=align).numpy()
        np.testing.assert_allclose(got, ref, atol=1e-5)

    @pytest.mark.parametrize(
        "mode,pad,align",
        list(itertools.product(["bilinear", "nearest"],
                               ["zeros", "border", "reflection"],
                               [True, False])))
    def test_grid_sample_4d(self, mode, pad, align):
        rng = np.random.RandomState(1)
        x = rng.randn(2, 3, 6, 7).astype("float32")
        g = (rng.rand(2, 5, 4, 2).astype("float32") * 2.4 - 1.2)
        got = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(g), mode,
                            pad, align).numpy()
        ref = torch.nn.functional.grid_sample(
            torch.tensor(x), torch.tensor(g), mode, pad, align).numpy()
        np.testing.assert_allclose(got, ref, atol=1e-4)

    def test_grid_sample_5d(self):
        rng = np.random.RandomState(2)
        x = rng.randn(2, 2, 4, 5, 6).astype("float32")
        g = (rng.rand(2, 3, 3, 3, 3).astype("float32") * 2 - 1)
        got = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(g)).numpy()
        ref = torch.nn.functional.grid_sample(
            torch.tensor(x), torch.tensor(g), align_corners=True).numpy()
        np.testing.assert_allclose(got, ref, atol=1e-4)

    def test_temporal_shift_kernel_semantics(self):
        x = np.arange(4 * 8, dtype="float32").reshape(4, 8, 1, 1)
        out = F.temporal_shift(paddle.to_tensor(x), seg_num=2,
                               shift_ratio=0.25).numpy()
        v5 = x.reshape(2, 2, 8, 1, 1)
        ref = np.zeros_like(v5)
        for t in range(2):
            ref[:, t, :2] = v5[:, t - 1, :2] if t >= 1 else 0
            ref[:, t, 2:4] = v5[:, t + 1, 2:4] if t + 1 < 2 else 0
            ref[:, t, 4:] = v5[:, t, 4:]
        np.testing.assert_allclose(out, ref.reshape(4, 8, 1, 1))

    def test_zeropad2d(self):
        x = np.random.RandomState(3).randn(1, 2, 3, 4).astype("float32")
        got = F.zeropad2d(paddle.to_tensor(x), [1, 2, 3, 4]).numpy()
        ref = torch.nn.functional.pad(torch.tensor(x), (1, 2, 3, 4)).numpy()
        np.testing.assert_allclose(got, ref)

    def test_diag_embed(self):
        x = np.random.RandomState(4).randn(2, 3, 4).astype("float32")
        for off, d1, d2 in [(0, -2, -1), (1, -2, -1), (-2, -2, -1), (0, 0, 2)]:
            got = F.diag_embed(paddle.to_tensor(x), off, d1, d2).numpy()
            ref = torch.diag_embed(torch.tensor(x), off, d1, d2).numpy()
            np.testing.assert_allclose(got, ref)


class TestNewLosses:
    @pytest.mark.parametrize("red", ["mean", "sum", "none"])
    def test_soft_margin(self, red):
        rng = np.random.RandomState(0)
        x = rng.randn(6, 5).astype("float32")
        y = np.sign(rng.randn(6, 5)).astype("float32")
        got = F.soft_margin_loss(paddle.to_tensor(x), paddle.to_tensor(y),
                                 red).numpy()
        ref = torch.nn.functional.soft_margin_loss(
            torch.tensor(x), torch.tensor(y), reduction=red).numpy()
        np.testing.assert_allclose(got, ref, atol=1e-5)

    @pytest.mark.parametrize("red", ["mean", "sum", "none"])
    def test_multi_label_soft_margin(self, red):
        rng = np.random.RandomState(1)
        x = rng.randn(6, 5).astype("float32")
        y = (rng.rand(6, 5) > 0.5).astype("float32")
        w = rng.rand(5).astype("float32")
        got = F.multi_label_soft_margin_loss(
            paddle.to_tensor(x), paddle.to_tensor(y), paddle.to_tensor(w),
            red).numpy()
        ref = torch.nn.functional.multilabel_soft_margin_loss(
            torch.tensor(x), torch.tensor(y), torch.tensor(w),
            reduction=red).numpy()
        np.testing.assert_allclose(got, ref, atol=1e-5)

    @pytest.mark.parametrize("p,red", [(1, "mean"), (2, "sum"), (1, "none")])
    def test_multi_margin(self, p, red):
        rng = np.random.RandomState(2)
        x = rng.randn(6, 5).astype("float32")
        y = rng.randint(0, 5, (6,)).astype("int64")
        got = F.multi_margin_loss(paddle.to_tensor(x), paddle.to_tensor(y),
                                  p=p, margin=0.7, reduction=red).numpy()
        ref = torch.nn.functional.multi_margin_loss(
            torch.tensor(x), torch.tensor(y), p=p, margin=0.7,
            reduction=red).numpy()
        np.testing.assert_allclose(got, ref, atol=1e-5)

    @pytest.mark.parametrize("swap", [False, True])
    def test_triplet_with_distance(self, swap):
        rng = np.random.RandomState(3)
        a, b, c = [rng.randn(4, 8).astype("float32") for _ in range(3)]
        df = lambda u, v: paddle.sqrt(
            paddle.sum(paddle.square(paddle.subtract(u, v)), axis=-1))
        tdf = lambda u, v: torch.sqrt(((u - v) ** 2).sum(-1))
        got = F.triplet_margin_with_distance_loss(
            paddle.to_tensor(a), paddle.to_tensor(b), paddle.to_tensor(c),
            distance_function=df, margin=0.8, swap=swap).numpy()
        ref = torch.nn.functional.triplet_margin_with_distance_loss(
            torch.tensor(a), torch.tensor(b), torch.tensor(c),
            distance_function=tdf, margin=0.8, swap=swap).numpy()
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_margin_cross_entropy_zero_margin_is_scaled_ce(self):
        rng = np.random.RandomState(4)
        cos = np.clip(rng.randn(6, 10) * 0.3, -1, 1).astype("float32")
        y = rng.randint(0, 10, (6,)).astype("int64")
        got = F.margin_cross_entropy(
            paddle.to_tensor(cos), paddle.to_tensor(y), margin1=1.0,
            margin2=0.0, margin3=0.0, scale=4.0).numpy()
        ref = torch.nn.functional.cross_entropy(
            torch.tensor(cos * 4.0), torch.tensor(y)).numpy()
        np.testing.assert_allclose(got, ref, atol=1e-4)
        # adding the additive-angle margin must increase the loss
        with_margin = F.margin_cross_entropy(
            paddle.to_tensor(cos), paddle.to_tensor(y), scale=4.0).numpy()
        assert with_margin > got

    def test_margin_ce_return_softmax(self):
        rng = np.random.RandomState(5)
        cos = np.clip(rng.randn(4, 6) * 0.3, -1, 1).astype("float32")
        y = rng.randint(0, 6, (4,)).astype("int64")
        loss, sm = F.margin_cross_entropy(
            paddle.to_tensor(cos), paddle.to_tensor(y), return_softmax=True)
        np.testing.assert_allclose(sm.numpy().sum(-1), 1.0, atol=1e-5)

    def test_hsigmoid_loss_trains(self):
        rng = np.random.RandomState(6)
        inp = paddle.to_tensor(rng.randn(4, 8).astype("float32"))
        inp.stop_gradient = False
        w = paddle.to_tensor(rng.randn(9, 8).astype("float32"))
        lbl = paddle.to_tensor(rng.randint(0, 10, (4,)).astype("int64"))
        loss = F.hsigmoid_loss(inp, lbl, 10, w)
        # reference returns the per-sample cost [N, 1] (no reduction)
        assert loss.shape == [4, 1]
        paddle.sum(loss).backward()
        assert inp.grad is not None and np.isfinite(loss.numpy()).all()

    def test_hsigmoid_layer(self):
        layer = nn.HSigmoidLoss(8, 10)
        rng = np.random.RandomState(7)
        loss = layer(paddle.to_tensor(rng.randn(4, 8).astype("float32")),
                     paddle.to_tensor(rng.randint(0, 10, (4,)).astype("int64")))
        assert loss.shape == [4, 1] and np.isfinite(loss.numpy()).all()

    def test_loss_layer_classes(self):
        rng = np.random.RandomState(8)
        x = rng.randn(5, 4).astype("float32")
        yl = (rng.rand(5, 4) > 0.5).astype("float32")
        yi = rng.randint(0, 4, (5,)).astype("int64")
        assert np.isfinite(float(nn.MultiLabelSoftMarginLoss()(
            paddle.to_tensor(x), paddle.to_tensor(yl)).numpy()))
        assert np.isfinite(float(nn.MultiMarginLoss()(
            paddle.to_tensor(x), paddle.to_tensor(yi)).numpy()))
        a, b, c = [paddle.to_tensor(rng.randn(3, 6).astype("float32"))
                   for _ in range(3)]
        assert np.isfinite(float(
            nn.TripletMarginWithDistanceLoss()(a, b, c).numpy()))


class TestSequenceOps:
    def test_sequence_mask(self):
        m = F.sequence_mask(paddle.to_tensor(np.array([1, 3, 2])),
                            maxlen=4).numpy()
        np.testing.assert_array_equal(
            m, [[1, 0, 0, 0], [1, 1, 1, 0], [1, 1, 0, 0]])
        m2 = F.sequence_mask(paddle.to_tensor(np.array([[1, 2], [3, 0]])),
                             dtype="bool").numpy()
        assert m2.shape == (2, 2, 3) and m2.dtype == np.bool_

    def test_gather_tree_backtrace(self):
        ids = np.array([[[2, 2]], [[6, 1]], [[7, 8]]], dtype="int64")
        par = np.array([[[0, 0]], [[1, 0]], [[0, 1]]], dtype="int64")
        got = F.gather_tree(paddle.to_tensor(ids),
                            paddle.to_tensor(par)).numpy()
        expect = np.zeros_like(ids)
        for b in range(2):
            beam = b
            for t in range(2, -1, -1):
                expect[t, 0, b] = ids[t, 0, beam]
                beam = par[t, 0, beam]
        np.testing.assert_array_equal(got, expect)

    def test_beam_search_decode(self):
        paddle.seed(0)
        V, H, B = 6, 8, 2
        emb = nn.Embedding(V, H)
        cell = nn.GRUCell(H, H)
        proj = nn.Linear(H, V)
        dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=1,
                                   beam_size=3, embedding_fn=emb,
                                   output_fn=proj)
        ids, final = nn.dynamic_decode(dec, inits=paddle.zeros([B, H]),
                                       max_step_num=5)
        assert ids.shape[0] == B and ids.shape[2] == 3
        scores = final.log_probs.numpy()
        assert (np.diff(scores, axis=1) <= 1e-5).all()  # beams sorted

    def test_sparse_attention_full_pattern_equals_dense(self):
        rng = np.random.RandomState(0)
        B, H, M, D = 1, 2, 4, 8
        q, k, v = [rng.randn(B, H, M, D).astype("float32") for _ in range(3)]
        off = np.tile(np.arange(0, (M + 1) * M, M), (B, H, 1)).astype("int32")
        cols = np.tile(np.tile(np.arange(M), M), (B, H, 1)).astype("int32")
        got = F.sparse_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            paddle.to_tensor(off), paddle.to_tensor(cols)).numpy()
        s = np.einsum("bhmd,bhnd->bhmn", q, k) / np.sqrt(D)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("bhmn,bhnd->bhmd", p, v)
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_inplace_activations(self):
        x = paddle.to_tensor(np.random.randn(3, 4).astype("float32"))
        xv = x.numpy()
        F.tanh_(x)
        np.testing.assert_allclose(x.numpy(), np.tanh(xv), atol=1e-6)
        y = paddle.to_tensor(np.random.randn(3, 4).astype("float32"))
        F.softmax_(y)
        np.testing.assert_allclose(y.numpy().sum(-1), 1.0, atol=1e-5)


class TestSparseModule:
    def _coo(self):
        idx = np.array([[0, 0, 1, 2], [1, 1, 0, 2]])
        vals = np.array([1., 2., 3., 4.], dtype="float32")
        return sparse.coalesce(
            sparse.sparse_coo_tensor(idx, vals, [3, 3]))

    def test_coalesce(self):
        c = self._coo()
        ref = np.zeros((3, 3), "float32")
        ref[0, 1] = 3; ref[1, 0] = 3; ref[2, 2] = 4
        np.testing.assert_allclose(c.to_dense().numpy(), ref)
        assert c.nnz() == 3

    def test_unary_keeps_pattern(self):
        c = self._coo()
        dense = c.to_dense().numpy()
        np.testing.assert_allclose(sparse.sin(c).to_dense().numpy(),
                                   np.sin(dense), atol=1e-6)
        np.testing.assert_allclose(sparse.neg(c).to_dense().numpy(), -dense)
        np.testing.assert_allclose(sparse.pow(c, 2).to_dense().numpy(),
                                   dense ** 2, atol=1e-5)

    def test_mv_addmm(self):
        c = self._coo()
        dense = c.to_dense().numpy()
        v = np.array([1., 2., 3.], dtype="float32")
        np.testing.assert_allclose(
            sparse.mv(c, paddle.to_tensor(v)).numpy(), dense @ v)
        eye = paddle.to_tensor(np.eye(3, dtype="float32"))
        ones = paddle.to_tensor(np.ones((3, 3), "float32"))
        got = sparse.addmm(ones, c, eye, beta=0.5, alpha=2.0).numpy()
        np.testing.assert_allclose(got, 0.5 + 2.0 * dense)

    def test_masked_matmul_coo_csr(self):
        c = self._coo()
        dense = c.to_dense().numpy()
        rng = np.random.RandomState(0)
        A = rng.randn(3, 4).astype("float32")
        B = rng.randn(4, 3).astype("float32")
        full = A @ B
        expect = np.where(dense != 0, full, 0.0)
        got = sparse.masked_matmul(paddle.to_tensor(A), paddle.to_tensor(B),
                                   c).to_dense().numpy()
        np.testing.assert_allclose(got, expect, atol=1e-5)
        csr = sparse.sparse_csr_tensor(
            np.array([0, 1, 2, 3]), np.array([1, 0, 2]),
            np.array([3., 3., 4.], dtype="float32"), [3, 3])
        got2 = sparse.masked_matmul(paddle.to_tensor(A), paddle.to_tensor(B),
                                    csr).to_dense().numpy()
        np.testing.assert_allclose(got2, expect, atol=1e-5)

    def test_reshape_transpose(self):
        c = self._coo()
        dense = c.to_dense().numpy()
        np.testing.assert_allclose(
            sparse.reshape(c, [9]).to_dense().numpy(), dense.reshape(9))
        np.testing.assert_allclose(
            sparse.transpose(c, [1, 0]).to_dense().numpy(), dense.T)

    def test_cast(self):
        c = self._coo()
        cz = sparse.cast(c, value_dtype="float64")
        assert str(cz.values.dtype).endswith("float64")
