"""Whole-step eager fusion: the auto-TrainStep layer (ops/step_fusion.py).

Covers cycle promotion + fused replay parity against the unfused eager
path over SGD / Momentum / Adam (including grad clipping, weight decay,
and an LR schedule), split-on-escape correctness (mid-step peeks fall back
BITWISE-identically — they replay through the same per-op executables),
invalidation (param `stop_gradient` flips, registry-generation bumps,
clip-attr mutation, clear_dispatch_cache), flag interactions
(FLAGS_eager_op_cache_size=0 must leave step fusion inert), zero
post-warmup retraces, the FusedStepNode tape marking, and the acceptance
micro-benchmark: ≥1.3x over PR 2's chain fusion on the matmul→add→gelu
fwd+bwd+SGD loop.

Parity note: a fused whole-step replay compiles forward + backward +
optimizer update into ONE XLA program. XLA's layout and fusion decisions
inside a single program differ from the multi-executable eager path at the
last-ULP level — exactly as `jit.TrainStep` differs from eager — so
fused-vs-unfused TRAJECTORIES are compared with tight allclose bounds
(observed deviations are ~1e-7 relative per step). Every transactional
FALLBACK (split) replays through the identical per-op executables and is
asserted bitwise.
"""
import time

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.autograd import FusedStepNode
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops.dispatch import clear_dispatch_cache
from paddle_tpu.ops.step_fusion import step_cache_info
from paddle_tpu.ops.registry import get_op, override_kernel
from paddle_tpu.profiler import (chain_fusion_stats, dispatch_cache_stats,
                                 reset_chain_fusion_stats,
                                 reset_dispatch_cache_stats,
                                 reset_step_fusion_stats, step_fusion_stats)

_DEFAULT_FLAGS = {
    "FLAGS_eager_op_cache": True,
    "FLAGS_eager_op_cache_size": 512,
    "FLAGS_eager_chain_fusion": True,
    "FLAGS_eager_chain_fusion_min_count": 3,
    "FLAGS_eager_chain_cache_size": 128,
    "FLAGS_eager_chain_stitching": True,
    "FLAGS_eager_step_fusion": True,
    "FLAGS_eager_step_fusion_min_count": 4,
    "FLAGS_eager_step_fusion_cache_size": 8,
    "FLAGS_eager_step_fusion_donate_params": False,
}


@pytest.fixture(autouse=True)
def _fresh():
    set_flags(dict(_DEFAULT_FLAGS))
    clear_dispatch_cache()
    reset_dispatch_cache_stats()
    reset_chain_fusion_stats()
    reset_step_fusion_stats()
    yield
    set_flags(dict(_DEFAULT_FLAGS))
    clear_dispatch_cache()
    reset_dispatch_cache_stats()
    reset_chain_fusion_stats()
    reset_step_fusion_stats()


def _params(seed=7, b=8, d=16):
    rng = np.random.default_rng(seed)
    x = paddle.to_tensor(rng.standard_normal((b, d)).astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((d, d)).astype(np.float32),
                         stop_gradient=False)
    bias = paddle.to_tensor(rng.standard_normal(d).astype(np.float32),
                            stop_gradient=False)
    return x, w, bias


def _make_opt(kind, params):
    if kind == "sgd":
        return paddle.optimizer.SGD(learning_rate=0.05, parameters=params)
    if kind == "momentum":
        return paddle.optimizer.Momentum(
            learning_rate=0.05, momentum=0.9, parameters=params,
            grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    if kind == "adam":
        sched = paddle.optimizer.lr.StepDecay(
            learning_rate=0.01, step_size=5, gamma=0.5)
        return paddle.optimizer.Adam(
            learning_rate=sched, parameters=params, weight_decay=0.01,
            grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    raise AssertionError(kind)


def _cycle(x, w, b, opt, sched=None):
    y = F.gelu(paddle.add(paddle.matmul(x, w), b))
    loss = y.sum()
    loss.backward()
    opt.step()
    opt.clear_grad()
    if sched is not None:
        sched.step()
    # reading the loss AFTER the step must be served from the fused outputs
    return float(loss.numpy())


def _run(kind, fused, n=30):
    set_flags({"FLAGS_eager_step_fusion": fused})
    clear_dispatch_cache()
    x, w, b = _params()
    opt = _make_opt(kind, [w, b])
    sched = opt._learning_rate \
        if not isinstance(opt._learning_rate, float) else None
    losses = [_cycle(x, w, b, opt, sched) for _ in range(n)]
    return np.asarray(losses), w.numpy().copy(), b.numpy().copy()


class TestParity:
    @pytest.mark.parametrize("kind", ["sgd", "momentum", "adam"])
    def test_trajectory_parity(self, kind):
        """Fused whole-step replays track the unfused eager trajectory
        (incl. grad clip, weight decay, LR schedule) within single-program
        compilation noise, and actually fuse."""
        unfused, w0, b0 = _run(kind, False)
        fused, w1, b1 = _run(kind, True)
        s = step_fusion_stats()
        assert s["steps_promoted"] >= 1
        assert s["fused_steps"] >= 20, s
        assert s["fallback_splits"] == 0, s
        np.testing.assert_allclose(fused, unfused, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(w1, w0, rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(b1, b0, rtol=1e-4, atol=1e-6)

    def test_lr_schedule_never_splits(self):
        """The LR value is hoisted to a scalar argument: a schedule that
        changes it every step must not break replay."""
        x, w, b = _params()
        sched = paddle.optimizer.lr.ExponentialDecay(
            learning_rate=0.05, gamma=0.9)
        opt = paddle.optimizer.SGD(learning_rate=sched, parameters=[w, b])
        for _ in range(20):
            _cycle(x, w, b, opt, sched)
        s = step_fusion_stats()
        assert s["fused_steps"] >= 10
        assert s["fallback_splits"] == 0

    def test_fused_root_is_fused_step_node(self):
        """After a fused replay the loss carries a FusedStepNode: it is not
        a leaf, and a second backward raises the consumed-graph error."""
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        loss = None
        for _ in range(10):
            y = F.gelu(paddle.add(paddle.matmul(x, w), b))
            loss = y.sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert step_fusion_stats()["fused_steps"] > 0
        assert isinstance(loss._grad_node, FusedStepNode)
        assert not loss.is_leaf
        with pytest.raises(RuntimeError, match="fused whole-step"):
            loss.backward()


class TestSplits:
    def test_mid_step_peek_splits_bitwise(self):
        """A loss.numpy() between backward and opt.step is a mid-step peek:
        every cycle splits, nothing ever fuses, and the whole trajectory is
        BITWISE identical to the unfused path (the fallback replays through
        the same per-op executables)."""
        def run(fused):
            set_flags({"FLAGS_eager_step_fusion": fused})
            clear_dispatch_cache()
            x, w, b = _params()
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=[w, b])
            out = []
            for _ in range(12):
                y = F.gelu(paddle.add(paddle.matmul(x, w), b))
                loss = y.sum()
                loss.backward()
                peek = loss.numpy().copy()     # mid-step peek
                opt.step()
                opt.clear_grad()
                out.append((peek, w.numpy().copy(), b.numpy().copy()))
            return out

        unfused = run(False)
        fused = run(True)
        s = step_fusion_stats()
        assert s["fused_steps"] == 0
        assert s["fallback_splits"] > 0 and s["escapes"] > 0
        for u, f in zip(unfused, fused):
            for i, (uv, fv) in enumerate(zip(u, f)):
                np.testing.assert_array_equal(uv, fv, err_msg=f"field {i}")

    def test_grad_read_pre_step_splits_and_serves_real_grads(self):
        """Reading p.grad between backward and step forces the pending
        grad placeholder: the replay splits and the grads are the real
        (bitwise) per-op backward results."""
        def run(fused):
            set_flags({"FLAGS_eager_step_fusion": fused})
            clear_dispatch_cache()
            x, w, b = _params()
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=[w, b])
            grads = []
            for _ in range(10):
                y = F.gelu(paddle.add(paddle.matmul(x, w), b))
                loss = y.sum()
                loss.backward()
                grads.append(w.grad.numpy().copy())
                opt.step()
                opt.clear_grad()
            return grads

        unfused = run(False)
        fused = run(True)
        assert step_fusion_stats()["fallback_splits"] > 0
        for u, f in zip(unfused, fused):
            np.testing.assert_array_equal(u, f)

    def test_persistent_splits_deactivate(self):
        """A cycle that always peeks stops being attempted: the program is
        deactivated after its fail streak."""
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        for _ in range(20):
            y = F.gelu(paddle.add(paddle.matmul(x, w), b))
            loss = y.sum()
            loss.backward()
            _ = loss.numpy()
            opt.step()
            opt.clear_grad()
        s = step_fusion_stats()
        assert s["deactivated"] >= 1
        assert s["fallback_splits"] <= 8, \
            "splits kept accruing after deactivation"

    def test_post_fire_intermediate_read_recomputes(self):
        """Reading a mid-step intermediate AFTER the fused step fired is
        served by a lazy per-op recompute from the captured inputs."""
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        h = None
        for _ in range(10):
            h = paddle.add(paddle.matmul(x, w), b)
            y = F.gelu(h)
            loss = y.sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert step_fusion_stats()["fused_steps"] > 0
        val = h.numpy()                  # post-fire lazy recompute
        assert val.shape == (8, 16)
        assert np.isfinite(val).all()

    def test_fired_step_releases_preupdate_buffers(self):
        """ROADMAP item 4(c): a fired step must NOT retain the pre-update
        parameter values or the batch buffers into the next step. The
        ext-val store demotes to weakrefs at the fire, so when the loop
        keeps no mid-step intermediates, everything the replay captured
        is refcount-freed before optimizer.step() returns — proven with
        the cycle collector disabled."""
        import gc
        import weakref
        rng = np.random.default_rng(3)
        w = paddle.to_tensor(rng.standard_normal((16, 16))
                             .astype(np.float32), stop_gradient=False)
        b = paddle.to_tensor(rng.standard_normal(16).astype(np.float32),
                             stop_gradient=False)
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        gc.disable()
        try:
            w_ref = x_ref = None
            for _ in range(10):
                xb = paddle.to_tensor(
                    rng.standard_normal((8, 16)).astype(np.float32))
                loss = F.gelu(paddle.add(paddle.matmul(xb, w), b)).sum()
                loss.backward()
                pre_w = weakref.ref(w._value)     # about to be replaced
                pre_x = weakref.ref(xb._value)    # the batch buffer
                opt.step()
                opt.clear_grad()
                if step_fusion_stats()["fused_steps"] > 0:
                    w_ref, x_ref = pre_w, pre_x
                    del xb                        # dataloader rebinding
                    break
            assert w_ref is not None, "loop never promoted"
            assert w_ref() is None, \
                "fused step retained the pre-update params past the " \
                "step boundary"
            assert x_ref() is None, \
                "fused step retained the batch buffer past the step " \
                "boundary"
        finally:
            gc.enable()


class TestInvalidation:
    def test_param_stop_gradient_flip_splits(self):
        """Flipping a param to stop_gradient re-keys its ops (diff mask):
        the promoted program stops matching on the very next cycle."""
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        for _ in range(8):
            _cycle(x, w, b, opt)
        assert step_fusion_stats()["fused_steps"] > 0
        before = step_fusion_stats()
        b.stop_gradient = True
        y = F.gelu(paddle.add(paddle.matmul(x, w), b))
        loss = y.sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        after = step_fusion_stats()
        assert after["fused_steps"] == before["fused_steps"]
        assert after["fallback_splits"] > before["fallback_splits"]
        assert w.grad is None and b.grad is None    # step+clear ran eagerly

    def test_registry_bump_invalidates(self):
        """A kernel override takes effect on the very next cycle — the
        bumped generation re-keys the op, the replay splits, and the
        override's numerics are served immediately."""
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        for _ in range(8):
            _cycle(x, w, b, opt)
        base = _cycle(x, w, b, opt)
        before = step_fusion_stats()
        override_kernel(
            "gelu", "tripled",
            lambda v: jnp.asarray(0.5 * v * (1.0 + jnp.tanh(v)),
                                  v.dtype) * 3.0,
            activate=True)
        try:
            changed = _cycle(x, w, b, opt)
            after = step_fusion_stats()
            assert after["fused_steps"] == before["fused_steps"]
            assert after["fallback_splits"] > before["fallback_splits"]
            assert changed != base
        finally:
            get_op("gelu").active = None

    def test_clip_attr_mutation_kills_program(self):
        """Clip attributes are baked into the traced step: mutating them
        deactivates the stale executable instead of serving it."""
        x, w, b = _params()
        clip = paddle.nn.ClipGradByGlobalNorm(1.0)
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b],
                                   grad_clip=clip)
        for _ in range(8):
            _cycle(x, w, b, opt)
        assert step_fusion_stats()["fused_steps"] > 0
        before = step_fusion_stats()
        clip.clip_norm = 0.01
        _cycle(x, w, b, opt)
        after = step_fusion_stats()
        assert after["fused_steps"] == before["fused_steps"]
        assert after["deactivated"] > before["deactivated"]

    def test_clear_dispatch_cache_drops_programs(self):
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        for _ in range(8):
            _cycle(x, w, b, opt)
        assert step_cache_info()["library"] >= 1
        clear_dispatch_cache()
        assert step_cache_info()["library"] == 0
        assert step_cache_info()["active"] is None


class TestFlags:
    def test_disabled_never_promotes(self):
        set_flags({"FLAGS_eager_step_fusion": False})
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        for _ in range(12):
            _cycle(x, w, b, opt)
        s = step_fusion_stats()
        assert s["steps_promoted"] == 0 and s["fused_steps"] == 0

    def test_op_cache_size_zero_leaves_step_fusion_inert(self):
        """FLAGS_eager_op_cache_size=0 disables the per-op cache, so cycle
        ops cannot be keyed: step fusion must observe nothing, promote
        nothing, and numerics must equal the cached unfused path bitwise."""
        def run(cache_size):
            set_flags({"FLAGS_eager_op_cache_size": cache_size,
                       "FLAGS_eager_step_fusion": cache_size == 0,
                       "FLAGS_eager_chain_fusion": False})
            clear_dispatch_cache()
            x, w, b = _params()
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=[w, b])
            return [_cycle(x, w, b, opt) for _ in range(10)], w.numpy()

        base, w0 = run(512)             # cached, no step fusion
        reset_step_fusion_stats()
        uncached, w1 = run(0)           # uncached, step fusion flag ON
        s = step_fusion_stats()
        assert s["steps_promoted"] == 0 and s["fused_steps"] == 0
        np.testing.assert_array_equal(np.asarray(base), np.asarray(uncached))
        np.testing.assert_array_equal(w0, w1)


class TestLayerInterplay:
    def test_chain_fusion_replays_while_step_fusion_observes(self):
        """Step fusion in observation mode (threshold not reached) must not
        interfere with the chain layer: chains keep replaying and nothing
        escape-splits — the step manager's pre-forcing must never touch
        this thread's own in-flight chain pending."""
        set_flags({"FLAGS_eager_step_fusion_min_count": 1000})
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[w, b])
        for _ in range(20):
            _cycle(x, w, b, opt)
        c = chain_fusion_stats()
        assert c["fused_replays"] >= 10, c
        assert c["escapes"] == 0, c
        assert step_fusion_stats()["steps_promoted"] == 0


class TestZeroRetrace:
    @pytest.mark.perf_smoke
    def test_zero_retraces_after_warmup(self):
        """After promotion, 30 more cycles run with zero new traces
        anywhere — per-op, chain, or step executables — and every cycle is
        one fused replay."""
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        for _ in range(10):
            _cycle(x, w, b, opt)
        d0, c0, s0 = (dispatch_cache_stats(), chain_fusion_stats(),
                      step_fusion_stats())
        assert s0["fused_steps"] > 0, "fusion never engaged during warmup"
        for _ in range(30):
            _cycle(x, w, b, opt)
        d1, c1, s1 = (dispatch_cache_stats(), chain_fusion_stats(),
                      step_fusion_stats())
        assert d1["retraces"] == d0["retraces"], "per-op retrace"
        assert c1["retraces"] == c0["retraces"], "chain retrace"
        assert s1["retraces"] == s0["retraces"], "step retrace"
        assert s1["fused_steps"] - s0["fused_steps"] == 30
        assert s1["fallback_splits"] == s0["fallback_splits"]


class TestMicroBenchmark:
    @pytest.mark.perf_smoke
    def test_fused_step_beats_chain_fusion(self):
        """The acceptance micro-benchmark: the whole-step executable beats
        PR 2's chain-fusion path by ≥1.3x wall time on the repeated
        matmul→add→gelu fwd+bwd+SGD loop (CPU). Best-of-3 timing per mode,
        up to 4 attempts, to keep shared-CI noise out of the signal."""
        def bench(step_fused, iters=100):
            set_flags({"FLAGS_eager_step_fusion": step_fused,
                       "FLAGS_eager_step_fusion_min_count": 6})
            clear_dispatch_cache()
            rng = np.random.default_rng(3)
            x = paddle.to_tensor(
                rng.standard_normal((32, 64)).astype(np.float32))
            w = paddle.to_tensor(
                rng.standard_normal((64, 64)).astype(np.float32),
                stop_gradient=False)
            b = paddle.to_tensor(
                rng.standard_normal(64).astype(np.float32),
                stop_gradient=False)
            opt = paddle.optimizer.SGD(learning_rate=1e-3,
                                       parameters=[w, b])
            def step():
                y = F.gelu(paddle.add(paddle.matmul(x, w), b))
                loss = y.sum()
                loss.backward()
                opt.step()
                opt.clear_grad()
            for _ in range(16):
                step()
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(iters):
                    step()
                best = min(best, (time.perf_counter() - t0) / iters)
            return best

        ratios = []
        for _ in range(4):      # retries absorb shared-CI load spikes
            t_chain = bench(False)
            t_step = bench(True)
            ratios.append(t_chain / t_step)
            if ratios[-1] >= 1.3:
                break
        assert max(ratios) >= 1.3, \
            f"fused step below 1.3x: {[round(r, 2) for r in ratios]}"
        assert step_fusion_stats()["fused_steps"] > 0
