"""Whole-step eager fusion: the auto-TrainStep layer (ops/step_fusion.py).

Covers cycle promotion + fused replay parity against the unfused eager
path over SGD / Momentum / Adam (including grad clipping, weight decay,
and an LR schedule), split-on-escape correctness (mid-step peeks fall back
BITWISE-identically — they replay through the same per-op executables),
invalidation (param `stop_gradient` flips, registry-generation bumps,
clip-attr mutation, clear_dispatch_cache), flag interactions
(FLAGS_eager_op_cache_size=0 must leave step fusion inert), zero
post-warmup retraces, the FusedStepNode tape marking, and the acceptance
micro-benchmark: ≥1.3x over PR 2's chain fusion on the matmul→add→gelu
fwd+bwd+SGD loop.

Parity note: a fused whole-step replay compiles forward + backward +
optimizer update into ONE XLA program. XLA's layout and fusion decisions
inside a single program differ from the multi-executable eager path at the
last-ULP level — exactly as `jit.TrainStep` differs from eager — so
fused-vs-unfused TRAJECTORIES are compared with tight allclose bounds
(observed deviations are ~1e-7 relative per step). Every transactional
FALLBACK (split) replays through the identical per-op executables and is
asserted bitwise.
"""
import time

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.autograd import FusedStepNode
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops.dispatch import clear_dispatch_cache
from paddle_tpu.ops.step_fusion import step_cache_info
from paddle_tpu.ops.registry import get_op, override_kernel
from paddle_tpu.profiler import (chain_fusion_stats, dispatch_cache_stats,
                                 reset_chain_fusion_stats,
                                 reset_dispatch_cache_stats,
                                 reset_step_fusion_stats, step_fusion_stats)

_DEFAULT_FLAGS = {
    "FLAGS_eager_op_cache": True,
    "FLAGS_eager_op_cache_size": 512,
    "FLAGS_eager_chain_fusion": True,
    "FLAGS_eager_chain_fusion_min_count": 3,
    "FLAGS_eager_chain_cache_size": 128,
    "FLAGS_eager_chain_stitching": True,
    "FLAGS_eager_step_fusion": True,
    "FLAGS_eager_step_fusion_min_count": 4,
    "FLAGS_eager_step_fusion_cache_size": 8,
    "FLAGS_eager_step_fusion_donate_params": False,
}


@pytest.fixture(autouse=True)
def _fresh():
    set_flags(dict(_DEFAULT_FLAGS))
    clear_dispatch_cache()
    reset_dispatch_cache_stats()
    reset_chain_fusion_stats()
    reset_step_fusion_stats()
    yield
    set_flags(dict(_DEFAULT_FLAGS))
    clear_dispatch_cache()
    reset_dispatch_cache_stats()
    reset_chain_fusion_stats()
    reset_step_fusion_stats()


def _params(seed=7, b=8, d=16):
    rng = np.random.default_rng(seed)
    x = paddle.to_tensor(rng.standard_normal((b, d)).astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((d, d)).astype(np.float32),
                         stop_gradient=False)
    bias = paddle.to_tensor(rng.standard_normal(d).astype(np.float32),
                            stop_gradient=False)
    return x, w, bias


def _make_opt(kind, params):
    if kind == "sgd":
        return paddle.optimizer.SGD(learning_rate=0.05, parameters=params)
    if kind == "momentum":
        return paddle.optimizer.Momentum(
            learning_rate=0.05, momentum=0.9, parameters=params,
            grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    if kind == "adam":
        sched = paddle.optimizer.lr.StepDecay(
            learning_rate=0.01, step_size=5, gamma=0.5)
        return paddle.optimizer.Adam(
            learning_rate=sched, parameters=params, weight_decay=0.01,
            grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    raise AssertionError(kind)


def _cycle(x, w, b, opt, sched=None):
    y = F.gelu(paddle.add(paddle.matmul(x, w), b))
    loss = y.sum()
    loss.backward()
    opt.step()
    opt.clear_grad()
    if sched is not None:
        sched.step()
    # reading the loss AFTER the step must be served from the fused outputs
    return float(loss.numpy())


def _run(kind, fused, n=30):
    set_flags({"FLAGS_eager_step_fusion": fused})
    clear_dispatch_cache()
    x, w, b = _params()
    opt = _make_opt(kind, [w, b])
    sched = opt._learning_rate \
        if not isinstance(opt._learning_rate, float) else None
    losses = [_cycle(x, w, b, opt, sched) for _ in range(n)]
    return np.asarray(losses), w.numpy().copy(), b.numpy().copy()


class TestParity:
    @pytest.mark.parametrize("kind", ["sgd", "momentum", "adam"])
    def test_trajectory_parity(self, kind):
        """Fused whole-step replays track the unfused eager trajectory
        (incl. grad clip, weight decay, LR schedule) within single-program
        compilation noise, and actually fuse."""
        unfused, w0, b0 = _run(kind, False)
        fused, w1, b1 = _run(kind, True)
        s = step_fusion_stats()
        assert s["steps_promoted"] >= 1
        assert s["fused_steps"] >= 20, s
        assert s["fallback_splits"] == 0, s
        np.testing.assert_allclose(fused, unfused, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(w1, w0, rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(b1, b0, rtol=1e-4, atol=1e-6)

    def test_lr_schedule_never_splits(self):
        """The LR value is hoisted to a scalar argument: a schedule that
        changes it every step must not break replay."""
        x, w, b = _params()
        sched = paddle.optimizer.lr.ExponentialDecay(
            learning_rate=0.05, gamma=0.9)
        opt = paddle.optimizer.SGD(learning_rate=sched, parameters=[w, b])
        for _ in range(20):
            _cycle(x, w, b, opt, sched)
        s = step_fusion_stats()
        assert s["fused_steps"] >= 10
        assert s["fallback_splits"] == 0

    def test_fused_root_is_fused_step_node(self):
        """After a fused replay the loss carries a FusedStepNode: it is not
        a leaf, and a second backward raises the consumed-graph error."""
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        loss = None
        for _ in range(10):
            y = F.gelu(paddle.add(paddle.matmul(x, w), b))
            loss = y.sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert step_fusion_stats()["fused_steps"] > 0
        assert isinstance(loss._grad_node, FusedStepNode)
        assert not loss.is_leaf
        with pytest.raises(RuntimeError, match="fused whole-step"):
            loss.backward()


class TestSplits:
    def test_mid_step_peek_splits_bitwise(self):
        """A loss.numpy() between backward and opt.step is a mid-step peek:
        every cycle splits, nothing ever fuses, and the whole trajectory is
        BITWISE identical to the unfused path (the fallback replays through
        the same per-op executables)."""
        def run(fused):
            set_flags({"FLAGS_eager_step_fusion": fused})
            clear_dispatch_cache()
            x, w, b = _params()
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=[w, b])
            out = []
            for _ in range(12):
                y = F.gelu(paddle.add(paddle.matmul(x, w), b))
                loss = y.sum()
                loss.backward()
                peek = loss.numpy().copy()     # mid-step peek
                opt.step()
                opt.clear_grad()
                out.append((peek, w.numpy().copy(), b.numpy().copy()))
            return out

        unfused = run(False)
        fused = run(True)
        s = step_fusion_stats()
        assert s["fused_steps"] == 0
        assert s["fallback_splits"] > 0 and s["escapes"] > 0
        for u, f in zip(unfused, fused):
            for i, (uv, fv) in enumerate(zip(u, f)):
                np.testing.assert_array_equal(uv, fv, err_msg=f"field {i}")

    def test_grad_read_pre_step_splits_and_serves_real_grads(self):
        """Reading p.grad between backward and step forces the pending
        grad placeholder: the replay splits and the grads are the real
        (bitwise) per-op backward results."""
        def run(fused):
            set_flags({"FLAGS_eager_step_fusion": fused})
            clear_dispatch_cache()
            x, w, b = _params()
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=[w, b])
            grads = []
            for _ in range(10):
                y = F.gelu(paddle.add(paddle.matmul(x, w), b))
                loss = y.sum()
                loss.backward()
                grads.append(w.grad.numpy().copy())
                opt.step()
                opt.clear_grad()
            return grads

        unfused = run(False)
        fused = run(True)
        assert step_fusion_stats()["fallback_splits"] > 0
        for u, f in zip(unfused, fused):
            np.testing.assert_array_equal(u, f)

    def test_persistent_splits_deactivate(self):
        """A cycle that always peeks stops being attempted: the program is
        deactivated after its fail streak."""
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        for _ in range(20):
            y = F.gelu(paddle.add(paddle.matmul(x, w), b))
            loss = y.sum()
            loss.backward()
            _ = loss.numpy()
            opt.step()
            opt.clear_grad()
        s = step_fusion_stats()
        assert s["deactivated"] >= 1
        assert s["fallback_splits"] <= 8, \
            "splits kept accruing after deactivation"

    def test_post_fire_intermediate_read_recomputes(self):
        """Reading a mid-step intermediate AFTER the fused step fired is
        served by a lazy per-op recompute from the captured inputs."""
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        h = None
        for _ in range(10):
            h = paddle.add(paddle.matmul(x, w), b)
            y = F.gelu(h)
            loss = y.sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert step_fusion_stats()["fused_steps"] > 0
        val = h.numpy()                  # post-fire lazy recompute
        assert val.shape == (8, 16)
        assert np.isfinite(val).all()

    def test_fired_step_releases_preupdate_buffers(self):
        """ROADMAP item 4(c): a fired step must NOT retain the pre-update
        parameter values or the batch buffers into the next step. The
        ext-val store demotes to weakrefs at the fire, so when the loop
        keeps no mid-step intermediates, everything the replay captured
        is refcount-freed before optimizer.step() returns — proven with
        the cycle collector disabled."""
        import gc
        import weakref
        rng = np.random.default_rng(3)
        w = paddle.to_tensor(rng.standard_normal((16, 16))
                             .astype(np.float32), stop_gradient=False)
        b = paddle.to_tensor(rng.standard_normal(16).astype(np.float32),
                             stop_gradient=False)
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        gc.disable()
        try:
            w_ref = x_ref = None
            for _ in range(10):
                xb = paddle.to_tensor(
                    rng.standard_normal((8, 16)).astype(np.float32))
                loss = F.gelu(paddle.add(paddle.matmul(xb, w), b)).sum()
                loss.backward()
                pre_w = weakref.ref(w._value)     # about to be replaced
                pre_x = weakref.ref(xb._value)    # the batch buffer
                opt.step()
                opt.clear_grad()
                if step_fusion_stats()["fused_steps"] > 0:
                    w_ref, x_ref = pre_w, pre_x
                    del xb                        # dataloader rebinding
                    break
            assert w_ref is not None, "loop never promoted"
            assert w_ref() is None, \
                "fused step retained the pre-update params past the " \
                "step boundary"
            assert x_ref() is None, \
                "fused step retained the batch buffer past the step " \
                "boundary"
        finally:
            gc.enable()


class TestInvalidation:
    def test_param_stop_gradient_flip_splits(self):
        """Flipping a param to stop_gradient re-keys its ops (diff mask):
        the promoted program stops matching on the very next cycle."""
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        for _ in range(8):
            _cycle(x, w, b, opt)
        assert step_fusion_stats()["fused_steps"] > 0
        before = step_fusion_stats()
        b.stop_gradient = True
        y = F.gelu(paddle.add(paddle.matmul(x, w), b))
        loss = y.sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        after = step_fusion_stats()
        assert after["fused_steps"] == before["fused_steps"]
        assert after["fallback_splits"] > before["fallback_splits"]
        assert w.grad is None and b.grad is None    # step+clear ran eagerly

    def test_registry_bump_invalidates(self):
        """A kernel override takes effect on the very next cycle — the
        bumped generation re-keys the op, the replay splits, and the
        override's numerics are served immediately."""
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        for _ in range(8):
            _cycle(x, w, b, opt)
        base = _cycle(x, w, b, opt)
        before = step_fusion_stats()
        override_kernel(
            "gelu", "tripled",
            lambda v: jnp.asarray(0.5 * v * (1.0 + jnp.tanh(v)),
                                  v.dtype) * 3.0,
            activate=True)
        try:
            changed = _cycle(x, w, b, opt)
            after = step_fusion_stats()
            assert after["fused_steps"] == before["fused_steps"]
            assert after["fallback_splits"] > before["fallback_splits"]
            assert changed != base
        finally:
            get_op("gelu").active = None

    def test_clip_attr_mutation_kills_program(self):
        """Clip attributes are baked into the traced step: mutating them
        deactivates the stale executable instead of serving it."""
        x, w, b = _params()
        clip = paddle.nn.ClipGradByGlobalNorm(1.0)
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b],
                                   grad_clip=clip)
        for _ in range(8):
            _cycle(x, w, b, opt)
        assert step_fusion_stats()["fused_steps"] > 0
        before = step_fusion_stats()
        clip.clip_norm = 0.01
        _cycle(x, w, b, opt)
        after = step_fusion_stats()
        assert after["fused_steps"] == before["fused_steps"]
        assert after["deactivated"] > before["deactivated"]

    def test_clear_dispatch_cache_drops_programs(self):
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        for _ in range(8):
            _cycle(x, w, b, opt)
        assert step_cache_info()["library"] >= 1
        clear_dispatch_cache()
        assert step_cache_info()["library"] == 0
        assert step_cache_info()["active"] is None


class TestFlags:
    def test_disabled_never_promotes(self):
        set_flags({"FLAGS_eager_step_fusion": False})
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        for _ in range(12):
            _cycle(x, w, b, opt)
        s = step_fusion_stats()
        assert s["steps_promoted"] == 0 and s["fused_steps"] == 0

    def test_op_cache_size_zero_leaves_step_fusion_inert(self):
        """FLAGS_eager_op_cache_size=0 disables the per-op cache, so cycle
        ops cannot be keyed: step fusion must observe nothing, promote
        nothing, and numerics must equal the cached unfused path bitwise."""
        def run(cache_size):
            set_flags({"FLAGS_eager_op_cache_size": cache_size,
                       "FLAGS_eager_step_fusion": cache_size == 0,
                       "FLAGS_eager_chain_fusion": False})
            clear_dispatch_cache()
            x, w, b = _params()
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=[w, b])
            return [_cycle(x, w, b, opt) for _ in range(10)], w.numpy()

        base, w0 = run(512)             # cached, no step fusion
        reset_step_fusion_stats()
        uncached, w1 = run(0)           # uncached, step fusion flag ON
        s = step_fusion_stats()
        assert s["steps_promoted"] == 0 and s["fused_steps"] == 0
        np.testing.assert_array_equal(np.asarray(base), np.asarray(uncached))
        np.testing.assert_array_equal(w0, w1)


class TestLayerInterplay:
    def test_chain_fusion_replays_while_step_fusion_observes(self):
        """Step fusion in observation mode (threshold not reached) must not
        interfere with the chain layer: chains keep replaying and nothing
        escape-splits — the step manager's pre-forcing must never touch
        this thread's own in-flight chain pending."""
        set_flags({"FLAGS_eager_step_fusion_min_count": 1000})
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[w, b])
        for _ in range(20):
            _cycle(x, w, b, opt)
        c = chain_fusion_stats()
        assert c["fused_replays"] >= 10, c
        assert c["escapes"] == 0, c
        assert step_fusion_stats()["steps_promoted"] == 0


class TestZeroRetrace:
    @pytest.mark.perf_smoke
    def test_zero_retraces_after_warmup(self):
        """After promotion, 30 more cycles run with zero new traces
        anywhere — per-op, chain, or step executables — and every cycle is
        one fused replay."""
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        for _ in range(10):
            _cycle(x, w, b, opt)
        d0, c0, s0 = (dispatch_cache_stats(), chain_fusion_stats(),
                      step_fusion_stats())
        assert s0["fused_steps"] > 0, "fusion never engaged during warmup"
        for _ in range(30):
            _cycle(x, w, b, opt)
        d1, c1, s1 = (dispatch_cache_stats(), chain_fusion_stats(),
                      step_fusion_stats())
        assert d1["retraces"] == d0["retraces"], "per-op retrace"
        assert c1["retraces"] == c0["retraces"], "chain retrace"
        assert s1["retraces"] == s0["retraces"], "step retrace"
        assert s1["fused_steps"] - s0["fused_steps"] == 30
        assert s1["fallback_splits"] == s0["fallback_splits"]


class TestMicroBenchmark:
    @pytest.mark.perf_smoke
    def test_fused_step_beats_chain_fusion(self):
        """The acceptance micro-benchmark: the whole-step executable beats
        PR 2's chain-fusion path by ≥1.3x wall time on the repeated
        matmul→add→gelu fwd+bwd+SGD loop (CPU). Best-of-3 timing per mode,
        up to 4 attempts, to keep shared-CI noise out of the signal."""
        def bench(step_fused, iters=100):
            set_flags({"FLAGS_eager_step_fusion": step_fused,
                       "FLAGS_eager_step_fusion_min_count": 6})
            clear_dispatch_cache()
            rng = np.random.default_rng(3)
            x = paddle.to_tensor(
                rng.standard_normal((32, 64)).astype(np.float32))
            w = paddle.to_tensor(
                rng.standard_normal((64, 64)).astype(np.float32),
                stop_gradient=False)
            b = paddle.to_tensor(
                rng.standard_normal(64).astype(np.float32),
                stop_gradient=False)
            opt = paddle.optimizer.SGD(learning_rate=1e-3,
                                       parameters=[w, b])
            def step():
                y = F.gelu(paddle.add(paddle.matmul(x, w), b))
                loss = y.sum()
                loss.backward()
                opt.step()
                opt.clear_grad()
            for _ in range(16):
                step()
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(iters):
                    step()
                best = min(best, (time.perf_counter() - t0) / iters)
            return best

        ratios = []
        for _ in range(4):      # retries absorb shared-CI load spikes
            t_chain = bench(False)
            t_step = bench(True)
            ratios.append(t_chain / t_step)
            if ratios[-1] >= 1.3:
                break
        assert max(ratios) >= 1.3, \
            f"fused step below 1.3x: {[round(r, 2) for r in ratios]}"
        assert step_fusion_stats()["fused_steps"] > 0


def _dropout_cycle(x, w, b, opt, p=0.3):
    y = F.dropout(F.gelu(paddle.add(paddle.matmul(x, w), b)), p)
    loss = y.sum()
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss.numpy())


class TestRNGHoisting:
    """Universal promotion part (a): dropout>0 loops promote to ONE fused
    executable — the PRNG key/epoch rides as hoisted device scalars and
    every key derives in-graph, bit-identical to the eager stream."""

    def test_dropout_promotes_with_parity(self):
        """The dropout loop fuses, with fused-vs-eager trajectory parity
        given the SAME seed (the key stream is bitwise shared; remaining
        deltas are single-program layout noise)."""
        def run(fused):
            set_flags({"FLAGS_eager_step_fusion": fused})
            clear_dispatch_cache()
            paddle.seed(11)
            x, w, b = _params()
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=[w, b])
            return np.asarray([_dropout_cycle(x, w, b, opt)
                               for _ in range(25)]), w.numpy().copy()

        unfused, w0 = run(False)
        fused, w1 = run(True)
        s = step_fusion_stats()
        assert s["steps_promoted"] >= 1
        assert s["fused_steps"] >= 15, s
        assert s["fallback_splits"] == 0, s
        np.testing.assert_allclose(fused, unfused, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(w1, w0, rtol=1e-4, atol=1e-6)

    def test_dropout_zero_steady_state_retraces(self):
        paddle.seed(3)
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[w, b])
        retraces_at = []
        for _ in range(20):
            _dropout_cycle(x, w, b, opt)
            retraces_at.append(step_fusion_stats()["retraces"])
        assert step_fusion_stats()["fused_steps"] >= 10
        assert retraces_at[-1] == retraces_at[7], retraces_at

    def test_dropout_split_is_bitwise(self):
        """A mid-step peek in a dropout loop splits BITWISE: the lazy key
        tensors materialize the exact stream keys the fused program would
        have derived, so the per-op fallback samples identically."""
        def run(fused):
            set_flags({"FLAGS_eager_step_fusion": fused})
            clear_dispatch_cache()
            paddle.seed(5)
            x, w, b = _params()
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=[w, b])
            out = []
            for _ in range(12):
                y = F.dropout(F.gelu(paddle.add(paddle.matmul(x, w), b)),
                              0.4)
                loss = y.sum()
                loss.backward()
                peek = loss.numpy().copy()     # mid-step peek → split
                opt.step()
                opt.clear_grad()
                out.append((peek, w.numpy().copy()))
            return out

        unfused = run(False)
        fused = run(True)
        assert step_fusion_stats()["fused_steps"] == 0
        assert step_fusion_stats()["fallback_splits"] > 0
        for u, f in zip(unfused, fused):
            np.testing.assert_array_equal(u[0], f[0])
            np.testing.assert_array_equal(u[1], f[1])

    def test_mid_cycle_stateful_consumption_splits(self):
        """An EXTRA stateful key drawn between the cycle's dropouts
        shifts the recorded stream deltas: the replay must split
        (rng_rekey), never silently sample from the wrong position."""
        from paddle_tpu.framework.random import get_rng_key
        paddle.seed(7)
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[w, b])
        for _ in range(10):
            _dropout_cycle(x, w, b, opt)
        assert step_fusion_stats()["fused_steps"] >= 4
        fired_before = step_fusion_stats()["fused_steps"]
        y = F.dropout(paddle.matmul(x, w), 0.3)
        get_rng_key()                       # interloper consumption
        y2 = F.dropout(F.gelu(paddle.add(paddle.matmul(x, w), b)), 0.3)
        loss = y2.sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        # the shifted stream cannot have produced a fused fire for this
        # cycle's recorded positions
        s = step_fusion_stats()
        assert s["fused_steps"] == fired_before \
            or s["fallback_splits"] > 0

    def test_checkpoint_resumes_stream_exactly(self):
        """EpochRange-style snapshot/restore mid-promoted-dropout-loop:
        the restored run reproduces the uninterrupted loss trajectory
        EXACTLY — the hoisted stream is (base key, position), both
        checkpointed."""
        from paddle_tpu.framework import random as frandom

        paddle.seed(21)
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w, b])
        for _ in range(10):                 # promote and run fused
            _dropout_cycle(x, w, b, opt)
        assert step_fusion_stats()["fused_steps"] >= 4
        rng_snap = frandom.rng_checkpoint_state()
        w_snap, b_snap = w.numpy().copy(), b.numpy().copy()
        tail_a = [_dropout_cycle(x, w, b, opt) for _ in range(6)]
        # "restore": wind state back and replay — same stream, same losses
        frandom.set_rng_checkpoint_state(rng_snap)
        w._value = jnp.asarray(w_snap)
        b._value = jnp.asarray(b_snap)
        tail_b = [_dropout_cycle(x, w, b, opt) for _ in range(6)]
        np.testing.assert_allclose(tail_a, tail_b, rtol=1e-6, atol=1e-7)


class TestSuperCycle:
    """Universal promotion part (b): k×(fwd+bwd)+step micro-batch
    accumulation promotes to ≤2 executables (a reusable sub-executable +
    one update executable), zero retraces at ANY k."""

    def _accum_run(self, fused, n=18, k=4, kind="momentum", seed=0):
        set_flags({"FLAGS_eager_step_fusion": fused})
        clear_dispatch_cache()
        paddle.seed(seed)
        rng = np.random.default_rng(9)
        xs = [paddle.to_tensor(
            rng.standard_normal((8, 16)).astype(np.float32))
            for _ in range(k)]
        w = paddle.to_tensor(
            rng.standard_normal((16, 16)).astype(np.float32),
            stop_gradient=False)
        b = paddle.to_tensor(rng.standard_normal(16).astype(np.float32),
                             stop_gradient=False)
        opt = _make_opt(kind, [w, b])
        losses = []
        for _ in range(n):
            per = []
            for m in range(k):
                y = F.gelu(paddle.add(paddle.matmul(xs[m], w), b))
                loss = y.sum()
                loss.backward()
                per.append(loss)
            opt.step()
            opt.clear_grad()
            # post-step reads are served from the sub-executable outputs
            losses.append([float(l.numpy()) for l in per])
        return np.asarray(losses), w.numpy().copy()

    @pytest.mark.parametrize("kind", ["sgd", "adam"])
    def test_accum_parity(self, kind):
        unfused, w0 = self._accum_run(False, kind=kind)
        fused, w1 = self._accum_run(True, kind=kind)
        s = step_fusion_stats()
        assert s["steps_promoted"] >= 1
        assert s["fused_steps"] >= 10, s
        assert s["fallback_splits"] == 0, s
        np.testing.assert_allclose(fused, unfused, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(w1, w0, rtol=1e-4, atol=1e-5)

    def test_any_k_without_recompiling(self):
        """After warmup at k=2, k=4/8/3 replay with ZERO fresh retraces
        (the canonical signature is k-independent)."""
        paddle.seed(0)
        rng = np.random.default_rng(9)
        x = paddle.to_tensor(
            rng.standard_normal((8, 16)).astype(np.float32))
        w = paddle.to_tensor(
            rng.standard_normal((16, 16)).astype(np.float32),
            stop_gradient=False)
        b = paddle.to_tensor(rng.standard_normal(16).astype(np.float32),
                             stop_gradient=False)
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[w, b])

        def cycle(k):
            for _ in range(k):
                y = F.dropout(
                    F.gelu(paddle.add(paddle.matmul(x, w), b)), 0.2)
                y.sum().backward()
            opt.step()
            opt.clear_grad()

        for _ in range(8):
            cycle(2)
        s0 = step_fusion_stats()
        assert s0["steps_promoted"] == 1
        # ≤2 executables: exactly one sub trace + one update trace
        assert s0["retraces"] == 2, s0["retraces"]
        for k in (4, 8, 3, 4):
            cycle(k)
        s1 = step_fusion_stats()
        assert s1["retraces"] == s0["retraces"]
        assert s1["fallback_splits"] == 0
        assert s1["fused_steps"] - s0["fused_steps"] == 4

    def test_mid_cycle_grad_peek_splits_bitwise(self):
        """Reading p.grad between micro-batches escapes the pending
        super-cycle: the replay runs every archived round's tape backward
        eagerly — accumulated grads BITWISE match unfused dispatch."""
        def run(fused):
            set_flags({"FLAGS_eager_step_fusion": fused})
            clear_dispatch_cache()
            paddle.seed(2)
            x, w, b = _params()
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=[w, b])
            peeks = []
            for _ in range(12):
                for m in range(3):
                    y = F.gelu(paddle.add(paddle.matmul(x, w), b))
                    y.sum().backward()
                    if m == 1:
                        peeks.append(w.grad.numpy().copy())  # escape
                opt.step()
                opt.clear_grad()
            return peeks, w.numpy().copy()

        (pu, wu) = run(False)
        (pf, wf) = run(True)
        assert step_fusion_stats()["fused_steps"] == 0
        for u, f in zip(pu, pf):
            np.testing.assert_array_equal(u, f)
        np.testing.assert_array_equal(wu, wf)

    def test_guardian_skip_on_accumulated_grads(self):
        """FLAGS_check_numerics: a NaN poisoning ONE micro-batch makes
        the whole accumulated update a bitwise no-op — fused and eager
        agree on params AND the skip accounting."""
        from paddle_tpu.ops import guardian

        def run(fused):
            set_flags({"FLAGS_eager_step_fusion": fused,
                       "FLAGS_check_numerics": True,
                       "FLAGS_check_numerics_level": 1})
            clear_dispatch_cache()
            paddle.seed(4)
            x, w, b = _params()
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=[w, b])
            try:
                for i in range(14):
                    for m in range(3):
                        y = F.gelu(paddle.add(paddle.matmul(x, w), b))
                        loss = y.sum()
                        if i == 10 and m == 1:
                            loss = loss * paddle.to_tensor(
                                np.float32("nan"))
                        loss.backward()
                    opt.step()
                    opt.clear_grad()
                guardian.flush()
            finally:
                set_flags({"FLAGS_check_numerics": False,
                           "FLAGS_check_numerics_level": 0})
            return w.numpy().copy(), b.numpy().copy()

        wu, bu = run(False)
        wf, bf = run(True)
        s = step_fusion_stats()
        assert s["fused_steps"] >= 6, s
        np.testing.assert_allclose(wf, wu, rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(bf, bu, rtol=1e-4, atol=1e-6)
        assert np.isfinite(wf).all()

    @pytest.mark.perf_smoke
    def test_perf_smoke_dropout_and_accum_promote(self):
        """perf_smoke mirror of tools/perf_smoke.py leg (m): the dropout
        loop promotes with zero steady-state retraces; the k=4
        accumulation loop runs ≤2 executables with zero retraces."""
        paddle.seed(0)
        x, w, b = _params()
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[w, b])
        for _ in range(12):
            _dropout_cycle(x, w, b, opt)
        s = step_fusion_stats()
        assert s["steps_promoted"] == 1 and s["fused_steps"] >= 6
        r0 = s["retraces"]
        for _ in range(4):
            _dropout_cycle(x, w, b, opt)
        assert step_fusion_stats()["retraces"] == r0
        # accumulation leg
        clear_dispatch_cache()
        reset_step_fusion_stats()
        opt2 = paddle.optimizer.SGD(learning_rate=0.01,
                                    parameters=[w, b])
        for _ in range(10):
            for m in range(4):
                y = F.gelu(paddle.add(paddle.matmul(x, w), b))
                y.sum().backward()
            opt2.step()
            opt2.clear_grad()
        s = step_fusion_stats()
        assert s["steps_promoted"] == 1
        assert s["retraces"] == 2, s["retraces"]     # sub + update ONLY
        assert s["fallback_splits"] == 0
        assert s["fused_steps"] >= 4

    def test_reseed_between_backward_and_step_stays_eager_exact(self):
        """A reseed BETWEEN backward and step swaps the global base key
        mid-cycle: the fused fire must derive this cycle's keys from the
        base they were RESERVED against (what eager sampled), and the
        next cycle re-anchors on the new base — trajectories match."""
        def run(fused):
            set_flags({"FLAGS_eager_step_fusion": fused})
            clear_dispatch_cache()
            paddle.seed(5)
            x, w, b = _params()
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=[w, b])
            out = []
            for i in range(16):
                y = F.dropout(F.gelu(paddle.add(paddle.matmul(x, w), b)),
                              0.4)
                loss = y.sum()
                loss.backward()
                if i == 10:
                    paddle.seed(777)       # mid-cycle reseed
                opt.step()
                opt.clear_grad()
                out.append(float(loss.numpy()))
            return np.asarray(out), w.numpy().copy()

        unfused, wu = run(False)
        fused, wf = run(True)
        s = step_fusion_stats()
        assert s["fused_steps"] >= 8, s
        np.testing.assert_allclose(fused, unfused, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(wf, wu, rtol=1e-4, atol=1e-5)


class TestRaggedTail:
    """PR 16 tentpole (c): an epoch of k−1 full micro-batches plus one
    SMALLER tail micro-batch (dataset length not divisible by the accum
    factor) promotes with ONE extra tail sub-executable keyed by the
    tail shape — ≤3 executables total, zero steady-state retraces — and
    the tail's grads ADD into the same accumulator the full rounds
    feed."""

    def _ragged_run(self, fused, n=14, k=4, kind="sgd", seed=3):
        set_flags({"FLAGS_eager_step_fusion": fused})
        clear_dispatch_cache()
        paddle.seed(seed)
        rng = np.random.default_rng(11)
        xs = [paddle.to_tensor(
            rng.standard_normal((8, 16)).astype(np.float32))
            for _ in range(k - 1)]
        # the short epoch-boundary batch: 3 rows instead of 8
        xs.append(paddle.to_tensor(
            rng.standard_normal((3, 16)).astype(np.float32)))
        w = paddle.to_tensor(
            rng.standard_normal((16, 16)).astype(np.float32),
            stop_gradient=False)
        b = paddle.to_tensor(rng.standard_normal(16).astype(np.float32),
                             stop_gradient=False)
        opt = _make_opt(kind, [w, b])
        losses = []
        for _ in range(n):
            per = []
            for x in xs:
                y = F.gelu(paddle.add(paddle.matmul(x, w), b))
                loss = paddle.mean(y)   # mean: the tail term differs
                loss.backward()
                per.append(loss)
            opt.step()
            opt.clear_grad()
            losses.append([float(l.numpy()) for l in per])
        return np.asarray(losses), w.numpy().copy()

    @pytest.mark.parametrize("kind", ["sgd", "adam"])
    def test_ragged_parity_three_executables(self, kind):
        unfused, w0 = self._ragged_run(False, kind=kind)
        fused, w1 = self._ragged_run(True, kind=kind)
        s = step_fusion_stats()
        assert s["steps_promoted"] >= 1, s
        assert s["fused_steps"] >= 5, s
        assert s["fallback_splits"] == 0, s
        # exactly 3 traces: main sub + tail sub + update — a 4th would
        # mean the tail retraces per epoch (the irregular_accum bug)
        assert s["retraces"] == 3, s["retraces"]
        np.testing.assert_allclose(fused, unfused, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(w1, w0, rtol=1e-4, atol=1e-5)

    def test_steady_state_zero_retraces(self):
        """After warmup, further ragged epochs — and a uniform epoch on
        the same params — replay with zero fresh retraces."""
        paddle.seed(6)
        rng = np.random.default_rng(13)
        full = paddle.to_tensor(
            rng.standard_normal((8, 16)).astype(np.float32))
        short = paddle.to_tensor(
            rng.standard_normal((3, 16)).astype(np.float32))
        w = paddle.to_tensor(
            rng.standard_normal((16, 16)).astype(np.float32),
            stop_gradient=False)
        b = paddle.to_tensor(rng.standard_normal(16).astype(np.float32),
                             stop_gradient=False)
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[w, b])

        def epoch(xs):
            for x in xs:
                y = F.gelu(paddle.add(paddle.matmul(x, w), b))
                paddle.mean(y).backward()
            opt.step()
            opt.clear_grad()

        for _ in range(8):
            epoch([full, full, full, short])
        s0 = step_fusion_stats()
        assert s0["steps_promoted"] == 1, s0
        assert s0["retraces"] == 3, s0["retraces"]
        for _ in range(6):
            epoch([full, full, full, short])
        # an all-full epoch replays main rounds + boundary on the SAME
        # program (the tail sub simply does not fire)
        epoch([full, full, full, full])
        s1 = step_fusion_stats()
        assert s1["retraces"] == s0["retraces"], s1
        assert s1["fallback_splits"] == 0, s1
        assert s1["fused_steps"] - s0["fused_steps"] == 7, s1
