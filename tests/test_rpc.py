"""distributed.rpc tests: multi-process workers in the TestDistBase style
(subprocess ranks on one host, SURVEY.md §4)."""
import os
import subprocess
import sys

import numpy as np

_WORKER = r"""
import sys, numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
from paddle_tpu.distributed import rpc

rank = int(sys.argv[1]); port = sys.argv[2]

def add(a, b):
    return a + b

def matsum(arr):
    return float(np.asarray(arr).sum())

rpc.init_rpc(f"worker{rank}", rank=rank, world_size=2,
             master_endpoint=f"127.0.0.1:{port}")

if rank == 0:
    out = rpc.rpc_sync("worker1", add, args=(2, 40))
    assert out == 42, out
    fut = rpc.rpc_async("worker1", matsum, args=(np.ones((4, 4)),))
    assert fut.wait() == 16.0
    # self-call roundtrip
    assert rpc.rpc_sync("worker0", add, args=(1, 1)) == 2
    infos = rpc.get_all_worker_infos()
    assert [w.name for w in infos] == ["worker0", "worker1"]
    print("RPC_OK")

rpc.shutdown()
"""


def test_rpc_two_workers(tmp_path):
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    script = tmp_path / "rpc_worker.py"
    script.write_text(_WORKER)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo_root)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        for r in range(2)]
    outs = [p.communicate(timeout=120)[0].decode() for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    assert "RPC_OK" in outs[0]
