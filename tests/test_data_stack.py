"""paddle.distributed namespace parity + dataset/reader/cost_model stack.

Reference analog: python/paddle/distributed/__init__.py __all__ (38 names),
python/paddle/reader/decorator.py tests (reader decorators), dataset
reader-creator contract, cost_model/cost_model.py.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


# ----------------------------------------------------- namespace parity

def test_distributed_all_38():
    import paddle_tpu.distributed as d
    assert len(d.__all__) == 38
    missing = [n for n in d.__all__ if not hasattr(d, n)]
    assert not missing, missing


def test_launch_is_callable_and_module_runs():
    import paddle_tpu.distributed as d
    assert callable(d.launch)


def test_parallel_mode_exported():
    import paddle_tpu.distributed as d
    assert hasattr(d.ParallelMode, "DATA_PARALLEL")


# ----------------------------------------------------------- entry_attr

def test_entry_attr_to_attr_strings():
    import paddle_tpu.distributed as d
    assert d.ProbabilityEntry(0.5)._to_attr() == "probability_entry:0.5"
    assert d.CountFilterEntry(3)._to_attr() == "count_filter_entry:3"
    assert d.ShowClickEntry("show", "click")._to_attr() == \
        "show_click_entry:show:click"


def test_entry_attr_validation():
    import paddle_tpu.distributed as d
    with pytest.raises(ValueError):
        d.ProbabilityEntry(0)
    with pytest.raises(ValueError):
        d.ProbabilityEntry("x")
    with pytest.raises(ValueError):
        d.CountFilterEntry(-1)
    with pytest.raises(ValueError):
        d.ShowClickEntry("s", 3)


def test_count_filter_entry_admits_after_n():
    from paddle_tpu.distributed.entry_attr import CountFilterEntry
    e = CountFilterEntry(3)
    assert not e.admit(7, None)
    assert not e.admit(7, None)
    assert e.admit(7, None)          # third touch admits
    assert e.admit(7, None)


# ------------------------------------------------------- fleet datasets

def _write_filelist(tmp_path, n_files=2, lines_per=8):
    paths = []
    rng = np.random.default_rng(0)
    for i in range(n_files):
        p = tmp_path / f"part-{i}.txt"
        with open(p, "w") as f:
            for _ in range(lines_per):
                feats = " ".join(f"{v:.3f}" for v in rng.random(4))
                f.write(f"{feats} {int(rng.integers(0, 2))}\n")
        paths.append(str(p))
    return paths


def test_in_memory_dataset(tmp_path):
    from paddle_tpu.distributed import InMemoryDataset
    ds = InMemoryDataset()
    ds.init(batch_size=4)
    ds.set_filelist(_write_filelist(tmp_path))
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 16
    ds.local_shuffle(seed=0)
    batches = list(ds.batches())
    assert len(batches) == 4
    x, y = batches[0]
    assert x.shape == (4, 4) and y.shape == (4,)
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


def test_queue_dataset_streams_and_rejects_shuffle(tmp_path):
    from paddle_tpu.distributed import QueueDataset
    ds = QueueDataset()
    ds.init(batch_size=8)
    ds.set_filelist(_write_filelist(tmp_path))
    batches = list(ds.batches())
    assert len(batches) == 2
    with pytest.raises(NotImplementedError):
        ds.local_shuffle()


def test_in_memory_dataset_custom_parser(tmp_path):
    from paddle_tpu.distributed import InMemoryDataset
    p = tmp_path / "csv.txt"
    with open(p, "w") as f:
        f.write("1,2\n3,4\n")
    ds = InMemoryDataset()
    ds.init(batch_size=2, pipe_command=lambda line: np.asarray(
        [float(v) for v in line.strip().split(",")], np.float32))
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    (batch,) = list(ds.batches())
    np.testing.assert_array_equal(batch, [[1, 2], [3, 4]])


# ------------------------------------------------------------- reader

def test_reader_decorators_compose():
    import paddle_tpu.reader as reader

    def r():
        return iter(range(10))

    assert list(reader.firstn(r, 3)()) == [0, 1, 2]
    assert list(reader.chain(r, r)()) == list(range(10)) * 2
    assert sorted(reader.shuffle(r, 4)()) == list(range(10))
    assert list(reader.buffered(r, 2)()) == list(range(10))
    assert list(reader.map_readers(lambda a, b: a + b, r, r)()) == \
        [2 * i for i in range(10)]
    cached = reader.cache(r)
    assert list(cached()) == list(range(10))
    assert list(cached()) == list(range(10))


def test_reader_compose_alignment():
    import paddle_tpu.reader as reader

    def r5():
        return iter(range(5))

    def r3():
        return iter(range(3))

    out = list(reader.compose(r5, r5)())
    assert out[0] == (0, 0)
    with pytest.raises(reader.ComposeNotAligned):
        list(reader.compose(r5, r3)())
    # check_alignment=False truncates instead
    assert len(list(reader.compose(r5, r3, check_alignment=False)())) == 3


def test_reader_xmap_and_multiprocess():
    import paddle_tpu.reader as reader

    def r():
        return iter(range(20))

    out = sorted(reader.xmap_readers(lambda x: x * 2, r, 3, 8)())
    assert out == [2 * i for i in range(20)]
    out2 = sorted(reader.xmap_readers(lambda x: x + 1, r, 2, 4, order=True)())
    assert out2 == [i + 1 for i in range(20)]
    mp = reader.multiprocess_reader([r, r], queue_size=16)
    assert sorted(mp()) == sorted(list(range(20)) * 2)


# -------------------------------------------------------- paddle.dataset

def test_dataset_mnist_reader():
    import paddle_tpu.dataset as dataset
    sample = next(dataset.mnist.train()())
    img, label = sample
    assert img.shape == (784,)
    assert img.min() >= -1.0 and img.max() <= 1.0
    assert 0 <= label <= 9


def test_dataset_cifar_uci_imdb_imikolov():
    import paddle_tpu.dataset as dataset
    img, label = next(dataset.cifar.train10()())
    assert img.shape == (3072,)
    feats, price = next(dataset.uci_housing.train()())
    assert feats.shape == (13,)
    toks, lab = next(dataset.imdb.train(dataset.imdb.word_dict())())
    assert isinstance(toks, list) and lab in (0, 1)
    gram = next(dataset.imikolov.train(n=5)())
    assert len(gram) == 5


def test_dataset_common_split_and_cluster(tmp_path):
    import paddle_tpu.dataset.common as common
    os.chdir(tmp_path)

    def r():
        return iter(range(10))

    common.split(r, 4, suffix=str(tmp_path / "chunk-%05d.pickle"))
    rd = common.cluster_files_reader(str(tmp_path / "chunk-*.pickle"), 1, 0)
    assert sorted(rd()) == list(range(10))


# ------------------------------------------------------------ cost_model

def test_cost_model():
    from paddle_tpu.cost_model import CostModel
    cm = CostModel()
    startup, main = cm.build_program()
    cost = cm.profile_measure(startup, main, device="cpu")
    assert cost["time"] > 0
    data = cm.static_cost_data()
    assert any(d["op"] == "matmul" for d in data)
    t = cm.get_static_op_time("matmul")
    assert t["op_time"] > 0
    back = cm.get_static_op_time("matmul", forward=False)
    assert back["op_time"] >= t["op_time"]
    with pytest.raises(ValueError):
        cm.get_static_op_time(None)


# -------------------------------------------------- gloo control plane

def test_gloo_single_rank_roundtrip():
    import paddle_tpu.distributed as d
    port = 29771
    d.gloo_init_parallel_env(0, 1, f"127.0.0.1:{port}")
    d.gloo_barrier()
    d.gloo_release()
    # double release is harmless
    d.gloo_release()


def test_sparse_table_entry_admission():
    """CountFilterEntry gates PS sparse-table materialization: rows appear
    only after N touches; un-admitted pulls are zeros."""
    from paddle_tpu.distributed.ps import SparseTable
    from paddle_tpu.distributed.entry_attr import CountFilterEntry
    t = SparseTable("emb", 4, entry=CountFilterEntry(2))
    first = t.pull([7])
    np.testing.assert_array_equal(first, np.zeros((1, 4), np.float32))
    assert 7 not in t.rows
    second = t.pull([7])                 # second touch admits
    assert 7 in t.rows
    assert np.abs(second).sum() > 0


def test_partial_p2p_warns_once_about_control_plane():
    """partial_send/recv ride the host-mediated path: a once-per-process
    RuntimeWarning must point users at the compiled ppermute data plane."""
    import warnings
    import paddle_tpu.distributed as d
    from paddle_tpu.distributed import collective as coll
    coll._partial_p2p_warned = False      # reset the once-latch
    t = paddle.to_tensor(np.arange(8, dtype=np.float32))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        d.partial_send(t, dst=0, nranks=2, rank_id=0)
        d.partial_send(t, dst=0, nranks=2, rank_id=1)
    msgs = [x for x in w if issubclass(x.category, RuntimeWarning)
            and "ppermute" in str(x.message)]
    assert len(msgs) == 1                 # fired exactly once


def test_communication_stream_package():
    """paddle.distributed.communication.stream variants (reference:
    distributed/communication/stream/) — use_calc_stream accepted, results
    match the eager collectives at world 1."""
    import paddle_tpu.distributed as d
    assert hasattr(d, "stream") and hasattr(d, "communication")
    t = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
    task = d.stream.all_reduce(t, sync_op=False, use_calc_stream=True)
    task.wait()
    np.testing.assert_allclose(np.asarray(t._value), [1.0, 2.0])
    out = []
    d.stream.all_gather(out, t)
    assert len(out) == 1
    dst = paddle.to_tensor(np.zeros(2, np.float32))
    d.stream.alltoall_single(dst, t)
    np.testing.assert_allclose(np.asarray(dst._value), [1.0, 2.0])
    for name in ("all_gather", "all_reduce", "alltoall", "alltoall_single",
                 "broadcast", "reduce", "reduce_scatter", "recv", "scatter",
                 "send"):
        assert hasattr(d.stream, name), name


# -------------------------------------------------------- fleet surface

def test_fleet_surface_39():
    """paddle.distributed.fleet exposes the reference __all__ + singleton
    bindings (reference fleet/__init__.py:39-104)."""
    from paddle_tpu.distributed import fleet
    names = ["CommunicateTopology", "UserDefinedRoleMaker",
             "PaddleCloudRoleMaker", "Role", "UtilBase",
             "HybridCommunicateGroup", "MultiSlotDataGenerator",
             "MultiSlotStringDataGenerator", "Fleet", "DistributedStrategy",
             "init", "is_first_worker", "worker_index", "worker_num",
             "is_worker", "worker_endpoints", "server_num", "server_index",
             "server_endpoints", "is_server", "util", "barrier_worker",
             "init_worker", "init_server", "run_server", "stop_worker",
             "distributed_optimizer", "save_inference_model",
             "save_persistables", "distributed_model", "state_dict",
             "set_state_dict", "shrink", "get_lr", "set_lr", "minimize",
             "DatasetBase", "InMemoryDataset", "QueueDataset"]
    missing = [n for n in names if not hasattr(fleet, n)]
    assert not missing, missing


def test_role_makers(monkeypatch):
    from paddle_tpu.distributed.fleet import (UserDefinedRoleMaker,
                                              PaddleCloudRoleMaker, Role)
    rm = UserDefinedRoleMaker(current_id=1, role=Role.WORKER, worker_num=4)
    assert rm._worker_index() == 1 and rm._worker_num() == 4
    assert rm._is_worker() and not rm._is_server()
    rm2 = UserDefinedRoleMaker(
        current_id=0, role=Role.SERVER,
        worker_endpoints=["127.0.0.1:6170"],
        server_endpoints=["127.0.0.1:6270", "127.0.0.1:6271"])
    assert rm2._is_server() and rm2._server_num() == 2
    monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
    monkeypatch.setenv("PADDLE_TRAINER_ENDPOINTS",
                       "127.0.0.1:6170,127.0.0.1:6171,127.0.0.1:6172")
    cloud = PaddleCloudRoleMaker()
    assert cloud._worker_index() == 2 and cloud._worker_num() == 3


def test_util_base_file_shard():
    from paddle_tpu.distributed.fleet import (UtilBase,
                                              UserDefinedRoleMaker, Role)
    files = [f"f{i}" for i in range(7)]
    shards = []
    for rank in range(3):
        u = UtilBase()
        u._set_role_maker(UserDefinedRoleMaker(
            current_id=rank, role=Role.WORKER, worker_num=3))
        shards.append(u.get_file_shard(files))
    # contiguous, disjoint, covering; earlier ranks carry the remainder
    assert [len(s) for s in shards] == [3, 2, 2]
    assert sum(shards, []) == files
    # all_reduce/all_gather degenerate correctly at world 1
    u = UtilBase()
    np.testing.assert_allclose(u.all_reduce(np.asarray([1.0, 2.0])),
                               [1.0, 2.0])
    assert u.all_gather(5) == [5]


def test_multislot_data_generator():
    from paddle_tpu.distributed.fleet import MultiSlotDataGenerator

    class G(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def gen():
                toks = [int(v) for v in line.split()]
                yield [("words", toks[:-1]), ("label", [toks[-1]])]
            return gen

    g = G()
    out = g.run_from_memory(["1 2 3 1", "4 5 6 0"])
    assert out == ["3 1 2 3 1 1\n", "3 4 5 6 1 0\n"]
    # inconsistent slot name must raise
    class Bad(MultiSlotDataGenerator):
        def __init__(self):
            super().__init__()
            self.n = 0
        def generate_sample(self, line):
            def gen():
                self.n += 1
                name = "words" if self.n == 1 else "other"
                yield [(name, [1])]
            return gen
    with pytest.raises(ValueError):
        Bad().run_from_memory(["a", "b"])


def test_data_generator_feeds_fleet_dataset(tmp_path):
    """The generator's MultiSlot lines parse back through InMemoryDataset
    with a matching parser — the end-to-end ingest contract."""
    from paddle_tpu.distributed.fleet import (MultiSlotDataGenerator,
                                              InMemoryDataset)

    class G(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def gen():
                vals = [float(v) for v in line.split()]
                yield [("feat", vals[:-1]), ("label", [int(vals[-1])])]
            return gen

    g = G()
    lines = g.run_from_memory(["0.5 0.25 1", "0.125 0.75 0"])
    p = tmp_path / "part-0.txt"
    with open(p, "w") as f:
        f.writelines(lines)

    def parse(line):
        toks = line.split()
        n_feat = int(toks[0])
        feats = np.asarray([float(v) for v in toks[1:1 + n_feat]],
                           np.float32)
        label = np.asarray(int(float(toks[2 + n_feat])), np.int64)
        return feats, label

    ds = InMemoryDataset()
    ds.init(batch_size=2, pipe_command=parse)
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    (x, y), = list(ds.batches())
    np.testing.assert_allclose(x, [[0.5, 0.25], [0.125, 0.75]])
    np.testing.assert_array_equal(y, [1, 0])


def test_fleet_singleton_state_passthrough():
    from paddle_tpu.distributed import fleet
    import paddle_tpu.nn as nn
    paddle.seed(0)
    m = nn.Linear(4, 2)
    fleet.init(is_collective=True)
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=0.5, parameters=m.parameters()))
    assert fleet.get_lr() == 0.5
    sd = fleet.state_dict()
    assert isinstance(sd, dict)
    assert fleet.is_first_worker() and fleet.is_worker()
    assert not fleet.is_server()
    assert fleet.worker_num() >= 1


def test_passes_framework():
    """paddle.distributed.passes (reference pass_base.py:131 new_pass,
    :311 PassManager): functional delegates + compiler-owned no-ops."""
    from paddle_tpu.distributed.passes import (new_pass, PassManager,
                                               PassContext)
    import paddle_tpu.nn as nn
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
    p_amp = new_pass("auto_parallel_amp",
                     {"model": model, "optimizer": opt})
    p_gm = new_pass("auto_parallel_gradient_merge_pass",
                    {"optimizer": opt, "k_steps": 2})
    p_fuse = new_pass("fuse_all_reduce")
    pm = PassManager([p_amp, p_gm, p_fuse])
    ctx = pm.apply()
    assert len(ctx.applied_passes) == 3
    assert opt._multi_precision is True
    import jax.numpy as jnp
    assert model[0].weight._value.dtype == jnp.bfloat16   # O2 cast
    from paddle_tpu.distributed.fleet.meta_optimizers import \
        GradientMergeOptimizer
    assert isinstance(ctx.attrs["optimizer"], GradientMergeOptimizer)
    assert ctx.attrs["compiler_owned"] == ["fuse_all_reduce"]
    assert pm.names == ["auto_parallel_amp",
                        "auto_parallel_gradient_merge_pass",
                        "fuse_all_reduce"]
    with pytest.raises(ValueError, match="not registered"):
        new_pass("no_such_pass")
    with pytest.raises(ValueError, match="needs"):
        new_pass("auto_parallel_recompute").apply(None, None, PassContext())


def test_passes_write_through_wrappers():
    """AMP/sharding passes must write on the INNERMOST optimizer when
    handed a fleet wrapper (review regression: wrapper __getattr__ makes
    reads transparent but writes land on the wrapper)."""
    from paddle_tpu.distributed.passes import new_pass
    from paddle_tpu.distributed.fleet.meta_optimizers import \
        GradientMergeOptimizer
    import paddle_tpu.nn as nn
    paddle.seed(0)
    m = nn.Linear(8, 4)
    inner = paddle.optimizer.AdamW(learning_rate=1e-2,
                                   parameters=m.parameters())
    wrapped = GradientMergeOptimizer(inner, k_steps=2)
    new_pass("auto_parallel_amp",
             {"model": m, "optimizer": wrapped}).apply(None, None)
    assert inner._multi_precision is True       # inner, not wrapper dict
    assert "_multi_precision" not in wrapped.__dict__
    # fp16 variant is registered too
    p = new_pass("auto_parallel_fp16", {"model": m})
    assert p.name == "auto_parallel_fp16"


def test_pass_manager_conflict_hooks():
    from paddle_tpu.distributed.passes import (PassBase, PassManager,
                                               register_pass, new_pass)

    @register_pass("_test_conflicting")
    class Conflicting(PassBase):
        def _check_conflict(self, other):
            return other.name != "fuse_all_reduce"

        def _apply_impl(self, mains, startups, ctx):
            pass

    a = new_pass("fuse_all_reduce")
    b = new_pass("_test_conflicting")
    pm = PassManager([a, b])                    # auto-solve drops b
    assert pm.names == ["fuse_all_reduce"]
    with pytest.raises(ValueError, match="conflicts"):
        PassManager([a, b], auto_solve_conflict=False)


# ----------------------------------------- secondary distributed modules

def test_moe_gate_utils():
    from paddle_tpu.distributed.models.moe import (
        _number_count, _assign_pos, _random_routing, _limit_by_capacity,
        _prune_gate_by_capacity)
    # number_count: reference docstring example
    numbers = paddle.to_tensor(np.asarray([[0, 2], [0, 2]], np.int32))
    nc = _number_count(numbers, 6)
    np.testing.assert_array_equal(np.asarray(nc._value), [2, 0, 2, 0, 0, 0])
    # assign_pos: tokens ordered expert-by-expert, stable within expert
    gate = paddle.to_tensor(np.asarray([1, 0, 1, 0], np.int64))
    cum = paddle.to_tensor(np.asarray([2, 4], np.int64))
    pos = _assign_pos(gate, cum)
    np.testing.assert_array_equal(np.asarray(pos._value), [1, 3, 0, 2])
    # random_routing: 2*value < prob drops the 2nd choice
    idx = paddle.to_tensor(np.asarray([[0, 1], [2, 3]], np.int64))
    val = paddle.to_tensor(np.asarray([[0.9, 0.05], [0.8, 0.4]],
                                      np.float32))
    prob = paddle.to_tensor(np.asarray([0.5, 0.5], np.float32))
    out = _random_routing(idx, val, prob)
    np.testing.assert_array_equal(np.asarray(out._value),
                                  [[0, -1], [2, 3]])
    # limit_by_capacity: worker 0 served first
    ec = paddle.to_tensor(np.asarray([3, 1, 4, 2], np.int64))  # 2 workers
    cap = paddle.to_tensor(np.asarray([4, 2], np.int64))       # x 2 experts
    lim = _limit_by_capacity(ec, cap, n_worker=2)
    np.testing.assert_array_equal(np.asarray(lim._value), [3, 1, 1, 1])
    # prune_gate: budget [1,1] kills the second token per expert
    g = paddle.to_tensor(np.asarray([0, 0, 1, 1], np.int64))
    budget = paddle.to_tensor(np.asarray([1, 1], np.int64))
    pruned = _prune_gate_by_capacity(g, budget, 2, 1)
    np.testing.assert_array_equal(np.asarray(pruned._value),
                                  [0, -1, 1, -1])


def test_global_scatter_gather_world1_roundtrip():
    import warnings
    from paddle_tpu.distributed.utils import global_scatter, global_gather
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(4, 3))
    local = paddle.to_tensor(np.asarray([2, 2], np.int64))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        y = global_scatter(x, local, local)
        np.testing.assert_allclose(np.asarray(y._value),
                                   np.asarray(x._value))
        back = global_gather(y, local, local)
    np.testing.assert_allclose(np.asarray(back._value),
                               np.asarray(x._value))


def test_distributed_metric_auc():
    from paddle_tpu.distributed.metric import init_metric, print_auc
    from paddle_tpu.distributed.metric.metrics import update_metric
    ptr = init_metric(name="auc", bucket_size=4095)
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 2, 512)
    # informative predictions -> AUC well above 0.5
    preds = np.clip(labels * 0.6 + rng.random(512) * 0.4, 0, 1)
    update_metric("auc", preds, labels)
    auc = print_auc(ptr)
    assert 0.7 < auc <= 1.0


def test_cloud_utils_cluster(monkeypatch):
    from paddle_tpu.distributed import cloud_utils
    monkeypatch.setenv("PADDLE_TRAINERS", "10.0.0.1,10.0.0.2")
    monkeypatch.setenv("POD_IP", "10.0.0.2")
    monkeypatch.setenv("TRAINER_PORTS", "6170,6171")
    cluster, pod = cloud_utils.get_cloud_cluster()
    assert cluster.world_size() == 4
    assert pod.ip == "10.0.0.2" and pod.rank == 1
    assert cluster.trainers_endpoints()[0] == "10.0.0.1:6170"
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "3")
    assert cloud_utils.get_trainers_num() == 3


def test_static_sparse_embedding_with_entry():
    import paddle_tpu.static.nn as snn
    from paddle_tpu.distributed.entry_attr import CountFilterEntry
    ids = paddle.to_tensor(np.asarray([[7, 9]], np.int64))
    e1 = snn.sparse_embedding(ids, size=[100, 8], name="se_test",
                              entry=CountFilterEntry(2))
    assert np.abs(np.asarray(e1._value)).sum() == 0.0   # gated
    e2 = snn.sparse_embedding(ids, size=[100, 8], name="se_test")
    assert tuple(e2.shape) == (1, 2, 8)
    assert np.abs(np.asarray(e2._value)).sum() > 0       # admitted
    # padding_idx rows stay zero
    ids3 = paddle.to_tensor(np.asarray([[0, 7]], np.int64))
    e3 = snn.sparse_embedding(ids3, size=[100, 8], name="se_test",
                              padding_idx=0)
    assert np.abs(np.asarray(e3._value)[0, 0]).sum() == 0.0


def test_sparse_embedding_identity_and_dim_guards():
    import paddle_tpu.static.nn as snn
    ids = paddle.to_tensor(np.asarray([[1]], np.int64))
    with pytest.raises(ValueError, match="stable identity"):
        snn.sparse_embedding(ids, size=[10, 4])
    snn.sparse_embedding(ids, size=[10, 4], name="se_dim_guard")
    with pytest.raises(ValueError, match="already exists"):
        snn.sparse_embedding(ids, size=[10, 8], name="se_dim_guard")


def test_cloud_utils_unknown_pod_ip_raises(monkeypatch):
    from paddle_tpu.distributed import cloud_utils
    monkeypatch.setenv("PADDLE_TRAINERS", "10.0.0.1,10.0.0.2")
    monkeypatch.setenv("POD_IP", "192.168.1.9")
    monkeypatch.setenv("TRAINER_PORTS", "6170")
    with pytest.raises(ValueError, match="not in the trainer list"):
        cloud_utils.get_cloud_cluster()


def test_assign_pos_skips_pruned_ids():
    """Pruned (-1) gate ids must not be dispatched (review regression)."""
    from paddle_tpu.distributed.models.moe import _assign_pos
    gate = paddle.to_tensor(np.asarray([-1, 0, -1, 1], np.int64))
    cum = paddle.to_tensor(np.asarray([1, 2], np.int64))
    pos = _assign_pos(gate, cum)
    np.testing.assert_array_equal(np.asarray(pos._value), [1, 3])


def test_metric_top_bucket_mass_counts():
    """Predictions in the top histogram bucket must contribute to the
    global AUC exactly as to the local one (review regression)."""
    from paddle_tpu.distributed.metric import init_metric, print_auc
    from paddle_tpu.distributed.metric.metrics import (update_metric,
                                                       get_metric)
    ptr = init_metric(name="auc_top")
    labels = np.asarray([0, 1, 0, 1])
    update_metric("auc_top", np.ones(4, np.float32), labels)  # all ties
    local = float(get_metric("auc_top").accumulate())
    glob = print_auc(ptr, name="auc_top")
    np.testing.assert_allclose(glob, local)
    assert abs(glob - 0.5) < 1e-6


def test_cloud_utils_multinode_needs_pod_ip(monkeypatch):
    from paddle_tpu.distributed import cloud_utils
    monkeypatch.setenv("PADDLE_TRAINERS", "10.0.0.1,10.0.0.2")
    monkeypatch.delenv("POD_IP", raising=False)
    monkeypatch.setenv("TRAINER_PORTS", "6170")
    with pytest.raises(ValueError, match="POD_IP"):
        cloud_utils.get_cloud_cluster()
