"""static.nn extended builders (reference: python/paddle/static/nn 41
exports). Sequence ops use the padded-dense [B, T, ...] (+ lengths)
representation — LoD has no TPU analog."""
from pathlib import Path

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.static.nn as snn


def _t(a, dtype=np.float32):
    return paddle.to_tensor(np.asarray(a, dtype))


class TestLayerDelegates:
    @pytest.mark.skipif(not Path("/root/reference").exists(),
                        reason="reference checkout not mounted in this "
                               "container")
    def test_all_41_present(self):
        import ast
        tree = ast.parse(open(
            "/root/reference/python/paddle/static/nn/__init__.py").read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and any(
                    getattr(t, "id", None) == "__all__"
                    for t in node.targets):
                names = [ast.literal_eval(e) for e in node.value.elts]
        missing = [n for n in names if not hasattr(snn, n)]
        assert not missing, missing

    def test_norm_builders(self):
        paddle.seed(0)
        x = _t(np.random.default_rng(0).normal(size=(2, 8, 4, 4)))
        ln = snn.layer_norm(x, begin_norm_axis=1, name="ln_ext")
        assert ln.shape == [2, 8, 4, 4]
        gn = snn.group_norm(x, groups=4, name="gn_ext")
        assert gn.shape == [2, 8, 4, 4]
        instn = snn.instance_norm(x, name="in_ext")
        assert instn.shape == [2, 8, 4, 4]
        # scope reuse: same name returns identical params
        ln2 = snn.layer_norm(x, begin_norm_axis=1, name="ln_ext")
        np.testing.assert_allclose(np.asarray(ln2._value),
                                   np.asarray(ln._value))

    def test_conv_builders(self):
        paddle.seed(0)
        x = _t(np.random.default_rng(0).normal(size=(2, 3, 8, 8)))
        y = snn.conv2d_transpose(x, 6, filter_size=3, name="c2t")
        assert y.shape[1] == 6
        x3 = _t(np.random.default_rng(0).normal(size=(2, 3, 4, 8, 8)))
        z = snn.conv3d(x3, 5, 3, name="c3")
        assert z.shape[1] == 5
        z2 = snn.conv3d_transpose(x3, 4, filter_size=3, name="c3t")
        assert z2.shape[1] == 4

    def test_bilinear_prelu_spectral(self):
        paddle.seed(0)
        a = _t(np.random.default_rng(0).normal(size=(4, 5)))
        b = _t(np.random.default_rng(1).normal(size=(4, 6)))
        out = snn.bilinear_tensor_product(a, b, 3, name="btp")
        assert out.shape == [4, 3]
        x = _t(np.random.default_rng(2).normal(size=(2, 4, 3, 3)))
        p = snn.prelu(x, "channel", name="prelu_ext")
        assert p.shape == [2, 4, 3, 3]
        w = _t(np.random.default_rng(3).normal(size=(8, 6)))
        sn = snn.spectral_norm(w, power_iters=3)
        # spectral norm of the output must be ~1
        s = np.linalg.svd(np.asarray(sn._value), compute_uv=False)[0]
        np.testing.assert_allclose(s, 1.0, rtol=0.35)

    def test_nce_and_row_conv(self):
        paddle.seed(0)
        x = _t(np.random.default_rng(0).normal(size=(6, 16)))
        lab = paddle.to_tensor(np.asarray([[1], [2], [0], [3], [1], [2]],
                                          np.int64))
        loss = snn.nce(x, lab, num_total_classes=10, num_neg_samples=4,
                       name="nce_ext")
        assert loss.shape == [6, 1]
        assert np.all(np.asarray(loss._value) > 0)
        seq = _t(np.random.default_rng(1).normal(size=(2, 5, 8)))
        rc = snn.row_conv(seq, future_context_size=2, name="rc_ext")
        assert rc.shape == [2, 5, 8]

    def test_data_norm_accumulates(self):
        paddle.seed(0)
        x = _t(np.random.default_rng(0).normal(size=(16, 4)))
        out1 = snn.data_norm(x, name="dn_ext")
        assert out1.shape == [16, 4]
        from paddle_tpu.static.nn import _LAYERS
        before = float(_LAYERS["dn_ext"].batch_size._value[0])
        snn.data_norm(x, name="dn_ext")
        after = float(_LAYERS["dn_ext"].batch_size._value[0])
        assert after == before + 16

    def test_crf_decoding(self):
        paddle.seed(0)
        em = _t(np.random.default_rng(0).normal(size=(2, 6, 4)))
        path = snn.crf_decoding(em, name="crf_ext")
        arr = np.asarray(path._value)
        assert arr.shape == (2, 6)
        assert arr.min() >= 0 and arr.max() < 4

    def test_multi_box_head(self):
        paddle.seed(0)
        feats = [_t(np.random.default_rng(i).normal(size=(2, 8, s, s)))
                 for i, s in enumerate((8, 4))]
        img = _t(np.zeros((2, 3, 64, 64)))
        locs, confs, boxes, vars_ = snn.multi_box_head(
            feats, img, base_size=64, num_classes=5,
            aspect_ratios=[[2.0], [2.0]], name="mbox_ext")
        assert locs.shape[0] == 2 and locs.shape[2] == 4
        assert confs.shape[2] == 5
        assert boxes.shape[0] == locs.shape[1]
        assert vars_.shape == boxes.shape


class TestSequenceOps:
    def test_pool_variants(self):
        x = _t([[[1, 2], [3, 4], [5, 6]],
                [[7, 8], [9, 10], [0, 0]]])
        lens = paddle.to_tensor(np.asarray([3, 2], np.int64))
        s = snn.sequence_pool(x, "sum", lengths=lens)
        np.testing.assert_allclose(np.asarray(s._value),
                                   [[9, 12], [16, 18]])
        m = snn.sequence_pool(x, "max", lengths=lens)
        np.testing.assert_allclose(np.asarray(m._value),
                                   [[5, 6], [9, 10]])
        last = snn.sequence_last_step(x, lengths=lens)
        np.testing.assert_allclose(np.asarray(last._value),
                                   [[5, 6], [9, 10]])
        first = snn.sequence_first_step(x)
        np.testing.assert_allclose(np.asarray(first._value),
                                   [[1, 2], [7, 8]])

    def test_softmax_respects_lengths(self):
        x = _t(np.zeros((1, 4)))
        lens = paddle.to_tensor(np.asarray([2], np.int64))
        out = np.asarray(snn.sequence_softmax(x, lengths=lens)._value)
        np.testing.assert_allclose(out[0, :2], 0.5)
        np.testing.assert_allclose(out[0, 2:], 0.0)

    def test_pad_unpad_roundtrip(self):
        x = _t(np.arange(12, dtype=np.float32).reshape(2, 3, 2))
        padded, lens = snn.sequence_pad(x, 0.0, maxlen=5)
        assert padded.shape == [2, 5, 2]
        lens2 = paddle.to_tensor(np.asarray([3, 2], np.int64))
        flat = snn.sequence_unpad(padded, lens2)
        assert flat.shape[0] == 5
        np.testing.assert_allclose(np.asarray(flat._value)[:3],
                                   np.asarray(x._value)[0])

    def test_reverse_expand_enumerate_reshape(self):
        x = _t(np.arange(6, dtype=np.float32).reshape(1, 3, 2))
        r = snn.sequence_reverse(x)
        np.testing.assert_allclose(np.asarray(r._value)[0, 0], [4, 5])
        lens = paddle.to_tensor(np.asarray([2], np.int64))
        r2 = snn.sequence_reverse(x, lengths=lens)
        np.testing.assert_allclose(np.asarray(r2._value)[0, 0], [2, 3])
        np.testing.assert_allclose(np.asarray(r2._value)[0, 2], [4, 5])
        v = _t(np.ones((2, 4)))
        y = _t(np.zeros((2, 5, 3)))
        ex = snn.sequence_expand(v, y)
        assert ex.shape == [2, 5, 4]
        ids = paddle.to_tensor(np.asarray([[3, 1, 4]], np.int64))
        en = snn.sequence_enumerate(ids, 2, pad_value=0)
        np.testing.assert_array_equal(np.asarray(en._value)[0],
                                      [[3, 1], [1, 4], [4, 0]])
        rs = snn.sequence_reshape(x, 3)
        assert rs.shape == [1, 2, 3]

    def test_conv_concat_slice_scatter(self):
        paddle.seed(0)
        x = _t(np.random.default_rng(0).normal(size=(2, 5, 4)))
        c = snn.sequence_conv(x, 6, filter_size=3, name="sconv_ext")
        assert c.shape == [2, 5, 6]
        cc = snn.sequence_concat([x, x])
        assert cc.shape == [2, 10, 4]
        off = paddle.to_tensor(np.asarray([1, 0], np.int64))
        ln = paddle.to_tensor(np.asarray([2, 2], np.int64))
        sl = snn.sequence_slice(x, off, ln)
        assert sl.shape == [2, 2, 4]
        np.testing.assert_allclose(np.asarray(sl._value)[0],
                                   np.asarray(x._value)[0, 1:3])
        upd = _t(np.ones((2, 2, 4)))
        idx = paddle.to_tensor(np.asarray([[0, 2], [1, 3]], np.int64))
        sc = snn.sequence_scatter(x, idx, upd)
        np.testing.assert_allclose(
            np.asarray(sc._value)[0, 0],
            np.asarray(x._value)[0, 0] + 1)


class TestStaticRNN:
    def test_accumulator_rnn_matches_cumsum(self):
        """memory + update_memory thread state: a running-sum RNN equals
        cumsum along time."""
        x = _t(np.random.default_rng(0).normal(size=(2, 5, 3)))
        rnn = snn.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            acc = rnn.memory(shape=(3,), batch_ref=xt, init_value=0.0)
            new = acc + xt
            rnn.update_memory(acc, new)
            rnn.output(new)
        out = rnn()
        np.testing.assert_allclose(np.asarray(out._value),
                                   np.cumsum(np.asarray(x._value), 1),
                                   rtol=1e-6)

    def test_fc_rnn_trains(self):
        """A learned RNN cell through the fc scope: gradients reach the
        cell parameters via the replayed scan."""
        paddle.seed(0)
        x = _t(np.random.default_rng(0).normal(size=(4, 6, 5)))
        target = _t(np.random.default_rng(1).normal(size=(4, 6, 8)))

        def run():
            rnn = snn.StaticRNN()
            with rnn.step():
                xt = rnn.step_input(x)
                h = rnn.memory(shape=(8,), batch_ref=xt, init_value=0.0)
                import paddle_tpu.ops.manipulation as manip
                nh = snn.fc(manip.concat([xt, h], axis=-1), 8,
                            name="srnn_cell", activation="tanh")
                rnn.update_memory(h, nh)
                rnn.output(nh)
            return rnn()

        from paddle_tpu.static.nn import _LAYERS
        losses = []
        for i in range(12):
            out = run()
            loss = ((out - target) * (out - target)).mean()
            loss.backward()
            cell = _LAYERS["srnn_cell"]
            for p in cell.parameters():
                assert p.grad is not None
                p._value = p._value - 0.3 * p.grad._value
                p.grad = None
            losses.append(float(loss))
        # strictly decreasing every step: gradients reach the cell params
        assert all(b < a for a, b in zip(losses, losses[1:])), losses
        assert losses[-1] < losses[0] * 0.97, losses


class TestSequenceGradFlow:
    def test_sequence_ops_are_differentiable(self):
        """The sequence family must record on the tape — a pooled loss
        reaches the input (drive regression: outputs were detached)."""
        x = _t(np.random.default_rng(0).normal(size=(2, 4, 3)))
        x.stop_gradient = False
        pooled = snn.sequence_pool(snn.sequence_reverse(x), "average")
        loss = (pooled * pooled).mean()
        loss.backward()
        assert x.grad is not None
        assert np.abs(np.asarray(x.grad._value)).sum() > 0

    def test_sequence_conv_params_get_grads(self):
        paddle.seed(0)
        x = _t(np.random.default_rng(0).normal(size=(2, 5, 4)))
        out = snn.sequence_conv(x, 6, filter_size=3, name="sconv_grad")
        loss = (out * out).mean()
        loss.backward()
        from paddle_tpu.static.nn import _LAYERS
        w = _LAYERS["sconv_grad"].weight
        assert w.grad is not None
        assert np.abs(np.asarray(w.grad._value)).sum() > 0
