"""Long-tail tensor API: inplace variants, arrays, utilities (reference:
python/paddle/tensor/__init__.py inplace rows + fluid array ops)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_inplace_variants_rebind_value_and_graph():
    x = paddle.to_tensor(np.array([1.0, 4.0], np.float32))
    out = x.sqrt_()
    assert out is x
    np.testing.assert_allclose(np.asarray(x._value), [1.0, 2.0])
    x.add_(paddle.to_tensor(np.ones(2, np.float32)))
    np.testing.assert_allclose(np.asarray(x._value), [2.0, 3.0])
    x.clip_(0.0, 2.5)
    np.testing.assert_allclose(np.asarray(x._value), [2.0, 2.5])


def test_inplace_keeps_autograd_chain():
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    y = x * 3
    y.exp_()
    y.backward()
    np.testing.assert_allclose(np.asarray(x.grad._value),
                               [3 * np.exp(6.0)], rtol=1e-5)


def test_frexp_quantile_inverse():
    m, e = paddle.frexp(paddle.to_tensor(np.array([8.0, 0.5], np.float32)))
    np.testing.assert_allclose(np.asarray(m._value), [0.5, 0.5])
    np.testing.assert_allclose(np.asarray(e._value), [4.0, 0.0])
    q = paddle.quantile(paddle.to_tensor(np.arange(10, dtype=np.float32)),
                        0.5)
    assert float(q) == 4.5
    nq = paddle.nanquantile(paddle.to_tensor(
        np.array([1.0, np.nan, 3.0], np.float32)), 0.5)
    assert float(nq) == 2.0
    a = np.array([[2.0, 0.0], [0.0, 4.0]], np.float32)
    inv = paddle.inverse(paddle.to_tensor(a))
    np.testing.assert_allclose(np.asarray(inv._value),
                               np.linalg.inv(a), rtol=1e-6)


def test_attribute_utilities():
    x = paddle.to_tensor(np.zeros((3, 4), np.float32))
    assert int(paddle.numel(x)) == 12
    assert int(paddle.rank(x)) == 2
    assert paddle.is_floating_point(x)
    assert not paddle.is_integer(x)
    assert not paddle.is_complex(x)
    assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]


def test_reverse_vsplit():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(6, 1))
    r = paddle.reverse(x, axis=0)
    np.testing.assert_allclose(np.asarray(r._value).reshape(-1),
                               [5, 4, 3, 2, 1, 0])
    parts = paddle.vsplit(x, 3)
    assert [p.shape for p in parts] == [[2, 1]] * 3
    parts = paddle.vsplit(x, [2, 5])
    assert [p.shape for p in parts] == [[2, 1], [3, 1], [1, 1]]
    with pytest.raises(ValueError):
        paddle.vsplit(paddle.to_tensor(np.zeros(3, np.float32)), 3)


def test_shard_index():
    ids = paddle.to_tensor(np.array([0, 5, 9, 14], np.int64))
    local = paddle.shard_index(ids, index_num=16, nshards=2, shard_id=0)
    np.testing.assert_array_equal(np.asarray(local._value), [0, 5, -1, -1])
    local = paddle.shard_index(ids, index_num=16, nshards=2, shard_id=1)
    np.testing.assert_array_equal(np.asarray(local._value), [-1, -1, 1, 6])
    with pytest.raises(ValueError):
        paddle.shard_index(ids, 16, 2, 5)


def test_tensor_array_ops():
    arr = paddle.create_array()
    paddle.array_write(paddle.to_tensor(np.ones(2, np.float32)), 0, arr)
    paddle.array_write(paddle.to_tensor(np.zeros(3, np.float32)),
                       paddle.to_tensor(np.int64(2)), arr)
    assert int(paddle.array_length(arr)) == 3
    assert paddle.array_read(arr, 0).shape == [2]
    assert arr[1] is None
    assert paddle.array_read(arr, 2).shape == [3]


def test_inplace_on_grad_leaf_raises_but_no_grad_allowed():
    """Reference parity: mutating a leaf that requires grad in place is an
    error; the paddle.no_grad() parameter-update idiom works and keeps the
    leaf's requires-grad status."""
    x = paddle.to_tensor(np.array([5.0], np.float32), stop_gradient=False)
    with pytest.raises(RuntimeError, match="in-place"):
        x.exp_()
    for _ in range(30):
        y = (x * x).sum()
        y.backward()
        with paddle.no_grad():
            x.subtract_(paddle.to_tensor(0.1) * x.grad)
        x.grad = None
    assert abs(float(x)) < 0.02
    assert not x.stop_gradient
