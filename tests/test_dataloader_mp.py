"""Multiprocess DataLoader workers (reference analog:
fluid/dataloader/dataloader_iter.py _DataLoaderIterMultiProcess)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader
from paddle_tpu.io.dataset import Dataset


class _DS(Dataset):
    def __len__(self):
        return 23

    def __getitem__(self, i):
        return np.full((3,), i, np.float32), np.int64(i % 4)


def test_mp_workers_preserve_order_and_content():
    dl = DataLoader(_DS(), batch_size=4, num_workers=2)
    batches = list(dl)
    assert len(batches) == 6
    got = np.concatenate([np.asarray(b[0]._value)[:, 0] for b in batches])
    np.testing.assert_array_equal(got, np.arange(23))
    assert batches[0][1].shape == [4]


def test_mp_custom_collate_runs_in_parent():
    dl = DataLoader(_DS(), batch_size=4, num_workers=2,
                    collate_fn=lambda samples: len(samples))
    out = list(dl)
    assert out[:5] == [4, 4, 4, 4, 4] and out[5] == 3


def test_mp_worker_error_propagates():
    class Bad(_DS):
        def __getitem__(self, i):
            if i == 5:
                raise ValueError("boom")
            return super().__getitem__(i)

    with pytest.raises(RuntimeError, match="boom"):
        list(DataLoader(Bad(), batch_size=4, num_workers=2))


def test_mp_worker_init_fn_called():
    import multiprocessing
    marks = multiprocessing.get_context("fork").Queue()

    def init(worker_id):
        marks.put(worker_id)

    list(DataLoader(_DS(), batch_size=4, num_workers=2,
                    worker_init_fn=init))
    seen = {marks.get(timeout=5) for _ in range(2)}
    assert seen == {0, 1}


def test_mp_shuffle_covers_dataset():
    dl = DataLoader(_DS(), batch_size=4, shuffle=True, num_workers=2)
    got = np.sort(np.concatenate(
        [np.asarray(b[0]._value)[:, 0] for b in dl]))
    np.testing.assert_array_equal(got, np.arange(23))
