"""Multiprocess DataLoader workers (reference analog:
fluid/dataloader/dataloader_iter.py _DataLoaderIterMultiProcess).

Workers start from a forkserver, so datasets / worker_init_fn must be
picklable (module-level), exactly like the reference's spawn-capable
plumbing — and unlike a raw fork, no "multi-threaded process" fork warnings
may appear.
"""
import os
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader
from paddle_tpu.io.dataset import Dataset


class _DS(Dataset):
    def __len__(self):
        return 23

    def __getitem__(self, i):
        return np.full((3,), i, np.float32), np.int64(i % 4)


class _BadDS(_DS):
    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom")
        return super().__getitem__(i)


def _touch_marker(worker_id, directory):
    with open(os.path.join(directory, f"w{worker_id}"), "w") as f:
        f.write(str(worker_id))


class _InitFn:
    """Picklable worker_init_fn writing a per-worker marker file."""

    def __init__(self, directory):
        self.directory = directory

    def __call__(self, worker_id):
        _touch_marker(worker_id, self.directory)


def test_mp_workers_preserve_order_and_content():
    dl = DataLoader(_DS(), batch_size=4, num_workers=2)
    batches = list(dl)
    assert len(batches) == 6
    got = np.concatenate([np.asarray(b[0]._value)[:, 0] for b in batches])
    np.testing.assert_array_equal(got, np.arange(23))
    assert batches[0][1].shape == [4]


def test_mp_no_fork_warnings():
    # forking the multithreaded JAX parent would emit CPython's
    # "multi-threaded, use of fork() may lead to deadlocks" warning;
    # the forkserver path must be clean
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        list(DataLoader(_DS(), batch_size=4, num_workers=2))
    msgs = [str(w.message) for w in caught]
    assert not any("fork" in m and "thread" in m for m in msgs), msgs


def test_mp_custom_collate_runs_in_parent():
    dl = DataLoader(_DS(), batch_size=4, num_workers=2,
                    collate_fn=lambda samples: len(samples))
    out = list(dl)
    assert out[:5] == [4, 4, 4, 4, 4] and out[5] == 3


def test_mp_worker_error_propagates():
    with pytest.raises(RuntimeError, match="boom"):
        list(DataLoader(_BadDS(), batch_size=4, num_workers=2))


def test_mp_worker_init_fn_called(tmp_path):
    list(DataLoader(_DS(), batch_size=4, num_workers=2,
                    worker_init_fn=_InitFn(str(tmp_path))))
    seen = {f for f in os.listdir(str(tmp_path))}
    assert seen == {"w0", "w1"}


def test_mp_shuffle_covers_dataset():
    dl = DataLoader(_DS(), batch_size=4, shuffle=True, num_workers=2)
    got = np.sort(np.concatenate(
        [np.asarray(b[0]._value)[:, 0] for b in dl]))
    np.testing.assert_array_equal(got, np.arange(23))
