"""Persistent AOT executable cache (PR 9, ops/aot_cache.py).

Covers the warm-start contract end to end:
  * stable content addressing — structurally identical op keys digest
    identically (code objects by bytecode, fns by module:qualname,
    process-local ids erased); undigestable components opt out cleanly;
  * in-process warm round trip — with a populated store, clearing every
    compiled cache and re-running the same loop reloads per-op AND
    whole-step executables with ZERO fresh traces, and the step promotes
    at the FIRST clean boundary (`warm_start` promotion, min_count
    bypassed) — the restart path minus the process boundary;
  * durability — a corrupted artifact (bit flip or truncation) is
    detected, quarantined as *.corrupt, attributed `artifact_corrupt`,
    and transparently recompiled with identical numerics; version skew
    (a different environment fingerprint) is reported and never
    deserialized;
  * concurrent writers — two subprocesses racing `store()` on the SAME
    keys and on disjoint keys leave only complete, loadable artifacts
    (atomic tmp+fsync+rename; content addressing makes last-writer-wins
    correct);
  * size/age-bounded eviction + the `fusion_doctor --cache [--gc]`
    subcommand;
  * the serving decode step round-trips too: a second engine over the
    same model deserializes the decode program (decode_compiles == 0)
    and stays token-identical;
  * perf guard (perf_smoke marker): a fresh subprocess against a warm
    store reaches a promoted fused step with zero compile events and
    faster time-to-first-promoted-step than the cold subprocess.
"""
from __future__ import annotations

import glob
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops import aot_cache
from paddle_tpu.ops.dispatch import clear_dispatch_cache
from paddle_tpu.profiler import (aot_cache_stats, chain_fusion_stats,
                                 dispatch_cache_stats,
                                 reset_aot_cache_stats,
                                 reset_chain_fusion_stats,
                                 reset_dispatch_cache_stats,
                                 reset_step_fusion_stats,
                                 step_fusion_stats)
from paddle_tpu.profiler.events import clear_fusion_events, fusion_events

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")

_DEFAULT_FLAGS = {
    "FLAGS_aot_cache": False,
    "FLAGS_aot_cache_dir": "",
    "FLAGS_eager_op_cache": True,
    "FLAGS_eager_op_cache_size": 512,
    "FLAGS_eager_chain_fusion": True,
    "FLAGS_eager_chain_fusion_min_count": 3,
    "FLAGS_eager_step_fusion": True,
    "FLAGS_eager_step_fusion_min_count": 4,
    "FLAGS_profiler_events": False,
}


@pytest.fixture(autouse=True)
def _fresh():
    set_flags(dict(_DEFAULT_FLAGS))
    clear_dispatch_cache()
    clear_fusion_events()
    reset_dispatch_cache_stats()
    reset_chain_fusion_stats()
    reset_step_fusion_stats()
    reset_aot_cache_stats()
    yield
    set_flags(dict(_DEFAULT_FLAGS))
    clear_dispatch_cache()
    clear_fusion_events()
    reset_dispatch_cache_stats()
    reset_chain_fusion_stats()
    reset_step_fusion_stats()
    reset_aot_cache_stats()


def _arm(tmp_path):
    set_flags({"FLAGS_aot_cache": True,
               "FLAGS_aot_cache_dir": str(tmp_path),
               "FLAGS_profiler_events": True})


def _make_state(seed=0):
    rng = np.random.default_rng(seed)
    x = paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32),
                         stop_gradient=False)
    b = paddle.to_tensor(rng.standard_normal(8).astype(np.float32),
                         stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=1e-2, parameters=[w, b])
    return x, w, b, opt


def _loop(state, n):
    x, w, b, opt = state
    opt.clear_grad()
    losses = []
    for _ in range(n):
        loss = F.gelu(paddle.add(paddle.matmul(x, w), b)).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def _clear_compiled():
    """Drop every in-process compiled executable (dispatch LRU, chains,
    promoted steps) WITHOUT touching the on-disk store — the in-process
    analog of a process restart."""
    clear_dispatch_cache()
    clear_fusion_events()
    reset_dispatch_cache_stats()
    reset_chain_fusion_stats()
    reset_step_fusion_stats()
    reset_aot_cache_stats()


def _events(cat):
    return [e for e in fusion_events() if e["cat"] == cat]


# ---------------------------------------------------------------------------
# content addressing
# ---------------------------------------------------------------------------

class TestDigests:
    def test_structurally_equal_keys_digest_identically(self):
        def make_key(scale):
            fn = lambda a, b: a * scale + b          # noqa: E731
            from paddle_tpu.ops.dispatch import _fn_token
            ftok = _fn_token(fn)
            avals = (((4, 8), np.dtype(np.float32), False),)
            return ("mul_add", ftok, avals, (True,), None, (None, 0),
                    False)
        # two closures from the same code with the same cell value are one
        # artifact; a different constant is a different artifact; the
        # registry GENERATION (process-local) must not matter
        d1 = aot_cache.op_key_digest(make_key(2.0))
        d2 = aot_cache.op_key_digest(make_key(2.0))
        d3 = aot_cache.op_key_digest(make_key(3.0))
        assert d1 == d2 and d1 is not None
        assert d3 != d1
        k = make_key(2.0)
        bumped = k[:5] + ((None, 7),) + k[5 + 1:]
        assert aot_cache.op_key_digest(bumped) == d1

    def test_undigestable_key_opts_out(self):
        key = ("weird", object(), (), None, None, (None, 0), False)
        assert aot_cache.op_key_digest(key) is None

    def test_fingerprint_changes_filename(self, tmp_path):
        _arm(tmp_path)
        fp = aot_cache.fingerprint_digest()
        assert fp in os.path.basename(
            aot_cache._artifact_path("op", "ab" * 20))


# ---------------------------------------------------------------------------
# warm round trip (the restart path minus the process boundary)
# ---------------------------------------------------------------------------

class TestWarmRoundTrip:
    def test_zero_retrace_warm_start_with_first_boundary_promotion(
            self, tmp_path):
        _arm(tmp_path)
        state = _make_state()
        _loop(state, 8)
        assert step_fusion_stats()["steps_promoted"] == 1
        assert aot_cache_stats()["stores"] >= 5   # 4 ops + step (+ chain)
        kinds = {os.path.basename(p).split("-")[0]
                 for p in glob.glob(str(tmp_path / "*.aot"))}
        assert {"op", "step"} <= kinds

        # "restart": same live objects, every compiled cache dropped
        _clear_compiled()
        _loop(state, 3)
        d, s, a = (dispatch_cache_stats(), step_fusion_stats(),
                   aot_cache_stats())
        assert d["retraces"] == 0, "warm per-op path traced"
        assert s["retraces"] == 0, "warm whole-step path traced"
        assert chain_fusion_stats()["retraces"] == 0
        assert a["hits"] >= 5 and a["misses"] == 0
        # promoted at the FIRST boundary (min_count 4 bypassed), fired on
        # the second cycle
        assert s["steps_promoted"] == 1 and s["fused_steps"] >= 2
        promo = _events("step.promote")
        assert promo and promo[0]["detail"]["warm_start"] is True
        assert not _events("dispatch.retrace")
        assert not _events("chain.compile")

    def test_warm_trajectory_matches_cold(self, tmp_path):
        _arm(tmp_path)
        ref = _loop(_make_state(), 8)
        _clear_compiled()
        paddle.seed(0)
        warm = _loop(_make_state(), 8)
        # fresh params re-derive the same trajectory through restored
        # executables; the restored ONE-program step may differ from the
        # cold build in the last ULP (the PR 3 layout contract)
        np.testing.assert_allclose(ref, warm, rtol=0, atol=1e-5)

    def test_disabled_flag_means_no_store_io(self, tmp_path):
        set_flags({"FLAGS_aot_cache_dir": str(tmp_path)})
        _loop(_make_state(), 6)
        assert not os.path.exists(str(tmp_path)) \
            or not os.listdir(str(tmp_path))
        assert aot_cache_stats()["stores"] == 0


# ---------------------------------------------------------------------------
# durability: corruption, torn writes, version skew
# ---------------------------------------------------------------------------

class TestDurability:
    def _populate(self, tmp_path, seed=0):
        _arm(tmp_path)
        ref = _loop(_make_state(seed), 8)
        return ref

    def test_bitflip_quarantines_and_recompiles(self, tmp_path):
        ref = self._populate(tmp_path)
        for p in glob.glob(str(tmp_path / "*.aot")):
            data = bytearray(open(p, "rb").read())
            data[len(data) // 2] ^= 0xFF
            open(p, "wb").write(data)
        _clear_compiled()
        paddle.seed(0)
        res = _loop(_make_state(), 8)
        a = aot_cache_stats()
        assert a["corrupt"] >= 4 and a["hits"] == 0
        assert glob.glob(str(tmp_path / "*.corrupt"))
        ev = _events("aot.corrupt")
        assert ev and all(e["reason"] == "artifact_corrupt" for e in ev)
        np.testing.assert_allclose(ref, res, rtol=0, atol=1e-5)
        # the recompiled executables re-stored fresh artifacts
        assert aot_cache_stats()["stores"] >= 4

    def test_truncated_artifact_is_corrupt_not_fatal(self, tmp_path):
        self._populate(tmp_path)
        victim = sorted(glob.glob(str(tmp_path / "op-*.aot")))[0]
        data = open(victim, "rb").read()
        open(victim, "wb").write(data[:len(data) // 2])   # torn write
        _clear_compiled()
        paddle.seed(0)
        _loop(_make_state(), 4)
        assert aot_cache_stats()["corrupt"] >= 1
        assert os.path.exists(victim + ".corrupt")

    def test_version_skew_reported_never_deserialized(self, tmp_path):
        self._populate(tmp_path)
        # a worker on a different jax: same key digests, different
        # fingerprint -> exact filename misses, the foreign artifact is
        # reported as skew and left for its own environment
        old_fp = dict(aot_cache.env_fingerprint())
        try:
            aot_cache._fp_cache = {**old_fp, "jax": "99.99.99"}
            aot_cache._fp_digest_cache = None      # re-derive the digest
            aot_cache._skew_scan = (0.0, None, frozenset())
            _clear_compiled()
            paddle.seed(0)
            _loop(_make_state(), 4)
            a = aot_cache_stats()
            assert a["hits"] == 0 and a["version_skew"] >= 1
            ev = _events("aot.version_skew")
            assert ev and all(e["reason"] == "version_skew" for e in ev)
        finally:
            aot_cache._fp_cache = old_fp
            aot_cache._fp_digest_cache = None
            aot_cache._skew_scan = (0.0, None, frozenset())
        # the original artifacts are untouched (not quarantined)
        assert not glob.glob(str(tmp_path / "*.corrupt"))


# ---------------------------------------------------------------------------
# eviction + doctor CLI
# ---------------------------------------------------------------------------

class TestEvictionAndDoctor:
    def test_size_bounded_eviction_oldest_first(self, tmp_path):
        _arm(tmp_path)
        for i in range(4):
            aot_cache.store_artifact("op", f"{i:02d}" * 20, f"fake{i}",
                                     [b"x" * 1024])
            os.utime(aot_cache._artifact_path("op", f"{i:02d}" * 20),
                     (1000 + i, 1000 + i))
        sizes = [os.path.getsize(p)
                 for p in glob.glob(str(tmp_path / "*.aot"))]
        budget = sum(sizes) - 2 * max(sizes) + 1   # forces out exactly 2
        removed = aot_cache.gc_store(str(tmp_path), max_bytes=budget,
                                     max_age_s=0)
        assert len(removed) == 2
        left = {os.path.basename(p).split("-")[1]
                for p in glob.glob(str(tmp_path / "*.aot"))}
        assert left == {"02" * 20, "03" * 20}   # oldest two evicted
        assert aot_cache_stats()["evictions"] == 2

    def test_age_bound_quarantine_and_stale_tmp(self, tmp_path):
        _arm(tmp_path)
        aot_cache.store_artifact("op", "aa" * 20, "old", [b"x"])
        p = aot_cache._artifact_path("op", "aa" * 20)
        os.utime(p, (1, 1))
        open(str(tmp_path / "op-dead-beef.aot.corrupt"), "wb").write(b"?")
        stale_tmp = str(tmp_path / "op-dead-beef.aot.tmp.123")
        open(stale_tmp, "wb").write(b"?")
        os.utime(stale_tmp, (1, 1))
        fresh_tmp = str(tmp_path / "op-cafe-f00d.aot.tmp.456")
        open(fresh_tmp, "wb").write(b"?")      # an in-flight writer
        removed = aot_cache.gc_store(str(tmp_path), max_bytes=0,
                                     max_age_s=3600)
        # over-age artifact + kill-9'd writer's stale tmp go; the FRESH
        # quarantine survives the automatic sweep (the doctor must still
        # be able to list it), as does the in-flight tmp
        assert sorted(removed) == ["op-aaaaaaaaaaaaaaaaaaaaaaaaaaaaaaaa"
                                   "aaaaaaaa-"
                                   + aot_cache.fingerprint_digest()
                                   + ".aot",
                                   "op-dead-beef.aot.tmp.123"]
        assert os.path.exists(fresh_tmp)
        # the explicit --gc path purges quarantines immediately
        removed = aot_cache.gc_store(str(tmp_path), max_bytes=0,
                                     max_age_s=3600,
                                     purge_quarantine=True)
        assert removed == ["op-dead-beef.aot.corrupt"]

    def test_doctor_cache_subcommand(self, tmp_path, capsys):
        _arm(tmp_path)
        _loop(_make_state(), 6)
        victim = sorted(glob.glob(str(tmp_path / "op-*.aot")))[0]
        open(victim, "ab").write(b"junk")        # break its trailer
        sys.path.insert(0, _TOOLS)
        try:
            import fusion_doctor
            rc = fusion_doctor.main(["--cache", "--cache-dir",
                                     str(tmp_path)])
            out = capsys.readouterr().out
            assert rc == 0
            assert "AOT executable store" in out
            assert "CORRUPT" in out and " ok" in out
            rc = fusion_doctor.main(["--cache", "--cache-dir",
                                     str(tmp_path), "--gc", "--json"])
            rep = json.loads(capsys.readouterr().out)
            assert rc == 0
        finally:
            sys.path.remove(_TOOLS)
        # --gc leaves only intact artifacts behind
        assert all(not e["corrupt"] and not e["quarantined"]
                   for e in rep["entries"])


# ---------------------------------------------------------------------------
# concurrent multi-process writers (satellite)
# ---------------------------------------------------------------------------

_CHILD_SRC = r"""
import os, sys
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.flags import set_flags

set_flags({"FLAGS_aot_cache": True,
           "FLAGS_aot_cache_dir": sys.argv[1],
           "FLAGS_eager_chain_fusion_min_count": 3,
           "FLAGS_eager_step_fusion_min_count": 4})
dim = int(sys.argv[2])
paddle.seed(0)
rng = np.random.default_rng(0)
x = paddle.to_tensor(rng.standard_normal((4, dim)).astype(np.float32))
w = paddle.to_tensor(rng.standard_normal((dim, dim)).astype(np.float32),
                     stop_gradient=False)
b = paddle.to_tensor(rng.standard_normal(dim).astype(np.float32),
                     stop_gradient=False)
opt = paddle.optimizer.SGD(learning_rate=1e-2, parameters=[w, b])
opt.clear_grad()
for _ in range(7):
    loss = F.gelu(paddle.add(paddle.matmul(x, w), b)).sum()
    loss.backward(); opt.step(); opt.clear_grad()
print("DONE", float(loss))
"""


class TestConcurrentWriters:
    def _spawn(self, store, dim):
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        return subprocess.Popen(
            [sys.executable, "-c", _CHILD_SRC, str(store), str(dim)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)

    def test_same_and_disjoint_key_races(self, tmp_path):
        store = tmp_path / "store"
        # two writers on the SAME keys (dim 8) + one on disjoint keys
        # (dim 16), all racing the same directory
        procs = [self._spawn(store, 8), self._spawn(store, 8),
                 self._spawn(store, 16)]
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, err[-800:]
            assert "DONE" in out
        # no torn files: every artifact verifies (CRC + envelope), no
        # quarantines, and both key families are present exactly once
        entries = aot_cache.store_entries(str(store), verify=True)
        assert entries
        assert all(not e["corrupt"] and not e["quarantined"]
                   for e in entries)
        step_arts = [e for e in entries if e["kind"] == "step"]
        assert len(step_arts) == 2   # one per dim — no lost entries
        # ...and a warm reader actually loads the per-op artifacts with
        # zero traces. (The STEP artifact only matches from a fresh
        # process: its digest includes the auto-generated parameter
        # names, which this long-lived pytest process has already
        # advanced past — the chaos warm_restart scenario proves the
        # cross-process step path.)
        _arm(store)
        paddle.seed(0)
        _loop(_make_state_dim(8), 3)
        assert aot_cache_stats()["hits"] >= 4
        assert dispatch_cache_stats()["retraces"] == 0


class TestSharedStoreFleet:
    """Cross-host shared-store contracts the elastic fabric leans on
    (distributed/fabric.py): store-if-absent races on one key converge
    to a single loadable artifact, every artifact records which host
    exported it, and a stored lowering that does not match the live
    program's calling convention is a MISS — never a quarantine of a
    healthy artifact (the plain-jit vs shard_map aliasing a probation
    demotion can create under one step digest)."""

    @staticmethod
    def _blob():
        import jax
        import jax.numpy as jnp
        f = jax.jit(lambda a: a * 2.0)
        return f, aot_cache.export_bytes(
            f, (jax.ShapeDtypeStruct((4,), jnp.float32),))

    def test_same_key_race_converges_with_host_provenance(self, tmp_path):
        import socket
        import threading
        _arm(tmp_path)
        _, blob = self._blob()
        digest = "f" * 40
        errors, results = [], []

        def writer():
            try:
                results.append(aot_cache.store_artifact(
                    "step", digest, "race", [blob], meta={"spmd": False}))
            except Exception as e:          # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=writer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # no raise, ever. A loser of the tmp-file race reports False
        # (accounted store_failure) — it must never tear the artifact
        assert not errors
        assert any(results)
        entries = [e for e in aot_cache.store_entries(str(tmp_path))
                   if e["kind"] == "step"]
        assert len(entries) == 1            # same key -> ONE file
        e = entries[0]
        assert not e["corrupt"] and not e["quarantined"]
        assert e["host"] == socket.gethostname()
        art = aot_cache.load_artifact("step", digest, "race")
        assert bytes(art["blobs"][0]) == bytes(blob)
        assert art["host"] == socket.gethostname()

    def test_lowering_mismatch_is_miss_not_quarantine(self, tmp_path):
        _arm(tmp_path)
        f, blob = self._blob()
        digest = "e" * 40
        assert aot_cache.store_artifact("step", digest, "mm", [blob],
                                        meta={"spmd": False})
        m0 = aot_cache_stats()["misses"]
        got = aot_cache.load_callable(
            "step", digest, "mm", fallback=lambda: f,
            accept=lambda meta: bool(meta.get("spmd")))
        assert got is None
        assert aot_cache_stats()["misses"] == m0 + 1
        assert aot_cache_stats()["corrupt"] == 0
        misses = [ev for ev in _events("aot.miss")
                  if ev["detail"].get("why") == "lowering_mismatch"]
        assert misses and misses[-1]["detail"]["digest"] == digest[:12]
        # the artifact survives untouched and a MATCHING caller loads it
        entries = [e for e in aot_cache.store_entries(str(tmp_path))
                   if e["kind"] == "step"]
        assert len(entries) == 1 and not entries[0]["quarantined"]
        got2 = aot_cache.load_callable(
            "step", digest, "mm", fallback=lambda: f,
            accept=lambda meta: not meta.get("spmd"))
        assert got2 is not None
        out = got2(np.full((4,), 3.0, np.float32))
        assert np.allclose(np.asarray(out), 6.0)


def _make_state_dim(dim):
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((4, dim)).astype(np.float32))
    w = paddle.to_tensor(
        rng.standard_normal((dim, dim)).astype(np.float32),
        stop_gradient=False)
    b = paddle.to_tensor(rng.standard_normal(dim).astype(np.float32),
                         stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=1e-2, parameters=[w, b])
    return x, w, b, opt


# ---------------------------------------------------------------------------
# serving decode warm start
# ---------------------------------------------------------------------------

class TestServingDecode:
    def test_decode_round_trip_token_identical(self, tmp_path):
        from paddle_tpu.incubate.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.serving import LLMEngine

        _arm(tmp_path)
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=16,
                        num_hidden_layers=2, num_attention_heads=2,
                        intermediate_size=32,
                        max_position_embeddings=32,
                        hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0,
                        use_flash_attention=False)
        model = GPTForCausalLM(cfg)
        model.eval()
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 64, n).tolist() for n in (5, 7)]

        eng_a = LLMEngine(model, max_batch_size=2, block_size=4)
        ref = eng_a.generate(prompts, max_new_tokens=6)
        # exactly ONE trace even while storing: jax.export reuses the
        # jit's cached trace for the already-seen avals
        assert eng_a.stats()["decode_compiles"] == 1
        assert any(os.path.basename(p).startswith("decode-")
                   for p in glob.glob(str(tmp_path / "*.aot")))

        reset_aot_cache_stats()
        eng_b = LLMEngine(model, max_batch_size=2, block_size=4)
        out = eng_b.generate(prompts, max_new_tokens=6)
        assert eng_b.stats()["decode_compiles"] == 0, \
            "warm engine traced decode"
        assert aot_cache_stats()["hits"] >= 1
        assert out == ref


# ---------------------------------------------------------------------------
# perf guard: warm subprocess beats cold (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.perf_smoke
def test_warm_start_subprocess_beats_cold(tmp_path):
    """The perf_smoke leg as a pytest: a fresh subprocess against a warm
    store must fire a promoted fused step with ZERO compile activity and
    not be slower to its first fused fire than the cold subprocess that
    populated the store (the CLI leg guards the sharper 0.85 ratio)."""
    child = os.path.join(_TOOLS, "perf_smoke.py")
    store = str(tmp_path / "store")

    def run(tag):
        out = str(tmp_path / f"{tag}.json")
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        r = subprocess.run(
            [sys.executable, child, "--aot-child", "--aot-dir", store,
             "--out", out], capture_output=True, text=True, timeout=300,
            env=env)
        assert r.returncode == 0, r.stderr[-800:]
        with open(out) as f:
            return json.load(f)

    cold = run("cold")
    assert cold["fused_steps"] > 0 and cold["aot"]["stores"] > 0
    warm = min((run(f"warm{i}") for i in range(2)),
               key=lambda r: r["t_first_fire_s"] or 1e9)
    assert warm["fused_steps"] > 0
    assert warm["dispatch_retraces"] == 0
    assert warm["chain_retraces"] == 0
    assert warm["step_retraces"] == 0
    assert warm["aot"]["hits"] >= 5 and warm["aot"]["misses"] == 0
    assert warm["t_first_fire_s"] <= cold["t_first_fire_s"], \
        (warm, cold)
