"""Multi-tenant serving suite (PR 17, paddle_tpu/serving/tenancy.py).

The contracts pinned here are the ISSUE 17 acceptance criteria:

  * N streams sharing a prompt prefix pay its prefill ONCE and its KV
    bytes once (refcounted aliasing), and every stream's greedy output
    stays token-identical to `model.generate` — including the stream
    that diverges mid-block and triggers copy-on-write;
  * admission accounting (`can_ever_fit`, the watermark check) counts a
    refcounted block once, before AND after aliasing — the PR 17 bugfix;
  * per-tenant LoRA-style adapters are VALUE inputs to the ONE compiled
    decode executable: base tenants are bit-identical to the
    adapter-free engine, tenant churn never recompiles, unknown
    adapters are refused (`adapter_mismatch`), and a live tenant's slot
    cannot be unregistered out from under it;
  * live weight hot-swap is a byte-exact cutover at an iteration
    boundary (zero recompiles), a crash snapshot taken under one weight
    set refuses to restore under another (`torn_swap`), and staging the
    byte-identical set is a no-op.

Prefix-cache and allocator unit tests are pure host-side (no jax work).
"""
from __future__ import annotations

import os

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import paddle_tpu as paddle
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.incubate.models import GPTConfig, GPTForCausalLM
from paddle_tpu.profiler.events import clear_fusion_events, fusion_events
from paddle_tpu.serving import (BlockAllocator, LLMEngine, Request,
                                Scheduler, ServeRefusal, NULL_BLOCK,
                                PrefixCache, AdapterSet, FINISHED)

VOCAB = 128


def _make_model(seed=0):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=64,
                    max_position_embeddings=64, hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _make_model(seed=0)


def _prompt(length, seed=0):
    rng = np.random.default_rng(seed * 1000 + length)
    return rng.integers(0, VOCAB, length).tolist()


def _gen(model, prompt, n):
    out = model.generate(paddle.Tensor(np.asarray([prompt], np.int64)),
                         max_new_tokens=n, do_sample=False)
    arr = out._value if hasattr(out, "_value") else out
    return np.asarray(arr)[0].tolist()


_REF_CACHE = {}


def _ref(model, prompt, n):
    key = (id(model), tuple(prompt), n)
    if key not in _REF_CACHE:
        _REF_CACHE[key] = _gen(model, prompt, n)
    return _REF_CACHE[key]


def _shared_prompts(n_prompts, prefix_len=12, suffix_len=3, seed=7):
    """n prompts sharing a `prefix_len`-token prefix, distinct tails."""
    rng = np.random.default_rng(seed)
    prefix = rng.integers(0, VOCAB, prefix_len).tolist()
    return [prefix + rng.integers(0, VOCAB, suffix_len).tolist()
            for _ in range(n_prompts)]


# ---------------------------------------------------------------------------
# refcounted block allocator (pure host-side)
# ---------------------------------------------------------------------------

class TestRefcountedAllocator:
    def test_incref_free_lifecycle(self):
        alloc = BlockAllocator(4)                 # capacity 3
        a, b = alloc.allocate(2)
        assert alloc.num_free == 1
        assert alloc.refcount(a) == 1
        alloc.incref(a)
        # a shared block counts ONCE in the free-block math
        assert alloc.refcount(a) == 2
        assert alloc.num_free == 1
        assert alloc.num_shared == 1
        alloc.free([a])                           # decref: still resident
        assert alloc.refcount(a) == 1
        assert alloc.num_free == 1
        assert alloc.num_shared == 0
        alloc.free([a, b])                        # last refs: back to pool
        assert alloc.num_free == 3
        assert alloc.refcount(a) == 0

    def test_incref_and_free_guard_null_and_unallocated(self):
        alloc = BlockAllocator(4)
        with pytest.raises(ValueError):
            alloc.incref(NULL_BLOCK)
        with pytest.raises(ValueError):
            alloc.incref(2)                       # never allocated
        with pytest.raises(ValueError):
            alloc.free([2])

    def test_all_or_nothing_allocation_unchanged(self):
        alloc = BlockAllocator(4)
        got = alloc.allocate(2)
        alloc.incref(got[0])
        assert alloc.allocate(2) is None          # only 1 truly free
        assert alloc.num_free == 1                # probe did not leak


# ---------------------------------------------------------------------------
# prefix cache index (pure host-side)
# ---------------------------------------------------------------------------

class TestPrefixCacheUnit:
    def _setup(self, num_blocks=16, block_size=4):
        alloc = BlockAllocator(num_blocks)
        return PrefixCache(alloc, block_size), alloc

    def test_publish_acquire_roundtrip_and_len_minus_one_cap(self):
        pc, alloc = self._setup()
        toks = list(range(10))                    # 2 full blocks + tail 2
        blocks = alloc.allocate(3)
        assert pc.publish(toks, blocks) == 3
        # the index holds its own reference on every published block
        assert all(alloc.refcount(b) == 2 for b in blocks)
        # identical prompt: the hit caps at len-1 (one input token must
        # remain so the DECODE step emits the first token)
        shared, hit = pc.probe(toks)
        assert (shared, hit) == (3, 9)
        got, hit = pc.acquire(toks)
        assert got == list(blocks) and hit == 9
        assert all(alloc.refcount(b) == 3 for b in blocks)
        assert pc.hits == 1
        alloc.free(got)                           # caller undo

    def test_partial_match_inside_full_block(self):
        pc, alloc = self._setup()
        toks = list(range(12))                    # 3 full blocks
        blocks = alloc.allocate(3)
        pc.publish(toks, blocks)
        # shares 1 full block + 2 tokens of the second block
        other = toks[:6] + [99, 98, 97, 96]
        got, hit = pc.acquire(other)
        assert hit == 6 and got == list(blocks[:2])
        alloc.free(got)

    def test_sub_block_hit_unusable_unless_whole_prompt(self):
        pc, alloc = self._setup()
        toks = list(range(12))
        pc.publish(toks, alloc.allocate(3))
        # 2 shared tokens < block_size and < len-1: not worth the chew
        assert pc.acquire(toks[:2] + [99] * 8) == ([], 0)
        assert pc.misses == 1
        # ...but a 2-token hit covering the whole cacheable prompt is
        assert pc.acquire(toks[:3])[1] == 2

    def test_reclaim_is_leaf_first_lru(self):
        pc, alloc = self._setup(num_blocks=8)     # capacity 7
        a = list(range(8))                        # chain of 2
        b = [50, 51, 52, 53]                      # chain of 1
        for toks, n in ((a, 2), (b, 1)):
            blocks = alloc.allocate(n)
            pc.publish(toks, blocks)
            alloc.free(blocks)                    # publisher finished:
        assert alloc.num_free == 4                # the index is sole owner
        # a's leaf is older than b's, but touch a so b becomes coldest
        got, _ = pc.acquire(a + [99])
        alloc.free(got)
        dropped = pc.reclaim(5)
        assert dropped == 1 and alloc.num_free == 5
        assert pc.acquire(b + [99]) == ([], 0)    # b was evicted
        assert pc.acquire(a + [99])[1] == 8       # a's chain survives
        # a's ROOT block is never dropped while its child entry lives
        pc.reclaim(6)
        shared, _ = pc.probe(a + [99])
        assert shared in (0, 1)

    def test_reclaim_popularity_beats_recency(self):
        """PR 18 aging eviction: a plain LRU would evict the OLDEST
        entry; the aged-hit-count policy evicts the LEAST POPULAR one,
        so a cold tenant's recent burst cannot rotate out the hot
        shared system prompt."""
        pc, alloc = self._setup(num_blocks=8)
        hot = [10, 11, 12, 13]
        cold = [20, 21, 22, 23]
        for toks in (hot, cold):
            blocks = alloc.allocate(1)
            pc.publish(toks, blocks)
            alloc.free(blocks)
        for _ in range(3):                        # hot: 3 hits, old ticks
            got, _ = pc.acquire(hot + [99])
            alloc.free(got)
        got, _ = pc.acquire(cold + [99])          # cold: 1 hit, NEWEST tick
        alloc.free(got)
        assert pc.reclaim(alloc.num_free + 1) == 1
        assert pc.acquire(cold + [99]) == ([], 0)  # recency didn't save it
        got, hit = pc.acquire(hot + [99])
        assert hit == 4                            # popularity did
        alloc.free(got)

    def test_reclaim_hit_tie_breaks_on_recency(self):
        pc, alloc = self._setup(num_blocks=8)
        first = [10, 11, 12, 13]
        second = [20, 21, 22, 23]
        for toks in (first, second):
            blocks = alloc.allocate(1)
            pc.publish(toks, blocks)
            alloc.free(blocks)
        for toks in (first, second):              # one hit each, in order
            got, _ = pc.acquire(toks + [99])
            alloc.free(got)
        assert pc.reclaim(alloc.num_free + 1) == 1
        assert pc.acquire(first + [99]) == ([], 0)  # older tick loses
        assert pc.acquire(second + [99])[1] == 4

    def test_aging_decays_stale_popularity(self):
        """Hit counts halve every _AGE_PERIOD lookups: an entry hot last
        epoch but cold now loses its eviction immunity to a recently
        used neighbor."""
        from paddle_tpu.serving.tenancy import _AGE_PERIOD
        pc, alloc = self._setup(num_blocks=8)
        stale = [10, 11, 12, 13]
        blocks = alloc.allocate(1)
        pc.publish(stale, blocks)
        alloc.free(blocks)
        for _ in range(4):                        # hot... for now
            got, _ = pc.acquire(stale + [99])
            alloc.free(got)
        for i in range(2 * _AGE_PERIOD):          # two epochs of misses:
            pc.acquire([70 + (i % 8), 1, 2, 3, 4])  # 4 hits decay to 1
        fresh = [20, 21, 22, 23]
        blocks = alloc.allocate(1)
        pc.publish(fresh, blocks)
        alloc.free(blocks)
        got, _ = pc.acquire(fresh + [99])         # 1 hit, newest tick
        alloc.free(got)
        # decayed tie (1 == 1): the stale entry's OLD tick evicts it —
        # without decay its 4 early hits would have been immunity forever
        assert pc.reclaim(alloc.num_free + 1) == 1
        assert pc.acquire(stale + [99]) == ([], 0)
        assert pc.acquire(fresh + [99])[1] == 4

    def test_reclaim_never_drops_pinned_interior(self):
        """A popular leaf cannot force eviction of its own chain's
        interior blocks: victims are leaves only, however cold the
        interior entry's own counters look."""
        pc, alloc = self._setup(num_blocks=8)
        chain = list(range(12))                   # 3 full blocks
        blocks = alloc.allocate(3)
        pc.publish(chain, blocks)
        alloc.free(blocks)
        assert pc.reclaim(alloc.num_free + 1) == 1  # only the leaf goes
        got, hit = pc.acquire(chain + [99])
        assert hit == 8                           # interior chain intact
        alloc.free(got)

    def test_invalidate_frees_reset_forgets(self):
        pc, alloc = self._setup()
        blocks = alloc.allocate(2)
        pc.publish(list(range(8)), blocks)
        alloc.free(blocks)                        # publisher finished
        assert pc.invalidate() == 2
        assert alloc.num_free == alloc.capacity   # index refs released
        blocks = alloc.allocate(2)
        pc.publish(list(range(8)), blocks)
        new_alloc = BlockAllocator(16)
        pc.reset(new_alloc)                       # forget, do NOT free
        assert pc.entries == 0
        assert all(alloc.refcount(b) == 2 for b in blocks)
        assert pc.allocator is new_alloc


# ---------------------------------------------------------------------------
# refcount-aware admission accounting (the PR 17 bugfix satellite)
# ---------------------------------------------------------------------------

class TestAliasedAdmission:
    def _sched(self, num_blocks=9, block_size=4, watermark=1,
               num_slots=2):
        alloc = BlockAllocator(num_blocks)
        return Scheduler(num_slots, alloc, block_size,
                         watermark_blocks=watermark), alloc

    def test_can_ever_fit_counts_shared_blocks_once(self):
        sched, _ = self._sched(num_blocks=9, watermark=1)  # budget 7
        req = Request("r", list(range(30)), 4)    # peak 9 blocks
        assert not sched.can_ever_fit(req)        # pre-aliasing: refused
        # post-aliasing: 2 blocks ride the shared prefix -> 7 <= 7
        assert sched.can_ever_fit(req, shared_blocks=2)

    def test_try_admit_watermark_counts_aliased_blocks_once(self):
        sched, alloc = self._sched(num_blocks=9, watermark=2)
        cached = alloc.allocate(3)                # the "published prefix"
        req = Request("r", list(range(20)), 2)    # ctx 20 -> 6 blocks
        sched.enqueue(req)

        def hook(r):
            for b in cached:
                alloc.incref(b)
            return list(cached), 12

        got = sched.try_admit(prefix_hook=hook)
        # pre-fix math would want 6 fresh of 5 free and refuse; aliasing
        # needs only 3 fresh, leaving exactly the watermark
        assert got is req
        assert req.blocks[:3] == list(cached) and len(req.blocks) == 6
        assert req.prefix_hit == 12
        assert alloc.num_shared == 3
        assert alloc.num_free == sched.watermark_blocks

    def test_failed_admission_releases_the_hooks_claim(self):
        sched, alloc = self._sched(num_blocks=9, watermark=5)
        cached = alloc.allocate(3)
        sched2 = None  # silence lint about unused
        req = Request("r", list(range(20)), 2)
        sched.enqueue(req)

        def hook(r):
            for b in cached:
                alloc.incref(b)
            return list(cached), 12

        # needs 3 fresh of 5 free, watermark 5: refused -> undo increfs
        assert sched.try_admit(prefix_hook=hook) is None
        assert all(alloc.refcount(b) == 1 for b in cached)
        assert alloc.num_shared == 0
        assert req.blocks == [] and sched.waiting == [req]

    def _enqueue(self, sched, req):
        sched.enqueue(req)
        return req

    def test_try_admit_watermark_hook_path_enqueued(self):
        # same as above but through the normal enqueue/admit flow
        sched, alloc = self._sched(num_blocks=9, watermark=2)
        cached = alloc.allocate(3)
        req = self._enqueue(sched, Request("r", list(range(20)), 2))

        def hook(r):
            for b in cached:
                alloc.incref(b)
            return list(cached), 12

        assert sched.try_admit(prefix_hook=hook) is req
        # eviction decrefs: shared blocks stay resident for the cache
        sched.preempt(req)
        assert all(alloc.refcount(b) == 1 for b in cached)
        assert alloc.num_free == 5                # only the 3 fresh ones


# ---------------------------------------------------------------------------
# shared-prefix serving (compiled path)
# ---------------------------------------------------------------------------

class TestPrefixServing:
    def test_shared_prefix_one_prefill_token_identical(self, model):
        """Four streams share a 12-token prefix: ONE prefill total, and
        every stream's greedy output matches per-stream generate —
        including through the copy-on-write divergence."""
        prompts = _shared_prompts(4, prefix_len=12, suffix_len=3)
        engine = LLMEngine(model, max_batch_size=4, block_size=4,
                           num_blocks=64, enable_prefix_cache=True)
        clear_fusion_events()
        set_flags({"FLAGS_profiler_events": True})
        try:
            outs = engine.generate(prompts, max_new_tokens=8)
            ev = fusion_events()
        finally:
            set_flags({"FLAGS_profiler_events": False})
        for p, o in zip(prompts, outs):
            assert o == _ref(model, p, 8)
        st = engine.stats()
        assert st["prefills"] == 1                # N sharers, one prefill
        assert st["decode_compiles"] == 1
        assert st["prefix_hit_tokens"] > 0
        assert 0.0 < st["prefix_hit_rate"] <= 1.0
        assert st["cow_copies"] >= 1              # tails diverge in-block
        cats = [e["cat"] for e in ev]
        assert "serve.prefix_miss" in cats        # the first, cold stream
        hits = [e for e in ev if e["cat"] == "serve.prefix_hit"]
        assert len(hits) == 3
        assert all(e["reason"] == "prefix_hit" for e in hits)

    def test_identical_prompts_full_alias_and_cow(self, model):
        """Bit-identical prompts alias every block (hit = len-1); the
        divergence then happens inside a SHARED block, so parity proves
        copy-on-write actually copies."""
        p = _prompt(12, seed=11)
        engine = LLMEngine(model, max_batch_size=2, block_size=4,
                           num_blocks=64, enable_prefix_cache=True)
        outs = engine.generate([p, list(p)], max_new_tokens=8)
        ref = _ref(model, p, 8)
        assert outs[0] == ref and outs[1] == ref
        st = engine.stats()
        assert st["prefills"] == 1
        assert st["prefix_hit_tokens"] == len(p) - 1
        assert st["cow_copies"] >= 1

    def test_prefix_survives_across_generate_calls(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4,
                           num_blocks=64, enable_prefix_cache=True)
        p = _prompt(12, seed=12)
        engine.generate([p], max_new_tokens=4)
        assert engine.stats()["prefix_entries"] > 0
        out = engine.generate([list(p)], max_new_tokens=4)[0]
        assert out == _ref(model, p, 4)
        st = engine.stats()
        assert st["prefills"] == 1                # second call aliased
        assert st["decode_compiles"] == 1

    def test_unrelated_prompts_all_miss_and_stay_correct(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4,
                           num_blocks=64, enable_prefix_cache=True)
        prompts = [_prompt(9, seed=13), _prompt(10, seed=14)]
        outs = engine.generate(prompts, max_new_tokens=6)
        for p, o in zip(prompts, outs):
            assert o == _ref(model, p, 6)
        st = engine.stats()
        assert st["prefix_hit_tokens"] == 0
        assert st["prefills"] == 2

    def test_pool_pressure_reclaims_index_leaf_first(self, model):
        """A dry pool evicts cold index entries instead of wedging
        admission; the evictions are attributed."""
        engine = LLMEngine(model, max_batch_size=2, block_size=4,
                           num_blocks=14, enable_prefix_cache=True)
        clear_fusion_events()
        set_flags({"FLAGS_profiler_events": True})
        try:
            for seed in (21, 22, 23, 24, 25):
                p = _prompt(12, seed=seed)        # 3+ blocks each
                out = engine.generate([p], max_new_tokens=6)[0]
                assert out == _ref(model, p, 6)
            ev = fusion_events()
        finally:
            set_flags({"FLAGS_profiler_events": False})
        st = engine.stats()
        assert st["prefix_evictions"] > 0
        assert any(e["cat"] == "serve.prefix_evict" for e in ev)


# ---------------------------------------------------------------------------
# batched adapters (compiled path)
# ---------------------------------------------------------------------------

class TestAdapters:
    def test_base_tenant_bit_identical_to_adapter_free(self, model):
        """Slot 0's delta is an exact 0.0 — base tenants on an
        adapter-enabled engine match per-stream generate exactly."""
        prompts = [_prompt(9, seed=31), _prompt(7, seed=32)]
        engine = LLMEngine(model, max_batch_size=2, block_size=4,
                           num_blocks=64, max_adapters=2, adapter_rank=2)
        outs = engine.generate(prompts, max_new_tokens=8)
        for p, o in zip(prompts, outs):
            assert o == _ref(model, p, 8)
        assert engine.stats()["decode_compiles"] == 1

    def test_adapter_changes_output_deterministically(self, model):
        p = _prompt(9, seed=33)
        engine = LLMEngine(model, max_batch_size=2, block_size=4,
                           num_blocks=64, max_adapters=2, adapter_rank=2)
        engine.register_adapter("tenant-a", seed=3, scale=25.0)
        runs = []
        for i in range(2):
            engine.add_request(p, max_new_tokens=6, request_id=f"a{i}",
                               adapter="tenant-a")
            engine.run()
            runs.append(engine.pop_finished()[f"a{i}"].generated)
        assert runs[0] != _ref(model, p, 6)       # the delta bites
        assert runs[0] == runs[1]                 # and is deterministic

    def test_tenant_churn_zero_recompiles(self, model):
        """Tenants joining/leaving only edit stack VALUES and slot
        indices: the decode executable compiles exactly once."""
        prompts = _shared_prompts(6, prefix_len=8, suffix_len=2, seed=40)
        engine = LLMEngine(model, max_batch_size=3, block_size=4,
                           num_blocks=64, max_adapters=3, adapter_rank=2)
        engine.register_adapter("t1", seed=1, scale=25.0)
        engine.register_adapter("t2", seed=2, scale=25.0)
        plan = ["t1", None, "t2", "t1", "t2", None]
        for i, (p, ad) in enumerate(zip(prompts, plan)):
            engine.add_request(p, max_new_tokens=5, request_id=f"c{i}",
                               adapter=ad)
        engine.run()
        done = engine.pop_finished()
        base2 = _ref(model, prompts[1], 5)
        base5 = _ref(model, prompts[5], 5)
        assert done["c1"].generated == base2
        assert done["c5"].generated == base5
        st = engine.stats()
        assert st["decode_compiles"] == 1
        assert st["adapter_switches"] >= 2
        assert sorted(st["adapters"]) == ["t1", "t2"]
        # churn: t2 leaves, t3 joins — still zero recompiles
        engine.unregister_adapter("t2")
        engine.register_adapter("t3", seed=9, scale=25.0)
        engine.add_request(prompts[0], max_new_tokens=5,
                           request_id="c9", adapter="t3")
        engine.run()
        assert engine.pop_finished()["c9"].state == FINISHED
        assert engine.stats()["decode_compiles"] == 1

    def test_unknown_adapter_refused_as_adapter_mismatch(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4,
                           num_blocks=64, max_adapters=2)
        with pytest.raises(ServeRefusal) as ei:
            engine.add_request(_prompt(5, seed=34), max_new_tokens=4,
                               adapter="nobody")
        assert ei.value.reason == "adapter_mismatch"
        # an adapter-free engine refuses EVERY adapter request
        plain = LLMEngine(model, max_batch_size=2, block_size=4,
                          num_blocks=64)
        with pytest.raises(ServeRefusal) as ei:
            plain.add_request(_prompt(5, seed=34), max_new_tokens=4,
                              adapter="anyone")
        assert ei.value.reason == "adapter_mismatch"

    def test_unregister_refuses_while_streams_live(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4,
                           num_blocks=64, max_adapters=2)
        engine.register_adapter("busy", seed=5)
        engine.add_request(_prompt(6, seed=35), max_new_tokens=4,
                           request_id="live", adapter="busy")
        with pytest.raises(ValueError, match="live"):
            engine.unregister_adapter("busy")
        engine.run()                              # drain
        assert engine.unregister_adapter("busy") >= 1

    def test_registry_validation(self, model):
        ad = AdapterSet(model, max_adapters=2, rank=2)
        ad.register("x", seed=1)
        with pytest.raises(ValueError, match="already registered"):
            ad.register("x", seed=2)
        ad.register("y", seed=2)
        with pytest.raises(ValueError, match="slots"):
            ad.register("z", seed=3)
        ad.unregister("y")
        with pytest.raises(KeyError):
            ad.slot_of("y")
        assert ad.slot_of(None) == 0              # base is always slot 0
        L = model.config.num_hidden_layers
        bad = {t: (np.zeros((L, 1, 1)), np.zeros((L, 1, 1)))
               for t in ("qkv", "out")}
        with pytest.raises(ValueError, match="want A"):
            ad.register("bad", weights=bad)
        with pytest.raises(ValueError):
            AdapterSet(model, max_adapters=0, rank=2)

    def test_merged_fallback_context_restores_weights(self, model):
        """The eager-fallback merge (W + A@B*scale) changes generate
        under the context and restores the base weights bit-for-bit on
        exit — the degraded-mode contract."""
        engine = LLMEngine(model, max_batch_size=2, block_size=4,
                           num_blocks=64, max_adapters=2, adapter_rank=2)
        engine.register_adapter("m", seed=6, scale=25.0)
        p = _prompt(8, seed=36)
        base = _gen(model, p, 6)
        with engine._adapters.merged("m"):
            merged = _gen(model, p, 6)
        assert merged != base
        assert _gen(model, p, 6) == base          # restored exactly


# ---------------------------------------------------------------------------
# live weight hot-swap (compiled path; fresh models — swap mutates them)
# ---------------------------------------------------------------------------

class TestHotSwap:
    def test_swap_between_steps_byte_exact_zero_recompiles(self):
        m1 = _make_model(seed=0)
        m2 = _make_model(seed=1)
        w2 = [np.asarray(p._value) for p in m2.parameters()]
        p = _prompt(9, seed=51)
        ref1 = _gen(m1, p, 6)
        engine = LLMEngine(m1, max_batch_size=2, block_size=4,
                           num_blocks=64, hot_swap=True)
        assert engine.generate([p], max_new_tokens=6)[0] == ref1
        assert engine.weight_epoch == 0
        epoch = engine.swap_weights(w2)
        assert epoch == 1
        out2 = engine.generate([list(p)], max_new_tokens=6)[0]
        assert out2 == _gen(m2, p, 6)             # serving m2's function
        st = engine.stats()
        assert st["decode_compiles"] == 1         # across the swap
        assert st["weight_swaps"] == 1
        assert st["weight_epoch"] == 1

    def test_mid_run_swap_cutover_boundary_is_exact(self):
        """Streams in flight at the cutover finish as: every token
        emitted before the swap is exactly the OLD weights' token,
        every token after is exactly the NEW weights' continuation of
        (prompt + old tokens) — never a half-epoch token."""
        m1 = _make_model(seed=0)
        m2 = _make_model(seed=1)
        w2 = [np.asarray(p._value) for p in m2.parameters()]
        prompts = [_prompt(8, seed=52), _prompt(10, seed=53)]
        refs1 = [_gen(m1, p, 10) for p in prompts]
        engine = LLMEngine(m1, max_batch_size=2, block_size=4,
                           num_blocks=64, hot_swap=True)
        reqs = [engine.add_request(p, max_new_tokens=10,
                                   request_id=f"w{i}")
                for i, p in enumerate(prompts)]
        for _ in range(4):
            engine.step()
        marks = [len(r.generated) for r in reqs]
        assert any(k > 0 for k in marks)          # genuinely mid-flight
        engine.swap_weights(w2)                   # boundary: commits now
        engine.run()
        for r, p, ref1, k in zip(reqs, prompts, refs1, marks):
            assert r.generated[:k] == ref1[:k]
            cont = _gen(m2, p + ref1[:k], 10 - k)
            assert r.generated[k:] == cont
        st = engine.stats()
        assert st["decode_compiles"] == 1
        assert st["weight_swaps"] == 1
        # the cutover is a PLANNED preemption, not kv pressure: the
        # in-flight streams re-prefilled, yet nothing was "evicted"
        assert any(r.preemptions >= 1 for r in reqs)
        assert st["evictions"] == 0

    def test_stage_identical_weights_is_a_skipped_noop(self):
        m1 = _make_model(seed=0)
        engine = LLMEngine(m1, max_batch_size=2, block_size=4,
                           num_blocks=64, hot_swap=True)
        same = [np.asarray(p._value) for p in m1.parameters()]
        clear_fusion_events()
        set_flags({"FLAGS_profiler_events": True})
        try:
            assert engine.stage_weights(same) is False
            ev = fusion_events()
        finally:
            set_flags({"FLAGS_profiler_events": False})
        assert engine.weight_epoch == 0
        assert engine.stats()["weight_swaps"] == 0
        skip = [e for e in ev if e["cat"] == "serve.swap"]
        assert skip and skip[0]["detail"]["skipped"]

    def test_swap_requires_hot_swap_engine(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4,
                           num_blocks=64)
        with pytest.raises(ValueError, match="hot_swap"):
            engine.stage_weights([])

    def test_swap_invalidates_prefix_index(self):
        """Cached KV is a function of the base weights: the index is
        emptied at the cutover and the post-swap stream re-prefills
        (and is correct) under the new weights."""
        m1 = _make_model(seed=0)
        m2 = _make_model(seed=1)
        w2 = [np.asarray(p._value) for p in m2.parameters()]
        p = _prompt(12, seed=54)
        engine = LLMEngine(m1, max_batch_size=2, block_size=4,
                           num_blocks=64, hot_swap=True,
                           enable_prefix_cache=True)
        engine.generate([p], max_new_tokens=4)
        assert engine.stats()["prefix_entries"] > 0
        engine.swap_weights(w2)
        assert engine.stats()["prefix_entries"] == 0
        out = engine.generate([list(p)], max_new_tokens=4)[0]
        assert out == _gen(m2, p, 4)
        assert engine.stats()["prefills"] == 2    # no stale-KV alias


# ---------------------------------------------------------------------------
# crash-resume under tenancy
# ---------------------------------------------------------------------------

class TestTenantCrashResume:
    def test_snapshot_roundtrips_adapter_assignment(self, model):
        """A mid-flight snapshot carries each stream's adapter; the
        restored engine finishes them under the SAME adapter,
        token-identically to the uninterrupted run."""
        p1, p2 = _prompt(8, seed=61), _prompt(7, seed=62)

        def build():
            e = LLMEngine(model, max_batch_size=2, block_size=4,
                          num_blocks=64, max_adapters=2, adapter_rank=2)
            e.register_adapter("tt", seed=8, scale=25.0)
            return e

        full = build()
        full.add_request(p1, max_new_tokens=8, request_id="u1",
                         adapter="tt")
        full.add_request(p2, max_new_tokens=8, request_id="u2")
        full.run()
        want = {rid: r.generated
                for rid, r in full.pop_finished().items()}
        assert want["u1"] != _ref(model, p1, 8)   # adapter is live

        half = build()
        half.add_request(p1, max_new_tokens=8, request_id="u1",
                         adapter="tt")
        half.add_request(p2, max_new_tokens=8, request_id="u2")
        for _ in range(4):
            half.step()
        payload = half.state_payload()
        assert any(rp["adapter"] == "tt"
                   for rp in payload["requests"])
        fresh = build()
        restored = fresh.restore_state(payload)
        fresh.run()
        by_rid = {r.rid: r for r in restored}
        for rid, toks in want.items():
            assert by_rid[rid].generated == toks
            assert by_rid[rid].state == FINISHED

    def test_restore_refuses_unregistered_adapter(self, model):
        engine = LLMEngine(model, max_batch_size=2, block_size=4,
                           num_blocks=64, max_adapters=2)
        engine.register_adapter("gone", seed=9)
        engine.add_request(_prompt(6, seed=63), max_new_tokens=4,
                           request_id="g", adapter="gone")
        payload = engine.state_payload()
        bare = LLMEngine(model, max_batch_size=2, block_size=4,
                         num_blocks=64, max_adapters=2)
        with pytest.raises(ServeRefusal) as ei:
            bare.restore_state(payload)
        assert ei.value.reason == "adapter_mismatch"

    def test_restore_refuses_torn_swap(self):
        """A snapshot taken under one weight set refuses to restore in
        an engine serving another — the supervisor must load the
        matching weights first (tools/chaos.py tenant_swap drills the
        full kill/restart path)."""
        m1 = _make_model(seed=0)
        m_other = _make_model(seed=1)
        engine = LLMEngine(m1, max_batch_size=2, block_size=4,
                           num_blocks=64, hot_swap=True)
        engine.add_request(_prompt(6, seed=64), max_new_tokens=4,
                           request_id="t")
        payload = engine.state_payload()
        assert payload["weights_crc"] is not None
        torn = LLMEngine(m_other, max_batch_size=2, block_size=4,
                         num_blocks=64, hot_swap=True)
        with pytest.raises(ServeRefusal) as ei:
            torn.restore_state(payload)
        assert ei.value.reason == "torn_swap"
        # loading the matching weight set unblocks the restore
        w1 = [np.asarray(p._value) for p in m1.parameters()]
        torn.swap_weights(w1)
        [req] = torn.restore_state(payload)
        torn.run()
        assert req.state == FINISHED
        assert req.generated == _gen(m1, _prompt(6, seed=64), 4)


# ---------------------------------------------------------------------------
# everything at once (the acceptance shape, scaled down)
# ---------------------------------------------------------------------------

class TestCombined:
    @pytest.mark.perf_smoke
    def test_prefix_adapters_swap_one_executable(self):
        """Scaled-down ISSUE 17 acceptance: streams over mixed tenants
        with a shared prefix, a mid-run weight swap — ONE decode
        compile through all of it (mirrors tools/perf_smoke.py leg o)."""
        m1 = _make_model(seed=0)
        m2 = _make_model(seed=1)
        w2 = [np.asarray(p._value) for p in m2.parameters()]
        engine = LLMEngine(m1, max_batch_size=4, block_size=4,
                           num_blocks=96, enable_prefix_cache=True,
                           max_adapters=3, adapter_rank=2, hot_swap=True)
        engine.register_adapter("a1", seed=1, scale=25.0)
        engine.register_adapter("a2", seed=2, scale=25.0)
        prompts = _shared_prompts(8, prefix_len=12, suffix_len=2,
                                  seed=70)
        plan = ["a1", None, "a2", "a1", None, "a2", "a1", None]
        for i, (p, ad) in enumerate(zip(prompts, plan)):
            engine.add_request(p, max_new_tokens=6, request_id=f"x{i}",
                               adapter=ad)
        for _ in range(3):
            engine.step()
        engine.swap_weights(w2)                   # mid-run cutover
        engine.run()
        done = engine.pop_finished()
        assert len(done) == 8
        assert all(r.state == FINISHED for r in done.values())
        st = engine.stats()
        assert st["decode_compiles"] == 1
        assert st["prefix_hit_tokens"] > 0
        assert st["adapter_switches"] >= 1
        assert st["weight_swaps"] == 1
