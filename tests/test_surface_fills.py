"""Parity fills: sparse.nn, nn.utils, incubate functional forms,
functional BFGS/L-BFGS, static.sparsity, fleet.utils FS, inference pool,
device.cuda shim (reference modules cited per test)."""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle


class TestSparseNN:
    def _coo(self):
        import paddle_tpu.sparse as sp
        idx = np.asarray([[0, 0, 1], [0, 2, 1]])
        vals = np.asarray([[1.0, -2.0], [3.0, -4.0], [-5.0, 6.0]],
                          np.float32)
        return sp.sparse_coo_tensor(idx, vals, shape=[2, 3, 2])

    def test_activations_preserve_pattern(self):
        import paddle_tpu.sparse.nn as spnn
        x = self._coo()
        y = spnn.ReLU()(x)
        assert y.nnz() == x.nnz()
        np.testing.assert_allclose(np.asarray(y.values._value),
                                   [[1, 0], [3, 0], [0, 6]])
        z = spnn.LeakyReLU(0.1)(x)
        np.testing.assert_allclose(np.asarray(z.values._value)[0],
                                   [1.0, -0.2])

    def test_batch_norm_on_values(self):
        import paddle_tpu.sparse.nn as spnn
        bn = spnn.BatchNorm(2)
        out = bn(self._coo())
        v = np.asarray(out.values._value)
        np.testing.assert_allclose(v.mean(0), 0.0, atol=1e-5)

    def test_conv3d_matches_dense(self):
        import paddle_tpu.sparse as sp
        import paddle_tpu.sparse.nn.functional as spf
        rng = np.random.default_rng(0)
        dense = np.zeros((1, 4, 4, 4, 3), np.float32)
        sites = [(0, 1, 1, 1), (0, 2, 3, 0)]
        for s in sites:
            dense[s[0], s[1], s[2], s[3]] = rng.normal(size=3)
        idx = np.asarray(list(zip(*[(s + (c,)) for s in sites
                                    for c in range(3)])))
        vals = np.asarray([dense[s][c] for s in sites for c in range(3)],
                          np.float32)
        x = sp.sparse_coo_tensor(idx, vals, shape=[1, 4, 4, 4, 3])
        w = paddle.to_tensor(rng.normal(size=(3, 3, 3, 3, 5))
                             .astype(np.float32))
        out = spf.conv3d(x, w, padding=1)
        # parity with the dense path
        import paddle_tpu.nn.functional as F
        import paddle_tpu.ops.manipulation as manip
        xd = manip.transpose(paddle.to_tensor(dense), [0, 4, 1, 2, 3])
        wd = manip.transpose(w, [4, 3, 0, 1, 2])
        ref = manip.transpose(F.conv3d(xd, wd, padding=1),
                              [0, 2, 3, 4, 1])
        np.testing.assert_allclose(np.asarray(out.to_dense()._value),
                                   np.asarray(ref._value),
                                   rtol=1e-4, atol=1e-5)

    def test_subm_conv_keeps_input_pattern(self):
        import paddle_tpu.sparse as sp
        import paddle_tpu.sparse.nn as spnn
        rng = np.random.default_rng(0)
        sites = [(0, 1, 2, 1), (0, 3, 0, 2)]
        idx = np.asarray(list(zip(*[(s + (c,)) for s in sites
                                    for c in range(3)])))
        vals = rng.normal(size=(len(sites) * 3,)).astype(np.float32)
        x = sp.sparse_coo_tensor(idx, vals, shape=[1, 4, 4, 4, 3])
        conv = spnn.SubmConv3D(3, 4, 3, padding=1)
        y = conv(x)
        got = np.abs(np.asarray(y.to_dense()._value)).sum(-1) != 0
        want = np.abs(np.asarray(x.to_dense()._value)).sum(-1) != 0
        assert (got & ~want).sum() == 0    # no new active sites


class TestNNUtils:
    def test_weight_norm_roundtrip(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.nn.utils import weight_norm, remove_weight_norm
        paddle.seed(0)
        l = nn.Linear(4, 3)
        w0 = np.asarray(l.weight._value).copy()
        x = paddle.to_tensor(np.random.default_rng(0)
                             .normal(size=(2, 4)).astype(np.float32))
        ref = np.asarray(l(x)._value)
        weight_norm(l, dim=0)
        assert hasattr(l, "weight_g") and hasattr(l, "weight_v")
        np.testing.assert_allclose(np.asarray(l(x)._value), ref, rtol=1e-5)
        # gradients flow to g and v
        out = l(x)
        ((out * out).mean()).backward()
        assert l.weight_g.grad is not None and l.weight_v.grad is not None
        remove_weight_norm(l)
        np.testing.assert_allclose(np.asarray(l(x)._value), ref, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(l.weight._value), w0,
                                   rtol=1e-5)

    def test_spectral_norm_bounds_sigma(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.nn.utils import spectral_norm
        paddle.seed(0)
        l = nn.Linear(6, 8)
        l.weight._value = l.weight._value * 10.0
        spectral_norm(l, n_power_iterations=5)
        x = paddle.to_tensor(np.eye(6, dtype=np.float32))
        out = np.asarray(l(x)._value) - np.asarray(l.bias._value)
        s = np.linalg.svd(out, compute_uv=False)[0]
        np.testing.assert_allclose(s, 1.0, rtol=0.2)

    def test_parameters_vector_roundtrip(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.nn.utils import (parameters_to_vector,
                                         vector_to_parameters)
        paddle.seed(0)
        m = nn.Linear(3, 2)
        vec = parameters_to_vector(m.parameters())
        assert vec.shape == [3 * 2 + 2]
        vector_to_parameters(vec * 0 + 1.0, m.parameters())
        for p in m.parameters():
            np.testing.assert_allclose(np.asarray(p._value), 1.0)


class TestFunctionalOptimizers:
    def test_bfgs_converges_on_rosenbrock(self):
        from paddle_tpu.incubate.optimizer.functional import minimize_bfgs

        def rosen(x):
            v = x._value
            return paddle.to_tensor(
                (100 * (v[1] - v[0] ** 2) ** 2 + (1 - v[0]) ** 2))

        ok, calls, pos, val, grad, H = minimize_bfgs(
            rosen, paddle.to_tensor(np.zeros(2, np.float32)),
            max_iters=200)
        np.testing.assert_allclose(np.asarray(pos._value), [1.0, 1.0],
                                   atol=1e-2)
        assert calls > 0 and H.shape == [2, 2]

    def test_lbfgs_matches_bfgs_on_quadratic(self):
        from paddle_tpu.incubate.optimizer.functional import (
            minimize_bfgs, minimize_lbfgs)

        def quad(x):
            v = x._value
            t = v - jnp.asarray([3.0, -1.0, 2.0, 0.5])
            return paddle.to_tensor((t * t).sum())

        x0 = paddle.to_tensor(np.zeros(4, np.float32))
        _, _, p1, _, _, _ = minimize_bfgs(quad, x0)
        _, _, p2, _, _ = minimize_lbfgs(quad, x0, history_size=3)
        np.testing.assert_allclose(np.asarray(p1._value),
                                   np.asarray(p2._value), atol=1e-4)


class TestMiscShims:
    def test_static_sparsity_reexports(self):
        import paddle_tpu.static.sparsity as sparsity
        assert callable(sparsity.calculate_density)
        assert sparsity.add_supported_layer("my_layer") == "my_layer"

    def test_local_fs(self, tmp_path):
        from paddle_tpu.distributed.fleet.utils import LocalFS
        fs = LocalFS()
        d = str(tmp_path / "a")
        fs.mkdirs(d)
        assert fs.is_dir(d) and fs.is_exist(d)
        f = str(tmp_path / "a" / "x.txt")
        fs.touch(f)
        assert fs.is_file(f)
        dirs, files = fs.ls_dir(str(tmp_path / "a"))
        assert files == ["x.txt"]
        fs.mv(f, str(tmp_path / "y.txt"))
        assert fs.is_exist(str(tmp_path / "y.txt"))
        assert not fs.need_upload_download()

    def test_hdfs_client_without_hadoop_raises(self):
        from paddle_tpu.distributed.fleet.utils import HDFSClient
        c = HDFSClient(hadoop_home="/nonexistent")
        with pytest.raises(RuntimeError, match="hadoop"):
            c.is_exist("/x")

    def test_inference_extras(self):
        import paddle_tpu.inference as infer
        assert infer.get_num_bytes_of_data_type(infer.DataType.FLOAT32) == 4
        assert infer.get_trt_compile_version() == (0, 0, 0)
        assert infer._get_phi_kernel_name("matmul_v2") == "matmul_v2"
        with pytest.raises(NotImplementedError):
            infer.convert_to_mixed_precision("a", "b", "c", "d")

    def test_device_cuda_shim(self):
        import paddle_tpu.device.cuda as cuda
        assert cuda.device_count() == 0
        cuda.synchronize()
        s = cuda.Stream()
        e = s.record_event()
        assert e.query()
        with cuda.stream_guard(s):
            pass
        with pytest.raises(RuntimeError):
            cuda.get_device_name()

    def test_bilinear_initializer_and_global(self):
        import paddle_tpu.nn.initializer as I
        w = I.Bilinear()((2, 2, 4, 4), jnp.float32)
        # center of the triangle kernel is the max
        assert float(w[0, 0, 1, 1]) == np.asarray(w[0, 0]).max()
        import paddle_tpu.nn as nn
        I.set_global_initializer(I.Constant(0.5), I.Constant(0.1))
        try:
            l = nn.Linear(2, 2)
            np.testing.assert_allclose(np.asarray(l.weight._value), 0.5)
            np.testing.assert_allclose(np.asarray(l.bias._value), 0.1)
        finally:
            I.set_global_initializer(None)
        l2 = nn.Linear(2, 2)
        assert not np.allclose(np.asarray(l2.weight._value), 0.5)

    def test_recompute_sequential_matches_plain(self):
        from paddle_tpu.incubate.distributed.fleet import (
            recompute_sequential)
        import paddle_tpu.nn as nn
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        x = paddle.to_tensor(np.random.default_rng(0)
                             .normal(size=(3, 4)).astype(np.float32))
        ref = m(x)
        got = recompute_sequential({"segments": 2}, list(m), x)
        np.testing.assert_allclose(np.asarray(got._value),
                                   np.asarray(ref._value), rtol=1e-5)

    def test_fused_functional_forms(self):
        from paddle_tpu.incubate.nn.functional import (
            fused_feedforward, fused_matmul_bias)
        paddle.seed(0)
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(2, 5, 8)).astype(np.float32))
        w1 = paddle.to_tensor(rng.normal(size=(8, 16)).astype(np.float32))
        w2 = paddle.to_tensor(rng.normal(size=(16, 8)).astype(np.float32))
        out = fused_feedforward(x, w1, w2, dropout1_rate=0.0,
                                dropout2_rate=0.0, training=False)
        assert out.shape == [2, 5, 8]
        mm = fused_matmul_bias(
            paddle.to_tensor(rng.normal(size=(3, 4)).astype(np.float32)),
            paddle.to_tensor(rng.normal(size=(4, 2)).astype(np.float32)),
            paddle.to_tensor(np.ones(2, np.float32)))
        assert mm.shape == [3, 2]
