"""Non-finite step guardian (PR 5): in-graph numerics checks, skip-step
rescue, crash-safe checkpoints, and the chaos harness.

Covers the robustness contract end to end:
  * `FLAGS_check_numerics` keeps ALL THREE fusion tiers engaged (the old
    `FLAGS_check_nan_inf` forces per-op debug dispatch): a dynamic-loss-
    scaled GradScaler loop promotes to ONE fused whole-step executable,
    with unscale / found-inf / loss-scale update folded in;
  * skip-step rescue: a non-finite-gradient step is a bitwise no-op on
    params AND optimizer slots, fused and eager paths alike; the scale
    halves; the flight recorder attributes `nonfinite_skip`;
  * non-finite FORWARD outputs raise (level 0) or warn (level >= 1) at a
    flush boundary — except on AMP threads, where the scaler's backoff is
    the designed response;
  * framework/io.py writes checkpoints atomically (tmp + os.replace + CRC
    trailer) and load() raises CheckpointCorruptError on torn/garbled
    files; EpochRange round-trips optimizer/scaler/RNG state with rolling
    retention and resumes a kill -9'd run to the uninterrupted result;
  * chaos fault injection (tools/chaos.py) is attributed as
    `injected_fault` and the loop recovers.
"""
from __future__ import annotations

import importlib.util
import os
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework import io as fio
from paddle_tpu.framework import random as frandom
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.incubate.checkpoint import (StepCheckpointer,
                                            train_epoch_range)
from paddle_tpu.ops import guardian
from paddle_tpu.ops.dispatch import clear_dispatch_cache
from paddle_tpu.profiler import (reset_step_fusion_stats, step_fusion_stats)
from paddle_tpu.profiler.events import clear_fusion_events, fusion_events
from paddle_tpu.profiler.explain import explain, format_report

_DEFAULTS = {
    "FLAGS_check_numerics": False,
    "FLAGS_check_numerics_level": 0,
    "FLAGS_check_nan_inf": False,
    "FLAGS_eager_op_cache": True,
    "FLAGS_eager_op_cache_size": 512,
    "FLAGS_eager_chain_fusion": True,
    "FLAGS_eager_chain_fusion_min_count": 3,
    "FLAGS_eager_step_fusion": True,
    "FLAGS_eager_step_fusion_min_count": 4,
    "FLAGS_eager_step_fusion_cache_size": 8,
    "FLAGS_profiler_events": False,
}


def _reset():
    set_flags(dict(_DEFAULTS))
    clear_dispatch_cache()
    clear_fusion_events()
    guardian.reset_guardian_stats()
    guardian.reset_thread_state()
    guardian.clear_faults()
    reset_step_fusion_stats()


@pytest.fixture(autouse=True)
def _fresh():
    _reset()
    yield
    _reset()


def _mk(seed=0, d=8, with_momentum=False, lr=1e-2):
    rng = np.random.default_rng(seed)
    x = paddle.to_tensor(rng.standard_normal((4, d)).astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((d, d)).astype(np.float32),
                         stop_gradient=False)
    if with_momentum:
        opt = paddle.optimizer.Momentum(learning_rate=lr, momentum=0.9,
                                        parameters=[w])
    else:
        opt = paddle.optimizer.SGD(learning_rate=lr, parameters=[w])
    return x, w, opt


def _nan_batch(d=8):
    return paddle.to_tensor(np.full((4, d), np.nan, np.float32))


def _plain_step(x, w, opt):
    F.gelu(paddle.matmul(x, w)).sum().backward()
    opt.step()
    opt.clear_grad()


def _amp_step(x, w, opt, scaler):
    loss = F.gelu(paddle.matmul(x, w)).sum()
    scaler.scale(loss).backward()
    scaler.step(opt)
    scaler.update()
    opt.clear_grad()


# ---------------------------------------------------------------------------
# crash-safe io
# ---------------------------------------------------------------------------

class TestAtomicCheckpointIO:
    def test_roundtrip_and_no_tmp_leftovers(self, tmp_path):
        path = os.path.join(tmp_path, "sub", "model.pdparams")
        fio.save({"w": paddle.to_tensor(np.arange(6.0, dtype=np.float32))},
                 path)
        out = fio.load(path)
        np.testing.assert_array_equal(np.asarray(out["w"]._value),
                                      np.arange(6.0, dtype=np.float32))
        leftovers = [f for d, _, fs in os.walk(tmp_path)
                     for f in fs if ".tmp" in f]
        assert leftovers == []

    def test_every_sync_save_carries_crc_trailer(self, tmp_path):
        import struct
        path = os.path.join(tmp_path, "x.pd")
        fio.save({"v": 1}, path)
        raw = open(path, "rb").read()
        magic, plen, _crc = struct.unpack("<QQQ", raw[-24:])
        assert magic == fio._TRAILER_MAGIC
        assert plen == len(raw) - 24

    def test_bitflip_detected(self, tmp_path):
        path = os.path.join(tmp_path, "x.pd")
        fio.save({"w": paddle.to_tensor(np.ones(32, np.float32))}, path)
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        open(path, "wb").write(bytes(raw))
        with pytest.raises(fio.CheckpointCorruptError, match="CRC"):
            fio.load(path)
        # the dedicated error is still an IOError (pre-PR5 callers catch it)
        assert issubclass(fio.CheckpointCorruptError, IOError)

    def test_truncation_detected(self, tmp_path):
        path = os.path.join(tmp_path, "x.pd")
        fio.save({"w": paddle.to_tensor(np.ones(64, np.float32))}, path)
        raw = open(path, "rb").read()
        open(path, "wb").write(raw[: len(raw) // 2])
        with pytest.raises(fio.CheckpointCorruptError):
            fio.load(path)

    def test_file_object_path_unchanged(self, tmp_path):
        path = os.path.join(tmp_path, "x.pd")
        with open(path, "wb") as f:
            fio.save({"v": 7}, f)
        with open(path, "rb") as f:
            assert fio.load(f)["v"] == 7

    def test_failed_save_preserves_previous_checkpoint(self, tmp_path):
        path = os.path.join(tmp_path, "x.pd")
        fio.save({"v": "good"}, path)

        class Boom:
            def __reduce__(self):
                raise RuntimeError("mid-serialization crash")

        with pytest.raises(RuntimeError):
            fio.save({"v": Boom()}, path)
        assert fio.load(path)["v"] == "good"


class TestEpochRangeCheckpoints:
    def test_state_roundtrip_with_retention(self, tmp_path):
        x, w, opt = _mk(seed=3, with_momentum=True)
        scaler = paddle.amp.GradScaler(init_loss_scaling=512.0)
        paddle.seed(9)
        er = train_epoch_range(5, save_dir=str(tmp_path), run_id="t",
                               max_checkpoints=2)
        for epoch in er:
            _plain_step(x, w, opt)
            er.save(epoch, model={"w": w}, optimizer=opt, scaler=scaler,
                    extra={"epoch": epoch})
        assert er._retained_epochs() == [3, 4]
        w_final = np.asarray(w._value).copy()
        acc = {k: np.asarray(v) for k, v
               in opt._accumulators["velocity"].items()}
        rng_before = frandom.rng_checkpoint_state()

        x2, w2, opt2 = _mk(seed=99, with_momentum=True)
        w2.name = w.name
        scaler2 = paddle.amp.GradScaler(init_loss_scaling=2.0)
        paddle.seed(1234)   # scrambled on purpose; restore must undo it
        er2 = train_epoch_range(5, save_dir=str(tmp_path), run_id="t",
                                max_checkpoints=2)
        extra = er2.restore(model={"w": w2}, optimizer=opt2, scaler=scaler2)
        assert extra == {"epoch": 4}
        assert er2.restored_from == 4
        np.testing.assert_array_equal(w_final, np.asarray(w2._value))
        for k, v in acc.items():
            np.testing.assert_array_equal(
                v, np.asarray(opt2._accumulators["velocity"][k]))
        assert getattr(opt2, "_step_count") == getattr(opt, "_step_count")
        assert scaler2.get_init_loss_scaling() == 512.0
        rng_after = frandom.rng_checkpoint_state()
        assert rng_after["epoch"] == rng_before["epoch"]
        np.testing.assert_array_equal(rng_after["key_data"],
                                      rng_before["key_data"])

    def test_restore_falls_back_past_corrupt_checkpoint(self, tmp_path):
        x, w, opt = _mk(seed=4)
        er = train_epoch_range(4, save_dir=str(tmp_path), run_id="t",
                               max_checkpoints=3)
        snaps = {}
        for epoch in er:
            _plain_step(x, w, opt)
            er.save(epoch, model={"w": w})
            snaps[epoch] = np.asarray(w._value).copy()
        # garble the NEWEST checkpoint (simulated torn write on a crashed
        # filesystem that ignored fsync)
        newest = os.path.join(er.checkpoint_path(3), er.CKPT_FILE)
        raw = bytearray(open(newest, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        open(newest, "wb").write(bytes(raw))

        w2 = paddle.to_tensor(np.zeros((8, 8), np.float32),
                              stop_gradient=False)
        er2 = train_epoch_range(4, save_dir=str(tmp_path), run_id="t")
        er2.restore(model={"w": w2})
        np.testing.assert_array_equal(snaps[2], np.asarray(w2._value))
        # the range rewinds so the lost epoch is re-run
        assert er2.restored_from == 2
        assert list(er2) == [3]

    def test_restore_refuses_when_every_checkpoint_is_corrupt(self, tmp_path):
        x, w, opt = _mk(seed=5)
        er = train_epoch_range(3, save_dir=str(tmp_path), run_id="t",
                               max_checkpoints=2)
        for epoch in er:
            _plain_step(x, w, opt)
            er.save(epoch, model={"w": w})
        for e in er._retained_epochs():
            p = os.path.join(er.checkpoint_path(e), er.CKPT_FILE)
            raw = bytearray(open(p, "rb").read())
            raw[len(raw) // 2] ^= 0xFF
            open(p, "wb").write(bytes(raw))
        w2 = paddle.to_tensor(np.zeros((8, 8), np.float32),
                              stop_gradient=False)
        er2 = train_epoch_range(3, save_dir=str(tmp_path), run_id="t")
        # resuming epochs 3.. on w2's fresh zeros would be silent garbage:
        # the restore must refuse, not return None
        with pytest.raises(fio.CheckpointCorruptError,
                           match="refusing to resume"):
            er2.restore(model={"w": w2})


# ---------------------------------------------------------------------------
# GradScaler semantics
# ---------------------------------------------------------------------------

class TestGradScaler:
    def test_double_unscale_raises(self):
        x, w, opt = _mk()
        scaler = paddle.amp.GradScaler()
        scaler.scale(F.gelu(paddle.matmul(x, w)).sum()).backward()
        scaler.unscale_(opt)
        with pytest.raises(RuntimeError, match="unscale_"):
            scaler.unscale_(opt)
        # step()+update() reset the latch: the next cycle unscales fine
        scaler.step(opt)
        scaler.update()
        opt.clear_grad()
        scaler.scale(F.gelu(paddle.matmul(x, w)).sum()).backward()
        scaler.unscale_(opt)
        scaler.step(opt)
        scaler.update()

    def test_state_dict_roundtrips_growth_tracker(self):
        scaler = paddle.amp.GradScaler(init_loss_scaling=128.0,
                                       incr_every_n_steps=3,
                                       decr_every_n_nan_or_inf=2)
        # one bad step (streak 1 of 2) and two good steps (streak 2 of 3)
        scaler._found_inf = True
        scaler.update()
        scaler._found_inf = False
        scaler.update()
        scaler.update()
        state = scaler.state_dict()
        assert state["scale"] == 128.0
        assert state["bad_steps"] == 0 and state["good_steps"] == 2
        fresh = paddle.amp.GradScaler(init_loss_scaling=1.0,
                                      incr_every_n_steps=3,
                                      decr_every_n_nan_or_inf=2)
        fresh.load_state_dict(state)
        # the third good step grows the scale exactly as the original would
        fresh._found_inf = False
        fresh.update()
        assert fresh.get_init_loss_scaling() == 256.0

    def test_legacy_skip_and_backoff_without_guardian(self):
        set_flags({"FLAGS_eager_step_fusion": False})
        x, w, opt = _mk(seed=5)
        scaler = paddle.amp.GradScaler(init_loss_scaling=64.0,
                                       decr_every_n_nan_or_inf=1)
        _amp_step(x, w, opt, scaler)
        w_good = np.asarray(w._value).copy()
        _amp_step(_nan_batch(), w, opt, scaler)
        np.testing.assert_array_equal(w_good, np.asarray(w._value))
        assert scaler.get_init_loss_scaling() == 32.0
        # legacy mode: the skip happened in Python, not via the guardian
        assert guardian.guardian_stats()["steps_skipped"] == 0


# ---------------------------------------------------------------------------
# guardian, eager tier
# ---------------------------------------------------------------------------

class TestGuardianEager:
    def test_strict_mode_takes_precedence(self):
        set_flags({"FLAGS_check_numerics": True, "FLAGS_check_nan_inf": True})
        assert not guardian.enabled()
        set_flags({"FLAGS_check_nan_inf": False})
        assert guardian.enabled()

    def test_forward_nonfinite_raises_at_flush(self):
        set_flags({"FLAGS_check_numerics": True,
                   "FLAGS_eager_step_fusion": False})
        x, w, opt = _mk()
        # the raise lands at the first boundary whose pipelined batch has
        # resolved — backward on a fast device, the explicit flush at the
        # latest
        with pytest.raises(FloatingPointError, match="non-finite"):
            F.gelu(paddle.matmul(_nan_batch(), w)).sum().backward()
            guardian.flush()
        assert guardian.guardian_stats()["nonfinite_outputs"] >= 1

    def test_forward_nonfinite_warns_at_level1(self):
        set_flags({"FLAGS_check_numerics": True,
                   "FLAGS_check_numerics_level": 1,
                   "FLAGS_eager_step_fusion": False})
        x, w, opt = _mk()
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            F.gelu(paddle.matmul(_nan_batch(), w)).sum().backward()
            guardian.flush()
        assert any("non-finite" in str(r.message) for r in rec)

    def test_eager_skip_step_is_bitwise_noop(self):
        set_flags({"FLAGS_check_numerics": True,
                   "FLAGS_check_numerics_level": 1,
                   "FLAGS_eager_step_fusion": False})
        x, w, opt = _mk(seed=6, with_momentum=True)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            _plain_step(x, w, opt)
            w_good = np.asarray(w._value).copy()
            vel = np.asarray(
                next(iter(opt._accumulators["velocity"].values()))).copy()
            _plain_step(_nan_batch(), w, opt)
            guardian.flush()
        np.testing.assert_array_equal(w_good, np.asarray(w._value))
        np.testing.assert_array_equal(
            vel, np.asarray(
                next(iter(opt._accumulators["velocity"].values()))))
        stats = guardian.guardian_stats()
        assert stats["steps_skipped"] == 1
        # step counter still advanced: LR schedules see the skipped step
        assert opt._step_count == 2
        # and a good batch updates again
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            _plain_step(x, w, opt)
        assert not np.array_equal(w_good, np.asarray(w._value))

    def test_scaler_thread_never_raises_on_forward_inf(self):
        set_flags({"FLAGS_check_numerics": True,
                   "FLAGS_eager_step_fusion": False})
        x, w, opt = _mk(seed=7)
        scaler = paddle.amp.GradScaler(init_loss_scaling=64.0,
                                       decr_every_n_nan_or_inf=1)
        _amp_step(x, w, opt, scaler)
        w_good = np.asarray(w._value).copy()
        _amp_step(_nan_batch(), w, opt, scaler)
        guardian.flush()     # must NOT raise: AMP overflow is rescued
        np.testing.assert_array_equal(w_good, np.asarray(w._value))
        assert scaler.get_init_loss_scaling() == 32.0
        stats = guardian.guardian_stats()
        assert stats["steps_skipped"] == 1
        assert stats["scaler_backoffs"] == 1


# ---------------------------------------------------------------------------
# guardian, fused whole-step tier
# ---------------------------------------------------------------------------

def _amp_run(steps, nan_at=(), fused=True, seed=11, lr=1e-2):
    """Fresh AMP loop; returns (params-before-each-step, w, opt, scaler)."""
    set_flags({"FLAGS_check_numerics": True,
               "FLAGS_eager_step_fusion": fused})
    clear_dispatch_cache()
    x, w, opt = _mk(seed=seed, with_momentum=True, lr=lr)
    scaler = paddle.amp.GradScaler(init_loss_scaling=256.0,
                                   decr_every_n_nan_or_inf=1)
    before = []
    for i in range(steps):
        before.append(np.asarray(w._value).copy())
        _amp_step(_nan_batch() if i in nan_at else x, w, opt, scaler)
    guardian.flush()
    return before, w, opt, scaler


class TestGuardianFused:
    def test_amp_loop_promotes_to_one_executable(self):
        _amp_run(10)
        s = step_fusion_stats()
        assert s["steps_promoted"] == 1
        assert s["fused_steps"] >= 4
        assert s["fallback_splits"] == 0

    def test_fused_nan_step_bitwise_noop_no_split(self):
        before, w, opt, scaler = _amp_run(12, nan_at=(9,))
        s = step_fusion_stats()
        assert s["fused_steps"] >= 6 and s["fallback_splits"] == 0
        # the NaN step (9) changed nothing: params before step 10 are
        # bitwise the params before step 9
        np.testing.assert_array_equal(before[9], before[10])
        # but training continued: step 10 updated again
        assert not np.array_equal(before[10], before[11])
        assert scaler.get_init_loss_scaling() == 128.0
        stats = guardian.guardian_stats()
        assert stats["steps_skipped"] == 1
        assert stats["scaler_backoffs"] == 1

    def test_fused_and_eager_nan_handling_agree(self):
        before_f, w_f, _, sc_f = _amp_run(12, nan_at=(9,), fused=True)
        guardian.reset_thread_state()
        before_e, w_e, _, sc_e = _amp_run(12, nan_at=(9,), fused=False)
        # identical skip semantics: both paths no-op step 9 bitwise...
        np.testing.assert_array_equal(before_f[9], before_f[10])
        np.testing.assert_array_equal(before_e[9], before_e[10])
        # ...took the same scale trajectory...
        assert sc_f.get_init_loss_scaling() == sc_e.get_init_loss_scaling()
        # ...and agree on the params (to the fused-vs-unfused reduction
        # tolerance, ROADMAP follow-on (d))
        np.testing.assert_allclose(np.asarray(w_f._value),
                                   np.asarray(w_e._value),
                                   rtol=0, atol=1e-5)

    def test_fused_no_scaler_nonfinite_loss_raises(self):
        # forward-contract parity with the unfused path: a promoted loop
        # WITHOUT a GradScaler still raises on a non-finite loss at level
        # 0 (the skip-step no-op protected the params, but silently
        # stalled training is not an acceptable steady state)
        set_flags({"FLAGS_check_numerics": True})
        x, w, opt = _mk(seed=17)
        for _ in range(8):
            _plain_step(x, w, opt)
        assert step_fusion_stats()["fused_steps"] >= 1
        w_good = np.asarray(w._value).copy()
        with pytest.raises(FloatingPointError, match="non-finite"):
            _plain_step(_nan_batch(), w, opt)
            guardian.flush()
        np.testing.assert_array_equal(w_good, np.asarray(w._value))

    def test_grad_placeholders_filled_with_unscaled_grads(self):
        def run(fused):
            _reset()
            set_flags({"FLAGS_check_numerics": True,
                       "FLAGS_eager_step_fusion": fused})
            x, w, opt = _mk(seed=13)
            scaler = paddle.amp.GradScaler(init_loss_scaling=256.0)
            grads = []
            for _ in range(8):
                loss = F.gelu(paddle.matmul(x, w)).sum()
                scaler.scale(loss).backward()
                scaler.step(opt)
                scaler.update()
                grads.append(np.asarray(w.grad._value).copy())
                opt.clear_grad()
            return grads

        fused_grads = run(True)
        assert step_fusion_stats()["fused_steps"] >= 2
        eager_grads = run(False)
        # after scaler.step the user-visible p.grad holds UNSCALED grads —
        # fused fires fill the placeholders with exactly what the eager
        # unscale_ path produces
        for gf, ge in zip(fused_grads, eager_grads):
            np.testing.assert_allclose(gf, ge, rtol=0, atol=1e-5)

    def test_doctor_attributes_nonfinite_skip(self):
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        _amp_run(12, nan_at=(9,))
        skips = [e for e in fusion_events("step.record")
                 if e["reason"] == "nonfinite_skip"]
        assert skips, "nonfinite_skip never hit the flight recorder"
        rep = explain()
        assert rep["guardian"].get("nonfinite_skip", {}).get("count", 0) >= 1
        assert rep["guardian"].get("scaler_backoff", {}).get("count", 0) >= 1
        # guardian decisions are NOT cycle poisons: the loop still reads
        # as a clean promotion
        assert rep["verdict"] == "clean_promotion", rep["headline"]
        text = format_report(rep)
        assert "nonfinite_skip" in text

    def test_scaler_hyperparam_change_kills_program(self):
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        before, w, opt, scaler = _amp_run(10)
        assert step_fusion_stats()["fused_steps"] > 0
        scaler._incr_ratio = 3.0    # baked into the traced transition
        x = paddle.to_tensor(
            np.random.default_rng(0).standard_normal((4, 8))
            .astype(np.float32))
        w_before = np.asarray(w._value).copy()
        _amp_step(x, w, opt, scaler)
        splits = [e for e in fusion_events("step.split")
                  if e["reason"] == "optimizer_state_change"]
        assert splits, "stale scaler constants did not split the replay"
        # the eager fallback still trained the step
        assert not np.array_equal(w_before, np.asarray(w._value))


# ---------------------------------------------------------------------------
# state-blowup gate + step-index stamping (PR 6 guardian follow-ons)
# ---------------------------------------------------------------------------

def _spike_run(fused, spike_at=9, steps=12):
    """Loop whose gradients stay FINITE while one step's LR spike
    overflows `p - lr*g` to inf: a pure optimizer-STATE blowup. The old
    grads-only predicate waved it through the gate; the new-state fold
    must turn it into a bitwise no-op step."""
    set_flags({"FLAGS_check_numerics": True,
               "FLAGS_eager_step_fusion": fused,
               "FLAGS_profiler_events": True})
    clear_dispatch_cache()
    clear_fusion_events()
    rng = np.random.default_rng(3)
    x = paddle.to_tensor(
        (rng.standard_normal((4, 8)) * 10).astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32),
                         stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=1e-3, parameters=[w])
    before = []
    for i in range(steps):
        # the LR is a hoisted scalar arg of the fused step executable, so
        # the spike neither splits nor retraces — it rides the same
        # program and the in-graph gate catches the overflow
        opt.set_lr(3e38 if i == spike_at else 1e-3)
        before.append(np.asarray(w._value).copy())
        paddle.matmul(x, w).sum().backward()
        opt.step()
        opt.clear_grad()
    guardian.flush()
    return before, w, opt


class TestStateBlowupGate:
    def test_eager_lr_spike_skips_bitwise(self):
        before, w, opt = _spike_run(fused=False)
        np.testing.assert_array_equal(before[9], before[10])   # no-op
        assert not np.array_equal(before[10], before[11])      # resumed
        assert guardian.guardian_stats()["steps_skipped"] == 1

    def test_fused_lr_spike_skips_bitwise_no_split(self):
        before, w, opt = _spike_run(fused=True)
        s = step_fusion_stats()
        assert s["fused_steps"] >= 2 and s["fallback_splits"] == 0
        np.testing.assert_array_equal(before[9], before[10])
        assert not np.array_equal(before[10], before[11])
        assert guardian.guardian_stats()["steps_skipped"] == 1

    def test_eager_and_fused_agree_bitwise(self):
        _, w_f, _ = _spike_run(fused=True)
        guardian.reset_thread_state()
        guardian.reset_guardian_stats()
        _, w_e, _ = _spike_run(fused=False)
        np.testing.assert_array_equal(np.asarray(w_f._value),
                                      np.asarray(w_e._value))

    def test_doctor_reports_which_step_skipped(self):
        for fused in (True, False):
            _reset()
            _spike_run(fused=fused)
            skips = [e for e in fusion_events("step.record")
                     if e["reason"] == "nonfinite_skip"]
            assert len(skips) == 1
            # optimizer step counter at the spike (10th step() call)
            assert skips[0]["detail"]["step"] == 10
            rep = explain()
            assert rep["guardian"]["nonfinite_skip"]["steps"] == [10]
            assert any("nonfinite_skip" in f and "at step(s) 10" in f
                       for f in rep["findings"])


# ---------------------------------------------------------------------------
# step-granular checkpoints (PR 6: save_every_n_steps)
# ---------------------------------------------------------------------------

class TestStepCheckpointer:
    def _loop(self, ck, steps, seed=0):
        rng = np.random.default_rng(seed)
        x = paddle.to_tensor(rng.standard_normal((4, 4)).astype(np.float32))
        w = paddle.to_tensor(rng.standard_normal((4, 4)).astype(np.float32),
                             stop_gradient=False)
        opt = paddle.optimizer.Momentum(learning_rate=1e-2, momentum=0.9,
                                        parameters=[w])
        for step in range(1, steps + 1):
            F.gelu(paddle.matmul(x, w)).sum().backward()
            opt.step()
            opt.clear_grad()
            ck.tick(step, model={"w": w}, optimizer=opt,
                    extra={"step": step})
        return w, opt

    def test_tick_grid_retention_and_bitwise_resume(self, tmp_path):
        ck = StepCheckpointer(str(tmp_path), save_every_n_steps=2,
                              max_checkpoints=2)
        w, opt = self._loop(ck, 6)
        # every 2nd step saved, newest 2 retained
        assert ck._retained_steps() == [4, 6]
        w2 = paddle.to_tensor(np.zeros((4, 4), np.float32),
                              stop_gradient=False)
        opt2 = paddle.optimizer.Momentum(learning_rate=1e-2, momentum=0.9,
                                         parameters=[w2])
        ck2 = StepCheckpointer(str(tmp_path), save_every_n_steps=2)
        resumed = ck2.restore(model={"w": w2}, optimizer=opt2)
        assert resumed == 6
        assert ck2.last_extra == {"step": 6}
        np.testing.assert_array_equal(np.asarray(w2._value),
                                      np.asarray(w._value))
        # optimizer step counter came back: LR schedules + step fusion
        # recording resume where the killed run stopped
        assert opt2._step_count == 6

    def test_off_grid_tick_is_a_noop(self, tmp_path):
        ck = StepCheckpointer(str(tmp_path), save_every_n_steps=100)
        assert ck.tick(7, model={}) is None
        assert ck._retained_steps() == []

    def test_restore_falls_back_past_corrupt(self, tmp_path):
        ck = StepCheckpointer(str(tmp_path), save_every_n_steps=2,
                              max_checkpoints=3)
        self._loop(ck, 6)
        newest = os.path.join(ck.checkpoint_path(6), ck.CKPT_FILE)
        with open(newest, "r+b") as f:
            f.seek(12)
            f.write(b"\xff\xff\xff")
        w2 = paddle.to_tensor(np.zeros((4, 4), np.float32),
                              stop_gradient=False)
        ck2 = StepCheckpointer(str(tmp_path), save_every_n_steps=2)
        assert ck2.restore(model={"w": w2}) == 4

    def test_refuses_when_every_snapshot_corrupt(self, tmp_path):
        ck = StepCheckpointer(str(tmp_path), save_every_n_steps=2,
                              max_checkpoints=2)
        self._loop(ck, 4)
        for s in ck._retained_steps():
            p = os.path.join(ck.checkpoint_path(s), ck.CKPT_FILE)
            with open(p, "r+b") as f:
                f.seek(12)
                f.write(b"\xff\xff\xff")
        with pytest.raises(fio.CheckpointCorruptError, match="refusing"):
            StepCheckpointer(str(tmp_path),
                             save_every_n_steps=2).restore(model={})

    def test_fresh_run_returns_minus_one(self, tmp_path):
        ck = StepCheckpointer(str(tmp_path))
        assert ck.restore(model={}) == -1


# ---------------------------------------------------------------------------
# chaos
# ---------------------------------------------------------------------------

def _load_chaos():
    spec = importlib.util.spec_from_file_location(
        "chaos", os.path.join(os.path.dirname(__file__), os.pardir,
                              "tools", "chaos.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestChaos:
    def test_nan_output_injection_skips_step(self):
        set_flags({"FLAGS_check_numerics": True,
                   "FLAGS_eager_step_fusion": False,
                   "FLAGS_eager_chain_fusion": False,
                   "FLAGS_profiler_events": True})
        clear_fusion_events()
        x, w, opt = _mk(seed=21)
        scaler = paddle.amp.GradScaler(init_loss_scaling=64.0,
                                       decr_every_n_nan_or_inf=1)
        _amp_step(x, w, opt, scaler)
        w_good = np.asarray(w._value).copy()
        inj = guardian.inject_fault("nan_output", op="matmul")
        try:
            _amp_step(x, w, opt, scaler)
        finally:
            inj.remove()
        guardian.flush()
        np.testing.assert_array_equal(w_good, np.asarray(w._value))
        stats = guardian.guardian_stats()
        assert stats["faults_injected"] == 1
        assert stats["steps_skipped"] == 1
        faults = [e for e in fusion_events("step.record")
                  if e["reason"] == "injected_fault"]
        assert len(faults) == 1

    def test_raise_injection_surfaces_and_recovers(self):
        set_flags({"FLAGS_check_numerics": True,
                   "FLAGS_eager_step_fusion": False,
                   "FLAGS_eager_chain_fusion": False})
        x, w, opt = _mk(seed=22)
        _plain_step(x, w, opt)
        w_before = np.asarray(w._value).copy()
        inj = guardian.inject_fault("raise", op="gelu")
        try:
            with pytest.raises(guardian.ChaosFault, match="injected"):
                _plain_step(x, w, opt)
        finally:
            inj.remove()
        opt.clear_grad()
        np.testing.assert_array_equal(w_before, np.asarray(w._value))
        _plain_step(x, w, opt)     # the loop keeps training
        assert not np.array_equal(w_before, np.asarray(w._value))
        assert np.all(np.isfinite(np.asarray(w._value)))

    def test_injector_after_and_times_budget(self):
        set_flags({"FLAGS_eager_chain_fusion": False,
                   "FLAGS_eager_step_fusion": False})
        x, w, opt = _mk(seed=23)
        inj = guardian.inject_fault("raise", op="matmul", after=1, times=1)
        try:
            paddle.matmul(x, w)                   # let through (after=1)
            with pytest.raises(guardian.ChaosFault):
                paddle.matmul(x, w)               # fires
            paddle.matmul(x, w)                   # disarmed (times=1)
        finally:
            inj.remove()

    @pytest.mark.perf_smoke
    def test_kill9_resume_matches_uninterrupted_run(self):
        chaos = _load_chaos()
        res = chaos.scenario_kill(epochs=3, steps=6)
        assert res["ok"], res["failures"]


class TestFusedTierFaultInjection:
    """PR 7: chaos can poison the FUSED tiers, not only raw dispatches —
    replayed chain/step ops never reach the dispatch hook, so without
    these sites the split-path recovery ladders were never exercised."""

    def test_fused_step_fault_splits_bitwise_and_recovers(self):
        """An injected fault at the fused-step fire recovers through the
        transactional per-op split: params update with the SAME values
        the eager path computes, the split is attributed
        `injected_fault`, and the next cycle replays fused again with
        zero retraces."""
        set_flags({"FLAGS_profiler_events": True})
        clear_fusion_events()
        x, w, opt = _mk(seed=31)
        for _ in range(8):
            _plain_step(x, w, opt)
        s0 = step_fusion_stats()
        assert s0["fused_steps"] > 0
        w_pre = np.asarray(w._value).copy()
        inj = guardian.inject_fault("raise", op="fused_step", times=1)
        try:
            _plain_step(x, w, opt)         # fault -> transactional split
        finally:
            inj.remove()
        s1 = step_fusion_stats()
        assert s1["fallback_splits"] == s0["fallback_splits"] + 1
        w_split = np.asarray(w._value).copy()
        _plain_step(x, w, opt)             # rejoins the fused path
        s2 = step_fusion_stats()
        assert s2["fused_steps"] > s1["fused_steps"]
        assert s2["retraces"] == s1["retraces"]
        splits = [e for e in fusion_events("step.split")
                  if e["reason"] == "injected_fault"]
        assert len(splits) == 1
        rep = explain()
        assert rep["guardian"].get("injected_fault", {}).get("count", 0) \
            >= 1
        # the split replayed through the per-op executables: its update
        # is BITWISE what an eager (unfused) step computes from the same
        # pre-split state
        set_flags({"FLAGS_eager_step_fusion": False,
                   "FLAGS_eager_chain_fusion": False})
        w2 = paddle.to_tensor(w_pre.copy(), stop_gradient=False)
        opt2 = paddle.optimizer.SGD(learning_rate=1e-2, parameters=[w2])
        _plain_step(x, w2, opt2)
        np.testing.assert_array_equal(w_split, np.asarray(w2._value))

    def test_fused_chain_nan_poison_is_detected(self):
        """Poisoning a fused CHAIN's outputs must not slip past the
        guardian: the downstream values are NaN and the flush raises,
        attributing both the injection and the non-finite output."""
        set_flags({"FLAGS_check_numerics": True,
                   "FLAGS_eager_step_fusion": False,
                   "FLAGS_profiler_events": True})
        clear_fusion_events()
        x, w, _ = _mk(seed=32)
        def fwd():
            return F.gelu(paddle.matmul(x, w)).sum()
        for _ in range(8):
            fwd().numpy()
        guardian.flush()
        inj = guardian.inject_fault("nan_output", op="fused_chain",
                                    times=1)
        try:
            y = fwd()
            assert np.isnan(y.numpy()).all()
            with pytest.raises(FloatingPointError):
                guardian.flush()
        finally:
            inj.remove()
        ev = fusion_events()
        assert any(e["reason"] == "injected_fault" for e in ev)
        assert any(e["reason"] == "nonfinite_output" for e in ev)

    def test_fused_chain_raise_splits_to_clean_values(self):
        """kind="raise" on the fused chain falls back per-op: the caller
        sees bitwise-clean values and a `chain.split` attributed
        `injected_fault` — never an exception, never NaN."""
        set_flags({"FLAGS_eager_step_fusion": False,
                   "FLAGS_profiler_events": True})
        clear_fusion_events()
        x, w, _ = _mk(seed=33)
        def fwd():
            return F.gelu(paddle.matmul(x, w)).sum()
        ref = None
        for _ in range(8):
            ref = fwd().numpy()
        inj = guardian.inject_fault("raise", op="fused_chain", times=1)
        try:
            val = fwd().numpy()
        finally:
            inj.remove()
        np.testing.assert_array_equal(ref, val)
        splits = [e for e in fusion_events("chain.split")
                  if e["reason"] == "injected_fault"]
        assert len(splits) == 1
