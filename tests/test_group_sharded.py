"""Group-sharded (ZeRO stage 1/2/3) tests on the 8-device virtual mesh.

Reference analog: unittests/collective/fleet/dygraph_group_sharded_stage2.py /
_stage3.py — sharded training must match unsharded training AND provably
store only 1/Nth of the state per device.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.mesh import build_mesh, set_global_mesh
from paddle_tpu.distributed.fleet.sharding_opt import shard_optimizer_states
from paddle_tpu.distributed.sharding import (
    group_sharded_parallel, save_group_sharded_model, shard_model_parameters)

N_DEV = 8


def _mlp():
    return nn.Sequential(
        nn.Linear(64, 128), nn.Tanh(),
        nn.Linear(128, 128), nn.Tanh(),
        nn.Linear(128, 64))


def _data():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(16, 64)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(16, 64)), jnp.float32)
    return paddle.Tensor(x, stop_gradient=True), \
        paddle.Tensor(y, stop_gradient=True)


def _loss(model, x, y):
    out = model(x)
    diff = out - y
    return (diff * diff).mean()


def _train(level, steps=6, lr=1e-2):
    """Eager loop (backward + optimizer.step) under the given sharding level;
    level=None trains unsharded on one device."""
    if level is None:
        set_global_mesh(build_mesh(dp=1, pp=1, sharding=1, sep=1, mp=1,
                                   devices=jax.devices()[:1]))
    else:
        set_global_mesh(build_mesh(dp=1, pp=1, sharding=N_DEV, sep=1, mp=1,
                                   devices=jax.devices()[:N_DEV]))
    paddle.seed(0)
    model = _mlp()
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=model.parameters())
    if level is not None:
        model, opt, _ = group_sharded_parallel(model, opt, level)
    x, y = _data()
    losses = []
    for _ in range(steps):
        loss = _loss(model, x, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses, model, opt


def _per_device_fraction(arrays):
    """sum(bytes held by device 0) / sum(global bytes) over `arrays`."""
    local = sum(a.addressable_shards[0].data.nbytes for a in arrays)
    total = sum(a.nbytes for a in arrays)
    return local / total


class TestShardOptimizerStates:
    """Direct tests of shard_optimizer_states (stage 1)."""

    def test_existing_accumulators_get_sharded(self):
        set_global_mesh(build_mesh(dp=1, pp=1, sharding=N_DEV, sep=1, mp=1,
                                   devices=jax.devices()[:N_DEV]))
        paddle.seed(0)
        model = _mlp()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        opt._create_accumulators(model.parameters())
        shard_optimizer_states(opt)
        mesh = None
        n_sharded = 0
        for name, per_param in opt._accumulators.items():
            for pname, val in per_param.items():
                if val.ndim and max(val.shape) % N_DEV == 0:
                    shd = val.sharding
                    assert isinstance(shd, NamedSharding), (name, pname)
                    assert "sharding" in jax.tree_util.tree_leaves(
                        [list(shd.spec)]) or "sharding" in tuple(shd.spec)
                    assert val.addressable_shards[0].data.size \
                        == val.size // N_DEV
                    n_sharded += 1
        assert n_sharded > 0

    def test_future_accumulators_sharded_at_creation(self):
        set_global_mesh(build_mesh(dp=1, pp=1, sharding=N_DEV, sep=1, mp=1,
                                   devices=jax.devices()[:N_DEV]))
        paddle.seed(0)
        model = _mlp()
        params = model.parameters()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=params)
        shard_optimizer_states(opt)          # before any accumulators exist
        opt._create_accumulators(params)     # created through the wrapper
        m1 = opt._accumulators["moment1"][params[0].name]
        assert m1.addressable_shards[0].data.size == m1.size // N_DEV

    def test_stage1_loss_parity(self):
        ref, _, _ = _train(None)
        got, _, opt = _train("os")
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        assert got[-1] < got[0]

    def test_stage1_state_memory_drops(self):
        _, _, opt = _train("os", steps=2)
        accs = [v for per in opt._accumulators.values()
                for v in per.values()]
        assert _per_device_fraction(accs) < 1.5 / N_DEV


class TestStage2:
    def test_stage2_loss_parity(self):
        ref, _, _ = _train(None)
        got, _, _ = _train("os_g")
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_stage2_grads_owned_sharded(self):
        """After step(), each device holds 1/N of every (divisible) grad —
        the reduce-scatter ownership of GroupShardedStage2."""
        set_global_mesh(build_mesh(dp=1, pp=1, sharding=N_DEV, sep=1, mp=1,
                                   devices=jax.devices()[:N_DEV]))
        paddle.seed(0)
        model = _mlp()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        model, opt, _ = group_sharded_parallel(model, opt, "os_g")
        x, y = _data()
        loss = _loss(model, x, y)
        loss.backward()
        opt.step()
        grads = [p.grad._value for p in model.parameters()
                 if p.grad is not None]
        assert grads
        assert _per_device_fraction(grads) < 1.5 / N_DEV


class TestOffload:
    def test_offload_keeps_states_on_host_across_steps(self):
        """Round-3 regression: offloaded accumulators silently migrated
        back to device after the first update. The _OffloadedStateOptimizer
        wrapper must re-pin them to host after EVERY step, with losses
        identical to the un-offloaded run (cost recorded in BASELINE.md)."""
        ref, _, _ = _train(None)
        set_global_mesh(build_mesh(dp=1, pp=1, sharding=N_DEV, sep=1, mp=1,
                                   devices=jax.devices()[:N_DEV]))
        paddle.seed(0)
        model = _mlp()
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        model, opt, _ = group_sharded_parallel(model, opt, "os_g",
                                               offload=True)
        x, y = _data()
        losses = []
        for _ in range(6):
            loss = _loss(model, x, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        np.testing.assert_allclose(losses, ref, rtol=1e-5, atol=1e-6)
        inner = opt
        while hasattr(inner, "_inner"):
            inner = inner._inner
        host = jax.devices("cpu")[0]
        n = 0
        for per in inner._accumulators.values():
            for v in per.values():
                if hasattr(v, "devices"):
                    assert v.devices() == {host}, \
                        "state not pinned to the host device"
                    n += 1
        assert n > 0


class TestStage3:
    def test_stage3_loss_parity(self):
        ref, _, _ = _train(None)
        got, _, _ = _train("p_g_os")
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
        assert got[-1] < got[0]

    def test_stage3_memory_proof(self):
        """Per-device live bytes for params + optimizer state drop to ~1/N
        of the replicated footprint (the GroupShardedStage3 guarantee)."""
        _, model, opt = _train("p_g_os", steps=3)
        params = [p._value for p in model.parameters()]
        accs = [v for per in opt._accumulators.values()
                for v in per.values()]
        frac = _per_device_fraction(params + accs)
        assert frac < 1.5 / N_DEV, f"per-device fraction {frac:.3f}"

    def test_stage3_params_stay_sharded_across_steps(self):
        _, model, _ = _train("p_g_os", steps=3)
        n = 0
        for p in model.parameters():
            if p._value.ndim and max(p._value.shape) % N_DEV == 0:
                assert p._value.addressable_shards[0].data.size \
                    == p._value.size // N_DEV
                n += 1
        assert n > 0

    def test_shard_model_parameters_direct(self):
        set_global_mesh(build_mesh(dp=1, pp=1, sharding=N_DEV, sep=1, mp=1,
                                   devices=jax.devices()[:N_DEV]))
        paddle.seed(0)
        model = shard_model_parameters(_mlp())
        w = model[0].weight._value
        assert isinstance(w.sharding, NamedSharding)
        assert w.addressable_shards[0].data.size == w.size // N_DEV


class TestLevelsAndSave:
    def test_bad_level_raises(self):
        set_global_mesh(build_mesh(dp=1, pp=1, sharding=N_DEV, sep=1, mp=1,
                                   devices=jax.devices()[:N_DEV]))
        paddle.seed(0)
        model = _mlp()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        with pytest.raises(AssertionError):
            group_sharded_parallel(model, opt, "stage7")

    def test_save_group_sharded_model(self, tmp_path):
        losses, model, opt = _train("p_g_os", steps=2)
        save_group_sharded_model(model, str(tmp_path), opt)
        assert (tmp_path / "model.pdmodel").exists()
        assert (tmp_path / "model.pdopt").exists()
        state = paddle.load(str(tmp_path / "model.pdmodel"))
        assert len(state) > 0

    def test_offload_states_to_host(self):
        set_global_mesh(build_mesh(dp=1, pp=1, sharding=N_DEV, sep=1, mp=1,
                                   devices=jax.devices()[:N_DEV]))
        paddle.seed(0)
        model = _mlp()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        model, opt, _ = group_sharded_parallel(model, opt, "os",
                                               offload=True)
        for per in opt._accumulators.values():
            for val in per.values():
                assert val.sharding.device_set == {jax.devices("cpu")[0]}
