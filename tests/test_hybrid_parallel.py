"""Direct parity tests for the Megatron mp layer classes under shard_map.

Reference analog: unittests/collective/fleet/hybrid_parallel_mp_layers.py —
each parallel layer, fed per-rank weight shards, must reproduce its dense
counterpart (forward AND backward), and the vocab-parallel embedding must
implement exact c_embedding masked-lookup semantics.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.mesh import build_mesh, set_global_mesh
from paddle_tpu.distributed.fleet.meta_parallel import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy)

MP = 4


def _mesh():
    mesh = build_mesh(dp=2, pp=1, sharding=1, sep=1, mp=MP,
                      devices=jax.devices()[:8])
    set_global_mesh(mesh)
    return mesh


def _swap_run(layer, params_specs, x_spec, out_spec, mesh, *arrays):
    """Run `layer` inside a shard_map over the "model" axis, swapping the
    given (param, spec) pairs in as per-rank local shards."""
    params = [p for p, _ in params_specs]
    specs = [s for _, s in params_specs]

    def inner(x, *pvals):
        saved = [p._value for p in params]
        try:
            for p, v in zip(params, pvals):
                p._value = v
            out = layer(paddle.Tensor(x, stop_gradient=True))._value
        finally:
            for p, v in zip(params, saved):
                p._value = v
        return _as_varying(out)[None]

    # every rank's result is returned stacked over a leading "model" dim
    # (replicated outputs appear n_model times; callers index [0] or
    # reassemble local shards)
    return jax.shard_map(inner, mesh=mesh, axis_names={"model"},
                         in_specs=(x_spec, *specs),
                         out_specs=P("model", *out_spec))(*arrays)


def _as_varying(v):
    """Mark an invariant (psum-produced) value varying so it can ride a
    P("model", ...) out_spec; values already varying pass through."""
    try:
        return jax.lax.pcast(v, "model", to="varying")
    except ValueError:
        return v


class TestColumnParallelLinear:
    def test_forward_matches_dense(self):
        mesh = _mesh()
        paddle.seed(0)
        layer = ColumnParallelLinear(16, 24, has_bias=True,
                                     gather_output=True)
        W = jnp.asarray(np.array(layer.weight._value))
        b = jnp.asarray(np.array(layer.bias._value))
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(6, 16)), jnp.float32)
        got = _swap_run(layer, [(layer.weight, P(None, "model")),
                                (layer.bias, P("model"))],
                        P(), P(), mesh, x, W, b)
        ref = x @ W + b
        for r in range(MP):   # gathered output is replicated on every rank
            np.testing.assert_allclose(np.asarray(got[r]), np.asarray(ref),
                                       rtol=1e-5, atol=1e-5)

    def test_no_gather_returns_local_shard(self):
        mesh = _mesh()
        paddle.seed(0)
        layer = ColumnParallelLinear(16, 24, has_bias=False,
                                     gather_output=False)
        W = jnp.asarray(np.array(layer.weight._value))
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(6, 16)), jnp.float32)
        got = _swap_run(layer, [(layer.weight, P(None, "model"))],
                        P(), P(None, None), mesh, x, W)
        ref = x @ W
        reassembled = np.concatenate([np.asarray(got[r])
                                      for r in range(MP)], axis=-1)
        np.testing.assert_allclose(reassembled, np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_weight_grad_matches_dense(self):
        mesh = _mesh()
        paddle.seed(0)
        layer = ColumnParallelLinear(16, 24, has_bias=False,
                                     gather_output=True)
        W = jnp.asarray(np.array(layer.weight._value))
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(6, 16)), jnp.float32)

        def loss_mp(w):
            y = _swap_run(layer, [(layer.weight, P(None, "model"))],
                          P(), P(), mesh, x, w)
            return jnp.sum(y[0] ** 2)

        def loss_dense(w):
            return jnp.sum((x @ w) ** 2)

        g_mp = jax.grad(loss_mp)(W)
        g_dense = jax.grad(loss_dense)(W)
        np.testing.assert_allclose(np.asarray(g_mp), np.asarray(g_dense),
                                   rtol=1e-4, atol=1e-4)


class TestRowParallelLinear:
    def test_forward_matches_dense(self):
        mesh = _mesh()
        paddle.seed(0)
        layer = RowParallelLinear(16, 24, has_bias=True,
                                  input_is_parallel=True)
        W = jnp.asarray(np.array(layer.weight._value))
        b = jnp.asarray(np.array(layer.bias._value))
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(6, 16)), jnp.float32)
        # x is split along the contraction dim (input_is_parallel)
        got = _swap_run(layer, [(layer.weight, P("model", None)),
                                (layer.bias, P())],
                        P(None, "model"), P(), mesh, x, W, b)
        ref = x @ W + b
        np.testing.assert_allclose(np.asarray(got[0]), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_weight_grad_matches_dense(self):
        mesh = _mesh()
        paddle.seed(0)
        layer = RowParallelLinear(16, 24, has_bias=False)
        W = jnp.asarray(np.array(layer.weight._value))
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(6, 16)), jnp.float32)

        def loss_mp(w):
            y = _swap_run(layer, [(layer.weight, P("model", None))],
                          P(None, "model"), P(), mesh, x, w)
            return jnp.sum(y[0] ** 2)

        g_mp = jax.grad(loss_mp)(W)
        g_dense = jax.grad(lambda w: jnp.sum((x @ w) ** 2))(W)
        np.testing.assert_allclose(np.asarray(g_mp), np.asarray(g_dense),
                                   rtol=1e-4, atol=1e-4)


class TestVocabParallelEmbedding:
    def test_masked_lookup_matches_dense(self):
        """ids spanning every shard: masked local lookup + psum must equal
        the dense gather (c_embedding_op.cc semantics)."""
        mesh = _mesh()
        paddle.seed(0)
        V, D = 32, 12
        layer = VocabParallelEmbedding(V, D)
        W = jnp.asarray(np.array(layer.weight._value))
        ids = jnp.asarray([0, 5, 7, 8, 15, 16, 23, 24, 31, 2, 19, 28],
                          jnp.int32).reshape(3, 4)
        got = _swap_run(layer, [(layer.weight, P("model", None))],
                        P(), P(), mesh, ids, W)
        ref = jnp.take(W, ids, axis=0)
        np.testing.assert_allclose(np.asarray(got[0]), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)

    def test_weight_grad_matches_dense(self):
        mesh = _mesh()
        paddle.seed(0)
        V, D = 32, 12
        layer = VocabParallelEmbedding(V, D)
        W = jnp.asarray(np.array(layer.weight._value))
        ids = jnp.asarray(np.arange(32).reshape(4, 8) % V, jnp.int32)

        def loss_mp(w):
            y = _swap_run(layer, [(layer.weight, P("model", None))],
                          P(), P(), mesh, ids, w)
            return jnp.sum(y[0] ** 2)

        g_mp = jax.grad(loss_mp)(W)
        g_dense = jax.grad(
            lambda w: jnp.sum(jnp.take(w, ids, axis=0) ** 2))(W)
        np.testing.assert_allclose(np.asarray(g_mp), np.asarray(g_dense),
                                   rtol=1e-4, atol=1e-4)

    def test_dense_path_outside_spmd(self):
        _mesh()
        paddle.seed(0)
        layer = VocabParallelEmbedding(32, 12)
        ids = paddle.Tensor(jnp.asarray([[1, 2], [3, 4]], jnp.int32),
                            stop_gradient=True)
        out = layer(ids)
        assert tuple(out.shape) == (2, 2, 12)


class TestParallelCrossEntropy:
    def test_matches_dense_cross_entropy(self):
        mesh = _mesh()
        paddle.seed(0)
        V, B = 32, 6
        layer = ParallelCrossEntropy()
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(size=(B, V)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, V, (B,)), jnp.int32)

        def inner(lg):
            return layer(paddle.Tensor(lg, stop_gradient=True),
                         paddle.Tensor(labels, stop_gradient=True))._value

        got = jax.shard_map(inner, mesh=mesh, axis_names={"model"},
                            in_specs=P(None, "model"),
                            out_specs=P())(logits)
        from paddle_tpu.nn.functional.loss import cross_entropy
        ref = cross_entropy(paddle.Tensor(logits),
                            paddle.Tensor(labels), reduction="none")
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(ref._value).reshape(-1),
                                   rtol=1e-5, atol=1e-5)

    def test_logits_grad_matches_dense(self):
        mesh = _mesh()
        paddle.seed(0)
        V, B = 32, 6
        layer = ParallelCrossEntropy()
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(size=(B, V)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, V, (B,)), jnp.int32)

        def loss_mp(lg):
            def inner(l):
                return layer(paddle.Tensor(l, stop_gradient=True),
                             paddle.Tensor(labels,
                                           stop_gradient=True))._value
            v = jax.shard_map(inner, mesh=mesh, axis_names={"model"},
                              in_specs=P(None, "model"), out_specs=P())(lg)
            return jnp.sum(v)

        def loss_dense(lg):
            m = jax.nn.log_softmax(lg, axis=-1)
            return -jnp.sum(jnp.take_along_axis(m, labels[:, None],
                                                axis=-1))

        g_mp = jax.grad(loss_mp)(logits)
        g_dense = jax.grad(loss_dense)(logits)
        np.testing.assert_allclose(np.asarray(g_mp), np.asarray(g_dense),
                                   rtol=1e-4, atol=1e-4)


class TestGlobalNormClip:
    def test_clip_correct_with_mixed_placements(self):
        """Global-norm clip over grads with different shardings (replicated,
        model-sharded, sharding-axis-sharded) matches the single-device
        computation — the cross-group clip of
        hybrid_parallel_optimizer.py:96."""
        from jax.sharding import NamedSharding
        mesh = _mesh()
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(16, 32), nn.Tanh(),
                              nn.Linear(32, 16))
        clip = nn.ClipGradByGlobalNorm(0.01)
        rng = np.random.default_rng(0)
        params = [p for p in model.parameters()]
        grads = [jnp.asarray(rng.normal(size=p._value.shape), jnp.float32)
                 for p in params]
        # mixed placements: shard some grads over model / sharding axes
        placed = []
        for i, g in enumerate(grads):
            if g.ndim == 2 and i % 2 == 0:
                g = jax.device_put(
                    g, NamedSharding(mesh, P(None, "model")))
            placed.append(g)
        pg = [(p, paddle.Tensor(g)) for p, g in zip(params, placed)]
        clipped = clip(pg)
        gnorm = float(np.sqrt(sum(float(jnp.sum(g ** 2)) for g in grads)))
        scale = min(1.0, 0.01 / (gnorm + 1e-6))
        for (_, cg), g in zip(clipped, grads):
            np.testing.assert_allclose(np.asarray(cg._value),
                                       np.asarray(g) * scale,
                                       rtol=1e-5, atol=1e-6)


class TestParallelCrossEntropyIgnoreIndex:
    def test_ignore_index_matches_dense(self):
        """Ignored labels must contribute zero loss in the SPMD path too
        (regression: log(denom) leaked through for out-of-range labels)."""
        mesh = _mesh()
        paddle.seed(0)
        V, B = 32, 4
        layer = ParallelCrossEntropy(ignore_index=-100)
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(size=(B, V)), jnp.float32)
        labels = jnp.asarray([3, -100, 7, -100], jnp.int32)

        def inner(lg):
            return layer(paddle.Tensor(lg, stop_gradient=True),
                         paddle.Tensor(labels, stop_gradient=True))._value

        got = jax.shard_map(inner, mesh=mesh, axis_names={"model"},
                            in_specs=P(None, "model"), out_specs=P())(logits)
        got = np.asarray(got)
        assert got[1] == 0.0 and got[3] == 0.0
        from paddle_tpu.nn.functional.loss import cross_entropy
        ref = cross_entropy(paddle.Tensor(logits), paddle.Tensor(labels),
                            reduction="none", ignore_index=-100)
        np.testing.assert_allclose(got, np.asarray(ref._value).reshape(-1),
                                   rtol=1e-5, atol=1e-6)
