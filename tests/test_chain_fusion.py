"""Eager op-chain fusion: the fused-executable layer (ops/fusion.py).

Covers bitwise parity of fused chains vs unfused per-op dispatch (fwd and
fwd+bwd), chain invalidation (registry-generation bump and
clear_dispatch_cache), mid-chain fallback/splitting when an intermediate
escapes the chain, the FLAGS_eager_op_cache_size=0 bypass semantics, the
chain LRU, and the tier-1 micro-benchmark: a repeated matmul→add→gelu
fwd+bwd loop must show zero post-warmup retraces, fewer executable launches
than op count, and beat the per-op cache by ≥1.3x wall time.
"""
import time

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops.dispatch import clear_dispatch_cache, dispatch_cache_info
from paddle_tpu.ops.fusion import chain_cache_info
from paddle_tpu.ops.registry import get_op, override_kernel
from paddle_tpu.profiler import (chain_fusion_stats, dispatch_cache_stats,
                                 reset_chain_fusion_stats,
                                 reset_dispatch_cache_stats)

_DEFAULT_FLAGS = {
    "FLAGS_eager_op_cache": True,
    "FLAGS_eager_op_cache_size": 512,
    "FLAGS_eager_op_cache_donate": False,
    "FLAGS_eager_chain_fusion": True,
    "FLAGS_eager_chain_fusion_min_count": 3,
    "FLAGS_eager_chain_cache_size": 128,
    "FLAGS_eager_chain_stitching": True,
    # chain-layer tests must see chains, not whole-step replays
    "FLAGS_eager_step_fusion": False,
}


@pytest.fixture(autouse=True)
def _fresh_caches():
    clear_dispatch_cache()
    reset_dispatch_cache_stats()
    reset_chain_fusion_stats()
    set_flags(dict(_DEFAULT_FLAGS))
    yield
    clear_dispatch_cache()
    reset_dispatch_cache_stats()
    reset_chain_fusion_stats()
    set_flags(dict(_DEFAULT_FLAGS))


def _t(arr, stop_gradient=True):
    return paddle.to_tensor(np.asarray(arr), stop_gradient=stop_gradient)


def _mlp_inputs(b=8, i=16, o=16, stop_gradient=False):
    rng = np.random.default_rng(7)
    x = _t(rng.standard_normal((b, i)).astype(np.float32))
    w = _t(rng.standard_normal((i, o)).astype(np.float32),
           stop_gradient=stop_gradient)
    bias = _t(rng.standard_normal(o).astype(np.float32),
              stop_gradient=stop_gradient)
    return x, w, bias


def _fwd_bwd_step(x, w, b):
    """One matmul→add→gelu→sum fwd+bwd iteration; returns every numeric
    artifact for bitwise comparison."""
    y = F.gelu(paddle.add(paddle.matmul(x, w), b))
    loss = y.sum()
    loss.backward()
    out = (y.numpy().copy(), loss.numpy().copy(),
           w.grad.numpy().copy(), b.grad.numpy().copy())
    w.clear_grad()
    b.clear_grad()
    return out


def _run_loop(iters, fused, x, w, b, step=_fwd_bwd_step):
    set_flags({"FLAGS_eager_chain_fusion": fused})
    clear_dispatch_cache()
    return [step(x, w, b) for _ in range(iters)]


class TestParity:
    def test_fwd_bwd_bitwise_parity(self):
        """Fused replays must be bitwise-identical to per-op dispatch:
        forward values, loss, and both parameter grads."""
        x, w, b = _mlp_inputs()
        unfused = _run_loop(12, False, x, w, b)
        fused = _run_loop(12, True, x, w, b)
        assert chain_fusion_stats()["fused_replays"] > 0, \
            "fusion never replayed — the parity check would be vacuous"
        for u, f in zip(unfused, fused):
            for i, (uv, fv) in enumerate(zip(u, f)):
                np.testing.assert_array_equal(uv, fv, err_msg=f"field {i}")

    def test_fwd_only_bitwise_parity(self):
        """No-grad chains (stop_gradient inputs) fuse and stay bitwise
        identical too."""
        x, w, b = _mlp_inputs(stop_gradient=True)

        def step(x, w, b):
            return F.gelu(paddle.add(paddle.matmul(x, w), b)).numpy().copy()

        unfused = _run_loop(12, False, x, w, b, step=step)
        fused = _run_loop(12, True, x, w, b, step=step)
        assert chain_fusion_stats()["fused_replays"] > 0
        for u, f in zip(unfused, fused):
            np.testing.assert_array_equal(u, f)

    def test_double_grad_parity_through_fused_chain(self):
        """create_graph=True double grad replays the fused node's recorded
        pure forward (FusedChainNode.fwd_fn) — results must match the
        unfused path bitwise."""
        def run(fused):
            set_flags({"FLAGS_eager_chain_fusion": fused})
            clear_dispatch_cache()
            rng = np.random.default_rng(11)
            x = _t(rng.standard_normal((4, 8)).astype(np.float32),
                   stop_gradient=False)
            w = _t(rng.standard_normal((8, 8)).astype(np.float32),
                   stop_gradient=False)
            b = _t(rng.standard_normal(8).astype(np.float32),
                   stop_gradient=False)
            outs = []
            for _ in range(8):
                y = F.gelu(paddle.add(paddle.matmul(x, w), b))
                (gx,) = paddle.grad([y.sum()], [x], create_graph=True)
                (ggw,) = paddle.grad([gx.sum()], [w])
                outs.append((gx.numpy().copy(), ggw.numpy().copy()))
            return outs

        unfused = run(False)
        fused = run(True)
        assert chain_fusion_stats()["fused_replays"] > 0
        for u, f in zip(unfused, fused):
            np.testing.assert_array_equal(u[0], f[0])
            np.testing.assert_array_equal(u[1], f[1])

    def test_fused_node_is_single_tape_node(self):
        """A fused chain records ONE FusedChainNode owning every op's
        outputs instead of N per-op nodes."""
        from paddle_tpu.framework.autograd import FusedChainNode
        x, w, b = _mlp_inputs()
        set_flags({"FLAGS_eager_chain_fusion": True})
        for _ in range(8):
            y = F.gelu(paddle.add(paddle.matmul(x, w), b))
            loss = y.sum()
            loss.backward()
            w.clear_grad(); b.clear_grad()
        assert chain_fusion_stats()["fused_replays"] > 0
        y = F.gelu(paddle.add(paddle.matmul(x, w), b))
        loss = y.sum()
        node = loss._grad_node
        assert isinstance(node, FusedChainNode)
        assert node.op_names == ("matmul", "add", "gelu", "sum")
        # flattened-output attribution: the loss is sum's output 0
        assert node.output_owner(loss._out_index) == ("sum", 0)
        loss.backward()
        w.clear_grad(); b.clear_grad()


class TestEscapesAndSplits:
    def test_mid_chain_value_escape_splits(self):
        """Reading an intermediate's buffer mid-chain splits the replay;
        numerics stay identical to per-op dispatch."""
        x, w, b = _mlp_inputs()

        def step(x, w, b):
            h = paddle.add(paddle.matmul(x, w), b)
            probe = h.numpy().copy()          # escapes a pending chain
            y = F.gelu(h)
            loss = y.sum()
            loss.backward()
            out = (probe, y.numpy().copy(), loss.numpy().copy(),
                   w.grad.numpy().copy(), b.grad.numpy().copy())
            w.clear_grad(); b.clear_grad()
            return out

        unfused = _run_loop(12, False, x, w, b, step=step)
        fused = _run_loop(12, True, x, w, b, step=step)
        for u, f in zip(unfused, fused):
            for i, (uv, fv) in enumerate(zip(u, f)):
                np.testing.assert_array_equal(uv, fv, err_msg=f"field {i}")

    def test_escape_is_counted(self):
        """An intermediate forced out of a pending chain shows up in the
        escape/split telemetry."""
        x, w, b = _mlp_inputs()
        # make matmul→add→gelu→sum hot
        for _ in range(8):
            _fwd_bwd_step(x, w, b)
        assert chain_fusion_stats()["fused_replays"] > 0
        before = chain_fusion_stats()
        # now break the pattern mid-chain: force the add output while the
        # chain is still pending
        h = paddle.add(paddle.matmul(x, w), b)
        _ = h.numpy()
        after = chain_fusion_stats()
        assert after["fallback_splits"] > before["fallback_splits"]
        assert after["escapes"] > before["escapes"]
        # the escaped prefix still computes correctly
        y = F.gelu(h)
        loss = y.sum()
        loss.backward()
        assert w.grad is not None
        w.clear_grad(); b.clear_grad()

    def test_grad_through_side_output_after_split(self):
        """backward() through a mid-chain intermediate (tape read while the
        chain is pending) splits and still produces correct grads."""
        x, w, b = _mlp_inputs()
        for _ in range(8):
            _fwd_bwd_step(x, w, b)

        h = paddle.add(paddle.matmul(x, w), b)
        h.backward(paddle.ones_like(h))       # forces the pending chain
        got = w.grad.numpy().copy()
        w.clear_grad(); b.clear_grad()

        set_flags({"FLAGS_eager_chain_fusion": False})
        clear_dispatch_cache()
        h2 = paddle.add(paddle.matmul(x, w), b)
        h2.backward(paddle.ones_like(h2))
        np.testing.assert_array_equal(got, w.grad.numpy())
        w.clear_grad(); b.clear_grad()


class TestInvalidation:
    def test_clear_dispatch_cache_drops_chains(self):
        x, w, b = _mlp_inputs()
        for _ in range(8):
            _fwd_bwd_step(x, w, b)
        assert chain_cache_info()["entries"] > 0
        clear_dispatch_cache()
        assert chain_cache_info()["entries"] == 0

    def test_registry_bump_invalidates_head_op(self):
        """An override on the chain's head op takes effect on the very next
        call: the bumped generation re-keys the op, the stale chain stops
        matching."""
        x, w, b = _mlp_inputs()
        for _ in range(8):
            _fwd_bwd_step(x, w, b)
        assert chain_fusion_stats()["fused_replays"] > 0
        base = _fwd_bwd_step(x, w, b)

        gen0 = get_op("matmul").generation
        override_kernel("matmul", "doubled",
                        lambda a, bm: jnp.matmul(a, bm) * 2.0, activate=True)
        try:
            assert get_op("matmul").generation > gen0
            doubled = _fwd_bwd_step(x, w, b)
            # the head op's change must flow through everything downstream
            assert not np.array_equal(doubled[0], base[0])
            set_flags({"FLAGS_eager_chain_fusion": False})
            clear_dispatch_cache()
            ref = _fwd_bwd_step(x, w, b)
            for i, (dv, rv) in enumerate(zip(doubled, ref)):
                np.testing.assert_array_equal(dv, rv, err_msg=f"field {i}")
        finally:
            get_op("matmul").active = None

    def test_registry_bump_invalidates_mid_chain_op(self):
        """An override on a MID-chain op: the replay defers the head, hits
        the key mismatch, splits, and the override still serves this very
        call — numerics never lag the registry."""
        x, w, b = _mlp_inputs()
        for _ in range(8):
            _fwd_bwd_step(x, w, b)
        base = _fwd_bwd_step(x, w, b)

        override_kernel("gelu", "scaled",
                        lambda v: jnp.asarray(
                            0.5 * v * (1.0 + jnp.tanh(v)), v.dtype) * 3.0,
                        activate=True)
        try:
            changed = _fwd_bwd_step(x, w, b)
            assert not np.array_equal(changed[0], base[0])
            set_flags({"FLAGS_eager_chain_fusion": False})
            clear_dispatch_cache()
            ref = _fwd_bwd_step(x, w, b)
            for i, (cv, rv) in enumerate(zip(changed, ref)):
                np.testing.assert_array_equal(cv, rv, err_msg=f"field {i}")
        finally:
            get_op("gelu").active = None


class TestFlags:
    def test_op_cache_size_zero_disables_caching(self):
        """FLAGS_eager_op_cache_size=0 must disable the per-op cache
        entirely — no entries, bypasses counted, numerics unchanged."""
        set_flags({"FLAGS_eager_op_cache_size": 0})
        clear_dispatch_cache()
        reset_dispatch_cache_stats()
        x = _t(np.linspace(-1, 1, 8, dtype=np.float32))
        a = paddle.exp(x).numpy()
        b = paddle.exp(x).numpy()
        np.testing.assert_allclose(
            a, np.exp(np.linspace(-1, 1, 8, dtype=np.float32)), rtol=1e-6)
        np.testing.assert_array_equal(a, b)
        s = dispatch_cache_stats()
        assert s["hits"] == 0 and s["misses"] == 0
        assert s["bypasses"] >= 2
        assert dispatch_cache_info()["entries"] == 0

    def test_chain_fusion_off_means_no_replays(self):
        set_flags({"FLAGS_eager_chain_fusion": False})
        x, w, b = _mlp_inputs()
        for _ in range(10):
            _fwd_bwd_step(x, w, b)
        s = chain_fusion_stats()
        assert s["fused_replays"] == 0 and s["chains_detected"] == 0

    def test_chain_cache_size_zero_means_no_replays(self):
        set_flags({"FLAGS_eager_chain_cache_size": 0})
        x, w, b = _mlp_inputs()
        for _ in range(10):
            _fwd_bwd_step(x, w, b)
        assert chain_fusion_stats()["fused_replays"] == 0

    def test_chain_lru_eviction(self):
        """Distinct hot chains past FLAGS_eager_chain_cache_size evict the
        least-recently-replayed one."""
        set_flags({"FLAGS_eager_chain_cache_size": 1})
        x, w, b = _mlp_inputs()
        x2, w2, b2 = _mlp_inputs(b=4, i=8, o=8)  # different avals → new keys
        for _ in range(8):
            _fwd_bwd_step(x, w, b)
        for _ in range(8):
            _fwd_bwd_step(x2, w2, b2)
        info = chain_cache_info()
        assert info["entries"] <= 1
        assert chain_fusion_stats()["evictions"] > 0


class TestWindowStitching:
    """Adjacent hot chains stitch into one longer chain (PR 3): sequences
    longer than the 8-op rolling window converge to a single launch."""

    @staticmethod
    def _pipeline(x, depth=8):
        h = x
        for _ in range(depth):
            h = paddle.tanh(h)
            h = paddle.scale(h, 0.9)
            h = paddle.exp(paddle.scale(h, 0.1))
        return h                     # 3 * depth unary ops, one dataflow

    def test_stitching_fuses_16_plus_op_chain(self):
        """A 24-op body converges past the 8-op detection window: a single
        stitched chain of ≥16 ops ends up doing the replays, bitwise equal
        to the unfused pipeline."""
        x = _t(np.linspace(-1.0, 1.0, 32, dtype=np.float32).reshape(4, 8))
        outs = []
        for _ in range(40):
            outs.append(self._pipeline(x).numpy().copy())
        s = chain_fusion_stats()
        assert s["chains_stitched"] >= 1, s
        info = chain_cache_info()
        long_replayed = [c for c in info["chains"]
                         if c["ops"] >= 16 and c["replays"] > 0]
        assert long_replayed, \
            f"no ≥16-op chain replayed: {[(c['ops'], c['replays']) for c in info['chains']]}"
        set_flags({"FLAGS_eager_chain_fusion": False})
        clear_dispatch_cache()
        ref = self._pipeline(x).numpy()
        np.testing.assert_array_equal(outs[-1], ref)

    def test_stitched_replay_counts_launches_saved_once(self):
        """Telemetry must not double-count: in the stitched steady state,
        each replay of an L-op chain adds exactly L-1 launches saved — the
        constituent chains stop replaying entirely."""
        x = _t(np.linspace(-1.0, 1.0, 32, dtype=np.float32).reshape(4, 8))
        for _ in range(40):            # converge to the stitched chain
            self._pipeline(x)
        info = chain_cache_info()
        top = max((c for c in info["chains"] if c["replays"] > 0),
                  key=lambda c: c["ops"])
        s0 = chain_fusion_stats()
        for _ in range(5):
            self._pipeline(x)
        s1 = chain_fusion_stats()
        replays = s1["fused_replays"] - s0["fused_replays"]
        saved = s1["launches_saved"] - s0["launches_saved"]
        assert replays > 0
        # every steady-state replay is the one stitched chain: launches
        # saved must be exactly (L-1) per replay, not the sum over the
        # constituent chains as well
        assert saved == replays * (top["ops"] - 1), \
            (saved, replays, top["ops"])

    def test_stitching_disabled_keeps_window_sized_chains(self):
        from paddle_tpu.ops.fusion import _WINDOW
        set_flags({"FLAGS_eager_chain_stitching": False})
        x = _t(np.linspace(-1.0, 1.0, 32, dtype=np.float32).reshape(4, 8))
        for _ in range(40):
            self._pipeline(x)
        s = chain_fusion_stats()
        assert s["chains_stitched"] == 0
        info = chain_cache_info()
        assert all(c["ops"] <= _WINDOW for c in info["chains"]), \
            [c["ops"] for c in info["chains"]]

    def test_stitched_chain_backward_parity(self):
        """Stitched chains in a grad-recording pipeline: forward values
        stay bitwise identical to the unfused path; the fused backward of
        a long (18-op) chain is ONE XLA program whose reassociation can
        differ from the per-op multiply sequence at the last ULP (the same
        single-program compilation noise as jit.TrainStep), so grads are
        checked at ULP-scale tolerance. Fallback splits remain bitwise —
        covered by TestEscapesAndSplits."""
        def run(fused):
            set_flags({"FLAGS_eager_chain_fusion": fused})
            clear_dispatch_cache()
            rng = np.random.default_rng(5)
            x = _t(rng.standard_normal((4, 8)).astype(np.float32),
                   stop_gradient=False)
            out = []
            for _ in range(30):
                y = self._pipeline(x, depth=6)     # 18 ops
                loss = y.sum()
                loss.backward()
                out.append((loss.numpy().copy(), x.grad.numpy().copy()))
                x.clear_grad()
            return out

        unfused = run(False)
        fused = run(True)
        assert chain_fusion_stats()["chains_stitched"] >= 1
        for u, f in zip(unfused, fused):
            np.testing.assert_array_equal(u[0], f[0])
            np.testing.assert_allclose(u[1], f[1], rtol=2e-6, atol=1e-12)


class TestMicroBenchmark:
    @pytest.mark.perf_smoke
    def test_zero_post_warmup_retraces_and_fewer_launches(self):
        """After warmup a 3-op matmul→add→gelu fwd+bwd chain replays with
        zero new traces anywhere (per-op AND chain executables) and fewer
        executable launches than op count."""
        x, w, b = _mlp_inputs()
        seed = paddle.ones_like(paddle.matmul(x, w))

        def step():
            y = F.gelu(paddle.add(paddle.matmul(x, w), b))
            y.backward(seed)                  # 3-op chain, no loss reduce
            w.clear_grad(); b.clear_grad()

        for _ in range(10):
            step()                            # warmup: detect + compile
        d0 = dispatch_cache_stats()
        c0 = chain_fusion_stats()
        for _ in range(30):
            step()
        d1 = dispatch_cache_stats()
        c1 = chain_fusion_stats()
        assert d1["retraces"] == d0["retraces"], "per-op retrace post-warmup"
        assert c1["retraces"] == c0["retraces"], "chain retrace post-warmup"
        replays = c1["fused_replays"] - c0["fused_replays"]
        assert replays >= 25, f"chain barely replayed: {replays}/30"
        # 3 ops per iteration, ≥2 launches saved per replay → strictly
        # fewer executable launches than op count
        saved = c1["launches_saved"] - c0["launches_saved"]
        assert saved >= 2 * replays

    @pytest.mark.perf_smoke
    def test_fused_beats_per_op_cache(self):
        """The acceptance micro-benchmark: fused chain replay beats the
        PR 1 per-op cache by ≥1.3x wall time on a repeated matmul→add→gelu
        fwd+bwd loop (CPU). Best-of-2 timing per mode, up to 4 attempts, to
        keep shared-CI noise out of the signal."""
        rng = np.random.default_rng(3)
        x = _t(rng.standard_normal((32, 64)).astype(np.float32))
        w = _t(rng.standard_normal((64, 64)).astype(np.float32),
               stop_gradient=False)
        b = _t(rng.standard_normal(64).astype(np.float32),
               stop_gradient=False)

        def bench(fused, iters=80):
            set_flags({"FLAGS_eager_chain_fusion": fused})
            clear_dispatch_cache()
            for _ in range(12):
                _fwd_bwd_step(x, w, b)
            best = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                for _ in range(iters):
                    _fwd_bwd_step(x, w, b)
                best = min(best, (time.perf_counter() - t0) / iters)
            return best

        ratios = []
        for _ in range(4):      # retries absorb shared-CI load spikes
            t_per_op = bench(False)
            t_fused = bench(True)
            ratios.append(t_per_op / t_fused)
            if ratios[-1] >= 1.3:
                break
        assert max(ratios) >= 1.3, \
            f"fused speedup below 1.3x: {[round(r, 2) for r in ratios]}"
        assert chain_fusion_stats()["fused_replays"] > 0
