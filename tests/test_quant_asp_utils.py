"""Tests for quantization (QAT/PTQ), ASP sparsity, and utils
(cpp_extension, dlpack, run_check)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import quantization as Q
from paddle_tpu.incubate import asp


def _t(a):
    return paddle.to_tensor(np.asarray(a, np.float32))


# ----------------------------------------------------------- quantization

def test_fake_quant_abs_max_values():
    x = _t([-1.0, -0.5, 0.0, 0.5, 1.0])
    out, scale = Q.fake_quantize_abs_max(x, bit_length=8)
    assert abs(float(scale) - 1.0) < 1e-6
    # 8-bit grid: values land within one step (1/127) of the original
    np.testing.assert_allclose(out.numpy(), x.numpy(), atol=1.0 / 127 + 1e-6)


def test_fake_quant_channel_wise():
    w = np.array([[1.0, -2.0], [0.1, 0.2]], np.float32)  # quant_axis=0 rows
    out, scale = Q.fake_quantize_channel_wise_abs_max(_t(w), quant_axis=0)
    np.testing.assert_allclose(scale.numpy(), [2.0, 0.2], rtol=1e-6)
    np.testing.assert_allclose(out.numpy(), w, atol=2.0 / 127 + 1e-6)


def test_fake_quant_ste_gradient():
    x = paddle.to_tensor(np.array([0.3, -0.7], np.float32),
                         stop_gradient=False)
    out, _ = Q.fake_quantize_abs_max(x)
    out.sum().backward()
    # STE: gradient is identity
    np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0], rtol=1e-6)


def test_qat_quantize_and_train():
    paddle.seed(0)
    model = paddle.nn.Sequential(
        paddle.nn.Linear(8, 16), paddle.nn.ReLU(), paddle.nn.Linear(16, 3))
    qat = Q.ImperativeQuantAware()
    qat.quantize(model)
    assert isinstance(model[0], Q.QuantizedLinear)
    assert isinstance(model[2], Q.QuantizedLinear)

    opt = paddle.optimizer.Adam(1e-2, parameters=model.parameters())
    x = _t(np.random.randn(16, 8))
    y = paddle.to_tensor(np.random.randint(0, 3, 16).astype(np.int64))
    import paddle_tpu.nn.functional as F
    l0 = None
    for _ in range(15):
        loss = F.cross_entropy(model(x), y)
        loss.backward(); opt.step(); opt.clear_grad()
        l0 = l0 or float(loss)
    assert float(loss) < l0  # trains through the fake-quant STE


def test_quant_post_dynamic():
    model = paddle.nn.Linear(8, 4)
    qsd = Q.quant_post_dynamic(model.state_dict())
    w = qsd["weight"]
    assert w["int8"].dtype == np.int8
    deq = w["int8"].astype(np.float32) * w["scale"] / 127
    np.testing.assert_allclose(deq, model.weight.numpy(), atol=w["scale"] / 100)


# ------------------------------------------------------------------- asp

def test_asp_mask_and_check():
    v = _t(np.random.randn(8, 16))
    mask = asp.create_mask(v, n=2, m=4)
    masked = v.numpy() * mask
    assert asp.check_sparsity(_t(masked), n=2, m=4)
    assert abs(asp.calculate_density(_t(masked)) - 0.5) < 1e-6


def test_asp_prune_and_decorate():
    paddle.seed(0)
    model = paddle.nn.Linear(16, 8)
    asp.prune_model(model, n=2, m=4)
    assert asp.check_sparsity(model.weight, n=2, m=4)

    opt = asp.decorate(
        paddle.optimizer.SGD(0.1, parameters=model.parameters()), model)
    x = _t(np.random.randn(4, 16))
    loss = (model(x) ** 2).mean()
    loss.backward()
    opt.step()
    # masks survive the update
    assert asp.check_sparsity(model.weight, n=2, m=4)
    asp.reset_excluded_layers()


# ----------------------------------------------------------------- utils

def test_cpp_extension_load(tmp_path):
    src = tmp_path / "my_relu.cc"
    src.write_text(r"""
#include <cstdint>
extern "C" void my_relu(const float* x, float* y, int64_t n) {
    for (int64_t i = 0; i < n; ++i) y[i] = x[i] > 0 ? x[i] : 0.0f;
}
extern "C" void my_square(const float* x, float* y, int64_t n) {
    for (int64_t i = 0; i < n; ++i) y[i] = x[i] * x[i];
}
""")
    from paddle_tpu.utils import cpp_extension
    ext = cpp_extension.load("my_relu", [str(src)],
                             functions=["my_relu", "my_square"])
    x = _t(np.array([-1.0, 2.0, -3.0, 4.0]))
    np.testing.assert_allclose(ext.my_relu(x).numpy(), [0, 2, 0, 4])
    np.testing.assert_allclose(ext.my_square(x).numpy(), [1, 4, 9, 16])


def test_dlpack_roundtrip():
    from paddle_tpu.utils import dlpack
    x = _t(np.array([1.0, 2.0, 3.0]))
    obj = dlpack.to_dlpack(x)
    y = dlpack.from_dlpack(obj)
    np.testing.assert_allclose(y.numpy(), x.numpy())
    # interop: torch tensor -> paddle Tensor (both directions via protocol)
    import torch
    t = torch.tensor([4.0, 5.0])
    z = dlpack.from_dlpack(t)
    np.testing.assert_allclose(z.numpy(), [4.0, 5.0])
    back = torch.from_dlpack(dlpack.to_dlpack(z))
    np.testing.assert_allclose(back.numpy(), [4.0, 5.0])


def test_run_check(capsys):
    paddle.utils.run_check()
    out = capsys.readouterr().out
    assert "installed successfully" in out


def test_sysconfig():
    import os
    assert os.path.isdir(paddle.sysconfig.get_include())
