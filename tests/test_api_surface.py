"""API-surface parity additions (round 3): top-level misc + Hermitian FFTs.

Reference analogs: python/paddle/__init__.py __all__, python/paddle/fft.py,
python/paddle/batch.py, python/paddle/hapi/dynamic_flops.py.
"""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn as nn


class TestTopLevelMisc:
    def test_renorm_matches_torch(self):
        x = np.random.RandomState(0).randn(3, 4, 5).astype("float32")
        got = paddle.renorm(paddle.to_tensor(x), 2.0, 0, 1.0).numpy()
        ref = torch.renorm(torch.tensor(x), 2.0, 0, 1.0).numpy()
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_renorm_keeps_small_slices(self):
        x = np.full((2, 3), 0.01, "float32")
        got = paddle.renorm(paddle.to_tensor(x), 2.0, 0, 5.0).numpy()
        np.testing.assert_allclose(got, x)

    def test_iinfo_finfo(self):
        assert paddle.iinfo(paddle.int32).max == 2**31 - 1
        assert paddle.iinfo("int8").min == -128
        f = paddle.finfo(paddle.bfloat16)
        assert f.bits == 16 and f.eps == 0.0078125
        assert paddle.finfo("float32").eps == np.finfo(np.float32).eps

    def test_batch_reader(self):
        r = paddle.batch(lambda: iter(range(7)), batch_size=3)
        assert list(r()) == [[0, 1, 2], [3, 4, 5], [6]]
        r = paddle.batch(lambda: iter(range(7)), batch_size=3, drop_last=True)
        assert list(r()) == [[0, 1, 2], [3, 4, 5]]
        with pytest.raises(ValueError):
            paddle.batch(lambda: iter([]), batch_size=0)

    def test_create_parameter(self):
        p = paddle.create_parameter([4, 3], "float32", name="w0")
        assert p.shape == [4, 3] and p.trainable and p.name == "w0"
        b = paddle.create_parameter(
            [4], "float32", is_bias=True,
            default_initializer=nn.initializer.Constant(0.0))
        np.testing.assert_allclose(b.numpy(), np.zeros(4, "float32"))

    def test_check_shape(self):
        paddle.check_shape([1, -1, 4])
        with pytest.raises(TypeError):
            paddle.check_shape("bad")
        with pytest.raises(ValueError):
            paddle.check_shape([1, -2])

    def test_flops_linear(self):
        net = nn.Linear(8, 16)
        total = paddle.flops(net, [2, 8])
        assert total == 2 * 16 * 8  # out_numel * in_features

    def test_lazy_guard_params_usable(self):
        with paddle.LazyGuard():
            lin = nn.Linear(4, 4)
        y = lin(paddle.to_tensor(np.ones((2, 4), "float32")))
        assert y.shape == [2, 4]

    def test_rng_state_roundtrip(self):
        paddle.seed(7)
        st = paddle.get_rng_state()
        a = paddle.rand([3]).numpy()
        paddle.set_rng_state(st)
        b = paddle.rand([3]).numpy()
        np.testing.assert_allclose(a, b)
        assert paddle.get_cuda_rng_state is not None

    def test_place_shims(self):
        assert paddle.NPUPlace(1).get_device_id() == 1
        assert paddle.CUDAPinnedPlace() == paddle.CUDAPinnedPlace()

    def test_dtype_alias(self):
        assert isinstance(paddle.float32, paddle.dtype)


class TestHermitianFFT:
    norms = ["backward", "ortho", "forward"]

    @pytest.mark.parametrize("norm", norms)
    def test_hfftn_ihfftn_match_torch(self, norm):
        rng = np.random.RandomState(1)
        x = (rng.randn(4, 5, 6) + 1j * rng.randn(4, 5, 6)).astype("complex64")
        xr = rng.randn(4, 5, 6).astype("float32")
        got = paddle.fft.hfftn(paddle.to_tensor(x), norm=norm).numpy()
        ref = torch.fft.hfftn(torch.tensor(x), norm=norm).numpy()
        np.testing.assert_allclose(got, ref, atol=2e-3)
        got = paddle.fft.ihfftn(paddle.to_tensor(xr), norm=norm).numpy()
        ref = torch.fft.ihfftn(torch.tensor(xr), norm=norm).numpy()
        np.testing.assert_allclose(got, ref, atol=1e-4)

    @pytest.mark.parametrize("norm", norms)
    def test_hfft2_ihfft2_match_torch(self, norm):
        rng = np.random.RandomState(2)
        x = (rng.randn(3, 4, 5) + 1j * rng.randn(3, 4, 5)).astype("complex64")
        xr = rng.randn(3, 4, 5).astype("float32")
        got = paddle.fft.hfft2(paddle.to_tensor(x), norm=norm).numpy()
        ref = torch.fft.hfft2(torch.tensor(x), norm=norm).numpy()
        np.testing.assert_allclose(got, ref, atol=2e-3)
        got = paddle.fft.ihfft2(paddle.to_tensor(xr), norm=norm).numpy()
        ref = torch.fft.ihfft2(torch.tensor(xr), norm=norm).numpy()
        np.testing.assert_allclose(got, ref, atol=1e-4)

    def test_hfftn_with_s(self):
        rng = np.random.RandomState(3)
        x = (rng.randn(4, 5) + 1j * rng.randn(4, 5)).astype("complex64")
        got = paddle.fft.hfftn(paddle.to_tensor(x), s=(4, 8),
                               axes=(0, 1)).numpy()
        ref = torch.fft.hfftn(torch.tensor(x), s=(4, 8), dim=(0, 1)).numpy()
        assert got.shape == ref.shape
        np.testing.assert_allclose(got, ref, atol=2e-3)
