"""Inference predictor + launch CLI + elastic manager (reference analogs:
inference/api/analysis_predictor.h, launch/main.py, fleet/elastic)."""
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit import InputSpec


def make_net():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))


def test_jit_save_load_translated_layer(tmp_path):
    net = make_net()
    path = str(tmp_path / "m.pdmodel")
    paddle.jit.save(net, path, input_spec=[InputSpec([1, 4], "float32")])
    art = paddle.jit.load(path)
    assert art.has_forward
    x = np.ones((1, 4), np.float32)
    ref = net(paddle.to_tensor(x)).numpy()
    out = art(x).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_predictor_run(tmp_path):
    net = make_net()
    path = str(tmp_path / "m.pdmodel")
    paddle.jit.save(net, path, input_spec=[InputSpec([2, 4], "float32")])
    cfg = paddle.inference.Config(path)
    cfg.enable_memory_optim()
    pred = paddle.inference.create_predictor(cfg)
    x = np.random.default_rng(0).standard_normal((2, 4)).astype(np.float32)
    # direct style
    outs = pred.run([x])
    ref = net(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5)
    # handle style
    names = pred.get_input_names()
    h = pred.get_input_handle(names[0])
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_save_inference_model(tmp_path):
    net = make_net()
    prefix = str(tmp_path / "inf")
    paddle.static.save_inference_model(
        prefix, [InputSpec([1, 4], "float32")], net)
    # reference static/io.py contract: [program, feed_names, fetch_targets]
    program, feed_names, fetches = paddle.static.load_inference_model(
        prefix + ".pdmodel")
    assert program._translated.has_forward
    assert len(feed_names) == 1 and len(fetches) == 1
    x = np.random.default_rng(0).standard_normal((1, 4)).astype(np.float32)
    out = paddle.static.Executor().run(program, feed={feed_names[0]: x},
                                       fetch_list=fetches)
    ref = net(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out[0], ref, rtol=1e-5)


def test_launch_two_workers(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        rank = os.environ["PADDLE_TRAINER_ID"]
        ws = os.environ["PADDLE_TRAINERS_NUM"]
        print(f"rank {rank} of {ws} master={os.environ['PADDLE_MASTER']}")
    """))
    log_dir = str(tmp_path / "logs")
    rc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", log_dir, str(script)],
        capture_output=True, text=True, timeout=120,
        cwd="/root/repo")
    assert rc.returncode == 0, rc.stderr
    for r in range(2):
        log = open(os.path.join(log_dir, f"workerlog.{r}")).read()
        assert f"rank {r} of 2" in log


def test_launch_elastic_restart(tmp_path):
    # worker fails once, then succeeds (state kept in a marker file)
    script = tmp_path / "flaky.py"
    marker = tmp_path / "failed_once"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        m = {str(marker)!r}
        if not os.path.exists(m):
            open(m, "w").write("x")
            sys.exit(3)
        print("recovered rank", os.environ["PADDLE_TRAINER_ID"])
    """))
    log_dir = str(tmp_path / "logs")
    rc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--max_restarts", "2",
         "--log_dir", log_dir, str(script)],
        capture_output=True, text=True, timeout=120, cwd="/root/repo")
    assert rc.returncode == 0, rc.stderr
    log = open(os.path.join(log_dir, "workerlog.0")).read()
    assert "recovered" in log


@pytest.mark.skipif(not paddle.distributed.TCPStore, reason="no native core")
def test_elastic_manager_heartbeat():
    from paddle_tpu.core import TCPStore, native_available
    if not native_available():
        pytest.skip("native core unavailable")
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticStatus)
    store = TCPStore("127.0.0.1", 0, is_master=True, world_size=2)
    m0 = ElasticManager(store=store, job_id="t", np=2, rank=0, interval=0.2)
    m1 = ElasticManager(store=store, job_id="t", np=2, rank=1, interval=0.2)
    m0.start(); m1.start()
    time.sleep(0.5)
    assert m0.dead_nodes() == []
    assert m0.watch() == ElasticStatus.COMPLETED
    m1.stop()
    time.sleep(1.0)
    assert 1 in m0.dead_nodes()
    assert m0.watch() == ElasticStatus.RESTART
    m0.stop()


class _FakeStore:
    def __init__(self):
        self.d = {}

    def set(self, k, v):
        self.d[k] = v

    def get(self, k, wait=False):
        if k not in self.d:
            raise KeyError(k)
        return self.d[k]


def test_elastic_scale_in_plan():
    """A node stops heartbeating -> ELASTIC level proposes a smaller world
    with densely renumbered ranks + rewritten endpoints (manager.py:127)."""
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticLevel,
                                                      ElasticStatus)
    store = _FakeStore()
    now = time.time()
    for r, alive in [(0, True), (1, False), (2, True)]:
        if alive:
            store.set(f"heartbeat/j/{r}", str(now).encode())
        store.set(f"nodes/j/{r}",
                  f"{now}|10.0.0.{r}:8000".encode())
    mgr = ElasticManager(store=store, job_id="j", np=3, rank=0,
                         level=ElasticLevel.ELASTIC)
    status, plan = mgr.scale_plan(np_min=2)
    assert status == ElasticStatus.RESTART
    assert plan == {0: (0, "10.0.0.0:8000"), 2: (1, "10.0.0.2:8000")}
    env = ElasticManager.rewrite_endpoints(plan)
    assert env["PADDLE_TRAINERS_NUM"] == "2"
    assert env["PADDLE_TRAINER_ENDPOINTS"] == \
        "10.0.0.0:8000,10.0.0.2:8000"
    assert env["PADDLE_MASTER"] == "10.0.0.0:8000"


def test_elastic_scale_out_plan():
    """A 4th node joins beyond np=3 -> RESTART at the larger world."""
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticLevel,
                                                      ElasticStatus)
    store = _FakeStore()
    now = time.time()
    for r in range(4):
        store.set(f"heartbeat/j/{r}", str(now).encode())
        store.set(f"nodes/j/{r}",
                  f"{now}|10.0.0.{r}:8000".encode())
    mgr = ElasticManager(store=store, job_id="j", np=3, rank=0,
                         level=ElasticLevel.ELASTIC)
    status, plan = mgr.scale_plan(np_min=1, np_max=8)
    assert status == ElasticStatus.RESTART
    assert len(plan) == 4 and plan[3] == (3, "10.0.0.3:8000")
    # capped by np_max
    status, plan = mgr.scale_plan(np_min=1, np_max=2)
    assert len(plan) == 2


def test_elastic_unchanged_world_is_completed():
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticLevel,
                                                      ElasticStatus)
    store = _FakeStore()
    now = time.time()
    for r in range(2):
        store.set(f"heartbeat/j/{r}", str(now).encode())
        store.set(f"nodes/j/{r}", f"{now}|h{r}:1".encode())
    mgr = ElasticManager(store=store, job_id="j", np=2, rank=0,
                         level=ElasticLevel.ELASTIC)
    status, plan = mgr.scale_plan()
    assert status == ElasticStatus.COMPLETED
    assert plan == {0: (0, "h0:1"), 1: (1, "h1:1")}


def test_elastic_below_min_errors():
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticLevel,
                                                      ElasticStatus)
    mgr = ElasticManager(store=_FakeStore(), job_id="j", np=3, rank=0,
                         level=ElasticLevel.ELASTIC)
    status, plan = mgr.scale_plan(np_min=2)
    assert status == ElasticStatus.ERROR and plan is None
