"""Native async checkpoint writer tests (csrc/ckpt_writer.cc)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.io import async_save, load
from paddle_tpu.core._build import load_library


def test_async_save_roundtrip(tmp_path):
    model = paddle.nn.Linear(16, 8)
    p = str(tmp_path / "m.pdparams")
    h = async_save(model.state_dict(), p)
    h.wait()
    assert h.done()
    sd = load(p)
    np.testing.assert_allclose(sd["weight"].numpy(), model.weight.numpy())
    np.testing.assert_allclose(sd["bias"].numpy(), model.bias.numpy())


def test_async_save_nested_and_poll(tmp_path):
    obj = {"model": paddle.nn.Linear(4, 2).state_dict(),
           "step": 42, "lr": 0.1,
           "history": [1.0, 2.0]}
    p = str(tmp_path / "ckpt.pd")
    h = async_save(obj, p)
    h.wait()
    out = load(p)
    assert out["step"] == 42 and out["history"] == [1.0, 2.0]
    assert "weight" in out["model"]


@pytest.mark.skipif(load_library() is None, reason="native core unavailable")
def test_corrupt_file_detected(tmp_path):
    p = str(tmp_path / "c.pdparams")
    h = async_save({"x": paddle.to_tensor(np.ones(64, np.float32))}, p)
    h.wait()
    # flip a payload byte: CRC must catch it
    with open(p, "r+b") as f:
        f.seek(30)
        b = f.read(1)
        f.seek(30)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(IOError, match="CRC"):
        load(p)


def test_legacy_files_still_load(tmp_path):
    # files written by plain save() (no trailer) load unchanged
    p = str(tmp_path / "legacy.pdparams")
    paddle.save({"a": paddle.to_tensor(np.arange(3).astype(np.float32))}, p)
    out = load(p)
    np.testing.assert_allclose(out["a"].numpy(), [0.0, 1.0, 2.0])


@pytest.mark.skipif(load_library() is None, reason="native core unavailable")
def test_async_save_failure_surfaces(tmp_path):
    # target path is a directory -> native writer cannot rename onto it
    target = tmp_path / "iam_a_dir"
    target.mkdir()
    h = async_save({"x": 1}, str(target))
    with pytest.raises(IOError):
        h.wait()
