"""nn.Layer mechanics + layer forward checks."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_layer_registration():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 8)
            self.fc2 = nn.Linear(8, 2)
            self.act = nn.ReLU()

        def forward(self, x):
            return self.fc2(self.act(self.fc1(x)))

    net = Net()
    names = [n for n, _ in net.named_parameters()]
    assert "fc1.weight" in names and "fc2.bias" in names
    assert len(net.parameters()) == 4
    assert len(net.sublayers()) == 3


def test_state_dict_roundtrip():
    net = nn.Linear(4, 3)
    sd = net.state_dict()
    net2 = nn.Linear(4, 3)
    missing, unexpected = net2.set_state_dict(sd)
    assert not missing and not unexpected
    np.testing.assert_allclose(net2.weight.numpy(), net.weight.numpy())


def test_train_eval_mode():
    net = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
    net.eval()
    assert not net[1].training
    x = paddle.ones([10, 4])
    np.testing.assert_allclose(net[1](x).numpy(), x.numpy())
    net.train()
    assert net[1].training


def test_forward_hooks():
    net = nn.Linear(2, 2)
    calls = []
    h = net.register_forward_post_hook(
        lambda layer, inp, out: calls.append(1))
    net(paddle.ones([1, 2]))
    assert calls == [1]
    h.remove()
    net(paddle.ones([1, 2]))
    assert calls == [1]


def test_linear_matches_numpy():
    net = nn.Linear(3, 4)
    x = np.random.rand(5, 3).astype(np.float32)
    out = net(paddle.to_tensor(x))
    expected = x @ net.weight.numpy() + net.bias.numpy()
    np.testing.assert_allclose(out.numpy(), expected, atol=1e-5)


def test_conv2d_shape_and_grad():
    conv = nn.Conv2D(3, 6, 3, stride=2, padding=1)
    x = paddle.randn([2, 3, 8, 8])
    x.stop_gradient = False
    out = conv(x)
    assert out.shape == [2, 6, 4, 4]
    out.sum().backward()
    assert conv.weight.grad is not None
    assert x.grad.shape == [2, 3, 8, 8]


def test_conv2d_matches_manual():
    conv = nn.Conv2D(1, 1, 2, bias_attr=False)
    x = np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3)
    w = conv.weight.numpy()
    out = conv(paddle.to_tensor(x)).numpy()
    # manual valid conv
    expected = np.zeros((1, 1, 2, 2), np.float32)
    for i in range(2):
        for j in range(2):
            expected[0, 0, i, j] = np.sum(x[0, 0, i:i+2, j:j+2] * w[0, 0])
    np.testing.assert_allclose(out, expected, atol=1e-5)


def test_batchnorm_train_and_eval():
    bn = nn.BatchNorm2D(4)
    x = paddle.randn([8, 4, 5, 5])
    bn.train()
    out = bn(x)
    m = out.numpy().mean(axis=(0, 2, 3))
    np.testing.assert_allclose(m, np.zeros(4), atol=1e-4)
    # running stats moved toward batch stats
    assert not np.allclose(bn._mean.numpy(), np.zeros(4))
    bn.eval()
    out2 = bn(x)
    assert out2.shape == [8, 4, 5, 5]


def test_layernorm():
    ln = nn.LayerNorm(8)
    x = paddle.randn([4, 8])
    out = ln(x).numpy()
    np.testing.assert_allclose(out.mean(-1), np.zeros(4), atol=1e-5)
    np.testing.assert_allclose(out.std(-1), np.ones(4), atol=1e-2)


def test_embedding():
    emb = nn.Embedding(10, 4)
    idx = paddle.to_tensor(np.array([[1, 2], [3, 4]]))
    out = emb(idx)
    assert out.shape == [2, 2, 4]
    np.testing.assert_allclose(out.numpy()[0, 0], emb.weight.numpy()[1])


def test_embedding_grad_scatter():
    emb = nn.Embedding(5, 3)
    idx = paddle.to_tensor(np.array([0, 0, 1]))
    out = emb(idx)
    out.sum().backward()
    g = emb.weight.grad.numpy()
    np.testing.assert_allclose(g[0], 2 * np.ones(3), atol=1e-5)
    np.testing.assert_allclose(g[1], np.ones(3), atol=1e-5)
    np.testing.assert_allclose(g[2], np.zeros(3), atol=1e-5)


def test_pooling():
    x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    mp = nn.MaxPool2D(2)
    np.testing.assert_allclose(mp(x).numpy().reshape(2, 2),
                               [[5, 7], [13, 15]])
    ap = nn.AvgPool2D(2)
    np.testing.assert_allclose(ap(x).numpy().reshape(2, 2),
                               [[2.5, 4.5], [10.5, 12.5]])
    aap = nn.AdaptiveAvgPool2D(1)
    np.testing.assert_allclose(float(aap(x).numpy()), 7.5)


def test_activations_match_numpy():
    x = np.linspace(-3, 3, 13).astype(np.float32)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(F.relu(t).numpy(), np.maximum(x, 0))
    np.testing.assert_allclose(F.sigmoid(t).numpy(), 1 / (1 + np.exp(-x)),
                               atol=1e-6)
    sm = F.softmax(paddle.to_tensor(x.reshape(1, -1))).numpy()
    e = np.exp(x - x.max())
    np.testing.assert_allclose(sm[0], e / e.sum(), atol=1e-6)


def test_losses():
    pred = paddle.to_tensor(np.array([[2.0, 1.0], [0.5, 3.0]], np.float32))
    lab = paddle.to_tensor(np.array([0, 1]))
    l = F.cross_entropy(pred, lab)
    p = np.exp(pred.numpy())
    p = p / p.sum(-1, keepdims=True)
    expected = -np.log(p[[0, 1], [0, 1]]).mean()
    np.testing.assert_allclose(float(l), expected, atol=1e-5)

    a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    b = paddle.to_tensor(np.array([1.5, 1.0], np.float32))
    np.testing.assert_allclose(float(F.mse_loss(a, b)),
                               ((np.array([1., 2.]) -
                                 np.array([1.5, 1.])) ** 2).mean(), atol=1e-6)
    np.testing.assert_allclose(float(F.l1_loss(a, b)), 0.75, atol=1e-6)


def test_cross_entropy_ignore_index():
    pred = paddle.randn([4, 5])
    lab = paddle.to_tensor(np.array([0, -100, 2, -100]))
    l = F.cross_entropy(pred, lab, ignore_index=-100)
    lab2 = paddle.to_tensor(np.array([0, 2]))
    pred2 = paddle.to_tensor(pred.numpy()[[0, 2]])
    l2 = F.cross_entropy(pred2, lab2)
    np.testing.assert_allclose(float(l), float(l2), atol=1e-5)


def test_sequential_and_layerlist():
    s = nn.Sequential(nn.Linear(2, 3), nn.ReLU(), nn.Linear(3, 1))
    assert len(s) == 3
    out = s(paddle.ones([4, 2]))
    assert out.shape == [4, 1]
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    assert len(list(ll.parameters())) == 8


def test_multihead_attention():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.randn([2, 6, 16])
    out = mha(x, x, x)
    assert out.shape == [2, 6, 16]


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.randn([2, 5, 16])
    out = enc(x)
    assert out.shape == [2, 5, 16]
    # grads flow to every distinct layer
    out.sum().backward()
    grads = [p.grad is not None for p in enc.parameters()]
    assert all(grads)


def test_lstm_gru():
    lstm = nn.LSTM(4, 8, num_layers=2)
    x = paddle.randn([3, 5, 4])
    out, (h, c) = lstm(x)
    assert out.shape == [3, 5, 8]
    assert h.shape == [2, 3, 8]
    gru = nn.GRU(4, 8, direction="bidirect")
    out, h = gru(x)
    assert out.shape == [3, 5, 16]

    out.sum().backward()
    assert all(p.grad is not None for p in gru.parameters())


def test_rnn_cells():
    cell = nn.LSTMCell(4, 6)
    x = paddle.randn([2, 4])
    h, (hn, cn) = cell(x)
    assert h.shape == [2, 6]
    rnn = nn.RNN(nn.GRUCell(4, 6))
    out, st = rnn(paddle.randn([2, 3, 4]))
    assert out.shape == [2, 3, 6]


def test_clip_grad_by_global_norm():
    p = nn.Parameter(np.ones(4, np.float32))
    from paddle_tpu.framework.core import Tensor
    g = paddle.to_tensor(np.full(4, 10.0, np.float32))
    clip = nn.ClipGradByGlobalNorm(1.0)
    out = clip([(p, g)])
    norm = np.linalg.norm(out[0][1].numpy())
    np.testing.assert_allclose(norm, 1.0, atol=1e-5)
