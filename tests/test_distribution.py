"""Distribution package tests against scipy references.

Covers every class exported from paddle_tpu.distribution, in particular the
Transform stack (Transform/Affine/Exp/Sigmoid/Chain/TransformedDistribution/
Independent/ExponentialFamily) and the distributions added late in round 3
(Gumbel/Cauchy/Geometric/LogNormal/Multinomial).  Reference analog:
python/paddle/distribution/ unittests (tests/unittests/distribution/).
"""
import math

import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle
import paddle_tpu.distribution as D


def t(x):
    return paddle.to_tensor(np.asarray(x, np.float32))


class TestLogProbVsScipy:
    """log_prob of every distribution against the scipy pdf/pmf."""

    def setup_method(self, _):
        paddle.seed(0)

    def test_normal(self):
        d = D.Normal(t(1.5), t(2.0))
        x = np.linspace(-3, 5, 7).astype(np.float32)
        np.testing.assert_allclose(
            d.log_prob(t(x)).numpy(), st.norm.logpdf(x, 1.5, 2.0), rtol=1e-5)

    def test_uniform(self):
        d = D.Uniform(t(-1.0), t(3.0))
        x = np.array([-0.5, 0.0, 2.9], np.float32)
        np.testing.assert_allclose(
            d.log_prob(t(x)).numpy(), st.uniform.logpdf(x, -1.0, 4.0),
            rtol=1e-5)
        assert np.isneginf(d.log_prob(t(np.array([5.0]))).numpy()).all()

    def test_bernoulli(self):
        d = D.Bernoulli(t(0.3))
        np.testing.assert_allclose(
            d.log_prob(t(1.0)).numpy(), math.log(0.3), rtol=1e-5)
        np.testing.assert_allclose(
            d.log_prob(t(0.0)).numpy(), math.log(0.7), rtol=1e-5)

    def test_categorical(self):
        # paddle Categorical logits are unnormalized probabilities
        w = np.array([1.0, 2.0, 3.0], np.float32)
        d = D.Categorical(logits=t(w))
        p = w / w.sum()
        for k in range(3):
            np.testing.assert_allclose(
                d.log_prob(t(np.array([k], np.int64))).numpy(),
                [math.log(p[k])], rtol=1e-5)

    def test_beta(self):
        d = D.Beta(t(2.0), t(5.0))
        x = np.array([0.1, 0.4, 0.8], np.float32)
        np.testing.assert_allclose(
            d.log_prob(t(x)).numpy(), st.beta.logpdf(x, 2.0, 5.0), rtol=1e-4)

    def test_dirichlet(self):
        a = np.array([1.5, 2.0, 3.0], np.float32)
        d = D.Dirichlet(t(a))
        x = np.array([0.2, 0.3, 0.5], np.float32)
        np.testing.assert_allclose(
            d.log_prob(t(x)).numpy(), st.dirichlet.logpdf(x, a), rtol=1e-4)

    def test_exponential(self):
        d = D.Exponential(t(1.7))
        x = np.array([0.1, 1.0, 3.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(t(x)).numpy(), st.expon.logpdf(x, scale=1 / 1.7),
            rtol=1e-5)

    def test_gamma(self):
        d = D.Gamma(t(3.0), t(2.0))
        x = np.array([0.5, 1.5, 4.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(t(x)).numpy(), st.gamma.logpdf(x, 3.0, scale=0.5),
            rtol=1e-4)

    def test_laplace(self):
        d = D.Laplace(t(0.5), t(1.2))
        x = np.array([-1.0, 0.5, 2.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(t(x)).numpy(), st.laplace.logpdf(x, 0.5, 1.2),
            rtol=1e-5)

    def test_lognormal(self):
        d = D.LogNormal(t(0.3), t(0.8))
        x = np.array([0.5, 1.0, 3.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(t(x)).numpy(),
            st.lognorm.logpdf(x, 0.8, scale=math.exp(0.3)), rtol=1e-4)

    def test_gumbel(self):
        d = D.Gumbel(t(1.0), t(2.0))
        x = np.array([-1.0, 1.0, 4.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(t(x)).numpy(), st.gumbel_r.logpdf(x, 1.0, 2.0),
            rtol=1e-5)

    def test_cauchy(self):
        d = D.Cauchy(t(0.5), t(1.5))
        x = np.array([-2.0, 0.5, 3.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(t(x)).numpy(), st.cauchy.logpdf(x, 0.5, 1.5),
            rtol=1e-5)

    def test_geometric(self):
        # trials convention (support {1, 2, ...}) == scipy.stats.geom
        d = D.Geometric(t(0.25))
        k = np.array([1.0, 2.0, 5.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(t(k)).numpy(), st.geom.logpmf(k, 0.25), rtol=1e-5)

    def test_multinomial(self):
        p = np.array([0.2, 0.3, 0.5], np.float32)
        d = D.Multinomial(10, t(p))
        x = np.array([2.0, 3.0, 5.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(t(x)).numpy(),
            st.multinomial.logpmf(x, 10, p), rtol=1e-4)


class TestEntropyAndKL:
    def test_entropy_vs_scipy(self):
        np.testing.assert_allclose(D.Normal(t(0.0), t(2.0)).entropy().numpy(),
                                   st.norm.entropy(0.0, 2.0), rtol=1e-5)
        np.testing.assert_allclose(D.Uniform(t(0.0), t(4.0)).entropy().numpy(),
                                   st.uniform.entropy(0, 4), rtol=1e-5)
        np.testing.assert_allclose(
            D.Bernoulli(t(0.3)).entropy().numpy(),
            st.bernoulli.entropy(0.3), rtol=1e-5)
        np.testing.assert_allclose(
            D.Beta(t(2.0), t(5.0)).entropy().numpy(),
            st.beta.entropy(2.0, 5.0), rtol=1e-4)
        w = np.array([1.0, 2.0, 3.0], np.float32)
        np.testing.assert_allclose(
            D.Categorical(logits=t(w)).entropy().numpy(),
            st.entropy(w / w.sum()), rtol=1e-5)

    def test_kl_registry(self):
        p, q = D.Normal(t(0.0), t(1.0)), D.Normal(t(1.0), t(2.0))
        expect = (math.log(2.0) + (1 + 1) / (2 * 4) - 0.5)
        np.testing.assert_allclose(D.kl_divergence(p, q).numpy(), expect,
                                   rtol=1e-5)
        # method alias
        np.testing.assert_allclose(p.kl_divergence(q).numpy(), expect,
                                   rtol=1e-5)
        np.testing.assert_allclose(
            D.kl_divergence(D.Uniform(t(1.0), t(2.0)),
                            D.Uniform(t(0.0), t(4.0))).numpy(),
            math.log(4.0 / 1.0), rtol=1e-5)
        pb, qb = D.Bernoulli(t(0.3)), D.Bernoulli(t(0.6))
        expect = (0.3 * math.log(0.3 / 0.6) + 0.7 * math.log(0.7 / 0.4))
        np.testing.assert_allclose(D.kl_divergence(pb, qb).numpy(), expect,
                                   rtol=1e-5)
        w1 = np.array([1.0, 1.0, 2.0], np.float32)
        w2 = np.array([2.0, 1.0, 1.0], np.float32)
        p1, p2 = w1 / w1.sum(), w2 / w2.sum()
        np.testing.assert_allclose(
            D.kl_divergence(D.Categorical(logits=t(w1)),
                            D.Categorical(logits=t(w2))).numpy(),
            (p1 * np.log(p1 / p2)).sum(), rtol=1e-5)

    def test_kl_unregistered_raises(self):
        with pytest.raises(NotImplementedError):
            D.kl_divergence(D.Normal(t(0.0), t(1.0)),
                            D.Laplace(t(0.0), t(1.0)))

    def test_register_kl_decorator(self):
        class _A(D.Distribution):
            pass

        @D.register_kl(_A, _A)
        def _kl_a(p, q):
            return t(42.0)

        assert float(D.kl_divergence(_A(), _A()).numpy()) == 42.0


class TestSampling:
    def setup_method(self, _):
        paddle.seed(7)

    def test_moments(self):
        n = (4096,)
        s = D.Gumbel(t(1.0), t(2.0)).sample(n).numpy()
        np.testing.assert_allclose(s.mean(), 1.0 + 2.0 * np.euler_gamma,
                                   atol=0.15)
        s = D.LogNormal(t(0.2), t(0.5)).sample(n).numpy()
        assert (s > 0).all()
        np.testing.assert_allclose(np.log(s).mean(), 0.2, atol=0.05)
        s = D.Geometric(t(0.4)).sample(n).numpy()
        assert (s >= 1).all()
        np.testing.assert_allclose(s.mean(), 1 / 0.4, atol=0.2)
        # Cauchy has no mean; the sample median estimates loc
        s = D.Cauchy(t(0.5), t(1.0)).sample(n).numpy()
        np.testing.assert_allclose(np.median(s), 0.5, atol=0.15)

    def test_multinomial_counts(self):
        s = D.Multinomial(10, t([0.2, 0.3, 0.5])).sample((64,)).numpy()
        assert s.shape == (64, 3)
        np.testing.assert_array_equal(s.sum(-1), 10.0)
        np.testing.assert_allclose(s.mean(0) / 10.0, [0.2, 0.3, 0.5],
                                   atol=0.1)

    def test_batch_shapes(self):
        d = D.Normal(t(np.zeros((2, 3))), t(np.ones((2, 3))))
        assert d.sample((5,)).shape == [5, 2, 3]
        assert d.batch_shape == (2, 3)


class TestTransforms:
    def _check_bijector(self, tr, x):
        """Round-trip + finite-difference check of the log-det-jacobian."""
        y = tr.forward(t(x)).numpy()
        back = tr.inverse(t(y)).numpy()
        np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-5)
        eps = 1e-3
        fd = (tr.forward(t(x + eps)).numpy()
              - tr.forward(t(x - eps)).numpy()) / (2 * eps)
        np.testing.assert_allclose(
            tr.forward_log_det_jacobian(t(x)).numpy(),
            np.log(np.abs(fd)), atol=1e-3)
        # inverse ldj is the negated forward ldj at the preimage
        np.testing.assert_allclose(
            tr.inverse_log_det_jacobian(t(y)).numpy(),
            -tr.forward_log_det_jacobian(t(x)).numpy(), atol=1e-5)

    def test_affine(self):
        self._check_bijector(D.AffineTransform(t(1.0), t(-2.5)),
                             np.linspace(-2, 2, 5).astype(np.float32))

    def test_exp(self):
        self._check_bijector(D.ExpTransform(),
                             np.linspace(-1, 1.5, 5).astype(np.float32))

    def test_sigmoid(self):
        self._check_bijector(D.SigmoidTransform(),
                             np.linspace(-2, 2, 5).astype(np.float32))

    def test_chain(self):
        chain = D.ChainTransform([D.AffineTransform(t(0.5), t(2.0)),
                                  D.ExpTransform()])
        x = np.linspace(-1, 1, 5).astype(np.float32)
        np.testing.assert_allclose(chain.forward(t(x)).numpy(),
                                   np.exp(0.5 + 2.0 * x), rtol=1e-5)
        self._check_bijector(chain, x)

    def test_call_alias(self):
        tr = D.ExpTransform()
        np.testing.assert_allclose(tr(t(0.3)).numpy(),
                                   tr.forward(t(0.3)).numpy())


class TestTransformedDistribution:
    def test_lognormal_via_exp_of_normal(self):
        d = D.TransformedDistribution(D.Normal(t(0.3), t(0.8)),
                                      D.ExpTransform())
        x = np.array([0.5, 1.0, 3.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(t(x)).numpy(),
            st.lognorm.logpdf(x, 0.8, scale=math.exp(0.3)), rtol=1e-4)
        paddle.seed(11)
        s = d.sample((2048,)).numpy()
        assert (s > 0).all()
        np.testing.assert_allclose(np.log(s).mean(), 0.3, atol=0.1)

    def test_chain_of_transforms(self):
        # sigmoid(2*z + 1) of a standard normal, log_prob checked by change
        # of variables computed manually
        base = D.Normal(t(0.0), t(1.0))
        d = D.TransformedDistribution(
            base, [D.AffineTransform(t(1.0), t(2.0)), D.SigmoidTransform()])
        y = np.array([0.3, 0.6, 0.9], np.float32)
        z = (np.log(y / (1 - y)) - 1.0) / 2.0
        ldj = np.log(y * (1 - y)) + math.log(2.0)
        np.testing.assert_allclose(
            d.log_prob(t(y)).numpy(), st.norm.logpdf(z) - ldj, rtol=1e-4)


class TestIndependent:
    def test_log_prob_sums_event_dims(self):
        loc = np.zeros((4, 3), np.float32)
        scale = np.ones((4, 3), np.float32)
        base = D.Normal(t(loc), t(scale))
        ind = D.Independent(base, 1)
        assert ind.batch_shape == (4,) and ind.event_shape == (3,)
        x = np.random.RandomState(0).randn(4, 3).astype(np.float32)
        np.testing.assert_allclose(
            ind.log_prob(t(x)).numpy(),
            base.log_prob(t(x)).numpy().sum(-1), rtol=1e-5)
        np.testing.assert_allclose(
            ind.entropy().numpy(), base.entropy().numpy().sum(-1), rtol=1e-5)

    def test_rank_check(self):
        with pytest.raises(ValueError):
            D.Independent(D.Normal(t(np.zeros(3)), t(np.ones(3))), 2)


class TestExponentialFamily:
    def test_normal_entropy_via_bregman(self):
        """A Normal expressed in natural parameters: entropy from the
        log-normalizer via autodiff must match the closed form."""
        import jax.numpy as jnp

        class NatNormal(D.ExponentialFamily):
            def __init__(self, loc, scale):
                self.loc, self.scale = float(loc), float(scale)
                super().__init__(())

            @property
            def _natural_parameters(self):
                return (self.loc / self.scale ** 2,
                        -0.5 / self.scale ** 2)

            def _log_normalizer(self, n1, n2):
                return -n1 ** 2 / (4 * n2) - 0.5 * jnp.log(-2 * n2)

            @property
            def _mean_carrier_measure(self):
                return -0.5 * math.log(2 * math.pi)

        ent = NatNormal(1.3, 2.1).entropy().numpy()
        np.testing.assert_allclose(ent, st.norm.entropy(1.3, 2.1), rtol=1e-5)
