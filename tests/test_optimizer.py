"""Optimizer correctness tests (reference analog: test_adam_op etc.)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.optimizer import SGD, Momentum, Adam, AdamW, Lamb, RMSProp, \
    Adagrad, Adamax, Adadelta
from paddle_tpu.optimizer.lr import StepDecay, CosineAnnealingDecay, \
    LinearWarmup, NoamDecay


def _loss_decreases(opt_cls, steps=25, **kw):
    paddle.seed(42)
    net = nn.Sequential(nn.Linear(6, 12), nn.Tanh(), nn.Linear(12, 1))
    opt = opt_cls(parameters=net.parameters(), **kw)
    x = paddle.to_tensor(np.random.rand(16, 6).astype(np.float32))
    y = paddle.to_tensor(np.random.rand(16, 1).astype(np.float32))
    first = None
    for _ in range(steps):
        loss = F.mse_loss(net(x), y)
        if first is None:
            first = float(loss)
        loss.backward()
        opt.step()
        opt.clear_grad()
    return first, float(loss)


@pytest.mark.parametrize("cls,kw", [
    (SGD, {"learning_rate": 0.1}),
    (Momentum, {"learning_rate": 0.05}),
    (Adam, {"learning_rate": 0.01}),
    (AdamW, {"learning_rate": 0.01}),
    (Lamb, {"learning_rate": 0.01}),
    (RMSProp, {"learning_rate": 0.005}),
    (Adagrad, {"learning_rate": 0.05}),
    (Adamax, {"learning_rate": 0.01}),
    (Adadelta, {"learning_rate": 1.0}),
])
def test_loss_decreases(cls, kw):
    first, last = _loss_decreases(cls, **kw)
    assert last < first * 0.9, f"{cls.__name__}: {first} -> {last}"


def test_sgd_matches_manual():
    p = nn.Parameter(np.array([1.0, 2.0], np.float32))
    opt = SGD(learning_rate=0.1, parameters=[p])
    (p * paddle.to_tensor(np.array([3.0, 4.0], np.float32))).sum().backward()
    opt.step()
    np.testing.assert_allclose(p.numpy(), [1.0 - 0.3, 2.0 - 0.4], atol=1e-6)


def test_adam_matches_reference_formula():
    w0 = np.array([0.5, -0.3], np.float32)
    p = nn.Parameter(w0.copy())
    opt = Adam(learning_rate=0.1, parameters=[p])
    g = np.array([0.2, -0.1], np.float32)
    (p * paddle.to_tensor(g)).sum().backward()
    opt.step()
    m = 0.1 * g
    v = 0.001 * g * g
    lr_t = 0.1 * np.sqrt(1 - 0.999) / (1 - 0.9)
    expected = w0 - lr_t * m / (np.sqrt(v) + 1e-8)
    np.testing.assert_allclose(p.numpy(), expected, atol=1e-5)


def test_adamw_decay():
    w0 = np.array([1.0], np.float32)
    p = nn.Parameter(w0.copy())
    opt = AdamW(learning_rate=0.1, parameters=[p], weight_decay=0.5)
    (p * 0.0).sum().backward()  # zero grad; only decay acts
    opt.step()
    np.testing.assert_allclose(p.numpy(), [1.0 * (1 - 0.1 * 0.5)], atol=1e-6)


def test_weight_decay_l2():
    p = nn.Parameter(np.array([2.0], np.float32))
    opt = SGD(learning_rate=0.1, parameters=[p], weight_decay=0.1)
    (p * 1.0).sum().backward()
    opt.step()
    # grad = 1 + 0.1*2 = 1.2
    np.testing.assert_allclose(p.numpy(), [2.0 - 0.12], atol=1e-6)


def test_grad_clip_in_optimizer():
    p = nn.Parameter(np.array([1.0], np.float32))
    opt = SGD(learning_rate=1.0, parameters=[p],
              grad_clip=nn.ClipGradByGlobalNorm(0.5))
    (p * 10.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(p.numpy(), [0.5], atol=1e-5)


def test_optimizer_state_dict_roundtrip():
    p = nn.Parameter(np.ones(3, np.float32))
    opt = Adam(learning_rate=0.01, parameters=[p])
    (p * 2).sum().backward()
    opt.step()
    state = opt.state_dict()
    p2 = nn.Parameter(np.ones(3, np.float32))
    p2.name = p.name
    opt2 = Adam(learning_rate=0.01, parameters=[p2])
    opt2.set_state_dict(state)
    np.testing.assert_allclose(
        np.asarray(opt2._accumulators["moment1"][p.name]),
        np.asarray(opt._accumulators["moment1"][p.name]))


def test_lr_schedulers():
    s = StepDecay(0.1, step_size=2, gamma=0.5)
    lrs = []
    for _ in range(5):
        lrs.append(s())
        s.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025], atol=1e-8)

    c = CosineAnnealingDecay(1.0, T_max=10)
    assert abs(c() - 1.0) < 1e-6
    for _ in range(10):
        c.step()
    assert c() < 1e-6

    w = LinearWarmup(0.1, warmup_steps=4, start_lr=0.0, end_lr=0.1)
    assert w() == 0.0
    w.step()
    assert abs(w() - 0.025) < 1e-8

    n = NoamDecay(d_model=512, warmup_steps=100, learning_rate=1.0)
    assert n() > 0


def test_scheduler_with_optimizer():
    p = nn.Parameter(np.ones(2, np.float32))
    sched = StepDecay(0.1, step_size=1, gamma=0.1)
    opt = SGD(learning_rate=sched, parameters=[p])
    assert abs(opt.get_lr() - 0.1) < 1e-8
    sched.step()
    assert abs(opt.get_lr() - 0.01) < 1e-8


def test_train_step_fused():
    """TrainStep must match eager step-by-step training."""
    paddle.seed(7)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    opt = Adam(learning_rate=0.01, parameters=net.parameters())
    from paddle_tpu.jit import TrainStep
    step = TrainStep(net, lambda out, y: F.mse_loss(out, y), opt)
    x = paddle.to_tensor(np.random.rand(8, 4).astype(np.float32))
    y = paddle.to_tensor(np.random.rand(8, 1).astype(np.float32))
    losses = [float(step(x, y)) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5


def test_lars_trains_and_excludes_bias_decay():
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    paddle.seed(0)
    model = paddle.nn.Linear(8, 4)
    opt = paddle.optimizer.Lars(0.5, lars_coeff=0.01,
                                parameters=model.parameters(),
                                exclude_from_weight_decay=["bias"])
    x = paddle.to_tensor(np.random.randn(16, 8).astype(np.float32))
    y = paddle.to_tensor(np.random.randint(0, 4, 16).astype(np.int64))
    # the 1-D bias must actually be excluded from decay (auto-named params
    # match by shape, not name)
    flags = [opt._decay_flags[p.name] for p in model.parameters()]
    assert False in flags and True in flags
    losses = []
    for _ in range(30):
        loss = F.cross_entropy(model(x), y)
        loss.backward(); opt.step(); opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_multi_precision_mixed_dtype_params():
    """multi_precision with a model mixing bf16 and f32 params: only bf16
    params carry a master_weight; the eager step must not require one for
    every param (regression: KeyError 'master_weight')."""
    import jax.numpy as jnp
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 4))
    # half the params to bf16, the rest stay f32 (the keep-norms-in-f32
    # recipe)
    for p in model[0].parameters():
        p._value = p._value.astype(jnp.bfloat16)
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters(),
                                 multi_precision=True)
    x = paddle.to_tensor(np.random.randn(16, 8).astype(np.float32))
    y = paddle.to_tensor(np.random.randint(0, 4, 16).astype(np.int64))
    import paddle_tpu.nn.functional as F
    losses = []
    for _ in range(5):
        loss = F.cross_entropy(model(x.astype("bfloat16")), y)
        loss.backward(); opt.step(); opt.clear_grad()
        losses.append(float(loss))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    # masters exist exactly for the bf16 params
    masters = opt._accumulators["master_weight"]
    bf16_names = {p.name for p in model[0].parameters()}
    assert set(masters.keys()) == bf16_names


def test_multi_precision_mixed_dtype_train_step():
    """Same regression through the fused TrainStep."""
    import jax.numpy as jnp
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.jit import TrainStep
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 4))
    for p in model[0].parameters():
        p._value = p._value.astype(jnp.bfloat16)
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters(),
                                 multi_precision=True)
    step = TrainStep(model, lambda o, t: F.cross_entropy(o, t), opt)
    x = paddle.to_tensor(np.random.randn(16, 8).astype(np.float32)) \
        .astype("bfloat16")
    y = paddle.to_tensor(np.random.randint(0, 4, 16).astype(np.int64))
    losses = [float(step(x, y)) for _ in range(5)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
