"""Compiled eager dispatch: the per-op executable cache (ops/dispatch.py).

Covers the cache-key contract (no collisions across dtype / shape /
stop_gradient mask / AMP state), registry-override generation invalidation,
LRU eviction at FLAGS_eager_op_cache_size, the residual-donation path, and
the tier-1 micro-benchmark: a repeated matmul+add+gelu sequence must stop
re-tracing after its first iteration and produce bitwise-identical outputs
to the uncached path.
"""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops.dispatch import (call_op, call_op_multi,
                                     clear_dispatch_cache,
                                     dispatch_cache_info)
from paddle_tpu.ops.registry import get_op, override_kernel, use_kernel
from paddle_tpu.profiler import (dispatch_cache_stats,
                                 reset_dispatch_cache_stats)


@pytest.fixture(autouse=True)
def _fresh_cache():
    clear_dispatch_cache()
    reset_dispatch_cache_stats()
    set_flags({"FLAGS_eager_op_cache": True,
               "FLAGS_eager_op_cache_size": 512,
               "FLAGS_eager_op_cache_donate": False})
    yield
    clear_dispatch_cache()
    reset_dispatch_cache_stats()
    set_flags({"FLAGS_eager_op_cache": True,
               "FLAGS_eager_op_cache_size": 512,
               "FLAGS_eager_op_cache_donate": False})


def _t(arr, stop_gradient=True):
    return paddle.to_tensor(np.asarray(arr), stop_gradient=stop_gradient)


_GLOBAL_SCALE = 2.0


def _gscale_op(v):
    return v * _GLOBAL_SCALE


class TestKeying:
    def test_repeat_hits(self):
        x = _t(np.linspace(-1, 1, 8, dtype=np.float32))
        a = paddle.exp(x)
        b = paddle.exp(x)
        s = dispatch_cache_stats()
        assert s["misses"] >= 1 and s["hits"] >= 1
        np.testing.assert_array_equal(a.numpy(), b.numpy())

    def test_dtype_does_not_collide(self):
        xf = _t(np.linspace(-1, 1, 8, dtype=np.float32))
        xb = paddle.to_tensor(jnp.linspace(-1, 1, 8, dtype=jnp.bfloat16))
        paddle.exp(xf)          # warm the f32 entry
        out = paddle.exp(xb)
        assert out._value.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out._value, np.float32),
            np.exp(np.asarray(xb._value, np.float32)), rtol=2e-2)

    def test_shape_does_not_collide(self):
        a = paddle.exp(_t(np.ones((3,), np.float32)))
        b = paddle.exp(_t(np.ones((2, 2), np.float32)))
        assert a.shape == [3] and b.shape == [2, 2]
        assert dispatch_cache_stats()["misses"] >= 2

    def test_stop_gradient_mask_does_not_collide(self):
        """Same op+avals with a different diff mask must compile separate
        executables — and both must produce correct grads."""
        xv = np.random.rand(4, 5).astype(np.float32)
        wv = np.random.rand(5, 3).astype(np.float32)

        x = _t(xv, stop_gradient=False)
        w = _t(wv, stop_gradient=True)      # mask (True, False)
        paddle.matmul(x, w).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   np.ones((4, 3)) @ wv.T, rtol=1e-5)
        assert w.grad is None

        x2 = _t(xv, stop_gradient=False)
        w2 = _t(wv, stop_gradient=False)    # mask (True, True)
        paddle.matmul(x2, w2).sum().backward()
        np.testing.assert_allclose(w2.grad.numpy(),
                                   xv.T @ np.ones((4, 3)), rtol=1e-5)

    def test_amp_state_does_not_collide(self):
        xv = np.random.rand(4, 4).astype(np.float32)
        x, w = _t(xv), _t(xv)
        plain = paddle.matmul(x, w)
        assert plain._value.dtype == jnp.float32
        with paddle.amp.auto_cast(level="O1"):
            amped = paddle.matmul(x, w)
        assert amped._value.dtype == jnp.bfloat16
        again = paddle.matmul(x, w)         # back outside: f32 again
        np.testing.assert_array_equal(plain.numpy(), again.numpy())

    def test_closure_scalar_in_key(self):
        """The fn token must distinguish closures over different scalars
        (same code object, different cell values)."""
        x = _t(np.ones(4, np.float32))
        a = (x + 2.0).numpy()
        b = (x + 3.0).numpy()
        np.testing.assert_array_equal(a, np.full(4, 3.0, np.float32))
        np.testing.assert_array_equal(b, np.full(4, 4.0, np.float32))

    def test_mutable_global_scalar_rekeys(self):
        """A module-global scalar read by the op fn is part of the key —
        rebinding it must NOT serve the stale cached trace."""
        global _GLOBAL_SCALE
        x = _t(np.ones(3, np.float32))
        _GLOBAL_SCALE = 2.0
        r1 = call_op("gscale_probe", _gscale_op, (x,)).numpy()
        r1b = call_op("gscale_probe", _gscale_op, (x,)).numpy()   # hit
        _GLOBAL_SCALE = 3.0
        try:
            r2 = call_op("gscale_probe", _gscale_op, (x,)).numpy()
        finally:
            _GLOBAL_SCALE = 2.0
        np.testing.assert_array_equal(r1, np.full(3, 2.0, np.float32))
        np.testing.assert_array_equal(r1b, r1)
        np.testing.assert_array_equal(r2, np.full(3, 3.0, np.float32))

    def test_global_tensor_bypasses(self):
        """An op fn reading a global Tensor's value must bypass the cache:
        in-place value swaps (optimizer updates) would go stale otherwise."""
        w = _t(np.full(3, 2.0, np.float32))

        def opw(v, _w=None):
            return v * w._value          # w is a closure cell → Tensor

        x = _t(np.ones(3, np.float32))
        r1 = call_op("wswap_probe", opw, (x,)).numpy()
        w._value = jnp.full(3, 5.0, jnp.float32)
        r2 = call_op("wswap_probe", opw, (x,)).numpy()
        np.testing.assert_array_equal(r1, np.full(3, 2.0, np.float32))
        np.testing.assert_array_equal(r2, np.full(3, 5.0, np.float32))
        assert dispatch_cache_stats()["bypasses"] >= 2

    def test_unkeyable_closure_bypasses(self):
        const = np.arange(4, dtype=np.float32)     # ndarray cell → bypass
        x = _t(np.ones(4, np.float32))
        out = call_op("bypass_probe", lambda v: v + jnp.asarray(const), (x,))
        np.testing.assert_array_equal(out.numpy(), 1.0 + const)
        assert dispatch_cache_stats()["bypasses"] >= 1

    def test_cache_disabled_flag(self):
        set_flags({"FLAGS_eager_op_cache": False})
        x = _t(np.ones(4, np.float32))
        out = paddle.exp(x)
        np.testing.assert_allclose(out.numpy(), np.e, rtol=1e-6)
        s = dispatch_cache_stats()
        assert s["hits"] == 0 and s["misses"] == 0
        assert dispatch_cache_info()["entries"] == 0


class TestOverrideInvalidation:
    def teardown_method(self, _m):
        od = get_op("exp")
        od.active = None
        od.overrides.clear()

    def test_override_after_hit_takes_effect(self):
        """A registry override activated AFTER the built-in kernel was
        cached (and hit) must serve the very next call — the per-op
        generation counter keeps the stale executable unreachable."""
        x = _t(np.zeros(3, np.float32))
        base = paddle.exp(x).numpy()
        base2 = paddle.exp(x).numpy()           # cache hit on the built-in
        assert dispatch_cache_stats()["hits"] >= 1
        np.testing.assert_array_equal(base, base2)

        gen0 = get_op("exp").generation
        override_kernel("exp", "doubled", lambda v: jnp.exp(v) * 2.0,
                        activate=True)
        assert get_op("exp").generation > gen0
        doubled = paddle.exp(x).numpy()
        np.testing.assert_allclose(doubled, 2.0 * base, rtol=1e-6)

        get_op("exp").active = None             # deactivate
        restored = paddle.exp(x).numpy()
        np.testing.assert_array_equal(restored, base)

    def test_use_kernel_scope_with_cache(self):
        x = _t(np.full(3, 0.5, np.float32))
        base = paddle.exp(x).numpy()
        override_kernel("exp", "tripled", lambda v: jnp.exp(v) * 3.0)
        with use_kernel("exp", "tripled"):
            inside = paddle.exp(x).numpy()
            inside2 = paddle.exp(x).numpy()     # hit on the override entry
        after = paddle.exp(x).numpy()
        np.testing.assert_allclose(inside, 3.0 * base, rtol=1e-6)
        np.testing.assert_array_equal(inside, inside2)
        np.testing.assert_array_equal(after, base)


class TestLRU:
    def test_eviction_at_capacity(self):
        set_flags({"FLAGS_eager_op_cache_size": 4})
        for n in range(1, 9):                   # 8 distinct shapes → keys
            paddle.exp(_t(np.ones(n, np.float32)))
        info = dispatch_cache_info()
        assert info["entries"] <= 4
        assert dispatch_cache_stats()["evictions"] >= 4

    def test_evicted_entry_recompiles_correctly(self):
        set_flags({"FLAGS_eager_op_cache_size": 1})
        a = _t(np.ones(3, np.float32))
        b = _t(np.ones(5, np.float32))
        r1 = paddle.exp(a).numpy()
        paddle.exp(b)                           # evicts the shape-3 entry
        r2 = paddle.exp(a).numpy()              # recompiles
        np.testing.assert_array_equal(r1, r2)
        assert dispatch_cache_info()["entries"] == 1


class TestGradPath:
    def test_multi_output_cached(self):
        x = _t(np.linspace(0.1, 1.0, 6, np.float32).reshape(2, 3),
               stop_gradient=False)
        fn = lambda v: (jnp.sin(v), jnp.cos(v))
        s1, c1 = call_op_multi("sincos_probe", fn, (x,), num_outputs=2)
        (s1 + c1).sum().backward()
        g1 = x.grad.numpy().copy()

        x2 = _t(x.numpy(), stop_gradient=False)
        s2, c2 = call_op_multi("sincos_probe", fn, (x2,), num_outputs=2)
        (s2 + c2).sum().backward()
        np.testing.assert_array_equal(g1, x2.grad.numpy())
        np.testing.assert_allclose(
            g1, np.cos(x.numpy()) - np.sin(x.numpy()), rtol=1e-5)
        assert dispatch_cache_stats()["hits"] >= 1

    def test_retain_graph_double_backward_run(self):
        """retain_graph=True must allow a second engine pass over the same
        cached VJP executables (no donation on non-final passes)."""
        x = _t(np.full(4, 0.5, np.float32), stop_gradient=False)
        y = paddle.tanh(x).sum()
        y.backward(retain_graph=True)
        g1 = x.grad.numpy().copy()
        x.clear_grad()
        y.backward()
        np.testing.assert_array_equal(g1, x.grad.numpy())

    def test_donate_flag_grads_correct(self):
        """FLAGS_eager_op_cache_donate routes the final backward through the
        donating applier (a warn-and-skip no-op on CPU) with exact grads."""
        import warnings
        set_flags({"FLAGS_eager_op_cache_donate": True})
        xv = np.linspace(-1, 1, 8).astype(np.float32)
        x = _t(xv, stop_gradient=False)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            paddle.exp(x).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.exp(xv), rtol=1e-6)

    def test_create_graph_replay_unaffected(self):
        """Double grad goes through replay (un-keyable closure → bypass) and
        must keep working with the cache on."""
        x = _t(np.array([0.7], np.float32), stop_gradient=False)
        y = (x * x * x).sum()
        (gx,) = paddle.grad([y], [x], create_graph=True)
        gx.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), 6 * 0.7, rtol=1e-5)


class TestMicroBenchmark:
    """The acceptance micro-benchmark (tier-1, not slow): repeated eager
    matmul+add+gelu with backward must hit the cache > 90% after warmup,
    stop re-tracing entirely after the first iteration, and match the
    uncached path bitwise."""

    @staticmethod
    def _step(xv, wv, bv):
        x = _t(xv, stop_gradient=False)
        w = _t(wv, stop_gradient=False)
        b = _t(bv, stop_gradient=False)
        out = F.gelu(paddle.add(paddle.matmul(x, w), b))
        out.sum().backward()
        return (out.numpy(), x.grad.numpy(), w.grad.numpy(), b.grad.numpy())

    def test_hit_rate_zero_retraces_bitwise(self):
        rng = np.random.default_rng(7)
        xv = rng.standard_normal((8, 16)).astype(np.float32)
        wv = rng.standard_normal((16, 16)).astype(np.float32)
        bv = rng.standard_normal((16,)).astype(np.float32)

        set_flags({"FLAGS_eager_op_cache": False})
        ref = self._step(xv, wv, bv)            # uncached ground truth

        set_flags({"FLAGS_eager_op_cache": True})
        clear_dispatch_cache()
        warm = self._step(xv, wv, bv)           # iteration 1: traces
        for r, u in zip(warm, ref):
            np.testing.assert_array_equal(r, u)

        reset_dispatch_cache_stats()
        for _ in range(10):
            res = self._step(xv, wv, bv)
        s = dispatch_cache_stats()
        assert s["retraces"] == 0, f"retraced after warmup: {s}"
        assert s["misses"] == 0, s
        assert s["hit_rate"] > 0.9, s
        for r, u in zip(res, ref):              # cached == uncached, bitwise
            np.testing.assert_array_equal(r, u)

    def test_no_grad_forward_bitwise(self):
        rng = np.random.default_rng(3)
        xv = rng.standard_normal((4, 16)).astype(np.float32)
        wv = rng.standard_normal((16, 8)).astype(np.float32)
        x, w = _t(xv), _t(wv)

        set_flags({"FLAGS_eager_op_cache": False})
        ref = F.gelu(paddle.matmul(x, w)).numpy()
        set_flags({"FLAGS_eager_op_cache": True})
        clear_dispatch_cache()
        warm = F.gelu(paddle.matmul(x, w)).numpy()
        hit = F.gelu(paddle.matmul(x, w)).numpy()
        np.testing.assert_array_equal(ref, warm)
        np.testing.assert_array_equal(ref, hit)
        assert dispatch_cache_stats()["hits"] >= 2
